#!/usr/bin/env python3
"""The 1M-node churn-storm config (BASELINE.md north star: 10% fail/rejoin
with ring rebalance + checksums in < 60 s wall-clock on a v5e-8).

Drives the O(N·U) scalable engine through a churn storm — a kill wave of
``fail_frac`` of the cluster, dissemination, then a revive wave, then
reconvergence — and reports wall-clock for the whole scanned run plus the
final convergence state.  Prints one JSON line.  (Select the device via
the ambient JAX platform, e.g. JAX_PLATFORMS=cpu.)

Usage: python benchmarks/storm_1m.py [-n 1000000] [--ticks 60]
       [--fail-frac 0.10]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="storm-1m")
    p.add_argument("-n", type=int, default=1_000_000)
    p.add_argument("--ticks", type=int, default=60)
    p.add_argument("--fail-frac", type=float, default=0.10)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    if args.ticks < 8:
        p.error("--ticks must be >= 8 (fail wave at 2, rejoin at ticks//2)")

    import jax
    import numpy as np

    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.models.sim.storm import ScalableCluster, StormSchedule

    n = args.n
    params = es.ScalableParams(n=n, u=512, checksum_in_tick=True)
    cluster = ScalableCluster(n=n, params=params, seed=args.seed)

    sched = StormSchedule.churn_storm(
        args.ticks, n, fraction=args.fail_frac, fail_tick=2, seed=args.seed
    )

    # compile + warm on a copy of the inputs
    t0 = time.perf_counter()
    metrics = cluster.run(sched)
    jax.block_until_ready(cluster.state)
    cold_s = time.perf_counter() - t0

    # distinct seed: with the shared executable cache this would otherwise
    # be the identical (executable, inputs) pair the tunnel memoizes
    # (RESULTS.md round 4); the work per seed is statistically identical
    cluster2 = ScalableCluster(n=n, params=params, seed=args.seed + 1)
    t0 = time.perf_counter()
    metrics = cluster2.run(sched)
    jax.block_until_ready(cluster2.state)
    warm_s = time.perf_counter() - t0

    ring_checksum = cluster2.ring_checksum()
    print(
        json.dumps(
            {
                "metric": "churn_storm_wall_clock_s",
                "value": round(warm_s, 2),
                "unit": "s (warm)",
                "vs_baseline": round(60.0 / warm_s, 2),  # target: < 60 s
                "n_nodes": n,
                "ticks": args.ticks,
                "fail_frac": args.fail_frac,
                "cold_s": round(cold_s, 2),
                "final_distinct_checksums": int(
                    np.asarray(metrics.distinct_checksums)[-1]
                ),
                "final_live_nodes": int(np.asarray(metrics.live_nodes)[-1]),
                "ring_checksum": ring_checksum,
                "platform": jax.devices()[0].platform,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
