#!/usr/bin/env python3
"""Micro-benchmarks mirroring the reference's harness suite
(/root/reference/benchmarks/): membership checksum compute, large
membership update, hash-ring add/remove (individual + bulk),
findMemberByAddress, join-response merge, and stat() emission with
cached vs uncached keys.  Prints one JSON line per benchmark:
{"bench", "value", "unit": "ops/sec", ...}.

Run: python benchmarks/micro.py [--bench NAME] [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Callable, Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def ops_per_sec(fn: Callable[[], None], min_time_s: float = 1.0) -> float:
    fn()  # warm
    n = 1
    while True:
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        dt = time.perf_counter() - t0
        if dt >= min_time_s:
            return n / dt
        n = max(n + 1, int(n * max(2.0, min_time_s / max(dt, 1e-9))))


def make_membership(n_members: int):
    from tests.lib.fixtures import RingpopFixture

    rp = RingpopFixture()
    for i in range(n_members - 1):
        rp.membership.update(
            {
                "address": "10.0.%d.%d:9000" % (i // 256, i % 256),
                "status": "alive",
                "incarnationNumber": 1414142122274 + i,
                "source": rp.host_port,
                "sourceIncarnationNumber": 1414142122274,
            }
        )
    return rp


def bench_compute_checksum(quick: bool) -> List[dict]:
    # benchmarks/compute-checksum.js:46-56 (100 and 1000 members)
    out = []
    for n in (100, 1000):
        rp = make_membership(n)
        rate = ops_per_sec(
            rp.membership.compute_checksum, 0.2 if quick else 1.0
        )
        out.append(
            {"bench": "compute-checksum-%d" % n, "value": round(rate, 1),
             "unit": "ops/sec"}
        )
    return out


def bench_large_membership_update(quick: bool) -> List[dict]:
    # benchmarks/large-membership-update.js:37-44 (1332-member changeset)
    changes = [
        {
            "address": "10.1.%d.%d:9000" % (i // 256, i % 256),
            "status": "alive",
            "incarnationNumber": 1414142122274 + i,
            "source": "127.0.0.1:3000",
            "sourceIncarnationNumber": 1414142122274,
        }
        for i in range(1332)
    ]

    def run():
        rp = make_membership(1)
        rp.membership.update(changes)

    rate = ops_per_sec(run, 0.2 if quick else 1.0)
    return [
        {"bench": "large-membership-update-1332", "value": round(rate, 2),
         "unit": "ops/sec"}
    ]


def bench_hashring(quick: bool) -> List[dict]:
    # benchmarks/add-remove-hashring.js:36-82
    from ringpop_tpu.models.ring.host import HashRing

    servers = ["10.2.%d.%d:9000" % (i // 256, i % 256) for i in range(1000)]

    def individual():
        ring = HashRing()
        for s in servers:
            ring.add_server(s)
        for s in servers:
            ring.remove_server(s)

    def bulk():
        ring = HashRing()
        ring.add_remove_servers(servers, [])
        ring.add_remove_servers([], servers)

    t = 0.2 if quick else 1.0
    return [
        {"bench": "hashring-add-remove-1000-individual",
         "value": round(ops_per_sec(individual, t), 2), "unit": "ops/sec"},
        {"bench": "hashring-add-remove-1000-bulk",
         "value": round(ops_per_sec(bulk, t), 2), "unit": "ops/sec"},
    ]


def bench_find_member(quick: bool) -> List[dict]:
    # benchmarks/find-member-by-address.js:31-49 (1 of 1000)
    rp = make_membership(1000)
    addr = "10.0.1.200:9000"

    def run():
        assert rp.membership.find_member_by_address(addr) is not None

    rate = ops_per_sec(run, 0.2 if quick else 1.0)
    return [
        {"bench": "find-member-by-address-1000", "value": round(rate, 1),
         "unit": "ops/sec"}
    ]


def bench_join_response_merge(quick: bool) -> List[dict]:
    # benchmarks/join-response-merge.js:30-60 (3 x 1000-member responses,
    # same vs different checksums)
    from ringpop_tpu.gossip.join_response_merge import merge_join_responses
    from tests.lib.fixtures import RingpopFixture

    rp = RingpopFixture()
    members = [
        {
            "address": "10.3.%d.%d:9000" % (i // 256, i % 256),
            "status": "alive",
            "incarnationNumber": 1414142122274 + i,
        }
        for i in range(1000)
    ]
    same = [{"checksum": 1, "members": members} for _ in range(3)]
    diff = [{"checksum": k, "members": members} for k in range(3)]
    t = 0.2 if quick else 1.0
    return [
        {"bench": "join-response-merge-3x1000-same-checksum",
         "value": round(ops_per_sec(lambda: merge_join_responses(rp, same), t), 1),
         "unit": "ops/sec"},
        {"bench": "join-response-merge-3x1000-diff-checksum",
         "value": round(ops_per_sec(lambda: merge_join_responses(rp, diff), t), 1),
         "unit": "ops/sec"},
    ]


def bench_stat_keys(quick: bool) -> List[dict]:
    # bench_ringpop_stat_cached_keys.js / bench_ringpop_stat_new_keys.js
    from ringpop_tpu.api.ringpop import Ringpop

    rp = Ringpop("bench", "127.0.0.1:3000")
    t = 0.2 if quick else 1.0

    def cached():
        rp.stat("increment", "bench-key")

    counter = [0]

    def uncached():
        counter[0] += 1
        rp.stat("increment", "bench-key-%d" % counter[0])

    return [
        {"bench": "stat-cached-keys",
         "value": round(ops_per_sec(cached, t), 1), "unit": "ops/sec"},
        {"bench": "stat-new-keys",
         "value": round(ops_per_sec(uncached, t), 1), "unit": "ops/sec"},
    ]


BENCHES: Dict[str, Callable[[bool], List[dict]]] = {
    "compute-checksum": bench_compute_checksum,
    "large-membership-update": bench_large_membership_update,
    "hashring": bench_hashring,
    "find-member": bench_find_member,
    "join-response-merge": bench_join_response_merge,
    "stat-keys": bench_stat_keys,
}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="micro-bench")
    p.add_argument("--bench", choices=sorted(BENCHES), help="run just one")
    p.add_argument("--quick", action="store_true", help="short timing windows")
    args = p.parse_args(argv)
    names = [args.bench] if args.bench else sorted(BENCHES)
    for name in names:
        for result in BENCHES[name](args.quick):
            print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    # standalone run: host-only benchmarks, no JAX/TPU init needed.  (Do
    # NOT set this at module level: importing this file inside a process
    # that also uses the JAX engine would silently disable x64 mode.)
    os.environ.setdefault("RINGPOP_TPU_NO_X64", "1")
    sys.exit(main())
