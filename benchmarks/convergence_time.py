#!/usr/bin/env python3
"""Convergence-time benchmark scenarios
(benchmarks/convergence-time/scenario-runner.js:37-98 rebuilt).

Each cycle induces a failure, measures the time until every live node
reports the same membership checksum — the reference's convergence rule
(scenario-runner.js:152-170) — then recovers (rejoins the failed nodes)
and reconverges before the next cycle.  Reports the reference's histogram
fields: count/min/max/mean/median/p75/p95/p99 (metrics Histogram printObj).

Scenarios (benchmarks/convergence-time/scenarios/*.js):
- ``single-node-failure`` — one random live node gracefully leaves
- ``half-cluster-failure`` — half the cluster leaves at once

Backends:
- ``jax-sim`` — the batched device simulator; convergence measured in
  protocol periods (ticks), reported as simulated milliseconds
  (ticks x 200 ms) plus the wall-clock compute cost
- ``live`` — real in-process Ringpop nodes over real sockets with REAL
  timers and auto-gossip; convergence measured in wall-clock ms, like the
  reference's multi-process runner

Prints one JSON line per run:
{"scenario", "backend", "n", "cycles", "unit", "histogram": {...}}
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time
from typing import Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def histogram(values: List[float]) -> Dict[str, float]:
    """count/min/max/mean/median/p75/p95/p99 (metrics Histogram printObj)."""
    if not values:
        return {"count": 0}
    s = sorted(values)

    def pct(p: float) -> float:
        i = min(len(s) - 1, max(0, int(round(p * (len(s) - 1)))))
        return s[i]

    return {
        "count": len(s),
        "min": s[0],
        "max": s[-1],
        "mean": sum(s) / len(s),
        "median": pct(0.5),
        "p75": pct(0.75),
        "p95": pct(0.95),
        "p99": pct(0.99),
    }


def pick_victims(scenario: str, hosts: List[str], rng: random.Random) -> List[int]:
    if scenario == "single-node-failure":
        return [rng.randrange(len(hosts))]
    if scenario == "half-cluster-failure":
        return rng.sample(range(len(hosts)), len(hosts) // 2)
    raise ValueError("unknown scenario %r" % scenario)


# -- jax-sim backend ---------------------------------------------------------


def run_jax_sim(scenario: str, n: int, cycles: int, seed: int) -> dict:
    import numpy as np

    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.cluster import SimCluster

    params = engine.SimParams(n=n, checksum_mode="fast")
    sim = SimCluster(n=n, params=params, seed=seed)
    sim.bootstrap()
    assert sim.run_until_converged() > 0
    rng = random.Random(seed)

    def live_mask() -> "np.ndarray":
        # the reference's convergence set is the ALIVE workers only —
        # left nodes drop out of hostToAliveWorker
        return np.asarray(
            sim.state.proc_alive & sim.state.ready & sim.state.gossip_on
        )

    def converged_fresh(pre: "np.ndarray") -> bool:
        # reference rule (scenario-runner.js:152-170): every alive worker
        # has REPORTED A NEW CHECKSUM since the event (hostToChecksum is
        # cleared each round) and all of them agree
        cs = sim.checksums()
        lm = live_mask()
        if not lm.any():
            return False
        vals = cs[lm]
        return bool((vals == vals[0]).all() and (vals != pre[lm]).all())

    def wait_fresh(pre: "np.ndarray", max_ticks: int = 10_000) -> int:
        ticks = 0
        while not converged_fresh(pre):
            sim.step()
            ticks += 1
            if ticks > max_ticks:
                raise RuntimeError("no convergence in %d ticks" % max_ticks)
        return ticks

    sim_ms: List[float] = []
    wall_start = time.perf_counter()
    for _ in range(cycles):
        victims = pick_victims(scenario, list(sim.universe.addresses), rng)
        pre = sim.checksums().copy()
        sim.leave(victims)
        ticks = 1 + wait_fresh(pre)
        sim_ms.append(ticks * params.period_ms)
        # recover: rejoin and reconverge before the next cycle
        pre = sim.checksums().copy()
        sim.rejoin(victims)
        wait_fresh(pre)
    wall_s = time.perf_counter() - wall_start

    return {
        "scenario": scenario,
        "backend": "jax-sim",
        "n": n,
        "cycles": cycles,
        "unit": "simulated-ms (ticks x %dms)" % params.period_ms,
        "histogram": histogram(sim_ms),
        "wall_clock_s_total": round(wall_s, 3),
    }


# -- live backend ------------------------------------------------------------


def run_live(scenario: str, n: int, cycles: int, seed: int) -> dict:
    from ringpop_tpu.api.ringpop import Ringpop
    from ringpop_tpu.net.channel import Channel

    nodes = []
    for i in range(n):
        ch = Channel("127.0.0.1:0")
        hp = ch.listen()
        # real timers + auto-gossip: genuine wall-clock protocol dynamics
        nodes.append(Ringpop("bench-app", hp, channel=ch, seed=seed + i))
    hosts = [rp.whoami() for rp in nodes]

    import threading

    threads = [
        threading.Thread(target=rp.bootstrap, args=(hosts,), daemon=True)
        for rp in nodes
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60.0)

    def live_nodes():
        return [rp for rp in nodes if rp.membership.local_member.status != "leave"]

    def snapshot() -> Dict[str, int]:
        return {rp.whoami(): rp.membership.checksum for rp in nodes}

    def converged_fresh(pre: Dict[str, int]) -> bool:
        # reference rule (scenario-runner.js:152-170): every alive worker
        # has reported a NEW checksum since the event and all agree
        live = live_nodes()
        vals = [rp.membership.checksum for rp in live]
        return (
            len(set(vals)) == 1
            and all(
                rp.membership.checksum != pre[rp.whoami()] for rp in live
            )
        )

    def wait_fresh(pre: Dict[str, int], timeout_s: float = 120.0) -> float:
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < timeout_s:
            if converged_fresh(pre):
                return (time.perf_counter() - t0) * 1000.0
            time.sleep(0.005)
        raise RuntimeError("no convergence within %ss" % timeout_s)

    # initial settle: everyone simply agrees
    t0 = time.perf_counter()
    while len({rp.membership.checksum for rp in nodes}) > 1:
        if time.perf_counter() - t0 > 120:
            raise RuntimeError("bootstrap never converged")
        time.sleep(0.01)

    rng = random.Random(seed)
    ms: List[float] = []
    try:
        for _ in range(cycles):
            victims = pick_victims(scenario, hosts, rng)
            pre = snapshot()
            for v in victims:
                nodes[v].server.admin_member_leave(None, {})
            ms.append(wait_fresh(pre))
            pre = snapshot()
            for v in victims:
                nodes[v].server.admin_member_join(None, {})
            wait_fresh(pre)
    finally:
        for rp in nodes:
            rp.destroy()

    return {
        "scenario": scenario,
        "backend": "live",
        "n": n,
        "cycles": cycles,
        "unit": "wall-clock ms",
        "histogram": histogram(ms),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="convergence-time")
    p.add_argument(
        "--scenario",
        choices=("single-node-failure", "half-cluster-failure"),
        default="single-node-failure",
    )
    p.add_argument("--backend", choices=("jax-sim", "live"), default="jax-sim")
    p.add_argument("-n", type=int, default=10)
    p.add_argument("--cycles", type=int, default=10)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    run = run_jax_sim if args.backend == "jax-sim" else run_live
    result = run(args.scenario, args.n, args.cycles, args.seed)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
