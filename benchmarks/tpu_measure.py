#!/usr/bin/env python3
"""One-shot TPU measurement sweep: grab the (single-client) axon tunnel
once and capture every chip-gated number in a single session —

  A. headline 1k-node tick rate, fast + farmhash-parity checksum modes
  B. hash32_rows Pallas kernel vs lax.scan lowering at the parity
     workload shape (SURVEY §2 native table)
  C. 100k-node epidemic broadcast, k=3 ping-req fanout, 5% packet loss
     (BASELINE.md north-star row 3: "runs in-jit on TPU"), gated and
     straight-line phase variants
  D. batched 8x1k vmapped multi-cluster aggregate throughput
  E. convergence-time scenarios at 1k (single-node-failure and
     half-cluster-failure; scenario-runner.js histogram fields)
  F. 1M-node churn storm, 10% fail/rejoin (north-star row 4: < 60 s),
     in-tick/deferred checksums x gated/straight-line variants
  G. round-10 fused exchange + sortless permutations: 1M storm A/B
     (sortless+pallas / sortless+xla / argsort+inline) with a bitwise
     final-state gate, plus the exchange op's isolated GB/s
  H. round-14 weak scaling: the shard_map'd exchange plane at 1M nodes
     PER CHIP over the available device mesh — per-rung node-ticks/s +
     weak-scaling efficiency, the <60 s 1M-storm check on a single
     chip, and a bitwise overlap gate (the same 1M storm sharded vs
     single-device).  CPU fallback runs a small marked ladder on
     forced host devices (utils.util.pin_cpu_platform is the one
     routed place for that flag) so the phase is rehearsable on
     tunnel-less images.
  I. round-17 mesh observatory: the per-shard exchange telemetry
     plane drained on the real interconnect (measured wire bytes vs
     the analytic traffic model, the check_traffic_model.py path) and
     an xprof capture of the sharded storm window (per-HLO-op time
     attribution via obs.xprof).

Each phase is independently guarded; results stream as JSON lines and the
combined dict lands in RESULTS_TPU_r06.json (TPU_MEASURE_OUT to override).
The tunnel is intermittently
held by another client, so backend init retries with backoff first.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT_PATH = os.environ.get("TPU_MEASURE_OUT", "RESULTS_TPU_r06.json")
RETRIES = int(os.environ.get("TPU_MEASURE_RETRIES", "90"))
SLEEP_S = float(os.environ.get("TPU_MEASURE_SLEEP_S", "20"))


def wait_for_tpu() -> str:
    from ringpop_tpu.utils.util import wait_for_tpu as _wait

    return _wait(__file__, "TPU_MEASURE_ATTEMPT", RETRIES, SLEEP_S)


def _todo(results: dict, key: str) -> bool:
    """False when ``key`` already holds a non-error result — crash-resume
    re-runs a phase but must not redo (or re-measure) finished configs."""
    v = results.get(key)
    return v is None or (isinstance(v, dict) and "error" in v)


def phase_headline(results: dict) -> None:
    import jax
    import numpy as np

    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster
    from ringpop_tpu.utils.util import retry_compile_helper

    # 256-tick window, same as bench.py: the tunnel charges ~0.9 s per
    # execution regardless of scan length (DIAG_1K.json), so a 32-tick
    # window measures the tunnel, not the engine.  Since round 5 the
    # farmhash window is the SAME 256 ticks: the bounded parity recompute
    # (auto K=4 chunk; engine.resolve_auto_parity — 256-tick scans
    # validated fault-free at K=32 and re-validated by the K-ladder
    # probes at 16/8/4, DIAG_BOUNDED.json + RESULTS.md).  Hygiene
    # (round-5 verdict item 7): every headline rate is the MEDIAN of
    # REPS warm runs with min/max recorded — state mutates between runs,
    # which defeats the tunnel's identical-execution result cache.
    n, ticks = 1024, 256
    REPS = 3

    def one_mode(mode):
        mode_ticks = ticks
        sim = SimCluster(n=n, params=engine.SimParams(n=n, checksum_mode=mode))
        sim.bootstrap()
        # converge via SINGLE steps before the long scan (same guard as
        # bench.py): a 256-tick scan over the post-bootstrap wave is a
        # long scan of heavy ticks — the worker's kernel-fault trigger —
        # and in bounded-parity mode it would overflow into a 256-tick
        # full-recompute replay, which is worse
        conv = sim.run_until_converged(max_ticks=96, quiet_after=1)
        assert conv > 0, "headline cluster failed to converge pre-window"
        sched = EventSchedule(ticks=mode_ticks, n=n)
        sim.run(sched)
        jax.block_until_ready(sim.state)
        rates = []
        metrics = None
        for _ in range(REPS):
            t0 = time.perf_counter()
            metrics = sim.run(sched)
            jax.block_until_ready(sim.state)
            rates.append(n * mode_ticks / (time.perf_counter() - t0))
        rates.sort()
        med = rates[len(rates) // 2]
        return {
            "node_ticks_per_sec": round(med, 1),
            "min_med_max": [round(r, 1) for r in (rates[0], med, rates[-1])],
            "ms_per_tick": round(1e3 * n / med, 2),
            "vs_realtime_baseline": round(med / (n * 5.0), 2),
            "ticks": mode_ticks,
            "reps": REPS,
            "converged": bool(np.asarray(metrics.converged)[-1]),
            "parity_replays": sim.parity_replays,
        }

    # per-mode capture with compile-helper-500 retries: a parity 500 must
    # not erase the fast number (nor vice versa) — the round-3 regression
    for mode in ("fast", "farmhash"):
        key = "headline_%s" % mode
        if not _todo(results, key):
            continue
        try:
            results[key] = retry_compile_helper(one_mode, mode)
        except Exception as e:
            results[key] = {"error": str(e)[:300]}
        print(json.dumps({key: results[key]}), flush=True)


def phase_pallas_vs_scan(results: dict) -> None:
    import jax
    import numpy as np

    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.cluster import SimCluster
    from ringpop_tpu.ops import checksum_encode as ce
    from ringpop_tpu.ops import jax_farmhash as jfh

    # the real parity workload: 1k converged membership rows (~40 KB each)
    n = 1024
    sim = SimCluster(
        n=n, params=engine.SimParams(n=n, checksum_mode="fast")
    )
    sim.bootstrap()
    for _ in range(3):
        sim.step()
    bufs, lens = ce.membership_rows(
        sim.universe,
        sim.state.known,
        sim.state.status,
        engine.stamp_to_ms(sim.state.inc, sim.params),
        max_digits=sim.params.max_digits,
    )
    bufs = jax.block_until_ready(bufs)
    row_bytes = int(bufs.shape[1])
    # measurement protocol: N repetitions INSIDE one compiled lax.scan,
    # each iteration salting one input byte, digest summed through the
    # carry and forced out at the end.  Host-loop repeat-then-block
    # timing is untrustworthy on this tunnel: dispatches whose results
    # are never consumed may not execute at all, and identical
    # (executable, inputs) executions are served from a cache
    # (RESULTS.md round 4).
    reps = 10
    for impl in ("scan", "pallas", "pallas_nogrid"):
        if not _todo(results, "hash32_rows_%s" % impl):
            continue
        try:
            import jax.numpy as jnp

            @jax.jit
            def run(b, impl=impl):
                def body(carry, _):
                    salt, acc = carry
                    h = jfh.hash32_rows(
                        b.at[0, 0].set(salt.astype(b.dtype)), lens, impl=impl
                    )
                    return (salt + 1, (acc + jnp.sum(h)).astype(h.dtype)), h

                (s, acc), hs = jax.lax.scan(
                    body,
                    (jnp.uint32(1), jnp.uint32(0)),
                    None,
                    length=reps,
                )
                return acc, hs[-1]

            np.asarray(run(bufs)[0])  # compile + warm, forced
            t0 = time.perf_counter()
            acc, last = run(bufs.at[1, 1].set(7))
            last = np.asarray(last)
            dt = (time.perf_counter() - t0) / reps
            # position-weighted digest, persisted in the artifact so a
            # crash-resumed process still validates against the first
            # impl's output instead of re-anchoring on its own
            digest = int(
                (
                    last.astype(np.uint64)
                    * (np.arange(n, dtype=np.uint64) + np.uint64(1))
                ).sum()
                & np.uint64(0x7FFFFFFFFFFFFFFF)
            )
            ref = results.get("hash32_rows_digest")
            if ref is not None:
                assert digest == ref, "pallas/scan hash mismatch"
            else:
                results["hash32_rows_digest"] = digest
            results["hash32_rows_%s" % impl] = {
                "ms": round(dt * 1e3, 2),
                "rows": n,
                "row_bytes": row_bytes,
                "mb_per_s": round(n * row_bytes / dt / 1e6, 1),
                "protocol": "in-scan x%d" % reps,
            }
        except Exception as e:
            results["hash32_rows_%s" % impl] = {"error": str(e)[:300]}


def phase_encode_impls(results: dict) -> None:
    """Checksum-string encode: scatter vs gather on the chip (the encode,
    not the hash, dominates parity-mode recomputes; CPU prefers scatter
    4x — device scatters may invert that)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.models.sim.cluster import default_addresses
    from ringpop_tpu.ops import checksum_encode as ce

    n = 1024
    u = ce.Universe.from_addresses(default_addresses(n))
    pres = jnp.ones((n, n), bool)
    stat = jnp.zeros((n, n), jnp.int32)
    inc = jnp.full((n, n), 1414142122274, jnp.int64)
    # direct byte-exact validation of the scatter_unique default ON THE
    # DEVICE, outside any timing: unique_indices=True is a promise whose
    # violation is UB only in the real TPU lowering (CPU/interpret tests
    # can't catch it), and the timing digest below is too weak to prove
    # byte placement
    if _todo(results, "encode_unique_bitexact_on_device"):
        try:
            # operands as jit ARGUMENTS, not baked constants — the
            # compile helper resource-limits large programs
            a_buf, a_len = jax.jit(
                lambda p, s, i: ce.membership_rows(
                    u, p, s, i, max_digits=14, impl="scatter"
                )
            )(pres, stat, inc)
            b_buf, b_len = jax.jit(
                lambda p, s, i: ce.membership_rows(
                    u, p, s, i, max_digits=14, impl="scatter_unique"
                )
            )(pres, stat, inc)
            a_buf, a_len = np.asarray(a_buf), np.asarray(a_len)
            b_buf, b_len = np.asarray(b_buf), np.asarray(b_len)
            ok = bool((a_len == b_len).all()) and all(
                (a_buf[r, : a_len[r]] == b_buf[r, : a_len[r]]).all()
                for r in range(n)
            )
            results["encode_unique_bitexact_on_device"] = ok
            if not ok:
                # a broken unique_indices promise is silent UB in the TPU
                # lowering — the production default depends on this holding
                results["encode_unique_bitexact_FAILURE"] = (
                    "scatter_unique diverged from scatter on-device: "
                    "revert checksum_encode.membership_rows' default "
                    "impl to 'scatter'"
                )
                print(
                    "WARNING: scatter_unique byte-exactness FAILED on "
                    "this backend — revert membership_rows default to "
                    "'scatter'",
                    file=sys.stderr,
                    flush=True,
                )
        except Exception as e:
            results["encode_unique_bitexact_on_device"] = {
                "error": str(e)[:300]
            }
        print(
            json.dumps(
                {
                    "encode_unique_bitexact_on_device": results[
                        "encode_unique_bitexact_on_device"
                    ]
                }
            ),
            flush=True,
        )

    # in-scan repetition protocol — see phase_pallas_vs_scan
    reps = 5
    for impl in ("scatter", "scatter_unique", "gather", "gather2"):
        if not _todo(results, "encode_%s" % impl):
            continue
        try:

            @jax.jit
            def run(i0, impl=impl):
                def body(carry, _):
                    salt, acc = carry
                    i = i0.at[0, 0].set(
                        jnp.int64(1414142122274) + salt.astype(jnp.int64)
                    )
                    bufs, lens = ce.membership_rows(
                        u, pres, stat, i, max_digits=14, impl=impl
                    )
                    # position-weighted digest over valid bytes only
                    # (impls differ in padding garbage past each row's
                    # length; a plain sum would be permutation-invariant
                    # and blind to misplaced bytes)
                    col = jnp.arange(bufs.shape[1], dtype=jnp.uint32)
                    row = jnp.arange(bufs.shape[0], dtype=jnp.uint32)
                    valid = col[None].astype(jnp.int32) < lens[:, None]
                    w = (col[None] + 1) * (row[:, None] + 1)
                    digest = jnp.sum(
                        jnp.where(valid, bufs.astype(jnp.uint32) * w, 0),
                        dtype=jnp.uint32,
                    ) + jnp.sum(lens).astype(jnp.uint32)
                    return (salt + 200, (acc + digest).astype(jnp.uint32)), (
                        digest
                    )

                (s, acc), ds = jax.lax.scan(
                    body,
                    (jnp.int32(200), jnp.uint32(0)),
                    None,
                    length=reps,
                )
                return acc, ds[-1]

            np.asarray(run(inc)[0])  # compile + warm, forced
            t0 = time.perf_counter()
            acc, last = run(inc.at[1, 1].set(7))
            last = int(np.asarray(last))
            dt = (time.perf_counter() - t0) / reps
            # digest persisted in the artifact: stable across crash-resume
            ref = results.get("encode_digest")
            if ref is not None:
                assert last == ref, "encode impl digest mismatch"
            else:
                results["encode_digest"] = last
            results["encode_%s" % impl] = {
                "ms": round(dt * 1e3, 2),
                "protocol": "in-scan x%d" % reps,
            }
        except Exception as e:
            results["encode_%s" % impl] = {"error": str(e)[:300]}


def phase_fused_parity(results: dict) -> None:
    """The round-6 fused pipeline on-chip, A/B'd against the classic
    composition at the 1k all-dirty parity shape, plus engine-level
    quiet and churn windows under the fused bounded recompute.

    The checksum-digest cross-check between the two pipelines is a
    device-level bit-exactness gate (the same role
    encode_unique_bitexact_on_device plays for the scatter promise):
    interpret-mode tests can't catch a TPU-lowering-only divergence in
    the streaming kernel's shift/select ladder."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.models.sim.cluster import default_addresses
    from ringpop_tpu.ops import checksum_encode as ce
    from ringpop_tpu.ops import fused_checksum as fc
    from ringpop_tpu.ops import jax_farmhash as jfh

    n = 1024
    u = ce.Universe.from_addresses(default_addresses(n))
    pres = jnp.ones((n, n), bool)
    stat = jnp.zeros((n, n), jnp.int32)
    inc = jnp.full((n, n), 1414142122274, jnp.int64)
    reps = 5

    def timed(key, fn):
        if not _todo(results, key):
            return
        try:

            @jax.jit
            def run(i0):
                def body(carry, _):
                    salt, acc = carry
                    i = i0.at[0, 0].set(
                        jnp.int64(1414142122274) + salt.astype(jnp.int64)
                    )
                    cs = fn(i)  # [n] uint32 checksums
                    digest = jnp.sum(cs, dtype=jnp.uint32)
                    return (
                        (salt + 200, (acc + digest).astype(jnp.uint32)),
                        digest,
                    )

                (s, acc), ds = jax.lax.scan(
                    body, (jnp.int32(200), jnp.uint32(0)), None, length=reps
                )
                return acc, ds[-1]

            np.asarray(run(inc)[0])  # compile + warm, forced
            t0 = time.perf_counter()
            acc, last = run(inc.at[1, 1].set(7))
            last = int(np.asarray(last))
            dt = (time.perf_counter() - t0) / reps
            ref = results.get("fused_digest")
            if ref is not None and last != ref:
                results["fused_digest_MISMATCH_%s" % key] = last
            elif ref is None:
                results["fused_digest"] = last
            row_bytes = int(
                np.asarray(u.addr_len).sum() + n * (5 + 13 + 1) - 1
            )
            results[key] = {
                "ms": round(dt * 1e3, 2),
                "encode_mb_per_s": round(n * row_bytes / dt / 1e6, 1),
                "protocol": "in-scan x%d" % reps,
            }
        except Exception as e:
            results[key] = {"error": str(e)[:300]}
        print(json.dumps({key: results.get(key)}), flush=True)

    def composed(i):
        bufs, lens = ce.membership_rows(u, pres, stat, i, max_digits=14)
        return jfh.hash32_rows(bufs, lens)

    def fused(i):
        return fc.membership_checksums(u, pres, stat, i, max_digits=14)

    timed("parity_composed_encode_hash", composed)
    timed("parity_fused_encode_hash", fused)

    # engine-level windows under the fused bounded recompute (auto
    # resolution on TPU), quiet + churn, replay-accounted
    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster

    for key, churn in (
        ("fused_engine_quiet_1k", False),
        ("fused_engine_churn_1k", True),
    ):
        if not _todo(results, key):
            continue
        try:
            sim = SimCluster(
                n=n, params=engine.SimParams(n=n, checksum_mode="farmhash")
            )
            sim.bootstrap()
            if sim.run_until_converged(max_ticks=96, quiet_after=1) < 0:
                raise RuntimeError("no convergence before window")
            ticks = 256
            sched = (
                EventSchedule.churn_window(ticks, n)  # bench's shape
                if churn
                else EventSchedule(ticks=ticks, n=n)
            )
            sim.run(sched)
            jax.block_until_ready(sim.state)
            warm = sim.parity_replays
            t0 = time.perf_counter()
            sim.run(sched)
            jax.block_until_ready(sim.state)
            dt = time.perf_counter() - t0
            results[key] = {
                "node_ticks_per_sec": round(n * ticks / dt, 1),
                "replays_in_window": sim.parity_replays - warm,
                "fused": sim.params.fused_checksum,
                "dirty_batch": sim.params.dirty_batch,
            }
        except Exception as e:
            results[key] = {"error": str(e)[:300]}
        print(json.dumps({key: results.get(key)}), flush=True)


def phase_fused_exchange(results: dict) -> None:
    """Round-10 hot-path rewrite on-chip: the sortless-PRP partner
    permutation + fused push-pull exchange megakernel, A/B'd against the
    argsort / pure-XLA / inline twins at the 1M churn-storm shape, plus
    a DEVICE-LEVEL bitwise gate (same seed + schedule across configs —
    the final heard mask / checksums / truth must match bit-for-bit;
    interpret-mode CPU tests can't catch a TPU-lowering-only divergence
    in the kernel's OR/popcount/delta ladder) and the exchange op's
    isolated GB/s, pallas vs the XLA twin, on the storm's own [1M, U/32]
    mask."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.models.sim import storm as storm_mod
    from ringpop_tpu.models.sim.storm import ScalableCluster, StormSchedule
    from ringpop_tpu.ops import exchange as exch

    n, ticks = 1_000_000, 60
    configs = (
        ("sortless_pallas", "sortless", "pallas"),  # the rewrite
        ("sortless_xla", "sortless", "xla"),  # op twin (sharding shape)
        ("argsort_off", "argsort", "off"),  # pre-round-10 baseline
    )
    # lazy: a crash-resumed session with every config done must not
    # rebuild the [60, 1M] schedule planes (the _todo protocol)
    sched = None
    gate_states: dict = {}
    for label, pi, fe in configs:
        key = "exchange_1m_%s" % label
        if not _todo(results, key):
            continue
        if sched is None:
            sched = StormSchedule.churn_storm(
                ticks, n, fraction=0.10, fail_tick=2, seed=0
            )
        try:
            params = es.ScalableParams(
                n=n, u=512, perm_impl=pi, fused_exchange=fe
            )
            # seed-0 run: cold compile + the bitwise-gate state
            cluster = ScalableCluster(n=n, params=params, seed=0)
            t0 = time.perf_counter()
            cluster.run(sched)
            jax.block_until_ready(cluster.state)
            cold = time.perf_counter() - t0
            gate_states[label] = {
                "heard": np.asarray(cluster.state.heard),
                "checksum": np.asarray(cluster.state.checksum),
                "truth": np.asarray(cluster.state.truth_status),
            }
            # warm wall-clock: min of 2, distinct seeds (the tunnel
            # memoizes identical (executable, inputs) pairs — storm_1m's
            # protocol)
            warms = []
            for r in range(2):
                c2 = ScalableCluster(n=n, params=params, seed=r + 1)
                t0 = time.perf_counter()
                c2.run(sched)
                jax.block_until_ready(c2.state)
                warms.append(time.perf_counter() - t0)
            results[key] = {
                "n": n,
                "ticks": ticks,
                "perm_impl": pi,
                "fused_exchange": fe,
                "cold_s": round(cold, 2),
                "warm_s": round(min(warms), 2),
                "warm_runs_s": [round(w, 2) for w in warms],
                "node_ticks_per_sec": round(n * ticks / min(warms), 1),
            }
        except Exception as e:
            results[key] = {"error": str(e)[:300]}
        print(json.dumps({key: results.get(key)}), flush=True)

    if _todo(results, "exchange_1m_bitwise_equal"):
        if len(gate_states) > 1:
            ref_label = next(iter(gate_states))
            ref = gate_states[ref_label]
            mismatches = [
                "%s.%s" % (label, field)
                for label, st in gate_states.items()
                for field in ("heard", "checksum", "truth")
                if not (st[field] == ref[field]).all()
            ]
            results["exchange_1m_bitwise_equal"] = {
                "configs": sorted(gate_states),
                "reference": ref_label,
                "equal": not mismatches,
                "mismatches": mismatches,
            }
        else:
            # crash-resume honesty: the configs' numbers were cached from
            # an earlier attempt, so the cross-config states needed for
            # the device gate don't exist in THIS process — say so
            # instead of silently never writing the acceptance key
            results["exchange_1m_bitwise_equal"] = {
                "skipped": (
                    "config results cached from an earlier attempt — "
                    "delete the exchange_1m_* keys and re-run this "
                    "phase in one session to evaluate the device gate"
                ),
            }
        print(
            json.dumps(
                {"exchange_1m_bitwise_equal": results["exchange_1m_bitwise_equal"]}
            ),
            flush=True,
        )

    # isolated op bandwidth at the 1M mask shape — the shared in-scan
    # probe + traffic model (ops.exchange.measure_bandwidth), same
    # convention as bench.py's scalable phase and the roofline artifact.
    # Arrays built lazily (3 x 64 MB of device masks — skip entirely on
    # a resumed session with both impls done)
    w = 512 // 32
    iters = 16
    op_args = None
    for impl in ("pallas", "xla"):
        key = "exchange_op_1m_gbps_%s" % impl
        if not _todo(results, key):
            continue
        if op_args is None:
            rng = np.random.default_rng(7)
            heard = jnp.asarray(
                rng.integers(0, 2**32, (n, w), dtype=np.uint32)
            )
            op_args = (
                heard,
                jnp.roll(heard, 1, axis=0),
                jnp.roll(heard, -1, axis=0),
                jnp.asarray(
                    rng.integers(0, 2**32, (w * 32,), dtype=np.uint32)
                ),
            )
        heard, pulled, pushed, r_delta = op_args
        try:
            gbps, sec = exch.measure_bandwidth(
                heard, pulled, pushed, r_delta, impl=impl, iters=iters
            )
            results[key] = {
                "gbps": round(gbps, 2),
                "ms_per_step": round(sec * 1e3, 3),
                "modeled_bytes_per_step": exch.step_traffic_bytes(n, w),
                "protocol": "in-scan x%d" % iters,
            }
        except Exception as e:
            results[key] = {"error": str(e)[:300]}
        print(json.dumps({key: results.get(key)}), flush=True)

    # three distinct 1M storm programs were compiled above — release them
    # before the epidemic/batched/storm phases pin their own
    storm_mod.clear_executable_cache()


def phase_weak_scaling(results: dict) -> None:
    """Round-14 weak scaling: 1M nodes per chip through the shard_map'd
    exchange plane (ROADMAP item 2's capture path).  Three deliverables:

    - a shard ladder at ``n = 1M * S`` (S up to the device count) with
      warm node-ticks/s per rung and the weak-scaling efficiency
      ``rate(S) / (S * rate(1))``;
    - the single-chip <60 s check: the 60-tick 1M churn storm through
      the PLANE (north-star row 4 — RESULTS.md round 3 measured 486 s
      warm on CPU; the chip number decides it);
    - the bitwise overlap gate: the SAME 1M seeded storm, sharded over
      every device vs the single-device engine — final heard/checksum/
      truth must match bit-for-bit (the CPU tests prove n<=64k; this is
      the on-chip proof at the real shape).

    On a CPU fallback (no tunnel) the ladder shrinks to a marked
    rehearsal shape so the phase stays runnable end-to-end."""
    import jax
    import numpy as np

    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.models.sim import storm as storm_mod
    from ringpop_tpu.models.sim.storm import ScalableCluster, StormSchedule
    from ringpop_tpu.ops import exchange as exch
    from ringpop_tpu.parallel import mesh as pmesh

    on_tpu = jax.default_backend() == "tpu"
    n_per = int(
        os.environ.get(
            "TPU_MEASURE_WEAK_N_PER_SHARD",
            "1000000" if on_tpu else "8192",
        )
    )
    ticks = int(os.environ.get("TPU_MEASURE_WEAK_TICKS", "60"))
    devs = len(jax.devices())
    ladder = [s for s in (1, 2, 4, 8, 16, 32) if s <= devs]
    rates: dict = {}
    for s in ladder:
        key = "weak_scaling_%dx%d" % (s, n_per)
        if not _todo(results, key):
            prev = results[key]
            if isinstance(prev, dict) and "node_ticks_per_sec" in prev:
                rates[s] = prev["node_ticks_per_sec"]
            continue
        try:
            n = n_per * s
            params = es.ScalableParams(n=n, u=512)
            sched = StormSchedule.churn_storm(
                ticks, n, fraction=0.10, fail_tick=2, seed=0
            )
            storm = pmesh.ShardedStorm(
                n=n, mesh=pmesh.make_mesh(s), params=params, seed=0
            )
            t0 = time.perf_counter()
            storm.run(sched)
            jax.block_until_ready(storm.state)
            cold = time.perf_counter() - t0
            # warm wall-clock: min of 2, distinct seeds (the tunnel
            # memoizes identical (executable, inputs) pairs)
            warms = []
            for r in range(2):
                s2 = pmesh.ShardedStorm(
                    n=n, mesh=pmesh.make_mesh(s), params=params, seed=r + 1
                )
                t0 = time.perf_counter()
                s2.run(sched)
                jax.block_until_ready(s2.state)
                warms.append(time.perf_counter() - t0)
            rate = n * ticks / min(warms)
            rates[s] = round(rate, 1)
            results[key] = {
                "n": n,
                "shards": s,
                "ticks": ticks,
                "cold_s": round(cold, 2),
                "warm_s": round(min(warms), 2),
                "warm_runs_s": [round(w2, 2) for w2 in warms],
                "node_ticks_per_sec": rates[s],
                "exchange_mode": storm.exchange_mode,
                "exchange_impl": storm.exchange_impl,
                "exchange_cap": storm.exchange_cap,
                "cpu_rehearsal": not on_tpu,  # NOT a chip number
            }
            if s == 1 and n_per == 1_000_000:
                # the north-star check rides the single-chip rung
                results[key]["under_60s"] = bool(min(warms) < 60.0)
        except Exception as e:
            results[key] = {"error": str(e)[:300]}
        print(json.dumps({key: results.get(key)}), flush=True)

    # 1 must be present: a failed first rung (e.g. a transient tunnel
    # error) must not KeyError the summary and skip the bitwise gate +
    # the executable-cache clears below
    if len(rates) > 1 and 1 in rates and _todo(
        results, "weak_scaling_efficiency"
    ):
        top = max(rates)
        results["weak_scaling_efficiency"] = {
            "shards": top,
            "n_per_shard": n_per,
            "efficiency": round(rates[top] / (top * rates[1]), 3),
            "traffic_model": exch.cross_shard_traffic_bytes(
                n_per * top, 512 // 32, top
            ),
            "cpu_rehearsal": not on_tpu,
        }
        print(
            json.dumps(
                {"weak_scaling_efficiency": results["weak_scaling_efficiency"]}
            ),
            flush=True,
        )

    # bitwise overlap gate at n = n_per: sharded over every device vs
    # the single-device engine, same seed + schedule
    if devs > 1 and _todo(results, "weak_scaling_bitwise_equal"):
        try:
            n = n_per
            params = es.ScalableParams(n=n, u=512)
            sched = StormSchedule.churn_storm(
                ticks, n, fraction=0.10, fail_tick=2, seed=0
            )
            single = ScalableCluster(n=n, params=params, seed=0)
            single.run(sched)
            # largest power-of-two shard count (n = 1M divides cleanly)
            gate_shards = 1 << (devs.bit_length() - 1)
            sharded = pmesh.ShardedStorm(
                n=n,
                mesh=pmesh.make_mesh(gate_shards),
                params=params,
                seed=0,
            )
            sharded.run(
                StormSchedule.churn_storm(
                    ticks, n, fraction=0.10, fail_tick=2, seed=0
                )
            )
            mismatches = [
                f
                for f in ("heard", "checksum", "truth_status")
                if not (
                    np.asarray(getattr(single.state, f))
                    == np.asarray(getattr(sharded.state, f))
                ).all()
            ]
            results["weak_scaling_bitwise_equal"] = {
                "n": n,
                "shards": int(sharded.mesh.devices.size),
                "equal": not mismatches,
                "mismatches": mismatches,
            }
        except Exception as e:
            results["weak_scaling_bitwise_equal"] = {"error": str(e)[:300]}
        print(
            json.dumps(
                {
                    "weak_scaling_bitwise_equal": results[
                        "weak_scaling_bitwise_equal"
                    ]
                }
            ),
            flush=True,
        )

    # several distinct 1M+ storm programs were compiled — release them
    storm_mod.clear_executable_cache()
    pmesh.clear_executable_cache()


def phase_route(results: dict) -> None:
    """Round-11 routing plane on-chip: the coupled membership+routing
    scan at n=1M under sparse churn — batched Zipf queries/s with the
    incremental bucketed ring vs the full-jnp.sort twin, a DEVICE-LEVEL
    bitwise gate on the materialized truth rings + counter streams
    (same seeds + schedule across impls), and the isolated
    ring-rebuild A/B (per-tick incremental re-merge vs full sort) —
    the next chip session's capture of BENCH_r11's CPU numbers."""
    import sys

    import jax
    import numpy as np

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import bench as bench_mod

    from ringpop_tpu.models.route import plane as route_plane

    n, ticks, q, churn = 1_000_000, 16, 1 << 20, 32
    runs: dict = {}
    for impl in ("incremental", "full"):
        key = "route_1m_%s" % impl
        if not _todo(results, key):
            continue
        try:
            rate, elapsed, driver, rm = bench_mod._route_rate(
                n, ticks, q, churn, impl
            )
            runs[impl] = (driver, rm)
            results[key] = {
                "n": n,
                "ticks": ticks,
                "q": q,
                "churn_per_tick": churn,
                "ring_impl": impl,
                "bucket_bits": driver.route_params.bucket_bits,
                "queries_per_sec": round(rate, 1),
                "lookups_per_sec": round(4 * rate, 1),
                "misroutes": int(np.asarray(rm.route_misroutes).sum()),
                "keys_diverged": int(
                    np.asarray(rm.route_keys_diverged).sum()
                ),
                "checksum_rejects": int(
                    np.asarray(rm.route_checksum_rejects).sum()
                ),
            }
        except Exception as e:
            results[key] = {"error": str(e)[:300]}
        print(json.dumps({key: results.get(key)}), flush=True)

    if _todo(results, "route_1m_bitwise_equal"):
        if len(runs) == 2:
            ri, rm_i = runs["incremental"]
            rf, rm_f = runs["full"]
            ring_eq = bool(
                (
                    np.asarray(ri.truth_ring())
                    == np.asarray(rf.truth_ring())
                ).all()
            )
            metric_eq = all(
                bool(
                    (
                        np.asarray(getattr(rm_i, f))
                        == np.asarray(getattr(rm_f, f))
                    ).all()
                )
                for f in rm_i._fields
            )
            results["route_1m_bitwise_equal"] = {
                "ring_equal": ring_eq,
                "metrics_equal": metric_eq,
            }
        else:
            results["route_1m_bitwise_equal"] = {
                "skipped": "cross-impl states unavailable after resume; "
                "delete the route_1m_* entries and rerun for the gate"
            }
        print(
            json.dumps(
                {"route_1m_bitwise_equal": results["route_1m_bitwise_equal"]}
            ),
            flush=True,
        )

    if _todo(results, "route_rebuild_ab_1m"):
        try:
            results["route_rebuild_ab_1m"] = bench_mod._ring_rebuild_ab(
                n, 16, 32, churn
            )
        except Exception as e:
            results["route_rebuild_ab_1m"] = {"error": str(e)[:300]}
        print(
            json.dumps({"route_rebuild_ab_1m": results["route_rebuild_ab_1m"]}),
            flush=True,
        )


def phase_observatory(results: dict) -> None:
    """Round-15 performance observatory on-chip: (a) device-side
    latency-histogram capture at 1M — a hist-enabled routed storm whose
    drained p50/p95/p99 (routing retry depth / reroute hops, rumor
    propagation latency, suspicion durations) are banked for the chip
    session, and (b) host dispatch-timer phase breakdowns of the 1M
    scalable storm (compile-vs-warm split via the jit-cache probe, warm
    wall percentiles per phase) — the per-phase attribution ROADMAP
    item 5 asks this session to bank."""
    import sys

    import jax

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import bench as bench_mod

    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.models.sim.storm import ScalableCluster, StormSchedule
    from ringpop_tpu.obs import perf as obs_perf

    if _todo(results, "observatory_hist_1m"):
        try:
            results["observatory_hist_1m"] = bench_mod._hist_capture(
                1_000_000, 16, 1 << 18, 32
            )
        except Exception as e:
            results["observatory_hist_1m"] = {"error": str(e)[:300]}
        print(
            json.dumps(
                {"observatory_hist_1m": results["observatory_hist_1m"]}
            ),
            flush=True,
        )

    if _todo(results, "observatory_phase_timing_1m"):
        try:
            n, ticks = 1_000_000, 16
            sc = ScalableCluster(
                n=n, params=es.ScalableParams(n=n, u=512), seed=0
            )
            timer = obs_perf.wrap_cluster(sc)
            sched = StormSchedule.churn_storm(
                ticks, n, fraction=0.10, fail_tick=1, seed=0
            )
            for _ in range(4):  # 1 compile-carrying + 3 warm scans
                sc.run(sched)
            jax.block_until_ready(sc.state)
            results["observatory_phase_timing_1m"] = {
                "n": n,
                "ticks": ticks,
                "phases": timer.summary(),
                "protocol_delay_ms": timer.protocol_delay_ms("scan"),
            }
        except Exception as e:
            results["observatory_phase_timing_1m"] = {
                "error": str(e)[:300]
            }
        print(
            json.dumps(
                {
                    "observatory_phase_timing_1m": results[
                        "observatory_phase_timing_1m"
                    ]
                }
            ),
            flush=True,
        )


def phase_request_observatory(results: dict) -> None:
    """Round-19 request observatory on-chip: a 1M-node routed storm
    with hash-of-key sampling on — (a) the host-side drain cost of the
    sampled record buffer, (b) the honest drop rate when the buffer is
    sized BELOW worst case (counts-never-overwrites means drops are
    measured, not silent), and (c) the sliding-window SLO p99 against
    the full-histogram p99 over the same span (must agree exactly when
    the window covers the whole run — the windowed extraction is the
    same nearest-rank machinery)."""
    import sys
    import time

    import numpy as np

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    from ringpop_tpu.models.route import reqtrace as rt
    from ringpop_tpu.models.route.plane import (
        ROUTE_HIST_TRACKS,
        RoutedStorm,
        RouteParams,
    )
    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.models.sim.storm import StormSchedule
    from ringpop_tpu.obs import histograms as oh
    from ringpop_tpu.obs.slo import SLOTarget, SLOWindowPlane
    from ringpop_tpu.ops import histogram as hg

    if not _todo(results, "request_observatory_1m"):
        return
    try:
        n, window, windows, q, churn = 1_000_000, 8, 2, 1 << 18, 32
        sample_log2 = 4  # trace 1/16 of the key space
        # sized at HALF the expected sampled volume: the drop rate at
        # cap is a measurement target here, not a failure
        cap = rt.req_capacity_for(q, window) >> (sample_log2 + 1)
        rs = RoutedStorm(
            n,
            params=es.ScalableParams(n=n, u=512),
            route=RouteParams(
                n=n,
                queries_per_tick=q,
                histograms=True,
                reqtrace=True,
                req_capacity=cap,
                req_sample_log2=sample_log2,
            ),
            seed=0,
        )
        slo = SLOWindowPlane(
            SLOTarget(name="route"), window_len=windows
        )
        full_hist = np.zeros(
            (len(ROUTE_HIST_TRACKS), hg.NBUCKETS), np.int64
        )
        records = drops = 0
        drain_s = []
        rng = np.random.default_rng(0)
        for w in range(windows):
            sched = StormSchedule(ticks=window, n=n)
            for t in range(1, window):
                sched.kill[t, rng.choice(n, churn, replace=False)] = True
            _, rm = rs.run(sched)
            hist = np.asarray(rs.rstate.hist)
            full_hist += hist
            rs.drain_histograms(reset=True)
            slo.observe_route_window(w * window + window, hist, rm)
            t0 = time.perf_counter()
            drained = rs.drain_requests(reset=True)
            drain_s.append(time.perf_counter() - t0)
            records += len(drained["records"])
            drops += drained["drops"]
        row = slo.window_row(windows * window)
        full_p99 = oh.percentile(
            full_hist[ROUTE_HIST_TRACKS.index("retry_depth")], 99
        )
        full_p99 = None if full_p99 is None else full_p99["value"]
        results["request_observatory_1m"] = {
            "n": n,
            "ticks": windows * window,
            "q": q,
            "sample_log2": sample_log2,
            "req_capacity": cap,
            "records": records,
            "drops": drops,
            "drop_rate_at_cap": round(
                drops / max(records + drops, 1), 4
            ),
            "drain_s_mean": round(sum(drain_s) / len(drain_s), 4),
            "drain_s_max": round(max(drain_s), 4),
            "windowed_p99": row["p99"],
            "full_hist_p99": full_p99,
            "p99_agreement": row["p99"] == full_p99,
        }
    except Exception as e:
        results["request_observatory_1m"] = {"error": str(e)[:300]}
    print(
        json.dumps(
            {
                "request_observatory_1m": results[
                    "request_observatory_1m"
                ]
            }
        ),
        flush=True,
    )


def phase_mesh_observatory(results: dict) -> None:
    """Round-17 mesh observatory on-chip: (a) the per-shard exchange
    telemetry plane (ScalableParams.exchange_metrics) drained after a
    short sharded storm — measured wire bytes reconciled against the
    analytic cross-shard traffic model on the real interconnect, the
    same path scripts/check_traffic_model.py gates on CPU; and (b) an
    xprof capture over the sharded storm window
    (obs.xprof.capture) so the chip session banks per-HLO-op time
    attribution next to the wall clocks — keyed, where op names allow,
    to COST_BUDGET entry names."""
    import sys

    import jax

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.models.sim.storm import StormSchedule
    from ringpop_tpu.obs import exchange_stats as oxs
    from ringpop_tpu.obs import xprof as obs_xprof
    from ringpop_tpu.parallel import mesh as pmesh

    on_tpu = jax.default_backend() == "tpu"
    devs = len(jax.devices())
    shards = 1 << max(0, devs.bit_length() - 1)
    if shards < 2:
        results["mesh_observatory_drain"] = {
            "error": "need >= 2 devices, have %d" % devs
        }
        return
    n_per = int(
        os.environ.get(
            "TPU_MEASURE_OBSERVATORY_N_PER_SHARD",
            "1000000" if on_tpu else "8192",
        )
    )
    n, u, ticks = n_per * shards, 512, 8

    storm = None
    if _todo(results, "mesh_observatory_drain"):
        try:
            params = es.ScalableParams(
                n=n, u=u, exchange_metrics=shards
            )
            storm = pmesh.ShardedStorm(
                n=n, mesh=pmesh.make_mesh(shards), params=params, seed=0
            )
            sched = StormSchedule.churn_storm(
                ticks, n, fraction=0.10, fail_tick=2, seed=0
            )
            storm.run(sched)
            jax.block_until_ready(storm.state)
            drained = storm.drain_exchange_metrics(reset=False)
            rec = oxs.reconcile(drained["totals"], n=n, w=u // 32)
            rec["cpu_rehearsal"] = not on_tpu  # NOT a chip number off-TPU
            results["mesh_observatory_drain"] = rec
        except Exception as e:
            results["mesh_observatory_drain"] = {"error": str(e)[:300]}
        print(
            json.dumps(
                {"mesh_observatory_drain": results["mesh_observatory_drain"]}
            ),
            flush=True,
        )

    if _todo(results, "mesh_observatory_xprof"):
        try:
            if storm is None:
                params = es.ScalableParams(
                    n=n, u=u, exchange_metrics=shards
                )
                storm = pmesh.ShardedStorm(
                    n=n,
                    mesh=pmesh.make_mesh(shards),
                    params=params,
                    seed=0,
                )
            sched = StormSchedule.churn_storm(
                ticks, n, fraction=0.10, fail_tick=2, seed=0
            )
            trace_dir = os.path.join(
                os.path.dirname(os.path.abspath(OUT_PATH)) or ".",
                "xprof-mesh-observatory",
            )
            row = obs_xprof.capture(
                lambda: storm.run(sched),
                trace_dir,
                phase="mesh-observatory-%dx%d" % (shards, n_per),
                warmup=1,
                repeats=1,
                shards=shards,
                n=n,
            )
            print(obs_xprof.render_table(row), flush=True)
            # the full per-op table lives in the runlog/trace artifacts;
            # the sweep result keeps the headline + top ops
            results["mesh_observatory_xprof"] = {
                "phase": row["phase"],
                "ok": row["ok"],
                "wall_s": row["wall_s"],
                "num_trace_files": row["num_trace_files"],
                "total_self_us": row["total_self_us"],
                "top_ops": row["ops"][:5],
                "trace_dir": row["trace_dir"],
                "error": row.get("error"),
                "cpu_rehearsal": not on_tpu,
            }
        except Exception as e:
            results["mesh_observatory_xprof"] = {"error": str(e)[:300]}
        print(
            json.dumps(
                {"mesh_observatory_xprof": results["mesh_observatory_xprof"]}
            ),
            flush=True,
        )


def phase_fused_full(results: dict) -> None:
    """Round-16 fused full-fidelity tick on-chip: the full [N, N]
    engine's fused (pallas streaming kernels) vs xla-twin vs classic
    phase-by-phase node-ticks/s at chip-viable sizes, on the same
    dissemination-active leave/rejoin window bench.py's full phase
    measures on CPU — with the bitwise final-state gate asserted per
    rung.  This is where the fused tick's real thesis (one VMEM pass
    per [N_tile, N] site instead of ~a dozen HBM temporaries) gets its
    first chip numbers; the CPU ladder (BENCH_r15) only proves the twin
    + gate harness."""
    import sys

    import jax
    import numpy as np

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import bench as bench_mod

    from ringpop_tpu.models.sim import engine

    for n in (1024, 4096):
        key = "fused_full_%d" % n
        if not _todo(results, key):
            continue
        try:
            ticks = 8
            rung: dict = {"n": n, "ticks": ticks}
            rates = {}
            states = {}
            for mode in ("off", "xla", "pallas"):
                rate, _el, sim = bench_mod._full_rate(n, ticks, mode)
                rates[mode] = round(rate, 1)
                states[mode] = jax.device_get(sim.state)
            rung["node_ticks_per_sec"] = rates
            rung["fused_vs_off"] = round(
                rates["pallas"] / rates["off"], 3
            )
            rung["xla_vs_off"] = round(rates["xla"] / rates["off"], 3)
            rung["bitwise_equal"] = bool(
                all(
                    np.array_equal(
                        np.asarray(getattr(states[m], f)),
                        np.asarray(getattr(states["off"], f)),
                    )
                    for m in ("xla", "pallas")
                    for f in engine.SimState._fields
                    if getattr(states["off"], f) is not None
                )
            )
            if not rung["bitwise_equal"]:
                rung["error"] = "fused trajectory diverged from classic"
            results[key] = rung
        except Exception as e:
            results[key] = {"error": str(e)[:300]}
        _drop_executables()
        print(json.dumps({key: results[key]}), flush=True)


def phase_ckpt(results: dict) -> None:
    """Round-13 recovery plane on-chip: checkpoint-cadence overhead and
    save/restore MB/s at n=1M (device->host gather + atomic manifest
    write, single-file vs sharded A/B with bitwise roundtrip gates) —
    the chip capture of BENCH_r12's CPU ckpt_* fields.  The number that
    matters for the weak-scaling runs: what fraction of storm wall time
    a checkpoint_every cadence costs when preemption is the norm."""
    import sys

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import bench as bench_mod

    n = int(os.environ.get("TPU_MEASURE_CKPT_N", "1000000"))
    ticks = int(os.environ.get("TPU_MEASURE_CKPT_TICKS", "8"))
    every = int(os.environ.get("TPU_MEASURE_CKPT_EVERY", "4"))
    out = bench_mod._ckpt_rate(n, ticks, every)
    for k, v in out.items():
        results["tpu_%s" % k] = v
    print(json.dumps({k: out[k] for k in sorted(out) if "mbps" in k or "frac" in k}))


def phase_epidemic_100k(results: dict) -> None:
    import jax
    import numpy as np

    from ringpop_tpu.models.sim import engine_scalable as es

    n, ticks = 100_000, 60
    for gate in (True, False):
        if not _todo(
            results, "epidemic_100k_5pct_loss" + ("" if gate else "_nogate")
        ):
            continue
        params = es.ScalableParams(
            n=n, u=512, packet_loss=0.05, gate_phases=gate
        )
        state = es.init_state(params, seed=0)
        step = jax.jit(functools.partial(es.tick, params=params))
        state, m = step(state, es.ChurnInputs.quiet(n))  # compile
        jax.block_until_ready(state)
        # median of 3 repetitions of the 60-tick window (hygiene pass,
        # round-5 verdict item 7); state evolves between reps, so no two
        # executions are identical and the tunnel result cache is moot
        rates = []
        susp = refutes = 0
        for _ in range(3):
            susp = refutes = 0  # per-window counts (the 60-tick
            # denominator every prior round's artifact used); the
            # recorded values are the LAST window's
            t0 = time.perf_counter()
            for _ in range(ticks):
                state, m = step(state, es.ChurnInputs.quiet(n))
                susp += int(m.suspects_published)
                refutes += int(m.refutes_published)
            jax.block_until_ready(state)
            rates.append(n * ticks / (time.perf_counter() - t0))
        rates.sort()
        med = rates[1]
        key = "epidemic_100k_5pct_loss" + ("" if gate else "_nogate")
        results[key] = {
            "node_ticks_per_sec": round(med, 1),
            "min_med_max": [round(r, 1) for r in rates],
            "ms_per_tick": round(1e3 * n / med, 2),
            "false_suspects": susp,
            "refutes": refutes,
            "permanent_faulty": int(
                (np.asarray(state.truth_status) == es.FAULTY).sum()
            ),
        }
        print(json.dumps({key: results[key]}), flush=True)


def phase_batched(results: dict) -> None:
    """B independent 1k clusters as one vmapped program (the
    TPU-utilization configuration; models/sim/batched.py) — aggregate
    and per-cluster node-ticks/s."""
    import time as _time

    import jax
    import numpy as np

    from ringpop_tpu.models.sim.batched import BatchedSimClusters
    from ringpop_tpu.models.sim.cluster import EventSchedule

    if not _todo(results, "batched_8x1k"):
        return
    # 64 ticks, NOT the 256 the single-cluster headline uses: the 8x1k
    # vmapped 256-tick scan kernel-faults the tunnel's TPU worker
    # (round-4 artifacts), while 32/64-tick scans run.  Treat the
    # number as an existence proof, not a throughput claim: same-config
    # batched runs measured 6x apart within minutes on this tunnel.
    b, n, ticks = 8, 1024, 64
    bat = BatchedSimClusters(b=b, n=n, seed=0)
    bat.bootstrap()
    sched = EventSchedule(ticks=ticks, n=n)
    bat.run(sched)  # compile + warm
    jax.block_until_ready(bat.state)
    rates = []
    ms = None
    for _ in range(3):  # median-of-3 (round-5 hygiene pass)
        t0 = _time.perf_counter()
        ms = bat.run(sched)
        jax.block_until_ready(bat.state)
        rates.append(b * n * ticks / (_time.perf_counter() - t0))
    rates.sort()
    results["batched_8x1k"] = {
        "clusters": b,
        "ticks": ticks,  # 64, NOT the headline's 256 — see cap above
        "aggregate_node_ticks_per_sec": round(rates[1], 1),
        "aggregate_min_med_max": [round(r, 1) for r in rates],
        "per_cluster_node_ticks_per_sec": round(rates[1] / b, 1),
        "converged": bool(np.asarray(ms.converged)[-1].all()),
        "caveat": "existence proof; 6x run-to-run variance observed",
    }
    print(json.dumps({"batched_8x1k": results["batched_8x1k"]}), flush=True)


def phase_convergence(results: dict) -> None:
    """The reference's convergence-time scenarios on the chip
    (benchmarks/convergence-time/scenario-runner.js:37-98 + scenarios/):
    single-node-failure and half-cluster-failure at 1k, convergence =
    all live checksums equal and fresh (scenario-runner.js:152-170);
    reports the reference's histogram fields."""
    from benchmarks.convergence_time import run_jax_sim

    for scenario in ("single-node-failure", "half-cluster-failure"):
        key = "convergence_%s" % scenario.replace("-", "_")
        if not _todo(results, key):
            continue
        try:
            results[key] = run_jax_sim(scenario, n=1024, cycles=10, seed=0)
        except Exception as e:
            results[key] = {"error": str(e)[:300]}
        print(json.dumps({key: results.get(key)}), flush=True)


def phase_storm_1m(results: dict) -> None:
    import jax
    import numpy as np

    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.models.sim.storm import ScalableCluster, StormSchedule

    n, ticks = 1_000_000, 60
    sched = StormSchedule.churn_storm(
        ticks, n, fraction=0.10, fail_tick=2, seed=0
    )
    for in_tick in (True, False):
        for gate in (True, False):
            key = (
                "storm_1m"
                + ("" if in_tick else "_deferred_checksums")
                + ("" if gate else "_nogate")
            )
            if not _todo(results, key):
                continue
            try:
                params = es.ScalableParams(
                    n=n, u=512, checksum_in_tick=in_tick, gate_phases=gate
                )
                cluster = ScalableCluster(n=n, params=params, seed=0)
                t0 = time.perf_counter()
                cluster.run(sched)
                jax.block_until_ready(cluster.state)
                cold = time.perf_counter() - t0
                if not in_tick:
                    # precompile the standalone checksum recompute OUTSIDE
                    # the timed window (in-tick mode reads state.checksum
                    # and needs no extra program)
                    jax.block_until_ready(
                        es.compute_checksums(cluster.state, params)
                    )

                # warm wall-clock: min of 2 full runs (tunnel background
                # load swings single runs by tens of percent; the round-3
                # artifact even recorded warm > cold).  Distinct seeds per
                # run: with the shared executable cache, seed=0 would make
                # every warm run the identical (executable, inputs) pair
                # the tunnel is known to memoize (RESULTS.md round 4) —
                # the work per seed is statistically identical
                warms = []
                for r in range(2):
                    cluster2 = ScalableCluster(n=n, params=params, seed=r + 1)
                    t0 = time.perf_counter()
                    metrics = cluster2.run(sched)
                    if in_tick:
                        cs = cluster2.state.checksum
                    else:
                        cs = es.compute_checksums(cluster2.state, params)
                    cs = jax.block_until_ready(cs)
                    warms.append(time.perf_counter() - t0)
                warm = min(warms)
                live = np.asarray(cluster2.state.proc_alive)
                ncs = np.unique(np.asarray(cs)[live]).size
                results[key] = {
                    "n": n,
                    "ticks": ticks,
                    "cold_s": round(cold, 2),
                    "warm_s": round(warm, 2),
                    "warm_runs_s": [round(w, 2) for w in warms],
                    "under_60s": bool(warm < 60.0),
                    "converged": bool(ncs == 1),
                    "distinct_checksums": int(ncs),
                    "full_coverage_final": bool(
                        np.asarray(metrics.full_coverage)[-1]
                    ),
                }
            except Exception as e:
                results[key] = {"error": str(e)[:300]}
            print(json.dumps({key: results.get(key)}), flush=True)


def _drop_executables() -> None:
    """Release each phase's compiled programs (the shared lru_caches pin
    them for process life otherwise — four distinct 1M-node storm
    programs by the final phase)."""
    for modpath in (
        "ringpop_tpu.models.sim.cluster",
        "ringpop_tpu.models.sim.batched",
        "ringpop_tpu.models.sim.storm",
        "ringpop_tpu.parallel.mesh",
    ):
        try:
            m = __import__(modpath, fromlist=[modpath.rsplit(".", 1)[1]])
            m.clear_executable_cache()
        except Exception:
            pass  # a phase that never imported the module


def _backend_alive() -> bool:
    """Tiny device probe: a crashed/restarted TPU worker leaves the whole
    process's backend dead (every later call fails UNAVAILABLE)."""
    try:
        import jax
        import jax.numpy as jnp

        jax.block_until_ready(jnp.arange(8) + 1)
        return True
    except Exception:
        return False


_PHASE_RETRIES = int(os.environ.get("TPU_MEASURE_PHASE_RETRIES", "2"))


def main() -> int:
    # repo-pointing PYTHONPATH entries break the axon discovery helper
    # (silent CPU fallback); imports ride the sys.path.insert above
    from ringpop_tpu.utils.util import reexec_retry, scrub_repo_pythonpath

    scrub_repo_pythonpath(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

    import ringpop_tpu  # noqa: F401  (x64 config before backend init)

    # TPU_MEASURE_FORCE_HOST=<k>: rehearse the sweep (notably the
    # weak_scaling ladder) on k forced virtual CPU devices — routed
    # through utils.util.pin_cpu_platform, the ONE place the device-
    # count flag is spelled (round-14 satellite; the multichip dryrun
    # and bench.py's mesh phase share it).  Skips the tunnel wait: a
    # forced-host run is an intentional CPU run, and every phase marks
    # its numbers with the platform.
    force_host = os.environ.get("TPU_MEASURE_FORCE_HOST")
    if force_host:
        from ringpop_tpu.utils.util import pin_cpu_platform

        pin_cpu_platform(int(force_host))
        plat = "cpu"
    try:
        plat = plat if force_host else wait_for_tpu()
    except RuntimeError as e:
        # keep the artifact alive like bench.py: an exhausted tunnel-retry
        # budget must still leave an error-bearing RESULTS_TPU file (the
        # sweep's consumers key off the file's existence, not the rc)
        with open(OUT_PATH, "w") as f:
            json.dump({"platform": "unavailable", "tunnel_error": str(e)}, f)
        print(json.dumps({"tunnel_error": str(e)}))
        return 1
    import jax

    # crash resume: the 8x1k batched phase has KILLED the TPU worker
    # (kernel fault), taking every later phase in the process down with
    # UNAVAILABLE.  Each phase's results are flushed to OUT_PATH as it
    # completes; on a dead backend the run re-execs a fresh interpreter,
    # which reloads the partial artifact, skips finished phases, and
    # retries the crashing phase up to _PHASE_RETRIES times before
    # recording the crash and moving on.
    results: dict = {}
    if os.environ.get("TPU_MEASURE_CRASH_ATTEMPT", "0") != "0":
        try:
            with open(OUT_PATH) as f:
                prev = json.load(f)
            if prev.get("_in_progress"):
                results = prev
        except Exception:
            pass
    results["platform"] = plat
    results["device"] = str(jax.devices()[0])
    done = set(results.get("_phases_done", []))
    attempts = dict(results.get("_phase_attempts", {}))

    def flush():
        results["_phases_done"] = sorted(done)
        results["_phase_attempts"] = attempts
        with open(OUT_PATH, "w") as f:
            json.dump(results, f, indent=1)

    for name, fn in (
        ("headline", phase_headline),
        ("pallas_vs_scan", phase_pallas_vs_scan),
        ("encode_impls", phase_encode_impls),
        ("fused_parity", phase_fused_parity),
        ("fused_exchange", phase_fused_exchange),
        ("weak_scaling", phase_weak_scaling),
        ("route", phase_route),
        ("observatory", phase_observatory),
        ("request_observatory", phase_request_observatory),
        ("mesh_observatory", phase_mesh_observatory),
        ("fused_full", phase_fused_full),
        ("ckpt", phase_ckpt),
        ("epidemic_100k", phase_epidemic_100k),
        ("batched", phase_batched),
        ("convergence", phase_convergence),
        ("storm_1m", phase_storm_1m),
    ):
        if name in done:
            continue
        if attempts.get(name, 0) >= _PHASE_RETRIES:
            results["%s_error" % name] = (
                "backend crashed in this phase on %d attempts"
                % attempts[name]
            )
            done.add(name)
            flush()
            continue
        attempts[name] = attempts.get(name, 0) + 1
        snapshot = set(results)
        results["_in_progress"] = True
        flush()
        try:
            fn(results)
        except Exception as e:
            results["%s_error" % name] = str(e)[:400]
        if not _backend_alive():
            # drop this phase's error-bearing keys (bogus UNAVAILABLE
            # fallout) and restart in a clean interpreter; keys that
            # succeeded before the crash survive, and the retried phase
            # skips them via _todo
            for k in [k for k in results if k not in snapshot]:
                v = results[k]
                if k.endswith("_error") or (
                    isinstance(v, dict) and "error" in v
                ):
                    del results[k]
            results["_in_progress"] = True
            flush()
            print(
                json.dumps({name: "backend crashed; re-exec"}), flush=True
            )
            env_budget = 4 * _PHASE_RETRIES * 7  # phases x retries slack
            if (
                reexec_retry(
                    "TPU_MEASURE_CRASH_ATTEMPT", env_budget, 15.0, __file__
                )
                is False
            ):
                # budget gone: keep what we have — but the purge above
                # removed this phase's error keys, so record the crash
                # explicitly or the artifact would silently omit the phase
                results["%s_error" % name] = (
                    "backend crashed; re-exec budget exhausted"
                )
                flush()
                break
            raise AssertionError("unreachable")  # pragma: no cover
        done.add(name)
        _drop_executables()
        flush()
        print(json.dumps({name: "done"}), flush=True)

    for k in ("_in_progress", "_phases_done", "_phase_attempts"):
        results.pop(k, None)
    with open(OUT_PATH, "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps(results))
    return 0


if __name__ == "__main__":
    sys.exit(main())
