"""Per-shard exchange telemetry plane (round 17, ISSUE 16 tentpole a).

The flight-recorder gates: with ``ScalableParams.exchange_metrics`` on,
(1) the mesh plane's device counters/histograms are bitwise-identical
to the single-device analytic twin's at every shard count (1/2/4/8 on
the virtual 8-device CPU mesh), (2) the drained per-shard rows sum to
the twin's totals bitwise, (3) the pooled cap-utilization histogram
summary equals the per-shard aggregate (obs.histograms.summarize_batched
— counts are exact, not sampled), and (4) instrumentation is gate-
equivalence-neutral: every trajectory field of an instrumented run is
bitwise-identical to the uninstrumented run's (n=64 tier-1, n=64k slow).
"""

import numpy as np
import pytest

import jax

from ringpop_tpu.models.sim import engine_scalable as es
from ringpop_tpu.models.sim.storm import ScalableCluster, StormSchedule
from ringpop_tpu.obs import exchange_stats as oxs
from ringpop_tpu.obs import histograms as oh
from ringpop_tpu.ops import exchange as exch
from ringpop_tpu.parallel import mesh as pmesh


@pytest.fixture(scope="module")
def eight_devices():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return jax.devices()[:8]


def _params(n, shards, **kw):
    kw.setdefault("u", 192)
    kw.setdefault("suspicion_ticks", 5)
    return es.ScalableParams(n=n, exchange_metrics=shards, **kw)


def _sched(ticks, n, seed=4):
    return StormSchedule.churn_storm(
        ticks, n, fraction=0.1, fail_tick=2, seed=seed
    )


def test_mesh_counters_match_single_device_twin(eight_devices):
    """The plane's in-body bumps == the analytic twin, bitwise, at
    every shard count — and the drained per-shard rows sum to the
    twin's totals."""
    n, ticks = 64, 8
    for shards in (2, 4, 8):
        params = _params(n, shards)
        sched = _sched(ticks, n)
        twin = ScalableCluster(n=n, params=params, seed=4)
        twin.run(sched)
        storm = pmesh.ShardedStorm(
            n=n, mesh=pmesh.make_mesh(shards), params=params, seed=4
        )
        storm.run(sched)
        assert storm.exchange_mode == "shard_map"
        np.testing.assert_array_equal(
            np.asarray(storm.state.exch),
            np.asarray(twin.state.exch),
            "exch counters diverged at %d shards" % shards,
        )
        np.testing.assert_array_equal(
            np.asarray(storm.state.exch_hist),
            np.asarray(twin.state.exch_hist),
            "exch_hist diverged at %d shards" % shards,
        )
        mesh_drained = storm.drain_exchange_metrics(reset=False)
        twin_drained = twin.drain_exchange_metrics(reset=False)
        assert mesh_drained["shards"] == twin_drained["shards"]
        assert mesh_drained["totals"] == twin_drained["totals"]


def test_single_shard_drain_totals(eight_devices):
    """The 1-shard twin is the degenerate case: every row is local, so
    the drain reconciles to zero interconnect bytes and the per-shard
    'spread' counts at most 1 destination."""
    n, ticks = 64, 8
    single = ScalableCluster(n=n, params=_params(n, 1), seed=4)
    single.run(_sched(ticks, n))
    drained = single.drain_exchange_metrics(reset=False)
    tot = drained["totals"]
    assert tot["shards"] == 1
    assert tot["ticks"] == ticks
    assert oxs.measured_interconnect_bytes(tot) == 0
    # one destination bucket per tick: the spread counter accumulates
    # exactly ticks on a 1-shard mesh
    assert all(r["dest_shards_pull"] == ticks for r in drained["shards"])


def test_drained_wire_bytes_reconcile_with_model(eight_devices):
    """Measured interconnect bytes == the analytic model x ticks (exact
    when every trip takes the a2a path) — the traffic gate's identity,
    checked here at the test shapes so a drift is attributable before
    the committed TRAFFIC_BUDGET.json diff fires."""
    n, ticks = 64, 8
    for shards in (2, 4, 8):
        storm = pmesh.ShardedStorm(
            n=n,
            mesh=pmesh.make_mesh(shards),
            params=_params(n, shards),
            seed=4,
        )
        storm.run(_sched(ticks, n))
        drained = storm.drain_exchange_metrics(reset=False)
        rec = drained["reconcile"]
        assert rec["fallback_trips"] == 0
        assert rec["ticks"] == ticks
        assert rec["measured_interconnect"] == rec["model_interconnect"]
        assert rec["ratio"] == 1.0


def test_cap_util_pooled_equals_aggregate(eight_devices):
    """summarize_batched over the [S, H, NB] histogram plane ==
    summarize of the shard-summed plane: device counts pool exactly."""
    n, shards, ticks = 64, 4, 8
    storm = pmesh.ShardedStorm(
        n=n,
        mesh=pmesh.make_mesh(shards),
        params=_params(n, shards),
        seed=4,
    )
    storm.run(_sched(ticks, n))
    hist = np.asarray(storm.state.exch_hist)
    pooled = oh.summarize_batched(hist, exch.EXCH_HIST_TRACKS)
    aggregate = oh.summarize(hist.sum(axis=0), exch.EXCH_HIST_TRACKS)
    assert pooled == aggregate
    # every tick records one cap-utilization sample per direction/shard
    assert pooled["cap_util_pull"]["count"] == shards * shards * ticks


def test_drain_reset_starts_a_fresh_window(eight_devices):
    n, shards, ticks = 64, 2, 4
    storm = pmesh.ShardedStorm(
        n=n,
        mesh=pmesh.make_mesh(shards),
        params=_params(n, shards),
        seed=4,
    )
    storm.run(_sched(ticks, n))
    first = storm.drain_exchange_metrics(reset=True)
    assert first["totals"]["ticks"] == ticks * shards
    assert not np.asarray(storm.state.exch).any()
    assert not np.asarray(storm.state.exch_hist).any()
    # the next window accumulates afresh (and keeps its sharding)
    storm.run(_sched(ticks, n, seed=9))
    second = storm.drain_exchange_metrics(reset=False)
    assert second["totals"]["ticks"] == ticks * shards


def test_drain_raises_when_telemetry_off(eight_devices):
    storm = pmesh.ShardedStorm(
        n=64, mesh=pmesh.make_mesh(2), params=_params(64, 0), seed=4
    )
    with pytest.raises(ValueError, match="exchange telemetry is off"):
        storm.drain_exchange_metrics()
    single = ScalableCluster(n=64, params=_params(64, 0), seed=4)
    with pytest.raises(ValueError, match="exchange telemetry is off"):
        single.drain_exchange_metrics()


def test_mesh_size_mismatch_rejected(eight_devices):
    with pytest.raises(ValueError, match="must equal the mesh size"):
        pmesh.ShardedStorm(
            n=64, mesh=pmesh.make_mesh(4), params=_params(64, 2), seed=4
        )


def _assert_trajectory_equal(a, b, ctx=""):
    for f in es.ScalableState._fields:
        if f in es.SCALABLE_OBS_ONLY_FIELDS:
            continue
        x, y = getattr(a, f), getattr(b, f)
        if x is None:
            assert y is None, f
            continue
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), "%s%s" % (ctx, f)
        )


def test_instrumentation_is_gate_equivalent_n64(eight_devices):
    """Telemetry ON vs OFF: bitwise-identical trajectories, single
    device and mesh (the noninterference prong proves this statically;
    this is the dynamic spot check at the tier-1 shape)."""
    n, ticks = 64, 8
    sched = _sched(ticks, n)
    for shards in (4, 8):
        off = pmesh.ShardedStorm(
            n=n,
            mesh=pmesh.make_mesh(shards),
            params=_params(n, 0),
            seed=4,
        )
        off.run(sched)
        on = pmesh.ShardedStorm(
            n=n,
            mesh=pmesh.make_mesh(shards),
            params=_params(n, shards),
            seed=4,
        )
        on.run(sched)
        _assert_trajectory_equal(
            on.state, off.state, "mesh s=%d " % shards
        )
    off1 = ScalableCluster(n=n, params=_params(n, 0), seed=4)
    off1.run(sched)
    on1 = ScalableCluster(n=n, params=_params(n, 4), seed=4)
    on1.run(sched)
    _assert_trajectory_equal(on1.state, off1.state, "single ")


@pytest.mark.slow
def test_instrumentation_is_gate_equivalent_n64k_slow(eight_devices):
    n, ticks, shards = 65536, 6, 8
    sched = _sched(ticks, n)
    off = pmesh.ShardedStorm(
        n=n,
        mesh=pmesh.make_mesh(shards),
        params=_params(n, 0, u=288),
        seed=4,
    )
    off.run(sched)
    on = pmesh.ShardedStorm(
        n=n,
        mesh=pmesh.make_mesh(shards),
        params=_params(n, shards, u=288),
        seed=4,
    )
    on.run(sched)
    _assert_trajectory_equal(on.state, off.state, "mesh 64k ")
    drained = on.drain_exchange_metrics(reset=False)
    assert drained["reconcile"]["ratio"] == 1.0
