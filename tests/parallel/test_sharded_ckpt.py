"""Sharded checkpoint/restore for the mesh drivers (ISSUE 9): per-shard
files + manifest, restore onto a DIFFERENT shard count, bitwise against
the single-file path.  Runs on the virtual 8-device CPU mesh."""

import os

import jax
import numpy as np
import pytest

from ringpop_tpu.models.sim import checkpoint as ckpt
from ringpop_tpu.models.sim import engine, engine_scalable as es
from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster
from ringpop_tpu.models.sim.storm import ScalableCluster, StormSchedule
from ringpop_tpu.parallel import mesh as pmesh


@pytest.fixture(scope="module")
def eight_mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return pmesh.make_mesh(8)


def _state_equal(a, b, cls):
    for f in cls._fields:
        x, y = getattr(a, f), getattr(b, f)
        if x is None:
            assert y is None, f
            continue
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y), f)


def test_sharded_storm_checkpoint_roundtrips_across_shard_counts(
    eight_mesh, tmp_path
):
    n = 32
    params = es.ScalableParams(n=n, u=160, suspicion_ticks=4)
    storm = pmesh.ShardedStorm(n=n, mesh=eight_mesh, params=params, seed=2)
    storm.run(StormSchedule.churn_storm(6, n, fraction=0.2, seed=0))
    want = {
        f: np.array(getattr(storm.state, f), copy=True)
        for f in es.ScalableState._fields
        if getattr(storm.state, f) is not None
    }

    p8 = str(tmp_path / "ck8")
    p1 = str(tmp_path / "ck1")
    storm.save(p8)  # default: one shard per mesh device
    storm.save(p1, shards=1)  # the single-file twin
    assert len([f for f in os.listdir(p8) if f.startswith("shard-")]) == 8

    # ACCEPTANCE: sharded save -> restore at a DIFFERENT shard count is
    # bitwise-identical to the single-file path, across driver kinds:
    # 8-shard artifact into the single-device ScalableCluster ...
    single = ScalableCluster(n=n, params=params, seed=9)
    single.load(p8)
    for f, x in want.items():
        np.testing.assert_array_equal(x, np.asarray(getattr(single.state, f)), f)
    # ... and the single-file artifact back onto the 8-device mesh
    storm2 = pmesh.ShardedStorm(n=n, mesh=eight_mesh, params=params, seed=9)
    storm2.load(p1)
    for f, x in want.items():
        np.testing.assert_array_equal(x, np.asarray(getattr(storm2.state, f)), f)
    # restored state keeps the mesh shardings
    assert storm2.state.heard.sharding.spec == jax.sharding.PartitionSpec(
        "nodes", None
    )

    # both resume the SAME trajectory: one more identical storm window
    sched = StormSchedule.churn_storm(4, n, fraction=0.1, seed=3)
    m_single = single.run(StormSchedule.churn_storm(4, n, fraction=0.1, seed=3))
    m_mesh = storm2.run(sched)
    for f in m_single._fields:
        a = np.asarray(getattr(m_single, f))
        b = np.asarray(getattr(m_mesh, f))
        if f == "mean_heard_frac":
            # the one float metric: the mesh's cross-device reduction
            # associates differently (~1e-7); the trajectory itself is
            # integer state and stays bitwise (below)
            np.testing.assert_allclose(a, b, rtol=1e-5, err_msg=f)
        else:
            np.testing.assert_array_equal(a, b, f)
    np.testing.assert_array_equal(single.checksums(), storm2.checksums())


def test_sharded_storm_cadence_and_restore(eight_mesh, tmp_path):
    """ShardedStorm under a checkpoint cadence: sharded families on the
    grid, recovery resumes bitwise."""
    n = 16
    params = es.ScalableParams(n=n, u=128, suspicion_ticks=4)
    a = pmesh.ShardedStorm(n=n, mesh=eight_mesh, params=params, seed=1)
    a.enable_checkpoints(str(tmp_path / "fam"), every=3, keep=2)
    a.run(StormSchedule.churn_storm(7, n, fraction=0.2, seed=1))
    fams = a.checkpoint_manager.list_checkpoints()
    assert [t for t, _ in fams] == [3, 6]
    manifest = ckpt.read_manifest(fams[-1][1])
    assert manifest["shards"] == 8

    b = pmesh.ShardedStorm(n=n, mesh=eight_mesh, params=params, seed=1)
    b.enable_checkpoints(str(tmp_path / "fam"))
    assert b.restore_latest() == 6
    want = {
        f: np.array(getattr(a.state, f), copy=True)
        for f in es.ScalableState._fields
        if getattr(a.state, f) is not None
    }
    sched = StormSchedule.churn_storm(7, n, fraction=0.2, seed=1)
    b.run(sched.window(6, 7))
    for f, x in want.items():
        np.testing.assert_array_equal(x, np.asarray(getattr(b.state, f)), f)


def test_sharded_sim_checkpoint_roundtrip(eight_mesh, tmp_path):
    """Full-fidelity mesh driver: sharded manifest save, restore into
    the single-device SimCluster and back, bitwise."""
    n = 16
    sim = pmesh.ShardedSim(n=n, mesh=eight_mesh, seed=3)
    sim.bootstrap()
    sim.run(EventSchedule(ticks=6, n=n))
    want = {
        f: np.array(getattr(sim.state, f), copy=True)
        for f in engine.SimState._fields
        if getattr(sim.state, f) is not None
    }
    path = str(tmp_path / "ck")
    sim.save(path)
    manifest = ckpt.read_manifest(path)
    assert manifest["shards"] == 8
    # NOT vacuous: the node-leading fields really split across shards
    assert manifest["states"]["state"]["fields"]["known"]["where"] == "shards"
    assert manifest["states"]["state"]["fields"]["checksum"]["where"] == "shards"

    single = SimCluster(n=n, seed=11)
    from ringpop_tpu.models.sim.checkpoint import load_checkpoint
    from ringpop_tpu.models.sim.cluster import fixup_sim_state

    single.state = fixup_sim_state(
        load_checkpoint(path, engine.SimState, single.params),
        single.params,
        single.universe,
    )
    for f, x in want.items():
        np.testing.assert_array_equal(x, np.asarray(getattr(single.state, f)), f)

    sim2 = pmesh.ShardedSim(n=n, mesh=eight_mesh, seed=11)
    sim2.load(path)
    m1 = single.run(EventSchedule(ticks=5, n=n))
    m2 = sim2.run(EventSchedule(ticks=5, n=n))
    np.testing.assert_array_equal(single.checksums(), sim2.checksums())
    np.testing.assert_array_equal(
        np.asarray(m1.distinct_checksums), np.asarray(m2.distinct_checksums)
    )
