"""Mesh-sharded simulator: the sharded tick must be the same program.

Runs on the virtual 8-device CPU mesh (conftest forces
xla_force_host_platform_device_count=8).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.models.sim import engine
from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster
from ringpop_tpu.parallel import mesh as pmesh


@pytest.fixture(scope="module")
def eight_mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return pmesh.make_mesh(8)


def test_sharded_matches_single_device(eight_mesh):
    """Same seed, same schedule => bitwise-identical checksums, sharded or not."""
    n = 32
    single = SimCluster(n=n, seed=3)
    sharded = pmesh.ShardedSim(n=n, mesh=eight_mesh, seed=3)

    single.bootstrap()
    sharded.bootstrap()
    sched = EventSchedule(ticks=12, n=n)
    kill = np.zeros((12, n), bool)
    kill[4, :3] = True  # fault injection mid-run
    sched.kill = kill
    m1 = single.run(sched)
    m2 = sharded.run(EventSchedule(ticks=12, n=n, kill=kill.copy()))

    np.testing.assert_array_equal(single.checksums(), sharded.checksums())
    np.testing.assert_array_equal(
        np.asarray(m1.distinct_checksums), np.asarray(m2.distinct_checksums)
    )


def test_state_is_node_sharded(eight_mesh):
    sim = pmesh.ShardedSim(n=16, mesh=eight_mesh)
    sim.bootstrap()
    sh = sim.state.known.sharding
    assert sh.spec == jax.sharding.PartitionSpec("nodes", None)
    assert sim.state.checksum.sharding.spec == jax.sharding.PartitionSpec("nodes")


def test_converges_sharded(eight_mesh):
    sim = pmesh.ShardedSim(n=24, mesh=eight_mesh, seed=1)
    sim.bootstrap()
    m = sim.run(EventSchedule(ticks=20, n=24))
    assert bool(np.asarray(m.converged)[-1])


def test_graft_entry_contract():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    state, metrics = out
    assert int(metrics.pings_sent) >= 0
    g.dryrun_multichip(8)


def test_2d_mesh_dcn_x_ici_bitwise_equal():
    """Sharding the node axis over a 2-D (hosts x chips) mesh — DCN outer,
    ICI inner — produces the same trajectory bitwise as a single device:
    the multi-host composition of the same SPMD program."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    n = 16
    mesh2d = pmesh.make_mesh_2d(2, 4)
    sharded = pmesh.ShardedSim(n=n, mesh=mesh2d, seed=5)
    single = SimCluster(n=n, seed=5)
    sharded.bootstrap()
    single.bootstrap()
    for _ in range(8):
        sharded.step()
        single.step()
    np.testing.assert_array_equal(sharded.checksums(), single.checksums())
    for f in ("known", "status", "inc", "iter_pos"):
        np.testing.assert_array_equal(
            np.asarray(getattr(sharded.state, f)),
            np.asarray(getattr(single.state, f)),
            f,
        )


def test_full_lifecycle_sharded_bitwise_equal(eight_mesh):
    """Every cond-gated engine phase — revive reset, rejoin write, leave,
    partition, ping-req, expiry — under GSPMD: the sharded trajectory
    must stay bitwise equal to the single-device one through a full
    fault lifecycle."""
    import jax.numpy as jnp

    n = 16
    sharded = pmesh.ShardedSim(n=n, mesh=eight_mesh, seed=9)
    single = SimCluster(n=n, seed=9)
    sharded.bootstrap()
    single.bootstrap()

    def ev(**kw):
        inp = engine.TickInputs.quiet(n)
        reps = {}
        for k, idx in kw.items():
            if k == "partition":
                reps[k] = jnp.asarray(idx, jnp.int32)
            else:
                v = np.zeros(n, bool)
                v[list(idx)] = True
                reps[k] = jnp.asarray(v)
        return inp._replace(**reps)

    part = np.zeros(n, np.int32)
    part[:4] = 1
    heal = np.zeros(n, np.int32)
    schedule = (
        [ev() for _ in range(4)]
        + [ev(kill=[2])]                     # -> ping-req suspect path
        + [ev() for _ in range(28)]          # -> suspicion expiry path
        + [ev(revive=[2])]                   # -> revive reset + join
        + [ev() for _ in range(6)]
        + [ev(leave=[5])]                    # -> leave write
        + [ev() for _ in range(4)]
        + [ev(join=[5])]                     # -> rejoin write
        + [ev(partition=part)]               # -> split
        + [ev() for _ in range(6)]
        + [ev(partition=heal)]               # -> heal
        + [ev() for _ in range(10)]
    )
    for inp in schedule:
        sharded.step(inp)
        single.step(inp)
    np.testing.assert_array_equal(sharded.checksums(), single.checksums())
    for f in ("known", "status", "inc", "susp_deadline", "gossip_on"):
        np.testing.assert_array_equal(
            np.asarray(getattr(sharded.state, f)),
            np.asarray(getattr(single.state, f)),
            f,
        )


def test_scalable_sharded_matches_single_device(eight_mesh):
    """The O(N·U) rumor engine sharded over the mesh must produce the
    bitwise-identical trajectory through a churn storm — the 1M-on-v5e-8
    path at test scale."""
    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.models.sim.storm import ScalableCluster, StormSchedule

    n = 64
    params = es.ScalableParams(n=n, u=192, suspicion_ticks=5)
    single = ScalableCluster(n=n, params=params, seed=4)
    sharded = pmesh.ShardedStorm(n=n, mesh=eight_mesh, params=params, seed=4)
    sched = StormSchedule.churn_storm(24, n, fraction=0.1, fail_tick=2, seed=4)
    m1 = single.run(sched)
    m2 = sharded.run(StormSchedule.churn_storm(24, n, fraction=0.1, fail_tick=2, seed=4))
    np.testing.assert_array_equal(single.checksums(), sharded.checksums())
    for f in ("truth_status", "truth_inc", "heard", "r_active", "r_delta",
              "susp_subject", "base_sum"):
        np.testing.assert_array_equal(
            np.asarray(getattr(single.state, f)),
            np.asarray(getattr(sharded.state, f)),
            f,
        )
    np.testing.assert_array_equal(
        np.asarray(m1.distinct_checksums), np.asarray(m2.distinct_checksums)
    )


def test_scalable_sharded_state_layout(eight_mesh):
    from ringpop_tpu.models.sim import engine_scalable as es

    s = pmesh.ShardedStorm(n=32, mesh=eight_mesh, params=es.ScalableParams(n=32, u=160))
    assert s.state.heard.sharding.spec == jax.sharding.PartitionSpec("nodes", None)
    assert s.state.r_delta.sharding.spec == jax.sharding.PartitionSpec()  # replicated


def test_scalable_sharded_partition_and_leave(eight_mesh):
    """Optional ChurnInputs subtrees (partition groups, graceful leaves)
    change the argument pytree — the sharded driver must accept them and
    stay bitwise-equal to single-device."""
    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.models.sim.storm import ScalableCluster

    n = 32
    params = es.ScalableParams(n=n, u=160, enable_leave=True)
    single = ScalableCluster(n=n, params=params, seed=6)
    sharded = pmesh.ShardedStorm(n=n, mesh=eight_mesh, params=params, seed=6)

    part = np.zeros(n, np.int32)
    part[: n // 4] = 1
    lv = np.zeros(n, bool)
    lv[5] = True
    steps = (
        [es.ChurnInputs.quiet(n)._replace(partition=jnp.asarray(part))]
        + [es.ChurnInputs.quiet(n)] * 4
        + [es.ChurnInputs.quiet(n)._replace(leave=jnp.asarray(lv))]
        + [es.ChurnInputs.quiet(n)] * 4
        + [es.ChurnInputs.quiet(n)._replace(partition=jnp.zeros(n, jnp.int32))]
        + [es.ChurnInputs.quiet(n)] * 6
    )
    for inp in steps:
        single.step(inp)
        sharded.step(inp)
    np.testing.assert_array_equal(single.checksums(), sharded.checksums())
    np.testing.assert_array_equal(
        np.asarray(single.state.truth_status),
        np.asarray(sharded.state.truth_status),
    )
