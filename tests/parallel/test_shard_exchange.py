"""The shard_map'd fused cross-shard exchange plane (round 14).

The tentpole gates: the explicit-collective exchange plane must be
bit-identical to the single-device sortless path at every shard count
(1/2/4/8), to the partitionable GSPMD XLA twin (the fallback gate), and
through the forced overflow fallback; the mesh-aware resolution table is
pinned; the observability note replaces the PR-5 silent drop-to-XLA; and
a mid-storm restore across shard counts (the PR-8 manifest loader)
resumes the identical trajectory.  Runs on the virtual 8-device CPU mesh
(conftest forces xla_force_host_platform_device_count=8).
"""

import json

import jax
import numpy as np
import pytest

from ringpop_tpu.models.sim import engine_scalable as es
from ringpop_tpu.models.sim.storm import ScalableCluster, StormSchedule
from ringpop_tpu.parallel import mesh as pmesh


@pytest.fixture(scope="module")
def eight_devices():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return jax.devices()[:8]


def _params(n, **kw):
    kw.setdefault("u", 192)
    kw.setdefault("suspicion_ticks", 5)
    return es.ScalableParams(n=n, **kw)


def _storm_sched(ticks, n, seed=4):
    # kill + rejoin + a partition split/heal: every exchange-adjacent
    # phase (indirect rounds, publishes, refutes) fires inside the window
    sched = StormSchedule.churn_storm(
        ticks, n, fraction=0.1, fail_tick=2, seed=seed
    )
    part = np.full((ticks, n), -1, np.int32)
    part[ticks // 3] = (np.arange(n) < n // 4).astype(np.int32)
    part[2 * ticks // 3] = 0
    sched.partition = part
    return sched


def _assert_states_equal(a, b, ctx=""):
    for f in es.ScalableState._fields:
        x, y = getattr(a, f), getattr(b, f)
        if x is None:
            assert y is None, f
            continue
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), "%s%s" % (ctx, f)
        )


def _run_single(n, ticks, seed=4, **pkw):
    single = ScalableCluster(n=n, params=_params(n, **pkw), seed=seed)
    single.run(_storm_sched(ticks, n, seed))
    return single


def test_shard_count_invariance_n64(eight_devices):
    """ACCEPTANCE: the same seeded storm is bitwise-equal across
    1/2/4/8 shards under the shard_map plane, and equal to the
    single-device sortless path — every state field and the checksums."""
    n, ticks = 64, 24
    single = _run_single(n, ticks, packet_loss=0.02)
    for shards in (1, 2, 4, 8):
        storm = pmesh.ShardedStorm(
            n=n,
            mesh=pmesh.make_mesh(shards),
            params=_params(n, packet_loss=0.02),
            seed=4,
        )
        assert storm.exchange_mode == "shard_map"
        storm.run(_storm_sched(ticks, n))
        _assert_states_equal(
            single.state, storm.state, "shards=%d: " % shards
        )
        np.testing.assert_array_equal(single.checksums(), storm.checksums())


def test_plane_matches_partitionable_xla_twin(eight_devices):
    """The fallback gate: the shard_map plane vs fused_exchange="xla"
    under whole-program GSPMD (the partitionable twin) — bit-identical
    states on the same mesh."""
    n, ticks = 64, 16
    mesh = pmesh.make_mesh(8)
    plane = pmesh.ShardedStorm(n=n, mesh=mesh, params=_params(n), seed=4)
    twin = pmesh.ShardedStorm(
        n=n, mesh=mesh, params=_params(n, fused_exchange="xla"), seed=4
    )
    assert plane.exchange_mode == "shard_map"
    assert twin.exchange_mode == "gspmd" and twin.exchange_impl == "xla"
    plane.run(_storm_sched(ticks, n))
    twin.run(_storm_sched(ticks, n))
    _assert_states_equal(plane.state, twin.state)


def test_overflow_fallback_bitwise_equal(eight_devices):
    """cap=1 overflows every tick's all_to_all buckets, forcing the
    all-gather fallback under lax.cond — the trajectory must not move."""
    n, ticks = 64, 12
    single = _run_single(n, ticks)
    storm = pmesh.ShardedStorm(
        n=n,
        mesh=pmesh.make_mesh(8),
        params=_params(n),
        seed=4,
        exchange_cap_override=1,
    )
    assert storm.exchange_cap == 1
    storm.run(_storm_sched(ticks, n))
    _assert_states_equal(single.state, storm.state)


def test_step_and_scan_agree_under_plane(eight_devices):
    """The plane inside lax.scan (the storm window program) and as
    per-tick dispatches produce the same trajectory."""
    n, ticks = 32, 8
    params = _params(n, u=160)
    mesh = pmesh.make_mesh(4)
    a = pmesh.ShardedStorm(n=n, mesh=mesh, params=params, seed=7)
    b = pmesh.ShardedStorm(n=n, mesh=mesh, params=params, seed=7)
    sched = _storm_sched(ticks, n, seed=7)
    a.run(sched)
    inputs = _storm_sched(ticks, n, seed=7)
    for t in range(ticks):
        b.step(
            es.ChurnInputs(
                kill=np.asarray(inputs.kill[t]),
                revive=np.asarray(inputs.revive[t]),
                partition=np.asarray(inputs.partition[t]),
            )
        )
    _assert_states_equal(a.state, b.state)


def test_exchange_cap_matches_shared_traffic_model():
    """parallel.mesh.exchange_cap and the ops-side cross-shard traffic
    model (ops.exchange.cross_shard_traffic_bytes) must agree on the
    default cap — the model's wire-byte claim is about the buffers the
    plane actually sends."""
    from ringpop_tpu.ops import exchange as exch

    for n, shards in ((64, 8), (64, 1), (1024, 4), (1_000_000, 8)):
        local = n // shards
        assert (
            exch.cross_shard_traffic_bytes(n, 16, shards)["cap"]
            == pmesh.exchange_cap(local, shards)
        )
    # single shard: everything is local, cap = L, nothing crosses
    m = exch.cross_shard_traffic_bytes(64, 16, 1)
    assert m["interconnect_total"] == 0
    # the cap never exceeds the local row count
    assert pmesh.exchange_cap(8, 8) <= 8
    assert pmesh.exchange_cap(125_000, 8) < 125_000


def test_resolution_table_pinned():
    """The FULL mesh-aware resolution table
    (es.resolve_sharded_exchange) — the PR-5 silent drop-to-XLA is gone:
    auto under a mesh picks the shard_map plane on every backend."""
    table = {
        ("auto", "tpu"): ("shard_map", "pallas"),
        ("auto", "cpu"): ("shard_map", "xla"),
        ("auto", "gpu"): ("shard_map", "xla"),
        ("pallas", "tpu"): ("shard_map", "pallas"),
        ("pallas", "cpu"): ("shard_map", "pallas"),
        ("xla", "tpu"): ("gspmd", "xla"),
        ("xla", "cpu"): ("gspmd", "xla"),
        ("off", "tpu"): ("gspmd", "off"),
        ("off", "cpu"): ("gspmd", "off"),
    }
    for (fe, backend), want in table.items():
        params = es.ScalableParams(n=16, fused_exchange=fe)
        for shards in (1, 8):
            assert (
                es.resolve_sharded_exchange(params, backend, shards)
                == want
            ), (fe, backend, shards)
    with pytest.raises(ValueError):
        es.resolve_sharded_exchange(
            es.ScalableParams(n=16, fused_exchange="bogus"), "cpu", 8
        )
    with pytest.raises(ValueError):
        es.resolve_sharded_exchange(es.ScalableParams(n=16), "cpu", 0)


def test_resolution_observable_not_silent(eight_devices, tmp_path):
    """Satellite 1: when "auto" resolves differently under a mesh than
    single-device, the divergence lands as a mesh_exchange_resolution
    runlog event + statsd gauge instead of the old silent drop."""
    from ringpop_tpu.obs import RunRecorder
    from ringpop_tpu.obs.statsd_bridge import StatsdBridge
    from ringpop_tpu.utils.util import NullStatsd

    n = 16
    storm = pmesh.ShardedStorm(
        n=n, mesh=pmesh.make_mesh(8), params=_params(n, u=160), seed=0
    )
    note = storm.exchange_resolution()
    # the flag compares the KERNEL, not the routing mode: on CPU the
    # single-device auto pick is "off" and the plane runs the xla twin
    # — a real lowering change, flagged; on TPU both run the pallas
    # megakernel — no divergence, flag 0 (the plane itself is not a
    # drop).  Pinned backend-independently against the resolver.
    assert note["mode"] == "shard_map"
    single_pick = es.resolve_fused_exchange(
        es.ScalableParams(n=n), jax.default_backend()
    )
    assert note["single_device_resolution"] == single_pick
    assert note["differs_from_single_device"] == (
        note["impl"] != single_pick
    )
    if jax.default_backend() != "tpu":
        assert note["differs_from_single_device"] is True
    rec = RunRecorder(str(tmp_path) + "/", run_id="meshres")
    storm.attach_recorder(rec)
    storm.step()
    rec.finish()
    rows = [
        json.loads(line)
        for line in open(rec.path, encoding="utf-8")
        if line.strip()
    ]
    events = [
        r
        for r in rows
        if r.get("kind") == "event"
        and r.get("name") == "mesh_exchange_resolution"
    ]
    assert len(events) == 1
    ev = events[0]
    for field in (
        "requested",
        "mode",
        "impl",
        "shards",
        "cap",
        "single_device_resolution",
        "differs_from_single_device",
    ):
        assert field in ev, field
    assert ev["shards"] == 8 and ev["mode"] == "shard_map"

    # the statsd face of the same note
    sent = []

    class _Capture(NullStatsd):
        def gauge(self, key, value):
            sent.append((key, value))

    storm.emit_resolution_stat(
        StatsdBridge(statsd=_Capture(), host_port="127.0.0.1:3000")
    )
    keys = dict(sent)
    assert (
        "ringpop.127_0_0_1_3000.sharded.exchange.resolution_differs"
        in keys
    )

    # an explicit non-auto request never flags a divergence
    twin = pmesh.ShardedStorm(
        n=n,
        mesh=pmesh.make_mesh(8),
        params=_params(n, u=160, fused_exchange="xla"),
        seed=0,
    )
    assert (
        twin.exchange_resolution()["differs_from_single_device"] is False
    )

    # ...and the single-device driver reports its own (never-differing)
    # resolution through the same shape
    single = ScalableCluster(n=n, params=_params(n, u=160), seed=0)
    snote = single.exchange_resolution()
    assert snote["mode"] == "inline"
    assert snote["differs_from_single_device"] is False


def test_restore_across_shard_counts_mid_storm(eight_devices, tmp_path):
    """Satellite 3: a PR-8 manifest checkpoint taken MID-STORM on a
    4-shard mesh restores onto an 8-shard mesh (and the single-device
    driver) and finishes the identical trajectory bitwise."""
    n, ticks, cut = 64, 20, 10
    params = _params(n)
    sched = _storm_sched(ticks, n)

    # uninterrupted single-device reference
    ref = ScalableCluster(n=n, params=params, seed=4)
    ref.run(_storm_sched(ticks, n))

    # 4-shard run to the cut, manifest save (one file per shard)
    a = pmesh.ShardedStorm(
        n=n, mesh=pmesh.make_mesh(4), params=params, seed=4
    )
    a.run(_storm_sched(ticks, n).window(0, cut))
    path = str(tmp_path / "midstorm")
    a.save(path)

    # restore at DIFFERENT shard counts, finish the storm
    b = pmesh.ShardedStorm(
        n=n, mesh=pmesh.make_mesh(8), params=params, seed=99
    )
    b.load(path)
    b.run(_storm_sched(ticks, n).window(cut, ticks))
    _assert_states_equal(ref.state, b.state, "8-shard resume: ")

    c = ScalableCluster(n=n, params=params, seed=99)
    c.load(path)
    c.run(sched.window(cut, ticks))
    _assert_states_equal(ref.state, c.state, "single resume: ")


@pytest.mark.slow
def test_explicit_pallas_plane_bitwise(eight_devices):
    """An explicit fused_exchange="pallas" under a mesh runs the real
    megakernel INSIDE the shard_map body (interpret mode off-TPU) —
    bitwise-equal to the single-device engine.  Slow-marked only for the
    interpret-mode kernel cost; on TPU this is the production path."""
    n, ticks = 64, 8
    single = _run_single(n, ticks)
    storm = pmesh.ShardedStorm(
        n=n,
        mesh=pmesh.make_mesh(4),
        params=_params(n, fused_exchange="pallas"),
        seed=4,
    )
    assert (storm.exchange_mode, storm.exchange_impl) == (
        "shard_map",
        "pallas",
    )
    storm.run(_storm_sched(ticks, n))
    _assert_states_equal(single.state, storm.state)


@pytest.mark.slow
def test_shard_count_invariance_n64k_slow(eight_devices):
    """The at-scale version of the invariance gate: n=64k storm across
    1/8 shards + the single-device engine, bitwise, including a restore
    from a different shard count mid-storm."""
    n, ticks, cut = 65536, 12, 6
    params = es.ScalableParams(n=n, suspicion_ticks=5)
    single = ScalableCluster(n=n, params=params, seed=4)
    single.run(StormSchedule.churn_storm(ticks, n, fraction=0.1, seed=4))
    for shards in (1, 8):
        storm = pmesh.ShardedStorm(
            n=n, mesh=pmesh.make_mesh(shards), params=params, seed=4
        )
        storm.run(
            StormSchedule.churn_storm(ticks, n, fraction=0.1, seed=4)
        )
        _assert_states_equal(
            single.state, storm.state, "shards=%d: " % shards
        )


@pytest.mark.slow
def test_restore_across_shard_counts_mid_storm_n64k(
    eight_devices, tmp_path
):
    n, ticks, cut = 65536, 12, 6
    params = es.ScalableParams(n=n, suspicion_ticks=5)
    ref = ScalableCluster(n=n, params=params, seed=4)
    ref.run(StormSchedule.churn_storm(ticks, n, fraction=0.1, seed=4))
    a = pmesh.ShardedStorm(
        n=n, mesh=pmesh.make_mesh(8), params=params, seed=4
    )
    a.run(
        StormSchedule.churn_storm(ticks, n, fraction=0.1, seed=4).window(
            0, cut
        )
    )
    path = str(tmp_path / "midstorm64k")
    a.save(path)
    b = pmesh.ShardedStorm(
        n=n, mesh=pmesh.make_mesh(2), params=params, seed=9
    )
    b.load(path)
    b.run(
        StormSchedule.churn_storm(ticks, n, fraction=0.1, seed=4).window(
            cut, ticks
        )
    )
    _assert_states_equal(ref.state, b.state)
