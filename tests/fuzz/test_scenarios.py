"""Scenario generator: determinism, bounds, coverage, sparse roundtrip."""

from __future__ import annotations

import numpy as np

from ringpop_tpu.fuzz import scenarios as sc


def _cfgs():
    return (
        sc.ScenarioConfig(engine="full", n=8, ticks=24),
        sc.ScenarioConfig(engine="scalable", n=32, ticks=20),
    )


def test_generate_is_a_pure_function_of_the_seed():
    for cfg in _cfgs():
        for seed in (0, 1, 7, 2**31, 2**32 - 1):
            a, b = sc.generate(seed, cfg), sc.generate(seed, cfg)
            for plane in sc.BOOL_PLANES[cfg.engine] + (sc.PARTITION_PLANE,):
                pa, pb = getattr(a, plane, None), getattr(b, plane, None)
                assert (pa is None) == (pb is None), plane
                if pa is not None:
                    assert np.array_equal(pa, pb), (plane, seed)


def test_adjacent_seeds_differ():
    cfg = _cfgs()[0]
    a, b = sc.generate(10, cfg), sc.generate(11, cfg)
    assert any(
        not np.array_equal(getattr(a, p), getattr(b, p))
        for p in ("kill", "revive", "partition")
    )


def test_planes_shapes_and_bounds():
    for cfg in _cfgs():
        for seed in range(40):
            s = sc.generate(seed, cfg)
            for plane in sc.BOOL_PLANES[cfg.engine]:
                arr = getattr(s, plane, None)
                if arr is not None:
                    assert arr.shape == (cfg.ticks, cfg.n)
                    assert arr.dtype == np.bool_
            part = s.partition
            assert part.shape == (cfg.ticks, cfg.n)
            assert part.min() >= -1
            assert part.max() < cfg.max_groups


def test_full_engine_bootstrap_row_always_present():
    cfg = _cfgs()[0]
    for seed in range(20):
        s = sc.generate(seed, cfg)
        assert s.join[0].all(), "tick-0 bootstrap join is the harness row"


def test_move_catalog_coverage_across_seeds():
    """Every storm-move class fires somewhere in a modest seed range —
    churn, pileups (kills without revive), flaps, splits, regroups,
    leaves, resumes."""
    cfg = sc.ScenarioConfig(engine="full", n=8, ticks=24, max_moves=4)
    seen_kill = seen_revive = seen_part = seen_leave = seen_resume = False
    seen_join_rejoin = False
    for seed in range(200):
        s = sc.generate(seed, cfg)
        seen_kill |= s.kill.any()
        seen_revive |= s.revive.any()
        seen_part |= (s.partition >= 0).any()
        seen_leave |= s.leave.any()
        seen_resume |= s.resume.any()
        seen_join_rejoin |= s.join[1:].any()
    assert all(
        (seen_kill, seen_revive, seen_part, seen_leave, seen_resume,
         seen_join_rejoin)
    )


def test_packet_loss_derivation_is_stable_and_on_menu():
    cfg = sc.ScenarioConfig(engine="full", loss_levels=(0.0, 0.05, 0.2))
    losses = {sc.packet_loss_of(s, cfg) for s in range(300)}
    assert losses == {0.0, 0.05, 0.2}
    assert sc.packet_loss_of(42, cfg) == sc.packet_loss_of(42, cfg)
    # loss derivation must not perturb the schedule stream
    a = sc.generate(5, cfg)
    b = sc.generate(5, cfg._replace(loss_levels=(0.9,)))
    assert np.array_equal(a.kill, b.kill)


def test_sparse_faults_roundtrip():
    for cfg in _cfgs():
        for seed in (3, 17, 91):
            s = sc.generate(seed, cfg)
            faults = sc.sparse_faults(s, cfg.engine)
            r = sc.schedule_from_faults(
                cfg.engine, cfg.n, cfg.ticks, faults, config=cfg
            )
            for plane in sc.BOOL_PLANES[cfg.engine]:
                pa, pb = getattr(s, plane, None), getattr(r, plane, None)
                if pa is not None:
                    assert np.array_equal(pa, pb), (plane, seed)
            assert np.array_equal(s.partition, r.partition), seed


def test_schedule_from_faults_rejects_disabled_planes():
    cfg = sc.ScenarioConfig(
        engine="scalable", n=8, ticks=4, use_leave=False
    )
    import pytest

    with pytest.raises(ValueError, match="disables"):
        sc.schedule_from_faults(
            "scalable", 8, 4, [("leave", 1, 0, 1)], config=cfg
        )
