"""Clean-engine fuzz sweeps: the fixed-seed tier-1 smoke and the wide
slow-tier sweep (ISSUE 7 acceptance: >= 1000 scenarios, zero violations
on the unmodified engines)."""

from __future__ import annotations

import numpy as np
import pytest

from ringpop_tpu.fuzz import executor as fex
from ringpop_tpu.fuzz import invariants as inv
from ringpop_tpu.fuzz import scenarios as sc


def _sweep_clean(cfg, seeds):
    runs = fex.sweep(seeds, cfg)
    bad = {}
    for run in runs:
        for b, vs in inv.check_run(run).items():
            bad[run.seeds[b]] = [
                "%s: %s" % (v.invariant, v.message) for v in vs[:3]
            ]
    assert bad == {}, bad
    return runs


def test_smoke_full_engine_fixed_seeds():
    cfg = sc.ScenarioConfig(
        engine="full", n=8, ticks=20, loss_levels=(0.0, 0.1)
    )
    runs = _sweep_clean(cfg, list(range(8)))
    # the sweep exercised real storms, not quiet ticks
    assert sum(len(r.events[b]) for r in runs for b in range(len(r.seeds))) > 200
    assert all(d == 0 for r in runs for d in r.drops)


def test_smoke_scalable_engine_fixed_seeds():
    cfg = sc.ScenarioConfig(
        engine="scalable", n=32, ticks=24, loss_levels=(0.0, 0.1)
    )
    runs = _sweep_clean(cfg, list(range(8)))
    total_susp = sum(
        int(np.asarray(r.metrics.suspects_published).sum()) for r in runs
    )
    assert total_susp > 0, "storms must provoke the failure detector"


@pytest.mark.slow
def test_wide_sweep_1000_scenarios():
    # ISSUE 7 acceptance: >= 1000 fixed-seed scenarios across both
    # engines pass the full invariant suite
    full_cfg = sc.ScenarioConfig(
        engine="full", n=8, ticks=24, loss_levels=(0.0, 0.05, 0.2)
    )
    _sweep_clean(full_cfg, list(range(640)))
    scal_cfg = sc.ScenarioConfig(
        engine="scalable", n=32, ticks=24, loss_levels=(0.0, 0.05, 0.2)
    )
    _sweep_clean(scal_cfg, list(range(384)))
