"""Committed regression fixtures: every shrunk schedule under
tests/fuzz/fixtures/ replays clean on the CURRENT engines.

A fixture is born when the shrinker minimizes a failing seed (a real
bug, or a mutation-gate hunt); committing it turns that storm into a
permanent cheap regression test — if a future change re-introduces the
failure mode, the named invariant fires here with the minimal schedule
already in hand."""

from __future__ import annotations

from pathlib import Path

import pytest

from ringpop_tpu.fuzz import shrinker

FIXTURE_DIR = Path(__file__).parent / "fixtures"
FIXTURES = sorted(FIXTURE_DIR.glob("*.json"))


def test_fixture_dir_is_populated():
    assert FIXTURES, "at least one shrunk regression fixture is committed"


@pytest.mark.parametrize(
    "path", FIXTURES, ids=[p.stem for p in FIXTURES]
)
def test_fixture_replays_clean(path):
    doc = shrinker.load_fixture(str(path))
    assert doc["invariants"], "a fixture names the invariant it once broke"
    assert doc["faults"], "a fixture carries a minimal non-empty schedule"
    violations = shrinker.replay_fixture(doc)
    assert violations == [], [
        "%s: %s" % (v.invariant, v.message) for v in violations
    ]
