"""Mutation-detection gate (ISSUE 7 acceptance): deliberately breaking
the protocol makes the invariant checker fail with a NAMED invariant,
and the shrinker reduces a failing seed to a minimal schedule.

Every mutated executor is built with ``shared_cache=False`` so broken
traces never enter the shared executable caches."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.fuzz import executor as fex
from ringpop_tpu.fuzz import invariants as inv
from ringpop_tpu.fuzz import scenarios as sc
from ringpop_tpu.fuzz import shrinker
from ringpop_tpu.models.sim import engine
from ringpop_tpu.models.sim import engine_scalable as es

FULL_CFG = sc.ScenarioConfig(
    engine="full", n=8, ticks=24, loss_levels=(0.05,)
)
SCAL_CFG = sc.ScenarioConfig(
    engine="scalable", n=16, ticks=20, loss_levels=(0.05,)
)


def _viol_names(run, contract=None):
    by = inv.check_run(run, contract=contract)
    return sorted({v.invariant for vs in by.values() for v in vs}), by


def test_shortened_suspicion_is_caught_and_shrunk(tmp_path):
    """The engine expires suspicions after 2 ticks while the protocol
    contract says 6: the checker names suspicion-lower-bound and the
    shrinker reduces a failing storm to a minimal schedule that still
    reproduces it."""
    contract = fex.default_full_params(8, 24, 0.05)
    broken = contract._replace(suspicion_ticks=2)
    ex = fex.FullFuzzExecutor(FULL_CFG, params=broken, shared_cache=False)
    run = ex.run_seeds(list(range(8)))
    names, by = _viol_names(run, contract=contract)
    assert "suspicion-lower-bound" in names
    failing_seed = run.seeds[sorted(by)[0]]

    res = shrinker.shrink_seed(ex, failing_seed, contract=contract)
    assert "suspicion-lower-bound" in res.invariant_names
    # minimal reproduction: a single fault — one kill (dead partner) or
    # one partition cell (cross-side false suspect) arms a suspicion
    # that then expires early
    assert len(res.faults) == 1
    assert res.faults[0][0] in ("kill", "partition")

    # the fixture round-trips, and the UNBROKEN engine passes it
    path = tmp_path / "m.json"
    shrinker.save_fixture(res, str(path), note="shortened suspicion")
    doc = shrinker.load_fixture(str(path))
    assert doc["invariants"] == ["suspicion-lower-bound"]
    assert shrinker.replay_fixture(doc, contract=contract) == []


def test_suppressed_refute_path_is_caught(monkeypatch):
    """A node that believes its own defamation instead of refuting
    (member.js:76-81 disabled) trips self-view-alive."""
    orig = engine._apply_updates

    def no_refute(state, now, recv_mask, u_status, u_inc, u_src, u_sinc):
        n = state.known.shape[0]
        ids = jnp.arange(n, dtype=jnp.int32)
        is_self = ids[:, None] == ids[None, :]
        self_defame = recv_mask & is_self & (
            (u_status == 1) | (u_status == 2)
        )
        st, gate, start_t, stop_t, refute = orig(
            state, now, recv_mask & ~self_defame, u_status, u_inc,
            u_src, u_sinc,
        )
        st = st._replace(
            status=jnp.where(self_defame, u_status, st.status),
            inc=jnp.where(self_defame, u_inc, st.inc),
        )
        return st, gate | self_defame, start_t, stop_t, refute & False

    monkeypatch.setattr(engine, "_apply_updates", no_refute)
    ex = fex.FullFuzzExecutor(
        FULL_CFG, packet_loss=0.05, shared_cache=False
    )
    run = ex.run_seeds(list(range(6)))
    names, _ = _viol_names(run)
    assert "self-view-alive" in names


def test_scalable_dropped_publish_delta_is_caught_and_shrunk(monkeypatch):
    """An incremental-checksum path that forgets the publish delta
    diverges from the full recompute — scalable-checksum-exact, with a
    shrunk minimal schedule."""
    orig = es._publish_batch

    def no_delta(state, csum, slot, subj, new_status, new_inc, hearer, tick):
        st, _csum2 = orig(
            state, csum, slot, subj, new_status, new_inc, hearer, tick
        )
        return st, csum  # hearers' checksums silently miss the delta

    monkeypatch.setattr(es, "_publish_batch", no_delta)
    ex = fex.ScalableFuzzExecutor(
        SCAL_CFG, packet_loss=0.05, shared_cache=False
    )
    run = ex.run_seeds(list(range(6)))
    names, by = _viol_names(run)
    assert "scalable-checksum-exact" in names

    res = shrinker.shrink_seed(
        ex,
        run.seeds[sorted(by)[0]],
        target=["scalable-checksum-exact"],
    )
    assert res.invariant_names == ["scalable-checksum-exact"]
    assert len(res.faults) <= 2  # one fault class suffices to publish


def test_scalable_shortened_suspicion_is_caught():
    contract = fex.default_scalable_params(16, 0.05)
    broken = contract._replace(suspicion_ticks=2)
    ex = fex.ScalableFuzzExecutor(
        SCAL_CFG, params=broken, shared_cache=False
    )
    run = ex.run_seeds(list(range(8)))
    names, _ = _viol_names(run, contract=contract)
    assert "suspicion-lower-bound" in names


@pytest.mark.slow
def test_stale_alive_override_is_caught():
    """SWIM precedence broken so a stale ALIVE at an EQUAL incarnation
    overrides FAULTY (member.js:171-202 requires strictly greater): a
    full-sync carrying the stale record flips a faulty view back without
    any refute — alive-after-faulty-refute."""
    cfg = sc.ScenarioConfig(
        engine="full", n=8, ticks=32, loss_levels=(0.2,)
    )
    orig = engine._overrides

    def broken(u_status, u_inc, c_status, c_inc):
        return orig(u_status, u_inc, c_status, c_inc) | (
            (u_status == 0) & (c_status == 2) & (u_inc >= c_inc)
        )

    engine._overrides = broken
    try:
        ex = fex.FullFuzzExecutor(
            cfg, packet_loss=0.2, shared_cache=False
        )
        run = ex.run_seeds(list(range(48)))
        names, _ = _viol_names(run)
        assert "alive-after-faulty-refute" in names
    finally:
        engine._overrides = orig


def test_shrink_refuses_a_passing_schedule():
    ex = fex.FullFuzzExecutor(FULL_CFG, packet_loss=0.05)
    with pytest.raises(ValueError, match="does not violate"):
        shrinker.shrink(ex, [("kill", 3, 1, 1)], seed=0)
