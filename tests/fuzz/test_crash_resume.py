"""Crash-and-recover gate (ISSUE 9 acceptance): a storm killed at a
randomized tick — including a kill injected mid-checkpoint-write leaving
a torn file — auto-recovers from the newest valid checkpoint (falling
back past corrupt ones) and reaches a final state bitwise-identical to
the uninterrupted run, for the full engine, the scalable engine, and
RoutedStorm.  n=64 tier-1; n=1k slow."""

import numpy as np
import pytest

from ringpop_tpu.fuzz import crash
from ringpop_tpu.fuzz.scenarios import (
    FULL,
    SCALABLE,
    CrashPlan,
    ScenarioConfig,
    crash_plan_of,
)

CFG64 = ScenarioConfig(n=64, ticks=12)


def _no_violations(report):
    assert report.violations == [], "\n".join(
        v.message for v in report.violations[:4]
    )


@pytest.mark.parametrize("driver", [FULL, SCALABLE, crash.ROUTED])
def test_crash_resume_bitwise_n64(driver, tmp_path):
    """Seed-drawn kill points + seed-drawn corruption modes, all three
    drivers.  Seeds chosen so the sample covers a clean preemption AND
    at least one corrupt-newest mode (asserted below so the coverage
    can't silently rot if crash_plan_of's derivation changes)."""
    seeds = (1, 7, 8)  # torn-manifest@8, flip-byte@3, clean-preempt@9
    modes = set()
    for seed in seeds:
        plan = crash_plan_of(seed, CFG64)
        modes.add(plan.corrupt)
        report = crash.run_crash_resume(
            seed, str(tmp_path), driver=driver, config=CFG64, every=3
        )
        _no_violations(report)
        if plan.corrupt != "none":
            # the damaged newest checkpoint was detected, named, skipped
            assert report.skipped_errors, report
    assert "none" in modes and len(modes) >= 2, modes


@pytest.mark.parametrize("driver", [FULL, SCALABLE, crash.ROUTED])
def test_torn_mid_write_falls_back_to_previous_checkpoint(driver, tmp_path):
    """The acceptance-critical shape, forced: kill AFTER a cadence save
    exists, mid-write of the next (torn manifest) — recovery must fall
    back to the previous valid checkpoint, never resume the torn one."""
    report = crash.run_crash_resume(
        5,
        str(tmp_path),
        driver=driver,
        config=CFG64,
        every=3,
        plan=CrashPlan(kill_tick=8, corrupt="torn-manifest", frac=0.5),
    )
    _no_violations(report)
    assert report.resumed_tick == 6  # fell back past the torn tick-8 save
    assert "CheckpointTornError" in report.skipped_errors


def test_bitrot_and_missing_shard_fall_back(tmp_path):
    """Flipped byte (digest) and missing shard (sharded family) each
    named and fallen past."""
    r = crash.run_crash_resume(
        9,
        str(tmp_path),
        driver=SCALABLE,
        config=CFG64,
        every=3,
        plan=CrashPlan(kill_tick=8, corrupt="flip-byte", frac=0.6),
    )
    _no_violations(r)
    assert "CheckpointDigestError" in r.skipped_errors
    r = crash.run_crash_resume(
        9,
        str(tmp_path),
        driver=SCALABLE,
        config=CFG64,
        every=3,
        shards=4,
        plan=CrashPlan(kill_tick=8, corrupt="missing-shard", frac=0.5),
    )
    _no_violations(r)
    assert "CheckpointShardError" in r.skipped_errors


def test_no_valid_checkpoint_is_a_clean_restart(tmp_path):
    """Kill before the first cadence line with the forced save torn: no
    valid checkpoint exists, recovery restarts clean — and still lands
    bitwise on the uninterrupted run."""
    report = crash.run_crash_resume(
        3,
        str(tmp_path),
        driver=SCALABLE,
        config=CFG64,
        every=6,
        plan=CrashPlan(kill_tick=2, corrupt="torn-array", frac=0.3),
    )
    _no_violations(report)
    assert report.resumed_tick is None
    assert report.skipped_errors  # the torn artifact was seen and named


def test_crash_resume_reports_are_deterministic(tmp_path):
    """Same seed, same plan -> identical report shape (the replay
    property every fuzz layer leans on)."""
    a = crash.run_crash_resume(
        13, str(tmp_path), driver=SCALABLE, config=CFG64, every=4
    )
    b = crash.run_crash_resume(
        13, str(tmp_path), driver=SCALABLE, config=CFG64, every=4
    )
    _no_violations(a)
    assert (a.kill_tick, a.corrupt, a.resumed_tick, a.skipped_errors) == (
        b.kill_tick,
        b.corrupt,
        b.resumed_tick,
        b.skipped_errors,
    )


@pytest.mark.slow
@pytest.mark.parametrize("driver", [FULL, SCALABLE, crash.ROUTED])
def test_crash_resume_bitwise_n1k(driver, tmp_path):
    cfg = ScenarioConfig(n=1000, ticks=10)
    report = crash.run_crash_resume(
        21,
        str(tmp_path),
        driver=driver,
        config=cfg,
        every=4,
        plan=CrashPlan(kill_tick=7, corrupt="torn-manifest", frac=0.5),
    )
    _no_violations(report)
    assert report.resumed_tick == 4
