"""Invariant checker unit tests over SYNTHETIC event streams — each
invariant must fire on a crafted counterexample and stay silent on the
matching clean stream (the engine-level clean sweep is
test_fuzz_smoke.py; the engine-level counterexamples are
test_mutation_gate.py)."""

from __future__ import annotations

from types import SimpleNamespace

import numpy as np
import pytest

from ringpop_tpu.fuzz import invariants as inv
from ringpop_tpu.fuzz.scenarios import ScenarioConfig, _blank_schedule
from ringpop_tpu.obs import events as ev

N, T = 4, 10
CONTRACT = SimpleNamespace(suspicion_ticks=4, piggyback_factor=15)


def _sched(**planes):
    sched = _blank_schedule(ScenarioConfig(engine="full", n=N, ticks=T))
    sched.join[0, :] = False  # quiet harness for synthetic streams
    for name, cells in planes.items():
        arr = getattr(sched, name)
        for t, node in cells:
            arr[t, node] = True
    return sched


def _state(ch_pb=0):
    return SimpleNamespace(
        ch_active=np.zeros((N, N), bool) if not ch_pb else np.ones((N, N), bool),
        ch_pb=np.full((N, N), ch_pb, np.int32),
    )


def _ev(tick, kind, observer, subject, old=-1, new=-1, inc=0, aux=0):
    return {
        "tick": tick,
        "kind": kind,
        "observer": observer,
        "subject": subject,
        "old_status": old,
        "new_status": new,
        "inc": inc,
        "aux": aux,
        "kind_name": ev.EVENT_KINDS[kind],
    }


def _metrics(events):
    """TickMetrics-compatible dict that reconciles with ``events``."""
    a = ev._as_arrays(events) if events else {
        k: np.zeros(0, np.int64) for k in ev.FIELDS
    }
    return {
        "pings_sent": np.array([int(np.sum(a["kind"] == ev.EV_PING))]),
        "suspects_marked": np.array(
            [int(np.sum(a["kind"] == ev.EV_SUSPECT))]
        ),
        "faulties_marked": np.array(
            [int(np.sum(a["kind"] == ev.EV_FAULTY))]
        ),
        "refutes": np.array([int(np.sum(a["kind"] == ev.EV_REFUTE))]),
        "join_merges": np.array([int(np.sum(a["kind"] == ev.EV_JOIN))]),
    }


def _check(events, sched=None, state=None, metrics=None):
    return inv.check_full_instance(
        events,
        state if state is not None else _state(),
        metrics if metrics is not None else _metrics(events),
        sched if sched is not None else _sched(),
        CONTRACT,
        contract=CONTRACT,
    )


def _names(violations):
    return inv.violation_names(violations)


def test_clean_stream_passes():
    events = [
        _ev(2, ev.EV_STATUS, 0, 1, old=-1, new=0, inc=1),
        _ev(3, ev.EV_STATUS, 0, 1, old=0, new=1, inc=1),  # suspect arm
        _ev(7, ev.EV_FAULTY, 0, 1, old=1, new=2, inc=1),
        _ev(7, ev.EV_STATUS, 0, 1, old=1, new=2, inc=1, aux=16),
    ]
    assert _check(events) == []


def test_incarnation_regression_fires():
    events = [
        _ev(2, ev.EV_STATUS, 0, 1, old=-1, new=0, inc=5),
        _ev(4, ev.EV_STATUS, 0, 1, old=0, new=0, inc=3),
    ]
    assert "incarnation-monotonic" in _names(_check(events))


def test_incarnation_regression_allowed_across_observer_revive():
    # observer 0 dies and revives: its view resets, the relearn may
    # legitimately regress
    sched = _sched(kill=[(2, 0)], revive=[(5, 0)])
    events = [
        _ev(2, ev.EV_STATUS, 0, 1, old=-1, new=0, inc=5),
        _ev(8, ev.EV_STATUS, 0, 1, old=-1, new=0, inc=3),
    ]
    assert _check(events, sched=sched) == []


def test_view_continuity_break_fires():
    events = [
        _ev(2, ev.EV_STATUS, 0, 1, old=-1, new=1, inc=1),
        _ev(4, ev.EV_STATUS, 0, 1, old=0, new=2, inc=1),  # old != prev new
    ]
    assert "view-continuity" in _names(_check(events))


def test_alive_after_faulty_without_refute_fires():
    events = [
        _ev(2, ev.EV_STATUS, 0, 1, old=-1, new=2, inc=1),
        _ev(5, ev.EV_STATUS, 0, 1, old=2, new=0, inc=1),
    ]
    assert "alive-after-faulty-refute" in _names(_check(events))


def test_alive_after_faulty_with_matching_refute_passes():
    events = [
        _ev(2, ev.EV_STATUS, 0, 1, old=-1, new=2, inc=1),
        _ev(4, ev.EV_REFUTE, 1, 1, new=0, inc=5),
        _ev(4, ev.EV_SUSPECT, 3, 1, old=0, new=1, inc=1),
        _ev(4, ev.EV_STATUS, 3, 1, old=0, new=1, inc=1),
        _ev(5, ev.EV_STATUS, 0, 1, old=2, new=0, inc=5),
    ]
    assert _check(events) == []


def test_alive_after_faulty_with_wrong_inc_refute_fires():
    events = [
        _ev(2, ev.EV_STATUS, 0, 1, old=-1, new=2, inc=1),
        _ev(4, ev.EV_REFUTE, 1, 1, new=0, inc=9),
        _ev(4, ev.EV_SUSPECT, 3, 1, old=0, new=1, inc=1),
        _ev(4, ev.EV_STATUS, 3, 1, old=0, new=1, inc=1),
        _ev(5, ev.EV_STATUS, 0, 1, old=2, new=0, inc=5),
    ]
    assert "alive-after-faulty-refute" in _names(_check(events))


def test_alive_after_faulty_via_scheduled_revive_passes():
    # subject 1 revived at row 5: stamp 7 minted at tick 6
    sched = _sched(kill=[(1, 1)], revive=[(5, 1)])
    events = [
        _ev(2, ev.EV_STATUS, 0, 1, old=-1, new=2, inc=1),
        _ev(8, ev.EV_STATUS, 0, 1, old=2, new=0, inc=7),
    ]
    assert _check(events, sched=sched) == []


def test_self_defamation_fires():
    events = [_ev(3, ev.EV_STATUS, 1, 1, old=0, new=1, inc=2)]
    assert "self-view-alive" in _names(_check(events))


def test_suspicion_lower_bound_fires():
    events = [
        _ev(3, ev.EV_STATUS, 0, 1, old=-1, new=1, inc=1),  # arm at 3
        _ev(5, ev.EV_FAULTY, 0, 1, old=1, new=2, inc=1),  # fire at 5 < 3+4
        _ev(5, ev.EV_STATUS, 0, 1, old=1, new=2, inc=1, aux=16),
    ]
    assert "suspicion-lower-bound" in _names(_check(events))


def test_suspicion_upper_bound_fires_for_undisturbed_observer():
    events = [
        _ev(2, ev.EV_STATUS, 0, 1, old=-1, new=1, inc=1),
        _ev(9, ev.EV_FAULTY, 0, 1, old=1, new=2, inc=1),  # 7 > 4 late
        _ev(9, ev.EV_STATUS, 0, 1, old=1, new=2, inc=1, aux=16),
    ]
    assert "suspicion-upper-bound" in _names(_check(events))


def test_suspicion_late_fire_allowed_for_disturbed_observer():
    # observer 0 SIGSTOP'd then resumed: its timers fire late, as the
    # reference's do
    sched = _sched(kill=[(3, 0)], resume=[(7, 0)])
    events = [
        _ev(2, ev.EV_STATUS, 0, 1, old=-1, new=1, inc=1),
        _ev(9, ev.EV_FAULTY, 0, 1, old=1, new=2, inc=1),
        _ev(9, ev.EV_STATUS, 0, 1, old=1, new=2, inc=1, aux=16),
    ]
    assert _check(events, sched=sched) == []


def test_piggyback_ceiling_fires():
    events = []
    vs = _check(events, state=_state(ch_pb=16))
    assert "piggyback-ceiling" in _names(vs)
    assert _check(events, state=_state(ch_pb=0)) == []


def test_refute_without_defamation_fires():
    events = [_ev(5, ev.EV_REFUTE, 2, 2, new=0, inc=7)]
    assert "refute-reachability" in _names(_check(events))


def test_refute_across_partition_cut_fires():
    # observers 0,1 in group 0 defame node 3; node 3 is alone in group 1
    # for the whole run — it could never have heard the defamation
    sched = _sched()
    sched.partition[1] = np.array([0, 0, 0, 1], np.int32)
    events = [
        _ev(3, ev.EV_SUSPECT, 0, 3, old=0, new=1, inc=1),
        _ev(3, ev.EV_STATUS, 0, 3, old=0, new=1, inc=1),
        _ev(6, ev.EV_REFUTE, 3, 3, new=0, inc=8),
    ]
    assert "refute-reachability" in _names(_check(events, sched=sched))
    # heal at row 4: now the defamation can reach it — clean
    sched2 = _sched()
    sched2.partition[1] = np.array([0, 0, 0, 1], np.int32)
    sched2.partition[4] = np.zeros(N, np.int32)
    assert _check(events, sched=sched2) == []


def test_reachability_closure_hops_through_groups():
    groups = np.array(
        [
            [0, 0, 1, 1],  # t0: 0~1, 2~3
            [0, 1, 1, 0],  # t1: 1~2 bridges
            [0, 0, 0, 0],
        ],
        np.int32,
    )
    assert inv._reachable(groups, 0, 0, 2, 1)  # 0->1 at t0, 1->2 at t1
    assert not inv._reachable(groups, 0, 0, 2, 0)  # no bridge yet
    assert inv._reachable(groups, 0, 0, 3, 2)


def test_metrics_reconcile_mismatch_fires():
    events = [_ev(2, ev.EV_PING, 0, 1, aux=1)]
    m = _metrics(events)
    m["pings_sent"] = np.array([3])  # counter says 3, stream says 1
    assert "metrics-reconcile" in _names(_check(events, metrics=m))


def test_event_overflow_fires():
    vs = inv.check_full_instance(
        [], _state(), _metrics([]), _sched(), CONTRACT,
        contract=CONTRACT, drops=5,
    )
    assert "event-overflow" in _names(vs)


# -- scalable checker --------------------------------------------------------


def _scal_sched(ticks=8, n=4):
    cfg = ScenarioConfig(engine="scalable", n=n, ticks=ticks)
    return _blank_schedule(cfg)


def _scal_metrics(ticks=8, **cols):
    base = {
        "suspects_published": np.zeros(ticks, np.int32),
        "faulties_published": np.zeros(ticks, np.int32),
        "refutes_published": np.zeros(ticks, np.int32),
        "pings_sent": np.full(ticks, 4, np.int32),
        "pings_delivered": np.full(ticks, 4, np.int32),
    }
    base.update({k: np.asarray(v) for k, v in cols.items()})
    return SimpleNamespace(**base)


def _scal_state(n=4, checksum=None, proc_alive=None):
    return SimpleNamespace(
        checksum=(
            checksum
            if checksum is not None
            else np.zeros(n, np.uint32)
        ),
        proc_alive=(
            proc_alive if proc_alive is not None else np.ones(n, bool)
        ),
    )


SCAL_PARAMS = SimpleNamespace(suspicion_ticks=4, checksum_in_tick=True)


def test_scalable_checksum_divergence_fires():
    vs = inv.check_scalable_instance(
        _scal_state(checksum=np.array([1, 2, 3, 4], np.uint32)),
        _scal_metrics(),
        _scal_sched(),
        SCAL_PARAMS,
        recomputed_checksum=np.array([1, 2, 3, 5], np.uint32),
    )
    assert "scalable-checksum-exact" in _names(vs)


def test_scalable_proc_alive_fold_fires():
    sched = _scal_sched()
    sched.kill[2, 1] = True
    vs = inv.check_scalable_instance(
        _scal_state(proc_alive=np.ones(4, bool)),  # engine says alive
        _scal_metrics(),
        sched,
        SCAL_PARAMS,
    )
    assert "scalable-proc-alive" in _names(vs)


def test_scalable_suspicion_lower_bound_fires():
    m = _scal_metrics(
        suspects_published=[0, 1, 0, 0, 0, 0, 0, 0],
        faulties_published=[0, 0, 0, 1, 0, 0, 0, 0],  # 2 < 4 ticks later
    )
    vs = inv.check_scalable_instance(
        _scal_state(), m, _scal_sched(), SCAL_PARAMS
    )
    assert "suspicion-lower-bound" in _names(vs)
    m2 = _scal_metrics(
        suspects_published=[0, 1, 0, 0, 0, 0, 0, 0],
        faulties_published=[0, 0, 0, 0, 0, 1, 0, 0],  # 4 ticks later: ok
    )
    assert (
        inv.check_scalable_instance(
            _scal_state(), m2, _scal_sched(), SCAL_PARAMS
        )
        == []
    )


def test_scalable_refutes_need_defamation_fires():
    m = _scal_metrics(refutes_published=[0, 0, 1, 0, 0, 0, 0, 0])
    vs = inv.check_scalable_instance(
        _scal_state(), m, _scal_sched(), SCAL_PARAMS
    )
    assert "refutes-need-defamation" in _names(vs)


def test_scalable_pings_conserved_fires():
    m = _scal_metrics(pings_delivered=np.full(8, 9, np.int32))
    vs = inv.check_scalable_instance(
        _scal_state(), m, _scal_sched(), SCAL_PARAMS
    )
    assert "pings-conserved" in _names(vs)
