"""Batched fuzz executor: vmap gate-equivalence against the single-
cluster drivers, flight-stream drain, and sweep bucketing."""

from __future__ import annotations

import jax
import numpy as np
import pytest

from ringpop_tpu.fuzz import executor as fex
from ringpop_tpu.fuzz import scenarios as sc
from ringpop_tpu.models.sim.cluster import SimCluster
from ringpop_tpu.models.sim.storm import ScalableCluster

FULL_CFG = sc.ScenarioConfig(engine="full", n=8, ticks=12, loss_levels=(0.0,))
SCAL_CFG = sc.ScenarioConfig(
    engine="scalable", n=16, ticks=12, loss_levels=(0.0,)
)


@pytest.fixture(scope="module")
def full_run():
    ex = fex.FullFuzzExecutor(FULL_CFG)
    return ex, ex.run_seeds([0, 1, 2])


def test_full_instances_match_single_cluster_bitwise(full_run):
    """vmap is semantics-preserving: instance b of the batched run IS
    the single-cluster trajectory for its (seed, schedule)."""
    ex, run = full_run
    for b, seed in enumerate(run.seeds):
        solo = SimCluster(n=FULL_CFG.n, params=ex.params, seed=seed)
        assert solo.params == ex.params  # no silent param drift
        sched = sc.generate(seed, FULL_CFG)
        solo.run(sched)
        solo_state = jax.device_get(solo.state)
        for field, batched in zip(
            type(solo_state)._fields, run.final_state
        ):
            if batched is None:
                continue
            got = np.asarray(batched)[b]
            want = np.asarray(getattr(solo_state, field))
            assert np.array_equal(got, want), (field, seed)


def test_full_event_streams_are_per_instance(full_run):
    ex, run = full_run
    assert len(run.events) == 3
    assert run.drops == (0, 0, 0)
    # every instance bootstraps: 8 joins recorded at tick 1
    for stream in run.events:
        joins = [e for e in stream if e["kind_name"] == "join"]
        assert len([e for e in joins if e["tick"] == 1]) == FULL_CFG.n
    # streams differ between instances (different storms)
    assert len(run.events[0]) != len(run.events[1]) or any(
        a != b for a, b in zip(run.events[0], run.events[1])
    )


def test_metrics_are_instance_major(full_run):
    _, run = full_run
    assert np.asarray(run.metrics.pings_sent).shape == (3, FULL_CFG.ticks)


def test_scalable_instances_match_single_cluster_bitwise():
    ex = fex.ScalableFuzzExecutor(SCAL_CFG)
    seeds = [4, 9]
    run = ex.run_schedules(
        [sc.generate(s, SCAL_CFG) for s in seeds], seeds=seeds
    )
    for b, seed in enumerate(seeds):
        solo = ScalableCluster(n=SCAL_CFG.n, params=ex.params, seed=seed)
        solo.run(sc.generate(seed, SCAL_CFG))
        solo_state = jax.device_get(solo.state)
        for field, batched in zip(
            type(solo_state)._fields, run.final_state
        ):
            if batched is None:
                continue
            got = np.asarray(batched)[b]
            want = np.asarray(getattr(solo_state, field))
            assert np.array_equal(got, want), (field, seed)


def test_sweep_buckets_by_packet_loss():
    cfg = FULL_CFG._replace(loss_levels=(0.0, 0.25))
    seeds = list(range(12))
    runs = fex.sweep(seeds, cfg)
    assert {r.params.packet_loss for r in runs} == {
        sc.packet_loss_of(s, cfg) for s in seeds
    }
    covered = sorted(s for r in runs for s in r.seeds)
    assert covered == seeds
    for r in runs:
        for s in r.seeds:
            assert sc.packet_loss_of(s, cfg) == r.params.packet_loss


def test_executor_rejects_recorderless_params():
    with pytest.raises(ValueError, match="flight_recorder"):
        fex.FullFuzzExecutor(
            FULL_CFG,
            params=fex.default_full_params(8, 12)._replace(
                flight_recorder=False
            ),
        )


def test_event_capacity_bound_covers_the_emitters():
    from ringpop_tpu.models.sim import flight

    # the sizing derives from the emitters' EXACT per-tick lane count
    assert flight.max_events_per_tick(8) == 3 * 64 + 10 * 8
    cap = fex.event_capacity_for(8, 24)
    assert cap >= 25 * flight.max_events_per_tick(8)
    assert cap & (cap - 1) == 0  # power of two
