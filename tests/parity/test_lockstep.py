"""North-star #1: engine vs host-oracle checksum parity, tick by tick.

The batched device engine (farmhash checksum mode — bit-exact reference
checksum strings, lib/membership/index.js:48-123) and the host object
oracle (one host Membership per node + the C++ FarmHash oracle) run the
same event schedule and must produce IDENTICAL per-node uint32 checksums
after every tick.  Any divergence in SWIM precedence, refutation,
dissemination budgets, full-sync, suspicion, or checksum encoding fails
these tests at the first differing tick.
"""

import jax
import numpy as np
import pytest

from ringpop_tpu.models.sim import engine
from ringpop_tpu.models.sim.cluster import default_addresses
from ringpop_tpu.ops import checksum_encode as ce
from ringpop_tpu.parity import OracleCluster


def run_lockstep(n, schedule, params=None, seed=0):
    """schedule: list of dicts with optional kill/revive/join/partition
    [N]-arrays.  Asserts per-tick checksum equality; returns tick count."""
    params = params or engine.SimParams(n=n, checksum_mode="farmhash")
    addresses = default_addresses(n)
    universe = ce.Universe.from_addresses(addresses)
    state = engine.init_state(params, seed=seed, universe=universe)
    oracle = OracleCluster(params, addresses, seed=seed)
    tick = jax.jit(lambda s, i: engine.tick(s, i, params, universe))

    for t, ev in enumerate(schedule):
        inputs = engine.TickInputs.quiet(n)._replace(
            **{
                k: jax.numpy.asarray(v)
                for k, v in ev.items()
                if k in ("kill", "revive", "join", "partition", "leave", "resume")
            }
        )
        state, metrics = tick(state, inputs)
        got = np.asarray(state.checksum).astype(np.uint32)
        res = oracle.tick(ev)
        want = res.checksums
        mismatch = np.flatnonzero(got != want)
        assert mismatch.size == 0, (
            f"tick {t}: engine/oracle checksums differ at nodes "
            f"{mismatch[:8].tolist()} (engine "
            f"{[hex(x) for x in got[mismatch[:4]]]}, oracle "
            f"{[hex(x) for x in want[mismatch[:4]]]})"
        )
        assert bool(np.asarray(metrics.converged)) == res.converged, f"tick {t}"
    return len(schedule)


def quiet(n, ticks):
    return [{} for _ in range(ticks)]


def join_all(n):
    return [{"join": np.ones(n, bool)}]


def test_bootstrap_and_converge_n16():
    n = 16
    run_lockstep(n, join_all(n) + quiet(n, 20))


def test_kill_suspect_faulty_n16():
    n = 16
    kill = np.zeros(n, bool)
    kill[5] = True
    sched = join_all(n) + quiet(n, 6) + [{"kill": kill}] + quiet(n, 40)
    run_lockstep(n, sched)


def test_revive_rejoin_n16():
    n = 16
    kill = np.zeros(n, bool)
    kill[3] = True
    revive = np.zeros(n, bool)
    revive[3] = True
    sched = (
        join_all(n)
        + quiet(n, 6)
        + [{"kill": kill}]
        + quiet(n, 34)
        + [{"revive": revive}]
        + quiet(n, 30)
    )
    run_lockstep(n, sched)


def test_staggered_joins_n16():
    n = 16
    sched = []
    for start in range(0, n, 4):
        j = np.zeros(n, bool)
        j[start : start + 4] = True
        sched.append({"join": j})
        sched += quiet(n, 3)
    sched += quiet(n, 20)
    run_lockstep(n, sched)


def test_packet_loss_n16():
    n = 16
    params = engine.SimParams(n=n, checksum_mode="farmhash", packet_loss=0.15)
    run_lockstep(n, join_all(n) + quiet(n, 40), params=params)


def test_partition_heal_n16():
    n = 16
    part = np.zeros(n, np.int32)
    part[n // 2 :] = 1
    heal = np.zeros(n, np.int32)
    sched = (
        join_all(n)
        + quiet(n, 8)
        + [{"partition": part}]
        + quiet(n, 40)
        + [{"partition": heal}]
        + quiet(n, 40)
    )
    run_lockstep(n, sched)


def test_churn_storm_n24():
    n = 24
    rng = np.random.default_rng(7)
    sched = join_all(n) + quiet(n, 8)
    alive = np.ones(n, bool)
    for _ in range(6):
        kill = np.zeros(n, bool)
        revive = np.zeros(n, bool)
        for i in rng.choice(n, size=3, replace=False):
            if alive[i]:
                kill[i] = True
                alive[i] = False
            else:
                revive[i] = True
                alive[i] = True
        sched.append({"kill": kill, "revive": revive})
        sched += quiet(n, 9)
    sched += quiet(n, 45)
    run_lockstep(n, sched)


def test_leave_and_rejoin_n16():
    n = 16
    lv = np.zeros(n, bool)
    lv[4] = True
    rj = np.zeros(n, bool)
    rj[4] = True
    sched = (
        join_all(n)
        + quiet(n, 8)
        + [{"leave": lv}]
        + quiet(n, 25)
        + [{"join": rj}]
        + quiet(n, 25)
    )
    run_lockstep(n, sched)


def test_suspend_resume_n16():
    n = 16
    kill = np.zeros(n, bool)
    kill[6] = True
    rs = np.zeros(n, bool)
    rs[6] = True
    # SIGSTOP (kill without reset) ... SIGCONT (resume keeps state)
    sched = (
        join_all(n)
        + quiet(n, 6)
        + [{"kill": kill}]
        + quiet(n, 12)
        + [{"resume": rs}]
        + quiet(n, 40)
    )
    run_lockstep(n, sched)


@pytest.mark.slow
def test_bootstrap_n128():
    n = 128
    run_lockstep(n, join_all(n) + quiet(n, 24))


def test_dirty_batch_boundary_n16():
    """dirty_batch=4 at n=16 forces BOTH checksum recompute paths — the
    bounded gather/encode/scatter batch (n_dirty <= 4) and the full
    recompute fallback (dissemination waves dirty > 4 rows) — through the
    kill/revive lifecycle, lockstep-checked against the oracle each tick."""
    n = 16
    params = engine.SimParams(n=n, checksum_mode="farmhash", dirty_batch=4)
    kill = np.zeros(n, bool)
    kill[7] = True
    revive = np.zeros(n, bool)
    revive[7] = True
    sched = (
        join_all(n)
        + quiet(n, 12)
        + [{"kill": kill}]
        + quiet(n, 34)
        + [{"revive": revive}]
        + quiet(n, 12)
    )
    run_lockstep(n, sched, params=params)


def test_parity_recompute_full_n16():
    """The straight-line full-recompute shape (the TPU production path —
    the tunnel's compile helper rejects the gated loop) must be
    bit-identical to the gated path: lockstep vs the oracle through the
    same kill/revive lifecycle as the dirty-batch boundary test."""
    n = 16
    params = engine.SimParams(
        n=n, checksum_mode="farmhash", parity_recompute="full"
    )
    kill = np.zeros(n, bool)
    kill[7] = True
    revive = np.zeros(n, bool)
    revive[7] = True
    sched = (
        join_all(n)
        + quiet(n, 12)
        + [{"kill": kill}]
        + quiet(n, 34)
        + [{"revive": revive}]
        + quiet(n, 12)
    )
    run_lockstep(n, sched, params=params)
