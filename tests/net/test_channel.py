"""Framed JSON channel: request/response, errors, timeouts, concurrency."""

import threading
import time

import pytest

from ringpop_tpu.net import Channel, ChannelError, RemoteError
from ringpop_tpu.net.timers import FakeTimers


@pytest.fixture
def pair():
    a, b = Channel("127.0.0.1:0"), Channel("127.0.0.1:0")
    a.listen()
    b.listen()
    yield a, b
    a.destroy()
    b.destroy()


def test_request_response(pair):
    a, b = pair
    b.register("/echo", lambda head, body: ({"h": head}, {"b": body}))
    head, body = a.request(b.host_port, "/echo", "hi", [1, 2], timeout_s=2)
    assert head == {"h": "hi"}
    assert body == {"b": [1, 2]}


def test_remote_error(pair):
    a, b = pair

    def boom(head, body):
        raise RemoteError({"type": "ringpop-tpu.test", "message": "nope"})

    b.register("/boom", boom)
    with pytest.raises(RemoteError) as e:
        a.request(b.host_port, "/boom", timeout_s=2)
    assert e.value.payload["type"] == "ringpop-tpu.test"


def test_unknown_endpoint(pair):
    a, b = pair
    with pytest.raises(RemoteError) as e:
        a.request(b.host_port, "/nope", timeout_s=2)
    assert e.value.payload["type"] == "ringpop-tpu.bad-endpoint"


def test_connect_failure():
    a = Channel("127.0.0.1:0")
    a.listen()
    try:
        with pytest.raises(ChannelError):
            a.request("127.0.0.1:1", "/x", timeout_s=2)
    finally:
        a.destroy()


def test_timeout(pair):
    a, b = pair
    release = threading.Event()

    def slow(head, body):
        release.wait(5)
        return None, None

    b.register("/slow", slow)
    t0 = time.time()
    with pytest.raises(ChannelError) as e:
        a.request(b.host_port, "/slow", timeout_s=0.2)
    assert e.value.type == "ringpop-tpu.timeout"
    assert time.time() - t0 < 2
    release.set()


def test_concurrent_out_of_order(pair):
    a, b = pair
    gate = threading.Event()

    def first(head, body):
        gate.wait(5)
        return None, "first"

    def second(head, body):
        return None, "second"

    b.register("/first", first)
    b.register("/second", second)
    results = {}

    def call(ep):
        results[ep] = a.request(b.host_port, ep, timeout_s=5)[1]

    t1 = threading.Thread(target=call, args=("/first",))
    t1.start()
    time.sleep(0.05)
    call("/second")  # completes while /first is parked
    assert results == {"/second": "second"}
    gate.set()
    t1.join(5)
    assert results["/first"] == "first"


def test_bidirectional_over_shared_socket(pair):
    a, b = pair
    a.register("/ping-back", lambda h, body: (None, body + 1))
    b.register("/fwd", lambda h, body: (None, body * 2))
    assert a.request(b.host_port, "/fwd", None, 21, timeout_s=2)[1] == 42
    assert b.request(a.host_port, "/ping-back", None, 1, timeout_s=2)[1] == 2


def test_fake_timers_ordering():
    ft = FakeTimers()
    fired = []
    ft.set_timeout(lambda: fired.append("b"), 2.0)
    h = ft.set_timeout(lambda: fired.append("a"), 1.0)
    ft.set_timeout(lambda: fired.append("c"), 3.0)
    ft.clear_timeout(h)
    assert ft.advance(2.5) == 1
    assert fired == ["b"]
    ft.advance(1.0)
    assert fired == ["b", "c"]
    assert ft.now_ms() > 1414142122274


def test_self_connect_treated_as_dead_peer():
    """Connecting to a freed ephemeral port can self-connect on localhost
    (source port == destination port); the channel must classify that as
    the peer being down, not answer requests with its own handlers."""
    import socket

    from ringpop_tpu.net.channel import Channel, ChannelError

    # deliberately self-connect to prove the phenomenon this guards
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    try:
        s.connect(("127.0.0.1", port))
        assert s.getsockname() == s.getpeername()
    finally:
        s.close()

    ch = Channel("127.0.0.1:0")
    ch.listen()
    try:
        # repeatedly request a dead ephemeral target; without the guard a
        # self-connect would make the request "succeed" via our own
        # handlers — with it, every attempt is a clean ChannelError
        ch.register("/echo", lambda head, body: (head, body))
        dead = "127.0.0.1:%d" % port
        for _ in range(5):
            with pytest.raises(ChannelError):
                ch.request(dead, "/echo", body={"x": 1}, timeout_s=1.0)
    finally:
        ch.destroy()


def test_destroyed_channel_refuses_new_connections():
    """destroy() must wake the blocked acceptor: otherwise the kernel
    listener keeps completing handshakes and a 'dead' node goes on
    answering requests (a destroyed cluster node would refute its own
    suspicion forever)."""
    import time

    from ringpop_tpu.net.channel import Channel, ChannelError, RemoteError

    server = Channel("127.0.0.1:0")
    hp = server.listen()
    server.register("/echo", lambda head, body: (head, body))
    client = Channel("127.0.0.1:0")
    client.listen()
    try:
        _, res = client.request(hp, "/echo", body="x", timeout_s=2.0)
        assert res == "x"
        server.destroy()
        time.sleep(0.05)
        for _ in range(20):
            with pytest.raises((ChannelError, RemoteError)):
                # fresh connection each time: the pooled one died with the
                # server, and new handshakes must now be refused/ignored
                client.request(hp, "/echo", body="y", timeout_s=0.5)
    finally:
        client.destroy()
        server.destroy()


def test_malformed_frame_closes_connection(pair):
    """A peer that sends garbage (a frame that isn't valid JSON) must not
    crash the server — the connection is dropped/errored and the channel
    keeps serving well-formed peers (the proxy layer can therefore never
    see an unparseable head: the transport rejects it first — the analog
    of proxy-test.js:911-955 'handle body failures' / 'non json head')."""
    import socket
    import struct

    a, b = pair
    b.register("/ok", lambda head, body: ("fine", None))

    host, port = b.host_port.split(":")
    raw = socket.create_connection((host, int(port)), timeout=2)
    try:
        garbage = b"\xff\xfenot json at all"
        raw.sendall(struct.pack(">I", len(garbage)) + garbage)
        # server must not hang or crash; it either closes or ignores
        raw.settimeout(2.0)
        try:
            got = raw.recv(65536)
        except (socket.timeout, ConnectionResetError, OSError):
            got = b""
    finally:
        raw.close()
    del got  # any response (or close) is fine; the invariant is below

    # the channel still serves well-formed requests afterwards
    head, _ = a.request(b.host_port, "/ok", None, None, timeout_s=2)
    assert head == "fine"
