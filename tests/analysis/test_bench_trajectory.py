"""Bench-trajectory collator (scripts/collate_bench_trajectory.py):
filename parsing, phase/direction classification, deterministic
collation, the regression detector, and the committed-artifact gate the
eighth check_all_budgets.py entry runs (ISSUE 19 satellite)."""

from __future__ import annotations

import importlib.util
import json
import os

import pytest

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _load():
    spec = importlib.util.spec_from_file_location(
        "collate_bench_trajectory",
        os.path.join(
            REPO_ROOT, "scripts", "collate_bench_trajectory.py"
        ),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def mod():
    return _load()


def test_parse_name(mod):
    assert mod.parse_name("BENCH_r4.json") == (4, "unknown")
    assert mod.parse_name("BENCH_r12_cpu.json") == (12, "cpu")
    assert mod.parse_name("BENCH_r7_mesh_tpu.json") == (7, "tpu")
    # a trailing non-platform tag folds under "unknown", not a backend
    assert mod.parse_name("BENCH_r7_mesh.json") == (7, "unknown")
    assert mod.parse_name("BENCH_TRAJECTORY.json") is None
    assert mod.parse_name("BENCH_rX.json") is None
    assert mod.parse_name("notes.json") is None


def test_phase_of_ordering(mod):
    # churn_parity_ must win over parity_ (prefix order matters)
    assert mod.phase_of("churn_parity_ticks") == "churn_parity"
    assert mod.phase_of("parity_ticks") == "parity"
    assert mod.phase_of("route_queries_per_sec") == "route"
    assert mod.phase_of("reqtrace_records") == "reqtrace"
    assert mod.phase_of("slo_p99") == "slo"
    assert mod.phase_of("value") == "core"


def test_numeric_metrics_keeps_numbers_folds_bools(mod):
    out = mod.numeric_metrics(
        {
            "a": 3,
            "b": 2.5,
            "gate": True,
            "off": False,
            "cmd": "python bench.py",  # string: dropped
            "series": [1, 2],  # list: dropped
            "nested": {"x": 1},  # object: dropped
            "none": None,
        }
    )
    assert out == {"a": 3, "b": 2.5, "gate": 1, "off": 0}


def test_direction_higher_better_wins_over_suffix_collision(mod):
    # the _per_sec / _sec collision: throughputs are HIGHER-better
    assert mod.direction("parity_mode_node_ticks_per_sec") == +1
    assert mod.direction("route_wire_mbps") == +1
    assert mod.direction("rings_equal") == +1
    assert mod.direction("drain_ms") == -1
    assert mod.direction("hist_overhead_frac") == -1
    assert mod.direction("reqtrace_drops") == -1
    # round-dependent headline scalars are informational, never flagged
    assert mod.direction("value") is None
    assert mod.direction("elapsed_s") is None


def _write_bench(root, name, payload):
    (root / name).write_text(json.dumps(payload), encoding="utf-8")


def test_collate_and_regressions(mod, tmp_path):
    _write_bench(
        tmp_path,
        "BENCH_r1_cpu.json",
        {"route_ticks_per_sec": 100.0, "drain_ms": 10.0, "note": "x"},
    )
    _write_bench(
        tmp_path,
        "BENCH_r2_cpu.json",
        {"route_ticks_per_sec": 80.0, "drain_ms": 10.5},
    )
    _write_bench(tmp_path, "BENCH_r2_tpu.json", {"route_ticks_per_sec": 5.0})
    _write_bench(tmp_path, "broken.json", {"x": 1})  # ignored
    (tmp_path / "BENCH_r3_cpu.json").write_text("not json")  # ignored
    traj = mod.collate(tmp_path)
    assert traj["sources"] == [
        "BENCH_r1_cpu.json",
        "BENCH_r2_cpu.json",
        "BENCH_r2_tpu.json",
    ]
    cpu = traj["backends"]["cpu"]
    assert cpu["rounds"] == [1, 2]
    assert cpu["phases"]["route"]["route_ticks_per_sec"] == {
        "1": 100.0,
        "2": 80.0,
    }
    assert "note" not in str(cpu["phases"])
    # backends never cross-compare: the tpu round is no regression
    found = mod.regressions(traj)
    assert len(found) == 1
    r = found[0]
    assert (r["backend"], r["metric"]) == ("cpu", "route_ticks_per_sec")
    assert r["from_round"] == 1 and r["to_round"] == 2
    assert r["drop_frac"] == pytest.approx(0.2)
    # the 5% drain_ms wobble stays under the 10% threshold...
    assert not any(f["metric"] == "drain_ms" for f in found)
    # ...but a tighter threshold flags it (direction-aware: UP is bad)
    tight = mod.regressions(traj, threshold=0.04)
    assert any(f["metric"] == "drain_ms" for f in tight)


def test_render_is_deterministic(mod, tmp_path):
    _write_bench(tmp_path, "BENCH_r1.json", {"b": 2, "a": 1})
    one = mod.render(mod.collate(tmp_path))
    two = mod.render(mod.collate(tmp_path))
    assert one == two
    assert one.endswith("\n")
    json.loads(one)  # valid JSON


def test_committed_artifact_matches_regeneration(mod):
    """The gate itself: BENCH_TRAJECTORY.json is committed and must
    byte-match a fresh collation of the committed BENCH files."""
    artifact = os.path.join(REPO_ROOT, "BENCH_TRAJECTORY.json")
    assert os.path.exists(artifact), (
        "run scripts/collate_bench_trajectory.py --write"
    )
    with open(artifact, encoding="utf-8") as fh:
        committed = fh.read()
    assert committed == mod.render(mod.collate()), (
        "BENCH_TRAJECTORY.json is stale — re-run "
        "scripts/collate_bench_trajectory.py --write"
    )
    traj = json.loads(committed)
    assert traj["sources"], "the trajectory must fold real snapshots"


def test_gate_is_registered_in_check_all_budgets(mod):
    spec = importlib.util.spec_from_file_location(
        "check_all_budgets",
        os.path.join(REPO_ROOT, "scripts", "check_all_budgets.py"),
    )
    driver = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(driver)
    assert ("bench-trajectory", "collate_bench_trajectory.py") in driver.GATES
