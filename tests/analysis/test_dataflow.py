"""Unit coverage for the jaxpr dataflow slicer (analysis/dataflow.py).

Small synthetic programs with KNOWN flows: the slicer must see exactly
the edges that exist — through scan carries (including flows that only
appear after one loop iteration), cond branches and predicates, while
bodies — and must NOT invent edges between independent dataflows (a
spurious edge here would make the noninterference prong cry wolf on
every obs plane in the repo).
"""

import jax
import jax.numpy as jnp
import pytest

from ringpop_tpu.analysis import dataflow


def _reach(fn, args, seeds):
    closed = jax.make_jaxpr(fn)(*args)
    return dataflow.slice_reachability(closed, seeds)


def _labels(reach):
    return [frozenset(r) for r in reach]


class TestPlainFlows:
    def test_independent_args_stay_separate(self):
        def fn(a, b):
            return a + 1, b * 2

        reach = _reach(fn, (jnp.ones(3), jnp.ones(3)), ["A", "B"])
        assert _labels(reach) == [frozenset({"A"}), frozenset({"B"})]

    def test_mixing_eqn_merges_labels(self):
        def fn(a, b):
            return a + b

        reach = _reach(fn, (jnp.ones(3), jnp.ones(3)), ["A", "B"])
        assert _labels(reach) == [frozenset({"A", "B"})]

    def test_unseeded_inputs_are_invisible(self):
        def fn(a, b):
            return a + b, b

        reach = _reach(fn, (jnp.ones(3), jnp.ones(3)), ["A", None])
        assert _labels(reach) == [frozenset({"A"}), frozenset()]

    def test_witness_chain_names_the_eqns(self):
        def fn(a):
            return (a * 2 + 1).sum()

        reach = _reach(fn, (jnp.ones(3),), ["A"])
        chain = dataflow.witness_chain(reach[0]["A"])
        assert "<input>" in chain
        assert "mul" in chain and "add" in chain and "reduce_sum" in chain

    def test_witness_chain_truncates_long_flows(self):
        def fn(a):
            for _ in range(40):
                a = a + 1
            return a

        reach = _reach(fn, (jnp.ones(3),), ["A"])
        chain = dataflow.witness_chain(reach[0]["A"], limit=8)
        assert "eqns) ..." in chain
        assert chain.count("->") <= 10


class TestScan:
    def test_carry_positions_stay_separate(self):
        # two independent carry lanes: taint must not jump lanes
        def fn(a, b, xs):
            def body(c, x):
                ca, cb = c
                return (ca + x, cb * 2), ca.sum()

            return jax.lax.scan(body, (a, b), xs)

        reach = _reach(
            fn,
            (jnp.ones(3), jnp.ones(3), jnp.ones((4, 3))),
            ["A", "B", None],
        )
        labels = _labels(reach)
        assert labels[0] == frozenset({"A"})  # final carry a
        assert labels[1] == frozenset({"B"})  # final carry b
        assert labels[2] == frozenset({"A"})  # ys from ca only

    def test_cross_iteration_flow_needs_the_fixpoint(self):
        # lane swap each iteration: A reaches BOTH final carries only
        # via the second iteration — a single body pass cannot see it
        def fn(a, b, xs):
            def body(c, x):
                ca, cb = c
                return (cb, ca + x), x.sum()

            return jax.lax.scan(body, (a, b), xs)

        reach = _reach(
            fn,
            (jnp.ones(3), jnp.ones(3), jnp.ones((4, 3))),
            ["A", "B", None],
        )
        labels = _labels(reach)
        assert labels[0] == frozenset({"A", "B"})
        assert labels[1] == frozenset({"A", "B"})

    def test_xs_reach_carry_and_ys(self):
        def fn(c0, xs):
            def body(c, x):
                return c + x, c

            return jax.lax.scan(body, c0, xs)

        reach = _reach(fn, (jnp.ones(3), jnp.ones((4, 3))), ["C", "X"])
        labels = _labels(reach)
        assert labels[0] == frozenset({"C", "X"})
        # ys emit the PRE-update carry, which from iteration 2 on holds
        # xs taint — the fixpoint must surface it
        assert labels[1] == frozenset({"C", "X"})


class TestCondAndWhile:
    def test_cond_branches_map_positionally(self):
        def fn(p, a, b):
            return jax.lax.cond(
                p, lambda x, y: (x + 1, y), lambda x, y: (x, y * 2), a, b
            )

        reach = _reach(
            fn, (jnp.bool_(True), jnp.ones(3), jnp.ones(3)), [None, "A", "B"]
        )
        labels = _labels(reach)
        assert labels[0] == frozenset({"A"})
        assert labels[1] == frozenset({"B"})

    def test_tainted_predicate_reaches_every_output(self):
        # control dependence: a value that picks the branch steers both
        # outputs even without a data edge
        def fn(p, a, b):
            return jax.lax.cond(
                p, lambda x, y: (x + 1, y), lambda x, y: (x, y * 2), a, b
            )

        reach = _reach(
            fn, (jnp.bool_(True), jnp.ones(3), jnp.ones(3)), ["P", None, None]
        )
        labels = _labels(reach)
        assert labels[0] == frozenset({"P"})
        assert labels[1] == frozenset({"P"})

    def test_zero_iteration_while_returns_its_initial_carry(self):
        # the body OVERWRITES the tainted slot — but a while that never
        # runs returns the initial carry, so the taint must still be
        # reported on the output (review round: soundness hole)
        def fn(n, a):
            def cond(c):
                return c[0] < n

            def body(c):
                return c[0] + 1, jnp.zeros_like(c[1])

            return jax.lax.while_loop(cond, body, (jnp.int32(0), a))

        reach = _reach(fn, (jnp.int32(0), jnp.ones(3)), [None, "A"])
        assert "A" in reach[1]

    def test_late_carry_taint_reaches_the_loop_condition(self):
        # taint enters the cond-read slot only AFTER one iteration
        # (b -> a via the body); the condition then steers every carry,
        # so B must spill to the untainted lane too (review round:
        # control sub must be walked AFTER the body fixpoint)
        def fn(a, b, z):
            def cond(c):
                return c[0].sum() < 10.0

            def body(c):
                ca, cb, cz = c
                return cb, cb, cz + 1.0

            return jax.lax.while_loop(cond, body, (a, b, z))

        reach = _reach(
            fn,
            (jnp.ones(3), jnp.ones(3), jnp.ones(3)),
            [None, "B", None],
        )
        assert "B" in reach[2]  # via the condition, not a data edge

    def test_while_carry_lanes_and_condition(self):
        def fn(n, a, b):
            def cond(c):
                return c[0] < n

            def body(c):
                i, x, y = c
                return i + 1, x + 1.0, y

            return jax.lax.while_loop(cond, body, (jnp.int32(0), a, b))

        reach = _reach(
            fn, (jnp.int32(5), jnp.ones(3), jnp.ones(3)), ["N", "A", "B"]
        )
        labels = _labels(reach)
        # N steers the loop condition -> reaches every carry out; the
        # x/y lanes otherwise stay separate
        assert labels[1] == frozenset({"A", "N"})
        assert labels[2] == frozenset({"B", "N"})


class TestShardMap:
    """ISSUE 18 satellite: direct coverage for the precise 1:1
    shard_map boundary (round 17 added it so telemetry planes entering
    the sharded exchange don't conservatively taint the heard tile)."""

    def _traced(self):
        import numpy as np
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()[:4]), ("i",))

        def inner(a, b):
            return a + 1.0, b * 2.0

        fn = shard_map(
            inner, mesh=mesh, in_specs=(P("i"), P("i")), out_specs=P("i")
        )
        return jax.make_jaxpr(fn)(jnp.ones(8), jnp.ones(8))

    def _shard_eqn(self, closed):
        def find(jaxpr):
            for eqn in jaxpr.eqns:
                if eqn.primitive.name == "shard_map":
                    return eqn
                for sub in dataflow.sub_jaxprs(eqn, precise=True):
                    inner, _ = sub.open_()
                    got = find(inner)
                    if got is not None:
                        return got
            return None

        eqn = find(closed.jaxpr)
        assert eqn is not None, "no shard_map eqn traced"
        return eqn

    def test_precise_boundary_is_positional(self):
        eqn = self._shard_eqn(self._traced())
        precise = dataflow.sub_jaxprs(eqn, precise=True)
        assert len(precise) == 1
        assert precise[0].in_map == list(range(len(eqn.invars)))
        assert precise[0].out_positional

    def test_audit_boundary_stays_conservative(self):
        # the historical traversal keeps its unmapped fallback (findings
        # text inside kernels is pinned against it)
        eqn = self._shard_eqn(self._traced())
        audit = dataflow.sub_jaxprs(eqn, precise=False)
        assert len(audit) == 1
        assert audit[0].in_map is None
        assert not audit[0].out_positional

    def test_slice_keeps_lanes_separate_through_shard_map(self):
        closed = self._traced()
        reach = dataflow.slice_reachability(closed, ["A", "B"])
        assert [frozenset(r) for r in reach] == [
            frozenset({"A"}),
            frozenset({"B"}),
        ]


class TestSliceApi:
    def test_seed_arity_mismatch_raises(self):
        closed = jax.make_jaxpr(lambda a, b: a + b)(
            jnp.ones(3), jnp.ones(3)
        )
        with pytest.raises(ValueError, match="seed_labels"):
            dataflow.slice_reachability(closed, ["A"])

    def test_audit_and_precise_sub_jaxprs_share_one_table(self):
        # the historical (audit) table and the precise table come from
        # ONE function — while is conservative there, mapped here
        def fn(n, a):
            return jax.lax.while_loop(
                lambda c: c[0] < n, lambda c: (c[0] + 1, c[1]), (n, a)
            )

        closed = jax.make_jaxpr(fn)(jnp.int32(3), jnp.ones(2))
        (eqn,) = [
            e for e in closed.jaxpr.eqns if e.primitive.name == "while"
        ]
        audit = dataflow.sub_jaxprs(eqn, precise=False)
        precise = dataflow.sub_jaxprs(eqn, precise=True)
        assert [s.label for s in audit] == ["while_cond", "while_body"]
        assert [s.label for s in precise] == ["while_cond", "while_body"]
        assert all(s.in_map is None for s in audit)
        assert all(s.in_map is not None for s in precise)
        assert precise[1].carry_feedback  # body carries feed back
        assert precise[0].control  # the condition steers control
