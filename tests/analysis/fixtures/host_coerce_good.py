"""GOOD: static host math + device-side dtype ops only."""
import jax
import jax.numpy as jnp


@jax.jit
def step(x):
    rows = int(x.shape[0])  # static metadata — not a traced value
    total = jnp.sum(x).astype(jnp.int32)
    return total + rows


def host_side(values):
    # not a jit context: coercion is fine
    return int(values[0])
