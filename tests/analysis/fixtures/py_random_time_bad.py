"""BAD: trace-time nondeterminism baked into a jitted function, in all
the common spellings."""
import datetime
import random
import time
from time import time as now_s

import jax


@jax.jit
def step(x):
    jitter = random.random()  # finding: py-random-time
    stamp = time.time()  # finding: py-random-time
    wall = datetime.datetime.now()  # finding: py-random-time
    bare = now_s()  # finding: py-random-time (from-import alias)
    return x * jitter + stamp + wall.microsecond + bare
