"""BAD: asserting over traced values inside a jitted function."""
import jax
import jax.numpy as jnp


@jax.jit
def step(x):
    total = jnp.sum(x)
    assert total > 0  # finding: assert-on-traced
    return total
