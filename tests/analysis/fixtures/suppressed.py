"""Suppression handling: same violations as the bad fixtures, silenced
per line — except one deliberately mis-named suppression that must NOT
silence its finding."""
import jax
import jax.numpy as jnp


@jax.jit
def step(x):
    scale = int(x[0])  # jaxgate: ignore[host-coerce]
    flag = bool(x.any())  # jaxgate: ignore
    total = float(jnp.sum(x, dtype=jnp.float32))  # jaxgate: ignore[implicit-dtype]
    wrapped = int(
        x[1]
    )  # jaxgate: ignore[host-coerce] — comment on the statement's LAST line
    return scale + flag + total + wrapped


def trace_time_table(n):  # jaxgate: host
    # host helper: called with static args during tracing; exempt from
    # jit-context rules even though step() calls it
    return [int(v) for v in range(n)]


@jax.jit
def uses_table(x):
    tbl = trace_time_table(x.shape[0])
    return x + jnp.asarray(tbl, jnp.int32)
