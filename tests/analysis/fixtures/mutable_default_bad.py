"""BAD: mutable / array-valued defaults shared across calls."""
import jax.numpy as jnp
import numpy as np


def gather(indices, out=[]):  # finding: mutable-default
    out.append(indices)
    return out


def scale(x, table=np.zeros(4), opts={}):  # findings: mutable-default x2
    return x * table, opts


def mask(x, keep=jnp.ones(8, bool)):  # finding: mutable-default
    return x[keep]
