"""GOOD: static-shape asserts and device-side clamping."""
import jax
import jax.numpy as jnp


@jax.jit
def step(x):
    rows = x.shape[0]
    assert rows % 8 == 0  # static metadata: checked once at trace time
    return jnp.maximum(jnp.sum(x), 0)
