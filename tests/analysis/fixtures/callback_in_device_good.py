"""GOOD: pure device math; observability happens host-side on outputs."""
import jax.numpy as jnp


def step(x):
    return jnp.sum(x + 1)
