"""GOOD: randomness and time are threaded in as data."""
import random
import time

import jax


@jax.jit
def step(x, rng_bits, now_ms):
    return x * rng_bits + now_ms


def make_inputs():
    # host side may draw freely
    return random.getrandbits(32), int(time.time() * 1000)
