"""GOOD: snapshots host-copied (or re-bound) before/after the dispatch."""
import functools

import jax


@functools.lru_cache(maxsize=None)
def _tick_fn(params):
    return jax.jit(lambda st, inp: st, donate_argnums=(0,))


def _plain_fn(params):
    return jax.jit(lambda st, inp: st)  # no donation: aliases stay live


class Cluster:
    def __init__(self, params):
        self.params = params
        self.state = None
        self._tick = _tick_fn(params)

    def step(self, inputs):
        # sanctioned: the snapshot is a HOST copy, not a device alias
        pre = jax.device_get(self.state)
        self.state = self._tick(self.state, inputs)
        return pre

    def step_rebound(self, inputs):
        pre = self.state
        self.state = self._tick(pre, inputs)
        pre = self.state  # re-snapshot after the dispatch
        return pre

    def step_before(self, inputs):
        pre = self.state
        out = pre.checksum  # read BEFORE the dispatch: buffers still live
        self.state = self._tick(pre, inputs)
        return out


class NonDonating:
    def __init__(self, params):
        self.state = None
        self._tick = _plain_fn(params)

    def step(self, inputs):
        # bounded-parity replay pattern (SimCluster): legal because this
        # driver's tick does NOT donate
        pre = self.state
        self.state = self._tick(pre, inputs)
        return pre
