"""BAD: numpy reductions on traced values inside a jitted function."""
import jax
import numpy as np


@jax.jit
def step(x, shape):
    size = int(np.prod(shape))  # findings: np-on-traced + host-coerce
    host = np.asarray(x)  # finding: np-on-traced
    return x.reshape((size,)) + host.sum()
