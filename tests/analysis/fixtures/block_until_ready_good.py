"""GOOD: no sync; callers (bench/obs) decide when to block."""


def run(fn, x):
    return fn(x)
