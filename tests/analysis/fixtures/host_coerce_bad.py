"""BAD: host coercions on traced values inside a jitted function."""
import jax
import jax.numpy as jnp


@jax.jit
def step(x):
    scale = int(x[0])  # finding: host-coerce
    val = float(jnp.sum(x))  # finding: host-coerce
    flag = bool(x.any())  # finding: host-coerce
    first = x[0].item()  # finding: host-coerce
    return scale + val + flag + first
