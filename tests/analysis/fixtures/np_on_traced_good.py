"""GOOD: math.prod for static shapes, jnp twins for traced data."""
import math

import jax
import jax.numpy as jnp
import numpy as np

TABLE = np.arange(16, dtype=np.uint32)  # module-level host constant is fine


@jax.jit
def step(x, shape):
    size = math.prod(shape)
    return jnp.sum(x).reshape(()) * size
