"""BAD: host callbacks in a device module."""
import jax
import jax.numpy as jnp
import numpy as np


def step(x):
    jax.debug.print("x = {}", x)  # finding: callback-in-device
    y = jax.pure_callback(  # finding: callback-in-device
        lambda v: np.asarray(v) + 1,
        jax.ShapeDtypeStruct(x.shape, x.dtype),
        x,
    )
    return jnp.sum(y)
