"""GOOD: None defaults, constructed per call."""
import jax.numpy as jnp
import numpy as np


def gather(indices, out=None):
    out = [] if out is None else out
    out.append(indices)
    return out


def scale(x, table=None):
    table = np.zeros(4) if table is None else table
    return x * table


def mask(x, keep=None, width=8):
    keep = jnp.ones(width, bool) if keep is None else keep
    return x[keep]
