"""BAD: bare device-state bindings read after a donating dispatch."""
import functools

import jax


@functools.lru_cache(maxsize=None)
def _tick_fn(params):
    return jax.jit(lambda st, inp: st, donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _routed_fns(params):
    f = jax.jit(lambda c, inp: c, donate_argnums=(0,))
    return f, f


class Cluster:
    def __init__(self, params):
        self.params = params
        self.state = None
        self._tick = _tick_fn(params)

    def step(self, inputs):
        pre = self.state  # bare alias of the donated carry
        self.state = self._tick(pre, inputs)
        return pre.checksum  # stale read: pre's buffers were donated

    def step_then_resnapshot(self, inputs):
        pre = self.state
        self.state = self._tick(pre, inputs)
        out = pre.checksum  # stale read — a LATER re-snapshot is no alibi
        pre = self.state
        return out, pre

    def step_via_attr(self, inputs):
        snap = self.state
        # the carry is dispatched through the ATTRIBUTE, not the alias —
        # snap still aliases the same donated buffers
        self.state = self._tick(self.state, inputs)
        return snap


class Routed:
    def __init__(self, params):
        self.state = None
        self.rstate = None
        # tuple unpacking from a donating factory
        self._tick, self._scanned = _routed_fns(params)

    def window(self, inputs):
        rpre = self.rstate
        (self.state, self.rstate), m = self._tick(
            (self.state, rpre), inputs
        )
        return m, rpre  # stale read of the routed half of the carry
