"""GOOD: every constructor pins its dtype (kwarg or positional)."""
import jax.numpy as jnp
import numpy as np


def build(n, buf):
    idx = jnp.arange(n, dtype=jnp.int32)
    acc = jnp.zeros(n, jnp.uint32)  # positional dtype
    pad = jnp.full((n, 2), 9, jnp.uint8)
    dev = jnp.asarray(np.asarray(buf, np.uint8))  # asarray preserves dtype
    return idx, acc, pad, dev
