"""BAD: flag-dependent default dtypes in a device module."""
import jax.numpy as jnp


def build(n):
    idx = jnp.arange(n)  # finding: implicit-dtype
    acc = jnp.zeros(n)  # finding: implicit-dtype
    pad = jnp.full((n, 2), 9)  # finding: implicit-dtype
    tbl = jnp.array([1, 2, 3])  # finding: implicit-dtype
    return idx, acc, pad, tbl
