"""BAD: device sync in library code."""


def run(fn, x):
    out = fn(x)
    out.block_until_ready()  # finding: block-until-ready
    return out
