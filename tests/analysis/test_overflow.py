"""The overflow prong: allowlist mechanics, the full-registry clean
pin, and the ISSUE 18 mutation proof.

The clean pin doubles as the satellite-1 triage regression: a full
sweep over every registered entry point must produce NO unsuppressed
event AND use every ALLOWED row (a bogus extra row is the only
stale-allowlist finding) — so the triage table can neither rot nor
silently grow.
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from ringpop_tpu.analysis import overflow
from ringpop_tpu.analysis.overflow import AllowRow


class TestAllowlistMatcher:
    def test_star_is_the_only_metacharacter(self):
        # the carry keys contain literal [i] — fnmatch would read a
        # character class and never match (the round-18 bug)
        assert overflow._match(
            "unbounded-carry:carry[0]", ("unbounded-carry:carry[*]",)
        )
        assert overflow._match(
            "unbounded-carry:carry[17]", ("unbounded-carry:carry[*]",)
        )
        assert not overflow._match(
            "unbounded-carry:carry0", ("unbounded-carry:carry[*]",)
        )
        # regex metachars in keys stay literal
        assert not overflow._match("a.c", ("abc",))

    def test_allowed_returns_first_matching_row(self):
        rows = (
            AllowRow(("e-*",), ("r:k1",), "one"),
            AllowRow(("*",), ("r:*",), "two"),
        )
        assert overflow.allowed("e-x", "r", "k1", rows) == 0
        assert overflow.allowed("other", "r", "k9", rows) == 1
        assert overflow.allowed("other", "q", "k9", rows) is None

    def test_every_committed_row_documents_why(self):
        for row in overflow.ALLOWED:
            assert len(row.why) > 40, row
            assert row.entries and row.keys, row


class TestMutationProof:
    """Seed the ISSUE 18 overflow bug class and prove the prong is the
    thing that catches it (with the detector's allowlist emptied, the
    finding appears; with a row covering it, it does not)."""

    def _doctored(self):
        # an engine-style tick scan accumulating an int32 event counter
        # by a per-tick delta: the classic silent-wrap telemetry bug
        def tick(state, _):
            count, mask = state
            count = count + jnp.sum(mask, dtype=jnp.int32) + 1
            return (count, mask), count

        def entry(count0, mask, ticks):
            return jax.lax.scan(tick, (count0, mask), ticks)

        args = (
            jnp.int32(0),
            jnp.ones(8, jnp.int32),
            jnp.zeros(4, jnp.int32),
        )
        return entry, args

    def test_seeded_accumulator_is_caught(self):
        entry, args = self._doctored()
        findings, used = overflow.check_entry(
            "doctored-entry", entry, args, allowlist=()
        )
        assert findings, "the seeded int32 accumulator escaped the prong"
        assert any(f.rule == "unbounded-carry" for f in findings)
        assert all(f.prong == "overflow" for f in findings)
        assert used == set()

    def test_detection_not_luck_allowlist_is_the_only_suppressor(self):
        entry, args = self._doctored()
        cover = (AllowRow(("doctored-*",), ("unbounded-carry:*",), "test"),)
        findings, used = overflow.check_entry(
            "doctored-entry", entry, args, allowlist=cover
        )
        assert [f for f in findings if f.rule == "unbounded-carry"] == []
        assert used == {0}

    def test_seeded_index_lane_is_caught(self):
        # int32 gather lane over a 100*N ring priced at the pod axis
        from ringpop_tpu.analysis import ranges

        def entry(table, idx):
            return jnp.take(table, idx)

        findings, _ = overflow.check_entry(
            "doctored-ring",
            entry,
            (jnp.zeros(800, jnp.uint32), jnp.zeros(3, jnp.int32)),
            spec=ranges.ScaleSpec(
                toy_n=8, n_max=ranges.N_MAX_PODS, coeffs=(1, 100)
            ),
            allowlist=(),
        )
        assert any(f.rule == "index-overflow" for f in findings)

    def test_broken_entry_is_a_trace_failure_finding(self):
        def boom():
            raise RuntimeError("nope")

        findings, _ = overflow.check_entry("broken", boom, ())
        assert [f.rule for f in findings] == ["trace-failure"]


class TestChangedOnlyScoping:
    def test_non_certifier_paths_skip_the_prong(self):
        assert overflow.entries_for_changed(["obs/statsd.py"]) == []
        assert overflow.entries_for_changed([]) == []

    def test_certifier_paths_rescan_everything(self):
        from ringpop_tpu.analysis import jaxpr_audit as ja

        names = overflow.entries_for_changed(["models/sim/engine.py"])
        assert names == [ep.name for ep in ja.DEFAULT_ENTRIES]
        assert overflow.entries_for_changed(["analysis/ranges.py"]) == names


class TestFullRegistryCleanPin:
    """One sweep proves three things: the tree is certifier-clean, no
    committed ALLOWED row is stale, and staleness detection itself
    works (the appended bogus row is flagged, and only it)."""

    def test_full_run_is_clean_and_allowlist_is_live(self):
        bogus = AllowRow(
            ("no-such-entry-*",), ("dtype-overflow:never.*",), "canary"
        )
        findings = overflow.check_overflow(
            allowlist=overflow.ALLOWED + (bogus,)
        )
        stale = [f for f in findings if f.rule == "stale-allowlist"]
        real = [f for f in findings if f.rule != "stale-allowlist"]
        assert real == [], "\n".join(f.message for f in real)
        assert len(stale) == 1, "\n".join(f.message for f in stale)
        assert f"ALLOWED[{len(overflow.ALLOWED)}]" in stale[0].message

    def test_subset_run_skips_staleness(self):
        findings = overflow.check_overflow(entry_names=["ring-device-lookup"])
        assert findings == []
