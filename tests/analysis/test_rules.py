"""Rule-framework coverage: good/bad fixture pairs per rule, suppression
comments, the ``# jaxgate: host`` opt-out, and the CLI surface."""

import json
from pathlib import Path

import pytest

from ringpop_tpu.analysis import astlint
from ringpop_tpu.analysis import findings as fmod

FIXTURES = Path(__file__).parent / "fixtures"

# rule -> (fixture stem, rel path the module is linted AS — device-scoped
# rules only fire under their configured path prefixes)
CASES = {
    "host-coerce": ("host_coerce", "ringpop_tpu/models/sim/fx.py"),
    "np-on-traced": ("np_on_traced", "ringpop_tpu/models/sim/fx.py"),
    "implicit-dtype": ("implicit_dtype", "ringpop_tpu/ops/fx.py"),
    "py-random-time": ("py_random_time", "ringpop_tpu/models/sim/fx.py"),
    "mutable-default": ("mutable_default", "ringpop_tpu/gossip/fx.py"),
    "block-until-ready": ("block_until_ready", "ringpop_tpu/api/fx.py"),
    "callback-in-device": ("callback_in_device", "ringpop_tpu/ops/fx.py"),
    "assert-on-traced": ("assert_on_traced", "ringpop_tpu/models/sim/fx.py"),
    "stale-ref-across-donation": (
        "stale_ref_across_donation",
        "ringpop_tpu/models/sim/fx.py",
    ),
}

EXPECTED_BAD_COUNTS = {
    "host-coerce": 4,
    "np-on-traced": 2,
    "implicit-dtype": 4,
    "py-random-time": 4,
    "mutable-default": 4,
    "block-until-ready": 1,
    "callback-in-device": 2,
    "assert-on-traced": 1,
    "stale-ref-across-donation": 4,
}


def _lint(stem: str, rel: str):
    src = (FIXTURES / f"{stem}.py").read_text()
    return astlint.lint_source(src, rel)


@pytest.mark.parametrize("rule", sorted(CASES))
def test_bad_fixture_fires(rule):
    stem, rel = CASES[rule]
    hits = [f for f in _lint(f"{stem}_bad", rel) if f.rule == rule]
    assert len(hits) == EXPECTED_BAD_COUNTS[rule], (
        f"{rule}: expected {EXPECTED_BAD_COUNTS[rule]} findings, got "
        f"{[(f.line, f.message) for f in hits]}"
    )
    assert all(f.line > 0 and f.path == rel for f in hits)


@pytest.mark.parametrize("rule", sorted(CASES))
def test_good_fixture_clean(rule):
    stem, rel = CASES[rule]
    hits = [f for f in _lint(f"{stem}_good", rel) if f.rule == rule]
    assert hits == [], [(f.line, f.message) for f in hits]


def test_scope_excludes_non_device_paths():
    # the same bad source outside the rule's path scope is not flagged
    src = (FIXTURES / "implicit_dtype_bad.py").read_text()
    hits = astlint.lint_source(src, "ringpop_tpu/gossip/fx.py")
    assert [f for f in hits if f.rule == "implicit-dtype"] == []
    src = (FIXTURES / "callback_in_device_bad.py").read_text()
    hits = astlint.lint_source(src, "ringpop_tpu/obs/fx.py")
    assert [f for f in hits if f.rule == "callback-in-device"] == []


def test_suppressions_and_host_marker():
    src = (FIXTURES / "suppressed.py").read_text()
    rel = "ringpop_tpu/models/sim/fx.py"
    hits = astlint.lint_source(src, rel)
    # named + bare suppressions silence their lines; the mis-named
    # ignore[implicit-dtype] must NOT silence the float() host-coerce
    assert [f.rule for f in hits] == ["host-coerce"]
    assert "float" in hits[0].source
    # without suppression handling all four coercions (including the
    # black-wrapped one whose comment sits on the statement's last line)
    # fire, and the host-marked helper stays exempt either way
    raw = astlint.lint_source(src, rel, respect_suppressions=False)
    assert len([f for f in raw if f.rule == "host-coerce"]) == 4


def test_module_alias_imports_do_not_evade_py_random_time():
    src = """
import time as clock
import numpy.random as npr
import jax

@jax.jit
def step(x):
    t = clock.time()
    r = npr.normal()
    return x * r + t
"""
    hits = astlint.lint_source(src, "ringpop_tpu/models/sim/fx.py")
    assert len([f for f in hits if f.rule == "py-random-time"]) == 2, hits


def test_marker_inside_string_literal_is_not_a_suppression():
    # only real comments count — a docstring or string mentioning the
    # marker syntax must not silence findings on its line
    src = '''
import jax

@jax.jit
def step(x):
    msg = "suppress with  # jaxgate: ignore  on the line"; y = int(x)
    return y
'''
    hits = astlint.lint_source(src, "ringpop_tpu/models/sim/fx.py")
    assert any(f.rule == "host-coerce" for f in hits)
    # the real-comment form on the same shape IS honored
    src_ok = src.replace(
        '"suppress with  # jaxgate: ignore  on the line"; y = int(x)',
        '"doc"; y = int(x)  # jaxgate: ignore[host-coerce]',
    )
    hits = astlint.lint_source(src_ok, "ringpop_tpu/models/sim/fx.py")
    assert not any(f.rule == "host-coerce" for f in hits)


def test_nested_def_violation_reported_once():
    # a violation inside a nested def must yield ONE finding (the nested
    # fn's own pass), not one per enclosing jit context
    src = """
import jax
import jax.numpy as jnp

@jax.jit
def outer(x):
    def inner(y):
        return int(y)
    return inner(x) + jnp.sum(x)
"""
    hits = astlint.lint_source(src, "ringpop_tpu/models/sim/fx.py")
    coerce = [f for f in hits if f.rule == "host-coerce"]
    assert len(coerce) == 1, [(f.line, f.message) for f in coerce]


def test_closure_captured_taint_still_flagged():
    # the nested def coerces a name captured from the enclosing jit
    # context — scope_taint must carry it across the boundary
    src = """
import jax

@jax.jit
def outer(x):
    def inner():
        return int(x)
    return inner()
"""
    hits = astlint.lint_source(src, "ringpop_tpu/models/sim/fx.py")
    assert any(f.rule == "host-coerce" for f in hits)


def test_traced_entries_registry_resolves():
    # every configured cross-module entry name must exist in its module —
    # a typo here silently un-registers a jit root (and its rule coverage)
    import ast as ast_mod

    pkg_root = Path(astlint.__file__).resolve().parents[1]
    for suffix, names in astlint.TRACED_ENTRIES.items():
        path = pkg_root / suffix
        assert path.exists(), f"TRACED_ENTRIES names missing module {suffix}"
        tree = ast_mod.parse(path.read_text())
        defined = {
            n.name
            for n in ast_mod.walk(tree)
            if isinstance(n, (ast_mod.FunctionDef, ast_mod.AsyncFunctionDef))
        }
        missing = names - defined
        assert not missing, f"{suffix}: unresolved entries {sorted(missing)}"


def test_jit_context_inference_via_lax_consumer():
    src = """
import jax
import jax.numpy as jnp

def body(carry, x):
    bad = int(carry)
    return carry + x, bad

def run(xs):
    return jax.lax.scan(body, jnp.int32(0), xs)
"""
    hits = astlint.lint_source(src, "ringpop_tpu/models/sim/fx.py")
    assert any(f.rule == "host-coerce" for f in hits)


def test_render_formats():
    f = fmod.Finding(
        rule="host-coerce",
        path="ringpop_tpu/x.py",
        line=3,
        message="int() on traced",
        source="y = int(x)",
    )
    text = fmod.render_text([f])
    assert "ringpop_tpu/x.py:3" in text and "host-coerce" in text
    doc = json.loads(fmod.render_json([f]))
    assert doc["count"] == 1
    assert doc["findings"][0]["rule"] == "host-coerce"


def test_cli_surface(tmp_path, capsys):
    from ringpop_tpu.analysis.__main__ import main

    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in astlint.RULES_BY_NAME:
        assert rule in out

    # a bad file passed explicitly exits non-zero with json findings
    bad = tmp_path / "ringpop_tpu" / "gossip"
    bad.mkdir(parents=True)
    target = bad / "fx.py"
    target.write_text((FIXTURES / "mutable_default_bad.py").read_text())
    rc = main([str(target), "--prong", "ast", "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any(f["rule"] == "mutable-default" for f in doc["findings"])


def test_cli_rejects_unknown_prong():
    from ringpop_tpu.analysis.__main__ import main

    with pytest.raises(SystemExit):
        main(["--prong", "nope"])


def test_explicit_missing_target_is_a_finding(capsys):
    # a typo'd CI/pre-commit path must not read as "0 findings"
    from ringpop_tpu.analysis.__main__ import main

    rc = main(
        ["--prong", "ast", "ringpop_tpu/ops/definitely_missing.py"]
    )
    assert rc == 1
    assert "unreadable-file" in capsys.readouterr().out
