"""The donation prong: cheap-probe tier-1 gate + mutation proofs.

Tier-1 wiring mirrors the cost gate: a cheap subset of the donating
drivers is compiled (seconds warm under the persistent XLA cache) and
diffed against the committed DONATION_BUDGET.json slice.  The PR-8 CPU
backend gate is VISIBLE manifest data here: ``donate_argnums`` is []
and every entry's alias map is empty on the CPU backend — the checker
has no backend special case.

Mutation proofs: a deliberately shape-mismatched donation is a
``donation-dropped`` finding; a doctored manifest makes the script exit
non-zero; ``--write`` refuses failed compiles AND dropped donations.
"""

import json

import jax
import jax.numpy as jnp
import pytest

from ringpop_tpu.analysis import donation
from ringpop_tpu.analysis.findings import render_text

pytestmark = pytest.mark.skipif(
    jax.default_backend()
    != donation.load_manifest().get("backend", "cpu"),
    reason="manifest banked on a different backend",
)


def test_cheap_probe_subset_matches_committed_manifest():
    findings = donation.check_against_manifest(
        entry_names=donation.CHEAP_ENTRIES
    )
    assert findings == [], "\n" + render_text(findings)


def test_manifest_pins_the_cpu_donation_off_gate():
    """On CPU the PR-8 gate (storm.donate_state_argnums() == ()) must be
    recorded as data: no donated params, empty alias maps."""
    manifest = donation.load_manifest()
    if manifest["backend"] != "cpu":
        pytest.skip("CPU-manifest shape check")
    assert manifest["donate_argnums"] == []
    for name, entry in manifest["entries"].items():
        assert entry["donated_params"] == 0, name
        assert entry["aliases"] == [], name
    # every registered donating driver is in the manifest
    assert set(manifest["entries"]) == {
        e.name for e in donation.DEFAULT_ENTRIES
    }


# -- mutation proofs --------------------------------------------------------


def _dropping_jit():
    # donated [8] f32 input, but the only output is a scalar — no
    # output matches, so XLA cannot alias and the donation is dropped
    return jax.jit(lambda x: x[:2].sum(), donate_argnums=(0,))


def test_shape_mismatched_donation_is_a_dropped_finding(recwarn):
    rec = donation.audit_jit(
        _dropping_jit(), (jnp.zeros((8,), jnp.float32),), (0,)
    )
    assert rec["donated_params"] == 1 and rec["aliased_params"] == 0
    assert rec["dropped"] == [
        {"param": 0, "shape": [8], "dtype": "float32"}
    ]
    findings = donation.compare_to_manifest(
        {"m": rec}, {"entries": {"m": rec}}
    )
    assert [f.rule for f in findings] == ["donation-dropped"]
    assert "float32[8]" in findings[0].message
    assert "silently dropped" in findings[0].message


def test_matching_donation_aliases_and_is_clean(recwarn):
    jf = jax.jit(lambda x, y: (x + 1, y.sum()), donate_argnums=(0,))
    rec = donation.audit_jit(
        jf, (jnp.zeros((4,), jnp.uint32), jnp.ones(3)), (0,)
    )
    assert rec["aliases"] == ["out{0} <- param 0"]
    assert rec["dropped"] == []
    findings = donation.compare_to_manifest(
        {"m": rec}, {"entries": {"m": rec}}
    )
    assert findings == []


def test_doctored_manifest_drifts(tmp_path):
    manifest = donation.load_manifest()
    doc = json.loads(json.dumps(manifest))  # deep copy
    name = donation.CHEAP_ENTRIES[0]
    doc["entries"][name]["aliases"] = ["out{0} <- param 0"]
    doc["entries"][name]["aliased_params"] = 1
    p = tmp_path / "DONATION_BUDGET.json"
    p.write_text(json.dumps(doc))
    findings = donation.check_against_manifest(
        entry_names=[name], path=p
    )
    assert any(f.rule == "donation-budget" for f in findings)


def test_doctored_manifest_script_exits_nonzero(tmp_path, capsys):
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "check_donation_budget",
        Path(__file__).resolve().parents[2]
        / "scripts"
        / "check_donation_budget.py",
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    manifest = donation.load_manifest()
    doc = json.loads(json.dumps(manifest))
    name = donation.CHEAP_ENTRIES[0]
    doc["entries"][name]["donated_params"] = 99
    p = tmp_path / "DONATION_BUDGET.json"
    p.write_text(json.dumps(doc))
    rc = mod.main(
        ["--budget", str(p), "--entries", name]
    )
    assert rc == 1
    assert "donation-budget" in capsys.readouterr().out


def test_write_refuses_failures_and_drops(tmp_path, recwarn):
    with pytest.raises(ValueError, match="failed entries"):
        donation.write_manifest(
            {"broken": {"error": "boom"}}, tmp_path / "d.json"
        )
    rec = donation.audit_jit(
        _dropping_jit(), (jnp.zeros((8,), jnp.float32),), (0,)
    )
    with pytest.raises(ValueError, match="dropped donations"):
        donation.write_manifest({"m": rec}, tmp_path / "d.json")


def test_backend_mismatch_is_loud_not_a_silent_pass(tmp_path):
    """A TPU session running against the CPU manifest (the one case
    where donation is LIVE) must fail with a bank-your-own message, not
    exit green with nothing compiled."""
    doc = json.loads(json.dumps(donation.load_manifest()))
    doc["backend"] = "definitely-not-this-backend"
    p = tmp_path / "DONATION_BUDGET.json"
    p.write_text(json.dumps(doc))
    findings = donation.check_against_manifest(path=p)
    assert len(findings) == 1
    assert findings[0].rule == "donation-budget"
    assert "banked on backend" in findings[0].message
    assert "--write" in findings[0].message


def test_unknown_entry_and_missing_manifest_are_findings(tmp_path):
    out = donation.collect(["no-such-entry"])
    assert out["no-such-entry"]["error"] == "unknown donation entry"
    findings = donation.check_against_manifest(
        path=tmp_path / "missing.json"
    )
    assert [f.rule for f in findings] == ["donation-budget"]
    assert "manifest missing" in findings[0].message
