"""The noninterference prong: repo-clean gate + mutation proofs.

The gate proves, for every obs-carrying entry point, that no obs-only
input leaf (flight recorder / histograms / wavefront) reaches a
trajectory output leaf — the static form of the gate-equivalence
property the n=64/n=1k A/B suites sample.  The mutation tests prove the
prong CAN fail: a seeded obs->trajectory edge (the ISSUE-15 example — a
histogram count folded into a suspicion deadline) and an unclassified
state field each produce a named finding.
"""

import jax
import jax.numpy as jnp
import pytest

from ringpop_tpu.analysis import jaxpr_audit as ja
from ringpop_tpu.analysis import noninterference as ni
from ringpop_tpu.analysis.findings import render_text

BY_NAME = {ep.name: ep for ep in ja.DEFAULT_ENTRIES}


# -- repo-clean gate --------------------------------------------------------


@pytest.mark.parametrize("name", ni.OBS_ENTRY_NAMES)
def test_obs_entry_is_noninterfering(name):
    fn, args = BY_NAME[name].build()
    findings = ni.check_entry(name, fn, args)
    assert findings == [], "\n" + render_text(findings)


def test_every_obs_carrying_entry_is_in_the_cheap_subset():
    """OBS_ENTRY_NAMES must stay exhaustive: an entry whose inputs carry
    obs-only leaves but which is missing from the subset would make the
    tier-1 gate silently partial."""
    regs = ni.state_registries()
    for ep in ja.DEFAULT_ENTRIES:
        fn, args = ep.build()
        labels = ni._flatten_labels(ni.label_tree(tuple(args), regs, "args"))
        has_obs = any(lab.kind == ni.KIND_OBS for lab in labels)
        assert has_obs == (ep.name in ni.OBS_ENTRY_NAMES), (
            f"{ep.name}: obs leaves={has_obs} but cheap-subset membership "
            f"={ep.name in ni.OBS_ENTRY_NAMES} — update "
            "noninterference.OBS_ENTRY_NAMES (and ENTRY_SOURCES)"
        )


# -- mutation proofs --------------------------------------------------------


def test_seeded_obs_to_trajectory_leak_is_caught():
    """The ISSUE-15 acceptance mutation: a histogram count folded into a
    suspicion deadline must fail with a named, eqn-located finding."""
    fn, args = BY_NAME["engine-tick-scan-histograms"].build()

    def doctored(state, inputs):
        st, metrics = fn(state, inputs)
        return st._replace(
            susp_deadline=st.susp_deadline
            + st.hist[0, 0].astype(jnp.int32)
        ), metrics

    findings = ni.check_entry("doctored", doctored, args)
    assert any(f.rule == "obs-interference" for f in findings)
    msg = next(
        f.message for f in findings if f.rule == "obs-interference"
    )
    assert "SimState.hist" in msg
    assert "SimState.susp_deadline" in msg
    assert "eqn chain:" in msg and "add@" in msg


def test_flight_recorder_leak_is_caught():
    """Same proof on the flight-recorder plane: the event head count
    steering the rng chain is an interference."""
    fn, args = BY_NAME["engine-tick-scan-flight-recorder"].build()

    def doctored(state, inputs):
        st, metrics = fn(state, inputs)
        return st._replace(
            iter_pos=st.iter_pos + st.ev_head.astype(jnp.int32)
        ), metrics

    findings = ni.check_entry("doctored-flight", doctored, args)
    assert any(
        f.rule == "obs-interference"
        and "SimState.ev_head" in f.message
        and "SimState.iter_pos" in f.message
        for f in findings
    ), "\n" + render_text(findings)


def test_exchange_telemetry_leak_is_caught():
    """Round-17 mesh observatory: an exchange counter steering the tick
    clock must fail — the exact leak class the bitwise telemetry-on/off
    A/B gate (tests/parallel/test_exchange_telemetry.py) samples
    dynamically."""
    fn, args = BY_NAME["engine-scalable-tick-exchange-metrics"].build()

    def doctored(state, inputs):
        st, metrics = fn(state, inputs)
        return st._replace(
            tick_index=st.tick_index + st.exch[0, 0].astype(jnp.int32)
        ), metrics

    findings = ni.check_entry("doctored-exch", doctored, args)
    assert any(
        f.rule == "obs-interference"
        and "ScalableState.exch" in f.message
        and "ScalableState.tick_index" in f.message
        for f in findings
    ), "\n" + render_text(findings)


def test_obs_to_obs_and_obs_to_metrics_flows_are_allowed():
    """Obs planes legitimately read themselves (append offsets) — only
    trajectory outputs are protected; metrics are observability sinks."""
    fn, args = BY_NAME["engine-tick-scan-histograms"].build()

    def doctored(state, inputs):
        st, metrics = fn(state, inputs)
        # obs -> obs: fine
        st = st._replace(hist=st.hist + jnp.uint32(1))
        # obs -> metrics: fine (metrics are obs sinks by classification)
        metrics = metrics._replace(
            dirty_rows=metrics.dirty_rows
            + st.hist[0, 0].astype(jnp.int32)
        )
        return st, metrics

    findings = ni.check_entry("doctored-ok", doctored, args)
    assert findings == [], "\n" + render_text(findings)


def test_unclassified_state_field_is_a_finding():
    regs = ni.state_registries()
    traj, obs = regs["SimState"]
    doctored = dict(regs)
    doctored["SimState"] = (traj - {"checksum"}, obs)

    from ringpop_tpu.models.sim import engine

    params = engine.SimParams(n=4, hash_impl="scan")
    params = engine.resolve_auto_parity(params, jax.default_backend())
    state = engine.init_state(
        params, seed=0, universe=ja._toy_universe(4)
    )
    labels = ni._flatten_labels(
        ni.label_tree((state,), doctored, "args")
    )
    assert any(lab.kind == ni.KIND_UNCLASSIFIED for lab in labels)
    # and through the public checker (monkeypatch-free: a local registry
    # copy exercised via label_tree is the same code path check_entry
    # walks; the finding text points at the fix)
    import unittest.mock as mock

    with mock.patch.object(ni, "state_registries", lambda: doctored):
        findings = ni.check_noninterference(
            ["engine-tick-scan-histograms"]
        )
    assert any(
        f.rule == "unclassified-state-field"
        and "SimState.checksum" in f.message
        and "SIM_TRAJECTORY_FIELDS" in f.message
        for f in findings
    ), "\n" + render_text(findings)


# -- changed-only mapping ---------------------------------------------------


def test_entries_for_changed_maps_modules_to_entries():
    assert ni.entries_for_changed(["models/route/plane.py"]) == list(
        ni.OBS_ENTRY_NAMES
    )  # a state-registry module re-proves everything
    assert ni.entries_for_changed(["models/sim/flight.py"]) == [
        "engine-tick-scan-flight-recorder",
        "fuzz-scenario-scan-full",
    ]
    assert ni.entries_for_changed(["fuzz/executor.py"]) == [
        "fuzz-scenario-scan-full"
    ]
    assert ni.entries_for_changed(["obs/recorder.py"]) == []
    # any analysis/ change re-proves everything
    assert ni.entries_for_changed(["analysis/dataflow.py"]) == list(
        ni.OBS_ENTRY_NAMES
    )
