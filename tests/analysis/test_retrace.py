"""Retrace-budget prong: the committed manifest matches reality, and
drift in either direction is a finding."""

import copy
import json
from pathlib import Path

import pytest

from ringpop_tpu.analysis import retrace

REPO_ROOT = Path(__file__).resolve().parents[2]

# probes cheap enough for tier-1 (the engine-tick probes compile the full
# tick twice — ~25 s on a contended CPU — and run under the slow marker /
# scripts/check_retrace_budget.py instead; the 870 s tier-1 cap is real)
CHEAP_PROBES = (
    "farmhash-scan",
    "fused-checksum-xla",
    "ring-device-lookup",
    "exchange-xla",  # [8,4] op jit — seconds, not an engine-tick compile
    # the shard_map'd exchange plane at [8,4] on a 1-device mesh —
    # small collective graphs, cheap (round 14)
    "exchange-plane",
    "route-tick",  # n=8 routing tick — small searchsorted graphs, cheap
    # n=8 B=2/4 scalable fuzz scan — the shrinker's cache discipline;
    # ~11 s cold, warm via the persistent XLA cache
    "fuzz-scenario-scan",
)


def test_manifest_is_committed_and_well_formed():
    doc = retrace.load_manifest(REPO_ROOT / retrace.MANIFEST_NAME)
    assert doc["version"] == 1
    probes = doc["probes"]
    assert set(probes) == {p.name for p in retrace.DEFAULT_PROBES}
    for steps in probes.values():
        # canonical probe shape: compile, cache hit, budgeted recompile
        assert [s["cache_size"] for s in steps] == [1, 1, 2]


def test_cheap_probes_match_committed_manifest():
    # the tier-1 acceptance gate: live compile counts == ANALYSIS_BUDGET.json
    # for the kernel-level probes
    manifest = retrace.load_manifest(REPO_ROOT / retrace.MANIFEST_NAME)
    probes = [p for p in retrace.DEFAULT_PROBES if p.name in CHEAP_PROBES]
    assert len(probes) == len(CHEAP_PROBES)
    actual = retrace.run_probes(probes)
    subset = {
        "probes": {k: manifest["probes"][k] for k in CHEAP_PROBES}
    }
    findings = retrace.compare_to_manifest(actual, subset)
    assert findings == [], [f.message for f in findings]


@pytest.mark.slow
def test_all_probes_match_committed_manifest():
    # full manifest diff including both engine-tick probes (what
    # scripts/check_retrace_budget.py runs on the chip session)
    findings = retrace.check_against_manifest(
        path=REPO_ROOT / retrace.MANIFEST_NAME
    )
    assert findings == [], [f.message for f in findings]


def test_drift_detection_both_directions():
    manifest = retrace.load_manifest(REPO_ROOT / retrace.MANIFEST_NAME)
    actual = copy.deepcopy(manifest["probes"])

    # silent retrace: probe compiled more than budgeted
    bumped = copy.deepcopy(actual)
    bumped["farmhash-scan"][1]["cache_size"] = 2
    findings = retrace.compare_to_manifest(bumped, manifest)
    assert any("silent retrace" in f.message for f in findings)

    # stale manifest: fewer compiles than committed
    dropped = copy.deepcopy(actual)
    dropped["farmhash-scan"][2]["cache_size"] = 1
    findings = retrace.compare_to_manifest(dropped, manifest)
    assert any("stale manifest" in f.message for f in findings)

    # probe set drift both ways
    missing = {k: v for k, v in actual.items() if k != "engine-tick"}
    findings = retrace.compare_to_manifest(missing, manifest)
    assert any("not run" in f.message for f in findings)
    extra = copy.deepcopy(actual)
    extra["brand-new-probe"] = [{"desc": "x", "cache_size": 1}]
    findings = retrace.compare_to_manifest(extra, manifest)
    assert any("no manifest entry" in f.message for f in findings)


def test_probe_baseline_immune_to_suite_order_pollution():
    # pjit caches key on the UNDERLYING callable: jitting the lru-shared
    # exchange-plane fixture at an off-budget shape (what any earlier
    # test in a full-suite run can do) used to pre-load the probe's
    # wrapper with a foreign cache entry and shift every step count up
    # — the round-12 test_all_probes_match_committed_manifest flake.
    # run_probe now clears the jit caches per probe, so the canonical
    # [1, 1, 2] sequence must survive deliberate pollution.
    import jax

    from ringpop_tpu.analysis import jaxpr_audit as ja

    plane = ja._plane_fixture()
    polluter = jax.jit(plane)
    polluter(*ja._plane_args(8, 16, 5))  # off-budget [8,16] mask shape
    assert polluter._cache_size() >= 1
    probe = next(
        p for p in retrace.DEFAULT_PROBES if p.name == "exchange-plane"
    )
    steps = retrace.run_probe(probe)
    assert [s["cache_size"] for s in steps] == [1, 1, 2]


def test_broken_probe_is_a_finding_not_a_crash(tmp_path):
    def boom():
        raise RuntimeError("entry point renamed")

    probes = [retrace.Probe("broken", boom)]
    actual = retrace.run_probes(probes)
    assert "error" in actual["broken"][0]
    findings = retrace.compare_to_manifest(
        actual, {"probes": {"broken": [{"desc": "a", "cache_size": 1}]}}
    )
    assert any(f.rule == "probe-failure" for f in findings)
    # same for a NEW probe with no manifest entry yet: surface the error,
    # not the (dead-end) regenerate-with---write advice
    findings = retrace.compare_to_manifest(actual, {"probes": {}})
    assert any(
        f.rule == "probe-failure" and "entry point renamed" in f.message
        for f in findings
    )
    # --write must refuse to commit a manifest with failed probes
    with pytest.raises(ValueError, match="failed probes"):
        retrace.write_manifest(actual, tmp_path / "m.json")


def test_missing_manifest_is_a_finding(tmp_path):
    findings = retrace.check_against_manifest(
        probes=[], path=tmp_path / "nope.json"
    )
    assert len(findings) == 1
    assert "manifest missing" in findings[0].message


def test_write_manifest_roundtrip(tmp_path):
    actual = {"p": [{"desc": "a", "cache_size": 1}]}
    out = retrace.write_manifest(actual, tmp_path / "b.json")
    doc = json.loads(out.read_text())
    assert doc["probes"] == actual
    assert retrace.compare_to_manifest(actual, doc) == []
