"""CI gate: the repo itself is jaxgate-clean.

Both prongs run over the live tree — the AST lint across ``ringpop_tpu/``
and the jaxpr audit of every registered entry point (toy n=8 shapes,
tracing only).  Any unsuppressed finding fails tier-1, so a stray host
callback in the scanned tick or an implicit dtype in the hash dataflow is
caught in the PR that introduces it, not on the next chip session.
"""

from pathlib import Path

from ringpop_tpu.analysis import astlint, jaxpr_audit
from ringpop_tpu.analysis.findings import render_text

PKG_ROOT = Path(astlint.__file__).resolve().parents[1]


def test_ast_prong_repo_clean():
    findings = astlint.lint_paths(PKG_ROOT)
    assert findings == [], "\n" + render_text(findings)


def test_jaxpr_prong_entry_points_clean():
    findings = jaxpr_audit.audit_entries()
    assert findings == [], "\n" + render_text(findings)


def test_jaxpr_prong_covers_required_entry_points():
    names = {ep.name for ep in jaxpr_audit.DEFAULT_ENTRIES}
    # ISSUE 3 acceptance: both sim engines, fused checksum, the
    # Pallas/XLA twins, and the ring device lookup
    assert {
        "engine-tick-scan",
        "engine-scalable-tick",
        "fused-checksum-xla",
        "fused-checksum-pallas",
        "farmhash-scan",
        "farmhash-pallas-nogrid",
        "ring-device-lookup",
        # ISSUE 4 acceptance: the flight-recorder-enabled scanned tick
        # and the wavefront-enabled scalable tick stay callback-free
        "engine-tick-scan-flight-recorder",
        "engine-scalable-tick-wavefront",
        # ISSUE 5 acceptance: the sortless+fused-exchange scalable tick
        # and both lowerings of the exchange megakernel hold the same
        # purity / uint32 gates
        "engine-scalable-tick-fused",
        "exchange-xla",
        "exchange-pallas",
        # ISSUE 6 acceptance: the routing plane's tick (both ring impls)
        # and the incremental ring-maintenance kernel are traced entries
        "route-tick-incremental",
        "route-tick-full",
        "route-ring-incremental",
        # ISSUE 7 acceptance: both engines' vmapped fuzz-scenario scans
        # (per-instance schedules) hold the same purity / uint32 gates
        "fuzz-scenario-scan-full",
        "fuzz-scenario-scan-scalable",
        # ISSUE 10 acceptance: the shard_map'd exchange plane and the
        # sharded storm tick built on it — the repo's first explicitly
        # collective programs hold the same purity / uint32 gates
        "exchange-plane",
        "engine-scalable-tick-shardmap",
        # ISSUE 11 acceptance: the latency-histogram-enabled ticks (both
        # engines + the routing plane) stay callback-free — the whole
        # point of device-side histograms is percentile telemetry
        # without host round-trips in the scan
        "engine-tick-scan-histograms",
        "engine-scalable-tick-histograms",
        "route-tick-histograms",
        # ISSUE 14 acceptance: the fused full-fidelity tick and both
        # lowerings of the two new toolkit ops hold the same purity /
        # dtype gates as the classic shapes
        "engine-tick-scan-fused",
        "fused-apply-xla",
        "fused-apply-pallas",
        "fused-piggyback-xla",
        "fused-piggyback-pallas",
    } <= names
    assert len(names) >= 5


def test_changed_only_mode_lints_the_diff_subset(monkeypatch):
    # --changed-only lints exactly the files git names — pin the "diff"
    # to known-clean package files so a developer's unrelated WIP edits
    # can't fail this gate
    from ringpop_tpu.analysis import __main__ as cli

    clean = [
        PKG_ROOT / "analysis" / "findings.py",
        PKG_ROOT / "analysis" / "retrace.py",
    ]
    monkeypatch.setattr(cli, "_changed_files", lambda: clean)
    assert cli.main(["--changed-only", "--prong", "ast"]) == 0
    # and an empty diff is a no-op exit 0 (the fast pre-commit path)
    monkeypatch.setattr(cli, "_changed_files", lambda: [])
    assert cli.main(["--changed-only", "--prong", "ast"]) == 0
