"""Unit coverage for the interval-range certifier (analysis/ranges.py).

Three layers: the interval algebra (pure lattice math must be sound —
a wrong bound here silently un-proves every entry point), the declared
scale-contract table, and the abstract interpreter over small synthetic
jaxprs with KNOWN ranges — including the ISSUE 18 satellite-4 edge
cases: negative strides, clamped gathers, and a never-stabilizing
while carry that must widen to top instead of looping forever.
"""

import jax
import jax.numpy as jnp
import pytest

from ringpop_tpu.analysis import ranges
from ringpop_tpu.analysis.ranges import Interval, point


def iv(lo, hi):
    return Interval(lo, hi)


class TestIntervalAlgebra:
    def test_union_and_top_absorbs(self):
        assert ranges.union(iv(0, 3), iv(2, 9)) == iv(0, 9)
        assert ranges.union(iv(0, 3), None) is None
        assert ranges.union(iv(None, 3), iv(0, 9)) == iv(None, 9)

    def test_widen_keeps_stable_bounds(self):
        # hi grew -> jumps to the next landmark; lo stayed -> kept exact
        w = ranges.widen(iv(0, 5), iv(0, 6))
        assert w.lo == 0
        assert w.hi == (1 << 8) - 1

    def test_widen_walks_the_landmark_ladder_to_top(self):
        cur = iv(0, 0)
        seen = []
        for _ in range(20):
            nxt = ranges.widen(cur, ranges.iv_add(cur, point(1)))
            if nxt == cur:
                break
            cur = nxt
            seen.append(cur.hi)
        # strictly increasing landmark hops, fixpoint at top
        assert seen[-1] is None
        assert len(seen) <= len(ranges._HI_LANDMARKS)
        assert ranges.widen(cur, ranges.iv_add(cur, point(1))) == cur

    def test_widen_lo_jumps_to_sentinel_then_negative_landmarks(self):
        w = ranges.widen(iv(0, 4), iv(-1, 4))
        assert w.lo == ranges.SENTINEL_LO  # the -1/-2 stamp sentinels
        w2 = ranges.widen(w, iv(-5, 4))
        assert w2.lo == -ranges.TICK_CEILING

    def test_mul_sign_cases(self):
        assert ranges.iv_mul(iv(-2, 3), iv(-4, 5)) == iv(-12, 15)
        assert ranges.iv_mul(iv(2, 3), iv(4, 5)) == iv(8, 15)
        # nonneg semi-infinite keeps the finite lower bound
        assert ranges.iv_mul(iv(2, None), iv(3, 4)) == iv(6, None)
        # mixed-sign semi-infinite degrades to full
        assert ranges.iv_mul(iv(-2, None), iv(3, 4)) == ranges.FULL

    def test_div_requires_nonzero_finite_divisor(self):
        assert ranges.iv_div(iv(4, 9), point(2)) == iv(2, 4)
        assert ranges.iv_div(iv(-9, 9), iv(2, 3)) == iv(-4, 4)
        assert ranges.iv_div(iv(4, 9), iv(-1, 1)) is None
        assert ranges.iv_div(iv(4, 9), iv(1, None)) is None

    def test_rem_precise_when_dividend_fits_below_modulus(self):
        assert ranges.iv_rem(iv(3, 6), point(100)) == iv(3, 6)
        assert ranges.iv_rem(iv(0, 500), point(100)) == iv(0, 99)
        # C-style: negative dividends pull the bound negative
        assert ranges.iv_rem(iv(-500, 500), point(100)) == iv(-99, 99)

    def test_bitwise_bounds(self):
        assert ranges.iv_and(iv(0, 200), iv(0, 15)) == iv(0, 15)
        assert ranges.iv_and(iv(-1, 5), iv(0, 5)) is None
        assert ranges.iv_orxor(iv(0, 5), iv(0, 9)) == iv(0, 15)
        assert ranges.iv_shl(iv(1, 3), point(4)) == iv(16, 48)
        assert ranges.iv_shr(iv(16, 64), point(4)) == iv(1, 4)
        # logical shift of a possibly-negative value reinterprets bits
        assert ranges.iv_shr(iv(-1, 64), point(4)) is None

    def test_dtype_interval_anchors(self):
        assert ranges.dtype_interval(jnp.int32) == iv(-(1 << 31), (1 << 31) - 1)
        assert ranges.dtype_interval(jnp.uint32) == iv(0, (1 << 32) - 1)
        assert ranges.dtype_interval(jnp.bool_) == ranges.BOOL
        assert ranges.dtype_interval(jnp.float32) is None


class TestScaleSpecs:
    def test_entry_patterns_resolve(self):
        assert ranges.entry_scale("engine-tick-scan").n_max == ranges.FULL_N_MAX
        assert (
            ranges.entry_scale("engine-scalable-tick").dim_map
            == ranges._SCALABLE_DIMS
        )
        assert ranges.entry_scale("ring-device-lookup").coeffs == (1, 100)
        assert ranges.entry_scale("route-tick-xla").n_max == ranges.ROUTE_N_MAX
        assert ranges.entry_scale("something-new").n_max == ranges.N_MAX_PODS

    def test_dim_rule_three_way(self):
        spec = ranges.ScaleSpec(
            toy_n=8, n_max=1000, coeffs=(1, 100), dim_map=((128, 512),)
        )
        assert ranges._dim_rule(128, spec) == ("pinned", 512)
        assert ranges._dim_rule(8, spec) == ("scaled", 1)
        assert ranges._dim_rule(800, spec) == ("scaled", 100)
        assert ranges._dim_rule(7, spec) == ("const", 7)
        # dim_map wins over the coefficient rule when both match
        pin8 = ranges.ScaleSpec(toy_n=8, n_max=1000, dim_map=((8, 99),))
        assert ranges._dim_rule(8, pin8) == ("pinned", 99)

    def test_scaled_dim_extents(self):
        spec = ranges.ScaleSpec(
            toy_n=8, n_max=1000, coeffs=(1, 100), dim_map=((128, 512),)
        )
        assert ranges.scaled_dim(8, spec) == 1000
        assert ranges.scaled_dim(800, spec) == 100 * 1000
        assert ranges.scaled_dim(128, spec) == 512
        assert ranges.scaled_dim(7, spec) == 7


def _events(fn, args, spec=None, invar_names=None):
    closed = jax.make_jaxpr(fn)(*args)
    return ranges.analyze_jaxpr(closed, spec=spec, invar_names=invar_names)


class TestAnalyzeJaxpr:
    def test_clean_program_has_no_events(self):
        def fn(a):  # uint32 hash-style mixing: wrap is the contract
            return (a * jnp.uint32(0x9E3779B9)) ^ (a >> 13)

        assert _events(fn, (jnp.zeros(8, jnp.uint32),)) == []

    def test_int32_product_escape_is_one_event_not_a_flood(self):
        # a*a busts int32 from in-range tick-contract inputs; the +1 and
        # *2 downstream must NOT re-report (the escape already widened
        # the inputs, _inputs_tame routes the report upstream)
        def fn(a):
            big = a * a
            return big + 1, big * 2

        evs = _events(fn, (jnp.zeros(8, jnp.int32),))
        assert [e.rule for e in evs] == ["dtype-overflow"]
        assert evs[0].key == "mul.out0"
        assert "escapes int32" in evs[0].detail

    def test_reduce_sum_repriced_at_declared_scale(self):
        # exact at the toy [8, 8] trace; re-check at N=64Mi^2 wraps int32
        def fn(m):
            return jnp.sum(m, dtype=jnp.int32)

        evs = _events(fn, (jnp.ones((8, 8), jnp.int32),))
        assert [e.rule for e in evs] == ["dtype-overflow"]
        assert evs[0].key.startswith("reduce_sum.scaled.")
        # the same sum under a toy-sized contract is fine
        tiny = ranges.ScaleSpec(toy_n=8, n_max=8)
        assert _events(fn, (jnp.ones((8, 8), jnp.int32),), spec=tiny) == []

    def test_scan_counter_carry_is_named_via_invar_names(self):
        def fn(c0, xs):
            def body(c, x):
                return c + 1, c

            return jax.lax.scan(body, c0, xs)

        evs = _events(
            fn,
            (jnp.int32(0), jnp.zeros(4, jnp.int32)),
            invar_names=["SimStateX.ticker", None],
        )
        carries = [e for e in evs if e.rule == "unbounded-carry"]
        assert [e.key for e in carries] == ["SimStateX.ticker"]
        assert "widens" in carries[0].detail

    def test_bounded_carry_stays_quiet(self):
        # the carry is clamped every iteration: the fixpoint must settle
        # inside int32 and emit nothing
        def fn(c0, xs):
            def body(c, x):
                return jnp.minimum(c + 1, jnp.int32(100)), c

            return jax.lax.scan(body, c0, xs)

        evs = _events(fn, (jnp.int32(0), jnp.zeros(4, jnp.int32)))
        assert evs == []

    def test_index_overflow_on_scaled_iota_extent(self):
        # ring geometry at the POD axis: toy 800 = 100*8 scales to
        # 100*64Mi > int32 (at the declared 16Mi route contract the
        # same lane fits — that asymmetry IS the certified ceiling)
        def fn():
            return jnp.arange(800, dtype=jnp.int32)

        spec = ranges.ScaleSpec(
            toy_n=8, n_max=ranges.N_MAX_PODS, coeffs=(1, 100)
        )
        evs = _events(fn, (), spec=spec)
        assert [(e.rule, e.key) for e in evs] == [("index-overflow", "iota.0")]
        # the certified route contract (16Mi*100 points) fits int32
        route = ranges.ScaleSpec(
            toy_n=8, n_max=ranges.ROUTE_N_MAX, coeffs=(1, 100)
        )
        assert _events(fn, (), spec=route) == []
        # int64 lanes hold the pod-axis extent fine
        def fn64():
            return jnp.arange(800, dtype=jnp.int64)

        assert _events(fn64, (), spec=spec) == []


class TestSatellite4EdgeCases:
    def test_negative_stride_slice_preserves_the_interval(self):
        # x[::-1] lowers through rev; x[::-2] through strided slice —
        # both are permutations/selections, neither may invent range
        def fn(a):
            r = a[::-1]
            s = a[::-2]
            return r[:4] + s

        assert _events(fn, (jnp.zeros(8, jnp.int32),)) == []

    def test_clamped_gather_still_flags_a_narrow_index_lane(self):
        # mode="clip" fixes out-of-bounds BEHAVIOR, not the index dtype:
        # an int32 lane cannot even NAME the rows past 2^31 at the
        # declared 100*16Mi ring extent, so the certifier still fires
        def fn(table, idx):
            return jnp.take(table, idx, mode="clip")

        spec = ranges.ScaleSpec(
            toy_n=8, n_max=ranges.N_MAX_PODS, coeffs=(1, 100)
        )
        evs = _events(
            fn,
            (jnp.zeros(800, jnp.uint32), jnp.zeros(3, jnp.int32)),
            spec=spec,
        )
        assert ("index-overflow", "gather.dim0") in [
            (e.rule, e.key) for e in evs
        ]

    def test_never_stabilizing_while_widens_to_top_and_terminates(self):
        # c doubles every iteration under a traced bound: no finite
        # fixpoint exists, so widening MUST hit top in bounded rounds
        # (this test hanging = the landmark ladder is broken)
        def fn(n, c0):
            def cond(c):
                return c < n

            def body(c):
                return c * 2 + 1

            return jax.lax.while_loop(cond, body, c0)

        evs = _events(fn, (jnp.int64(10), jnp.int64(1)))
        carries = [e for e in evs if e.rule == "unbounded-carry"]
        assert len(carries) == 1
        assert "int64" in carries[0].detail

    def test_zero_iteration_while_keeps_the_init_range(self):
        # body would overflow, but the certifier must still include the
        # zero-iteration identity (init passes through untouched)
        def fn(n, c0):
            def body(c):
                return c * c

            return jax.lax.while_loop(lambda c: c < n, body, c0)

        evs = _events(fn, (jnp.int32(0), jnp.int32(2)))
        # the in-body escape is real and reported; what matters here is
        # analysis soundness, not silence
        assert all(
            e.rule in ("unbounded-carry", "dtype-overflow") for e in evs
        )


class TestFootprintPolynomial:
    def test_poly_prices_scaled_and_pinned_dims(self):
        def fn(plane, tile):
            return plane.sum(dtype=jnp.int32) + tile.sum(dtype=jnp.int32)

        spec = ranges.ScaleSpec(toy_n=8, n_max=1000, dim_map=((128, 512),))
        closed = jax.make_jaxpr(fn)(
            jnp.ones((8, 8), jnp.int32), jnp.ones((8, 128), jnp.int32)
        )
        poly = ranges.buffer_poly(closed, spec)
        # [8,8] -> degree 2; [8,128] -> degree 1 with the 512 envelope
        assert poly[2] >= 4
        assert poly[1] >= 4 * 512

    def test_poly_bytes_and_feasible_n(self):
        assert ranges.poly_bytes({0: 7, 1: 4, 2: 2}, 10) == 7 + 40 + 200
        assert ranges.feasible_n({1: 4}, 400, 10**6) == 100
        # constant term alone busts the budget -> infeasible everywhere
        assert ranges.feasible_n({0: 500}, 400, 10**6) == 0
        # cheap programs are ceiling-bound at the declared n_max
        assert ranges.feasible_n({1: 1}, 1 << 60, 4096) == 4096

    def test_feasible_n_is_monotone_in_the_budget(self):
        poly = {0: 1024, 1: 100, 2: 3}
        prev = 0
        for budget in (10**4, 10**6, 10**8, 10**10):
            cur = ranges.feasible_n(poly, budget, 1 << 40)
            assert cur >= prev
            prev = cur
        assert ranges.poly_bytes(poly, prev) <= 10**10
        assert ranges.poly_bytes(poly, prev + 1) > 10**10
