"""The scale prong: manifest round-trip, drift detection, and the
ISSUE 18 oversized-buffer mutation proof.

The committed SCALE_BUDGET.json is kept honest cheaply here (name-set
pin + one re-analyzed entry); the full diff runs in CI via
scripts/check_scale_budget.py (and scripts/check_all_budgets.py).
"""

import json

import jax.numpy as jnp
import pytest

from ringpop_tpu.analysis import ranges, scale_budget
from ringpop_tpu.analysis.jaxpr_audit import DEFAULT_ENTRIES


def _clean_entry():
    def fn(stamps):  # O(N): one [8] plane in, one out
        return stamps + 1

    return fn, (jnp.zeros(8, jnp.int32),)


def _doctored_entry():
    def fn(stamps):  # seeded [N, N] int64 plane: the footprint mutation
        plane = jnp.zeros((8, 8), jnp.int64) + stamps[:, None]
        return stamps + 1, plane

    return fn, (jnp.zeros(8, jnp.int32),)


class TestEntryBudget:
    def test_clean_entry_is_ceiling_bound(self):
        fn, args = _clean_entry()
        b = scale_budget.entry_budget("clean", fn, args)
        assert b["degree"] == 1
        assert b["n_star"] == b["n_max"] == ranges.N_MAX_PODS
        assert b["ceiling_bound"] is True

    def test_oversized_buffer_collapses_n_star(self):
        fn, args = _doctored_entry()
        bad = scale_budget.entry_budget("doctored", fn, args)
        clean = scale_budget.entry_budget("clean", *_clean_entry())
        assert bad["degree"] == 2
        assert not bad["ceiling_bound"]
        assert bad["n_star"] < clean["n_star"] // 100
        # N* is the BINDING search point of the priced polynomial
        poly = {int(e): c for e, c in bad["poly_bytes"].items()}
        n = bad["n_star"]
        assert ranges.poly_bytes(poly, n) <= scale_budget.HBM_BUDGET_BYTES
        assert ranges.poly_bytes(poly, n + 1) > scale_budget.HBM_BUDGET_BYTES

    def test_broken_entry_reports_error(self):
        def boom(_):
            raise RuntimeError("nope")

        b = scale_budget.entry_budget("broken", boom, (jnp.zeros(2),))
        assert "nope" in b["error"]


class TestManifestGate:
    def _manifest(self, entries):
        return {
            "version": 1,
            "hbm_budget_bytes": scale_budget.HBM_BUDGET_BYTES,
            "entries": entries,
        }

    def test_round_trip_is_clean(self, tmp_path):
        fn, args = _clean_entry()
        actual = {"clean": scale_budget.entry_budget("clean", fn, args)}
        path = tmp_path / "SCALE_BUDGET.json"
        scale_budget.write_manifest(actual, path)
        again = {"clean": scale_budget.entry_budget("clean", fn, args)}
        assert (
            scale_budget.compare_to_manifest(
                again, json.loads(path.read_text())
            )
            == []
        )

    def test_mutation_fails_the_gate(self):
        # the committed manifest blessed the clean shape; the doctored
        # refactor must fail BOTH ways: degree bump and N* collapse
        clean = scale_budget.entry_budget("e", *_clean_entry())
        bad = scale_budget.entry_budget("e", *_doctored_entry())
        findings = scale_budget.compare_to_manifest(
            {"e": bad}, self._manifest({"e": clean})
        )
        msgs = "\n".join(f.message for f in findings)
        assert any(f.rule == "scale-budget" for f in findings)
        assert "degree changed" in msgs
        assert "N* shrank" in msgs

    def test_growth_past_rtol_is_a_stale_manifest(self):
        clean = scale_budget.entry_budget("e", *_clean_entry())
        stale = dict(clean, n_star=clean["n_star"] // 2)
        findings = scale_budget.compare_to_manifest(
            {"e": clean}, self._manifest({"e": stale})
        )
        assert any("bank the win" in f.message for f in findings)

    def test_small_drift_within_rtol_passes(self):
        clean = scale_budget.entry_budget("e", *_clean_entry())
        near = dict(clean, n_star=int(clean["n_star"] * 0.99))
        assert (
            scale_budget.compare_to_manifest(
                {"e": clean}, self._manifest({"e": near})
            )
            == []
        )

    def test_one_sided_entries_are_findings(self):
        clean = scale_budget.entry_budget("e", *_clean_entry())
        only_manifest = scale_budget.compare_to_manifest(
            {}, self._manifest({"e": clean})
        )
        assert any("not analyzed" in f.message for f in only_manifest)
        only_actual = scale_budget.compare_to_manifest(
            {"e": clean}, self._manifest({})
        )
        assert any("no manifest entry" in f.message for f in only_actual)

    def test_write_refuses_broken_entries(self, tmp_path):
        with pytest.raises(ValueError, match="refusing"):
            scale_budget.write_manifest(
                {"x": {"error": "boom"}}, tmp_path / "S.json"
            )

    def test_missing_manifest_is_a_finding(self, tmp_path):
        findings = scale_budget.check_against_manifest(
            entry_names=[], path=tmp_path / "absent.json"
        )
        assert [f.rule for f in findings] == ["scale-budget"]
        assert "manifest missing" in findings[0].message


class TestCommittedManifest:
    def test_covers_exactly_the_registry(self):
        doc = scale_budget.load_manifest()
        assert set(doc["entries"]) == {ep.name for ep in DEFAULT_ENTRIES}
        for name, entry in doc["entries"].items():
            assert "error" not in entry, name
            assert entry["n_star"] >= 1, name

    def test_one_entry_still_matches_the_committed_ceiling(self):
        findings = scale_budget.check_against_manifest(
            entry_names=["ring-device-lookup"]
        )
        assert findings == []
