"""Static cost manifest gate (analysis/cost.py + COST_BUDGET.json).

Tier-1 wiring: a cheap-probe subset of the auditable entry points is
compiled and diffed against the committed manifest every run (the full
set belongs to scripts/check_cost_budget.py).  The mutation tests prove
the gate FIRES: a deliberately cost-blown twin of an entry drifts the
manifest and the script exits non-zero."""

import importlib.util as ilu
import json
import os
from pathlib import Path

import jax
import pytest

from ringpop_tpu.analysis import cost

# cheap compiles (seconds total, warm under the persistent XLA cache) —
# the tier-1 slice of the manifest; the full diff is the script's job
CHEAP_COST_ENTRIES = (
    "exchange-xla",
    "ring-device-lookup",
    "fused-checksum-xla",
    "route-tick-incremental",
)


def _script():
    spec = ilu.spec_from_file_location(
        "check_cost_budget",
        os.path.join(
            os.path.dirname(__file__), "..", "..", "scripts",
            "check_cost_budget.py",
        ),
    )
    mod = ilu.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_cheap_probe_subset_matches_committed_manifest():
    findings = cost.check_against_manifest(
        entry_names=CHEAP_COST_ENTRIES
    )
    from ringpop_tpu.analysis.findings import render_text

    assert findings == [], "\n" + render_text(findings)


def test_manifest_covers_observatory_entries():
    manifest = cost.load_manifest()
    entries = set(manifest["entries"])
    assert set(CHEAP_COST_ENTRIES) <= entries
    # the round-15 histogram-enabled ticks are budgeted too
    assert {
        "engine-tick-scan-histograms",
        "engine-scalable-tick-histograms",
        "route-tick-histograms",
    } <= entries
    for e in manifest["entries"].values():
        assert "error" not in e
        assert e["flops"] >= 0 and e["bytes_accessed"] > 0


def test_mutation_cost_blown_entry_drifts_manifest():
    """The gate fires on a real cost regression: a twin of exchange-xla
    that accidentally runs the op twice (the unbatched/recompute
    anti-pattern — 2x flops and bytes) must drift every cost metric far
    past the tolerance."""
    import jax.numpy as jnp

    from ringpop_tpu.analysis import jaxpr_audit as ja
    from ringpop_tpu.ops import exchange as exch

    def blown(heard, pulled, pushed, r_delta):
        nh, d, c = exch.exchange(heard, pulled, pushed, r_delta, impl="xla")
        nh2, d2, _ = exch.exchange(nh, pulled, pushed, r_delta, impl="xla")
        return nh2, d + d2, c

    args = ja._exchange_args()
    mutated = cost._extract(jax.jit(blown).lower(*args).compile())
    manifest = cost.load_manifest()
    sliced = dict(manifest)
    sliced["entries"] = {"exchange-xla": manifest["entries"]["exchange-xla"]}
    findings = cost.compare_to_manifest(
        {"exchange-xla": mutated}, sliced
    )
    assert findings, "cost-blown twin produced no drift findings"
    assert any("flops" in f.message for f in findings)
    assert all(f.rule == "cost-budget" for f in findings)


def test_mutation_widened_dtype_drifts_manifest():
    """A widened dtype on the farmhash row-hash path (uint8 bytes
    upcast to float32 before hashing-adjacent reductions) blows bytes
    accessed — the HBM-traffic regression class the manifest exists to
    catch."""
    import jax.numpy as jnp

    from ringpop_tpu.analysis import jaxpr_audit as ja
    from ringpop_tpu.ops import jax_farmhash as jfh

    mat, lens = ja._farmhash_args()

    def widened(mat, lens):
        out = jfh.hash32_rows(mat, lens, impl="scan")
        # the accidental fp32 materialization of the byte matrix
        return out, jnp.sum(mat.astype(jnp.float32) * 1.5, axis=1)

    mutated = cost._extract(jax.jit(widened).lower(mat, lens).compile())
    manifest = cost.load_manifest()
    exp = manifest["entries"]["farmhash-scan"]
    assert cost._drifted(
        mutated["bytes_accessed"], exp["bytes_accessed"], cost.DEFAULT_RTOL
    ) or cost._drifted(
        mutated["flops"], exp["flops"], cost.DEFAULT_RTOL
    ), (mutated, exp)


def test_script_exits_nonzero_on_doctored_manifest(tmp_path):
    """End-to-end proof the CI gate fires: perturb one committed entry
    (the O(N^2)-blowup signature: 3x flops + 3x bytes) and the script's
    diff mode exits non-zero; the pristine manifest exits zero."""
    mod = _script()
    pristine = tmp_path / "ok.json"
    doctored = tmp_path / "bad.json"
    manifest = cost.load_manifest()
    pristine.write_text(json.dumps(manifest))
    bad = json.loads(json.dumps(manifest))
    bad["entries"]["exchange-xla"]["flops"] *= 3
    bad["entries"]["exchange-xla"]["bytes_accessed"] *= 3
    doctored.write_text(json.dumps(bad))
    args = ["--entries", ",".join(CHEAP_COST_ENTRIES)]
    assert mod.main(args + ["--budget", str(pristine)]) == 0
    assert mod.main(args + ["--budget", str(doctored)]) == 1


def test_write_manifest_refuses_failed_entries(tmp_path):
    with pytest.raises(ValueError, match="refusing"):
        cost.write_manifest(
            {"good": {"flops": 1}, "broken": {"error": "boom"}},
            tmp_path / "m.json",
        )


def test_compare_flags_missing_and_extra_entries():
    manifest = {"entries": {"a": {"flops": 10}, "b": {"flops": 10}}}
    findings = cost.compare_to_manifest(
        {"a": {"flops": 10}, "c": {"flops": 5}}, manifest
    )
    msgs = "\n".join(f.message for f in findings)
    assert "not measured" in msgs  # b missing
    assert "no manifest entry" in msgs  # c extra


def test_compare_tolerance_and_direction():
    manifest = {"entries": {"a": {"flops": 1000}}}
    ok = cost.compare_to_manifest({"a": {"flops": 1050}}, manifest)
    assert ok == []  # 5% < rtol
    up = cost.compare_to_manifest({"a": {"flops": 1500}}, manifest)
    assert len(up) == 1 and "cost regression" in up[0].message
    down = cost.compare_to_manifest({"a": {"flops": 500}}, manifest)
    assert len(down) == 1 and "stale manifest" in down[0].message


def test_full_run_detects_stale_manifest_entry(tmp_path, monkeypatch):
    """An entry point removed from the registry while its manifest row
    survives must be a finding on a FULL run (no --entries subset) —
    the subset path legitimately slices, the full path must not."""
    manifest = {
        "backend": jax.default_backend(),
        "entries": {"a": {"flops": 1}, "ghost": {"flops": 2}},
    }
    p = tmp_path / "m.json"
    p.write_text(json.dumps(manifest))
    monkeypatch.setattr(cost, "_entry_names_for_backend", lambda b: ["a"])
    monkeypatch.setattr(
        cost, "collect_costs", lambda names=None: {"a": {"flops": 1}}
    )
    findings = cost.check_against_manifest(path=Path(p))
    assert any("not measured" in f.message for f in findings)
    # the explicit subset path still slices the manifest to scope
    assert cost.check_against_manifest(("a",), Path(p)) == []


def test_backend_mismatch_skips_cleanly(tmp_path):
    other = {
        "backend": "tpu" if jax.default_backend() != "tpu" else "cpu",
        "entries": {"exchange-xla": {"flops": 1}},
    }
    p = tmp_path / "other.json"
    p.write_text(json.dumps(other))
    assert cost.check_against_manifest(("exchange-xla",), Path(p)) == []


def test_missing_manifest_is_a_finding(tmp_path):
    findings = cost.check_against_manifest(
        ("exchange-xla",), tmp_path / "nope.json"
    )
    assert len(findings) == 1 and "missing" in findings[0].message
