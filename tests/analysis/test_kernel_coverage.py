"""Kernel-coverage prong (analysis/kernel_coverage.py): the live tree
is clean, and — mutation-proven — the rule FIRES on an unregistered
Pallas kernel, a registry row whose entries/test are missing, and a
stale row whose kernel was removed."""

from __future__ import annotations

from pathlib import Path

from ringpop_tpu.analysis import kernel_coverage as kc
from ringpop_tpu.analysis.findings import render_text
from ringpop_tpu.ops import toolkit


def _rules(findings):
    return {f.rule for f in findings}


def test_live_tree_is_clean():
    findings = kc.check_kernel_coverage()
    assert findings == [], "\n" + render_text(findings)


def test_every_new_fused_op_is_registered():
    """The round-16 ops must be in the registry (required-coverage
    style, like the jaxpr entry-point gate)."""
    rows = {(t.module, t.kernel_entry) for t in toolkit.TWIN_REGISTRY}
    assert ("fused_apply", "apply_updates") in rows
    assert ("fused_piggyback", "pb_budget") in rows
    assert ("exchange", "exchange") in rows
    assert ("pallas_farmhash", "fused_stream_nogrid") in rows


def _fake_ops(tmp_path: Path, body: str) -> Path:
    ops = tmp_path / "ops"
    ops.mkdir()
    (ops / "__init__.py").write_text("")
    (ops / "mykernel.py").write_text(body)
    return ops


KERNEL_BODY = """
from jax.experimental import pallas as pl

def my_entry(x):
    return pl.pallas_call(lambda i, o: None, out_shape=x)(x)

def my_twin(x):
    return x
"""


def test_mutation_unregistered_kernel_fires():
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        ops = _fake_ops(Path(td), KERNEL_BODY)
        findings = kc.check_kernel_coverage(
            ops_root=ops, registry=(), repo_root=Path(td)
        )
        assert _rules(findings) == {"unregistered-kernel"}, findings


def test_mutation_scaffold_call_counts_as_kernel():
    """A kernel built on the toolkit scaffold (no direct pallas_call)
    is still in scope — stream_row_tiles call sites are detected."""
    import tempfile

    body = """
from ringpop_tpu.ops import toolkit

def my_entry(x):
    return toolkit.stream_row_tiles(None, [x], ["plane"], [x.dtype], n_cols=4)
"""
    with tempfile.TemporaryDirectory() as td:
        ops = _fake_ops(Path(td), body)
        findings = kc.check_kernel_coverage(
            ops_root=ops, registry=(), repo_root=Path(td)
        )
        assert _rules(findings) == {"unregistered-kernel"}, findings


def test_mutation_missing_entries_and_test_fire():
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        ops = _fake_ops(Path(td), KERNEL_BODY)
        reg = (
            toolkit.KernelTwin(
                "mykernel", "no_such_entry", "no_such_twin",
                "tests/no_such_test.py",
            ),
        )
        findings = kc.check_kernel_coverage(
            ops_root=ops, registry=reg, repo_root=Path(td)
        )
        assert _rules(findings) == {
            "missing-kernel-entry",
            "missing-twin-entry",
            "missing-gate-test",
        }, findings


def test_mutation_gate_test_must_mention_entry():
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        td = Path(td)
        ops = _fake_ops(td, KERNEL_BODY)
        t = td / "tests"
        t.mkdir()
        (t / "test_mykernel.py").write_text("def test_other(): pass\n")
        reg = (
            toolkit.KernelTwin(
                "mykernel", "my_entry", "my_twin",
                "tests/test_mykernel.py",
            ),
        )
        findings = kc.check_kernel_coverage(
            ops_root=ops, registry=reg, repo_root=td
        )
        assert _rules(findings) == {"missing-gate-test"}, findings
        # mentioning the entry heals it
        (t / "test_mykernel.py").write_text(
            "def test_gate():\n    assert 'my_entry'\n"
        )
        findings = kc.check_kernel_coverage(
            ops_root=ops, registry=reg, repo_root=td
        )
        assert findings == [], findings


def test_mutation_stale_row_fires():
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        td = Path(td)
        ops = _fake_ops(td, "def my_entry(x):\n    return x\n")
        (td / "tests").mkdir()
        (td / "tests" / "t.py").write_text("my_entry\n")
        reg = (
            toolkit.KernelTwin(
                "mykernel", "my_entry", "my_entry", "tests/t.py"
            ),
        )
        findings = kc.check_kernel_coverage(
            ops_root=ops, registry=reg, repo_root=td
        )
        assert _rules(findings) == {"stale-registry-row"}, findings


def test_cli_prong_runs(capsys):
    from ringpop_tpu.analysis.__main__ import main

    assert main(["--prong", "kernels"]) == 0
    assert "0 finding(s)" in capsys.readouterr().out
