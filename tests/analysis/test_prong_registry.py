"""The prong registry is the single source (ISSUE 15 satellite): CLI
help, ``--prong all``, ``--list-rules`` and the README prong table all
derive from ``analysis/prongs.py`` and cannot drift."""

import json
import re
from pathlib import Path

from ringpop_tpu.analysis.prongs import ALL_PRONGS, DEFAULT_PRONGS, PRONGS

REPO_ROOT = Path(__file__).resolve().parents[2]


def test_registry_shape():
    assert set(DEFAULT_PRONGS) <= set(ALL_PRONGS)
    # cheap-by-default contract: the prongs that compile entry points
    # are opt-in
    assert set(ALL_PRONGS) - set(DEFAULT_PRONGS) == {
        "retrace",
        "cost",
        "donation",
    }
    for spec in PRONGS.values():
        assert spec.rules, spec.name
        assert spec.summary and spec.ci


def test_cli_dispatch_covers_every_registered_prong():
    """__main__ must have a runner arm for each registry entry — a prong
    declared but never dispatched would silently no-op."""
    src = (
        REPO_ROOT / "ringpop_tpu" / "analysis" / "__main__.py"
    ).read_text()
    for name in ALL_PRONGS:
        assert f'"{name}" in prongs' in src, (
            f"prong {name!r} is registered but __main__ never runs it"
        )


def test_list_rules_prints_every_prong_and_rule(capsys):
    from ringpop_tpu.analysis.__main__ import main

    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for spec in PRONGS.values():
        assert f"{spec.name} prong" in out
        for rule in spec.rules:
            assert rule in out


def test_every_registered_prong_name_is_accepted(monkeypatch, capsys):
    """Each registry name parses; scoped to an empty diff so the slow
    prongs do no real work (their scoping gates skip them)."""
    from ringpop_tpu.analysis import __main__ as cli

    monkeypatch.setattr(cli, "_changed_files", lambda: [])
    for name in ALL_PRONGS:
        if name in ("retrace", "cost"):
            continue  # no --changed-only gate (their scripts scope them)
        assert (
            cli.main(["--prong", name, "--changed-only"]) == 0
        ), name
        capsys.readouterr()


def test_readme_prong_table_matches_registry():
    """The README table rows carry each prong's name and its EXACT
    registry summary — edit analysis/prongs.py and README together."""
    readme = (REPO_ROOT / "README.md").read_text()
    rows = {
        m.group(1): m.group(2).strip()
        for m in re.finditer(
            r"^\| `([a-z]+)` \| (?:yes|opt-in) \| (.+) \|$",
            readme,
            re.M,
        )
    }
    assert set(rows) == set(ALL_PRONGS), (
        "README prong table rows != registry: "
        f"{sorted(set(rows) ^ set(ALL_PRONGS))}"
    )
    for name, spec in PRONGS.items():
        assert rows[name] == spec.summary, (
            f"README summary for {name!r} drifted from "
            "analysis/prongs.py — update them together"
        )
    # default/opt-in column tracks the registry too
    for name, spec in PRONGS.items():
        flag = "yes" if spec.default else "opt-in"
        assert f"| `{name}` | {flag} |" in readme


def test_json_output_records_per_prong_wall_time(capsys):
    from ringpop_tpu.analysis.__main__ import main

    rc = main(
        [
            "--prong",
            "ast",
            "--format",
            "json",
            "ringpop_tpu/analysis/findings.py",
        ]
    )
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert "prong_seconds" in doc
    assert set(doc["prong_seconds"]) == {"ast"}
    assert doc["prong_seconds"]["ast"] >= 0
