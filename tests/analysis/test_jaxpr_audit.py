"""Injection tests: the jaxpr auditor must catch the two failure classes
the parity claim is most exposed to — a host callback smuggled into the
scanned tick body, and a lost uint32 dtype on the hash dataflow."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ringpop_tpu.analysis import jaxpr_audit as ja


def test_pure_callback_in_scanned_tick_body_is_flagged():
    # take the REAL engine tick and inject one pure_callback into the
    # scanned body — the auditor must flag it and exit non-zero
    engine, params, universe, state = ja._sim_setup(8)
    n, t = 8, 2
    inputs = engine.TickInputs(
        kill=jnp.zeros((t, n), bool),
        revive=jnp.zeros((t, n), bool),
        join=jnp.zeros((t, n), bool),
        partition=jnp.full((t, n), -1, jnp.int32),
    )

    def body(st, inp):
        st, m = engine.tick(st, inp, params, universe)
        leaked = jax.pure_callback(
            lambda v: np.asarray(v),
            jax.ShapeDtypeStruct((), jnp.int32),
            m.pings_sent,
        )
        return st, m._replace(pings_sent=leaked)

    def scanned(state, inputs):
        return jax.lax.scan(body, state, inputs)

    findings = ja.audit_fn("injected-tick", scanned, (state, inputs))
    cb = [f for f in findings if f.rule == "callback-primitive"]
    assert cb, findings
    assert any("scanned/while body" in f.message for f in cb)
    # clean twin: the same scan without the callback audits clean
    def clean(state, inputs):
        return jax.lax.scan(
            lambda st, inp: engine.tick(st, inp, params, universe),
            state,
            inputs,
        )

    assert ja.audit_fn("clean-tick", clean, (state, inputs)) == []


def test_float_on_hash_path_is_flagged():
    # the canonical missing-dtype failure: an accumulator created without
    # an explicit dtype joins the farmhash dataflow as float32
    C1 = np.uint32(0xCC9E2D51)

    def bad_mix(x):  # x: [B] uint32
        acc = jnp.zeros(x.shape)  # implicit float32
        return acc + x * C1

    x = jnp.arange(8, dtype=jnp.uint32)
    findings = ja.audit_fn("bad-mix", bad_mix, (x,))
    wide = [f for f in findings if f.rule == "wide-dtype-on-hash-path"]
    assert wide, findings

    def good_mix(x):
        acc = jnp.zeros(x.shape, jnp.uint32)
        return acc + x * C1

    assert ja.audit_fn("good-mix", good_mix, (x,)) == []


def test_int64_promotion_on_hash_path_is_flagged():
    # the 64-bit arm must be reachable: under x64 an explicit (or
    # implicit) widening of a hash value to int64 is a parity break,
    # and it lowers to convert_element_type like any promotion
    from jax.experimental import enable_x64

    C1 = np.uint32(0xCC9E2D51)

    def bad_widen(x):  # x: [B] uint32
        h = x * C1
        return h.astype(jnp.int64) + 1

    with enable_x64():
        findings = ja.audit_fn(
            "bad-widen", bad_widen, (jnp.arange(8, dtype=jnp.uint32),)
        )
    assert any(
        f.rule == "wide-dtype-on-hash-path" and "64-bit" in f.message
        for f in findings
    ), findings


def test_taint_entering_unmapped_boundary_is_flagged():
    # taint flowing INTO a while loop (an unmapped sub-jaxpr) must
    # follow the loop's outputs to a downstream widening
    def taint_through_loop(x):
        h = x * np.uint32(0xCC9E2D51)
        h = jax.lax.while_loop(
            lambda c: c < jnp.uint32(9),
            lambda c: c + jnp.uint32(1),
            h,
        )
        return h.astype(jnp.float32)

    findings = ja.audit_fn(
        "taint-through-loop", taint_through_loop, (jnp.uint32(3),)
    )
    assert any(
        f.rule == "wide-dtype-on-hash-path" for f in findings
    ), findings


def test_int32_hop_does_not_launder_taint():
    # int32 is a bit-preserving hop for mod-2^32 values; a float
    # widening one eqn later must still be flagged
    def launder(x):
        h = x * np.uint32(0xCC9E2D51)
        return h.astype(jnp.int32).astype(jnp.float32)

    findings = ja.audit_fn("launder", launder, (jnp.uint32(3),))
    assert any(
        f.rule == "wide-dtype-on-hash-path" for f in findings
    ), findings


def test_taint_survives_unmapped_sub_jaxpr_boundary():
    # hash-constant taint born INSIDE a while body (an unmapped
    # sub-jaxpr, like a pallas_call kernel) must follow the loop's
    # outputs: widening the result downstream is a finding
    from jax.experimental import enable_x64

    def bad_loop(x):  # x: scalar uint32
        h = jax.lax.while_loop(
            lambda c: c < jnp.uint32(1 << 30),
            lambda c: c * np.uint32(0x85EBCA6B) + jnp.uint32(1),
            x,
        )
        return h.astype(jnp.int64) + 1

    with enable_x64():
        findings = ja.audit_fn(
            "bad-loop", bad_loop, (jnp.uint32(3),)
        )
    assert any(
        f.rule == "wide-dtype-on-hash-path" for f in findings
    ), findings


def test_removing_uint32_dtype_in_jax_farmhash_is_caught():
    # ISSUE 3 acceptance, demonstrated literally: strip an explicit uint32
    # dtype from ops/jax_farmhash.py, re-exec the module source, and audit
    # its hash32_rows — the tool must go non-zero (the float accumulator
    # either taints the hash dataflow or kills the trace at a bitwise op)
    import ringpop_tpu.ops.jax_farmhash as jfh

    src_path = jfh.__file__
    src = open(src_path).read()
    broken = src.replace(
        "b = jnp.zeros(B, jnp.uint32)", "b = jnp.zeros(B)"
    )
    assert broken != src, "expected explicit-uint32 site moved — update test"
    ns = {"__name__": "jax_farmhash_broken", "__file__": src_path}
    exec(compile(broken, src_path, "exec"), ns)

    mat, lens = ja._farmhash_args()
    findings = ja.audit_fn(
        "farmhash-broken",
        lambda m, l: ns["hash32_rows"](m, l, impl="scan"),
        (mat, lens),
    )
    assert findings, "auditor missed the dropped uint32 dtype"
    assert {f.rule for f in findings} <= {
        "wide-dtype-on-hash-path",
        "trace-failure",
    }


def test_audit_recurses_into_control_flow():
    # a callback hidden under cond-inside-scan is still found
    def leaky(xs):
        def body(c, x):
            c = jax.lax.cond(
                x > 0,
                lambda v: jax.pure_callback(
                    lambda a: np.asarray(a),
                    jax.ShapeDtypeStruct((), jnp.int32),
                    v,
                ),
                lambda v: v,
                c,
            )
            return c, c

        return jax.lax.scan(body, jnp.int32(0), xs)

    findings = ja.audit_fn(
        "nested", leaky, (jnp.arange(4, dtype=jnp.int32),)
    )
    assert any(f.rule == "callback-primitive" for f in findings)


def test_cli_exit_codes_mirror_findings(monkeypatch, capsys):
    # exit 0 on the clean registry, non-zero when any entry yields findings
    from ringpop_tpu.analysis.__main__ import main

    fake_bad = [
        ja.EntryPoint(
            "bad",
            lambda: (
                lambda x: jnp.zeros(x.shape)
                + x * np.uint32(0xCC9E2D51),
                (jnp.arange(4, dtype=jnp.uint32),),
            ),
        )
    ]
    monkeypatch.setattr(ja, "DEFAULT_ENTRIES", fake_bad)
    assert main(["--prong", "jaxpr"]) == 1
    assert "wide-dtype-on-hash-path" in capsys.readouterr().out
