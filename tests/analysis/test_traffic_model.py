"""Measured-vs-model traffic gate (scripts/check_traffic_model.py +
TRAFFIC_BUDGET.json).

Tier-1 wiring mirrors test_cost_budget.py: a cheap-probe subset (the
2-shard mesh at n=64) is measured and diffed against the committed
manifest every run; the mutation tests prove the gate FIRES on a
doctored manifest and on a measured-vs-model break."""

import importlib.util as ilu
import json
import os
from pathlib import Path

import jax
import pytest

# one cheap config (2-shard mesh, n=64, 8 ticks — seconds warm); the
# full 2/4/8 sweep belongs to the script / the mesh telemetry tests
CHEAP_TRAFFIC_ENTRIES = ("mesh-s2-n64",)


def _script():
    spec = ilu.spec_from_file_location(
        "check_traffic_model",
        os.path.join(
            os.path.dirname(__file__), "..", "..", "scripts",
            "check_traffic_model.py",
        ),
    )
    mod = ilu.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def mod():
    return _script()


def test_cheap_probe_subset_matches_committed_manifest(mod):
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    findings = mod.check_against_manifest(
        entry_names=CHEAP_TRAFFIC_ENTRIES
    )
    from ringpop_tpu.analysis.findings import render_text

    assert findings == [], "\n" + render_text(findings)


def test_manifest_covers_every_mesh_config(mod):
    manifest = mod.load_manifest()
    assert manifest is not None, "TRAFFIC_BUDGET.json not committed"
    names = {mod.entry_name(c) for c in mod.MESH_CONFIGS}
    assert set(manifest["entries"]) == names
    for e in manifest["entries"].values():
        assert "error" not in e
        # the committed windows reconcile exactly: every trip a2a
        assert e["ratio"] == 1.0
        assert e["fallback_trips"] == 0
        assert e["measured_interconnect"] == e["model_interconnect"]


def test_script_exits_nonzero_on_doctored_manifest(mod, tmp_path, capsys):
    """End-to-end proof the CI gate fires: perturb the committed
    measured bytes (a silently changed wire format) and diff mode exits
    non-zero; the pristine manifest exits zero."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    manifest = mod.load_manifest()
    pristine = tmp_path / "ok.json"
    doctored = tmp_path / "bad.json"
    pristine.write_text(json.dumps(manifest))
    bad = json.loads(json.dumps(manifest))
    bad["entries"]["mesh-s2-n64"]["measured_interconnect"] *= 3
    bad["entries"]["mesh-s2-n64"]["model_interconnect"] *= 3
    doctored.write_text(json.dumps(bad))
    args = ["--entries", ",".join(CHEAP_TRAFFIC_ENTRIES)]
    assert mod.main(args + ["--budget", str(pristine)]) == 0
    assert mod.main(args + ["--budget", str(doctored)]) == 1


def test_reconcile_finding_fires_on_model_break(mod):
    """The manifest-free layer: measured bytes off the analytic model
    by more than rtol is a finding even with a colluding manifest."""
    actual = {
        "mesh-s2-n64": {
            "shards": 2,
            "n": 64,
            "w": 4,
            "cap": 32,
            "ticks": 8,
            "measured_interconnect": 30000,
            "model_interconnect": 20480,
            "ratio": 1.46,
            "fallback_trips": 0,
        }
    }
    findings = mod.reconcile_findings(actual)
    assert len(findings) == 1
    assert "exceeds rtol" in findings[0].message
    assert findings[0].prong == "traffic"
    # a failed measurement is a finding too, not a silent skip
    failed = mod.reconcile_findings({"x": {"error": "boom"}})
    assert len(failed) == 1 and "measurement failed" in failed[0].message


def test_compare_flags_identity_and_band_drift(mod):
    entry = {
        "shards": 2,
        "n": 64,
        "w": 4,
        "cap": 32,
        "ticks": 8,
        "measured_interconnect": 20480,
        "model_interconnect": 20480,
        "ratio": 1.0,
        "fallback_trips": 0,
    }
    manifest = {"entries": {"mesh-s2-n64": dict(entry)}}
    assert mod.compare_to_manifest({"mesh-s2-n64": dict(entry)}, manifest) == []
    # identity fields are exact: a cap change at equal bytes still fires
    recapped = dict(entry, cap=16)
    findings = mod.compare_to_manifest({"mesh-s2-n64": recapped}, manifest)
    assert any("cap changed" in f.message for f in findings)
    # banded fields tolerate rtol, fire beyond it
    drifted = dict(entry, measured_interconnect=30000)
    findings = mod.compare_to_manifest({"mesh-s2-n64": drifted}, manifest)
    assert any("drifted" in f.message for f in findings)
    # missing/extra entries both fire
    findings = mod.compare_to_manifest(
        {"other": dict(entry)}, manifest
    )
    msgs = "\n".join(f.message for f in findings)
    assert "not measured" in msgs and "no manifest entry" in msgs


def test_write_manifest_refuses_failed_entries(mod, tmp_path):
    with pytest.raises(ValueError, match="refusing"):
        mod.write_manifest(
            {"good": {"shards": 2}, "broken": {"error": "boom"}},
            tmp_path / "m.json",
        )


def test_backend_mismatch_skips_cleanly(mod, tmp_path):
    other = {
        "backend": "tpu" if jax.default_backend() != "tpu" else "cpu",
        "entries": {"mesh-s2-n64": {"shards": 2}},
    }
    p = tmp_path / "other.json"
    p.write_text(json.dumps(other))
    assert mod.check_against_manifest(("mesh-s2-n64",), Path(p)) == []


def test_missing_manifest_is_a_finding(mod, tmp_path):
    findings = mod.check_against_manifest(
        ("mesh-s2-n64",), tmp_path / "nope.json"
    )
    assert len(findings) == 1 and "missing manifest" in findings[0].message
