"""Registry gate (ISSUE 15 satellite): every engine-state field is
classified exactly once as trajectory or obs-only.

A new field added to SimState/ScalableState/RouteState without a
classification fails HERE with a how-to-fix message — which is what
keeps the noninterference prong's proof meaningful (an unclassified
field would otherwise be invisible to it until trace time).
"""

import pytest

from ringpop_tpu.models.route import plane
from ringpop_tpu.models.sim import engine, engine_scalable as es

REGISTRIES = [
    (
        engine.SimState,
        engine.SIM_TRAJECTORY_FIELDS,
        engine.SIM_OBS_ONLY_FIELDS,
        "models/sim/engine.py (SIM_TRAJECTORY_FIELDS / SIM_OBS_ONLY_FIELDS)",
    ),
    (
        es.ScalableState,
        es.SCALABLE_TRAJECTORY_FIELDS,
        es.SCALABLE_OBS_ONLY_FIELDS,
        "models/sim/engine_scalable.py (SCALABLE_TRAJECTORY_FIELDS / "
        "SCALABLE_OBS_ONLY_FIELDS)",
    ),
    (
        plane.RouteState,
        plane.ROUTE_TRAJECTORY_FIELDS,
        plane.ROUTE_OBS_ONLY_FIELDS,
        "models/route/plane.py (ROUTE_TRAJECTORY_FIELDS / "
        "ROUTE_OBS_ONLY_FIELDS)",
    ),
]


@pytest.mark.parametrize(
    "cls,traj,obs,where", REGISTRIES, ids=[r[0].__name__ for r in REGISTRIES]
)
def test_every_field_classified_exactly_once(cls, traj, obs, where):
    fields = set(cls._fields)
    unclassified = fields - traj - obs
    assert not unclassified, (
        f"{cls.__name__} field(s) {sorted(unclassified)} are classified "
        f"neither trajectory nor obs-only.  Fix: add each to exactly one "
        f"of the registry sets in {where} — obs-only ONLY if the field is "
        "write-only within the tick (nothing the protocol reads), else "
        "trajectory.  The noninterference analysis prong then proves the "
        "obs case statically."
    )
    overlap = traj & obs
    assert not overlap, (
        f"{cls.__name__} field(s) {sorted(overlap)} are classified BOTH "
        f"trajectory and obs-only — remove each from one set in {where}"
    )
    stale = (traj | obs) - fields
    assert not stale, (
        f"registry in {where} names non-existent field(s) "
        f"{sorted(stale)} — remove them (the state class changed)"
    )


def test_registries_match_the_prong_view():
    """analysis/noninterference.py consumes exactly these registries."""
    from ringpop_tpu.analysis import noninterference as ni

    regs = ni.state_registries()
    assert set(regs) == {"SimState", "ScalableState", "RouteState"}
    for cls, traj, obs, _ in REGISTRIES:
        assert regs[cls.__name__] == (traj, obs)


def test_executor_split_obs_rides_the_registry():
    """fuzz.executor.split_obs partitions by the same single source."""
    import jax.numpy as jnp

    from ringpop_tpu.fuzz import executor as fex

    params = es.ScalableParams(n=4, u=128, wavefront=True)
    state = es.init_state(params, seed=0)
    traj, obs = fex.split_obs(state)
    assert set(obs) == {"first_heard"}  # hist off -> absent
    assert traj.first_heard is None and traj.hist is None
    assert traj.heard is state.heard  # trajectory planes untouched
    assert (
        jnp.asarray(obs["first_heard"]).shape
        == state.first_heard.shape
    )
