"""Gossip loop rate adaptation + lifecycle (lib/gossip/index.js:42-105)."""

from ringpop_tpu.gossip.gossip import Gossip


class StubRingpop:
    def __init__(self):
        from ringpop_tpu.net.timers import FakeTimers

        self.timers = FakeTimers()

        class _Log:
            def debug(self, *a, **k):
                pass

            info = warning = error = debug

        self.logger = _Log()
        self.stats = []

    def whoami(self):
        return "127.0.0.1:3000"

    def stat(self, t, k, v=None):
        self.stats.append((t, k))


def test_first_tick_staggered_within_min_period():
    import random

    g = Gossip(StubRingpop(), rng=random.Random(7))
    delays = {g.compute_protocol_delay_ms() for _ in range(20)}
    assert all(0 <= d < g.min_protocol_period_ms for d in delays)
    assert len(delays) > 1  # actually random, not constant


def test_rate_is_twice_p50_floored():
    g = Gossip(StubRingpop())
    # no observations: floored at the minimum period
    assert g.compute_protocol_rate_ms() == g.min_protocol_period_ms
    for ms in (10.0, 20.0, 30.0):
        g.protocol_timing.update(ms)
    # p50=20 -> 2x = 40 < 200 floor
    assert g.compute_protocol_rate_ms() == g.min_protocol_period_ms
    for ms in (400.0, 500.0, 600.0, 700.0):
        g.protocol_timing.update(ms)
    assert g.compute_protocol_rate_ms() > g.min_protocol_period_ms


def test_start_stop_idempotent():
    class M:
        def shuffle(self):
            pass

    rp = StubRingpop()
    rp.membership = M()
    g = Gossip(rp)
    assert g.is_stopped
    g.start()
    assert not g.is_stopped
    g.start()  # no-op
    g.stop()
    assert g.is_stopped
    g.stop()  # warns, no crash
