"""Dissemination piggyback-buffer semantics (vs lib/gossip/dissemination.js).

Regression focus: the receiver-origin filter must run BEFORE the piggyback
bump (dissemination.js:147-160) so changes the requester originated don't
burn dissemination budget, and the filter only fires when all four of
sender address/incarnation and change source/sourceIncarnationNumber are
truthy (dissemination.js:90-97).
"""

from ringpop_tpu.gossip.dissemination import Dissemination

LOCAL = "127.0.0.1:3000"
PEER = "127.0.0.1:3001"


class StubRing:
    def __init__(self, count=3):
        self.count = count

    def get_server_count(self):
        return self.count


class StubMembership:
    def __init__(self):
        self.checksum = 12345
        self.members = []


class StubRingpop:
    def __init__(self):
        self.ring = StubRing()
        self.membership = StubMembership()
        self.stats = []

        class _Log:
            def info(self, *a, **k):
                pass

            debug = warning = error = info

        self.logger = _Log()

    def whoami(self):
        return LOCAL

    def stat(self, type_, key, value=None):
        self.stats.append((type_, key, value))


def change(addr=PEER, source=LOCAL, source_inc=1414142122274):
    return {
        "id": "id-1",
        "source": source,
        "sourceIncarnationNumber": source_inc,
        "address": addr,
        "status": "alive",
        "incarnationNumber": 1414142122274,
    }


def test_issue_as_sender_bumps_and_expires():
    d = Dissemination(StubRingpop())
    d.max_piggyback_count = 2
    d.record_change(change())
    assert len(d.issue_as_sender()) == 1
    assert len(d.issue_as_sender()) == 1
    # third issue exceeds the max: dropped from the buffer, not issued
    assert d.issue_as_sender() == []
    assert d.get_change_count() == 0


def test_receiver_origin_filter_does_not_consume_budget():
    d = Dissemination(StubRingpop())
    d.max_piggyback_count = 2
    origin_inc = 999
    d.record_change(change(source=PEER, source_inc=origin_inc))
    # the originating peer pings us many times: always filtered, and the
    # filtered issues must not bump piggybackCount toward expiry
    for _ in range(10):
        changes, full_sync = d.issue_as_receiver(PEER, origin_inc, 12345)
        assert changes == []
        assert not full_sync
    assert d.get_change_count() == 1
    # a different receiver still gets the change afterwards
    changes, _ = d.issue_as_receiver("127.0.0.1:3002", 5, 12345)
    assert [c["address"] for c in changes] == [PEER]


def test_filtered_change_stat_incremented():
    rp = StubRingpop()
    d = Dissemination(rp)
    d.record_change(change(source=PEER, source_inc=7))
    d.issue_as_receiver(PEER, 7, rp.membership.checksum)
    assert ("increment", "filtered-change", None) in rp.stats


def test_filter_requires_all_fields_truthy():
    # sourceIncarnationNumber None/0 on both sides must NOT trigger the
    # filter (reference truthiness guard, dissemination.js:90-97)
    d = Dissemination(StubRingpop())
    d.record_change(change(source=PEER, source_inc=None))
    changes, _ = d.issue_as_receiver(PEER, None, 12345)
    assert len(changes) == 1

    d2 = Dissemination(StubRingpop())
    d2.record_change(change(source=PEER, source_inc=7))
    # sender matches on address but not incarnation: issued
    changes, _ = d2.issue_as_receiver(PEER, 8, 12345)
    assert len(changes) == 1


def test_full_sync_on_checksum_mismatch_when_empty():
    rp = StubRingpop()

    class M:
        address = PEER
        status = "alive"
        incarnation_number = 1

    rp.membership.members = [M()]
    d = Dissemination(rp)
    changes, full_sync = d.issue_as_receiver(PEER, 1, rp.membership.checksum + 1)
    assert full_sync and len(changes) == 1
    changes, full_sync = d.issue_as_receiver(PEER, 1, rp.membership.checksum)
    assert changes == [] and not full_sync


def test_max_piggyback_scales_with_server_count():
    rp = StubRingpop()
    d = Dissemination(rp)
    rp.ring.count = 9  # ceil(log10(10)) = 1
    d.adjust_max_piggyback_count()
    assert d.max_piggyback_count == 15
    rp.ring.count = 1000  # ceil(log10(1001)) = 4... log10(1001)≈3.0004 → 4
    d.adjust_max_piggyback_count()
    assert d.max_piggyback_count == 60
