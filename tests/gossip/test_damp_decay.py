"""The damp-score decay loop, wired from the facade.

Mirrors /root/reference/test/unit/membership_test.js:280-330 (decayer
start/stop + decay math) and the facade wiring in
/root/reference/lib/membership/index.js:399-413: the decayer starts with
the instance (prematurely, per the comment there), runs every
``dampScoringDecayInterval`` (config.js:62, 1000 ms), decays every
member's flap-penalty score exponentially (member.js:45-66), and stops on
destroy.  Recovery: once a member crossed ``dampScoringSuppressLimit``,
decaying back under ``dampScoringReuseLimit`` (config.js:69) emits
``memberSuppressRecovered`` — the reuse side of the reference's TODO'd
flap-damping subprotocol.
"""

from __future__ import annotations

from ringpop_tpu.api.ringpop import Ringpop
from ringpop_tpu.net.timers import FakeTimers


def make_ringpop(**options):
    timers = FakeTimers()
    rp = Ringpop(
        "test-app", "127.0.0.1:3000", timers=timers, options=options
    )
    # force-ready without a transport (test-ringpop.js:25-68 does the same)
    rp.is_ready = True
    rp.membership.make_alive(rp.whoami(), timers.now_ms())
    rp.membership.make_alive("127.0.0.1:3001", timers.now_ms())
    return rp, timers


def penalize(rp, timers, address="127.0.0.1:3001"):
    """One flap penalty: any applied update adds dampScoringPenalty.

    A fresh-incarnation ALIVE update is the penalty vehicle (it always
    overrides) — deliberately not make_suspect, whose facade wiring also
    starts a 5 s suspicion timer that would fire during advance() and
    re-penalize the member via makeFaulty mid-test."""
    member = rp.membership.find_member_by_address(address)
    rp.membership.make_alive(address, member.incarnation_number + 1)
    return rp.membership.find_member_by_address(address)


def test_decayer_runs_without_updates():
    """Scores decay BETWEEN updates — the round-4 gap: the method existed
    but nothing ever called it, so a penalized member's score froze until
    its next penalty."""
    rp, timers = make_ringpop()
    member = penalize(rp, timers)
    assert member.damp_score == 500  # dampScoringPenalty default

    # one half-life with NO further updates
    timers.advance(60.0)
    assert member.damp_score < 500, (
        "damp score must decay between updates (decayer not running?)"
    )
    # 60 s = one dampScoringHalfLife: score ~ 500 * 0.5, rounded per tick
    assert abs(member.damp_score - 250) <= 5


def test_decay_emits_damp_score_decayed():
    rp, timers = make_ringpop()
    member = penalize(rp, timers)
    seen = []
    member.on("dampScoreDecayed", lambda new, old: seen.append((new, old)))
    timers.advance(3.0)
    assert len(seen) == 3  # one per 1 s interval
    news = [new for new, _ in seen]
    assert news == sorted(news, reverse=True)  # monotone decay
    assert all(new <= old for new, old in seen)


def test_suppress_limit_crossing_both_ways():
    rp, timers = make_ringpop(
        dampScoringSuppressLimit=400, dampScoringReuseLimit=300
    )
    suppressed, recovered = [], []
    rp.on("memberSuppressLimitExceeded", lambda m: suppressed.append(m))
    rp.on("memberSuppressRecovered", lambda m, s: recovered.append((m, s)))

    member = penalize(rp, timers)  # score 500 > 400
    assert member.suppressed
    assert [m.address for m in suppressed] == ["127.0.0.1:3001"]
    assert not recovered

    # decay to < 300 (reuse limit): 500 * e^(-t ln2 / 60) < 300 at t ~ 45 s
    timers.advance(60.0)
    assert recovered and recovered[0][0] is member
    assert not member.suppressed
    assert member.damp_score < 300
    # stats carried the signal too
    assert any("suppress-limit-exceeded" in (k or "") for k in rp.stat_keys)
    assert any("suppress-recovered" in (k or "") for k in rp.stat_keys)


def test_destroy_stops_decayer():
    rp, timers = make_ringpop()
    member = penalize(rp, timers)
    rp.destroy()
    before = member.damp_score
    timers.advance(10.0)
    assert member.damp_score == before  # no decay after destroy


def test_decayer_disabled_by_config():
    rp, timers = make_ringpop(dampScoringDecayEnabled=False)
    member = penalize(rp, timers)
    timers.advance(10.0)
    assert member.damp_score == 500  # lazy decay only, on next penalty


def test_start_during_inflight_callback_does_not_double_arm():
    """Regression: a start() landing while a decay callback is mid-flight
    (after it cleared decay_timer, before it re-armed) must not leave TWO
    live loops.  start_damp_score_decayer bumps the generation, so the
    in-flight callback's re-arm is suppressed and exactly one loop
    survives.  The interleave is reproduced with a decay listener — it
    runs at precisely the decay_timer=None / re-arm gap."""
    rp, timers = make_ringpop()
    member = penalize(rp, timers)
    membership = rp.membership

    member.once(
        "dampScoreDecayed",
        lambda *a: membership.start_damp_score_decayer(),
    )
    timers.advance(1.0)  # callback: decay -> concurrent start() -> re-arm

    seen = []
    member.on("dampScoreDecayed", lambda new, old: seen.append(new))
    timers.advance(3.0)
    assert len(seen) == 3, (
        "decay loop double-armed: %d firings in 3 intervals" % len(seen)
    )

    # and the surviving loop still stops cleanly
    membership.stop_damp_score_decayer()
    timers.advance(3.0)
    assert len(seen) == 3


def test_decay_disabled_mid_run_stops_loop():
    rp, timers = make_ringpop()
    member = penalize(rp, timers)
    timers.advance(1.0)
    after_one = member.damp_score
    assert after_one < 500
    rp.config.set("dampScoringDecayEnabled", False)
    # the already-armed timer still fires once (the reference's schedule()
    # checks the flag only when re-arming, index.js:338-341) ...
    timers.advance(1.0)
    after_two = member.damp_score
    # ... and then the loop is dead
    timers.advance(10.0)
    assert member.damp_score == after_two
