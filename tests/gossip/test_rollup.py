"""Membership update rollup buffering/flush (lib/membership/rollup.js)."""

from ringpop_tpu.net.timers import FakeTimers
from ringpop_tpu.utils.rollup import MembershipUpdateRollup


class StubRingpop:
    def __init__(self):
        self.timers = FakeTimers()
        self.debug_logs = []

        class M:
            checksum = 123

        self.membership = M()

        outer = self

        class _Log:
            def debug(self, msg, extra=None):
                outer.debug_logs.append((msg, extra))

            info = warning = error = debug

        self.logger = _Log()

    def whoami(self):
        return "127.0.0.1:3000"


def upd(addr, status="alive", inc=1):
    return {"address": addr, "status": status, "incarnationNumber": inc}


def test_flush_after_quiet_interval():
    rp = StubRingpop()
    r = MembershipUpdateRollup(rp, flush_interval_ms=5000)
    r.track_updates([upd("a:1"), upd("b:2")])
    assert r._num_updates() == 2
    assert not rp.debug_logs
    rp.timers.advance(5.5)  # quiet interval elapses -> timer flush
    assert r.buffer == {}
    assert len(rp.debug_logs) == 1
    _, extra = rp.debug_logs[0]
    assert extra["updateCount"] == 2
    assert set(extra["updates"]) == {"a:1", "b:2"}


def test_force_flush_at_max_updates():
    rp = StubRingpop()
    r = MembershipUpdateRollup(rp, flush_interval_ms=5000, max_num_updates=3)
    r.track_updates([upd("a:1"), upd("a:1")])  # same address: 2 updates
    assert not rp.debug_logs
    r.track_updates([upd("b:2")])  # hits the max -> immediate flush
    assert len(rp.debug_logs) == 1
    assert rp.debug_logs[0][1]["updateCount"] == 3
    assert r.buffer == {}


def test_flushed_event_and_destroy_cancels_timer():
    rp = StubRingpop()
    r = MembershipUpdateRollup(rp, flush_interval_ms=5000)
    flushes = []
    r.on("flushed", lambda *a: flushes.append(1))
    r.track_updates([upd("a:1")])
    r.destroy()
    rp.timers.advance(10.0)
    assert not flushes  # destroyed before the quiet flush fired
    r.flush_buffer()  # manual flush still works
    assert flushes == [1]
