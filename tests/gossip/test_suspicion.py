"""Suspicion subprotocol timers (vs lib/gossip/suspicion.js).

Regression focus: the faulty declaration at expiry must use the incarnation
captured from the update that STARTED the suspect period (suspicion.js:67-70
closure semantics) — a concurrently bumped incarnation must survive and ride
out a fresh period.
"""

from ringpop_tpu.gossip.suspicion import Suspicion
from ringpop_tpu.net.timers import FakeTimers

LOCAL = "127.0.0.1:3000"
SUSPECT = "127.0.0.1:3001"


class StubMembership:
    def __init__(self):
        self.faulty_calls = []

    def make_faulty(self, address, incarnation_number):
        self.faulty_calls.append((address, incarnation_number))


class StubRingpop:
    def __init__(self):
        self.membership = StubMembership()
        self.timers = FakeTimers()

        class _Log:
            def info(self, *a, **k):
                pass

            debug = warning = error = info

        self.logger = _Log()

    def whoami(self):
        return LOCAL


def update(addr=SUSPECT, inc=100):
    return {"address": addr, "status": "suspect", "incarnationNumber": inc}


def test_expiry_declares_faulty_with_started_incarnation():
    rp = StubRingpop()
    s = Suspicion(rp)
    s.start(update(inc=100))
    rp.timers.advance(5.0)
    assert rp.membership.faulty_calls == [(SUSPECT, 100)]


def test_restart_uses_fresh_incarnation_and_resets_clock():
    rp = StubRingpop()
    s = Suspicion(rp)
    s.start(update(inc=100))
    rp.timers.advance(3.0)
    # refuted-then-resuspected with a newer incarnation: old timer cancelled,
    # a full fresh period must elapse before faulty, with the new incarnation
    s.start(update(inc=200))
    rp.timers.advance(3.0)  # 6s since first start, 3s since restart
    assert rp.membership.faulty_calls == []
    rp.timers.advance(2.5)
    assert rp.membership.faulty_calls == [(SUSPECT, 200)]


def test_never_for_local_member():
    rp = StubRingpop()
    s = Suspicion(rp)
    s.start(update(addr=LOCAL))
    rp.timers.advance(10.0)
    assert rp.membership.faulty_calls == []


def test_stop_all_and_reenable():
    rp = StubRingpop()
    s = Suspicion(rp)
    s.start(update())
    s.stop_all()
    rp.timers.advance(10.0)
    assert rp.membership.faulty_calls == []
    # while stopped, new periods cannot start
    s.start(update())
    rp.timers.advance(10.0)
    assert rp.membership.faulty_calls == []
    s.reenable()
    s.start(update(inc=300))
    rp.timers.advance(5.0)
    assert rp.membership.faulty_calls == [(SUSPECT, 300)]


def test_stop_single_member():
    rp = StubRingpop()
    s = Suspicion(rp)
    s.start(update())
    s.stop(update())
    rp.timers.advance(10.0)
    assert rp.membership.faulty_calls == []
