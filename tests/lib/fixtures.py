"""Test fixtures mirroring /root/reference/test/lib/test-ringpop.js:25-68 —
a real membership stack with no transport, forced ready, local member alive —
and a deterministic clock so incarnation numbers are reproducible."""

from __future__ import annotations

import random
from typing import Optional

from ringpop_tpu.models.membership import Membership, MembershipIterator
from ringpop_tpu.utils.config import Config
from ringpop_tpu.utils.util import null_logger


class FakeClock:
    """Deterministic Date.now() — starts at a realistic ms epoch and can be
    advanced manually (the reference uses time-mock timers similarly)."""

    def __init__(self, start_ms: int = 1414142122274):
        self.ms = start_ms

    def __call__(self) -> int:
        return self.ms

    def advance(self, ms: int) -> None:
        self.ms += ms


class RingpopFixture:
    """Minimal ringpop context: config/logger/stat/whoami + membership."""

    def __init__(
        self,
        host_port: str = "127.0.0.1:3000",
        ready: bool = True,
        seed: Optional[dict] = None,
        clock: Optional[FakeClock] = None,
    ):
        self.host_port = host_port
        self.is_ready = False
        self.logger = null_logger()
        self.config = Config(self, seed)
        self.clock = clock or FakeClock()
        self.now = self.clock
        self.stats = []
        self.membership = Membership(self, rng=random.Random(0xC0FFEE))
        if ready:
            self.membership.make_alive(self.host_port, self.now())
            self.is_ready = True

    def whoami(self) -> str:
        return self.host_port

    def stat(self, type_: str, key: str, value=None) -> None:
        self.stats.append((type_, key, value))


def make_ringpop(**kw) -> RingpopFixture:
    return RingpopFixture(**kw)


def make_iterator(rp: RingpopFixture) -> MembershipIterator:
    return MembershipIterator(rp)
