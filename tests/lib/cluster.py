"""In-process live-cluster fixture: N full Ringpop nodes with real framed
JSON-over-TCP channels on 127.0.0.1 — the equivalent of the reference's
``testRingpopCluster`` (test/lib/test-ringpop-cluster.js:31-135).

Gossip is driven manually (``autoGossip: False`` + ``tick_all``) and every
node gets ``FakeTimers`` so suspicion clocks and proxy retry sleeps advance
virtually (the reference wires time-mock the same way,
test/lib/alloc-ringpop.js:24-63) while the RPC plane stays real sockets.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ringpop_tpu.api.ringpop import Ringpop
from ringpop_tpu.net.channel import Channel
from ringpop_tpu.net.timers import FakeTimers


class LiveCluster:
    def __init__(
        self,
        n: int = 5,
        app: str = "integration-app",
        options: Optional[dict] = None,
        tap=None,
    ):
        self.nodes: List[Ringpop] = []
        for i in range(n):
            ch = Channel("127.0.0.1:0")
            host_port = ch.listen()
            rp = Ringpop(
                app,
                host_port,
                channel=ch,
                timers=FakeTimers(),
                options=dict({"autoGossip": False}, **(options or {})),
                seed=i,
            )
            self.nodes.append(rp)
        self.hosts = [rp.whoami() for rp in self.nodes]
        if tap is not None:
            # pre-bootstrap sabotage hook (test-ringpop-cluster.js tap())
            tap(self)

    # -- lifecycle --------------------------------------------------------

    def bootstrap_all(self, timeout_s: float = 30.0) -> None:
        """Concurrent bootstrap against the shared hosts list, like
        tick-cluster's simultaneous child-process startup."""
        errors: List[tuple] = []

        def boot(rp: Ringpop) -> None:
            try:
                rp.bootstrap(self.hosts)
            except Exception as e:  # collected for the assert below
                errors.append((rp.whoami(), e))

        threads = [
            threading.Thread(target=boot, args=(rp,), daemon=True)
            for rp in self.nodes
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout_s)
        assert not errors, errors
        assert all(rp.is_ready for rp in self.nodes)
        # start gossip so tick() runs its full path (the ping-req fallback
        # is skipped while stopped, gossip/index.js:129-131); the
        # self-rescheduling timer lands in FakeTimers, so protocol periods
        # still only run when the test calls tick_all()/advance_all()
        for rp in self.nodes:
            rp.gossip.start()

    def destroy_all(self) -> None:
        for rp in self.nodes:
            rp.destroy()

    # -- drive ------------------------------------------------------------

    def live(self) -> List[Ringpop]:
        return [rp for rp in self.nodes if rp.is_ready and not rp.destroyed]

    def tick_all(self) -> None:
        # manual drive: run a protocol period on every live node (stopped
        # gossip still ticks, mirroring /admin/gossip/tick)
        for rp in self.live():
            rp.gossip.tick()

    def advance_all(self, seconds: float) -> None:
        for rp in self.live():
            rp.timers.advance(seconds)

    def checksums(self) -> Dict[str, int]:
        return {rp.whoami(): rp.membership.checksum for rp in self.live()}

    def converged(self) -> bool:
        # all live checksums equal (scenario-runner.js:152-170)
        values = set(self.checksums().values())
        return len(values) <= 1

    def tick_until_converged(self, max_ticks: int = 60) -> int:
        for i in range(max_ticks):
            self.tick_all()
            if self.converged():
                return i + 1
        raise AssertionError(
            "no convergence after %d ticks: %r" % (max_ticks, self.checksums())
        )

    # -- queries ----------------------------------------------------------

    def node(self, i: int) -> Ringpop:
        return self.nodes[i]

    def status_of(self, viewer: Ringpop, address: str) -> Optional[str]:
        m = viewer.membership.find_member_by_address(address)
        return m.status if m is not None else None

    def statuses_of(self, address: str) -> Dict[str, Optional[str]]:
        return {
            rp.whoami(): self.status_of(rp, address)
            for rp in self.live()
            if rp.whoami() != address
        }
