"""Test harness config.

Forces JAX onto a virtual 8-device CPU mesh (the driver validates the real
multi-chip path separately via __graft_entry__.dryrun_multichip).

Note: this image's sitecustomize imports jax and registers the single-client
`axon` TPU tunnel in every interpreter, and jax captures JAX_PLATFORMS at
import time — so mutating os.environ here is too late for the platform
selection.  We must update jax.config directly (safe: no backend has been
initialized yet at conftest time).  XLA_FLAGS, by contrast, is read by XLA at
backend-init time, so the env mutation works for the device count.
"""

import importlib.util as _ilu
import os

# single source for the forced-host-device flag spelling (round 14):
# ringpop_tpu.utils.util.force_host_device_count.  Loaded by FILE PATH,
# not package import: `import ringpop_tpu` pulls in jax (the x64
# enable), and jax snapshots JAX_NUM_CPU_DEVICES at import — the env
# pin must land before any jax import to stay meaningful on jax >= 0.5
# (today's 0.4.37 reads the count from XLA_FLAGS at backend init, but
# the ordering must not silently rot under an upgrade).  An ambient
# count (a user's own XLA_FLAGS) wins.
_spec = _ilu.spec_from_file_location(
    "_ringpop_util_boot",
    os.path.join(
        os.path.dirname(__file__), "..", "ringpop_tpu", "utils", "util.py"
    ),
)
_util_boot = _ilu.module_from_spec(_spec)
_spec.loader.exec_module(_util_boot)
if (
    "xla_force_host_platform_device_count"
    not in os.environ.get("XLA_FLAGS", "")
    and "JAX_NUM_CPU_DEVICES" not in os.environ
):
    _util_boot.force_host_device_count(8)
# Round-13 note: buffer donation is DISABLED on the CPU backend
# (storm.donate_state_argnums) — cache-deserialized executables
# mis-execute donation when other dispatches interleave.  Full write-up
# + the machine-checked defenses (DONATION_BUDGET.json, the donation
# analysis prong, astlint stale-ref-across-donation): README "Donation
# hazards".  If re-enabled on CPU, the cadence tests in
# tests/models/test_recovery.py flake within a few runs.
os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses tests may spawn

# Persistent XLA compilation cache: the tier-1 suite is dominated by
# compiles of large scanned programs (the 870 s budget bites), and the
# cache survives across pytest runs, cutting warm reruns to a fraction.
# Kept INSIDE the repo (gitignored) — nothing outside /root/repo is
# touched.  The env var (not just jax.config) so spawned subprocesses
# (dryrun_multichip) share it; config.update below covers THIS process,
# whose jax was already imported by sitecustomize without the var.
_CACHE_DIR = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _CACHE_DIR)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update(
    "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running parity/scale tests (deselect with -m 'not slow')"
    )
