"""Fused encode+hash pipeline vs the classic composition, bit for bit.

The fused path (ops.fused_checksum: record encode -> streaming VMEM
assemble+hash) must produce the SAME uint32 as
``hash32_rows(*membership_rows(...))`` on every view — that composition is
itself pinned to the host oracle and Google's compiled farmhash by the
existing suites, so equality here extends the parity chain to the fused
kernel.  Interpret-mode Pallas runs everywhere, keeping the kernel logic
itself under test off-chip (tier-1 budget: all cases are small)."""

import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.ops import checksum_encode as ce
from ringpop_tpu.ops import fused_checksum as fc
from ringpop_tpu.ops import jax_farmhash as jfh


def _views(seed=3, n_extra="10.0.0.9:99"):
    """A small universe + adversarial view batch: empty row, full row,
    single member (short-string buckets), pairs, every status, digit
    counts 1..14 including zero incarnations."""
    addrs = ["127.0.0.1:%d" % (3000 + i) for i in range(17)] + [n_extra]
    uni = ce.Universe.from_addresses(addrs)
    n = uni.n
    rng = np.random.default_rng(seed)
    B = 9
    present = rng.random((B, n)) > 0.3
    present[0] = True  # full membership
    present[1] = False  # empty row -> len 0
    present[4] = False
    present[4, 2] = True  # single member -> short bucket
    present[5] = False
    present[5, [0, 9]] = True
    status = rng.integers(0, 4, size=(B, n))
    status[6] = 3  # all-leave records
    inc = rng.integers(1, 10**14, size=(B, n))
    inc[2, :] = 7  # single-digit incarnations
    inc[3, :5] = 0  # zero incarnation edge ("0" is one digit)
    inc[7] = 99999999999999  # 14-digit boundary
    return uni, present, status, inc


def test_member_records_rebuild_row_strings():
    """Concatenating present members' records (dropping the final ';')
    must reproduce membership_rows' assembled string byte-for-byte."""
    uni, present, status, inc = _views()
    bufs, lens = ce.membership_rows(
        uni, jnp.asarray(present), jnp.asarray(status), jnp.asarray(inc)
    )
    rec_b, rec_l = fc.member_records(
        uni, jnp.asarray(present), jnp.asarray(status), jnp.asarray(inc)
    )
    bufs, lens = np.asarray(bufs), np.asarray(lens)
    rec_b, rec_l = np.asarray(rec_b), np.asarray(rec_l)
    for b in range(present.shape[0]):
        parts = [
            bytes(rec_b[b, j, : rec_l[b, j]])
            for j in range(uni.n)
            if rec_l[b, j]
        ]
        want = b"".join(parts)[:-1] if parts else b""
        assert want == bytes(bufs[b, : lens[b]]), b
        # zero-padding invariant past each record's length (the stream
        # kernel ORs records in; garbage there would corrupt the row)
        for j in range(uni.n):
            assert not rec_b[b, j, rec_l[b, j] :].any(), (b, j)


@pytest.mark.parametrize("max_digits", [14, 19])
def test_fused_matches_composition(max_digits):
    uni, present, status, inc = _views()
    bufs, lens = ce.membership_rows(
        uni,
        jnp.asarray(present),
        jnp.asarray(status),
        jnp.asarray(inc),
        max_digits=max_digits,
    )
    want = np.asarray(jfh.hash32_rows(bufs, lens, impl="scan"))
    got = np.asarray(
        fc.membership_checksums(
            uni,
            jnp.asarray(present),
            jnp.asarray(status),
            jnp.asarray(inc),
            max_digits=max_digits,
            impl="xla",
        )
    )
    assert (got == want).all(), np.flatnonzero(got != want)


def test_fused_pallas_interpret_matches_composition():
    """The gridless streaming kernel (interpret mode off-chip), with a
    small member chunk to exercise the scan-of-slabs path."""
    uni, present, status, inc = _views(seed=11)
    bufs, lens = ce.membership_rows(
        uni, jnp.asarray(present), jnp.asarray(status), jnp.asarray(inc)
    )
    want = np.asarray(jfh.hash32_rows(bufs, lens, impl="scan"))
    rec_b, rec_l = fc.member_records(
        uni, jnp.asarray(present), jnp.asarray(status), jnp.asarray(inc)
    )
    got = np.asarray(
        fc.fused_hash_rows(
            fc.pack_record_words(rec_b), rec_l, impl="pallas", chunk=4
        )
    )
    assert (got == want).all(), np.flatnonzero(got != want)


def test_incremental_cell_update_matches_dense():
    """The sparse cache-update path (member_records_at + scatter) must
    land exactly the bytes a dense re-encode would: flip a few members'
    (status, incarnation) and an unknown->known edge, update only those
    cells, and compare the whole cache against a fresh dense encode —
    untouched cells byte-identical (reused), touched cells fresh."""
    uni, present, status, inc = _views(seed=7)
    n = uni.n
    rec_b, rec_l = fc.member_records(
        uni, jnp.asarray(present), jnp.asarray(status), jnp.asarray(inc)
    )
    rec_b, rec_l = np.asarray(rec_b).copy(), np.asarray(rec_l).copy()

    # mutate: (row, member) cells — status flip, incarnation bump with a
    # digit-count change, a member appearing, a member leaving
    edits = [(0, 3), (0, 11), (2, 2), (4, 2), (5, 9)]
    present2 = present.copy()
    status2 = status.copy()
    inc2 = inc.copy()
    status2[0, 3] = (status[0, 3] + 1) % 4
    inc2[0, 11] = 10**13  # 7 -> 14 digits on row 2's scale
    status2[2, 2] = 2
    present2[4, 2] = False  # row 4 empties out
    inc2[5, 9] = 0

    rows = np.array([e[0] for e in edits])
    cols = np.array([e[1] for e in edits])
    cell_b, cell_l = fc.member_records_at(
        uni,
        jnp.asarray(cols),
        jnp.asarray(status2[rows, cols]),
        jnp.asarray(inc2[rows, cols]),
        jnp.asarray(present2[rows, cols]),
    )
    rec_b[rows, cols] = np.asarray(cell_b)
    rec_l[rows, cols] = np.asarray(cell_l)

    dense_b, dense_l = fc.member_records(
        uni, jnp.asarray(present2), jnp.asarray(status2), jnp.asarray(inc2)
    )
    assert (rec_b == np.asarray(dense_b)).all()
    assert (rec_l == np.asarray(dense_l)).all()

    # and the fused hash over the incrementally-updated cache equals the
    # composition over the mutated views
    bufs, lens = ce.membership_rows(
        uni, jnp.asarray(present2), jnp.asarray(status2), jnp.asarray(inc2)
    )
    want = np.asarray(jfh.hash32_rows(bufs, lens, impl="scan"))
    got = np.asarray(
        fc.fused_hash_rows(
            fc.pack_record_words(jnp.asarray(rec_b)),
            jnp.asarray(rec_l),
            impl="xla",
        )
    )
    assert (got == want).all()


def test_engine_cache_invariant_under_churn():
    """Engine-level incremental recompute: through a kill -> suspect ->
    faulty -> revive lifecycle, the fused engine's record cache must
    equal a dense re-encode of the live (known, status, inc) state after
    EVERY tick (i.e. every changed cell was re-encoded, every untouched
    cell kept its bytes), and its checksums must match an unfused twin
    run bitwise."""
    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.cluster import SimCluster

    # shared params with tests/models/test_churn_window.py so the
    # lru-cached compiled ticks are reused across the two files (tier-1
    # runs them in one process; a second compile set costs ~30 s)
    from tests.models.test_churn_window import _fused_params

    n = 16
    fused = SimCluster(n=n, params=_fused_params(n))
    plain = SimCluster(
        n=n,
        params=fused.params._replace(
            fused_checksum="off", parity_recompute="gated"
        ),
    )
    kill = np.zeros(n, bool)
    kill[5] = True
    revive = np.zeros(n, bool)
    revive[5] = True
    sched = (
        [{"join": np.ones(n, bool)}]
        + [{}] * 4
        + [{"kill": kill}]
        + [{}] * 10  # suspicion_ticks=6: faulty escalates in-window
        + [{"revive": revive}]
        + [{}] * 6
    )
    for t, ev in enumerate(sched):
        inputs = engine.TickInputs.quiet(n)._replace(
            **{k: jnp.asarray(v) for k, v in ev.items()}
        )
        fused.step(inputs)
        plain.step(inputs)
        assert (fused.checksums() == plain.checksums()).all(), t
        dense_b, dense_l = fc.member_records(
            fused.universe,
            fused.state.known,
            fused.state.status,
            engine.stamp_to_ms(fused.state.inc, fused.params),
            fused.params.max_digits,
        )
        assert (
            np.asarray(fused.state.rec_bytes) == np.asarray(dense_b)
        ).all(), t
        assert (
            np.asarray(fused.state.rec_len) == np.asarray(dense_l)
        ).all(), t


def test_stream_kernel_twin_bitwise_equal():
    """The toolkit TWIN_REGISTRY contract, pinned on the raw stream
    entries: pallas_farmhash.fused_stream_nogrid (interpret mode
    off-chip) vs pallas_farmhash.fused_stream_xla, every carry lane
    bitwise-identical on the adversarial view batch."""
    from ringpop_tpu.ops import pallas_farmhash as pf

    uni, present, status, inc = _views(seed=19)
    rec_b, rec_l = fc.member_records(
        uni, jnp.asarray(present), jnp.asarray(status), jnp.asarray(inc)
    )
    rec_w = fc.pack_record_words(rec_b)
    lens = jnp.asarray(rec_l, jnp.int32)
    row_len = jnp.sum(rec_l, axis=1, dtype=jnp.int32)
    total_blocks = jnp.where(row_len > 24, (row_len - 1) // 20, 0)
    B = rec_w.shape[0]
    h0 = jnp.zeros(B, jnp.uint32)
    g0 = jnp.ones(B, jnp.uint32)
    f0 = jnp.full(B, 2, jnp.uint32)
    want = pf.fused_stream_xla(h0, g0, f0, rec_w, lens, total_blocks)
    got = pf.fused_stream_nogrid(
        h0, g0, f0, rec_w, lens, total_blocks, chunk=4, interpret=True
    )
    for a, b in zip(want, got):
        assert np.array_equal(np.asarray(a), np.asarray(b))
