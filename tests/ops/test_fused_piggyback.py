"""Gate tests for the fused dissemination-budget op
(ops/fused_piggyback.py): host-numpy reference equality against every
classic site shape (sender select / receiver bump / ping-req legs),
Pallas-interpret vs XLA-twin bitwise equivalence, and validation."""

from __future__ import annotations

import numpy as np
import pytest
import jax.numpy as jnp

from ringpop_tpu.ops import fused_piggyback as fp
from ringpop_tpu.ops import toolkit


def _fixture(n: int, seed: int = 0, max_bump: int = 4):
    rng = np.random.default_rng(seed)
    active = jnp.asarray(rng.random((n, n)) < 0.5)
    pb = jnp.asarray(rng.integers(0, 20, (n, n)), dtype=jnp.int32)
    nbump = jnp.asarray(
        rng.integers(0, max_bump, n), dtype=jnp.int32
    )
    max_pb = jnp.asarray(rng.integers(5, 25, n), dtype=jnp.int32)
    hits = jnp.asarray(rng.integers(0, 2, (n, n)), dtype=jnp.int32)
    return active, pb, nbump, max_pb, hits


def _reference(active, pb, nbump, max_pb, hits):
    """The classic receiver-bump arithmetic (engine phase 5.5) — the
    sender-select and ping-req shapes are the hits=0 / nbump-vector
    special cases of the same cell formula."""
    a, p = np.asarray(active), np.asarray(pb)
    nb = np.asarray(nbump)[:, None]
    mx = np.asarray(max_pb)[:, None]
    h = np.zeros_like(p) if hits is None else np.asarray(hits)
    eff = np.where(a & (nb > 0), nb - h, 0)
    p2 = p + eff
    over = a & (p2 > mx)
    return p2, a & ~over, a & (nb > 0) & ~over, int(over.sum())


@pytest.mark.parametrize("impl", ["xla", "pallas"])
@pytest.mark.parametrize("n", [16, 37, 64])
@pytest.mark.parametrize("with_hits", [True, False])
def test_matches_host_reference(impl, n, with_hits):
    active, pb, nbump, max_pb, hits = _fixture(n, seed=n)
    h = hits if with_hits else None
    p2, a2, content, drops = _reference(active, pb, nbump, max_pb, h)
    out = fp.pb_budget(active, pb, nbump, max_pb, h, impl=impl)
    assert np.array_equal(np.asarray(out.ch_pb), p2)
    assert np.array_equal(np.asarray(out.ch_active), a2)
    assert np.array_equal(np.asarray(out.content), content)
    assert int(out.drops) == drops


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_sender_site_shape(impl):
    """phase 3: nbump = valid_send (0/1), no hits — content must equal
    the classic ``bump & ~over`` sendable mask."""
    active, pb, _, max_pb, _ = _fixture(48, seed=5)
    rng = np.random.default_rng(9)
    valid = rng.random(48) < 0.7
    nbump = jnp.asarray(valid.astype(np.int32))
    out = fp.pb_budget(active, pb, nbump, max_pb, impl=impl)
    bump = valid[:, None] & np.asarray(active)
    p2 = np.asarray(pb) + bump.astype(np.int32)
    over = np.asarray(active) & (p2 > np.asarray(max_pb)[:, None])
    assert np.array_equal(np.asarray(out.ch_pb), p2)
    assert np.array_equal(np.asarray(out.content), bump & ~over)
    assert int(out.drops) == int(over.sum())


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_leg1_multi_bump_shape(impl):
    """ping-req leg 1: nbump = n_slots (can exceed 1), ungated add —
    the op's nbump>0 gate is bit-neutral because 0 adds 0."""
    active, pb, _, max_pb, _ = _fixture(32, seed=11)
    n_slots = jnp.asarray(
        np.random.default_rng(4).integers(0, 4, 32), dtype=jnp.int32
    )
    out = fp.pb_budget(
        active, pb, n_slots, max_pb, impl=impl, want_content=False
    )
    assert out.content is None
    new_pb = np.asarray(pb) + np.where(
        np.asarray(active), np.asarray(n_slots)[:, None], 0
    )
    over = np.asarray(active) & (
        new_pb > np.asarray(max_pb)[:, None]
    )
    assert np.array_equal(np.asarray(out.ch_pb), new_pb)
    assert np.array_equal(
        np.asarray(out.ch_active), np.asarray(active) & ~over
    )
    assert int(out.drops) == int(over.sum())


def test_pallas_twin_bitwise_equal():
    active, pb, nbump, max_pb, hits = _fixture(48, seed=3)

    def op(active, pb, nbump, max_pb, hits, impl):
        return fp.pb_budget(active, pb, nbump, max_pb, hits, impl=impl)

    toolkit.assert_twin_bitwise(op, (active, pb, nbump, max_pb, hits))


def test_arg_validation():
    active, pb, nbump, max_pb, hits = _fixture(16)
    with pytest.raises(ValueError, match="matching"):
        fp.pb_budget(active[:8], pb, nbump, max_pb)
    with pytest.raises(ValueError, match="vectors"):
        fp.pb_budget(active, pb, nbump[:8], max_pb)
    with pytest.raises(ValueError, match="impl"):
        fp.pb_budget(active, pb, nbump, max_pb, impl="bogus")


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_tiny_n_meta_width_collision(impl):
    """n=2: the [N, 2] meta vector's width equals n — the explicit
    in_planes flags keep it a narrow input (review-found regression
    class)."""
    active, pb, nbump, max_pb, hits = _fixture(2, seed=8)
    p2, a2, content, drops = _reference(active, pb, nbump, max_pb, hits)
    out = fp.pb_budget(active, pb, nbump, max_pb, hits, impl=impl)
    assert np.array_equal(np.asarray(out.ch_pb), p2)
    assert np.array_equal(np.asarray(out.ch_active), a2)
    assert np.array_equal(np.asarray(out.content), content)
    assert int(out.drops) == drops
