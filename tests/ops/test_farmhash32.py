"""Cross-implementation FarmHash32 tests.

Every implementation (pure-Python scalar, numpy batch, native C++) must agree
bit-for-bit on every length class the algorithm branches on: 0-4, 5-12,
13-24, >24 single-block, and multi-block (>44, >1000).  The strings exercised
mirror what the reference actually hashes: host:port addresses, replica-point
strings "addr<i>" (lib/ring/index.js:54-57) and membership checksum strings
"addr+status+incarnation;..." (lib/membership/index.js:100-123).
"""

import random

import numpy as np
import pytest

from ringpop_tpu.ops import farmhash32 as fh
from ringpop_tpu.ops import native


def sample_strings():
    strs = [
        b"",
        b"a",
        b"ab",
        b"abc",
        b"abcd",
        b"abcde",
        b"hello world.",
        b"0123456789abc",
        b"0123456789abcdefghijklmn",  # 24
        b"0123456789abcdefghijklmno",  # 25
        b"127.0.0.1:3000",
        b"127.0.0.1:30000",
        b"10.0.0.1:300042",
        b"127.0.0.1:3000alive1414142122274",
        b"127.0.0.1:3000alive1414142122274;127.0.0.1:3001alive1414142122275",
    ]
    # replica-point strings
    for i in (0, 1, 7, 42, 99):
        strs.append(f"127.0.0.1:3000{i}".encode())
    # random binary strings across length classes
    rng = random.Random(0xFA12)
    for n in [3, 4, 5, 11, 12, 13, 20, 24, 25, 30, 44, 45, 64, 100, 1000, 4097]:
        strs.append(bytes(rng.randrange(256) for _ in range(n)))
        strs.append(bytes(rng.randrange(32, 127) for _ in range(n)))
    # long checksum-style string (1k members)
    member_strs = [
        f"10.0.{i // 256}.{i % 256}:9000alive{1414142122274 + i}" for i in range(1000)
    ]
    strs.append(";".join(sorted(member_strs)).encode())
    return sorted(set(strs), key=len)


STRINGS = sample_strings()


def test_scalar_known_length_classes():
    # sanity: distinct inputs produce distinct hashes (no degenerate paths)
    hashes = [fh.hash32(s) for s in STRINGS]
    assert all(0 <= h <= 0xFFFFFFFF for h in hashes)
    assert len(set(hashes)) == len(hashes)


def test_numpy_batch_matches_scalar():
    mat, lens = fh.encode_rows(STRINGS)
    got = fh.hash32_batch(mat, lens)
    want = np.array([fh.hash32(s) for s in STRINGS], dtype=np.uint32)
    mismatches = [
        (i, STRINGS[i][:40], int(got[i]), int(want[i]))
        for i in range(len(STRINGS))
        if got[i] != want[i]
    ]
    assert not mismatches, mismatches[:5]


@pytest.mark.skipif(not native.available(), reason="native toolchain unavailable")
def test_native_matches_scalar():
    for s in STRINGS:
        assert native.hash32(s) == fh.hash32(s), s[:60]


@pytest.mark.skipif(not native.available(), reason="native toolchain unavailable")
def test_native_batch_matches_numpy():
    mat, lens = fh.encode_rows(STRINGS)
    got = native.hash32_batch(mat, lens)
    want = fh.hash32_batch(mat, lens)
    np.testing.assert_array_equal(got, want)


@pytest.mark.skipif(not native.available(), reason="native toolchain unavailable")
def test_native_replica_hashes():
    name = "127.0.0.1:3000"
    got = native.replica_hashes(name, 100)
    want = np.array([fh.hash32(f"{name}{i}") for i in range(100)], dtype=np.uint32)
    np.testing.assert_array_equal(got, want)


def test_str_and_bytes_agree():
    assert fh.hash32("127.0.0.1:3000") == fh.hash32(b"127.0.0.1:3000")


def test_property_sweep_scalar_vs_batch_and_native():
    # dense random sweep across every length class — the 13-24 path in
    # particular has a 25%-probability carry-overflow in rot(a + f, 12) that
    # sparse fixtures can miss (caught by review; keep this sweep dense)
    rng = random.Random(0xBEEF)
    strs = []
    for n in range(0, 64):
        for _ in range(40):
            strs.append(bytes(rng.randrange(256) for _ in range(n)))
    strs += [bytes([0xFF]) * n for n in range(1, 64)]  # all-carry patterns
    mat, lens = fh.encode_rows(strs)
    batch = fh.hash32_batch(mat, lens)
    for i, s in enumerate(strs):
        assert fh.hash32(s) == int(batch[i]), (len(s), s[:24])
    if native.available():
        nat = native.hash32_batch(mat, lens)
        np.testing.assert_array_equal(nat, batch)


def test_native_batch_rejects_bad_lens():
    if not native.available():
        pytest.skip("native toolchain unavailable")
    mat = np.zeros((2, 8), np.uint8)
    with pytest.raises(ValueError):
        native.hash32_batch(mat, np.array([4, 9]))
