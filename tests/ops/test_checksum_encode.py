"""In-jit checksum-string encoding vs host-built strings."""

import jax.numpy as jnp
import numpy as np

from ringpop_tpu.ops import checksum_encode as ce
from ringpop_tpu.ops import farmhash32 as fh
from ringpop_tpu.ops import jax_farmhash as jfh

STATUS_NAME = ce.STATUS_STRINGS


def host_membership_string(members):
    # the reference's generateChecksumString (membership/index.js:100-123)
    ordered = sorted(members, key=lambda m: m[0])
    return ";".join("%s%s%d" % (a, STATUS_NAME[s], i) for a, s, i in ordered)


def test_membership_rows_match_host_strings():
    addrs = ["127.0.0.1:%d" % (3000 + i) for i in range(17)] + ["10.0.0.9:99"]
    uni = ce.Universe.from_addresses(addrs)
    n = uni.n

    rng = np.random.default_rng(3)
    B = 5
    present = rng.random((B, n)) > 0.3
    present[0] = True  # full membership row
    present[1] = False  # empty row
    status = rng.integers(0, 4, size=(B, n))
    inc = rng.integers(1, 10**14, size=(B, n))
    inc[2, :] = 7  # single-digit incarnations
    inc[3, :5] = 0  # zero incarnation edge ("0" is one digit)

    bufs, lens = ce.membership_rows(
        uni,
        jnp.asarray(present),
        jnp.asarray(status),
        jnp.asarray(inc),
        chunk=2,  # force the lax.map chunked path
    )
    hashes = np.asarray(jfh.hash32_rows_jit(bufs, lens))
    bufs = np.asarray(bufs)
    lens = np.asarray(lens)

    for b in range(B):
        members = [
            (uni.addresses[j], int(status[b, j]), int(inc[b, j]))
            for j in range(n)
            if present[b, j]
        ]
        want = host_membership_string(members)
        got = bytes(bufs[b, : lens[b]]).decode()
        assert got == want, (b, got[:80], want[:80])
        assert int(hashes[b]) == fh.hash32(want)


def test_ring_rows_match_host_strings():
    addrs = ["h%d:%d" % (i, 1000 + i) for i in range(9)]
    uni = ce.Universe.from_addresses(addrs)
    rng = np.random.default_rng(11)
    B = 4
    in_ring = rng.random((B, uni.n)) > 0.4
    in_ring[1] = False

    bufs, lens = ce.ring_rows(uni, jnp.asarray(in_ring))
    bufs = np.asarray(bufs)
    lens = np.asarray(lens)
    for b in range(B):
        want = ";".join(
            sorted(a for j, a in enumerate(uni.addresses) if in_ring[b, j])
        )
        got = bytes(bufs[b, : lens[b]]).decode()
        assert got == want
        assert fh.hash32(got) == fh.hash32(want)


def test_gather_impl_matches_scatter_impl():
    """The gather-form encoder (TPU candidate) must produce byte-identical
    strings to the scatter form on adversarial inputs: empty rows, full
    rows, every status, and incarnation digit counts from 1 to 18."""
    import numpy as np

    from ringpop_tpu.models.sim.cluster import default_addresses

    rng = np.random.default_rng(42)
    for n, B in ((16, 10), (128, 33)):
        u = ce.Universe.from_addresses(default_addresses(n))
        pres = rng.random((B, n)) < 0.6
        pres[0] = False
        pres[1] = True
        stat = rng.integers(0, 4, (B, n)).astype(np.int32)
        inc = rng.choice(
            [0, 1, 9, 10, 99, 1414142122274, 999999999999999999],
            size=(B, n),
        ).astype(np.int64)
        a = ce.membership_rows(
            u, jnp.asarray(pres), jnp.asarray(stat), jnp.asarray(inc),
            impl="scatter",
        )
        # chunk=8 < B forces the lax.map chunked path in every impl
        for impl in ("gather", "gather2", "scatter_unique"):
            b = ce.membership_rows(
                u, jnp.asarray(pres), jnp.asarray(stat), jnp.asarray(inc),
                impl=impl, chunk=8,
            )
            la, lb = np.asarray(a[1]), np.asarray(b[1])
            assert (la == lb).all(), impl
            ba, bb = np.asarray(a[0]), np.asarray(b[0])
            for r in range(B):
                assert (ba[r, : la[r]] == bb[r, : la[r]]).all(), (
                    impl,
                    n,
                    r,
                )
