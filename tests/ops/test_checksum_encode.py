"""In-jit checksum-string encoding vs host-built strings."""

import jax.numpy as jnp
import numpy as np

from ringpop_tpu.ops import checksum_encode as ce
from ringpop_tpu.ops import farmhash32 as fh
from ringpop_tpu.ops import jax_farmhash as jfh

STATUS_NAME = ce.STATUS_STRINGS


def host_membership_string(members):
    # the reference's generateChecksumString (membership/index.js:100-123)
    ordered = sorted(members, key=lambda m: m[0])
    return ";".join("%s%s%d" % (a, STATUS_NAME[s], i) for a, s, i in ordered)


def test_membership_rows_match_host_strings():
    addrs = ["127.0.0.1:%d" % (3000 + i) for i in range(17)] + ["10.0.0.9:99"]
    uni = ce.Universe.from_addresses(addrs)
    n = uni.n

    rng = np.random.default_rng(3)
    B = 5
    present = rng.random((B, n)) > 0.3
    present[0] = True  # full membership row
    present[1] = False  # empty row
    status = rng.integers(0, 4, size=(B, n))
    inc = rng.integers(1, 10**14, size=(B, n))
    inc[2, :] = 7  # single-digit incarnations
    inc[3, :5] = 0  # zero incarnation edge ("0" is one digit)

    bufs, lens = ce.membership_rows(
        uni,
        jnp.asarray(present),
        jnp.asarray(status),
        jnp.asarray(inc),
        chunk=2,  # force the lax.map chunked path
    )
    hashes = np.asarray(jfh.hash32_rows_jit(bufs, lens))
    bufs = np.asarray(bufs)
    lens = np.asarray(lens)

    for b in range(B):
        members = [
            (uni.addresses[j], int(status[b, j]), int(inc[b, j]))
            for j in range(n)
            if present[b, j]
        ]
        want = host_membership_string(members)
        got = bytes(bufs[b, : lens[b]]).decode()
        assert got == want, (b, got[:80], want[:80])
        assert int(hashes[b]) == fh.hash32(want)


def test_ring_rows_match_host_strings():
    addrs = ["h%d:%d" % (i, 1000 + i) for i in range(9)]
    uni = ce.Universe.from_addresses(addrs)
    rng = np.random.default_rng(11)
    B = 4
    in_ring = rng.random((B, uni.n)) > 0.4
    in_ring[1] = False

    bufs, lens = ce.ring_rows(uni, jnp.asarray(in_ring))
    bufs = np.asarray(bufs)
    lens = np.asarray(lens)
    for b in range(B):
        want = ";".join(
            sorted(a for j, a in enumerate(uni.addresses) if in_ring[b, j])
        )
        got = bytes(bufs[b, : lens[b]]).decode()
        assert got == want
        assert fh.hash32(got) == fh.hash32(want)
