"""PARITY_REPLAY.json self-check: every snapshot's expected checksum is
re-derivable from its member triples via the documented recipe
(scripts/replay_node.md) using the INDEPENDENT native farmhash oracle —
the same computation a Node validator performs with the farmhash addon.
"""

import json
import os

import pytest

ARTIFACT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "PARITY_REPLAY.json",
)


@pytest.mark.skipif(
    not os.path.exists(ARTIFACT), reason="artifact not generated"
)
def test_replay_artifact_checksums_rederive():
    from ringpop_tpu.ops import native

    d = json.load(open(ARTIFACT))
    assert d["snapshots"], "artifact has no snapshots"
    statuses = set()
    for s in d["snapshots"]:
        ms = sorted(s["members"], key=lambda m: m["address"])
        statuses |= {m["status"] for m in ms}
        cs = ";".join(
            "%s%s%d" % (m["address"], m["status"], m["incarnationNumber"])
            for m in ms
        )
        assert native.hash32(cs) == s["expected_checksum"], (
            s["tick"],
            s["observer"],
        )
    # the artifact must exercise the three status spellings that appear
    # in reference checksum strings during churn
    assert {"alive", "suspect", "faulty"} <= statuses
