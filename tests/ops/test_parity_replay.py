"""PARITY_REPLAY.json self-check: every snapshot's expected checksum is
re-derivable from its member triples via the documented recipe
(scripts/replay_node.md) using the INDEPENDENT native farmhash oracle —
the same computation a Node validator performs with the farmhash addon.
"""

import json
import os

import pytest

ARTIFACT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "PARITY_REPLAY.json",
)


@pytest.mark.skipif(
    not os.path.exists(ARTIFACT), reason="artifact not generated"
)
def test_replay_artifact_checksums_rederive():
    from ringpop_tpu.ops import native

    d = json.load(open(ARTIFACT))
    assert d["snapshots"], "artifact has no snapshots"
    statuses = set()
    for s in d["snapshots"]:
        ms = sorted(s["members"], key=lambda m: m["address"])
        statuses |= {m["status"] for m in ms}
        cs = ";".join(
            "%s%s%d" % (m["address"], m["status"], m["incarnationNumber"])
            for m in ms
        )
        assert native.hash32(cs) == s["expected_checksum"], (
            s["tick"],
            s["observer"],
        )
    # the artifact must exercise the three status spellings that appear
    # in reference checksum strings during churn
    assert {"alive", "suspect", "faulty"} <= statuses


def test_trajectory_groups_native_oracle():
    """Every represented group checksum in PARITY_TRAJECTORY.json
    re-derives with the independent native farmhash oracle from the
    representative view's reference checksum string — the in-image twin
    of scripts/replay_node.md's validate_trajectory.js."""
    import json
    import os

    from ringpop_tpu.ops import native

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))),
        "PARITY_TRAJECTORY.json",
    )
    if not os.path.exists(path):
        import pytest

        pytest.skip("PARITY_TRAJECTORY.json not generated")
    art = json.load(open(path))
    checked = 0
    for t in art["ticks_data"]:
        for g in t["groups"]:
            rep = g.get("representative")
            if rep is None:
                continue
            s = ";".join(
                "%s%s%d" % (m[0], m[1], m[2])
                for m in sorted(rep["members"], key=lambda m: m[0])
            )
            assert native.hash32(s) == g["checksum"], (
                "tick %d observer %s" % (t["tick"], rep["observer"])
            )
            checked += 1
    assert checked >= art["ticks"], checked  # at least one group per tick
    assert art["ticks_data"][-1]["distinct_checksums"] == 1
