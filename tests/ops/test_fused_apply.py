"""Gate tests for the fused membership-update op (ops/fused_apply.py):
host-numpy reference equality, Pallas-interpret vs XLA-twin bitwise
equivalence (the toolkit TWIN_REGISTRY contract), output-flag variants,
and argument validation."""

from __future__ import annotations

import numpy as np
import pytest
import jax.numpy as jnp

from ringpop_tpu.ops import fused_apply as fa
from ringpop_tpu.ops import toolkit

ALIVE, SUSPECT, FAULTY, LEAVE = 0, 1, 2, 3


def _fixture(n: int, seed: int = 0, dense: float = 0.4):
    rng = np.random.default_rng(seed)

    def bpl(p):
        return jnp.asarray(rng.random((n, n)) < p)

    def ipl(lo, hi):
        return jnp.asarray(
            rng.integers(lo, hi, (n, n)), dtype=jnp.int32
        )

    st = fa.ApplyState(
        known=bpl(0.8),
        status=ipl(0, 4),
        inc=ipl(0, 50),
        ch_active=bpl(0.3),
        ch_status=ipl(0, 4),
        ch_inc=ipl(0, 50),
        ch_source=ipl(-1, n),
        ch_source_inc=ipl(0, 50),
        ch_pb=ipl(0, 20),
        susp_deadline=ipl(-1, 60),
    )
    upd = (bpl(dense), ipl(0, 4), ipl(0, 50), ipl(0, n), ipl(0, 50))
    union = jnp.asarray(
        rng.integers(0, 2**32, (n, toolkit.packed_width(n)), dtype=np.uint32)
    )
    return st, upd, union


def _reference(st, upd, now, dl):
    """Straight numpy transliteration of engine._apply_updates + the
    caller-side deadline stamp (the classic phase code)."""
    recv, us, ui, usrc, usi = (np.asarray(x) for x in upd)
    n = recv.shape[0]
    node = np.arange(n)[:, None]
    subject = np.arange(n)[None, :]
    is_self = node == subject
    c_s, c_i = np.asarray(st.status), np.asarray(st.inc)
    refute = recv & is_self & ((us == SUSPECT) | (us == FAULTY))
    eff_s = np.where(refute, ALIVE, us)
    eff_i = np.where(refute, now, ui)
    alive_ov = (eff_s == ALIVE) & (eff_i > c_i)
    suspect_ov = (eff_s == SUSPECT) & (
        ((c_s == SUSPECT) & (eff_i > c_i))
        | ((c_s == FAULTY) & (eff_i > c_i))
        | ((c_s == ALIVE) & (eff_i >= c_i))
    )
    faulty_ov = (eff_s == FAULTY) & (
        ((c_s == SUSPECT) & (eff_i >= c_i))
        | ((c_s == FAULTY) & (eff_i > c_i))
        | ((c_s == ALIVE) & (eff_i >= c_i))
    )
    leave_ov = (eff_s == LEAVE) & (c_s != LEAVE) & (eff_i >= c_i)
    new_member = recv & ~np.asarray(st.known)
    gate = recv & (
        refute | new_member | alive_ov | suspect_ov | faulty_ov | leave_ov
    )
    status = np.where(gate, eff_s, c_s)
    inc = np.where(gate, eff_i, c_i)
    start = gate & (status == SUSPECT) & ~is_self
    stop = gate & (status != SUSPECT)
    susp = np.where(stop, -1, np.asarray(st.susp_deadline))
    susp = np.where(start, dl, susp)
    out = dict(
        known=np.asarray(st.known) | new_member,
        status=status,
        inc=inc,
        ch_active=np.asarray(st.ch_active) | gate,
        ch_status=np.where(gate, status, np.asarray(st.ch_status)),
        ch_inc=np.where(gate, inc, np.asarray(st.ch_inc)),
        ch_source=np.where(gate, usrc, np.asarray(st.ch_source)),
        ch_source_inc=np.where(
            gate, usi, np.asarray(st.ch_source_inc)
        ),
        ch_pb=np.where(gate, 0, np.asarray(st.ch_pb)),
        susp_deadline=susp,
    )
    return out, gate, refute


@pytest.mark.parametrize("impl", ["xla", "pallas"])
@pytest.mark.parametrize("n", [16, 37, 64])
def test_matches_host_reference(impl, n):
    st, upd, union = _fixture(n, seed=n)
    now, dl = jnp.int32(51), jnp.int32(77)
    ref, gate, refute = _reference(st, upd, 51, 77)
    out = fa.apply_updates(
        st, *upd, now, dl, union, impl=impl,
        want_masks=True, want_count=True, want_refute=True,
    )
    for f in fa.ApplyState._fields:
        assert np.array_equal(
            np.asarray(getattr(out.state, f)), ref[f]
        ), (impl, f)
    assert np.array_equal(np.asarray(out.applied), gate)
    assert np.array_equal(np.asarray(out.applied_rows), gate.any(1))
    assert int(out.applied_count) == int(gate.sum())
    assert np.array_equal(
        np.asarray(out.refute_diag), np.diagonal(refute)
    )
    # packed union accumulates exactly: popcount == |old ∪ gate|
    want = np.asarray(union) | np.asarray(
        toolkit.pack_bool_rows(jnp.asarray(gate))
    )
    assert np.array_equal(np.asarray(out.union), want)


def test_pallas_twin_bitwise_equal():
    """The TWIN_REGISTRY contract: kernel vs twin bitwise across every
    output, via the shared toolkit gate helper."""
    st, upd, union = _fixture(48, seed=3)

    def op(st, *upd, impl):
        return fa.apply_updates(
            st, *upd, jnp.int32(9), jnp.int32(30), union,
            impl=impl, want_masks=True, want_count=True,
        )

    toolkit.assert_twin_bitwise(op, (st,) + upd)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_output_flag_variants(impl):
    st, upd, _ = _fixture(32, seed=7)
    out = fa.apply_updates(
        st, *upd, jnp.int32(5), jnp.int32(11), None, impl=impl,
        want_masks=False, want_count=False, want_refute=False,
    )
    assert out.union is None
    assert out.applied is None
    assert out.applied_count is None
    assert out.refute_diag is None
    full = fa.apply_updates(
        st, *upd, jnp.int32(5), jnp.int32(11), None, impl=impl,
        want_masks=True, want_count=True,
    )
    # the lean variant's state planes and rows match the full variant's
    for f in fa.ApplyState._fields:
        assert np.array_equal(
            np.asarray(getattr(out.state, f)),
            np.asarray(getattr(full.state, f)),
        ), f
    assert np.array_equal(
        np.asarray(out.applied_rows), np.asarray(full.applied_rows)
    )


def test_arg_validation():
    st, upd, union = _fixture(16)
    with pytest.raises(ValueError, match="square"):
        bad = st._replace(
            **{f: jnp.zeros((16, 8), getattr(st, f).dtype)
               for f in fa.ApplyState._fields}
        )
        fa.apply_updates(bad, *upd, jnp.int32(1), jnp.int32(2))
    with pytest.raises(ValueError, match="packed"):
        fa.apply_updates(
            st, *upd, jnp.int32(1), jnp.int32(2),
            jnp.zeros((16, 16), jnp.uint32),
        )
    with pytest.raises(ValueError, match="impl"):
        fa.apply_updates(
            st, *upd, jnp.int32(1), jnp.int32(2), impl="bogus"
        )


def test_overrides_is_engines_table():
    """engine._overrides must BE this module's table (single source)."""
    from ringpop_tpu.models.sim import engine

    assert engine._overrides is fa.overrides


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_tiny_n_packed_width_collision(impl):
    """n=4: packed_width(4) == 1 but a 4-wide meta/union could collide
    with n in width-based plane inference — the explicit in_planes
    flags keep the scaffold exact at any n (review-found regression)."""
    st, upd, union = _fixture(4, seed=2)
    now, dl = jnp.int32(3), jnp.int32(9)
    ref, gate, refute = _reference(st, upd, 3, 9)
    out = fa.apply_updates(
        st, *upd, now, dl, union, impl=impl, want_masks=True,
        want_count=True,
    )
    for f in fa.ApplyState._fields:
        assert np.array_equal(
            np.asarray(getattr(out.state, f)), ref[f]
        ), (impl, f)
    want = np.asarray(union) | np.asarray(
        toolkit.pack_bool_rows(jnp.asarray(gate))
    )
    assert np.array_equal(np.asarray(out.union), want)
