"""Device log2-bucket histogram primitives (ops/histogram.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.ops import histogram as hg


def test_bucket_index_matches_numpy_reference_on_edges():
    edges = [0, 1, 2, 3, 4, 7, 8, 15, 16, 2**20 - 1, 2**20, 2**30, 2**31 - 1]
    vals = jnp.asarray(edges, jnp.int32)
    got = np.asarray(hg.bucket_index(vals))
    want = hg.bucket_index_np(edges)
    assert (got == want).all(), (got, want)
    # spot the closed-form: 0 -> 0, v>0 -> floor(log2)+1
    assert got[0] == 0 and got[1] == 1 and got[2] == 2 and got[3] == 2
    assert got[-1] == hg.NBUCKETS - 1


def test_bucket_index_matches_numpy_reference_randomized():
    rng = np.random.default_rng(0)
    # log-uniform coverage of the whole int32 range
    v = np.unique(
        (2.0 ** (rng.random(4096) * 31)).astype(np.int64) - 1
    ).astype(np.int32)
    got = np.asarray(hg.bucket_index(jnp.asarray(v)))
    assert (got == hg.bucket_index_np(v)).all()


def test_bucket_bounds_partition_the_int32_range():
    lo_prev = -1
    for b in range(hg.NBUCKETS):
        lo, hi = hg.bucket_lo(b), hg.bucket_hi(b)
        assert lo <= hi
        assert lo == lo_prev + 1  # contiguous, gap-free
        lo_prev = hi
    assert hg.bucket_hi(hg.NBUCKETS - 1) == 2**31 - 1


def test_record_masked_adds_and_duplicate_buckets_accumulate():
    h = hg.init(2)
    vals = jnp.asarray([0, 1, 1, 3, 8, -5, 100], jnp.int32)
    mask = jnp.asarray([True, True, True, True, True, True, False])
    h = hg.record(h, 1, vals, mask)
    out = np.asarray(h)
    assert out[0].sum() == 0  # untouched track
    assert out[1].sum() == 5  # negative + masked-out lanes dropped
    assert out[1][0] == 1  # value 0
    assert out[1][1] == 2  # duplicate 1s accumulate
    assert out[1][2] == 1  # value 3
    assert out[1][4] == 1  # value 8
    # accumulation across calls
    h = hg.record(h, 1, vals, mask)
    assert np.asarray(h)[1].sum() == 10


def test_record_count_records_one_observation():
    h = hg.init(1)
    h = hg.record_count(h, 0, jnp.int32(5))
    h = hg.record_count(h, 0, jnp.int32(0))
    out = np.asarray(h)[0]
    assert out.sum() == 2 and out[0] == 1 and out[3] == 1


def test_record_is_scan_and_jit_safe():
    def body(h, v):
        return hg.record(h, 0, v, v >= 0), None

    vals = jnp.asarray(
        np.random.default_rng(1).integers(-4, 100, size=(16, 8)), jnp.int32
    )
    h, _ = jax.jit(lambda h, v: jax.lax.scan(body, h, v))(hg.init(1), vals)
    want = np.zeros(hg.NBUCKETS, np.int64)
    flat = np.asarray(vals).reshape(-1)
    for b in hg.bucket_index_np(flat[flat >= 0]):
        want[b] += 1
    assert (np.asarray(h)[0] == want).all()


def test_record_rejects_nothing_silently_counts_are_uint32():
    assert hg.init(3).dtype == jnp.uint32


@pytest.mark.parametrize("shape", [(4, 4), (3, 2, 2)])
def test_record_flattens_any_mask_shape(shape):
    vals = jnp.ones(shape, jnp.int32)
    h = hg.record(hg.init(1), 0, vals, jnp.ones(shape, bool))
    assert int(np.asarray(h)[0][1]) == int(np.prod(shape))


def test_vmapped_batch_records_and_drains():
    """The vmapped-driver shape: B instances each carrying their own
    [H, NB] counters through a scanned recorder, drained as [B, H, NB]
    via obs.histograms.summarize_batched — aggregate == pooled counts,
    per-instance == each instance's own observations."""
    from ringpop_tpu.obs import histograms as oh

    b, t = 4, 16
    rng = np.random.default_rng(3)
    vals = jnp.asarray(
        rng.integers(0, 500, size=(b, t, 8)), jnp.int32
    )  # per-instance observation streams

    def one_instance(stream):  # [T, 8] -> [1, NB]
        def body(h, v):
            return hg.record(h, 0, v, v >= 0), None

        h, _ = jax.lax.scan(body, hg.init(1), stream)
        return h

    hists = jax.jit(jax.vmap(one_instance))(vals)  # [B, 1, NB]
    assert hists.shape == (b, 1, hg.NBUCKETS)
    agg = oh.summarize_batched(hists, ("x",), aggregate=True)
    assert agg["x"]["count"] == b * t * 8
    per = oh.summarize_batched(hists, ("x",), aggregate=False)
    for i, inst in enumerate(per):
        assert inst["x"]["count"] == t * 8
        # per-instance p50 buckets match a host recount of that instance
        want = np.zeros(hg.NBUCKETS, np.int64)
        for bb in hg.bucket_index_np(np.asarray(vals[i]).reshape(-1)):
            want[bb] += 1
        assert (np.asarray(hists[i][0]) == want).all()
