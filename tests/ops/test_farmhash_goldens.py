"""Externally sourced FarmHash32 golden vectors.

Round-1 weakness: all four in-repo FarmHash implementations were written by
the same hand from the same reading of the algorithm, so a shared
misreading would pass every cross-check.  These goldens break that cycle:
each ``(input, hash)`` pair below was produced by Google's own compiled
``farmhashmk::Hash32`` (the symbol ``_ZN10farmhashmk6Hash32EPKcm`` exported
by tensorflow's bundled ``libtensorflow_framework.so``, built from the
upstream https://github.com/google/farmhash source) — the same farmhashmk
algorithm the npm ``farmhash@0.2`` addon dispatches to on machines without
SSE4.1/AESNI, i.e. the hash the reference calls at lib/ring/index.js:21 and
lib/membership/index.js:24.

When the tensorflow library is present we additionally fuzz live against it
(1k random strings across every length class); when absent, the hardcoded
vectors still pin every branch of the algorithm (0-4, 5-12, 13-24, one
block, multi-block, >255, >1024).
"""

import ctypes
import glob
import random

import numpy as np
import pytest

from ringpop_tpu.ops import farmhash32 as fh
from ringpop_tpu.ops import native

# (input bytes, farmhashmk::Hash32) — generated once from Google's compiled
# library; see module docstring.  Inputs cover every length-class branch and
# the address / checksum-string shapes ringpop actually hashes.
GOLDENS = [
    (b"", 0xDC56D17A),
    (b"a", 0x3C973D4D),
    (b"ab", 0x417330FD),
    (b"abc", 0x2F635EC7),
    (b"abcd", 0x98B51E95),
    (b"abcde", 0xA3F366AC),
    (b"hello world", 0x19A7581A),
    (b"127.0.0.1:3000", 0x38F33445),
    (b"127.0.0.1:300000", 0x27D3A8AD),
    (b"10.30.8.26:20600", 0x9DD564C9),
    (b"127.0.0.1:3000;alive;1470000000000", 0xF59B50DB),
    (
        b"10.0.0.1:3000;suspect;1470000000001;"
        b"10.0.0.2:3000;alive;1470000000002",
        0x8F288648,
    ),
    (bytes(range(25)), 0x2B1014AD),
    (bytes(range(48)), 0x40B54C18),
    (bytes(range(97)), 0x23C004E8),
    (b"x" * 13, 0xA4128D93),
    (b"x" * 24, 0x90B1E609),
    (b"x" * 64, 0x6CC6B60B),
    (b"q" * 255, 0x2AB28F77),
    (b"m" * 1024, 0x7E656A8D),
    (b'X. ', 0xF45214D9),
    (b'+j$ux*,', 0x45B013D2),
    (b'M>"#"Lro]n[', 0xBED68CE6),
    (b'3+7{.!`^?(ue[(l', 0xED160416),
    (b'v+aj%Bg(rF]MB?s9Zcu', 0x43D55ED7),
    (b'"a) J2z\\tP5&)k_4)g;2#L.', 0x4C0194A2),
    (b'c2uGZ%UCt%6B3F3[%hQL_Kj[\\%\\', 0x14A33C88),
    (b'l5X}bXEC/7UW/c-^Pt@r8L-yy4jB3|I', 0x849E41F0),
    (b"Y|)*R;&D$<`+yHGZ(j@)xV9,R8zZ`>N:ayU6j:F'", 0x0DD27E93),
    (
        b"Md3_f\\J10&o52e({I5 uv'q+2;%WR~I:vPCdpFVHwi3d+ACTShCc.yP",
        0x2463174E,
    ),
    (
        b'C;F{kR&LX=^5PG )]RFVw]7Sp]4DkOslL:5bhZu\\t#|[t-#N\\(1kJLEFwwjJhEh8'
        b'aC)dxm:KaJIZB*ck',
        0x6EF24F78,
    ),
    (
        b'jf/?@O1#R$u%:u3HbMWa(GAy^j<L`*s"wjJh=4]_wv1doo(2d?x5``xRI0zghdnl'
        b'Y%O(OvT%mn)H=o9LbxPk_&#Y*EVK2^vs>x#~MkOU6)q";9mof}2`0v@s&l[Nl}OD'
        b'R',
        0x98AC21E6,
    ),
]


def _tf_farmhashmk():
    """ctypes handle to Google's compiled farmhashmk::Hash32, if present."""
    pats = [
        "/opt/venv/lib/python*/site-packages/tensorflow/"
        "libtensorflow_framework.so*",
        "/usr/lib/python*/site-packages/tensorflow/"
        "libtensorflow_framework.so*",
    ]
    for pat in pats:
        for path in sorted(glob.glob(pat)):
            try:
                lib = ctypes.CDLL(path)
                fn = getattr(lib, "_ZN10farmhashmk6Hash32EPKcm")
                fn.restype = ctypes.c_uint32
                fn.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
                if fn(b"", 0) == 0xDC56D17A:
                    return fn
            except (OSError, AttributeError):
                continue
    return None


def test_scalar_matches_goldens():
    for s, want in GOLDENS:
        assert fh.hash32(s) == want, (s[:40], hex(fh.hash32(s)), hex(want))


def test_numpy_batch_matches_goldens():
    strs = [s for s, _ in GOLDENS]
    mat, lens = fh.encode_rows(strs)
    got = fh.hash32_batch(mat, lens)
    want = np.array([h for _, h in GOLDENS], dtype=np.uint32)
    np.testing.assert_array_equal(got, want)


@pytest.mark.skipif(not native.available(), reason="native toolchain unavailable")
def test_native_matches_goldens():
    for s, want in GOLDENS:
        assert native.hash32(s) == want, s[:40]


def test_jax_matches_goldens():
    from ringpop_tpu.ops import jax_farmhash

    got = jax_farmhash.hash32_strings_device([s for s, _ in GOLDENS])
    want = np.array([h for _, h in GOLDENS], dtype=np.uint32)
    np.testing.assert_array_equal(got.astype(np.uint32), want)


def test_live_fuzz_against_google_library():
    oracle = _tf_farmhashmk()
    if oracle is None:
        pytest.skip("tensorflow farmhashmk library not present")
    rng = random.Random(0x60061E)
    strs = []
    for n in list(range(0, 80)) + [100, 128, 200, 255, 256, 333, 1000, 2048]:
        for _ in range(12 if n < 80 else 3):
            strs.append(bytes(rng.randrange(256) for _ in range(n)))
    mat, lens = fh.encode_rows(strs)
    batch = fh.hash32_batch(mat, lens)
    for i, s in enumerate(strs):
        want = oracle(s, len(s))
        assert fh.hash32(s) == want, (len(s), s[:24])
        assert int(batch[i]) == want, (len(s), s[:24])


# ---------------------------------------------------------------------------
# Variant analysis: which Hash32 does a real reference deployment compute?
#
# Google farmhash's Hash32 entry dispatches AT COMPILE TIME on
# __SSE4_1__/__AES__: no flags -> farmhashmk (portable), -msse4.1 ->
# farmhashsa, -msse4.1 -maes -> farmhashsu.  node-gyp's default Linux
# x86-64 flags target the SSE2 baseline (no -msse4.1 / -march=native), so
# the npm farmhash@0.2 addon the reference depends on
# (package.json:34, lib/ring/index.js:21) compiles the PORTABLE
# farmhashmk dispatch — the variant this framework implements and pins.
#
# Measured against Google's own compiled library (farmhashsa::Hash32 from
# tensorflow's bundle): farmhashsa falls back to farmhashmk for EVERY
# input <= 24 bytes and first diverges at 25 bytes.  Consequence: ring
# replica-point hashes ("host:port" + index, < 25 bytes for typical
# addresses) are IDENTICAL under either build; only long inputs — the
# membership checksum strings — would differ on a hypothetical
# -msse4.1-built addon.  These tests pin both facts.
# ---------------------------------------------------------------------------

# (input, farmhashmk::Hash32, farmhashsa::Hash32) for >24-byte inputs —
# generated from Google's compiled library; documents the divergence this
# framework does NOT follow (we implement the addon's portable dispatch).
SA_DIVERGENCE_GOLDENS = [
    (b"x" * 25, 0x02214D9D, 0x29EA069D),
    (b"x" * 64, 0x6CC6B60B, 0x99C1B57C),
    (b"127.0.0.1:3000;alive;1470000000000", 0xF59B50DB, 0x941A441A),
    (bytes(range(25)), 0x2B1014AD, 0x60B58852),
    (bytes(range(100)), 0x04BCE9AE, 0xEE696E8A),
    (b"10.0.0.1:3000;suspect;1470000000001;" * 40, 0x711C4BB3, 0xAC54E48B),
]


def _tf_farmhashsa():
    """ctypes handle to Google's compiled farmhashsa::Hash32, if present."""
    pats = [
        "/opt/venv/lib/python*/site-packages/tensorflow/"
        "libtensorflow_framework.so*",
        "/usr/lib/python*/site-packages/tensorflow/"
        "libtensorflow_framework.so*",
    ]
    for pat in pats:
        for path in sorted(glob.glob(pat)):
            try:
                lib = ctypes.CDLL(path)
                fn = getattr(lib, "_ZN10farmhashsa6Hash32EPKcm")
                fn.restype = ctypes.c_uint32
                fn.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
                if fn(b"", 0) == 0xDC56D17A:
                    return fn
            except (OSError, AttributeError):
                continue
    return None


def test_sa_variant_divergence_goldens():
    """Our implementation is farmhashmk everywhere — including the >24-byte
    range where an SSE4.1-built addon (farmhashsa) would differ."""
    for data, want_mk, want_sa in SA_DIVERGENCE_GOLDENS:
        got = fh.hash32(data)
        assert got == want_mk, (data[:20], hex(got))
        assert want_mk != want_sa  # the divergence is real above 24 bytes


def test_sa_falls_back_to_mk_below_25_bytes():
    """Ring replica-point hashes are variant-independent: farmhashsa
    defers to farmhashmk for every input <= 24 bytes, so short strings
    (addresses + replica indices) hash identically under either build of
    the npm addon.  Verified live against Google's compiled farmhashsa
    when available."""
    sa = _tf_farmhashsa()
    if sa is None:
        pytest.skip("tensorflow farmhash library not present")
    rng = random.Random(0xFA11BACC)
    for length in range(0, 25):
        for _ in range(40):
            data = bytes(rng.randrange(256) for _ in range(length))
            assert sa(data, length) == fh.hash32(data)
    # and divergence begins immediately after the fallback range
    for data, want_mk, want_sa in SA_DIVERGENCE_GOLDENS:
        assert sa(data, len(data)) == want_sa
