"""In-jit JAX FarmHash32 vs the numpy/scalar oracles."""

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.ops import farmhash32 as fh
from ringpop_tpu.ops import jax_farmhash as jfh
from tests.ops.test_farmhash32 import STRINGS


def test_jax_matches_oracle_all_length_classes():
    mat, lens = fh.encode_rows(STRINGS)
    got = jfh.hash32_strings_device(STRINGS)
    want = fh.hash32_batch(mat, lens)
    bad = [
        (i, STRINGS[i][:40], int(got[i]), int(want[i]))
        for i in range(len(STRINGS))
        if got[i] != want[i]
    ]
    assert not bad, bad[:5]


def test_jax_hash_under_outer_jit():
    # the kernel must compose inside larger jitted programs
    mat, lens = fh.encode_rows([b"127.0.0.1:%d" % (3000 + i) for i in range(64)])

    @jax.jit
    def f(m, l):
        return jfh.hash32_rows(m, l).sum()

    expected = int(fh.hash32_batch(mat, lens).astype(np.uint64).sum() & 0xFFFFFFFFFFFFFFFF)
    got = int(np.uint64(f(jnp.asarray(mat), jnp.asarray(lens))))
    assert got == expected


def test_jax_hash_under_vmap():
    # per-node checksum batches vmap over a leading cluster axis
    groups = [
        [b"127.0.0.1:3000", b"hello world, hello world, hello!"],
        [b"127.0.0.1:3001", b"0123456789abcdefghijk"],
    ]
    mats, lens = [], []
    for g in groups:
        m, l = fh.encode_rows(g, pad_to=40)
        mats.append(m[:, :40])
        lens.append(l)
    mats = jnp.asarray(np.stack(mats))
    lens = jnp.asarray(np.stack(lens))
    got = np.asarray(jax.vmap(jfh.hash32_rows)(mats, lens))
    for gi, g in enumerate(groups):
        for si, s in enumerate(g):
            assert int(got[gi, si]) == fh.hash32(s)


def test_pack_words_roundtrip():
    rng = np.random.default_rng(7)
    mat = rng.integers(0, 256, size=(5, 23), dtype=np.uint8)
    words = np.asarray(jfh.pack_words(jnp.asarray(mat)))
    padded = np.pad(mat, ((0, 0), (0, 1)))
    want = padded.reshape(5, -1, 4).astype(np.uint32)
    want = want[..., 0] | (want[..., 1] << 8) | (want[..., 2] << 16) | (want[..., 3] << 24)
    np.testing.assert_array_equal(words, want)


def test_empty_row_golden():
    mat = jnp.zeros((1, 8), jnp.uint8)
    lens = jnp.zeros((1,), jnp.int32)
    assert int(jfh.hash32_rows_jit(mat, lens)[0]) == 0xDC56D17A


def test_pallas_block_loop_matches_scan(monkeypatch):
    """The Pallas TPU kernel for the 20-byte block loop (interpret mode off
    TPU) produces the same bits as the lax.scan lowering and the goldens."""
    import numpy as np

    from ringpop_tpu.ops import farmhash32 as fh
    from ringpop_tpu.ops import jax_farmhash as jfh

    strs = [b"x" * n for n in (25, 44, 45, 64, 100, 333)] + [
        bytes(range(97)),
        b"q" * 255,
        b"addr-%d" % 7 * 40,
    ]
    mat, lens = fh.encode_rows(strs)
    want = fh.hash32_batch(mat, lens)
    monkeypatch.setenv("RINGPOP_TPU_PALLAS", "1")
    got = np.asarray(jfh.hash32_strings_device(strs)).astype(np.uint32)
    np.testing.assert_array_equal(got, want)
    # golden pin (farmhashmk of 'q'*255 from the compiled Google library)
    assert int(fh.hash32(b"q" * 255)) == 0x2AB28F77


def test_pallas_nogrid_matches_scan():
    """The GRIDLESS Pallas block loop (the axon-tunnel workaround: its
    compile helper 500s on any grid'd kernel, PALLAS_BISECT.json) is
    bit-exact against the scan lowering, including partially-active rows
    and iteration counts that don't divide the chunk."""
    import numpy as np

    from ringpop_tpu.ops import jax_farmhash as jfh

    rng = np.random.default_rng(3)
    for rows, width in ((5, 25), (33, 444), (130, 2048)):
        mat = rng.integers(0, 256, size=(rows, width), dtype=np.uint8)
        lens = rng.integers(0, width + 1, size=(rows,)).astype(np.int32)
        a = np.asarray(jfh.hash32_rows(mat, lens, impl="scan"))
        b = np.asarray(jfh.hash32_rows(mat, lens, impl="pallas_nogrid"))
        np.testing.assert_array_equal(a, b)


def test_pallas_nogrid_row_tiling_bitexact():
    """Beyond ~420k rows even a chunk=1 slab exceeds the VMEM budget, so
    block_loop_nogrid tiles the row/sublane axis too (ADVICE r4, medium).
    The tiled program must be bit-identical to the untiled one — exercised
    at a small shape by shrinking the budget."""
    import numpy as np

    from ringpop_tpu.ops import pallas_farmhash as pf

    rng = np.random.default_rng(11)
    B, I = 4000, 3  # pads to s=32 sublanes; tiny budget forces s_t=8, rt=4
    h0, g0, f0 = (
        rng.integers(0, 2**32, size=B, dtype=np.uint32) for _ in range(3)
    )
    blocks = rng.integers(0, 2**32, size=(B, I, 5), dtype=np.uint32)
    iters = rng.integers(0, I + 1, size=B).astype(np.int32)

    plain = pf.block_loop_nogrid(
        h0, g0, f0, blocks, iters, interpret=True
    )
    tiled = pf.block_loop_nogrid(
        h0, g0, f0, blocks, iters, interpret=True,
        vmem_budget=5 * 8 * 128 * 4,  # one chunk=1, s_t=8 slab exactly
    )
    for a, b in zip(plain, tiled):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
