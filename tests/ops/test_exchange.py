"""Fused push-pull exchange op (ops.exchange): bit-exactness gates.

The op's contract is EXACT mod-2^32 arithmetic — the Pallas megakernel,
the pure-XLA twin, and the engine's inline OR + ``_bit_delta_sum`` path
must all agree bit-for-bit (that equality is the round-10 acceptance
gate).  Every test here pins one implementation against another or
against an independent host-side reference.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from ringpop_tpu.ops import exchange as ex


def _mk(n, w, seed):
    rng = np.random.default_rng(seed)

    def u32(shape):
        return rng.integers(0, 2**32, size=shape, dtype=np.uint32)

    return u32((n, w)), u32((n, w)), u32((n, w)), u32((w * 32,))


def _ref(heard, pulled, pushed, delta):
    """Independent host reference: python ints, explicit mod-2^32."""
    new = heard | pulled | pushed
    diff = new ^ heard
    n, w = heard.shape
    acc = np.zeros(n, np.uint32)
    cnt = np.zeros(n, np.int64)
    for i in range(n):
        for wd in range(w):
            d = int(diff[i, wd])
            for b in range(32):
                if (d >> b) & 1:
                    acc[i] = np.uint32(
                        (int(acc[i]) + int(delta[wd * 32 + b]))
                        & 0xFFFFFFFF
                    )
                    cnt[i] += 1
    return new, acc, cnt


@pytest.mark.parametrize(
    "n,w", [(1, 1), (5, 2), (64, 4), (130, 3)]
)
def test_xla_matches_host_reference(n, w):
    heard, pulled, pushed, delta = _mk(n, w, seed=n * 31 + w)
    want_new, want_acc, want_cnt = _ref(heard, pulled, pushed, delta)
    got_new, got_acc, got_cnt = ex.exchange(
        jnp.asarray(heard),
        jnp.asarray(pulled),
        jnp.asarray(pushed),
        jnp.asarray(delta),
        impl="xla",
    )
    assert (np.asarray(got_new) == want_new).all()
    assert (np.asarray(got_acc) == want_acc).all()
    assert (np.asarray(got_cnt) == want_cnt).all()


def test_xla_chunking_is_invisible():
    """Row chunking (incl. the padded ragged tail) must not change any
    output — padded rows contribute nothing."""
    heard, pulled, pushed, delta = _mk(67, 4, seed=9)
    args = tuple(map(jnp.asarray, (heard, pulled, pushed, delta)))
    base = ex.exchange_xla(*args)
    for chunk in (1, 8, 64, 67, 1024):
        out = ex.exchange_xla(*args, _chunk_rows=chunk)
        for a, b in zip(base, out):
            assert (np.asarray(a) == np.asarray(b)).all(), chunk


@pytest.mark.parametrize("n,w", [(1, 2), (64, 4), (1025, 4)])
def test_pallas_interpret_matches_xla_twin(n, w):
    """The gridless kernel (interpret mode off-TPU) must agree with the
    pure-XLA twin bit-for-bit — including ragged N padded up to the
    sublane tile."""
    heard, pulled, pushed, delta = _mk(n, w, seed=n + w)
    args = tuple(map(jnp.asarray, (heard, pulled, pushed, delta)))
    want = ex.exchange(*args, impl="xla")
    got = ex.exchange(*args, impl="pallas", interpret=True)
    for a, b in zip(got, want):
        assert (np.asarray(a) == np.asarray(b)).all()


def test_pallas_vmem_tiling_path():
    """A tiny VMEM budget forces the outer lax.scan over row tiles; the
    multi-tile path must still be bit-exact.  (128 KiB sits above the
    w=2 single-sublane floor the guard enforces but below the
    whole-problem tile, so the shrink loop lands on 2 row tiles.)"""
    n, w = 2100, 2
    heard, pulled, pushed, delta = _mk(n, w, seed=3)
    args = tuple(map(jnp.asarray, (heard, pulled, pushed, delta)))
    want = ex.exchange(*args, impl="xla")
    got = ex.exchange(
        *args, impl="pallas", interpret=True, vmem_budget=128 * 1024
    )
    for a, b in zip(got, want):
        assert (np.asarray(a) == np.asarray(b)).all()


def test_delta_matches_engine_bit_delta_sum():
    """The op's row delta must equal the engine's MXU-limb reduction
    (``_bit_delta_sum``) on the same new-bit mask — the equality the
    fused tick's checksum correctness rests on (adversarial deltas to
    force uint32 wrap)."""
    from ringpop_tpu.models.sim import engine_scalable as es

    n, w = 96, 5
    heard, pulled, pushed, delta = _mk(n, w, seed=12)
    delta[:] = np.uint32(0xF0000000) + (delta >> 4)  # force wraps
    new = heard | pulled | pushed
    diff = jnp.asarray(new ^ heard)
    want = np.asarray(
        es._bit_delta_sum(diff, jnp.asarray(delta), w * 32)
    )
    _, got_acc, _ = ex.exchange(
        jnp.asarray(heard),
        jnp.asarray(pulled),
        jnp.asarray(pushed),
        jnp.asarray(delta),
        impl="xla",
    )
    assert (np.asarray(got_acc) == want).all()


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_want_counts_false_drops_only_the_count(impl):
    """The engine's hot path (want_counts=False) must return the SAME
    mask and delta with new_bits=None — the popcount just disappears."""
    heard, pulled, pushed, delta = _mk(70, 3, seed=21)
    args = tuple(map(jnp.asarray, (heard, pulled, pushed, delta)))
    kw = {"interpret": True} if impl == "pallas" else {}
    full = ex.exchange(*args, impl=impl, **kw)
    lean = ex.exchange(*args, impl=impl, want_counts=False, **kw)
    assert lean[2] is None
    for a, b in zip(lean[:2], full[:2]):
        assert (np.asarray(a) == np.asarray(b)).all()


def test_pallas_vmem_floor_raises_toward_xla():
    """When the lane-broadcast delta table alone exceeds the VMEM budget
    (wide-U masks), the kernel must refuse loudly and point at the XLA
    twin — not issue a program that OOMs VMEM on chip."""
    heard, pulled, pushed, delta = _mk(8, 256, seed=2)  # u=8192
    with pytest.raises(ValueError, match="use impl='xla'"):
        ex.exchange(
            *map(jnp.asarray, (heard, pulled, pushed, delta)),
            impl="pallas",
            interpret=True,
        )


def test_shape_mismatch_rejected():
    heard, pulled, pushed, delta = _mk(8, 4, seed=0)
    with pytest.raises(AssertionError):
        ex.exchange(
            jnp.asarray(heard),
            jnp.asarray(pulled),
            jnp.asarray(pushed),
            jnp.asarray(delta[:96]),  # table shorter than the mask
            impl="xla",
        )
    with pytest.raises(ValueError):
        ex.exchange(
            jnp.asarray(heard),
            jnp.asarray(pulled),
            jnp.asarray(pushed),
            jnp.asarray(delta),
            impl="nope",
        )
