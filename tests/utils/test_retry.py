"""retry_compile_helper: backoff retries ONLY for axon remote-compile
helper 500s (the transient failure that cost round 3 its parity-mode
headline); every other error propagates immediately."""

import pytest

from ringpop_tpu.utils.util import retry_compile_helper


def test_matching_error_retries_then_succeeds():
    calls = []

    def fn():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError(
                "INTERNAL: remote_compile: HTTP 500: tpu_compile_helper"
            )
        return "ok"

    assert retry_compile_helper(fn, backoffs=(0, 0, 0)) == "ok"
    assert len(calls) == 3


def test_non_matching_error_raises_immediately():
    calls = []

    def fn():
        calls.append(1)
        raise ValueError("shape mismatch")

    with pytest.raises(ValueError):
        retry_compile_helper(fn, backoffs=(0, 0, 0))
    assert len(calls) == 1


def test_exhaustion_reraises_last_matching_error():
    def fn():
        raise RuntimeError("tpu_compile_helper subprocess exit code 1")

    with pytest.raises(RuntimeError, match="tpu_compile_helper"):
        retry_compile_helper(fn, backoffs=(0, 0))


def test_args_forwarded():
    def fn(a, b=0):
        return a + b

    assert retry_compile_helper(fn, 2, b=3, backoffs=(0,)) == 5
