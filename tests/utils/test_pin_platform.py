"""Forced-host-device setup goes through ONE place (round 14, ISSUE 10
satellite): utils.util.force_host_device_count spells the device-count
flag; pin_cpu_platform, tests/conftest.py, bench.py's mesh phase and
tpu_measure.py's weak-scaling fallback all route through it."""

import os
import subprocess
import sys
from pathlib import Path

import pytest

from ringpop_tpu.utils import util

REPO_ROOT = Path(__file__).resolve().parents[2]


def test_force_host_device_count_env_only():
    env = {"XLA_FLAGS": "--xla_foo=1 --xla_force_host_platform_device_count=2"}
    util.force_host_device_count(8, env=env)
    flags = env["XLA_FLAGS"].split()
    # replaced, not appended — exactly one count flag, others preserved
    assert flags.count("--xla_force_host_platform_device_count=8") == 1
    assert "--xla_foo=1" in flags
    assert not any(
        f.startswith("--xla_force_host_platform_device_count=2")
        for f in flags
    )
    assert env["JAX_NUM_CPU_DEVICES"] == "8"
    # idempotent
    util.force_host_device_count(8, env=env)
    assert env["XLA_FLAGS"].split().count(
        "--xla_force_host_platform_device_count=8"
    ) == 1
    with pytest.raises(ValueError):
        util.force_host_device_count(0, env=env)


def test_flag_spelled_in_exactly_one_place():
    """The regression the satellite asks for: no driver hand-rolls the
    flag assignment — the ``--...=N`` spelling lives in utils/util.py
    alone (read-only containment checks, like conftest's, don't spell
    the assignment)."""
    needle = "--xla_force_host_platform_device_count"
    offenders = []
    for base in ("ringpop_tpu", "benchmarks", "scripts", "tests", "."):
        root = REPO_ROOT / base
        files = (
            root.glob("*.py") if base == "." else root.rglob("*.py")
        )
        for path in files:
            if path.name == "test_pin_platform.py":
                continue
            try:
                text = path.read_text(encoding="utf-8")
            except OSError:
                continue
            if needle in text and path != REPO_ROOT / "ringpop_tpu" / "utils" / "util.py":
                offenders.append(str(path.relative_to(REPO_ROOT)))
    assert offenders == [], (
        "forced-host-device flag hand-rolled outside utils/util.py: %s"
        % offenders
    )


def test_pin_cpu_platform_subprocess_regression():
    """pin_cpu_platform(n) in a FRESH interpreter yields >= n virtual
    CPU devices — the path the multichip dryrun and the tpu_measure /
    bench forced-host fallbacks depend on."""
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "from ringpop_tpu.utils.util import pin_cpu_platform\n"
        "pin_cpu_platform(5)\n"
        "import jax\n"
        "assert jax.devices()[0].platform == 'cpu'\n"
        "assert len(jax.devices()) >= 5, jax.devices()\n"
        "print('OK', len(jax.devices()))\n" % str(REPO_ROOT)
    )
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_NUM_CPU_DEVICES", "JAX_PLATFORMS")
    }
    out = subprocess.run(
        [sys.executable, "-c", code],
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert out.stdout.startswith("OK")
