"""Smoke tests: benchmark harnesses run and emit well-formed results."""

import numpy as np
import pytest

from benchmarks.convergence_time import histogram, run_jax_sim, run_live
from benchmarks.micro import BENCHES


def test_histogram_fields():
    h = histogram([5.0, 1.0, 3.0, 2.0, 4.0])
    assert h["count"] == 5 and h["min"] == 1.0 and h["max"] == 5.0
    assert h["mean"] == 3.0 and h["median"] == 3.0
    assert h["p75"] == 4.0 and h["p99"] == 5.0
    assert histogram([]) == {"count": 0}


def test_convergence_jax_sim_single_node():
    res = run_jax_sim("single-node-failure", n=12, cycles=2, seed=0)
    assert res["histogram"]["count"] == 2
    assert res["histogram"]["min"] >= 200  # at least one protocol period


def test_convergence_jax_sim_half_cluster():
    res = run_jax_sim("half-cluster-failure", n=12, cycles=1, seed=1)
    assert res["histogram"]["count"] == 1


@pytest.mark.slow
def test_convergence_live_single_node():
    res = run_live("single-node-failure", n=5, cycles=1, seed=0)
    assert res["histogram"]["count"] == 1
    assert res["histogram"]["min"] > 0


@pytest.mark.slow
def test_bench_pinned_fallback_skips_reexec():
    """Regression: a BENCH_PINNED_FALLBACK=1 child (inherited bench-made
    CPU pin) must mark fallback='cpu' directly instead of burning the
    re-exec budget re-probing a tunnel that already exhausted it —
    attempts stays 1 and no BENCH_REEXEC_ATTEMPT round-trips happen."""
    import json
    import os
    import subprocess
    import sys

    env = dict(
        os.environ,
        BENCH_N="12",
        BENCH_TICKS="2",
        BENCH_PINNED_FALLBACK="1",
        BENCH_RETRIES="3",
        JAX_PLATFORMS="cpu",
    )
    env.pop("BENCH_REEXEC_ATTEMPT", None)
    env.pop("BENCH_ALLOW_CPU", None)
    out = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..", "..", "bench.py")],
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    result = json.loads(out.stdout.strip().splitlines()[-1])
    assert result["fallback"] == "cpu"
    assert result["platform"] == "cpu"
    assert result["attempts"] == 1  # no re-exec round-trips


@pytest.mark.parametrize("name", sorted(BENCHES))
def test_micro_bench_smoke(name):
    if name in ("hashring", "large-membership-update", "join-response-merge",
                "compute-checksum"):
        pytest.skip("heavier micro benches exercised via CLI, not CI")
    for result in BENCHES[name](True):
        assert result["value"] > 0
        assert result["unit"] == "ops/sec"
