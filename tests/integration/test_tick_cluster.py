"""tick-cluster harness: both backends drive the same command surface
(scripts/tick-cluster.js scope): convergence groups, kill/suspend/revive,
CLI node processes, generate-hosts."""

import json
import os
import subprocess
import sys
import time

import pytest

from ringpop_tpu.api.tick_cluster import (
    JaxSimBackend,
    LiveBackend,
    TickCluster,
    generate_hosts,
)

BASE_PORT = 23100  # away from other suites' ephemeral ports


def test_generate_hosts(tmp_path):
    path = str(tmp_path / "hosts.json")
    hosts = generate_hosts(path, 4, base_port=9000)
    assert hosts == ["127.0.0.1:%d" % (9000 + i) for i in range(4)]
    with open(path) as f:
        assert json.load(f) == hosts


def test_jax_sim_backend_commands():
    tc = TickCluster.create("jax-sim", 8)
    tc.start()
    ticks = tc.tick_until_converged()
    assert ticks >= 1 and tc.converged()

    out = tc.run_command("k 3")
    assert "killed" in out
    # dead node drops out of the groups; cluster reconverges around it
    for _ in range(60):
        tc.tick()
        groups = tc.checksum_groups()
        if None in groups and sum(1 for c in groups if c is not None) == 1:
            break
    groups = tc.checksum_groups()
    assert groups.get(None) == [tc.backend.hosts[3]]

    tc.run_command("K 3")  # revive: fresh state, rejoins
    for _ in range(80):
        tc.tick()
        if tc.converged() and None not in tc.checksum_groups():
            break
    assert tc.converged()

    # suspend keeps state but stops participation; resume restores it
    tc.run_command("l 2")
    tc.tick()
    assert None in tc.checksum_groups()
    tc.run_command("K 2")
    for _ in range(60):
        tc.tick()
        groups = tc.checksum_groups()
        if None not in groups and tc.converged():
            break
    assert tc.converged() and None not in tc.checksum_groups()

    display = tc.format_groups()
    assert "CONVERGED" in display


def test_jax_sim_stats_and_join():
    tc = TickCluster.create("jax-sim", 4)
    tc.start()
    tc.tick_until_converged()
    stats = tc.backend.stats_all()
    assert len(stats) == 4
    membership = stats[tc.backend.hosts[0]]["membership"]
    assert len(membership) == 4
    assert tc.run_command("j") == "join sent to all nodes"


@pytest.mark.slow
def test_live_backend_cluster(tmp_path):
    """Real processes: spawn 4 CLI nodes, converge, SIGKILL one, SIGSTOP
    another, revive both, reconverge (tick-cluster.js:351-470)."""
    tc = TickCluster.create(
        "live", 4, base_port=BASE_PORT, hosts_file=str(tmp_path / "hosts.json")
    )
    try:
        tc.start()
        for _ in range(120):
            tc.tick()
            if tc.converged() and None not in tc.checksum_groups():
                break
            time.sleep(0.05)
        assert tc.converged()

        tc.backend.kill(1)
        tc.backend.suspend(2)
        deadline = time.time() + 60
        while time.time() < deadline:
            tc.tick()
            groups = tc.checksum_groups()
            dead = set(groups.get(None, []))
            if {tc.backend.hosts[1], tc.backend.hosts[2]} <= dead:
                break
            time.sleep(0.1)
        groups = tc.checksum_groups()
        assert {tc.backend.hosts[1], tc.backend.hosts[2]} <= set(
            groups.get(None, [])
        )

        tc.backend.revive(1)  # respawn (was SIGKILLed)
        tc.backend.revive(2)  # SIGCONT (was SIGSTOPped)
        deadline = time.time() + 90
        while time.time() < deadline:
            tc.tick()
            groups = tc.checksum_groups()
            if None not in groups and tc.converged():
                break
            time.sleep(0.2)
        groups = tc.checksum_groups()
        assert None not in groups, groups
        assert tc.converged()
    finally:
        tc.destroy()


@pytest.mark.slow
def test_cli_single_node(tmp_path):
    """The CLI bin starts, bootstraps a single-node cluster, answers
    /health, and exits on SIGTERM (main.js:24-85)."""
    hosts_file = str(tmp_path / "hosts.json")
    hp = "127.0.0.1:%d" % (BASE_PORT + 50)
    generate_hosts(hosts_file, 1, base_port=BASE_PORT + 50)
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env = dict(
        os.environ,
        RINGPOP_TPU_NO_X64="1",
        JAX_PLATFORMS="cpu",
        PYTHONPATH=repo,
    )
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "ringpop_tpu.api.cli",
            "--listen",
            hp,
            "--hosts",
            hosts_file,
            "--quiet",
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
    )
    try:
        line = proc.stdout.readline().decode()
        assert json.loads(line) == {"listening": hp, "ready": True}
        from ringpop_tpu.api.client import RingpopClient

        cl = RingpopClient()
        assert cl.health(hp) == "ok"
        status = cl.admin_gossip_status(hp)
        assert status["status"] == "running"
        cl.destroy()
    finally:
        proc.terminate()
        assert proc.wait(10.0) == 0


def test_cli_requires_listen_and_hosts():
    from ringpop_tpu.api.cli import main

    assert main([]) == 1
    assert main(["--listen", "127.0.0.1:9"]) == 1


def test_jax_sim_lookup_matches_host_ring():
    """The jax-sim backend's device-ring lookup agrees with the host
    HashRing for the same member set (the /admin/lookup analog)."""
    from ringpop_tpu.models.ring.host import HashRing

    tc = TickCluster.create("jax-sim", 6)
    tc.start()
    tc.tick_until_converged()
    host_ring = HashRing()
    for hp in tc.backend.hosts:
        host_ring.add_server(hp)
    for key in ("a", "b", "key-%d" % 17, "zz-9"):
        assert tc.backend.lookup(key) == host_ring.lookup(key)
    out = tc.run_command("lookup some-key")
    assert "->" in out and out.split("-> ")[1] in tc.backend.hosts

    # after a kill disseminates, the dead node drops out of the ring view
    tc.run_command("k 2")
    victim = tc.backend.hosts[2]
    for _ in range(80):
        tc.tick()
        if all(
            tc.backend.lookup("probe-%d" % i) != victim for i in range(30)
        ):
            break
    assert all(
        tc.backend.lookup("probe-%d" % i) != victim for i in range(30)
    )


def test_scalable_backend_commands():
    """The jax-sim-scalable backend (O(N·U) engine) drives the same
    command surface: tick/kill/revive/stats/lookup at a node count the
    [N,N] backend could not host interactively."""
    import json as _json

    tc = TickCluster.create("jax-sim-scalable", 512)
    tc.start()
    tc.tick()
    assert tc.converged()  # rumor engine starts converged-alive

    out = tc.run_command("k 37")
    assert "killed" in out
    for _ in range(60):
        tc.tick()
        groups = tc.checksum_groups()
        if None in groups and sum(1 for c in groups if c is not None) == 1:
            break
    groups = tc.checksum_groups()
    assert groups.get(None) == ["node37"]

    stats = _json.loads(tc.run_command("s"))
    assert stats["cluster"]["live_nodes"] == 511
    assert stats["cluster"]["n"] == 512
    assert "ring_checksum" in stats["cluster"]

    # lookup serves from the live device ring; a key's owner is live
    out = tc.run_command("w somekey")
    owner = out.split("-> ")[1]
    assert owner.startswith("node") and owner != "node37"

    tc.run_command("K 37")
    for _ in range(80):
        tc.tick()
        if tc.converged() and None not in tc.checksum_groups():
            break
    assert tc.converged() and None not in tc.checksum_groups()
    assert "CONVERGED" in tc.format_groups()


def test_scalable_backend_lookup_excludes_dead_owner():
    """After a kill disseminates to faulty, the dead node's replica points
    leave the ring: lookups never route to it (ring rebalance)."""
    tc = TickCluster.create("jax-sim-scalable", 64)
    tc.start()
    tc.run_command("k 5")
    for _ in range(80):
        tc.tick()
        stats = tc.backend.stats_all()["cluster"]
        if stats["faulty_in_truth"] >= 1:
            break
    assert tc.backend.stats_all()["cluster"]["faulty_in_truth"] >= 1
    for i in range(50):
        owner = tc.backend.lookup("key-%d" % i)
        assert owner != "node5"
