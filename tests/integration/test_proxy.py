"""Request forwarding over a live cluster (test/integration/proxy-test.js
scope): handle-or-proxy, retries with re-lookup and reroute, keys-diverged
abort, checksum-mismatch rejection, handle_or_proxy_all grouping, and the
sk-header sharding handler (ringpop-handler.js).

Case-by-case checklist against /root/reference/test/integration/
proxy-test.js (every test name there, with its coverage here):

| reference case (line) | covered by |
|---|---|
| handleOrProxy() returns true for me (41) | test_handle_or_proxy_local_and_remote |
| handleOrProxy() proxies for not me (52) | test_handle_or_proxy_local_and_remote |
| handleOrProxyAll() proxies and handles locally (73) | test_handle_or_proxy_all_groups_by_owner, test_handle_or_proxy_all_partial_failure |
| can proxyReq() to someone (141) | test_handle_or_proxy_local_and_remote (spied proxy_req) |
| one retry (165) | test_checksum_mismatch_rejected_then_retried_to_success |
| two retries (202) | test_two_retries_then_success |
| no retries, invalid checksum (249) | test_max_retries_zero_fails_fast |
| no retries ... enforceConsistency false (286) | test_enforce_consistency_false_serves_despite_mismatch |
| exceeds max retries, errors out (326) | test_max_retries_five_exhaustion_counts_attempts |
| cleans up pending sends (364) | test_destroy_mid_retry_aborts_forwarding |
| cleans up some pending sends (405) | test_destroy_aborts_pending_send_completed_one_unaffected |
| overrides /proxy/req endpoint (443) | test_proxy_endpoint_override |
| overrides /proxy/req endpoint and fails (485) | test_proxy_endpoint_override_to_missing_endpoint_fails |
| aborts retry because keys diverge (514) | test_keys_diverged_aborts_retry, test_keys_diverged_through_full_retry_path |
| retries multiple keys w/ same dest (566) | test_retries_multiple_keys_same_dest |
| reroutes retry to local (607) | test_reroute_local_serves_in_process |
| reroutes retry to remote (649) | test_retry_reroutes_to_new_owner |
| can serialize url/headers/method/httpVersion (692-755) | test_forwarded_head_fidelity |
| will timeout after default timeout (756) | test_custom_timeout_expires_against_stuck_handler (same expiry path; the 30 s default VALUE is asserted there) |
| can serialize body (788) | test_forwarded_head_fidelity, test_proxies_big_json |
| can serialize response statusCode/headers/body (805-872) | test_response_status_and_headers_propagate |
| can handle errors differently (873) | EMPTY TODO STUB in the reference (test name with no body) |
| adds forwarding header (874) | EMPTY TODO STUB in the reference |
| does not handle MockResponse errors (875) | EMPTY TODO STUB in the reference |
| checks the checksum for response (876) | EMPTY TODO STUB in the reference |
| can send back a close event (877) | EMPTY TODO STUB in the reference |
| custom timeouts (880) | test_custom_timeout_expires_against_stuck_handler |
| handle body failures (911) | test_body_limit_enforced_and_at_limit_passes (limit path); receiver-side parse failures live at the framed-JSON transport (tests/net/test_channel.py::test_malformed_frame_closes_connection) and cannot reach the proxy layer |
| non json head is ok (932) | structurally N/A: the channel is JSON-typed end to end, a non-JSON head cannot be constructed (the reference tolerates raw tchannel arg2); the nearest behavior — head fields missing — is test_missing_head_fields_handled |
| handle tchannel failures (956) | test_two_retries_then_success (channel-level failures retried) |
| handles checksum failures (993) | test_checksum_mismatch_rejected_then_retried_to_success |
| does not crash ... closed socket (1016) | test_channel_destroy_mid_retry_aborts_forwarding |
| send on destroyed channel not allowed (1043) | test_send_on_destroyed_channel_refused_up_front |
| proxies big json (1066) | test_proxies_big_json |
"""

import pytest

from ringpop_tpu.utils import errors
from tests.lib.cluster import LiveCluster


@pytest.fixture
def cluster():
    made = []

    def make(n=3, **kw):
        c = LiveCluster(n=n, **kw)
        made.append(c)
        c.bootstrap_all()
        c.tick_until_converged()
        return c

    yield make
    for c in made:
        c.destroy_all()


def wire_echo_handlers(c):
    """Every node answers proxied requests with its own identity."""
    for rp in c.nodes:
        def handler(req, res, head, rp=rp):
            res.end(
                {"handledBy": rp.whoami(), "keys": req.get("ringpopKeys")},
            )
        rp.on("request", handler)


def key_owned_by(c, owner, tag="k"):
    """A key whose ring owner is `owner` in everyone's converged view."""
    for i in range(10000):
        key = "%s-%d" % (tag, i)
        if c.node(0).lookup(key) == owner.whoami():
            return key
    raise AssertionError("no key found for %s" % owner.whoami())


def test_handle_or_proxy_local_and_remote(cluster):
    c = cluster(n=3)
    wire_echo_handlers(c)
    sender, remote = c.node(0), c.node(1)
    local_key = key_owned_by(c, sender)
    remote_key = key_owned_by(c, remote)

    assert sender.handle_or_proxy(local_key, {"url": "/x"}) is True

    captured = {}
    orig = sender.request_proxy.proxy_req

    def spy(opts):
        res = orig(opts)
        captured.update(res)
        return res

    sender.request_proxy.proxy_req = spy
    assert sender.handle_or_proxy(remote_key, {"url": "/x"}) is False
    assert captured["body"]["handledBy"] == remote.whoami()
    assert captured["body"]["keys"] == [remote_key]


def test_handle_or_proxy_all_groups_by_owner(cluster):
    c = cluster(n=3)
    wire_echo_handlers(c)
    sender = c.node(0)
    keys = [key_owned_by(c, rp, tag="g%d" % i) for i, rp in enumerate(c.nodes)]
    results = sender.handle_or_proxy_all(keys, {"url": "/all"})
    assert len(results) == 3
    by_dest = {r["dest"]: r for r in results}
    for rp, key in zip(c.nodes, keys):
        entry = by_dest[rp.whoami()]
        assert entry["keys"] == [key]
        assert "error" not in entry
        assert entry["res"]["body"]["handledBy"] == rp.whoami()


def test_checksum_mismatch_rejected_then_retried_to_success(cluster):
    c = cluster(n=3)
    wire_echo_handlers(c)
    sender, dest = c.node(0), c.node(1)
    key = key_owned_by(c, dest)
    # destabilize the DEST's checksum so the first attempt is rejected;
    # convergence repairs it and the retry (after re-lookup) succeeds
    phantom = "127.0.0.1:19998"
    dest.membership.update(
        {
            "address": phantom,
            "status": "faulty",
            "incarnationNumber": 1,
            "source": dest.whoami(),
            "sourceIncarnationNumber": 1,
        }
    )
    stats_before = _stat_count(sender, "requestProxy.retry.attempted")

    # background convergence: the proxy retry sleeps on FakeTimers, so we
    # drive gossip from a thread while proxy_req blocks — until the
    # request completes (a fixed iteration count raced the retry
    # schedule and flaked under load)
    import threading
    import time as _time

    done = threading.Event()

    def converge():
        deadline = _time.monotonic() + 30.0
        while not done.is_set() and _time.monotonic() < deadline:
            c.tick_all()
            sender.timers.advance(2.0)
            _time.sleep(0.001)

    t = threading.Thread(target=converge, daemon=True)
    t.start()
    try:
        res = sender.proxy_req(
            {"keys": [key], "dest": dest.whoami(), "req": {"url": "/y"}}
        )
    finally:
        done.set()
    t.join(10.0)
    assert res["body"]["handledBy"] in {rp.whoami() for rp in c.nodes}
    assert (
        _stat_count(sender, "requestProxy.retry.attempted") > stats_before
    ), "first attempt should have been checksum-rejected and retried"


def test_keys_diverged_aborts_retry(cluster):
    c = cluster(n=3)
    sender = c.node(0)
    k1 = key_owned_by(c, c.node(1), tag="d1")
    k2 = key_owned_by(c, c.node(2), tag="d2")
    with pytest.raises(errors.KeysDivergedError):
        sender.request_proxy._relookup([k1, k2], c.node(1).whoami())


def test_retry_reroutes_to_new_owner(cluster):
    c = cluster(n=3)
    wire_echo_handlers(c)
    sender, old_owner = c.node(0), c.node(1)
    key = key_owned_by(c, old_owner)
    # point the first attempt at a dead address: retries re-lookup and
    # reroute to the real owner (send.js:181-208)
    dead = "127.0.0.1:1"
    res = sender.proxy_req(
        {"keys": [key], "dest": dead, "req": {"url": "/z"}}
    )
    assert res["body"]["handledBy"] == old_owner.whoami()


def test_sharding_handler_relays_by_sk(cluster):
    from ringpop_tpu.api.handler import RingpopHandler

    c = cluster(n=3)
    for rp in c.nodes:
        def app_handler(head, body, rp=rp):
            return None, {"servedBy": rp.whoami(), "echo": body}

        RingpopHandler(rp, app_handler, "/app/op").register()

    sender, other = c.node(0), c.node(2)
    sk = key_owned_by(c, other, tag="sk")
    _, body = sender.channel.request(
        sender.whoami(), "/app/op", head={"sk": sk}, body={"v": 1}
    )
    assert body["servedBy"] == other.whoami()
    assert body["echo"] == {"v": 1}

    sk_local = key_owned_by(c, sender, tag="skl")
    _, body = sender.channel.request(
        sender.whoami(), "/app/op", head={"sk": sk_local}, body={"v": 2}
    )
    assert body["servedBy"] == sender.whoami()


def _stat_count(rp, suffix):
    # NullStatsd records nothing; count via the stat-key cache side effect
    # is unreliable — attach a counting statsd instead
    return getattr(rp, "_test_counts", {}).get(suffix, 0)


@pytest.fixture(autouse=True)
def counting_statsd(monkeypatch):
    """Wrap Ringpop.stat to count increments per suffix for assertions."""
    from ringpop_tpu.api.ringpop import Ringpop

    orig = Ringpop.stat

    def counting(self, stat_type, key, value=None):
        if stat_type == "increment":
            counts = getattr(self, "_test_counts", None)
            if counts is None:
                counts = self._test_counts = {}
            counts[key] = counts.get(key, 0) + 1
        return orig(self, stat_type, key, value)

    monkeypatch.setattr(Ringpop, "stat", counting)


def test_sharding_handler_blacklist_passes_through(cluster):
    """Blacklisted endpoints skip sk routing entirely
    (ringpop-handler.js:52-68)."""
    from ringpop_tpu.api.handler import RingpopHandler

    c = cluster(n=3)
    sender, other = c.node(0), c.node(1)

    def app_handler(head, body):
        return None, {"servedBy": sender.whoami()}

    RingpopHandler(
        sender, app_handler, "/app/admin-ish", blacklist=["/app/admin-ish"]
    ).register()
    sk = key_owned_by(c, other, tag="bl")
    # even with a remote-owned sk, the blacklist serves locally
    _, body = sender.channel.request(
        sender.whoami(), "/app/admin-ish", head={"sk": sk}, body={}
    )
    assert body["servedBy"] == sender.whoami()


def test_sharding_handler_no_sk_serves_locally(cluster):
    from ringpop_tpu.api.handler import RingpopHandler

    c = cluster(n=2)
    sender = c.node(0)
    RingpopHandler(
        sender, lambda h, b: (None, {"servedBy": sender.whoami()}), "/app/nosk"
    ).register()
    _, body = sender.channel.request(
        sender.whoami(), "/app/nosk", head={}, body={}
    )
    assert body["servedBy"] == sender.whoami()


def test_body_limit_enforced_and_at_limit_passes(cluster):
    """Oversized buffered bodies fail the forward with the body-module's
    413 (lib/request-proxy/index.js:88-100); a body exactly at the limit
    forwards fine (proxy-test.js 'proxies big json').  Like the
    reference, enforcement is sender-side only: handleRequest
    (index.js:168-229) never re-checks the limit on the receive path."""
    c = cluster(n=2)
    wire_echo_handlers(c)
    sender, dest = c.node(0), c.node(1)
    key = key_owned_by(c, dest, tag="bl")

    big = "x" * 512
    limit = len('"%s"' % big)  # serialized length, like the raw stream
    res = sender.proxy_req(
        {
            "keys": [key],
            "dest": dest.whoami(),
            "req": {"url": "/b", "body": big},
            "bodyLimit": limit,
        }
    )
    assert res["body"]["handledBy"] == dest.whoami()

    with pytest.raises(errors.BodyLimitExceededError) as ei:
        sender.proxy_req(
            {
                "keys": [key],
                "dest": dest.whoami(),
                "req": {"url": "/b", "body": big + "y"},
                "bodyLimit": limit,
            }
        )
    assert ei.value.fields["limit"] == limit
    assert ei.value.fields["length"] > limit


def test_max_retries_zero_fails_fast(cluster):
    """maxRetries=0: a failed first attempt raises immediately with no
    retry (proxy-test.js requestProxyMaxRetries:0)."""
    c = cluster(n=2)
    sender = c.node(0)
    before = _stat_count(sender, "requestProxy.retry.attempted")
    with pytest.raises(errors.MaxRetriesExceededError):
        sender.proxy_req(
            {
                "keys": ["k"],
                "dest": "127.0.0.1:1",
                "req": {"url": "/x"},
                "maxRetries": 0,
            }
        )
    assert _stat_count(sender, "requestProxy.retry.attempted") == before
    assert _stat_count(sender, "requestProxy.retry.failed") >= 1


def test_max_retries_five_exhaustion_counts_attempts(cluster):
    """maxRetries=5 against a permanently-dead owner retries exactly 5
    times, then fails (proxy-test.js requestProxyMaxRetries:5)."""
    c = cluster(n=2)
    sender = c.node(0)
    sender.request_proxy.retry_schedule_s = [0.0]
    # a key that re-looks-up to a dead address every time: phantom member
    # added to the SENDER's ring only
    phantom = "127.0.0.1:19997"
    sender.ring.add_server(phantom)
    key = None
    for i in range(10000):
        k = "ex-%d" % i
        if sender.lookup(k) == phantom:
            key = k
            break
    assert key is not None
    before = _stat_count(sender, "requestProxy.retry.attempted")
    with pytest.raises(errors.MaxRetriesExceededError) as ei:
        sender.proxy_req(
            {
                "keys": [key],
                "dest": phantom,
                "req": {"url": "/x"},
                "maxRetries": 5,
            }
        )
    assert ei.value.fields["maxRetries"] == 5
    assert _stat_count(sender, "requestProxy.retry.attempted") - before == 5


def test_destroy_mid_retry_aborts_forwarding(cluster):
    """A proxy destroyed between attempts aborts the in-flight retry
    ('Channel was destroyed before forwarding attempt',
    proxy-test.js:1039-1063)."""
    c = cluster(n=2)
    sender = c.node(0)
    sender.request_proxy.retry_schedule_s = [0.0]
    remote = c.node(1).whoami()

    def destroy_then_relookup(keys, dest):
        # destroyed between attempts; re-route lands on a REMOTE owner so
        # the loop re-enters its pre-attempt destroyed check
        sender.request_proxy.destroy()
        return remote

    sender.request_proxy._relookup = destroy_then_relookup
    with pytest.raises(errors.RequestProxyDestroyedError):
        sender.proxy_req(
            {"keys": ["k"], "dest": "127.0.0.1:1", "req": {"url": "/x"}}
        )


def test_keys_diverged_through_full_retry_path(cluster):
    """Divergent keys abort at the retry re-lookup inside proxy_req, not
    just in _relookup directly (send.js:91-104)."""
    c = cluster(n=3)
    sender = c.node(0)
    k1 = key_owned_by(c, c.node(1), tag="fd1")
    k2 = key_owned_by(c, c.node(2), tag="fd2")
    sender.request_proxy.retry_schedule_s = [0.0]
    with pytest.raises(errors.KeysDivergedError) as ei:
        sender.proxy_req(
            {
                "keys": [k1, k2],
                "dest": "127.0.0.1:1",  # first attempt fails -> re-lookup
                "req": {"url": "/x"},
            }
        )
    assert sorted(ei.value.fields["keys"]) == sorted([k1, k2])


def test_forwarded_head_fidelity(cluster):
    """The routing envelope carries url, method, headers, httpVersion,
    the sender's checksum, and the keys (util.js:22-35)."""
    c = cluster(n=2)
    sender, dest = c.node(0), c.node(1)
    key = key_owned_by(c, dest, tag="hf")
    seen = {}

    def handler(req, res, head):
        seen.update(head)
        res.end({"ok": True})

    dest.on("request", handler)
    sender.proxy_req(
        {
            "keys": [key],
            "dest": dest.whoami(),
            "req": {
                "url": "/fidelity?q=1",
                "method": "PUT",
                "headers": {"x-app": "v"},
                "httpVersion": "1.0",
            },
        }
    )
    assert seen["url"] == "/fidelity?q=1"
    assert seen["method"] == "PUT"
    assert seen["headers"] == {"x-app": "v"}
    assert seen["httpVersion"] == "1.0"
    assert seen["ringpopKeys"] == [key]
    assert seen["ringpopChecksum"] == sender.membership.checksum


def test_channel_destroy_mid_retry_aborts_forwarding(cluster):
    """The real reference path: the CHANNEL dying mid-retry (ringpop
    destroyed / channel.quit()) aborts the forward instead of burning the
    whole retry schedule against a dead channel (send.js:228-234)."""
    c = cluster(n=2)
    sender = c.node(0)
    sender.request_proxy.retry_schedule_s = [0.0]
    remote = c.node(1).whoami()

    def destroy_ringpop_then_relookup(keys, dest):
        sender.destroy()  # destroys channel AND proxy, like production
        return remote

    sender.request_proxy._relookup = destroy_ringpop_then_relookup
    with pytest.raises(errors.RequestProxyDestroyedError):
        sender.proxy_req(
            {"keys": ["k"], "dest": "127.0.0.1:1", "req": {"url": "/x"}}
        )


def test_response_status_and_headers_propagate(cluster):
    """The remote handler's statusCode and headers ride back through the
    proxy envelope (request-proxy/index.js onResponse: responseHead)."""
    c = cluster(n=2)
    sender, dest = c.node(0), c.node(1)
    key = key_owned_by(c, dest, tag="rs")

    def handler(req, res, head):
        res.end({"made": "it"}, status=201, headers={"x-served": "yes"})

    dest.on("request", handler)
    res = sender.proxy_req(
        {"keys": [key], "dest": dest.whoami(), "req": {"url": "/s"}}
    )
    assert res["statusCode"] == 201
    assert res["headers"] == {"x-served": "yes"}
    assert res["body"] == {"made": "it"}


def test_handle_or_proxy_all_partial_failure(cluster):
    """One dead owner must not poison the other groups: its entry carries
    `error`, the rest carry `res` (index.js:609-667 per-group callbacks)."""
    c = cluster(n=3)
    wire_echo_handlers(c)
    sender, healthy, doomed = c.node(0), c.node(1), c.node(2)
    k_ok = key_owned_by(c, healthy, tag="pf-ok")
    k_bad = key_owned_by(c, doomed, tag="pf-bad")
    sender.request_proxy.retry_schedule_s = [0.0]
    doomed.destroy()  # owner is gone; ring on sender still maps to it
    results = sender.handle_or_proxy_all([k_ok, k_bad], {"url": "/pf"})
    by_dest = {r["dest"]: r for r in results}
    ok = by_dest[healthy.whoami()]
    assert ok["res"]["body"]["handledBy"] == healthy.whoami()
    bad = by_dest[doomed.whoami()]
    assert "error" in bad and "res" not in bad


def test_proxy_endpoint_override(cluster):
    """opts.endpoint replaces /proxy/req (send.js channelOpts.endpoint) —
    e.g. routing to a custom registered handler."""
    c = cluster(n=2)
    sender, dest = c.node(0), c.node(1)
    key = key_owned_by(c, dest, tag="ep")
    seen = {}

    def custom(head, body):
        seen["head"] = head
        return None, {"via": "custom"}

    dest.channel.register("/custom/endpoint", custom)
    res = sender.proxy_req(
        {
            "keys": [key],
            "dest": dest.whoami(),
            "req": {"url": "/x"},
            "endpoint": "/custom/endpoint",
        }
    )
    # a custom endpoint's handler answers with its raw body (the
    # {statusCode, headers, body} envelope is built by /proxy/req's own
    # handler, not the channel)
    assert res == {"via": "custom"}
    assert seen["head"]["ringpopKeys"] == [key]


def test_enforce_consistency_false_serves_despite_mismatch(cluster):
    """enforceConsistency=false: a checksum mismatch still increments the
    differ stat but the request IS served (proxy-test.js 'no retries,
    invalid checksum emit request when enforceConsistency is false';
    lib/request-proxy/index.js:186-193)."""
    c = cluster(n=2, options={"requestProxy": {"enforceConsistency": False}})
    wire_echo_handlers(c)
    from ringpop_tpu.utils.stats import CapturingStatsd

    sender, dest = c.node(0), c.node(1)
    dest.statsd = CapturingStatsd()
    key = key_owned_by(c, dest)
    # destabilize dest's checksum: sender's head now carries a stale one
    dest.membership.update(
        {
            "address": "127.0.0.1:19996",
            "status": "faulty",
            "incarnationNumber": 1,
            "source": dest.whoami(),
            "sourceIncarnationNumber": 1,
        }
    )
    res = sender.proxy_req(
        {"keys": [key], "dest": dest.whoami(), "req": {"url": "/ec"}}
    )
    assert res["body"]["handledBy"] == dest.whoami()
    assert any(
        "checksumsDiffer" in k
        for _, k, _ in dest.statsd.records
    ), "the differ stat must fire even when not enforcing"


def test_per_retry_stats_full_lifecycle(cluster):
    """Per-retry stat emission (send.js:92-200): attempted on each retry,
    reroute.remote on re-lookup to another node, succeeded when a retry
    lands, and send.success exactly once for the whole request."""
    c = cluster(n=3)
    wire_echo_handlers(c)
    from ringpop_tpu.utils.stats import CapturingStatsd

    sender, owner = c.node(0), c.node(1)
    sender.statsd = CapturingStatsd()
    sender.request_proxy.retry_schedule_s = [0.0]
    key = key_owned_by(c, owner)

    def count(fragment):
        return sum(
            1 for _, k, _ in sender.statsd.records if fragment in k
        )

    res = sender.proxy_req(
        {"keys": [key], "dest": "127.0.0.1:1", "req": {"url": "/st"}}
    )
    assert res["body"]["handledBy"] == owner.whoami()
    assert count("requestProxy.retry.attempted") == 1
    assert count("requestProxy.retry.reroute.remote") == 1
    assert count("requestProxy.retry.succeeded") == 1
    assert count("requestProxy.send.success") == 1
    assert count("requestProxy.retry.failed") == 0


def test_reroute_local_serves_in_process(cluster):
    """A retry whose re-lookup lands on the SENDER handles the request
    in-process and emits reroute.local (send.js:190-198, proxy-test.js
    'reroutes retry to local')."""
    c = cluster(n=2)
    wire_echo_handlers(c)
    from ringpop_tpu.utils.stats import CapturingStatsd

    sender = c.node(0)
    sender.statsd = CapturingStatsd()
    sender.request_proxy.retry_schedule_s = [0.0]
    key = key_owned_by(c, sender)
    res = sender.proxy_req(
        {"keys": [key], "dest": "127.0.0.1:1", "req": {"url": "/lo"}}
    )
    assert res["body"]["handledBy"] == sender.whoami()
    assert any(
        "retry.reroute.local" in k for _, k, _ in sender.statsd.records
    )


def test_retries_multiple_keys_same_dest(cluster):
    """Multiple keys that re-lookup to ONE owner retry fine — divergence
    aborts only when owners differ (proxy-test.js 'retries multiple keys
    w/ same dest')."""
    c = cluster(n=3)
    wire_echo_handlers(c)
    sender, owner = c.node(0), c.node(1)
    sender.request_proxy.retry_schedule_s = [0.0]
    k1 = key_owned_by(c, owner, tag="mk1")
    k2 = key_owned_by(c, owner, tag="mk2")
    res = sender.proxy_req(
        {"keys": [k1, k2], "dest": "127.0.0.1:1", "req": {"url": "/mk"}}
    )
    assert res["body"]["handledBy"] == owner.whoami()
    assert res["body"]["keys"] == [k1, k2]


def test_proxies_big_json(cluster):
    """A ~1 MB JSON body survives the round trip intact (proxy-test.js
    'proxies big json')."""
    c = cluster(n=2)
    sender, dest = c.node(0), c.node(1)
    got = {}

    def handler(req, res, head):
        got["body"] = req["body"]
        res.end({"n": len(req["body"]["blob"])})

    dest.on("request", handler)
    key = key_owned_by(c, dest)
    blob = "x" * (1 << 20)
    res = sender.proxy_req(
        {
            "keys": [key],
            "dest": dest.whoami(),
            "req": {"url": "/big", "body": {"blob": blob}},
        }
    )
    assert res["body"]["n"] == len(blob)
    assert got["body"]["blob"] == blob


def test_custom_timeout_expires_against_stuck_handler(cluster):
    """A per-request timeout bounds a handler that never responds
    (proxy-test.js 'will timeout after default timeout' / 'custom
    timeouts'), surfacing as retry exhaustion."""
    c = cluster(n=2)
    sender, dest = c.node(0), c.node(1)

    def never_responds(req, res, head):
        pass  # res.end never called

    dest.on("request", never_responds)
    key = key_owned_by(c, dest)
    t0 = __import__("time").perf_counter()
    with pytest.raises(errors.MaxRetriesExceededError):
        sender.proxy_req(
            {
                "keys": [key],
                "dest": dest.whoami(),
                "req": {"url": "/slow"},
                "timeout": 300,  # ms
                "maxRetries": 0,
            }
        )
    assert __import__("time").perf_counter() - t0 < 10.0


def test_two_retries_then_success(cluster):
    """proxy-test.js:202-248 'two retries': two channel-level failures,
    then the third attempt lands; attempt accounting matches."""
    c = cluster(n=3)
    wire_echo_handlers(c)
    sender, dest = c.node(0), c.node(1)
    key = key_owned_by(c, dest)

    fails = {"left": 2}
    orig = sender.channel.request

    def flaky(dest_hp, endpoint, head=None, body=None, **kw):
        if endpoint == "/proxy/req" and fails["left"] > 0:
            fails["left"] -= 1
            from ringpop_tpu.net.channel import ChannelError

            raise ChannelError("injected send failure", "test.flaky")
        return orig(dest_hp, endpoint, head=head, body=body, **kw)

    sender.channel.request = flaky
    before = _stat_count(sender, "requestProxy.retry.attempted")
    res = sender.proxy_req(
        {"keys": [key], "dest": dest.whoami(), "req": {"url": "/2r"}}
    )
    assert res["body"]["handledBy"] == dest.whoami()
    assert _stat_count(sender, "requestProxy.retry.attempted") - before == 2
    assert _stat_count(sender, "requestProxy.retry.succeeded") >= 1


def test_destroy_aborts_pending_send_completed_one_unaffected(cluster):
    """proxy-test.js:405-442 'cleans up some pending sends': with one
    request already completed and another still pending, destroy aborts
    only the pending one; the completed result is untouched."""
    import threading

    c = cluster(n=3)
    wire_echo_handlers(c)
    sender, healthy, stuck = c.node(0), c.node(1), c.node(2)
    key_ok = key_owned_by(c, healthy, tag="ok")
    key_stuck = key_owned_by(c, stuck, tag="stuck")

    release = threading.Event()

    def stuck_handler(req, res, head):
        release.wait(10.0)
        res.end({"handledBy": stuck.whoami()})

    stuck.remove_all_listeners("request")
    stuck.on("request", stuck_handler)

    outcome = {}

    def pending():
        try:
            outcome["res"] = sender.proxy_req(
                {
                    "keys": [key_stuck],
                    "dest": stuck.whoami(),
                    "req": {"url": "/pending"},
                    "timeout": 500,  # ms: expire fast, then hit the
                    # destroyed check at the retry-loop top
                }
            )
        except Exception as e:
            outcome["err"] = e

    t = threading.Thread(target=pending, daemon=True)
    t.start()

    done = sender.proxy_req(
        {"keys": [key_ok], "dest": healthy.whoami(), "req": {"url": "/done"}}
    )
    assert done["body"]["handledBy"] == healthy.whoami()

    sender.request_proxy.destroy()
    # do NOT release yet: the pending attempt must expire on its own
    # timeout and then hit the destroyed check at the retry-loop top
    t.join(15.0)
    release.set()  # free the handler thread for teardown
    assert not t.is_alive(), "pending send did not unwind after destroy"
    assert isinstance(outcome.get("err"), errors.RequestProxyDestroyedError)
    # the completed request's result is unaffected by the destroy
    assert done["body"]["handledBy"] == healthy.whoami()


def test_proxy_endpoint_override_to_missing_endpoint_fails(cluster):
    """proxy-test.js:485-513 'overrides /proxy/req endpoint and fails':
    an override pointing at an unregistered endpoint errors out (a
    non-checksum remote error does not retry)."""
    c = cluster(n=3)
    wire_echo_handlers(c)
    sender, dest = c.node(0), c.node(1)
    key = key_owned_by(c, dest)
    from ringpop_tpu.net.channel import ChannelError, RemoteError

    with pytest.raises((ChannelError, RemoteError)):
        sender.proxy_req(
            {
                "keys": [key],
                "dest": dest.whoami(),
                "req": {"url": "/x"},
                "endpoint": "/no/such/endpoint",
            }
        )
    assert _stat_count(sender, "requestProxy.send.error") >= 1


def test_missing_head_fields_handled(cluster):
    """Nearest analog of proxy-test.js:932-955 'non json head is ok' for
    a JSON-typed transport: a /proxy/req with missing head fields (no
    checksum, no keys) is handled without crashing — the checksum
    mismatch path rejects it cleanly under enforceConsistency."""
    c = cluster(n=3)
    wire_echo_handlers(c)
    sender, dest = c.node(0), c.node(1)
    from ringpop_tpu.net.channel import RemoteError

    with pytest.raises(RemoteError) as ei:
        sender.channel.request(
            dest.whoami(), "/proxy/req", head={"url": "/bare"}, body=None,
            timeout_s=5,
        )
    assert "checksum" in str(ei.value.payload).lower()


def test_send_on_destroyed_channel_refused_up_front(cluster):
    """proxy-test.js:1043-1065 'send on destroyed channel not allowed':
    a proxy_req AFTER the channel is destroyed refuses before any
    forwarding attempt (send.js:228-234)."""
    c = cluster(n=3)
    wire_echo_handlers(c)
    sender, dest = c.node(0), c.node(1)
    key = key_owned_by(c, dest)
    sender.channel.destroyed = True
    try:
        before = _stat_count(sender, "requestProxy.retry.attempted")
        with pytest.raises(errors.RequestProxyDestroyedError):
            sender.proxy_req(
                {"keys": [key], "dest": dest.whoami(), "req": {"url": "/x"}}
            )
        assert _stat_count(sender, "requestProxy.retry.attempted") == before
    finally:
        sender.channel.destroyed = False


# -- retry accounting closure (ISSUE 6 satellite) ---------------------------
# The routing plane's device counters (models/route/plane.RouteMetrics)
# mirror these statsd keys one-to-one (obs/statsd_bridge.py); the tests
# below pin the per-request accounting the aggregate counters must match:
# send.js:91-208 client semantics, request-proxy/index.js:168-229 server.


def _counts(rp, *suffixes):
    return {s: _stat_count(rp, "requestProxy.%s" % s) for s in suffixes}


def test_keys_diverged_abort_closes_retry_aborted_and_send_error(cluster):
    """A keys-diverged abort on the retry re-lookup closes the request's
    accounting: retry.aborted + send.error fire exactly once, and NO
    success stat fires (send.js:91-104 — the request fails permanently,
    it is not rerouted)."""
    c = cluster(n=3)
    wire_echo_handlers(c)
    sender = c.node(0)
    sender.request_proxy.retry_schedule_s = [0.0]
    k1 = key_owned_by(c, c.node(1), tag="acc1")
    k2 = key_owned_by(c, c.node(2), tag="acc2")
    before = _counts(
        sender,
        "retry.attempted", "retry.aborted", "retry.succeeded",
        "send.error", "send.success",
    )
    # first attempt targets a dead address -> ChannelError -> retry path
    # re-looks up BOTH keys, finds two owners, aborts
    with pytest.raises(errors.KeysDivergedError):
        sender.proxy_req(
            {"keys": [k1, k2], "dest": "127.0.0.1:1", "req": {"url": "/d"}}
        )
    after = _counts(
        sender,
        "retry.attempted", "retry.aborted", "retry.succeeded",
        "send.error", "send.success",
    )
    delta = {k: after[k] - before[k] for k in after}
    assert delta["retry.attempted"] == 1
    assert delta["retry.aborted"] == 1
    assert delta["send.error"] == 1
    assert delta["retry.succeeded"] == 0
    assert delta["send.success"] == 0


def test_reroute_local_fires_full_success_accounting(cluster):
    """A retry rerouted to the SENDER serves in-process AND fires the
    complete success accounting — reroute.local, retry.succeeded and
    send.success — exactly like a remote landing (send.js:190-198); no
    error stat leaks."""
    c = cluster(n=2)
    wire_echo_handlers(c)
    sender = c.node(0)
    sender.request_proxy.retry_schedule_s = [0.0]
    key = key_owned_by(c, sender, tag="accl")
    before = _counts(
        sender,
        "retry.attempted", "retry.reroute.local", "retry.succeeded",
        "send.success", "send.error", "retry.aborted",
    )
    res = sender.proxy_req(
        {"keys": [key], "dest": "127.0.0.1:1", "req": {"url": "/l"}}
    )
    assert res["body"]["handledBy"] == sender.whoami()
    after = _counts(
        sender,
        "retry.attempted", "retry.reroute.local", "retry.succeeded",
        "send.success", "send.error", "retry.aborted",
    )
    delta = {k: after[k] - before[k] for k in after}
    assert delta["retry.attempted"] == 1
    assert delta["retry.reroute.local"] == 1
    assert delta["retry.succeeded"] == 1
    assert delta["send.success"] == 1
    assert delta["send.error"] == 0
    assert delta["retry.aborted"] == 0


def test_destroyed_channel_mid_retry_aborts_without_success_stats(cluster):
    """A channel destroyed between attempts aborts the in-flight retry
    (send.js:228-234) with NO success accounting and no further retry
    attempts — the abort happens at the pre-attempt destroyed check,
    before any forwarding."""
    c = cluster(n=2)
    sender = c.node(0)
    sender.request_proxy.retry_schedule_s = [0.0]
    remote = c.node(1).whoami()

    def destroy_channel_then_relookup(keys, dest):
        sender.channel.destroyed = True
        return remote

    sender.request_proxy._relookup = destroy_channel_then_relookup
    before = _counts(
        sender, "retry.attempted", "retry.succeeded", "send.success"
    )
    try:
        with pytest.raises(errors.RequestProxyDestroyedError):
            sender.proxy_req(
                {"keys": ["k"], "dest": "127.0.0.1:1", "req": {"url": "/x"}}
            )
        after = _counts(
            sender, "retry.attempted", "retry.succeeded", "send.success"
        )
        assert after["retry.attempted"] - before["retry.attempted"] == 1
        assert after["retry.succeeded"] == before["retry.succeeded"]
        assert after["send.success"] == before["send.success"]
    finally:
        sender.channel.destroyed = False
