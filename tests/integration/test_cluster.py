"""Live multi-node integration: real Ringpop nodes over real sockets.

The test-ringpop-cluster scope (test/lib/test-ringpop-cluster.js): N nodes
bootstrap against each other, converge, survive kill -> suspect -> faulty,
refute wrong suspicion, leave/rejoin, and keep the ring consistent.
"""

import pytest

from ringpop_tpu.gossip.join_sender import JoinError
from ringpop_tpu.models.membership.host import Status
from tests.lib.cluster import LiveCluster


@pytest.fixture
def cluster():
    made = []

    def make(n=5, **kw):
        c = LiveCluster(n=n, **kw)
        made.append(c)
        return c

    yield make
    for c in made:
        c.destroy_all()


def test_bootstrap_converges(cluster):
    c = cluster(n=5)
    c.bootstrap_all()
    ticks = c.tick_until_converged()
    assert ticks <= 60
    for rp in c.nodes:
        assert rp.membership.get_member_count() == 5
        assert sorted(rp.ring.servers) == sorted(c.hosts)
        assert rp.membership.checksum is not None


def test_kill_suspect_then_faulty(cluster):
    c = cluster(n=5)
    c.bootstrap_all()
    c.tick_until_converged()
    victim = c.node(2)
    victim_addr = victim.whoami()
    victim.destroy()  # SIGKILL equivalent: sockets die, no goodbye

    # gossip: someone's direct ping fails, ping-req finds no path -> suspect
    for _ in range(30):
        c.tick_all()
        if any(
            s == Status.suspect for s in c.statuses_of(victim_addr).values()
        ):
            break
    assert any(
        s == Status.suspect for s in c.statuses_of(victim_addr).values()
    ), c.statuses_of(victim_addr)

    # suspicion clocks expire (5s virtual) -> faulty, disseminated to all
    for _ in range(40):
        c.advance_all(6.0)
        c.tick_all()
        statuses = c.statuses_of(victim_addr)
        if all(s == Status.faulty for s in statuses.values()):
            break
    assert all(
        s == Status.faulty for s in c.statuses_of(victim_addr).values()
    ), c.statuses_of(victim_addr)
    c.tick_until_converged()
    # faulty members leave the ring but stay in the member list
    for rp in c.live():
        assert victim_addr not in rp.ring.servers
        assert rp.membership.find_member_by_address(victim_addr) is not None


def test_wrongly_suspected_node_refutes(cluster):
    c = cluster(n=4)
    c.bootstrap_all()
    c.tick_until_converged()
    accuser, accused = c.node(0), c.node(1)
    inc_before = accused.membership.local_member.incarnation_number
    # accuser wrongly declares the (live) accused suspect
    m = accuser.membership.find_member_by_address(accused.whoami())
    accuser.membership.make_suspect(accused.whoami(), m.incarnation_number)
    assert c.status_of(accuser, accused.whoami()) == Status.suspect

    # accused's own clock must move past the stale incarnation so the
    # refute is fresh (incarnations are clock-derived, member.js:78-81)
    accused.timers.advance(1.0)
    for _ in range(40):
        c.tick_all()
        statuses = c.statuses_of(accused.whoami())
        if all(s == Status.alive for s in statuses.values()):
            break
    assert all(
        s == Status.alive for s in c.statuses_of(accused.whoami()).values()
    ), c.statuses_of(accused.whoami())
    assert (
        accused.membership.local_member.incarnation_number > inc_before
    ), "refute must bump the incarnation number"
    c.tick_until_converged()


def test_leave_and_rejoin(cluster):
    c = cluster(n=4)
    c.bootstrap_all()
    c.tick_until_converged()
    leaver = c.node(3)
    addr = leaver.whoami()

    _, res = leaver.server.admin_member_leave(None, {})
    assert res["status"] == "ok"
    # LocalMemberLeaveEvent stops the leaver's gossip
    assert leaver.gossip.is_stopped
    for _ in range(40):
        c.tick_all()
        statuses = {
            k: v for k, v in c.statuses_of(addr).items()
        }
        if all(s == Status.leave for s in statuses.values()):
            break
    assert all(
        s == Status.leave for s in c.statuses_of(addr).values()
    ), c.statuses_of(addr)
    for rp in c.live():
        if rp.whoami() != addr:
            assert addr not in rp.ring.servers

    # rejoin: fresh incarnation, gossip restarted, back in every ring
    leaver.timers.advance(1.0)
    _, res = leaver.server.admin_member_join(None, {})
    assert res["status"] == "rejoined"
    assert not leaver.gossip.is_stopped
    for _ in range(60):
        c.tick_all()
        if all(
            s == Status.alive for s in c.statuses_of(addr).values()
        ):
            break
    assert all(s == Status.alive for s in c.statuses_of(addr).values())
    c.tick_until_converged()
    for rp in c.live():
        assert addr in rp.ring.servers


def test_deny_joins(cluster):
    def deny(cl):
        for rp in cl.nodes[1:]:
            rp.deny_joins()

    c = cluster(n=3, tap=deny)
    joiner = c.node(0)
    for rp in c.nodes[1:]:
        rp.bootstrap([rp.whoami()])  # bring up targets standalone
    with pytest.raises(JoinError):
        joiner.bootstrap({"bootstrapFile": c.hosts, "maxJoinDuration": 2000})


def test_full_sync_recovers_divergence(cluster):
    """A node whose change buffer is empty but whose checksum differs gets
    the target's full membership (dissemination.js:101-114)."""
    c = cluster(n=3)
    c.bootstrap_all()
    c.tick_until_converged()
    # fabricate divergence: node0 learns of a phantom member directly, with
    # the change buffer cleared so only full-sync can repair the others
    phantom = "127.0.0.1:19999"
    c.node(0).membership.update(
        {
            "address": phantom,
            "status": Status.faulty,
            "incarnationNumber": 1,
            "source": c.node(0).whoami(),
            "sourceIncarnationNumber": 1,
        }
    )
    c.node(0).dissemination.clear_changes()
    assert not c.converged()
    c.tick_until_converged(max_ticks=40)
    for rp in c.live():
        assert rp.membership.find_member_by_address(phantom) is not None


def test_join_failure_triage_stats(cluster):
    """Failed join attempts are triaged by error type and surfaced in the
    join result (join-sender.js:233-283 stats)."""
    c = cluster(n=4)

    def deny(rp):
        rp.deny_joins()

    # one denier + one dead address in the bootstrap list
    c.node(1).deny_joins()
    for rp in c.nodes[1:]:
        rp.bootstrap([rp.whoami()])

    joiner = c.node(0)
    hosts = c.hosts + ["127.0.0.1:1"]
    joiner.membership.make_alive(joiner.whoami(), joiner.timers.now_ms())
    from ringpop_tpu.gossip.join_sender import join_cluster

    joiner.bootstrap_hosts = hosts
    result = join_cluster(
        joiner, {"joinSize": 2, "joinTimeout": 500, "maxJoinDuration": 10000}
    )
    assert result["numJoined"] >= 2
    assert result["numGroups"] >= 1
    if result["numFailed"]:
        assert all("errType" in f for f in result["failures"])
