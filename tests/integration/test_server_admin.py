"""Server endpoint handlers + admin client over live channels
(server/protocol/*.js, server/admin/*.js, client.js, lib/trace scope)."""

import pytest

from ringpop_tpu.api.client import RingpopClient
from ringpop_tpu.net.channel import RemoteError
from tests.lib.cluster import LiveCluster


@pytest.fixture
def cluster():
    made = []

    def make(n=3, **kw):
        c = LiveCluster(n=n, **kw)
        made.append(c)
        c.bootstrap_all()
        c.tick_until_converged()
        return c

    yield make
    for c in made:
        c.destroy_all()


@pytest.fixture
def client():
    cl = RingpopClient()
    yield cl
    cl.destroy()


# -- /protocol/join validation (server/protocol/join.js:53-135) -----------


def test_join_rejects_self(cluster):
    c = cluster(n=2)
    rp = c.node(0)
    with pytest.raises(RemoteError):
        rp.channel.request(
            rp.whoami(),
            "/protocol/join",
            body={
                "app": rp.app,
                "source": rp.whoami(),
                "incarnationNumber": 1,
            },
        )


def test_join_rejects_wrong_app(cluster):
    c = cluster(n=2)
    rp = c.node(0)
    with pytest.raises(RemoteError) as e:
        c.node(1).channel.request(
            rp.whoami(),
            "/protocol/join",
            body={
                "app": "some-other-app",
                "source": c.node(1).whoami(),
                "incarnationNumber": 1,
            },
        )
    assert "app" in str(e.value).lower()


def test_join_rejects_blacklisted(cluster):
    import re

    c = cluster(n=2)
    rp = c.node(0)
    rp.config.set("memberBlacklist", [re.compile(r"127\.0\.0\.1:19\d+")])
    with pytest.raises(RemoteError):
        c.node(1).channel.request(
            rp.whoami(),
            "/protocol/join",
            body={
                "app": rp.app,
                "source": "127.0.0.1:19001",
                "incarnationNumber": 1,
            },
        )


def test_join_replies_full_membership(cluster):
    c = cluster(n=3)
    rp = c.node(0)
    joiner = "127.0.0.1:18999"
    _, res = c.node(1).channel.request(
        rp.whoami(),
        "/protocol/join",
        body={"app": rp.app, "source": joiner, "incarnationNumber": 7},
    )
    assert res["coordinator"] == rp.whoami()
    assert res["membershipChecksum"] == rp.membership.checksum
    addrs = {m["address"] for m in res["membership"]}
    assert joiner in addrs and set(c.hosts) <= addrs


def test_ping_requires_ready():
    c = LiveCluster(n=1)
    rp = c.node(0)
    try:
        with pytest.raises(RemoteError):
            rp.channel.request(rp.whoami(), "/protocol/ping", body={})
    finally:
        c.destroy_all()


# -- admin endpoints over the admin client (client.js) --------------------


def test_admin_client_surface(cluster, client):
    c = cluster(n=3)
    hp = c.node(0).whoami()

    assert client.health(hp) == "ok"
    assert client.admin_gossip_status(hp)["status"] == "running"
    client.admin_gossip_stop(hp)
    assert client.admin_gossip_status(hp)["status"] == "stopped"
    client.admin_gossip_start(hp)
    assert client.admin_gossip_status(hp)["status"] == "running"

    tick = client.admin_gossip_tick(hp)
    assert tick["checksum"] == c.node(0).membership.checksum

    stats = client.admin_stats(hp)
    assert stats["ring"] == sorted(c.hosts)
    assert {m["address"] for m in stats["membership"]["members"]} == set(
        c.hosts
    )

    looked = client.admin_lookup(hp, "some-key")
    assert looked["dest"] in c.hosts

    cfg = client.admin_config_get(hp)
    assert "TEST_KEY" in cfg
    client.admin_config_set(hp, {"TEST_KEY": 42})
    assert client.admin_config_get(hp)["TEST_KEY"] == 42


def test_admin_debug_flags(cluster, client):
    c = cluster(n=2)
    hp = c.node(0).whoami()
    c.node(0).channel.request(hp, "/admin/debugSet", body={"debugFlag": "p"})
    assert c.node(0).debug_flag_enabled("p")
    c.node(0).channel.request(hp, "/admin/debugClear", body={})
    assert not c.node(0).debug_flag_enabled("p")


def test_admin_metrics_renders_prometheus_text(cluster):
    """/admin/metrics — Prometheus text exposition next to /admin/stats
    (obs.prometheus.render_ringpop_metrics over the channel)."""
    c = cluster(n=3)
    rp = c.node(0)
    head, body = c.node(1).channel.request(
        rp.whoami(), "/admin/metrics", body={}
    )
    assert head["contentType"].startswith("text/plain")
    assert isinstance(body, str) and body.strip()
    assert "# TYPE ringpop_members gauge" in body
    assert "ringpop_members{" in body
    assert 'instance="%s"' % rp.whoami() in body
    assert "ringpop_membership_checksum" in body
    # a converged 3-node cluster: every member alive on the serving node
    assert 'ringpop_members_by_status{' in body
    assert 'status="alive"' in body
    assert "ringpop_ring_servers" in body
    # request meters moved — this very request marked the server plane
    assert 'ringpop_requests_total{' in body


# -- trace subsystem over the wire (lib/trace/) ---------------------------


def test_trace_add_fires_sink_and_removes(cluster):
    c = cluster(n=2)
    source, collector = c.node(0), c.node(1)
    received = []

    def sink(head, body):
        received.append((head, body))
        return None, "ok"

    collector.channel.register("/trace/sink", sink)
    _, res = collector.channel.request(
        source.whoami(),
        "/trace/add",
        body={
            "event": "membership.checksum.update",
            "sink": {
                "type": "channel",
                "hostPort": collector.whoami(),
                "serviceName": "/trace/sink",
            },
            "expiresIn": 60000,
        },
    )
    assert res["status"] == "ok"
    # force a checksum change on the source -> tap fires -> sink called
    source.membership.update(
        {
            "address": "127.0.0.1:18777",
            "status": "alive",
            "incarnationNumber": 3,
            "source": source.whoami(),
            "sourceIncarnationNumber": 3,
        }
    )
    import time

    for _ in range(50):
        if received:
            break
        time.sleep(0.05)
    assert received, "trace channel sink never fired"
    head, body = received[0]
    assert head["event"] == "membership.checksum.update"
    assert body["checksum"] == source.membership.checksum

    _, res = collector.channel.request(
        source.whoami(),
        "/trace/remove",
        body={
            "event": "membership.checksum.update",
            "sink": {
                "type": "channel",
                "hostPort": collector.whoami(),
                "serviceName": "/trace/sink",
            },
        },
    )
    assert res["status"] == "ok"


def test_trace_add_unknown_event_rejected(cluster):
    c = cluster(n=2)
    with pytest.raises(RemoteError):
        c.node(1).channel.request(
            c.node(0).whoami(),
            "/trace/add",
            body={"event": "no.such.event", "sink": {"type": "log"}},
        )
