"""Shared schedule-builder semantics (ISSUE 7 satellite): EventSchedule
and StormSchedule ride ONE memoized as_inputs()/invalidate() base
(models/sim/schedule.py) with identical freeze semantics, and
StormSchedule's new partition plane keeps the ChurnInputs None-structure
contract."""

from __future__ import annotations

import numpy as np
import pytest

from ringpop_tpu.models.sim.cluster import EventSchedule
from ringpop_tpu.models.sim.schedule import DeviceScheduleMixin
from ringpop_tpu.models.sim.storm import StormSchedule


@pytest.mark.parametrize(
    "make",
    [
        lambda: EventSchedule(ticks=4, n=6),
        lambda: StormSchedule(ticks=4, n=6),
    ],
    ids=["event", "storm"],
)
def test_shared_memoize_and_invalidate_semantics(make):
    sched = make()
    assert isinstance(sched, DeviceScheduleMixin)
    first = sched.as_inputs()
    # frozen at first use: same object back, mutations invisible...
    assert sched.as_inputs() is first
    sched.kill[2, 3] = True
    assert not bool(np.asarray(sched.as_inputs().kill)[2, 3])
    # ...until invalidate() drops the memo
    sched.invalidate()
    fresh = sched.as_inputs()
    assert fresh is not first
    assert bool(np.asarray(fresh.kill)[2, 3])


def test_unused_planes_stay_none_for_both_schedules():
    ev = EventSchedule(ticks=3, n=4).as_inputs()
    assert ev.resume is None and ev.leave is None
    st = StormSchedule(ticks=3, n=4).as_inputs()
    assert st.partition is None and st.leave is None


def test_storm_partition_plane_becomes_dense_when_set():
    sched = StormSchedule(ticks=3, n=4)
    sched.partition = np.full((3, 4), -1, np.int32)
    sched.partition[1, 2] = 5
    inputs = sched.as_inputs()
    part = np.asarray(inputs.partition)
    assert part.shape == (3, 4)
    assert part[1, 2] == 5 and part[0, 0] == -1


def test_mixin_requires_build_inputs():
    class Bare(DeviceScheduleMixin):
        pass

    with pytest.raises(NotImplementedError):
        Bare().as_inputs()
