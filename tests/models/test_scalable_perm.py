"""Sortless partner permutations (round 10): PRP properties + the
mode-equivalence gates.

The scalable engine's per-tick base permutation is a keyed Feistel PRP
over [0, N) with cycle-walking for ragged N and an ANALYTIC inverse
(engine_scalable._prp_perm) — no argsort.  These tests pin:

- bijectivity over power-of-two AND ragged N (including N=1);
- inverse correctness both ways (the analytic inverse IS the inverse);
- per-tick freshness (folded keys draw distinct permutations);
- a chi-square uniformity smoke test of the per-position marginals
  (the deviation envelope documented at the _prp_perm note: the family
  is not a uniform draw over all n! permutations, but its marginals are
  statistically uniform);
- the gate-equivalence acceptance criterion: sortless + fused-exchange
  storm trajectories bit-identical to the argsort / pure-XLA / inline
  twins (n=64 tier-1, n=1k slow).
"""

import functools
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ringpop_tpu.models.sim import engine_scalable as es


def _key(a, b):
    return jnp.asarray([a % 2**32, b % 2**32], jnp.uint32)


# ---------------------------------------------------------------------------
# PRP properties


@pytest.mark.parametrize(
    "n", [1, 2, 3, 7, 8, 64, 65, 100, 128, 1000, 1024]
)
def test_prp_is_bijective_with_correct_inverse(n):
    key = _key(123456789, 987654321)
    fwd = np.asarray(es._prp_perm(key, n, salt=0xA11CE))
    inv = np.asarray(es._prp_perm(key, n, salt=0xA11CE, inverse=True))
    assert sorted(fwd.tolist()) == list(range(n))
    assert (fwd[inv] == np.arange(n)).all()
    assert (inv[fwd] == np.arange(n)).all()


@pytest.mark.parametrize("n", [8, 64, 100])
def test_argsort_twin_is_bit_identical(n):
    """perm_impl="argsort" keeps the SAME forward values and derives the
    inverse by argsort — both pairs must match elementwise (argsort of a
    bijection over [0, n) is its inverse)."""
    key = _key(77, 0xBEEF)
    f_s, i_s = es._base_perm_pair(key, n, "sortless", salt=0xA11CE)
    f_a, i_a = es._base_perm_pair(key, n, "argsort", salt=0xA11CE)
    assert (np.asarray(f_s) == np.asarray(f_a)).all()
    assert (np.asarray(i_s) == np.asarray(i_a)).all()


def test_per_tick_freshness():
    """Folding the key (what tick does every step) must draw distinct
    permutations — the protocol's partner rotation depends on a fresh
    base every tick."""
    n = 64
    seen = set()
    key = _key(5, 0xABCD1234)
    for _ in range(50):
        key = es._fold(key, 0xA11CE)
        seen.add(
            tuple(np.asarray(es._prp_perm(key, n, salt=0xA11CE)).tolist())
        )
    assert len(seen) == 50


@pytest.mark.parametrize("n", [16, 64, 100])
def test_marginal_uniformity_chi_square_smoke(n):
    """Per-position marginals of the PRP family are statistically
    uniform: the summed chi-square over all (position, value) cells must
    sit within a few sigma of its df (fixed seeds — deterministic).
    Ragged tiny domains (n ~ 12) carry a measurable cycle-walk bias and
    are deliberately NOT pinned here; the envelope note at _prp_perm
    documents that deviation.  The K trials run as ONE vmapped device
    call — per-trial dispatch made this the single most expensive tier-1
    test (~110 s/case; now ~1 s) with identical keys and counts."""
    K = 1200
    s = np.arange(K, dtype=np.uint64)
    keys = jnp.asarray(
        np.stack(
            [
                (s * 2654435761) % 2**32,
                ((s ^ 0xDEADBEEF) * 40503) % 2**32,
            ],
            axis=1,
        ).astype(np.uint32)
    )
    perms = np.asarray(
        jax.vmap(lambda k: es._prp_perm(k, n, salt=7))(keys)
    )
    counts = np.zeros((n, n), np.int64)
    np.add.at(
        counts, (np.broadcast_to(np.arange(n), (K, n)), perms), 1
    )
    exp = K / n
    stat = ((counts - exp) ** 2 / exp).sum()
    df = n * (n - 1)
    z = (stat - df) / math.sqrt(2 * df)
    assert abs(z) < 4.0, f"chi2={stat:.1f} df={df} z={z:.2f}"


def test_resolvers_validate_and_pin():
    p = es.ScalableParams(n=8, u=128)
    assert es.resolve_perm_impl(p, "cpu") == "sortless"
    assert es.resolve_fused_exchange(p, "cpu") == "off"
    assert es.resolve_fused_exchange(p, "tpu") == "pallas"
    pinned = es.resolve_scalable_params(p, "cpu")
    assert pinned.perm_impl == "sortless"
    assert pinned.fused_exchange == "off"
    with pytest.raises(ValueError):
        es.resolve_perm_impl(p._replace(perm_impl="bogus"), "cpu")
    with pytest.raises(ValueError):
        es.resolve_fused_exchange(
            p._replace(fused_exchange="bogus"), "cpu"
        )


# ---------------------------------------------------------------------------
# gate equivalence: whole trajectories bit-identical across modes


def _run_traj(n, u, ticks, perm_impl, fused_exchange, seed=1):
    params = es.ScalableParams(
        n=n,
        u=u,
        packet_loss=0.05,
        suspicion_ticks=4,
        perm_impl=perm_impl,
        fused_exchange=fused_exchange,
    )
    st = es.init_state(params, seed=seed)
    step = jax.jit(functools.partial(es.tick, params=params))
    rng = np.random.default_rng(0)
    mets = []
    for t in range(ticks):
        kill = jnp.asarray(rng.random(n) < (0.05 if t == 3 else 0.0))
        revive = (
            jnp.asarray(~np.asarray(st.proc_alive))
            if t == ticks // 2
            else jnp.zeros(n, bool)
        )
        st, m = step(st, es.ChurnInputs(kill=kill, revive=revive))
        mets.append(m)
    return st, mets


def _assert_same(a, b, label):
    st_a, ms_a = a
    st_b, ms_b = b
    for f in st_a._fields:
        x, y = getattr(st_a, f), getattr(st_b, f)
        if x is None or y is None:
            assert x is None and y is None, (label, f)
            continue
        assert (np.asarray(x) == np.asarray(y)).all(), (
            "state field %s diverges under %s" % (f, label)
        )
    for ma, mb in zip(ms_a, ms_b):
        for f in ma._fields:
            assert (
                np.asarray(getattr(ma, f)) == np.asarray(getattr(mb, f))
            ).all(), "metric %s diverges under %s" % (f, label)


@pytest.mark.parametrize(
    "perm_impl,fused_exchange",
    [
        ("sortless", "off"),
        ("sortless", "xla"),
        ("sortless", "pallas"),
        ("argsort", "xla"),
    ],
)
def test_gate_equivalence_n64(perm_impl, fused_exchange):
    """The acceptance gate at tier-1 scale: every (perm_impl,
    fused_exchange) combination reproduces the argsort + inline-phase
    twin's churny trajectory and metrics bit-for-bit.  (Pallas runs in
    interpret mode on CPU — same arithmetic, same gate.)"""
    base = _run_traj(64, 160, 24, "argsort", "off")
    got = _run_traj(64, 160, 24, perm_impl, fused_exchange)
    _assert_same(got, base, f"{perm_impl}+{fused_exchange}")


@pytest.mark.slow
def test_gate_equivalence_n1k_slow():
    """The n=1k gate: sortless + fused exchange (both the XLA twin and
    the interpret-mode kernel) vs the argsort/inline baseline."""
    base = _run_traj(1000, 256, 30, "argsort", "off")
    for pi, fe in (("sortless", "xla"), ("sortless", "pallas")):
        got = _run_traj(1000, 256, 30, pi, fe)
        _assert_same(got, base, f"{pi}+{fe}")
