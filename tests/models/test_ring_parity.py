"""Host/device ring parity property test (ISSUE 6 satellite).

Randomized masks, churn sequences and keys: ``HashRing.lookup/lookup_n``
(models/ring/host.py, the reference-semantics numpy ring) must agree
BIT-FOR-BIT with ``device.lookup/lookup_n`` (models/ring/device.py) on
every query — including across replica-point hash collisions, where
both rings order colliding points by (hash, universe index): the host
ring lexsorts (hash, server name) and the device ring sorts
``(hash << 32) | owner``, which coincide because the device universe is
address-sorted.  This is the collision-order claim pinned in both
module docstrings."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.models.ring import HashRing
from ringpop_tpu.models.ring import device as dring
from ringpop_tpu.ops import farmhash32 as fh


def _universe(n):
    # mixed port widths so lexicographic name order is exercised
    return sorted(
        ["10.0.%d.%d:%d" % (i % 7, i, 3000 + 13 * i) for i in range(n)]
    )


def _host_ring_for(universe, mask):
    host = HashRing(replica_points=20)
    host.add_remove_servers(
        [s for s, m in zip(universe, mask) if m], None
    )
    return host


@functools.lru_cache(maxsize=None)
def _lookup_fn(n_lookup: int):
    # one compiled program per (ring size, n_lookup) shape — eager
    # per-key retracing of the lookup_n while_loop dominated this
    # file's runtime otherwise (tier-1 budget)
    @jax.jit
    def run(table, mask, khashes):
        ring = dring.build_ring(table, mask)
        n_points = dring.ring_size(mask, table.shape[1])
        one = dring.lookup(ring, n_points, khashes)
        many = jax.vmap(
            lambda h: dring.lookup_n(ring, n_points, h, n_lookup)
        )(khashes)
        return one, many

    return run


def _device_owner_names(universe, table, mask, keys, n_lookup):
    khashes = jnp.asarray(fh.hash32_strings([str(k) for k in keys]))
    one, many = _lookup_fn(n_lookup)(
        jnp.asarray(table), jnp.asarray(mask), khashes
    )
    return np.asarray(one), np.asarray(many)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_host_device_parity_random_masks_and_keys(seed):
    rng = np.random.default_rng(seed)
    universe = _universe(24)
    table = dring.replica_table(universe, replica_points=20)
    keys = ["key-%d-%d" % (seed, i) for i in range(120)]

    mask = rng.random(24) < rng.uniform(0.15, 0.95)
    if not mask.any():
        mask[0] = True
    host = _host_ring_for(universe, mask)
    one, many = _device_owner_names(universe, table, mask, keys, 4)
    for k, o, m in zip(keys, one, many):
        assert universe[int(o)] == host.lookup(k), k
        got = [universe[int(x)] for x in m if int(x) >= 0]
        assert got == host.lookup_n(k, 4), k


def test_host_device_parity_under_churn_sequence():
    rng = np.random.default_rng(7)
    universe = _universe(16)
    table = dring.replica_table(universe, replica_points=20)
    mask = np.ones(16, bool)
    keys = ["churn-key-%d" % i for i in range(60)]
    for step in range(12):
        flips = rng.choice(16, size=int(rng.integers(1, 4)), replace=False)
        mask = mask.copy()
        mask[flips] = ~mask[flips]
        if not mask.any():
            mask[int(rng.integers(0, 16))] = True
        host = _host_ring_for(universe, mask)
        one, many = _device_owner_names(universe, table, mask, keys, 3)
        for k, o, m in zip(keys, one, many):
            assert universe[int(o)] == host.lookup(k), (step, k)
            got = [universe[int(x)] for x in m if int(x) >= 0]
            assert got == host.lookup_n(k, 3), (step, k)


def test_collision_order_is_universe_index_order():
    """Force replica-point hash collisions across servers with a stub
    hash and check both rings break the tie identically: owner = the
    lexicographically smaller server name == the smaller universe
    index.  (The real-hash property tests above cover the claim
    statistically; this pins it deterministically.)"""

    def stub_hash(s):
        # every replica point of every server collides pairwise: the
        # hash only sees the replica suffix digit
        return int(str(s)[-1]) if str(s)[-1].isdigit() else 0

    universe = sorted(["b:1", "a:2", "c:3"])
    host = HashRing(replica_points=4, hash_func=stub_hash)
    host.add_remove_servers(universe, None)

    # device table under the same stub hash
    table = np.stack(
        [
            np.array(
                [stub_hash(s + str(i)) for i in range(4)], dtype=np.uint32
            )
            for s in universe
        ]
    )
    mask = jnp.ones(3, bool)
    ring = dring.build_ring(jnp.asarray(table), mask)
    n_points = dring.ring_size(mask, 4)
    for key in ["x0", "x1", "x2", "x3", "zz"]:
        h = jnp.uint32(stub_hash(key))
        dev = universe[int(dring.lookup(ring, n_points, h))]
        # host.lookup hashes via the same stub
        assert dev == host.lookup(key), key
        walk = [
            universe[int(x)]
            for x in np.asarray(dring.lookup_n(ring, n_points, h, 3))
            if int(x) >= 0
        ]
        assert walk == host.lookup_n(key, 3), key


def test_empty_host_and_device_agree():
    universe = _universe(4)
    table = dring.replica_table(universe, replica_points=20)
    host = HashRing(replica_points=20)
    mask = np.zeros(4, bool)
    jmask = jnp.asarray(mask)
    ring = dring.build_ring(jnp.asarray(table), jmask)
    n_points = dring.ring_size(jmask, 20)
    h = jnp.uint32(fh.hash32("k"))
    assert host.lookup("k") is None
    assert int(dring.lookup(ring, n_points, h)) == -1
    assert host.lookup_n("k", 3) == []
    assert all(
        int(x) == -1 for x in np.asarray(dring.lookup_n(ring, n_points, h, 3))
    )
