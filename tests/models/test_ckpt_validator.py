"""Checkpoint-manifest CI gate (the tier-1 twin of
scripts/check_ckpt_manifest.py): every committed manifest-format
checkpoint must deep-verify, the committed sample keeps the format
readable, and --repair-scan reports the recovery order."""

from __future__ import annotations

import importlib.util
import os

import numpy as np

from ringpop_tpu.models.sim import checkpoint as ckpt
from ringpop_tpu.models.sim import engine_scalable as es

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
SAMPLE = os.path.join(REPO_ROOT, "runlogs", "sample_ckpt_scalable_n8")


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_ckpt_manifest",
        os.path.join(REPO_ROOT, "scripts", "check_ckpt_manifest.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_every_repo_checkpoint_validates():
    checker = _load_checker()
    ckpts = checker.find_checkpoints()
    # the sample artifact is committed, so the gate is never vacuous —
    # AND it pins the on-disk format: a format change that can no longer
    # read old checkpoints fails here, not in a user's recovery path
    assert SAMPLE in ckpts, "committed sample checkpoint missing"
    problems = checker.check(ckpts, verbose=False)
    assert problems == [], "\n".join(problems)


def test_committed_sample_still_loads():
    state = ckpt.load_checkpoint(SAMPLE, es.ScalableState)
    assert np.asarray(state.proc_alive).shape == (8,)
    manifest = ckpt.read_manifest(SAMPLE)
    assert manifest["shards"] == 2
    assert manifest["meta"]["tick"] == 6


def test_checker_names_a_bad_checkpoint(tmp_path):
    checker = _load_checker()
    params = es.ScalableParams(n=8, u=128)
    path = str(tmp_path / "ck")
    ckpt.save_checkpoint(path, es.init_state(params, seed=0), params)
    assert checker.check([path], verbose=False) == []
    # bit-rot it: the checker must name the digest failure
    target = os.path.join(path, "common.npz")
    size = os.path.getsize(target)
    with open(target, "r+b") as fh:
        fh.seek(size // 2)
        b = fh.read(1)
        fh.seek(size // 2)
        fh.write(bytes([b[0] ^ 0xFF]))
    problems = checker.check([path], verbose=False)
    assert len(problems) == 1 and "CheckpointDigestError" in problems[0]


def test_repair_scan_reports_recovery_order(tmp_path):
    checker = _load_checker()
    params = es.ScalableParams(n=8, u=128)
    fam = str(tmp_path / "fam")
    os.makedirs(fam)
    state = es.init_state(params, seed=0)
    for t in (2, 4, 6):
        ckpt.save_checkpoint(
            os.path.join(fam, "ckpt-%010d" % t), state, params, meta={"tick": t}
        )
    # torn newest
    mpath = os.path.join(fam, "ckpt-%010d" % 6, ckpt.MANIFEST_NAME)
    with open(mpath, "r+b") as fh:
        fh.truncate(os.path.getsize(mpath) // 2)
    report = checker.repair_scan(fam, verbose=False)
    assert [t for t, _ in report["valid"]] == [4, 2]  # newest-first
    assert [t for t, _, _ in report["corrupt"]] == [6]
    assert report["resume_from"][0] == 4
    # CLI contract: salvageable family exits 0, hopeless family exits 1
    assert checker.main(["--repair-scan", fam, "-q"]) == 0
    for t in (2, 4):
        mp = os.path.join(fam, "ckpt-%010d" % t, ckpt.MANIFEST_NAME)
        os.remove(mp)
    assert checker.main(["--repair-scan", fam, "-q"]) == 1
