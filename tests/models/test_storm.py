"""ScalableCluster churn-storm driver."""

import numpy as np

from ringpop_tpu.models.sim import engine_scalable as es
from ringpop_tpu.models.sim.storm import ScalableCluster, StormSchedule


def test_churn_storm_reconverges():
    n = 64
    sim = ScalableCluster(
        n=n, params=es.ScalableParams(n=n, u=192, suspicion_ticks=4)
    )
    ring0 = sim.ring_checksum()
    sched = StormSchedule.churn_storm(
        ticks=40, n=n, fraction=0.1, fail_tick=1, rejoin_tick=20, seed=3
    )
    ms = sim.run(sched)
    # storm detected: suspects and faulties published
    assert ms.suspects_published.sum() >= 1
    assert ms.faulties_published.sum() >= 1
    # post-rejoin, the cluster reconverges to one view
    assert int(ms.distinct_checksums[-1]) == 1
    assert int(ms.live_nodes[-1]) == n
    # ring rebalance: during the storm the ring digest changed, after full
    # rejoin + alive re-assertions everyone is back in the ring
    ring1 = sim.ring_checksum()
    assert ring1 == ring0  # all nodes alive again -> same ring membership


def test_ring_checksum_tracks_membership():
    n = 32
    sim = ScalableCluster(n=n, params=es.ScalableParams(n=n, u=192, suspicion_ticks=2))
    r_full = sim.ring_checksum()
    sched = StormSchedule(ticks=10, n=n)
    sched.kill[1, :4] = True
    sim.run(sched)
    assert int(np.asarray(sim.state.truth_status)[:4].max()) >= es.SUSPECT
    r_degraded = sim.ring_checksum()
    assert r_degraded != r_full


def test_checksum_on_demand_mode():
    n = 32
    sim = ScalableCluster(
        n=n,
        params=es.ScalableParams(n=n, u=192, checksum_in_tick=False),
    )
    sched = StormSchedule(ticks=5, n=n)
    sim.run(sched)
    cs = sim.checksums()
    assert np.unique(cs).size == 1


def test_storm_schedule_with_leaves():
    """A storm mixing graceful leaves with kills runs under one scan and
    reconverges after rejoin."""
    n = 48
    params = es.ScalableParams(n=n, u=256, suspicion_ticks=4, enable_leave=True)
    cluster = ScalableCluster(n=n, params=params, seed=3)
    leave = np.zeros((50, n), bool)
    kill = np.zeros((50, n), bool)
    revive = np.zeros((50, n), bool)
    leave[2, :6] = True   # 6 graceful leavers
    kill[2, 10:13] = True  # 3 crashes
    revive[25, :6] = True  # leavers rejoin
    revive[25, 10:13] = True  # crashed restart
    sched = StormSchedule(ticks=50, n=n, kill=kill, revive=revive, leave=leave)
    m = cluster.run(sched)
    assert int(np.asarray(m.leaves_published)[2]) == 6
    assert int(np.asarray(m.live_nodes)[-1]) == n
    assert int(np.asarray(m.distinct_checksums)[-1]) == 1
    ts = np.asarray(cluster.state.truth_status)
    assert (ts == es.ALIVE).all()
