"""ScalableCluster churn-storm driver."""

import numpy as np

from ringpop_tpu.models.sim import engine_scalable as es
from ringpop_tpu.models.sim.storm import ScalableCluster, StormSchedule


def test_churn_storm_reconverges():
    n = 64
    sim = ScalableCluster(
        n=n, params=es.ScalableParams(n=n, u=192, suspicion_ticks=4)
    )
    ring0 = sim.ring_checksum()
    sched = StormSchedule.churn_storm(
        ticks=40, n=n, fraction=0.1, fail_tick=1, rejoin_tick=20, seed=3
    )
    ms = sim.run(sched)
    # storm detected: suspects and faulties published
    assert ms.suspects_published.sum() >= 1
    assert ms.faulties_published.sum() >= 1
    # post-rejoin, the cluster reconverges to one view
    assert int(ms.distinct_checksums[-1]) == 1
    assert int(ms.live_nodes[-1]) == n
    # ring rebalance: during the storm the ring digest changed, after full
    # rejoin + alive re-assertions everyone is back in the ring
    ring1 = sim.ring_checksum()
    assert ring1 == ring0  # all nodes alive again -> same ring membership


def test_ring_checksum_tracks_membership():
    n = 32
    sim = ScalableCluster(n=n, params=es.ScalableParams(n=n, u=192, suspicion_ticks=2))
    r_full = sim.ring_checksum()
    sched = StormSchedule(ticks=10, n=n)
    sched.kill[1, :4] = True
    sim.run(sched)
    assert int(np.asarray(sim.state.truth_status)[:4].max()) >= es.SUSPECT
    r_degraded = sim.ring_checksum()
    assert r_degraded != r_full


def test_checksum_on_demand_mode():
    n = 32
    sim = ScalableCluster(
        n=n,
        params=es.ScalableParams(n=n, u=192, checksum_in_tick=False),
    )
    sched = StormSchedule(ticks=5, n=n)
    sim.run(sched)
    cs = sim.checksums()
    assert np.unique(cs).size == 1
