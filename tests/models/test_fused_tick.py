"""Fused full-fidelity tick (SimParams.fused_tick): gate-equivalence.

ISSUE 14 acceptance pins:

- the fused tick ("xla" twin and "pallas" interpret kernels alike) is
  bitwise-identical to the classic phase-by-phase path on EVERY
  SimState field and TickMetrics counter, across ``gate_phases`` x
  ``histograms`` x ``flight_recorder`` (n=64 tier-1, n=1k farmhash
  slow),
- ``step()`` == ``run()`` under the fused tick,
- a checkpoint written under one fused_tick mode restores and finishes
  the identical trajectory under another (trajectory-neutral knob,
  checkpoint._TRAJECTORY_NEUTRAL_PARAMS).
"""

from __future__ import annotations

import itertools

import numpy as np
import pytest
import jax.numpy as jnp

from ringpop_tpu.models.sim import engine
from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster

N = 64
TICKS = 32


def _schedule(n: int, ticks: int) -> EventSchedule:
    """Every fused site exercised: kills (suspicion starts + ping-req
    + expiry), revive (join merge + makeAlive), graceful leave + rejoin
    (admin self-writes), steady dissemination in between."""
    sched = EventSchedule(ticks=ticks, n=n)
    sched.kill[3, 5] = True
    sched.revive[ticks // 2, 5] = True
    sched.kill[7, 11] = True
    sched.leave = np.zeros((ticks, n), bool)
    sched.leave[5, 9] = True
    sched.join[3 * ticks // 4, 9] = True
    return sched


def _run(fused_tick: str, n: int = N, ticks: int = TICKS, **params):
    p = engine.SimParams(
        n=n,
        checksum_mode=params.pop("checksum_mode", "fast"),
        suspicion_ticks=6,
        fused_tick=fused_tick,
        **params,
    )
    sim = SimCluster(n=n, params=p, seed=1)
    sim.bootstrap()
    metrics = sim.run(_schedule(n, ticks))
    return sim, metrics


def _assert_same(sim_a, m_a, sim_b, m_b, label):
    for f in engine.SimState._fields:
        v_a = getattr(sim_a.state, f)
        if v_a is None:
            continue
        assert np.array_equal(
            np.asarray(getattr(sim_b.state, f)), np.asarray(v_a)
        ), "state field %r diverged under %s" % (f, label)
    for f in engine.TickMetrics._fields:
        assert np.array_equal(
            np.asarray(getattr(m_b, f)), np.asarray(getattr(m_a, f))
        ), "metric %r diverged under %s" % (f, label)


@pytest.fixture(scope="module")
def classic_run():
    return _run("off")


@pytest.mark.parametrize(
    "gate,hist,flight",
    list(itertools.product([True, False], [False, True], [False, True])),
)
def test_fused_xla_bitwise_across_obs_combos(classic_run, gate, hist, flight):
    sim_off, m_off = classic_run
    sim, m = _run(
        "xla",
        gate_phases=gate,
        histograms=hist,
        flight_recorder=flight,
        event_capacity=1 << 15,
    )
    _assert_same(
        sim_off, m_off, sim, m,
        "fused_tick=xla gate=%s hist=%s flight=%s" % (gate, hist, flight),
    )


def test_fused_pallas_interpret_bitwise(classic_run):
    sim_off, m_off = classic_run
    sim, m = _run("pallas")
    _assert_same(sim_off, m_off, sim, m, "fused_tick=pallas")


def test_auto_resolution_and_knob_validation():
    import jax

    p = engine.SimParams(n=8, checksum_mode="fast")
    backend = jax.default_backend()
    # small-n off-TPU auto keeps the classic shape (the BENCH_r15
    # crossover); at ladder scale the twin takes over
    resolved = engine.resolve_fused_tick(p, backend)
    assert resolved == ("pallas" if backend == "tpu" else "off")
    big = engine.resolve_fused_tick(p._replace(n=4096), backend)
    assert big == ("pallas" if backend == "tpu" else "xla")
    # explicit values honored; junk rejected with the toolkit message
    assert engine.resolve_fused_tick(
        p._replace(fused_tick="off"), backend
    ) == "off"
    with pytest.raises(ValueError, match="fused_tick must be auto"):
        engine.resolve_fused_tick(p._replace(fused_tick="bogus"), backend)
    # driver construction pins a concrete value
    sim = SimCluster(n=8, params=p, seed=0)
    assert sim.params.fused_tick in ("pallas", "xla", "off")


def test_step_equals_scan_fused():
    p = engine.SimParams(
        n=N, checksum_mode="fast", suspicion_ticks=6, fused_tick="xla"
    )
    sched = _schedule(N, 12)
    sim_scan = SimCluster(n=N, params=p, seed=1)
    sim_scan.bootstrap()
    sim_scan.run(sched)
    sim_step = SimCluster(n=N, params=p, seed=1)
    sim_step.bootstrap()
    inputs = sched.as_inputs()
    for t in range(12):
        sim_step.step(
            engine.TickInputs(
                kill=inputs.kill[t],
                revive=inputs.revive[t],
                join=inputs.join[t],
                partition=inputs.partition[t],
                resume=None,
                leave=inputs.leave[t],
            )
        )
    for f in engine.SimState._fields:
        v = getattr(sim_scan.state, f)
        if v is None:
            continue
        assert np.array_equal(
            np.asarray(getattr(sim_step.state, f)), np.asarray(v)
        ), f


def test_checkpoint_roundtrip_toggles_fused_knob(tmp_path, classic_run):
    """Save mid-storm under fused_tick="xla", resume under "off" (and
    back) — the finished trajectory must equal the uninterrupted
    classic run's (trajectory-neutral knob)."""
    sim_off, _ = classic_run
    sched = _schedule(N, TICKS)
    first = EventSchedule(
        ticks=TICKS // 2,
        n=N,
        kill=sched.kill[: TICKS // 2].copy(),
        revive=sched.revive[: TICKS // 2].copy(),
        join=sched.join[: TICKS // 2].copy(),
        partition=sched.partition[: TICKS // 2].copy(),
        leave=sched.leave[: TICKS // 2].copy(),
    )
    second = EventSchedule(
        ticks=TICKS - TICKS // 2,
        n=N,
        kill=sched.kill[TICKS // 2:].copy(),
        revive=sched.revive[TICKS // 2:].copy(),
        join=sched.join[TICKS // 2:].copy(),
        partition=sched.partition[TICKS // 2:].copy(),
        leave=sched.leave[TICKS // 2:].copy(),
    )
    p_x = engine.SimParams(
        n=N, checksum_mode="fast", suspicion_ticks=6, fused_tick="xla"
    )
    sim = SimCluster(n=N, params=p_x, seed=1)
    sim.bootstrap()
    sim.run(first)
    path = str(tmp_path / "ckpt_fused")
    sim.save(path)

    p_off = p_x._replace(fused_tick="off")
    resumed = SimCluster(n=N, params=p_off, seed=1)
    resumed.bootstrap()  # replaced by the load below
    resumed.load(path)
    resumed.run(second)
    for f in engine.SimState._fields:
        v = getattr(sim_off.state, f)
        if v is None:
            continue
        assert np.array_equal(
            np.asarray(getattr(resumed.state, f)), np.asarray(v)
        ), "resumed (xla->off) state field %r diverged" % f

    # and the reverse toggle: classic save, fused resume
    sim2 = SimCluster(n=N, params=p_off, seed=1)
    sim2.bootstrap()
    sim2.run(first)
    path2 = str(tmp_path / "ckpt_classic")
    sim2.save(path2)
    resumed2 = SimCluster(n=N, params=p_x, seed=1)
    resumed2.bootstrap()
    resumed2.load(path2)
    resumed2.run(second)
    assert np.array_equal(
        np.asarray(resumed2.state.checksum), np.asarray(sim_off.state.checksum)
    )
    assert np.array_equal(
        np.asarray(resumed2.state.status), np.asarray(sim_off.state.status)
    )


def test_op_resolution_runlog_and_gauges(tmp_path):
    """The toolkit's shared resolution observability: attach_recorder
    lands one op_resolution row per fused-op knob, and the statsd
    emitter publishes the PR-9 gauge shape."""
    import json

    from ringpop_tpu.obs.recorder import RunRecorder

    p = engine.SimParams(n=8, checksum_mode="fast")
    sim = SimCluster(n=8, params=p, seed=0)
    path = tmp_path / "r.runlog.jsonl"
    rec = RunRecorder(str(path))
    sim.attach_recorder(rec)
    rec.close()
    rows = [json.loads(l) for l in path.read_text().splitlines()]
    res = {
        r["knob"]: r for r in rows
        if r.get("kind") == "event" and r.get("name") == "op_resolution"
    }
    assert {"fused_checksum", "fused_tick", "parity_recompute"} <= set(res)
    assert res["fused_tick"]["impl"] == sim.params.fused_tick
    assert res["fused_tick"]["requested"] == "auto"

    class Bridge:
        def __init__(self):
            self.gauges = {}

        def gauge(self, key, value):
            self.gauges[key] = value

    b = Bridge()
    sim.emit_resolution_stat(b)
    assert "sim.fused_tick.resolution_differs" in b.gauges
    assert b.gauges["sim.fused_tick.resolution_differs"] in (0, 1)


@pytest.mark.slow
def test_fused_bitwise_n1k_farmhash():
    """The n=1k farmhash rung of the acceptance gate: full parity
    checksums, classic vs fused twin, every state field bitwise."""
    n, ticks = 1024, 12
    sim_off, m_off = _run("off", n=n, ticks=ticks, checksum_mode="farmhash")
    sim_x, m_x = _run("xla", n=n, ticks=ticks, checksum_mode="farmhash")
    _assert_same(sim_off, m_off, sim_x, m_x, "n=1k farmhash fused_tick=xla")


def test_sharded_fused_tick_resolution():
    """ShardedSim must never embed pallas kernels in a GSPMD tick: the
    sharded resolver drops pallas to the partitionable xla twin (the
    round-14 exchange lesson applied up front) and the driver keeps an
    observable resolution note."""
    import jax

    from ringpop_tpu.parallel.mesh import ShardedSim, make_mesh

    backend = jax.default_backend()
    p = engine.SimParams(n=4096, checksum_mode="fast")
    # table: auto-on-tpu and explicit pallas both drop to xla; xla/off
    # honored; small-n off-TPU auto keeps the single-device pick
    assert engine.resolve_sharded_fused_tick(p, "tpu") == "xla"
    assert engine.resolve_sharded_fused_tick(
        p._replace(fused_tick="pallas"), backend
    ) == "xla"
    assert engine.resolve_sharded_fused_tick(
        p._replace(fused_tick="off"), backend
    ) == "off"
    assert engine.resolve_sharded_fused_tick(
        p._replace(n=8), "cpu"
    ) == engine.resolve_fused_tick(p._replace(n=8), "cpu")

    sim = ShardedSim(
        n=16,
        mesh=make_mesh(1),
        params=engine.SimParams(n=16, checksum_mode="fast",
                                fused_tick="pallas"),
    )
    assert sim.params.fused_tick == "xla"
    note = sim.fused_tick_resolution()
    assert note["requested"] == "pallas"
    assert note["impl"] == "xla"
    assert note["shards"] == 1
    sim.bootstrap()
    sim.step()
