"""Scalable engine partition-input merge (engine_scalable.py fault plane).

ISSUE 7 satellite: the ``inputs.partition >= 0`` masked partial-regroup
path and the ``partition=None`` pytree-structure-preserving path had no
direct coverage — the fuzzer leans on both (every fuzz schedule carries a
dense partition plane; quiet drivers carry None)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.models.sim import engine_scalable as es
from ringpop_tpu.models.sim.storm import ScalableCluster, StormSchedule

N = 16


def _params(**kw):
    kw.setdefault("n", N)
    kw.setdefault("u", 128)
    kw.setdefault("suspicion_ticks", 4)
    return es.ScalableParams(**kw)


def _state_eq(a, b):
    fa = jax.tree.flatten(jax.tree.map(np.asarray, a))[0]
    fb = jax.tree.flatten(jax.tree.map(np.asarray, b))[0]
    assert len(fa) == len(fb)
    return all(np.array_equal(x, y) for x, y in zip(fa, fb))


def test_partial_regroup_masks_negative_entries():
    """Entries >= 0 reassign; -1 entries keep the CURRENT group — a
    partial regroup touches only the named nodes."""
    params = _params()
    state = es.init_state(params, seed=0)
    # first: a full split
    groups = np.zeros(N, np.int32)
    groups[N // 2:] = 1
    inputs = es.ChurnInputs.quiet(N)._replace(
        partition=jnp.asarray(groups)
    )
    state, _ = es.tick(state, inputs, params)
    assert np.array_equal(np.asarray(state.partition), groups)
    # then: move ONLY node 3 to group 1, everyone else -1 (keep)
    partial = np.full(N, -1, np.int32)
    partial[3] = 1
    state, _ = es.tick(
        state,
        es.ChurnInputs.quiet(N)._replace(partition=jnp.asarray(partial)),
        params,
    )
    want = groups.copy()
    want[3] = 1
    assert np.array_equal(np.asarray(state.partition), want)


def test_partition_none_matches_dense_keep_and_preserves_structure():
    """partition=None must be bitwise-identical to a dense all -1 plane,
    and must keep the quiet-inputs pytree structure (one compiled
    executable serves partition-free ticks: the jit cache does not grow
    when None-structured inputs repeat)."""
    params = _params()
    state0 = es.init_state(params, seed=1)
    fn = jax.jit(functools.partial(es.tick, params=params))

    quiet = es.ChurnInputs.quiet(N)
    assert quiet.partition is None  # the structure-preserving contract
    s_none, m_none = fn(state0, quiet)
    caches = getattr(fn, "_cache_size", None)
    if caches is not None:
        assert fn._cache_size() == 1
    # same structure, fresh values: must reuse the executable
    s_none2, _ = fn(s_none, es.ChurnInputs.quiet(N))
    if caches is not None:
        assert fn._cache_size() == 1

    dense = quiet._replace(partition=jnp.full(N, -1, jnp.int32))
    s_dense, m_dense = fn(state0, dense)
    if caches is not None:
        assert fn._cache_size() == 2  # new pytree structure: one recompile
    assert _state_eq(s_none, s_dense)
    assert _state_eq(m_none, m_dense)


def test_split_blocks_rumor_flow_until_heal():
    """Partition cuts gate every exchange: an ISOLATED node (alone in
    its group — rumor slots themselves are shared by both sides, so a
    lone node is the clean witness) hears no rumor born during the cut,
    then floods after the heal.  The wavefront matrix is the proof."""
    params = _params(wavefront=True, packet_loss=0.0)
    lone = N - 1
    cluster = ScalableCluster(n=N, params=params, seed=3)
    # split at row 1 (node `lone` alone in group 1), kill at row 2
    pre = StormSchedule(ticks=10, n=N)
    pre.partition = np.full((10, N), -1, np.int32)
    groups = np.zeros(N, np.int32)
    groups[lone] = 1
    pre.partition[1] = groups
    pre.kill[2, 0] = True
    ms = cluster.run(pre)
    assert int(np.asarray(ms.suspects_published).sum()) >= 1
    fh = np.asarray(cluster.state.first_heard)
    births = np.asarray(cluster.state.r_birth)
    born = np.asarray(cluster.state.r_active) & (births >= 3)
    assert born.any(), "the kill must have published a rumor"
    # rumor slots are SHARED batches: the lone node may co-publish into
    # a slot (it falsely suspects its unreachable partners), stamping
    # its own first_heard at the slot's birth tick — but it can never
    # LEARN a slot via exchange across the cut (stamp > birth)
    lone_fh = fh[lone, np.nonzero(born)[0]]
    lone_birth = births[np.nonzero(born)[0]]
    assert (
        (lone_fh == -1) | (lone_fh == lone_birth)
    ).all(), "an isolated node must not learn rumors across the cut"
    unheard = born.copy()
    unheard[np.nonzero(born)[0]] &= fh[lone, np.nonzero(born)[0]] == -1
    # heal + a few ticks: the rumors flood the rejoined node
    post = StormSchedule(ticks=6, n=N)
    post.partition = np.full((6, N), -1, np.int32)
    post.partition[0] = 0
    cluster.run(post)
    fh2 = np.asarray(cluster.state.first_heard)
    still_active = np.asarray(cluster.state.r_active) & unheard
    assert still_active.any()
    assert (fh2[lone, np.nonzero(still_active)[0]] >= 0).all(), (
        "healed node must catch up on the cut's rumors"
    )


def test_storm_schedule_partition_plane_matches_stepwise():
    """StormSchedule's partition plane drives the scanned run exactly
    like per-tick ChurnInputs partitions."""
    params = _params()
    sched = StormSchedule(ticks=6, n=N)
    sched.partition = np.full((6, N), -1, np.int32)
    sched.partition[1, :4] = 2
    sched.kill[2, 1] = True
    sched.partition[4] = 0

    scanned = ScalableCluster(n=N, params=params, seed=5)
    scanned.run(sched)
    # snapshot into OWNED host copies BEFORE running the twin: the
    # driver's executables donate their input state, and comparing two
    # live device states across further donating dispatches is exactly
    # the aliasing hazard the ScalableCluster docstring warns about —
    # and on CPU a bare device_get can be ZERO-COPY, which would keep
    # the snapshot aliased to the buffer at risk
    scanned_state = jax.tree.map(
        lambda a: np.array(a, copy=True), jax.device_get(scanned.state)
    )

    stepped = ScalableCluster(n=N, params=params, seed=5)
    for t in range(6):
        stepped.step(
            es.ChurnInputs(
                kill=jnp.asarray(sched.kill[t]),
                revive=jnp.asarray(sched.revive[t]),
                partition=jnp.asarray(sched.partition[t]),
            )
        )
    assert _state_eq(scanned_state, jax.device_get(stepped.state))
