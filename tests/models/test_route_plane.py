"""Routing plane: counter semantics + impl gate-equivalence + driver.

Pins (ISSUE 6): RouteMetrics bitwise-identical between
``ring_impl="incremental"`` and the full-sort twin over a churn storm;
materialized truth rings bitwise-equal; counters follow the
send.js:91-208 / index.js:168-229 semantics the host proxy's accounting
tests pin one request at a time."""

import numpy as np
import pytest

import jax.numpy as jnp

from ringpop_tpu.models.route.plane import (
    RoutedStorm,
    RouteParams,
    resolve_ring_impl,
    resolve_route_params,
)
from ringpop_tpu.models.sim import engine_scalable as es
from ringpop_tpu.models.sim.storm import StormSchedule


def _params(n, **kw):
    return es.ScalableParams(n=n, u=192, suspicion_ticks=4, **kw)


def _route(n, **kw):
    base = dict(queries_per_tick=256, key_space=1024)
    base.update(kw)
    return RouteParams(n=n, **base)


def _storm(n, ticks, seed=3):
    return StormSchedule.churn_storm(
        ticks=ticks, n=n, fraction=0.1, fail_tick=1,
        rejoin_tick=ticks // 2, seed=seed,
    )


def test_resolution():
    p = RouteParams(n=16)
    assert resolve_ring_impl(p, "cpu") == "incremental"
    assert resolve_ring_impl(p._replace(ring_impl="full"), "tpu") == "full"
    with pytest.raises(ValueError):
        resolve_ring_impl(p._replace(ring_impl="rbtree"), "cpu")
    r = resolve_route_params(p, "cpu")
    assert r.ring_impl == "incremental" and r.bucket_bits >= 1


def test_gate_equivalence_incremental_vs_full_sort_twin():
    n = 64
    sched = _storm(n, 30)
    runs = {}
    for impl in ("incremental", "full"):
        rs = RoutedStorm(
            n, params=_params(n), route=_route(n, ring_impl=impl), seed=1
        )
        em, rm = rs.run(sched)
        runs[impl] = (em, rm, np.asarray(rs.truth_ring()))
    em_i, rm_i, ring_i = runs["incremental"]
    em_f, rm_f, ring_f = runs["full"]
    assert (ring_i == ring_f).all()  # the bitwise ring gate
    for f in rm_i._fields:
        assert (
            np.asarray(getattr(rm_i, f)) == np.asarray(getattr(rm_f, f))
        ).all(), f
    for f in em_i._fields:  # routing is membership-trajectory-neutral
        assert (
            np.asarray(getattr(em_i, f)) == np.asarray(getattr(em_f, f))
        ).all(), f


def test_quiet_cluster_routes_cleanly():
    n = 32
    rs = RoutedStorm(n, params=_params(n), route=_route(n), seed=0)
    em, rm = rs.run(StormSchedule(ticks=6, n=n))
    rm = {f: np.asarray(getattr(rm, f)) for f in rm._fields}
    # no churn: no ring motion, no misroutes, no rejects, no retries
    assert rm["route_queries"].sum() > 0
    for f in (
        "route_misroutes",
        "route_reroute_local",
        "route_reroute_remote",
        "route_keys_diverged",
        "route_checksums_differ",
        "route_checksum_rejects",
        "route_ring_changed",
        "route_ring_dirty_buckets",
        "route_ring_full_rebuilds",
    ):
        assert rm[f].sum() == 0, f
    assert (rm["route_ring_points"] == n * 16).all()


def test_storm_produces_routing_pathology():
    n = 64
    rs = RoutedStorm(
        n,
        params=_params(n),
        route=_route(n, multi_key_frac=0.5),
        seed=1,
    )
    em, rm = rs.run(_storm(n, 30))
    assert rm.route_misroutes.sum() > 0
    assert (
        rm.route_reroute_local.sum() + rm.route_reroute_remote.sum() > 0
    )
    # checksum divergence appears during the storm and the reject stat
    # tracks the differ stat one-to-one under enforce_consistency
    assert rm.route_checksums_differ.sum() > 0
    assert (
        np.asarray(rm.route_checksum_rejects)
        == np.asarray(rm.route_checksums_differ)
    ).all()
    # churn dirtied buckets but never overflowed the default caps
    assert rm.route_ring_changed.sum() > 0
    assert rm.route_ring_dirty_buckets.sum() > 0
    assert rm.route_ring_full_rebuilds.sum() == 0


def test_reroute_split_is_exhaustive():
    # every misroute resolves to exactly one of {local, remote, owner
    # vanished}: local + remote <= misroutes, componentwise
    n = 64
    rs = RoutedStorm(n, params=_params(n), route=_route(n), seed=2)
    em, rm = rs.run(_storm(n, 25, seed=9))
    mis = np.asarray(rm.route_misroutes)
    loc = np.asarray(rm.route_reroute_local)
    rem = np.asarray(rm.route_reroute_remote)
    assert (loc + rem <= mis).all()
    assert (loc >= 0).all() and (rem >= 0).all()


def test_enforce_consistency_off_rejects_nothing():
    n = 32
    rs = RoutedStorm(
        n,
        params=_params(n),
        route=_route(n, enforce_consistency=False),
        seed=1,
    )
    em, rm = rs.run(_storm(n, 20))
    assert rm.route_checksums_differ.sum() > 0  # stat fires regardless
    assert rm.route_checksum_rejects.sum() == 0  # rejection is gated


def test_keys_diverged_fires_under_heavy_churn():
    n = 16
    sched = StormSchedule(ticks=12, n=n)
    rng = np.random.default_rng(0)
    for t in range(1, 12):
        sched.kill[t, rng.choice(n, 3, replace=False)] = True
        sched.revive[t, rng.choice(n, 3, replace=False)] = True
    rs = RoutedStorm(
        n,
        params=es.ScalableParams(n=n, u=192, suspicion_ticks=3),
        route=RouteParams(
            n=n, queries_per_tick=2048, key_space=512, multi_key_frac=1.0
        ),
        seed=0,
    )
    em, rm = rs.run(sched)
    assert rm.route_keys_diverged.sum() > 0
    # an abort presupposes a multi-key retry: diverged <= misroutes+rejects
    assert rm.route_keys_diverged.sum() <= (
        rm.route_misroutes.sum() + rm.route_checksum_rejects.sum()
    )


def test_step_matches_scanned_run():
    n = 32
    sched = _storm(n, 6)
    rs_a = RoutedStorm(n, params=_params(n), route=_route(n), seed=5)
    em_a, rm_a = rs_a.run(sched)
    rs_b = RoutedStorm(n, params=_params(n), route=_route(n), seed=5)
    kills = np.asarray(sched.kill)
    revives = np.asarray(sched.revive)
    rows = []
    for t in range(6):
        _, rm = rs_b.step(
            es.ChurnInputs(
                kill=jnp.asarray(kills[t]), revive=jnp.asarray(revives[t])
            )
        )
        rows.append(rm)
    for f in rm_a._fields:
        scanned = np.asarray(getattr(rm_a, f))
        stepped = np.asarray([getattr(r, f) for r in rows])
        assert (scanned == stepped).all(), f


def test_routed_storm_runlog(tmp_path):
    from ringpop_tpu.obs.recorder import RunRecorder, read_run_log

    n = 32
    rs = RoutedStorm(n, params=_params(n), route=_route(n), seed=1)
    rec = RunRecorder(str(tmp_path) + "/", run_id="route-test")
    rs.attach_recorder(rec)
    rs.run(_storm(n, 10))
    summary = rec.finish()
    log = read_run_log(rec.path)
    assert log["header"]["config"]["engine"] == "sim.engine_scalable+route"
    assert log["header"]["config"]["route_params"]["ring_impl"] == (
        "incremental"
    )
    row = log["ticks"][-1]["metrics"]
    for f in (
        "route_queries",
        "route_misroutes",
        "route_keys_diverged",
        "route_checksum_rejects",
        "route_ring_points",
        "live_nodes",  # sim metrics ride the same rows
    ):
        assert f in row, f
    assert summary["totals"]["route_queries"] > 0
    # the extended schema validator accepts the rows it just wrote
    import importlib.util as ilu
    import os

    spec = ilu.spec_from_file_location(
        "check_metrics_schema",
        os.path.join(
            os.path.dirname(__file__),
            "..", "..", "scripts", "check_metrics_schema.py",
        ),
    )
    mod = ilu.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.check([rec.path], verbose=False) == []


def test_checksum_in_tick_required():
    n = 16
    with pytest.raises(ValueError, match="checksum_in_tick"):
        RoutedStorm(
            n, params=es.ScalableParams(n=n, u=192, checksum_in_tick=False)
        )


def test_routed_storm_checkpoint_roundtrip_is_resume_bitwise(tmp_path):
    """ISSUE 9 satellite: persist/restore the routing-plane carry
    (membership mask + traffic rng), rebuild the incremental bucketed
    ring from the restored membership, and pin resume-bitwise against an
    uninterrupted routed storm — state, RouteMetrics, and the
    materialized truth ring."""
    n = 48
    sched = StormSchedule.churn_storm(10, n, fraction=0.2, seed=4)

    ref = RoutedStorm(n=n, params=_params(n), route=_route(n), seed=6)
    ref.run(StormSchedule.churn_storm(10, n, fraction=0.2, seed=4))
    want = {
        f: np.array(getattr(ref.cluster.state, f), copy=True)
        for f in es.ScalableState._fields
        if getattr(ref.cluster.state, f) is not None
    }
    want_ring = int(ref.ring_checksum())

    half = RoutedStorm(n=n, params=_params(n), route=_route(n), seed=6)
    em_a, rm_a = half.run(sched.window(0, 5))
    path = str(tmp_path / "ck")
    half.save(path)

    resumed = RoutedStorm(n=n, params=_params(n), route=_route(n), seed=6)
    resumed.load(path)
    # the rebuilt bucketed ring equals the incrementally-maintained one
    # field-for-field (full_rebuild is canonical)
    for f in half.rstate.ring._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(half.rstate.ring, f)),
            np.asarray(getattr(resumed.rstate.ring, f)),
            f,
        )
    em_b, rm_b = half.run(sched.window(5, 10))
    em_c, rm_c = resumed.run(sched.window(5, 10))
    for f in rm_b._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(rm_b, f)), np.asarray(getattr(rm_c, f)), f
        )
    for f, x in want.items():
        np.testing.assert_array_equal(
            x, np.asarray(getattr(resumed.cluster.state, f)), f
        )
    assert int(resumed.ring_checksum()) == want_ring


def test_routed_storm_cadence_events_reach_the_recorder(tmp_path):
    """checkpoint_every on RoutedStorm emits ckpt.saved rows through the
    SAME runlog the route metrics ride (the obs integration contract)."""
    from ringpop_tpu.obs.recorder import RunRecorder, read_run_log

    n = 32
    storm = RoutedStorm(n=n, params=_params(n), route=_route(n), seed=1)
    rec = RunRecorder(str(tmp_path / "r.runlog.jsonl"))
    storm.attach_recorder(rec)
    storm.enable_checkpoints(str(tmp_path / "fam"), every=3, keep=2)
    storm.run(StormSchedule.churn_storm(7, n, fraction=0.1, seed=0))
    rec.finish()
    log = read_run_log(rec.path)
    saved = [e for e in log["events"] if e["name"] == "ckpt.saved"]
    assert [e["tick"] for e in saved] == [3, 6]
    assert all(e["nbytes"] > 0 for e in saved)
    # route rows still complete (the schema gate's contract)
    assert log["ticks"], "tick rows missing"
    assert "route_queries" in log["ticks"][-1]["metrics"]
