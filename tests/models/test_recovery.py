"""Recovery plane: cadence, keep-last-K rotation, newest-valid fallback,
obs events + statsd counters (models/sim/recovery.py, round 13)."""

import os

import numpy as np
import pytest

from ringpop_tpu.models.sim import checkpoint as ckpt
from ringpop_tpu.models.sim import engine_scalable as es
from ringpop_tpu.models.sim.recovery import (
    CheckpointManager,
    CheckpointSpec,
    checkpoint_name,
)
from ringpop_tpu.models.sim.storm import ScalableCluster, StormSchedule

N, U = 24, 160


def _params():
    return es.ScalableParams(n=N, u=U, suspicion_ticks=4)


def _cluster(seed=5):
    return ScalableCluster(n=N, params=_params(), seed=seed)


def _sched(ticks=10, seed=1):
    return StormSchedule.churn_storm(ticks, N, fraction=0.2, seed=seed)


def _flip_byte(path):
    """Bit-rot one array file of a checkpoint dir (size-preserving)."""
    target = os.path.join(path, "common.npz")
    size = os.path.getsize(target)
    with open(target, "r+b") as fh:
        fh.seek(size // 2)
        b = fh.read(1)
        fh.seek(size // 2)
        fh.write(bytes([b[0] ^ 0xFF]))


class FakeStatsd:
    def __init__(self):
        self.counts = {}

    def increment(self, key, value=1):
        self.counts[key] = self.counts.get(key, 0) + value

    def gauge(self, key, value):
        pass


class FakeRecorder:
    def __init__(self):
        self.events = []

    def record_event(self, name, **fields):
        self.events.append((name, fields))


def _manager(tmp_path, **kw):
    c = _cluster()
    return (
        CheckpointManager(
            str(tmp_path / "fam"),
            CheckpointSpec(es.ScalableState, c.params, es.NODE_SHARDED_FIELDS),
            **kw,
        ),
        c,
    )


def test_rotation_keeps_last_k(tmp_path):
    mgr, c = _manager(tmp_path, keep=2)
    for t in (2, 4, 6, 8):
        mgr.save(t, c.state)
    assert [t for t, _ in mgr.list_checkpoints()] == [6, 8]


def test_gc_never_evicts_the_valid_fallback(tmp_path):
    """A corrupt newest checkpoint must not count toward keep: with
    keep=1 and a torn newest, GC keeps the older valid one (deleting it
    would leave recovery with nothing)."""
    mgr, c = _manager(tmp_path, keep=1)
    # lay both checkpoints down WITHOUT intermediate gc (save() gc's and
    # would evict tick 3 while tick 6 is still pristine)
    ckpt.save_checkpoint(
        mgr.path_of(3), c.state, c.params, meta={"tick": 3}
    )
    p6 = mgr.path_of(6)
    ckpt.save_checkpoint(p6, c.state, c.params, meta={"tick": 6})
    # the mid-write kill: torn manifest at the newest (shallow-visible)
    mpath = os.path.join(p6, ckpt.MANIFEST_NAME)
    with open(mpath, "r+b") as fh:
        fh.truncate(os.path.getsize(mpath) // 2)
    removed = mgr.gc()
    assert removed == []  # tick 3 is the keep=1 survivor, not tick 6
    assert [t for t, _ in mgr.list_checkpoints()] == [3, 6]
    got = mgr.restore_latest()
    assert got is not None and got[0] == 3
    assert [type(e).__name__ for _, _, e in mgr.last_errors] == [
        "CheckpointTornError"
    ]


def test_restore_falls_back_past_torn_then_resumes(tmp_path):
    mgr, c = _manager(tmp_path, keep=3)
    rec = FakeRecorder()
    statsd = FakeStatsd()
    mgr.recorder = rec
    mgr.statsd = statsd
    mgr.save(3, c.state)
    mgr.save(6, c.state)
    p9 = mgr.save(9, c.state)
    # torn newest: truncate its manifest (kill mid-write)
    mpath = os.path.join(p9, ckpt.MANIFEST_NAME)
    with open(mpath, "r+b") as fh:
        fh.truncate(os.path.getsize(mpath) // 2)
    got = mgr.restore_latest()
    assert got is not None
    tick, state = got
    assert tick == 6
    names = [e[0] for e in rec.events]
    assert "ckpt.corrupt" in names and "ckpt.resumed" in names
    corrupt = [f for n, f in rec.events if n == "ckpt.corrupt"][0]
    assert corrupt["error"] == "CheckpointTornError"
    resumed = [f for n, f in rec.events if n == "ckpt.resumed"][0]
    assert resumed["tick"] == 6 and resumed["skipped_corrupt"] == 1
    assert statsd.counts["sim.ckpt.corrupt"] == 1
    assert statsd.counts["sim.ckpt.resumed"] == 1
    # nothing valid at all -> None (clean restart), each corrupt named
    for _, p in mgr.list_checkpoints():
        _flip_byte(p)
    mpath9 = os.path.join(p9, ckpt.MANIFEST_NAME)
    assert mgr.restore_latest() is None
    assert len(mgr.last_errors) == len(mgr.list_checkpoints())


def test_save_emits_saved_event_and_counter(tmp_path):
    mgr, c = _manager(tmp_path, keep=3, shards=2)
    rec, statsd = FakeRecorder(), FakeStatsd()
    mgr.recorder = rec
    mgr.statsd = statsd
    path = mgr.save(4, c.state)
    assert os.path.basename(path) == checkpoint_name(4)
    name, fields = rec.events[0]
    assert name == "ckpt.saved"
    assert fields["tick"] == 4 and fields["shards"] == 2
    assert fields["nbytes"] > 0 and fields["wall_s"] >= 0
    assert statsd.counts["sim.ckpt.saved"] == 1


def test_cadenced_run_is_bitwise_neutral(tmp_path):
    """run() under a checkpoint cadence (scan split at cadence lines)
    must be bitwise-identical — state AND stacked metrics — to the
    unchunked scan, and leave checkpoints exactly on the grid."""
    plain = _cluster()
    m_plain = plain.run(_sched(10))
    # snapshot with copies BEFORE the twin's donating dispatches run —
    # comparing live device states across donating dispatches is the
    # documented aliasing hazard (test_scalable_partition's device_get
    # note); the crash harness snapshots the same way
    want = {
        f: np.array(getattr(plain.state, f), copy=True)
        for f in es.ScalableState._fields
        if getattr(plain.state, f) is not None
    }

    ck = _cluster()
    ck.enable_checkpoints(str(tmp_path / "fam"), every=4, keep=3)
    m_ck = ck.run(_sched(10))

    for f in es.ScalableState._fields:
        b = getattr(ck.state, f)
        if f not in want:
            assert b is None, f
            continue
        np.testing.assert_array_equal(want[f], np.asarray(b), f)
    for f in m_plain._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(m_plain, f)), np.asarray(getattr(m_ck, f)), f
        )
    assert [t for t, _ in ck.checkpoint_manager.list_checkpoints()] == [4, 8]
    assert ck.tick_count == 10


def test_step_cadence_and_restore_roundtrip(tmp_path):
    c = _cluster()
    c.enable_checkpoints(str(tmp_path / "fam"), every=2, keep=2)
    for _ in range(5):
        c.step()
    assert [t for t, _ in c.checkpoint_manager.list_checkpoints()] == [2, 4]

    # a fresh driver resumes from the newest checkpoint and continues
    # bitwise: drive the original to tick 7, the resumed from 4 -> 7
    d = _cluster()
    d.enable_checkpoints(str(tmp_path / "fam"))
    assert d.restore_latest() == 4
    assert d.tick_count == 4
    # original state at tick 4 was checkpointed; re-drive both 3 quiet
    # ticks from their respective positions: c is at 5, so step c twice
    # and d thrice to land both at tick 7
    for _ in range(2):
        c.step()
    # snapshot c BEFORE d's donating dispatches (aliasing hazard)
    want = {
        f: np.array(getattr(c.state, f), copy=True)
        for f in es.ScalableState._fields
        if getattr(c.state, f) is not None
    }
    for _ in range(3):
        d.step()
    for f, a in want.items():
        np.testing.assert_array_equal(a, np.asarray(getattr(d.state, f)), f)


def test_restore_without_enable_raises(tmp_path):
    c = _cluster()
    with pytest.raises(ValueError):
        c.restore_latest()
    with pytest.raises(ValueError):
        c.checkpoint_now()


def test_tmp_leftovers_are_ignored_by_the_scan(tmp_path):
    """A kill between tmp-write and rename leaves *.tmp.<pid> files; the
    inventory and the recovery scan must skip them."""
    mgr, c = _manager(tmp_path, keep=3)
    p = mgr.save(5, c.state)
    open(os.path.join(p, "common.npz.tmp.12345"), "wb").write(b"partial")
    open(
        os.path.join(mgr.directory, "ckpt-0000000007.tmp"), "w"
    ).write("not a checkpoint dir")
    assert [t for t, _ in mgr.list_checkpoints()] == [5]
    got = mgr.restore_latest()
    assert got is not None and got[0] == 5
