"""End-to-end simulator tests: the 'minimum end-to-end slice' — an N-node
simulated cluster joins, gossips to convergence, suffers a kill, and
re-converges with the victim marked faulty (SURVEY.md §7)."""

import numpy as np

from ringpop_tpu.models.sim import engine
from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster
from ringpop_tpu.ops import farmhash32 as fh


def make_cluster(n=5, **params):
    p = engine.SimParams(n=n, suspicion_ticks=3, **params)
    return SimCluster(n=n, params=p, seed=42)


def test_join_and_converge():
    c = make_cluster(5)
    c.bootstrap()
    took = c.run_until_converged(max_ticks=50)
    assert took >= 0, "cluster did not converge"
    groups = c.checksum_groups()
    assert len(groups) == 1
    # every node sees all 5 members alive
    for i in range(5):
        members = c.membership_of(i)
        assert len(members) == 5
        assert all(m["status"] == "alive" for m in members)


def test_checksum_matches_host_farmhash():
    c = make_cluster(4)
    c.bootstrap()
    c.run_until_converged(max_ticks=50)
    for i in range(4):
        want = fh.hash32(c.checksum_string_of(i))
        assert int(c.checksums()[i]) == want


def run_until(c, pred, max_ticks=150):
    for t in range(max_ticks):
        m = c.step()
        if pred(c, m):
            return t + 1
    return -1


def test_kill_leads_to_faulty_and_reconvergence():
    c = make_cluster(5)
    c.bootstrap()
    assert c.run_until_converged(max_ticks=50) >= 0

    c.kill([2])
    victim = c.universe.addresses[2]

    def victim_faulty_everywhere(c, m):
        if not bool(m.converged):
            return False
        for i in range(5):
            if i == 2:
                continue
            statuses = {x["address"]: x["status"] for x in c.membership_of(i)}
            if statuses.get(victim) != "faulty":
                return False
        return True

    # a transient all-suspect convergence is legitimate (the checksums agree
    # before suspicion timers fire); wait for the faulty wave to settle
    assert run_until(c, victim_faulty_everywhere) >= 0


def test_refute_suspect_comes_back_alive():
    # a suspected-but-alive node refutes with a higher incarnation
    c = make_cluster(4)
    c.bootstrap()
    assert c.run_until_converged(max_ticks=50) >= 0

    # partition node 3 away so it gets suspected...
    part = np.zeros(4, np.int32)
    part[3] = 1
    c.partition(part)
    for _ in range(4):  # long enough for suspects, shorter than faulty+full propagation
        c.step()
    suspected = any(
        m["address"] == c.universe.addresses[3] and m["status"] == "suspect"
        for i in range(3)
        for m in c.membership_of(i)
    )
    assert suspected, "partitioned node was never suspected"

    # ...then heal the partition before/after faulty: node 3 refutes
    c.partition(np.zeros(4, np.int32))
    took = c.run_until_converged(max_ticks=100)
    assert took >= 0
    for i in range(4):
        statuses = {m["address"]: m["status"] for m in c.membership_of(i)}
        assert statuses[c.universe.addresses[3]] == "alive", (i, statuses)


def test_scan_run_matches_step_loop():
    # the lax.scan path and the step() loop must produce identical states
    ca = make_cluster(4)
    cb = make_cluster(4)
    ca.bootstrap()
    cb.bootstrap()

    T = 10
    sched = EventSchedule(ticks=T, n=4)
    ms = ca.run(sched)
    for _ in range(T):
        cb.step()
    np.testing.assert_array_equal(ca.checksums(), cb.checksums())
    np.testing.assert_array_equal(
        np.asarray(ca.state.inc), np.asarray(cb.state.inc)
    )
    assert ms.converged.shape == (T,)


def test_packet_loss_still_converges():
    c = make_cluster(6, packet_loss=0.3)
    c.bootstrap()
    took = c.run_until_converged(max_ticks=200)
    assert took >= 0, "lossy cluster did not converge"


def test_revive_rejoins():
    c = make_cluster(4)
    c.bootstrap()
    assert c.run_until_converged(max_ticks=50) >= 0
    c.kill([1])
    assert c.run_until_converged(max_ticks=100) >= 0
    c.revive([1])
    took = c.run_until_converged(max_ticks=150)
    assert took >= 0, "revived node did not reconverge"
    victim = c.universe.addresses[1]
    for i in range(4):
        statuses = {m["address"]: m["status"] for m in c.membership_of(i)}
        assert statuses[victim] == "alive", (i, statuses)


def test_gate_phases_off_is_bitwise_identical():
    """gate_phases=False (straight-line phases, the TPU/vmap setting) must
    reproduce the gated engine's trajectory bit-for-bit: every gated
    branch is a masked no-op on empty inputs and its draws are salt-pure
    (SimParams.gate_phases)."""
    import numpy as np

    n = 48
    results = {}
    for gate in (True, False):
        p = engine.SimParams(
            n=n,
            checksum_mode="farmhash",
            gate_phases=gate,
            packet_loss=0.05,
            suspicion_ticks=6,
        )
        sim = SimCluster(n=n, params=p, seed=2)
        sim.bootstrap()
        sched = EventSchedule(ticks=40, n=n)
        sched.kill[7, 3] = True
        sched.revive[24, 3] = True
        m = sim.run(sched)
        results[gate] = (sim.state, m)
    st_t, m_t = results[True]
    st_f, m_f = results[False]
    for f in st_t._fields:
        a, b = np.asarray(getattr(st_t, f)), np.asarray(getattr(st_f, f))
        assert (a == b).all(), "state field %s diverges" % f
    for f in m_t._fields:
        a, b = np.asarray(getattr(m_t, f)), np.asarray(getattr(m_f, f))
        assert (a == b).all(), "metric %s diverges" % f


def test_bounded_parity_recompute_bitwise_and_overflow_replay():
    """parity_recompute="bounded" (the TPU shape: one cond-gated K-row
    encode chunk, no loop) must reproduce the gated trajectory bit-for-bit
    whenever per-tick dirty counts fit the chunk — and when they DON'T
    (bootstrap dirties every row), the overflow must surface in
    TickMetrics.parity_overflow and SimCluster must transparently replay
    the window under an exact shape so the observable trajectory is
    IDENTICAL either way."""
    import numpy as np

    n = 48
    sched_kill, sched_rev = 7, 24

    def drive(recompute, dirty_batch):
        p = engine.SimParams(
            n=n,
            checksum_mode="farmhash",
            parity_recompute=recompute,
            dirty_batch=dirty_batch,
            packet_loss=0.05,
            suspicion_ticks=6,
        )
        sim = SimCluster(n=n, params=p, seed=2)
        sim.bootstrap()
        sched = EventSchedule(ticks=40, n=n)
        sched.kill[sched_kill, 3] = True
        sched.revive[sched_rev, 3] = True
        m = sim.run(sched)
        return sim, m

    ref_sim, ref_m = drive("gated", 16)

    # chunk covers every per-tick dirty set except bootstrap's: the
    # bootstrap step overflows (all 48 rows dirty > K=16) and replays
    bounded_sim, bounded_m = drive("bounded", 16)
    assert bounded_sim.parity_replays >= 1  # bootstrap overflow replayed
    for f in ref_sim.state._fields:
        a = np.asarray(getattr(ref_sim.state, f))
        b = np.asarray(getattr(bounded_sim.state, f))
        assert (a == b).all(), "state field %s diverges" % f
    for f in ref_m._fields:
        if f == "parity_overflow":
            continue  # replay-path metric, mode-specific by design
        a, b = np.asarray(getattr(ref_m, f)), np.asarray(getattr(bounded_m, f))
        assert (a == b).all(), "metric %s diverges" % f

    # K = n can never overflow (n_dirty <= n): no replays, same trajectory
    wide_sim, _ = drive("bounded", n)
    assert wide_sim.parity_replays == 0
    assert (
        np.asarray(wide_sim.state.checksum)
        == np.asarray(ref_sim.state.checksum)
    ).all()


def test_bounded_parity_overflow_metric_from_raw_engine():
    """Direct engine users see the overflow signal: a bootstrap tick under
    "bounded" with a small chunk reports n_dirty - K uncovered rows."""
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.models.sim.cluster import default_addresses
    from ringpop_tpu.ops import checksum_encode as ce

    n = 32
    p = engine.SimParams(
        n=n, checksum_mode="farmhash", parity_recompute="bounded",
        dirty_batch=8,
    )
    u = ce.Universe.from_addresses(default_addresses(n))
    st = engine.init_state(p, seed=0, universe=u)
    inputs = engine.TickInputs.quiet(n)._replace(join=jnp.ones(n, bool))
    _, m = engine.tick(st, inputs, p, u)
    assert int(np.asarray(m.parity_overflow)) > 0


def test_bounded_parity_straightline_matches_gated():
    """bounded + gate_phases=False (no cond even around the chunk) is the
    vmap-safe shape; still bitwise vs the gated reference trajectory."""
    import numpy as np

    n = 32
    outs = {}
    for mode, gate in (("gated", True), ("bounded", False)):
        p = engine.SimParams(
            n=n,
            checksum_mode="farmhash",
            parity_recompute=mode,
            gate_phases=gate,
            dirty_batch=n,  # never overflows
            suspicion_ticks=4,
        )
        sim = SimCluster(n=n, params=p, seed=5)
        sim.bootstrap()
        sched = EventSchedule(ticks=24, n=n)
        sched.kill[6, 2] = True
        sim.run(sched)
        outs[mode] = sim.state
    for f in outs["gated"]._fields:
        a = np.asarray(getattr(outs["gated"], f))
        b = np.asarray(getattr(outs["bounded"], f))
        assert (a == b).all(), "state field %s diverges" % f


def test_resolve_auto_parity_policy():
    """The driver-level auto resolution: on TPU the fused pipeline is on
    and the bounded chunk is K=min(n, 1024) — one streaming-kernel row
    tile covers every row, so row overflow is impossible (the unfused
    K=4 ladder optimum applies only with fused_checksum="off"); gated +
    unfused on CPU with dirty_batch untouched; explicit bounded keeps
    the caller's K; the exact-fallback resolvers never return bounded
    (a bounded replay would overflow again and loop)."""
    p = engine.SimParams(n=64, checksum_mode="farmhash")
    t = engine.resolve_auto_parity(p, "tpu")
    assert (t.parity_recompute, t.dirty_batch, t.fused_checksum) == (
        "bounded",
        64,
        "on",
    )
    tu = engine.resolve_auto_parity(p._replace(fused_checksum="off"), "tpu")
    assert (tu.parity_recompute, tu.dirty_batch) == ("bounded", 4)
    c = engine.resolve_auto_parity(p, "cpu")
    assert (c.parity_recompute, c.dirty_batch, c.fused_checksum) == (
        "gated",
        p.dirty_batch,
        "off",
    )
    e = engine.resolve_auto_parity(
        p._replace(parity_recompute="bounded", dirty_batch=64), "tpu"
    )
    assert e.dirty_batch == 64  # explicit bounded: caller's K kept
    for backend in ("tpu", "cpu"):
        assert engine.resolve_parity_recompute(backend) != "bounded"
        assert (
            engine.resolve_exact_recompute(
                p._replace(fused_checksum="on"), backend
            )
            == "full"
        )  # fused replays have exactly one exact shape
