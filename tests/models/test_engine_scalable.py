"""Scalable (rumor-table) engine: publish/propagate/expire semantics.

Small-N functional tests of the O(N·U) large-scale mode — the engine behind
the 100k epidemic-broadcast / 1M churn-storm configs.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.models.sim import engine_scalable as es


def make(n=16, **kw):
    params = es.ScalableParams(n=n, u=160, **kw)
    state = es.init_state(params, seed=7)
    step = jax.jit(functools.partial(es.tick, params=params))
    return params, state, step


def run_ticks(state, step, t, n):
    ms = []
    for _ in range(t):
        state, m = step(state, es.ChurnInputs.quiet(n))
        ms.append(m)
    return state, ms


def test_quiet_cluster_stays_converged():
    params, state, step = make(n=16)
    state, ms = run_ticks(state, step, 5, 16)
    m = ms[-1]
    assert int(m.live_nodes) == 16
    assert int(m.active_rumors) == 0
    assert int(m.distinct_checksums) == 1
    assert bool(m.full_coverage)


def test_kill_publishes_suspect_then_faulty_rumor():
    params, state, step = make(n=16, suspicion_ticks=3)
    kill = jnp.zeros(16, bool).at[5].set(True)
    state, m = step(state, es.ChurnInputs(kill=kill, revive=jnp.zeros(16, bool)))
    total_susp = int(m.suspects_published)
    total_faulty = 0
    for _ in range(12):
        state, m = step(state, es.ChurnInputs.quiet(16))
        total_susp += int(m.suspects_published)
        total_faulty += int(m.faulties_published)
    assert total_susp >= 1
    assert total_faulty >= 1
    assert int(state.truth_status[5]) == es.FAULTY
    # the faulty rumor disseminates: all live nodes eventually share checksum
    state, ms = run_ticks(state, step, 10, 16)
    assert int(ms[-1].distinct_checksums) == 1


def test_rumors_reach_full_coverage():
    params, state, step = make(n=32, suspicion_ticks=50)  # long suspicion
    kill = jnp.zeros(32, bool).at[3].set(True)
    state, _ = step(state, es.ChurnInputs(kill=kill, revive=jnp.zeros(32, bool)))
    # after O(log N) push-pull rounds every live node heard the suspect rumor
    state, ms = run_ticks(state, step, 12, 32)
    assert bool(ms[-1].full_coverage)
    assert float(ms[-1].mean_heard_frac) == 1.0


def test_checksums_discriminate_views():
    params, state, step = make(n=16, packet_loss=0.9, suspicion_ticks=100)
    kill = jnp.zeros(16, bool).at[2].set(True)
    state, m = step(state, es.ChurnInputs(kill=kill, revive=jnp.zeros(16, bool)))
    # with heavy loss, right after the suspect rumor is born only some nodes
    # heard it -> more than one distinct checksum among live nodes
    state, m = step(state, es.ChurnInputs.quiet(16))
    if int(m.active_rumors) > 0 and float(m.mean_heard_frac) < 1.0:
        assert int(m.distinct_checksums) > 1


def test_revive_resets_heard_and_publishes_alive():
    params, state, step = make(n=16, suspicion_ticks=2)
    kill = jnp.zeros(16, bool).at[4].set(True)
    state, _ = step(state, es.ChurnInputs(kill=kill, revive=jnp.zeros(16, bool)))
    state, ms = run_ticks(state, step, 8, 16)
    assert int(state.truth_status[4]) == es.FAULTY
    inc_before = int(state.truth_inc[4])
    rv = jnp.zeros(16, bool).at[4].set(True)
    state, m = step(state, es.ChurnInputs(kill=jnp.zeros(16, bool), revive=rv))
    # revived node: fresh incarnation alive rumor, heard reset to just-own
    assert int(state.truth_status[4]) == es.ALIVE
    assert int(state.truth_inc[4]) > inc_before
    state, ms = run_ticks(state, step, 12, 16)
    assert int(ms[-1].distinct_checksums) == 1
    assert bool(ms[-1].full_coverage)


def test_batch_publish_delta_and_hearers():
    """One batch rumor covers a whole subject set with one scalar delta."""
    params = es.ScalableParams(n=8, u=128)
    state = es.init_state(params, seed=1)
    subj_mask = jnp.zeros(8, bool).at[1].set(True).at[6].set(True)
    hearers = jnp.zeros(8, bool).at[0].set(True)
    new_status = jnp.full(8, es.SUSPECT, jnp.int32)
    state2, csum2 = es._publish_batch(
        state, state.checksum, jnp.int32(5), subj_mask, new_status,
        state.truth_inc, hearers, jnp.int32(1),
    )
    assert bool(state2.r_active[5])
    # truth advanced only for the subjects
    ts = np.asarray(state2.truth_status)
    assert ts[1] == es.SUSPECT and ts[6] == es.SUSPECT
    assert ts[0] == es.ALIVE and ts[7] == es.ALIVE
    # delta equals the summed record-hash movement of the two subjects
    from ringpop_tpu.ops.record_mix import record_mix
    ids = jnp.arange(8, dtype=jnp.int32)
    prev = record_mix(ids, state.truth_status, state.truth_inc)
    new = record_mix(ids, new_status, state.truth_inc)
    want = np.uint32(
        (int(new[1] - prev[1]) + int(new[6] - prev[6])) & 0xFFFFFFFF
    )
    assert np.uint32(state2.r_delta[5]) == want
    # only the hearer has the bit; checksum of hearer = base + delta
    heard = np.asarray(state2.heard)
    assert (heard[0, 0] >> 5) & 1 and not (heard[3, 0] >> 5) & 1
    cs = np.asarray(es.compute_checksums(state2, params))
    assert cs[0] == np.uint32((int(state2.base_sum) + int(want)) & 0xFFFFFFFF)
    assert cs[3] == np.uint32(state2.base_sum)
    # the incrementally-returned checksums agree with the recompute
    assert (np.asarray(csum2) == cs).all()


def test_mass_churn_does_not_overflow_table():
    """10%% simultaneous churn costs 1 rumor slot, not one per victim."""
    n = 64
    params = es.ScalableParams(n=n, u=192, suspicion_ticks=3)
    state = es.init_state(params, seed=2)
    step = jax.jit(functools.partial(es.tick, params=params))
    kill = jnp.zeros(n, bool).at[jnp.arange(6)].set(True)
    state, m = step(state, es.ChurnInputs(kill=kill, revive=jnp.zeros(n, bool)))
    for _ in range(10):
        state, m = step(state, es.ChurnInputs.quiet(n))
        assert int(m.active_rumors) <= 4 * 11  # <= SLOTS_PER_TICK per tick
    rv = kill
    state, m = step(state, es.ChurnInputs(kill=jnp.zeros(n, bool), revive=rv))
    for _ in range(15):
        state, m = step(state, es.ChurnInputs.quiet(n))
    assert int(m.live_nodes) == n
    assert int(m.distinct_checksums) == 1


def test_rumor_expiry_drops_active():
    params, state, step = make(n=8, suspicion_ticks=1000, age_slack=0)
    kill = jnp.zeros(8, bool).at[2].set(True)
    state, _ = step(state, es.ChurnInputs(kill=kill, revive=jnp.zeros(8, bool)))
    # detection is evidence-based: tick until some live node's direct
    # ping draws the dead node and its ping-req evidence lands
    for _ in range(10):
        if int(jnp.sum(state.r_active)) >= 1:
            break
        state, _ = step(state, es.ChurnInputs.quiet(8))
    assert int(jnp.sum(state.r_active)) >= 1
    # max age = 15 * digits(live=7 -> 1) + 0 = 15 ticks
    state, ms = run_ticks(state, step, 20, 8)
    assert int(ms[-1].active_rumors) == 0


def test_epoch_respected_in_checksums():
    params = es.ScalableParams(n=8, u=128, epoch=999_000)
    state = es.init_state(params, seed=0)
    cs = es.compute_checksums(state, params)
    assert np.unique(np.asarray(cs)).size == 1


def test_false_suspects_under_loss_are_refuted():
    """Packet loss (no dead processes) must produce false suspects via the
    failed-direct + failed-indirect evidence path, and the suspected live
    nodes must refute with fresh incarnations — no permanent faulty marks
    (ping-req: lib/gossip/ping-req-sender.js:249-262, refute:
    lib/membership/member.js:76-81)."""
    n = 64
    params = es.ScalableParams(n=n, u=256, packet_loss=0.35, suspicion_ticks=30)
    state = es.init_state(params, seed=3)
    step = jax.jit(functools.partial(es.tick, params=params))
    total_susp = total_refute = 0
    for _ in range(40):
        state, m = step(state, es.ChurnInputs.quiet(n))
        total_susp += int(m.suspects_published)
        total_refute += int(m.refutes_published)
    assert total_susp >= 1, "35% loss never produced a false suspect"
    assert total_refute >= 1, "false suspects were never refuted"
    # run loss-free to quiesce: every refute must win — nobody stays
    # suspect/faulty, and fresh incarnations disseminate to convergence
    params2 = params._replace(packet_loss=0.0)
    step2 = jax.jit(functools.partial(es.tick, params=params2))
    for _ in range(60):
        state, m = step2(state, es.ChurnInputs.quiet(n))
    ts = np.asarray(state.truth_status)
    assert (ts == es.ALIVE).all(), np.flatnonzero(ts != es.ALIVE)
    assert int(m.distinct_checksums) == 1
    assert int(m.live_nodes) == n


def test_no_false_suspects_without_loss():
    n = 32
    params = es.ScalableParams(n=n, u=160)
    state = es.init_state(params, seed=4)
    step = jax.jit(functools.partial(es.tick, params=params))
    for _ in range(20):
        state, m = step(state, es.ChurnInputs.quiet(n))
        assert int(m.suspects_published) == 0
        assert int(m.refutes_published) == 0


def test_partition_split_brain_and_heal():
    """A partition gates every exchange: cross-side pings fail, producing
    false suspects, and the sides' checksums diverge while split (each
    side hears only its own rumors).  Cross-side suspicions ESCALATE
    during the split (the defame_by reachability gate keeps the accused
    from refuting accusations it could never have heard — reference
    faulty-retention semantics); healing restores rumor flow, the
    defamed nodes refute, and the cluster reconverges all-alive."""
    n = 32
    params = es.ScalableParams(n=n, u=256, suspicion_ticks=4)
    state = es.init_state(params, seed=5)
    step = jax.jit(functools.partial(es.tick, params=params))
    part = jnp.asarray(
        np.where(np.arange(n) < n // 2, 0, 1).astype(np.int32)
    )
    state, m = step(
        state,
        es.ChurnInputs(
            kill=jnp.zeros(n, bool), revive=jnp.zeros(n, bool), partition=part
        ),
    )
    suspects = refutes = 0
    diverged = False
    for _ in range(40):
        state, m = step(state, es.ChurnInputs.quiet(n))
        suspects += int(m.suspects_published)
        refutes += int(m.refutes_published)
        diverged = diverged or int(m.distinct_checksums) > 1
    assert suspects >= 1, "partition never produced cross-side suspects"
    assert refutes == 0, (
        "a partitioned-away subject refuted an accusation it could not "
        "have heard (defame_by reachability gate broken)"
    )
    assert diverged, "sides' checksums never diverged during the split"
    # heal: same group again
    heal = jnp.zeros(n, jnp.int32)
    state, m = step(
        state,
        es.ChurnInputs(
            kill=jnp.zeros(n, bool), revive=jnp.zeros(n, bool), partition=heal
        ),
    )
    for _ in range(80):
        state, m = step(state, es.ChurnInputs.quiet(n))
        refutes += int(m.refutes_published)
    assert refutes >= 1, "defamed live nodes never refuted after the heal"
    ts = np.asarray(state.truth_status)
    assert (ts == es.ALIVE).all(), np.flatnonzero(ts != es.ALIVE)
    assert int(m.distinct_checksums) == 1


@pytest.mark.slow
def test_100k_nodes_5pct_loss_false_suspects_refuted():
    """The 100k epidemic-broadcast regime (BASELINE.md north star: k=3
    ping-req fanout, 5% packet loss): false suspects must arise from loss
    alone and be refuted — no permanent faulty marks on live processes."""
    n = 100_000
    params = es.ScalableParams(n=n, u=512, packet_loss=0.05)
    state = es.init_state(params, seed=9)
    step = jax.jit(functools.partial(es.tick, params=params))
    susp = ref = fau = 0
    for _ in range(50):
        state, m = step(state, es.ChurnInputs.quiet(n))
        susp += int(m.suspects_published)
        ref += int(m.refutes_published)
        fau += int(m.faulties_published)
    assert susp >= 10, "5% loss at 100k nodes produced almost no suspects"
    assert ref >= 10, "false suspects were not refuted"
    assert fau == 0, "a live process was escalated to faulty"
    # drain: loss-free ticks let outstanding refutes land
    params2 = params._replace(packet_loss=0.0)
    step2 = jax.jit(functools.partial(es.tick, params=params2))
    for _ in range(40):
        state, m = step2(state, es.ChurnInputs.quiet(n))
    ts = np.asarray(state.truth_status)
    assert (ts == es.ALIVE).all()
    assert int(m.distinct_checksums) == 1


def test_graceful_leave_and_rejoin_at_scale():
    """A left node publishes status=leave at its current incarnation and
    stops initiating gossip, but keeps answering — the rumor reaches every
    live node AND the leaver. Revive on a live-but-left node rejoins:
    alive with a fresh incarnation, gossip back on."""
    n = 32
    params = es.ScalableParams(n=n, u=192, enable_leave=True)
    state = es.init_state(params, seed=6)
    step = jax.jit(functools.partial(es.tick, params=params))
    lv = jnp.zeros(n, bool).at[5].set(True)
    state, m = step(
        state, es.ChurnInputs(kill=jnp.zeros(n, bool),
                              revive=jnp.zeros(n, bool), leave=lv)
    )
    assert int(m.leaves_published) == 1
    assert int(state.truth_status[5]) == es.LEAVE
    inc_at_leave = int(state.truth_inc[5])
    assert not bool(state.gossip_on[5])
    # everyone (including the leaver) converges on the leave view; the
    # leaver must not be suspected — it still answers pings
    susp = 0
    for _ in range(25):
        state, m = step(state, es.ChurnInputs.quiet(n))
        susp += int(m.suspects_published)
    assert susp == 0
    assert int(m.distinct_checksums) == 1
    assert int(m.live_nodes) == n

    rv = jnp.zeros(n, bool).at[5].set(True)
    state, m = step(
        state, es.ChurnInputs(kill=jnp.zeros(n, bool), revive=rv)
    )
    assert int(state.truth_status[5]) == es.ALIVE
    assert int(state.truth_inc[5]) > inc_at_leave
    assert bool(state.gossip_on[5])
    for _ in range(25):
        state, m = step(state, es.ChurnInputs.quiet(n))
    assert int(m.distinct_checksums) == 1


def test_checksum_matmul_limbs_match_numpy_reference():
    """The MXU limb-matmul checksum must equal the direct mod-2^32 sum
    base_sum + Σ_{heard ∩ active} r_delta, computed independently in
    numpy — including wrap-around of large deltas."""
    n, u = 257, 256  # odd n exercises chunk padding
    params = es.ScalableParams(n=n, u=u)
    state = es.init_state(params, seed=11)
    rng = np.random.default_rng(5)
    # adversarial rumor table: huge deltas to force uint32 wrap, random
    # active set, random heard bits
    state = state._replace(
        r_active=jnp.asarray(rng.random(u) < 0.7),
        r_delta=jnp.asarray(
            rng.integers(0, 2**32, size=u, dtype=np.uint32)
        ),
        heard=jnp.asarray(
            rng.integers(0, 2**32, size=(n, u // 32), dtype=np.uint32)
        ),
        base_sum=jnp.uint32(0xDEADBEEF),
    )
    got = np.asarray(es.compute_checksums(state, params))
    # chunked path with padding: 257 rows in 64-row chunks pads the last
    # chunk; padded rows must contribute nothing
    got_padded = np.asarray(es.compute_checksums(state, params, _chunk_rows=64))
    assert (got_padded == got).all()

    active = np.asarray(state.r_active)
    delta = np.asarray(state.r_delta)
    heard = np.asarray(state.heard)
    want = np.zeros(n, np.uint32)
    for i in range(n):
        total = np.uint64(0xDEADBEEF)
        for r in range(u):
            if active[r] and (heard[i, r // 32] >> np.uint32(r % 32)) & 1:
                total += np.uint64(delta[r])
        want[i] = np.uint32(total & np.uint64(0xFFFFFFFF))
    assert (got == want).all(), np.flatnonzero(got != want)[:5]


def test_incremental_checksum_matches_recompute_through_churn():
    """state.checksum (incrementally maintained in-tick) must equal the
    full O(N*U) recompute bit-for-bit on EVERY tick of a churny run:
    kill wave, suspicion expiry, revive, refutes, packet loss, and a
    partition that forces the rare retirement-adjustment path (a revived
    node isolated so it cannot re-hear an old rumor before the rumor
    ages into base_sum — its checksum must still gain the fold's delta)."""
    n = 64
    # u >= slots_per_tick * (max_age + 2): digits(64)=2 -> 15*2+8=38 -> 120
    params = es.ScalableParams(n=n, u=160, packet_loss=0.05)
    state = es.init_state(params, seed=3)
    step = jax.jit(functools.partial(es.tick, params=params))
    victims = np.zeros(n, bool)
    victims[[3, 9, 17]] = True
    part_iso = np.zeros(n, np.int32) - 1
    part_iso[[3, 9, 17]] = 1  # isolate the revived nodes
    part_heal = np.zeros(n, np.int32)  # everyone back to group 0
    for t in range(110):
        kill = jnp.asarray(victims if t == 4 else np.zeros(n, bool))
        revive = jnp.asarray(victims if t == 12 else np.zeros(n, bool))
        if t == 12:
            inputs = es.ChurnInputs(
                kill=kill, revive=revive, partition=jnp.asarray(part_iso)
            )
        elif t == 95:
            inputs = es.ChurnInputs(
                kill=kill, revive=revive, partition=jnp.asarray(part_heal)
            )
        else:
            inputs = es.ChurnInputs(kill=kill, revive=revive)
        state, m = step(state, inputs)
        want = np.asarray(es.compute_checksums(state, params))
        got = np.asarray(state.checksum)
        assert (got == want).all(), (
            "tick %d: %d rows diverge" % (t, int((got != want).sum()))
        )
        # the gated distinct-count metric agrees with a host recount
        live = np.asarray(state.proc_alive)
        assert int(m.distinct_checksums) == np.unique(got[live]).size
    assert int(m.distinct_checksums) == 1  # healed and reconverged


def test_gate_phases_off_is_bitwise_identical_scalable():
    """ScalableParams.gate_phases=False (straight-line phases — the
    storm-on-TPU setting) must reproduce the gated engine's trajectory
    and metrics bit-for-bit."""
    n = 96
    outs = {}
    for gate in (True, False):
        params = es.ScalableParams(
            n=n, u=192, packet_loss=0.05, gate_phases=gate
        )
        st = es.init_state(params, seed=1)
        step = jax.jit(functools.partial(es.tick, params=params))
        rng = np.random.default_rng(0)
        mets = []
        for t in range(40):
            kill = jnp.asarray(rng.random(n) < (0.05 if t == 4 else 0.0))
            revive = jnp.asarray(
                np.zeros(n, bool)
                if t != 25
                else ~np.asarray(st.proc_alive)
            )
            st, m = step(st, es.ChurnInputs(kill=kill, revive=revive))
            mets.append(m)
        outs[gate] = (st, mets)
    st_t, st_f = outs[True][0], outs[False][0]
    for f in st_t._fields:
        a, b = np.asarray(getattr(st_t, f)), np.asarray(getattr(st_f, f))
        assert (a == b).all(), "state field %s diverges" % f
    for mt, mf in zip(outs[True][1], outs[False][1]):
        for f in mt._fields:
            a, b = np.asarray(getattr(mt, f)), np.asarray(getattr(mf, f))
            assert (a == b).all(), "metric %s diverges" % f


def test_farmhash_truth_checksum_matches_reference():
    """The scalable engine's on-demand parity export: the truth view's
    fused-encoded FarmHash32 must equal the host-built reference
    checksum string's hash, before and after churn mutates the truth
    chain (kill -> faulty escalation with a fresh status)."""
    from ringpop_tpu.models.sim.cluster import default_addresses
    from ringpop_tpu.ops import checksum_encode as ce
    from ringpop_tpu.ops import farmhash32 as fh

    n = 64
    params = es.ScalableParams(n=n, u=128, suspicion_ticks=3)
    uni = ce.Universe.from_addresses(default_addresses(n))
    st = es.init_state(params, seed=0)
    step = jax.jit(functools.partial(es.tick, params=params))

    def host_truth(state):
        status = np.asarray(state.truth_status)
        inc = np.asarray(state.truth_inc)
        members = []
        for j, a in enumerate(uni.addresses):
            ms = params.epoch + (int(inc[j]) - 1) * 200 if inc[j] > 0 else 0
            members.append(
                (a, ce.STATUS_STRINGS[int(status[j])], ms)
            )
        return fh.hash32(
            ";".join("%s%s%d" % m for m in sorted(members))
        )

    assert int(
        es.farmhash_truth_checksum(st, uni, params, impl="xla")
    ) == host_truth(st)

    kill = np.zeros(n, bool)
    kill[7] = True
    st, _ = step(st, es.ChurnInputs(kill=jnp.asarray(kill),
                                    revive=jnp.zeros(n, bool)))
    for _ in range(10):  # escalate to faulty in the truth chain
        st, _ = step(st, es.ChurnInputs.quiet(n))
    assert {0, 2} <= set(
        np.unique(np.asarray(st.truth_status)).tolist()
    ), "churn must mutate the truth chain for this test to bite"
    assert int(
        es.farmhash_truth_checksum(st, uni, params, impl="xla")
    ) == host_truth(st)
