"""Incremental bucketed ring kernel: bitwise equivalence gates.

The acceptance contract (ISSUE 6): under randomized churn the
incremental dirty-bucket update must be bit-identical to (a) the full
sortless re-compaction and (b) the classic full-``jnp.sort`` ring
(models/ring/device.build_ring) after :func:`materialize` — n=64 in
tier-1, n>=64k slow.  Lookups on the bucketed layout must agree with
``device.lookup`` on the flat ring, and the fixed-width ``lookup_n``
twin must match the while_loop walk inside its documented envelope."""

import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.models.ring import device as rd
from ringpop_tpu.models.route import ring_kernel as rk


def _buckets(n, r, bits):
    reps = np.asarray(rd.device_replica_hashes(n, r))
    return rk.build_buckets(reps, bits), reps


def _assert_state_equal(a, b):
    assert (np.asarray(a.seg_keys) == np.asarray(b.seg_keys)).all()
    assert (np.asarray(a.count) == np.asarray(b.count)).all()
    assert (np.asarray(a.n_points) == np.asarray(b.n_points)).all()
    assert int(a.first_owner) == int(b.first_owner)
    assert (np.asarray(a.next_owner) == np.asarray(b.next_owner)).all()


def _churn_equivalence(n, r, bits, ticks, flips_hi, seed):
    bk, reps = _buckets(n, r, bits)
    rng = np.random.default_rng(seed)
    mask = rng.random(n) < 0.8
    st = rk.full_rebuild(bk, jnp.asarray(mask))
    for t in range(ticks):
        flips = rng.choice(
            n, size=int(rng.integers(0, flips_hi)), replace=False
        )
        mask = mask.copy()
        mask[flips] = ~mask[flips]
        jmask = jnp.asarray(mask)
        st, n_changed, n_dirty, ov = rk.update(
            bk, st, jmask, max_changed=max(8, flips_hi), max_dirty=1 << bits
        )
        assert int(n_changed) == len(flips)
        _assert_state_equal(st, rk.full_rebuild(bk, jmask))
        flat = rk.materialize(st, n * r)
        ref = rd.build_ring(jnp.asarray(reps), jmask)
        assert (np.asarray(flat) == np.asarray(ref)).all(), t
    return bk, st, mask


def test_incremental_equals_full_sort_under_randomized_churn():
    _churn_equivalence(n=64, r=8, bits=4, ticks=25, flips_hi=5, seed=0)


def test_incremental_equivalence_other_geometry():
    # ragged loads: few buckets, many replica points per server
    _churn_equivalence(n=37, r=12, bits=2, ticks=15, flips_hi=4, seed=7)


@pytest.mark.slow
def test_incremental_equals_full_sort_large():
    # n>=64k: one sparse-churn pass at bench geometry
    _churn_equivalence(n=65536, r=4, bits=10, ticks=4, flips_hi=16, seed=1)


def test_overflow_falls_back_bitwise():
    bk, reps = _buckets(48, 8, 3)
    rng = np.random.default_rng(3)
    mask = rng.random(48) < 0.9
    st = rk.full_rebuild(bk, jnp.asarray(mask))
    flipped = ~mask  # mass churn: every server flips
    st2, n_changed, n_dirty, ov = rk.update(
        bk, st, jnp.asarray(flipped), max_changed=4, max_dirty=4
    )
    assert int(ov) == 1 and int(n_changed) == 48
    _assert_state_equal(st2, rk.full_rebuild(bk, jnp.asarray(flipped)))
    assert (
        np.asarray(rk.materialize(st2, 48 * 8))
        == np.asarray(rd.build_ring(jnp.asarray(reps), jnp.asarray(flipped)))
    ).all()


def test_bucketed_lookup_matches_device_lookup():
    bk, reps = _buckets(64, 8, 4)
    rng = np.random.default_rng(5)
    for trial in range(4):
        mask = jnp.asarray(rng.random(64) < rng.uniform(0.2, 0.95))
        st = rk.full_rebuild(bk, mask)
        ring = rd.build_ring(jnp.asarray(reps), mask)
        npts = rd.ring_size(mask, 8)
        keys = jnp.asarray(
            rng.integers(0, 2**32, size=512, dtype=np.uint32)
        )
        assert (
            np.asarray(rk.lookup(st, keys))
            == np.asarray(rd.lookup(ring, npts, keys))
        ).all(), trial


def test_bucketed_lookup_exact_replica_point_hits():
    # a key hashing exactly onto a replica point returns that point's
    # owner (the rbtree upperBound-is-lower-bound semantics)
    bk, reps = _buckets(32, 8, 3)
    mask = jnp.ones(32, bool)
    st = rk.full_rebuild(bk, mask)
    point_hashes = jnp.asarray(reps.reshape(-1)[:128])
    ring = rd.build_ring(jnp.asarray(reps), mask)
    npts = rd.ring_size(mask, 8)
    assert (
        np.asarray(rk.lookup(st, point_hashes))
        == np.asarray(rd.lookup(ring, npts, point_hashes))
    ).all()


def test_empty_and_single_server_ring():
    bk, reps = _buckets(16, 4, 2)
    keys = jnp.asarray(np.arange(8, dtype=np.uint32) * 0x1234567)
    empty = rk.full_rebuild(bk, jnp.zeros(16, bool))
    assert (np.asarray(rk.lookup(empty, keys)) == -1).all()
    one = rk.full_rebuild(bk, jnp.zeros(16, bool).at[5].set(True))
    assert (np.asarray(rk.lookup(one, keys)) == 5).all()


def test_materialize_shape_and_sentinel_padding():
    bk, reps = _buckets(16, 4, 2)
    mask = jnp.asarray(np.arange(16) % 2 == 0)
    st = rk.full_rebuild(bk, mask)
    flat = np.asarray(rk.materialize(st, 64))
    assert flat.shape == (64,)
    assert (flat[32:] == np.uint64(0xFFFFFFFFFFFFFFFF)).all()
    assert (np.diff(flat.astype(np.uint64)) >= 0).all() or (
        np.sort(flat) == flat
    ).all()


def test_lookup_n_fixed_matches_while_loop_walk():
    bk, reps = _buckets(24, 8, 3)
    rng = np.random.default_rng(9)
    mask = jnp.asarray(rng.random(24) < 0.7)
    ring = rd.build_ring(jnp.asarray(reps), mask)
    npts = rd.ring_size(mask, 8)
    for kh in rng.integers(0, 2**32, size=40, dtype=np.uint32):
        walk = np.asarray(rd.lookup_n(ring, npts, jnp.uint32(kh), 4))
        fixed, found = rk.lookup_n_fixed(
            ring, npts, jnp.uint32(kh), 4, width=int(npts)
        )
        # width >= n_points: the window saw the whole ring, so the twin
        # is bit-identical regardless of how many owners exist
        assert (walk == np.asarray(fixed)).all(), kh
        assert int(found) == int((walk >= 0).sum())


def test_lookup_n_fixed_short_window_envelope():
    # a window that found n unique owners agrees with the walk even when
    # width << n_points; the guarantee is conditional on found == n
    bk, reps = _buckets(32, 8, 3)
    mask = jnp.ones(32, bool)
    ring = rd.build_ring(jnp.asarray(reps), mask)
    npts = rd.ring_size(mask, 8)
    rng = np.random.default_rng(11)
    checked = 0
    for kh in rng.integers(0, 2**32, size=60, dtype=np.uint32):
        fixed, found = rk.lookup_n_fixed(
            ring, npts, jnp.uint32(kh), 3, width=24
        )
        if int(found) == 3:
            walk = np.asarray(rd.lookup_n(ring, npts, jnp.uint32(kh), 3))
            assert (walk == np.asarray(fixed)).all()
            checked += 1
    assert checked > 0  # envelope exercised, not vacuous


def test_lookup_n_fixed_empty_ring():
    bk, _ = _buckets(8, 4, 2)
    ring = rd.build_ring(
        jnp.asarray(np.asarray(rd.device_replica_hashes(8, 4))),
        jnp.zeros(8, bool),
    )
    owners, found = rk.lookup_n_fixed(
        ring, jnp.int32(0), jnp.uint32(123), 3, width=8
    )
    assert (np.asarray(owners) == -1).all() and int(found) == 0


def test_build_buckets_validates_bits():
    reps = np.asarray(rd.device_replica_hashes(8, 2))
    with pytest.raises(ValueError):
        rk.build_buckets(reps, 0)
    with pytest.raises(ValueError):
        rk.build_buckets(reps, 21)


def test_default_bucket_bits_scales():
    assert rk.default_bucket_bits(64, 8) >= 1
    assert rk.default_bucket_bits(1_000_000, 16) <= 16
    assert rk.default_bucket_bits(100_000, 16) > rk.default_bucket_bits(
        1_000, 16
    )
