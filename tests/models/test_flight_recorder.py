"""Device-side flight recorder: gate-equivalence + reconciliation.

ISSUE 4 acceptance pins:

- with the recorder *enabled*, membership trajectory and checksums are
  bit-identical to recorder-off runs (n=64 tier-1, n=1k slow),
- the decoded event stream reconciles with ``TickMetrics`` counters for
  the same window (pings, suspects_marked, faulties_marked, full_syncs),
- the drop counter is zero at tier-1 sizes, and overflow degrades
  gracefully (honest prefix + counted drops) when it is not.
"""

from __future__ import annotations

import numpy as np
import pytest

from ringpop_tpu.models.sim import engine
from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster
from ringpop_tpu.obs import events as obs_events

N = 64
TICKS = 40


def _schedule(n: int, ticks: int) -> EventSchedule:
    """Churn inside the window: a kill (suspect -> faulty escalation),
    a revive (rejoin + dissemination wave), and an operator-plane
    graceful leave + rejoin (admin self-writes) — the event-rich
    shape."""
    sched = EventSchedule(ticks=ticks, n=n)
    sched.kill[3, 5] = True
    sched.revive[ticks // 2, 5] = True
    sched.leave = np.zeros((ticks, n), bool)
    sched.leave[5, 9] = True
    sched.join[3 * ticks // 4, 9] = True  # rejoin of the left node
    return sched


def _run(n: int, ticks: int, flight: bool, **params):
    p = engine.SimParams(
        n=n,
        checksum_mode="fast",
        suspicion_ticks=6,
        flight_recorder=flight,
        **params,
    )
    sim = SimCluster(n=n, params=p, seed=1)
    sim.bootstrap()
    if flight:
        sim.drain_events()  # align the event window with the run window
    metrics = sim.run(_schedule(n, ticks))
    return sim, metrics


@pytest.fixture(scope="module")
def recorder_pair():
    sim_on, m_on = _run(N, TICKS, flight=True, event_capacity=65536)
    sim_off, m_off = _run(N, TICKS, flight=False)
    return sim_on, m_on, sim_off, m_off


def test_recorder_is_trajectory_neutral(recorder_pair):
    sim_on, m_on, sim_off, m_off = recorder_pair
    for f in engine.SimState._fields:
        v_off = getattr(sim_off.state, f)
        if v_off is None:
            continue  # recorder-only planes have no off-side twin
        assert np.array_equal(
            np.asarray(getattr(sim_on.state, f)), np.asarray(v_off)
        ), "state field %r diverged with the flight recorder on" % f
    for f in engine.TickMetrics._fields:
        assert np.array_equal(
            np.asarray(getattr(m_on, f)), np.asarray(getattr(m_off, f))
        ), "metric %r diverged with the flight recorder on" % f
    assert np.array_equal(sim_on.checksums(), sim_off.checksums())


def test_event_stream_reconciles_with_tick_metrics(recorder_pair):
    sim_on, m_on, _, _ = recorder_pair
    assert sim_on.event_drops() == 0  # tier-1 sizes must not truncate
    events = sim_on.drain_events(reset=False)
    rec = obs_events.reconcile(events, m_on)
    # the ISSUE 4 acceptance counters, plus every other counter with a
    # defined event equivalent
    for field in (
        "pings_sent",
        "suspects_marked",
        "faulties_marked",
        "full_syncs",
    ):
        assert field in rec, field
    mismatches = {k: v for k, v in rec.items() if not v["match"]}
    assert mismatches == {}, mismatches
    # the window actually exercised the detection plane
    assert rec["suspects_marked"]["events"] >= 1
    assert rec["faulties_marked"]["events"] >= 1


def test_wavefront_matrix_and_derivations(recorder_pair):
    sim_on, m_on, _, _ = recorder_pair
    fh = sim_on.first_heard()
    n = sim_on.params.n
    # every off-diagonal known cell was learned at some recorded tick
    known = np.asarray(sim_on.state.known)
    off_diag = ~np.eye(n, dtype=bool)
    assert (fh[known & off_diag] >= 1).all()
    assert (np.diagonal(fh) >= 0).all()
    # per-rumor wavefronts: curves are monotone, latencies non-negative
    events = sim_on.drain_events(reset=False)
    wavefronts = obs_events.rumor_wavefronts(events)
    assert wavefronts, "churn window must produce disseminating rumors"
    summary = obs_events.dissemination_summary(wavefronts)
    assert summary["rumors"], summary
    for r in summary["rumors"]:
        curve = r["convergence_curve"]
        assert all(
            curve[i][0] < curve[i + 1][0] and curve[i][1] < curve[i + 1][1]
            for i in range(len(curve) - 1)
        )
        assert r["convergence_latency"] >= 0
    assert summary["latency_histogram_ticks"]


def test_leave_and_rejoin_emit_admin_self_events(recorder_pair):
    """The operator-plane self-writes (graceful leave, rejoin-of-left)
    bypass the gossip apply masks — the recorder must still emit the
    rumor's BIRTH event (observer == subject, PHASE_ADMIN aux), or
    chrome-trace self-status spans and wavefront hop-0 attribution
    misassign the rumor to its first OTHER hearer."""
    sim_on, _, _, _ = recorder_pair
    events = sim_on.drain_events(reset=False)
    admin = [
        e
        for e in events
        if e["kind"] == obs_events.EV_STATUS
        and e["aux"] & obs_events.PHASE_ADMIN
    ]
    assert {(e["observer"], e["subject"]) for e in admin} == {(9, 9)}
    statuses = [e["new_status"] for e in sorted(admin, key=lambda e: e["tick"])]
    assert statuses == [3, 0]  # LEAVE self-write, then ALIVE rejoin
    # the leave rumor's wavefront is born AT the origin (hop 0)
    wavefronts = obs_events.rumor_wavefronts(events)
    leave_rumors = [
        w for rid, w in wavefronts.items() if rid[0] == 9 and rid[1] == 3
    ]
    assert leave_rumors, wavefronts.keys()
    assert any(
        w["hops"].get(9) == 0 and w["latency"].get(9) == 0
        for w in leave_rumors
    )


def test_drain_resets_the_window(recorder_pair):
    sim_on, _, _, _ = recorder_pair
    before = len(sim_on.drain_events())  # resets
    assert int(np.asarray(sim_on.state.ev_head)) == 0
    # steps, not run(): reuses the tick executable compiled at bootstrap
    # instead of tracing a fresh 3-tick scan (tier-1 budget)
    rows = [sim_on.step() for _ in range(3)]
    m = {
        f: np.stack([np.asarray(getattr(r, f)) for r in rows])
        for f in engine.TickMetrics._fields
    }
    events = sim_on.drain_events(reset=False)
    assert 0 < len(events) < max(before, 1) + N * 3
    rec = obs_events.reconcile(events, m)
    assert all(v["match"] for v in rec.values()), rec


def test_overflow_drops_and_counts_instead_of_lying():
    # capacity far below the bootstrap wave's event volume: the buffer
    # must fill, drop the excess, count it — and leave the trajectory
    # untouched (same engine, only the buffer differs)
    n, cap = 16, 64
    p = engine.SimParams(
        n=n,
        checksum_mode="fast",
        suspicion_ticks=6,
        flight_recorder=True,
        event_capacity=cap,
    )
    sim = SimCluster(n=n, params=p, seed=1)
    sim.bootstrap()
    sim.run(EventSchedule(ticks=6, n=n))
    assert int(np.asarray(sim.state.ev_head)) == cap
    drops = sim.event_drops()
    assert drops > 0
    events = sim.drain_events(reset=False)
    assert len(events) == cap
    # truncation is surfaced on every decoded event
    assert all(ev.get("truncated_stream") for ev in events)
    # the honest prefix is still schema-valid and tick-ordered
    assert obs_events.validate_event_stream(events) == []


def test_checkpoint_roundtrip_and_toggle(tmp_path, recorder_pair):
    sim_on, _, _, _ = recorder_pair
    path = str(tmp_path / "flight.ckpt")
    sim_on.save(path)
    # recorder-on resume: trajectory fields identical, buffer usable
    re_on = SimCluster(n=N, params=sim_on.params, seed=1)
    re_on.load(path)
    assert np.array_equal(
        np.asarray(re_on.state.known), np.asarray(sim_on.state.known)
    )
    assert re_on.state.ev_buf is not None
    # recorder-off resume drops the telemetry plane, keeps trajectory
    p_off = sim_on.params._replace(flight_recorder=False)
    re_off = SimCluster(n=N, params=p_off, seed=1)
    re_off.load(path)
    assert re_off.state.ev_buf is None
    assert np.array_equal(
        np.asarray(re_off.state.status), np.asarray(sim_on.state.status)
    )


@pytest.mark.slow
def test_recorder_gate_equivalence_farmhash_1k():
    """The acceptance's n=1k twin: farmhash parity mode, recorder on vs
    off, bit-identical trajectory and checksums.  The run window rides
    the post-bootstrap dissemination wave (~n^2 view adoptions), so the
    drop-free claim needs a capacity sized to the wave: 2^21 records
    (64 MiB of int32) holds the ~1M-event window with 2x margin."""
    n, ticks = 1000, 12
    sched = EventSchedule(ticks=ticks, n=n)
    sched.kill[2, 7] = True
    runs = []
    for flight in (True, False):
        p = engine.SimParams(
            n=n,
            checksum_mode="farmhash",
            suspicion_ticks=6,
            flight_recorder=flight,
            event_capacity=2**21,
        )
        sim = SimCluster(n=n, params=p, seed=3)
        sim.bootstrap()
        if flight:
            # align the event window with the run window: the n=1k
            # bootstrap wave alone is ~n^2 view-change events, far over
            # the default capacity — the acceptance drop-free claim is
            # about the churn window, not the join storm
            sim.drain_events()
        sim.run(sched)
        runs.append(sim)
    on, off = runs
    for f in engine.SimState._fields:
        v_off = getattr(off.state, f)
        if v_off is None:
            continue
        assert np.array_equal(
            np.asarray(getattr(on.state, f)), np.asarray(v_off)
        ), f
    assert on.event_drops() == 0
