"""Rumor wavefront tracing on the scalable engine.

``ScalableParams(wavefront=True)`` carries a first-heard tick matrix
through the scan; it must (a) never touch the trajectory, (b) agree
bit-for-bit with the heard bitmask it mirrors, and (c) yield sane
dissemination summaries (obs.events.scalable_wavefront_summary)."""

from __future__ import annotations

import numpy as np

import pytest

from ringpop_tpu.models.sim import engine_scalable as es
from ringpop_tpu.models.sim.storm import ScalableCluster, StormSchedule
from ringpop_tpu.obs import events as obs_events

N = 32
# 46 ticks: past max_rumor_age at n=32 (15*2 + 8 = 38), so slot
# recycling is exercised on the same compiled scan (and the same window
# shape as tests/obs/test_counter_parity.py keeps tier-1 compile count
# down)
TICKS = 46


def _run(wavefront: bool, ticks: int = TICKS):
    import jax

    sc = ScalableCluster(
        n=N,
        params=es.ScalableParams(
            n=N, u=128, suspicion_ticks=6, wavefront=wavefront
        ),
        seed=1,
    )
    sched = StormSchedule(ticks=ticks, n=N)
    sched.kill[3, 5] = True
    sched.revive[ticks // 2, 5] = True
    ms = sc.run(sched)
    # snapshot the state into OWNED host copies: the driver's scan
    # DONATES its input state, and this module compares two clusters'
    # final states across further donating dispatches — exactly the
    # aliasing hazard the ScalableCluster docstring warns about (a
    # donated-aliased buffer read after later dispatches has been seen
    # to return zeros on this image's CPU backend).  np.array(copy=True)
    # matters: on CPU both device_get and a re-upload can be ZERO-COPY,
    # which would keep the snapshot aliased to the very buffer at risk.
    sc.state = jax.tree.map(
        lambda a: np.array(a, copy=True), jax.device_get(sc.state)
    )
    return sc, ms


@pytest.fixture(scope="module")
def wavefront_run():
    return _run(True)


def test_wavefront_is_trajectory_neutral(wavefront_run):
    sc_on, m_on = wavefront_run
    sc_off, m_off = _run(False)
    for f in es.ScalableState._fields:
        v_off = getattr(sc_off.state, f)
        if v_off is None:
            continue
        assert np.array_equal(
            np.asarray(getattr(sc_on.state, f)), np.asarray(v_off)
        ), "state field %r diverged with wavefront tracing on" % f
    for f in es.ScalableMetrics._fields:
        assert np.array_equal(
            np.asarray(getattr(m_on, f)), np.asarray(getattr(m_off, f))
        ), f


def test_first_heard_mirrors_heard_bits(wavefront_run):
    sc, _ = wavefront_run
    st = sc.state
    fh = np.asarray(st.first_heard)
    heard = np.asarray(st.heard)
    active = np.asarray(st.r_active)
    tick = int(np.asarray(st.tick_index))
    u = fh.shape[1]
    bits = (
        (heard[:, :, None] >> np.arange(32, dtype=np.uint32)) & 1
    ).astype(bool).reshape(fh.shape[0], u)
    # active rumors: the bit is set iff a first-heard tick is recorded,
    # and every recorded tick is inside the run
    assert ((fh >= 0) == bits)[:, active].all()
    assert (fh[:, active].max() <= tick) if active.any() else True
    births = np.asarray(st.r_birth)
    for r in np.nonzero(active)[0]:
        lat = fh[:, r][fh[:, r] >= 0] - births[r]
        assert (lat >= 0).all()


def test_wavefront_summary_shapes(wavefront_run):
    sc, _ = wavefront_run
    summary = sc.wavefront_summary()
    assert summary["rumors"], "churn window must leave active rumors"
    for r in summary["rumors"]:
        curve = r["convergence_curve"]
        assert all(
            curve[i][0] < curve[i + 1][0] and curve[i][1] < curve[i + 1][1]
            for i in range(len(curve) - 1)
        )
        assert r["convergence_latency"] >= 0
        # the kill-era rumor disseminated beyond its publisher
        assert r["observers"] >= 1
    assert summary["latency_histogram_ticks"]
    # derivation helper accepts the raw snapshot too
    snap = sc.wavefront_snapshot()
    again = obs_events.scalable_wavefront_summary(
        snap["first_heard"], snap["r_birth"], snap["r_active"], snap["live"]
    )
    assert again == summary


def test_recycled_slots_reset_their_history(wavefront_run):
    # the window runs past max_rumor_age, so the kill-era rumors retire:
    # recycled slots must come back with a clean (-1) wavefront column
    sc, m = wavefront_run
    st = sc.state
    assert int(np.asarray(m.rumors_retired).sum()) > 0
    fh = np.asarray(st.first_heard)
    inactive = ~np.asarray(st.r_active)
    heard = np.asarray(st.heard)
    u = fh.shape[1]
    bits = (
        (heard[:, :, None] >> np.arange(32, dtype=np.uint32)) & 1
    ).astype(bool).reshape(fh.shape[0], u)
    # wherever the heard bit is clear, the wavefront must be unset too
    # (recycle clears both together)
    assert (fh[~bits] == -1).all()
    assert inactive.any()
