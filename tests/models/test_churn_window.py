"""Churn-window parity regression: kill+revive INSIDE the measured
window must stay overflow-free under the fused bounded recompute.

The round-5 verdict's catastrophic case: any dissemination wave doubled
dirty rows past every compilable K, so churn windows overflowed the
bounded chunk and replayed at the straight-line full-recompute rate
(DIAG_BOUNDED.json v2_bounded_churn: 3/3 windows replayed).  The fused
pipeline's re-tuned chunk (K = min(n, 1024) — one streaming-kernel row
tile) makes row overflow impossible at headline scale; the only replay
trigger left is cell overflow (> cell_batch changed cells in one tick),
which SWIM churn waves sit far under — bootstrap-scale full merges are
the only crossers.  These tests pin that contract end-to-end: replays
happen where expected (bootstrap), never inside the churn window, and
the trajectory stays bit-exact against the unfused engine and the host
farmhash oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.models.sim import engine
from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster
from ringpop_tpu.ops import farmhash32 as fh


def _churn_schedule(n, ticks=40, kill_at=4, revive_at=20, victims=(3, 11)):
    sched = EventSchedule(ticks=ticks, n=n)
    for v in victims:
        sched.kill[kill_at, v % n] = True
        sched.revive[revive_at, v % n] = True
    return sched


def _fused_params(n, cell_batch=16384):
    return engine.SimParams(
        n=n,
        checksum_mode="farmhash",
        fused_checksum="on",
        parity_recompute="bounded",
        dirty_batch=n,  # the auto pick: one kernel row tile covers all
        cell_batch=cell_batch,
        suspicion_ticks=6,
    )


def test_cell_overflow_replay_machinery():
    """An adversarially tiny cell_batch forces the dissemination wave
    past the changed-cell chunk: the overflow counter must fire and the
    driver's exact-shape replay must keep the trajectory bit-identical
    to an unfused run — proving the zero-replay assertions below are
    backed by live machinery, not a counter that can't trip."""
    n = 16
    sim = SimCluster(n=n, params=_fused_params(n, cell_batch=4))
    twin = SimCluster(
        n=n,
        params=sim.params._replace(
            fused_checksum="off", parity_recompute="gated"
        ),
    )
    sim.bootstrap()
    twin.bootstrap()
    for _ in range(12):
        sim.step()
        twin.step()
        assert (sim.checksums() == twin.checksums()).all()
    assert sim.parity_replays >= 1, "cell_batch=4 must overflow the wave"


def test_churn_window_zero_replays_and_parity():
    """Fused bounded churn window at n=64: zero replays, wave really
    happened, and every live node's final checksum equals the host
    farmhash oracle's hash of its own checksum string.  (Per-tick
    bitwise equality against the unfused engine is pinned at n=16 by
    test_engine_cache_invariant_under_churn — no twin cluster here, its
    compile set would double this test's tier-1 cost.)"""
    n = 64
    sim = SimCluster(n=n, params=_fused_params(n))
    sim.bootstrap()
    assert sim.run_until_converged(max_ticks=64) > 0

    # the measured churn window: kill -> suspect -> faulty -> revive ->
    # reconverge, all inside one scanned run
    pre_replays = sim.parity_replays
    sched = _churn_schedule(n)
    m = sim.run(sched)
    assert sim.parity_replays == pre_replays, (
        "churn window replayed %d times — the re-tuned chunk must hold"
        % (sim.parity_replays - pre_replays)
    )
    # the wave really happened (suspects + faulties marked in-window)
    assert np.asarray(m.suspects_marked).sum() > 0
    assert np.asarray(m.faulties_marked).sum() > 0
    assert bool(np.asarray(m.converged)[-1])
    # host farmhash oracle: every live node's cached checksum equals the
    # reference hash of its own checksum string (independent host impl)
    alive = np.asarray(sim.state.proc_alive & sim.state.ready)
    cs = sim.checksums()
    for i in np.flatnonzero(alive):
        assert int(cs[i]) == fh.hash32(sim.checksum_string_of(int(i))), i


def test_fused_checkpoint_roundtrip(tmp_path):
    """The record cache is derivable state: a fused checkpoint restores
    it verbatim, and an UNFUSED checkpoint loaded into a fused cluster
    rebuilds it from (known, status, inc) — both resume bit-exactly."""
    n = 16
    fused = SimCluster(n=n, params=_fused_params(n))
    fused.bootstrap()
    for _ in range(4):
        fused.step()
    p = str(tmp_path / "fused.npz")
    fused.save(p)
    twin = SimCluster(n=n, params=fused.params)
    twin.load(p)
    assert (
        np.asarray(twin.state.rec_bytes)
        == np.asarray(fused.state.rec_bytes)
    ).all()

    # unfused checkpoint -> fused cluster: cache rebuilt on load
    plain = SimCluster(
        n=n,
        params=fused.params._replace(
            fused_checksum="off", parity_recompute="gated"
        ),
    )
    plain.bootstrap()
    for _ in range(4):
        plain.step()
    p2 = str(tmp_path / "plain.npz")
    plain.save(p2)
    rebuilt = SimCluster(n=n, params=fused.params)
    rebuilt.load(p2)
    assert rebuilt.state.rec_bytes is not None
    # identical trajectories so far -> identical caches and, after more
    # ticks on each, identical checksums
    assert (
        np.asarray(rebuilt.state.rec_bytes)
        == np.asarray(fused.state.rec_bytes)
    ).all()
    for _ in range(3):
        fused.step()
        rebuilt.step()
        plain.step()
    assert (fused.checksums() == rebuilt.checksums()).all()
    assert (fused.checksums() == plain.checksums()).all()

    # fused -> unfused -> fused cycle (fused_checksum is checkpoint-
    # neutral): the unfused leg evolves views WITHOUT maintaining the
    # cache, so the final fused load must not trust the stored bytes —
    # regression for the silent-parity-divergence bug where load()
    # skipped the rebuild whenever rec_bytes was present
    p3 = str(tmp_path / "cycle.npz")
    fused.save(p3)
    leg = SimCluster(
        n=n,
        params=fused.params._replace(
            fused_checksum="off", parity_recompute="gated"
        ),
    )
    leg.load(p3)
    assert leg.state.rec_bytes is None  # unfused leg drops the cache
    kill = np.zeros(n, bool)
    kill[2] = True
    leg.kill(np.flatnonzero(kill))
    for _ in range(3):
        leg.step()
    leg.save(p3)
    back = SimCluster(n=n, params=fused.params)
    back.load(p3)
    from ringpop_tpu.ops import fused_checksum as fc

    dense_b, dense_l = fc.member_records(
        back.universe,
        back.state.known,
        back.state.status,
        engine.stamp_to_ms(back.state.inc, back.params),
        back.params.max_digits,
    )
    assert (np.asarray(back.state.rec_bytes) == np.asarray(dense_b)).all()
    assert (np.asarray(back.state.rec_len) == np.asarray(dense_l)).all()
    back.step()
    from ringpop_tpu.ops import farmhash32 as fh2

    cs = back.checksums()
    alive = np.asarray(back.state.proc_alive & back.state.ready)
    for i in np.flatnonzero(alive)[:4]:
        assert int(cs[i]) == fh2.hash32(back.checksum_string_of(int(i)))


@pytest.mark.slow
def test_churn_window_parity_n1k():
    """The headline-scale (N=1k) churn window, fast settings: zero
    replays inside the window and final-state host-oracle equality for a
    sample of observers (the full per-tick lockstep at 1k lives on the
    chip sweeps; this pins the CPU-runnable contract)."""
    n = 1024
    sim = SimCluster(n=n, params=_fused_params(n, cell_batch=16384))
    sim.bootstrap()
    assert sim.run_until_converged(max_ticks=96) > 0
    pre = sim.parity_replays
    sched = _churn_schedule(n, ticks=32, victims=(5, 200, 900))
    m = sim.run(sched)
    assert sim.parity_replays == pre
    assert np.asarray(m.suspects_marked).sum() > 0
    assert bool(np.asarray(m.converged)[-1])
    cs = sim.checksums()
    for i in (0, 5, 513, 900):
        assert int(cs[i]) == fh.hash32(sim.checksum_string_of(i)), i


@pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="chip-only throughput assertion (>= 1x real-time churn)",
)
def test_churn_window_throughput_tpu():
    """On-chip acceptance gate: a 1k churn window (kill+revive inside)
    must sustain >= 5,120 node-ticks/s (1x real-time) with zero replays
    — the round-5 structural hole this PR exists to close."""
    import time

    n = 1024
    sim = SimCluster(
        n=n, params=engine.SimParams(n=n, checksum_mode="farmhash")
    )
    sim.bootstrap()
    assert sim.run_until_converged(max_ticks=96) > 0
    sched = _churn_schedule(n, ticks=64, victims=(5, 200, 900))
    sim.run(sched)  # compile + warm
    jax.block_until_ready(sim.state)
    pre = sim.parity_replays
    t0 = time.perf_counter()
    m = sim.run(sched)
    jax.block_until_ready(sim.state)
    rate = n * sched.ticks / (time.perf_counter() - t0)
    assert sim.parity_replays == pre, "churn window must not replay"
    assert bool(np.asarray(m.converged)[-1])
    assert rate >= 5120, "churn window below 1x real-time: %.0f" % rate
