"""Hash ring tests mirroring /root/reference/test/unit/ring-test.js and
hashring_test.js, plus device-ring equivalence against the host ring."""

import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.models.ring import HashRing
from ringpop_tpu.models.ring import device as dring
from ringpop_tpu.ops import farmhash32 as fh


def create_servers(n):
    return ["127.0.0.1:%d" % (3000 + i) for i in range(n)]


def extract_port(server: str) -> int:
    # the reference's deterministic stub hashFunc (ring-test.js:32-34)
    return int(str(server)[str(server).rindex(":") + 1 :])


SERVERS = create_servers(200)


def test_server_count_add_remove():
    ring = HashRing()
    ring.add_remove_servers(SERVERS, None)
    assert ring.get_server_count() == len(SERVERS)
    ring.add_remove_servers(None, SERVERS)
    assert ring.get_server_count() == 0
    ring.add_remove_servers(SERVERS, SERVERS)
    assert ring.get_server_count() == 0


def test_checksum_computed_once_per_bulk_change():
    ring = HashRing()
    count = []
    ring.on("checksumComputed", lambda *a: count.append(1))
    ring.add_remove_servers(SERVERS, SERVERS)
    assert len(count) == 1


def test_lookup_own_replica_point():
    # '1000 lookups' (ring-test.js:65-79): lookup(server + '0') lands exactly
    # on server's replica-0 point; the rbtree's upperBound is >= (lower bound)
    ring = HashRing()
    ring.add_remove_servers(SERVERS, None)
    for server in SERVERS:
        assert ring.lookup(server + "0") == server


def test_lookup_n_with_port_hash():
    # '1000 lookupN' (ring-test.js:81-100): with hashFunc=extractPort the
    # successors are the next servers by port
    servers = SERVERS[:50]
    ring = HashRing(hash_func=extract_port)
    ring.add_remove_servers(servers, None)
    for i, server in enumerate(servers):
        expect = [
            servers[i],
            servers[(i + 1) % len(servers)],
            servers[(i + 2) % len(servers)],
        ]
        assert ring.lookup_n(server + "0", 3) == expect


def test_lookup_n_small_and_empty_ring():
    ring = HashRing(hash_func=extract_port)
    server = SERVERS[0]
    ring.add_remove_servers([server], None)
    assert ring.lookup_n(server + "0", 3) == [server]

    empty = HashRing(hash_func=extract_port)
    assert empty.lookup_n(server + "0", 3) == []


def test_lookup_n_corrupted_ring():
    # serverCount out of sync with the point table must not loop forever
    ring = HashRing(hash_func=extract_port)
    ring.add_remove_servers([SERVERS[0]], None)
    ring.servers[SERVERS[1]] = True  # corrupt: claims 2 servers, tree has 1
    assert ring.lookup_n(SERVERS[0] + "0", 3) == [SERVERS[0]]

    empty = HashRing(hash_func=extract_port)
    empty.servers[SERVERS[0]] = True
    assert empty.lookup_n(SERVERS[0] + "0", 3) == []


def test_checksum_lifecycle():
    ring = HashRing()
    assert ring.checksum is None
    ring.add_server(SERVERS[0])
    first = ring.checksum
    assert first is not None
    ring.remove_server("127.0.0.1:9999")  # non-existent: no recompute
    assert ring.checksum == first
    ring.add_server(SERVERS[1])
    assert ring.checksum != first
    ring.remove_server(SERVERS[1])
    assert ring.checksum == first


def test_checksum_order_independent():
    a = HashRing()
    b = HashRing()
    for s in SERVERS[:10]:
        a.add_server(s)
    for s in reversed(SERVERS[:10]):
        b.add_server(s)
    assert a.checksum == b.checksum
    # checksum equals hash32 of sorted names joined ';'
    assert a.checksum == fh.hash32(";".join(sorted(SERVERS[:10])))


def test_wraparound_past_max_hash():
    ring = HashRing()
    ring.add_remove_servers(SERVERS[:8], None)
    hashes, owners = ring.table()
    # a key hashing beyond the max ring point must wrap to the ring minimum
    max_hash = int(hashes.max())
    # find a key whose hash exceeds every point (search a few candidates)
    key = None
    for i in range(100000):
        cand = "wrap-%d" % i
        if fh.hash32(cand) > max_hash:
            key = cand
            break
    if key is None:
        pytest.skip("no key found beyond max point hash")
    min_owner = owners[int(np.argmin(hashes))]
    assert ring.lookup(key) == min_owner


# -- device ring equivalence -------------------------------------------------


def test_device_ring_matches_host():
    servers = create_servers(32)
    universe = sorted(servers)
    table = dring.replica_table(universe, replica_points=100)

    host = HashRing()
    host.add_remove_servers(servers, None)

    mask = jnp.ones(len(universe), bool)
    ring = dring.build_ring(jnp.asarray(table), mask)
    n_points = dring.ring_size(mask, 100)

    keys = ["key-%d" % i for i in range(300)]
    key_hashes = jnp.asarray(fh.hash32_strings(keys))
    owners = np.asarray(
        jnp.stack([dring.lookup(ring, n_points, h) for h in key_hashes])
    )
    for k, o in zip(keys, owners):
        assert universe[int(o)] == host.lookup(k), k


def test_device_ring_masked_rebuild_matches_host_subset():
    servers = create_servers(24)
    universe = sorted(servers)
    table = dring.replica_table(universe, replica_points=100)

    alive = [s for i, s in enumerate(universe) if i % 3 != 0]
    host = HashRing()
    host.add_remove_servers(alive, None)

    mask = jnp.asarray([i % 3 != 0 for i in range(len(universe))])
    ring = dring.build_ring(jnp.asarray(table), mask)
    n_points = dring.ring_size(mask, 100)

    for k in ["alpha", "beta", "gamma", "host:123", "127.0.0.1:30001"]:
        h = jnp.asarray(np.uint32(fh.hash32(k)))
        got = int(dring.lookup(ring, n_points, h))
        assert universe[got] == host.lookup(k), k


def test_device_lookup_n_matches_host():
    servers = create_servers(16)
    universe = sorted(servers)
    table = dring.replica_table(universe, replica_points=100)
    host = HashRing()
    host.add_remove_servers(servers, None)

    mask = jnp.ones(len(universe), bool)
    ring = dring.build_ring(jnp.asarray(table), mask)
    n_points = dring.ring_size(mask, 100)

    for k in ["a", "bb", "ccc", "127.0.0.1:3005"]:
        h = jnp.asarray(np.uint32(fh.hash32(k)))
        got = [int(x) for x in dring.lookup_n(ring, n_points, h, 4)]
        got_names = [universe[g] for g in got if g >= 0]
        assert got_names == host.lookup_n(k, 4), k


def test_device_empty_ring():
    table = dring.replica_table(["127.0.0.1:3000"], replica_points=10)
    mask = jnp.zeros(1, bool)
    ring = dring.build_ring(jnp.asarray(table), mask)
    n_points = dring.ring_size(mask, 10)
    h = jnp.asarray(np.uint32(fh.hash32("x")))
    assert int(dring.lookup(ring, n_points, h)) == -1
    assert all(int(x) == -1 for x in dring.lookup_n(ring, n_points, h, 3))
