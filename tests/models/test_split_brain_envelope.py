"""Split-brain deviation envelope: scalable engine vs full engine, measured.

The scalable engine keeps ONE global truth chain, so a partitioned side's
suspicions are cancelled the moment the accused side's refute lands —
where the reference (and the full [N, N] engine, parity-pinned against the
host oracle) lets the cut-off side escalate suspect -> faulty and merge
views only after the heal (docstring, engine_scalable.py).  These tests
bound that deviation with numbers instead of prose: both engines run the
same scenario SHAPE (split one tenth of the cluster away for > the
suspicion window, then heal) and must agree on the qualitative
convergence shape —

- the split produces cross-side false suspects on both engines,
- both sides keep making progress during the split,
- after the heal both engines reconverge to a single all-alive view
  within a bounded number of ticks, with every false mark refuted.

The measured difference — the full engine marks cross-side FAULTY during
the split while the scalable engine's refutes cancel first — is asserted
here as the envelope's edge, and the numbers are recorded in COVERAGE.md.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.models.sim import engine, engine_scalable as es
from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster


def run_full_engine_split(n=1024, split_frac=0.1, split_ticks=35, heal_ticks=60):
    """Full engine: partition `split_frac` of nodes away, heal, measure."""
    params = engine.SimParams(n=n, checksum_mode="fast")
    sim = SimCluster(n=n, params=params)
    sim.bootstrap()
    assert sim.run_until_converged(40) > 0

    cut = int(n * split_frac)
    part = np.zeros(n, np.int32)
    part[:cut] = 1

    sched = EventSchedule(ticks=split_ticks, n=n)
    sched.partition[0] = part
    m_split = sim.run(sched)

    # cross-side faulty marks at split end: majority side's view of the cut
    status = np.asarray(sim.state.status)
    faulty_marks = int(
        (status[cut:, :cut] == engine.FAULTY).sum()
    )
    suspect_marks = int((status[cut:, :cut] == engine.SUSPECT).sum())

    heal = EventSchedule(ticks=heal_ticks, n=n)
    heal.partition[0] = np.zeros(n, np.int32)
    m_heal = sim.run(heal)
    converged_at = next(
        (i + 1 for i, c in enumerate(np.asarray(m_heal.converged)) if c), -1
    )
    status = np.asarray(sim.state.status)
    return {
        "suspects_during_split": int(np.asarray(m_split.suspects_marked).sum()),
        "faulty_marks_at_heal": faulty_marks,
        "suspect_marks_at_heal": suspect_marks,
        "reconverge_ticks": converged_at,
        "residual_bad_marks": int((status >= engine.SUSPECT).sum()),
    }


def run_scalable_split(n=100_000, split_frac=0.1, split_ticks=35, heal_ticks=80):
    params = es.ScalableParams(n=n, u=512, suspicion_ticks=25)
    state = es.init_state(params, seed=0)
    step = jax.jit(functools.partial(es.tick, params=params))

    cut = int(n * split_frac)
    part = np.zeros(n, np.int32)
    part[:cut] = 1
    quiet = es.ChurnInputs.quiet(n)

    susp = refutes = faulties = 0
    inp = es.ChurnInputs(
        kill=jnp.zeros(n, bool),
        revive=jnp.zeros(n, bool),
        partition=jnp.asarray(part),
    )
    for i in range(split_ticks):
        state, m = step(state, inp if i == 0 else quiet._replace(partition=None))
        susp += int(m.suspects_published)
        refutes += int(m.refutes_published)
        faulties += int(m.faulties_published)
    truth_mid = np.asarray(state.truth_status)
    faulty_mid = int((truth_mid == es.FAULTY).sum())
    # cross-side split: minority subjects marked faulty by the majority
    faulty_mid_minority = int((truth_mid[:cut] == es.FAULTY).sum())

    heal_inp = es.ChurnInputs(
        kill=jnp.zeros(n, bool),
        revive=jnp.zeros(n, bool),
        partition=jnp.zeros(n, jnp.int32),
    )
    reconverge_ticks = -1
    for i in range(heal_ticks):
        state, m = step(state, heal_inp if i == 0 else quiet)
        refutes += int(m.refutes_published)
        if reconverge_ticks < 0 and int(m.distinct_checksums) == 1:
            reconverge_ticks = i + 1
    truth_end = np.asarray(state.truth_status)
    return {
        "suspects_during_split": susp,
        "refutes": refutes,
        "faulties_published": faulties,
        "faulty_truth_at_heal": faulty_mid,
        "faulty_truth_at_heal_minority": faulty_mid_minority,
        "bad_truth_at_heal": int((truth_mid >= es.SUSPECT).sum()),
        "reconverge_ticks": reconverge_ticks,
        "residual_bad_marks": int((truth_end >= es.SUSPECT).sum()),
    }


@pytest.mark.slow
def test_split_brain_envelope_full_vs_scalable():
    full = run_full_engine_split(n=1024)
    scal = run_scalable_split(n=100_000)

    # both engines: the split manufactures false suspects
    assert full["suspects_during_split"] > 0
    assert scal["suspects_during_split"] > 0

    # BOTH engines escalate cross-side suspicions to FAULTY during a
    # >suspicion_ticks split (reference behavior: faulty marks are
    # retained through the partition).  For the scalable engine this is
    # the round-4 defame_by reachability gate at work: partitioned-away
    # subjects cannot refute accusations they could never have heard, so
    # the accusing side's suspicion clocks run out and publish faulty
    # batches.
    assert full["faulty_marks_at_heal"] > 0, (
        "full engine should have escalated cross-side suspects to faulty "
        "during a 35-tick split (suspicion window 25)"
    )
    assert scal["faulties_published"] > 0, scal
    assert scal["faulty_truth_at_heal_minority"] > 0, (
        "majority side should have escalated partitioned-away subjects "
        "to faulty during the split: %r" % (scal,)
    )
    # the defamed-but-live subjects clean themselves up after the heal
    assert scal["refutes"] > 0
    assert scal["residual_bad_marks"] == 0

    # after heal: both reconverge to one view with no bad marks left
    assert full["reconverge_ticks"] > 0, full
    assert full["residual_bad_marks"] == 0
    assert scal["reconverge_ticks"] > 0, scal

    # record the measured shape for COVERAGE.md maintenance
    print("ENVELOPE full:", full)
    print("ENVELOPE scalable:", scal)
