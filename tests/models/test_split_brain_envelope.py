"""Split-brain deviation envelope: scalable engine vs full engine, measured.

The scalable engine keeps ONE global truth chain, so a partitioned side's
suspicions are cancelled the moment the accused side's refute lands —
where the reference (and the full [N, N] engine, parity-pinned against the
host oracle) lets the cut-off side escalate suspect -> faulty and merge
views only after the heal (docstring, engine_scalable.py).  These tests
bound that deviation with numbers instead of prose: both engines run the
same scenario SHAPE (split one tenth of the cluster away for > the
suspicion window, then heal) and must agree on the qualitative
convergence shape —

- the split produces cross-side false suspects on both engines,
- both sides keep making progress during the split,
- after the heal both engines reconverge to a single all-alive view
  within a bounded number of ticks, with every false mark refuted.

The measured difference — the full engine marks cross-side FAULTY during
the split while the scalable engine's refutes cancel first — is asserted
here as the envelope's edge, and the numbers are recorded in COVERAGE.md.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.models.sim import engine, engine_scalable as es
from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster


def run_full_engine_split(n=1024, split_frac=0.1, split_ticks=35, heal_ticks=60):
    """Full engine: partition `split_frac` of nodes away, heal, measure."""
    params = engine.SimParams(n=n, checksum_mode="fast")
    sim = SimCluster(n=n, params=params)
    sim.bootstrap()
    assert sim.run_until_converged(40) > 0

    cut = int(n * split_frac)
    part = np.zeros(n, np.int32)
    part[:cut] = 1

    sched = EventSchedule(ticks=split_ticks, n=n)
    sched.partition[0] = part
    m_split = sim.run(sched)

    # cross-side faulty marks at split end: majority side's view of the cut
    status = np.asarray(sim.state.status)
    faulty_marks = int(
        (status[cut:, :cut] == engine.FAULTY).sum()
    )
    suspect_marks = int((status[cut:, :cut] == engine.SUSPECT).sum())

    heal = EventSchedule(ticks=heal_ticks, n=n)
    heal.partition[0] = np.zeros(n, np.int32)
    m_heal = sim.run(heal)
    converged_at = next(
        (i + 1 for i, c in enumerate(np.asarray(m_heal.converged)) if c), -1
    )
    status = np.asarray(sim.state.status)
    return {
        "suspects_during_split": int(np.asarray(m_split.suspects_marked).sum()),
        "faulty_marks_at_heal": faulty_marks,
        "suspect_marks_at_heal": suspect_marks,
        "reconverge_ticks": converged_at,
        "residual_bad_marks": int((status >= engine.SUSPECT).sum()),
    }


def run_scalable_split(n=100_000, split_frac=0.1, split_ticks=35, heal_ticks=80):
    params = es.ScalableParams(n=n, u=512, suspicion_ticks=25)
    state = es.init_state(params, seed=0)
    step = jax.jit(functools.partial(es.tick, params=params))

    cut = int(n * split_frac)
    part = np.zeros(n, np.int32)
    part[:cut] = 1
    quiet = es.ChurnInputs.quiet(n)

    susp = refutes = faulties = 0
    inp = es.ChurnInputs(
        kill=jnp.zeros(n, bool),
        revive=jnp.zeros(n, bool),
        partition=jnp.asarray(part),
    )
    for i in range(split_ticks):
        state, m = step(state, inp if i == 0 else quiet._replace(partition=None))
        susp += int(m.suspects_published)
        refutes += int(m.refutes_published)
        faulties += int(m.faulties_published)
    truth_mid = np.asarray(state.truth_status)
    faulty_mid = int((truth_mid == es.FAULTY).sum())
    # cross-side split: minority subjects marked faulty by the majority
    faulty_mid_minority = int((truth_mid[:cut] == es.FAULTY).sum())

    heal_inp = es.ChurnInputs(
        kill=jnp.zeros(n, bool),
        revive=jnp.zeros(n, bool),
        partition=jnp.zeros(n, jnp.int32),
    )
    reconverge_ticks = -1
    for i in range(heal_ticks):
        state, m = step(state, heal_inp if i == 0 else quiet)
        refutes += int(m.refutes_published)
        if reconverge_ticks < 0 and int(m.distinct_checksums) == 1:
            reconverge_ticks = i + 1
    truth_end = np.asarray(state.truth_status)
    return {
        "suspects_during_split": susp,
        "refutes": refutes,
        "faulties_published": faulties,
        "faulty_truth_at_heal": faulty_mid,
        "faulty_truth_at_heal_minority": faulty_mid_minority,
        "bad_truth_at_heal": int((truth_mid >= es.SUSPECT).sum()),
        "reconverge_ticks": reconverge_ticks,
        "residual_bad_marks": int((truth_end >= es.SUSPECT).sum()),
    }


@pytest.mark.slow
def test_split_brain_envelope_full_vs_scalable():
    full = run_full_engine_split(n=1024)
    scal = run_scalable_split(n=100_000)

    # both engines: the split manufactures false suspects
    assert full["suspects_during_split"] > 0
    assert scal["suspects_during_split"] > 0

    # BOTH engines escalate cross-side suspicions to FAULTY during a
    # >suspicion_ticks split (reference behavior: faulty marks are
    # retained through the partition).  For the scalable engine this is
    # the round-4 defame_by reachability gate at work: partitioned-away
    # subjects cannot refute accusations they could never have heard, so
    # the accusing side's suspicion clocks run out and publish faulty
    # batches.
    assert full["faulty_marks_at_heal"] > 0, (
        "full engine should have escalated cross-side suspects to faulty "
        "during a 35-tick split (suspicion window 25)"
    )
    assert scal["faulties_published"] > 0, scal
    assert scal["faulty_truth_at_heal_minority"] > 0, (
        "majority side should have escalated partitioned-away subjects "
        "to faulty during the split: %r" % (scal,)
    )
    # the defamed-but-live subjects clean themselves up after the heal
    assert scal["refutes"] > 0
    assert scal["residual_bad_marks"] == 0

    # after heal: both reconverge to one view with no bad marks left
    assert full["reconverge_ticks"] > 0, full
    assert full["residual_bad_marks"] == 0
    assert scal["reconverge_ticks"] > 0, scal

    # record the measured shape for COVERAGE.md maintenance
    print("ENVELOPE full:", full)
    print("ENVELOPE scalable:", scal)


# ---------------------------------------------------------------------------
# >= 3-way splits: the merged-truth-chain union envelope, measured
# (round-5 verdict item 6).  The full engine keeps exact per-observer
# views: side X's marks about side Y never leak into side Z's view.  The
# scalable engine's single truth chain holds the UNION of every side's
# marks — per-side information survives only in the heard bitsets.  The
# observable consequence: after a PARTIAL heal (A+B merge, C still cut),
# a B subject whose recorded representative defamer (defame_by) sits in
# the still-unreachable C cannot refute yet, where the full engine's
# A-observers accept B's refutes immediately.  These tests measure that
# union error and its resolution at full heal.
# ---------------------------------------------------------------------------


def run_full_engine_3way(n=1024, fracs=(0.8, 0.1, 0.1), split_ticks=35):
    params = engine.SimParams(n=n, checksum_mode="fast")
    sim = SimCluster(n=n, params=params)
    sim.bootstrap()
    assert sim.run_until_converged(40) > 0

    cut_b = int(n * fracs[1])
    cut_c = cut_b + int(n * fracs[2])
    side = np.zeros(n, np.int32)  # 0 = A (majority)
    side[:cut_b] = 1  # B
    side[cut_b:cut_c] = 2  # C

    sched = EventSchedule(ticks=split_ticks, n=n)
    sched.partition[0] = side
    sim.run(sched)

    def cross_matrix():
        status = np.asarray(sim.state.status)
        m = np.zeros((3, 3), np.int64)
        for ox in range(3):
            for sx in range(3):
                if ox == sx:
                    continue
                m[ox, sx] = (
                    status[np.ix_(side == ox, side == sx)] == engine.FAULTY
                ).sum()
        return m

    faulty_3x3_at_split = cross_matrix()

    # PARTIAL heal: merge A+B (group 0), C stays cut
    part2 = np.where(side == 2, 2, 0).astype(np.int32)
    sched = EventSchedule(ticks=30, n=n)
    sched.partition[0] = part2
    sim.run(sched)
    status = np.asarray(sim.state.status)
    # exact per-observer behavior: A-observers accept B's refutes — no
    # A-side faulty marks about B survive the partial heal
    a_of_b_after_partial = int(
        (status[np.ix_(side == 0, side == 1)] == engine.FAULTY).sum()
    )

    # full heal.  After C's LONG (65-tick) isolation, full reconvergence
    # is NOT expected: a C observer whose faulty mark about a majority
    # node burned its piggyback budget during the split (65 pings >>
    # max_pb = 60 at 1k) can no longer disseminate the mark, so the
    # defamed subject never learns of it and never refutes — and neither
    # incoming alive@equal-incarnation nor a full-sync can override
    # faulty under the reference precedence (member.js:171-202; full
    # syncs apply through the same gate).  The stale mark is STICKY.
    # This is faithful reference behavior (SWIM's known partition-heal
    # limitation), measured here.
    sched = EventSchedule(ticks=120, n=n)
    sched.partition[0] = np.zeros(n, np.int32)
    m_heal = sim.run(sched)
    converged_at = next(
        (i + 1 for i, c in enumerate(np.asarray(m_heal.converged)) if c), -1
    )
    status = np.asarray(sim.state.status)
    cs = np.asarray(sim.state.checksum)
    vals, counts = np.unique(cs, return_counts=True)
    majority = int(counts.max())
    stragglers = np.flatnonzero(cs != vals[np.argmax(counts)])
    # a straggler whose split-time view went (nearly) ALL-faulty has no
    # pingable targets left: it sends nothing, so it never receives the
    # full-sync that would trigger its refute — and everyone else holds
    # IT faulty, so nothing arrives either.  Mutual isolation, faithful
    # to the reference (no automatic partition healer in ringpop-node;
    # rescue = admin re-join / process restart)
    known = np.asarray(sim.state.known)
    pingable = known & (status <= engine.SUSPECT)
    np.fill_diagonal(pingable, False)
    isolated = [
        int(i) for i in stragglers if pingable[i].sum() == 0
    ]

    rescued_converged = -1
    if converged_at < 0 and len(stragglers):
        # the documented rescue path: operator revive (process restart +
        # re-join — the tick-cluster 'j' / server/admin/member.js flow)
        sim.revive(stragglers.tolist())
        for t in range(80):
            if bool(sim.step().converged):
                rescued_converged = t + 1
                break
    status = np.asarray(sim.state.status)
    return {
        "faulty_3x3_at_split": faulty_3x3_at_split.tolist(),
        "a_view_of_b_faulty_after_partial_heal": a_of_b_after_partial,
        "reconverge_ticks_after_full_heal": converged_at,
        "majority_group_after_heal": majority,
        "straggler_observers": stragglers.tolist(),
        "straggler_sides": side[stragglers].tolist(),
        "fully_isolated_stragglers": isolated,
        "rescued_reconverge_ticks": rescued_converged,
        "residual_bad_marks_after_rescue": int(
            (status >= engine.SUSPECT).sum()
        ),
    }


def run_scalable_3way(n=100_000, fracs=(0.8, 0.1, 0.1), split_ticks=35):
    params = es.ScalableParams(n=n, u=512, suspicion_ticks=25)
    state = es.init_state(params, seed=0)
    step = jax.jit(functools.partial(es.tick, params=params))

    cut_b = int(n * fracs[1])
    cut_c = cut_b + int(n * fracs[2])
    side = np.zeros(n, np.int32)
    side[:cut_b] = 1
    side[cut_b:cut_c] = 2
    quiet = es.ChurnInputs.quiet(n)

    def run_ticks(t, partition):
        nonlocal state
        inp = es.ChurnInputs(
            kill=jnp.zeros(n, bool),
            revive=jnp.zeros(n, bool),
            partition=jnp.asarray(partition.astype(np.int32)),
        )
        for i in range(t):
            state, m = step(
                state, inp if i == 0 else quiet._replace(partition=None)
            )
        return m

    run_ticks(split_ticks, side)
    truth = np.asarray(state.truth_status)
    faulty_per_side_split = [
        int((truth[side == s] == es.FAULTY).sum()) for s in range(3)
    ]
    # the union property itself: ONE truth chain carries every side's
    # marks — per-side views exist only via heard bitsets (distinct
    # checksums per side during the split)
    cs = np.asarray(es.compute_checksums(state, params)) if not bool(
        params.checksum_in_tick
    ) else np.asarray(state.checksum)
    distinct_per_side = [
        len(set(cs[side == s].tolist())) for s in range(3)
    ]

    # PARTIAL heal (A+B merge; C cut): B subjects whose representative
    # defamer is C-side cannot refute yet — the union error
    part2 = np.where(side == 2, 2, 0)
    run_ticks(30, part2)
    truth = np.asarray(state.truth_status)
    union_error_b_stuck = int((truth[side == 1] >= es.SUSPECT).sum())
    a_or_b_bad = int((truth[side != 2] >= es.SUSPECT).sum())

    # full heal
    run_ticks(80, np.zeros(n, np.int32))
    truth = np.asarray(state.truth_status)
    return {
        "faulty_per_side_at_split": faulty_per_side_split,
        "distinct_checksums_per_side_at_split": distinct_per_side,
        "b_subjects_stuck_after_partial_heal": union_error_b_stuck,
        "ab_bad_after_partial_heal": a_or_b_bad,
        "residual_bad_marks_after_full_heal": int(
            (truth >= es.SUSPECT).sum()
        ),
    }


@pytest.mark.slow
def test_three_way_split_union_envelope():
    full = run_full_engine_3way()
    scal = run_scalable_3way()

    # full engine: every cross-side pair escalated to faulty (exact
    # per-observer bookkeeping), and B recovers in A's view as soon as
    # A+B heal — C's opinions never contaminate A's view of B
    m = np.asarray(full["faulty_3x3_at_split"])
    assert (m[~np.eye(3, dtype=bool)] > 0).all(), full
    assert full["a_view_of_b_faulty_after_partial_heal"] == 0, full
    # after C's LONG isolation the full engine reconverges its vast
    # majority but may strand a few C observers on sticky faulty marks
    # whose dissemination budget burned during the split — faithful
    # reference behavior (see run_full_engine_3way's comment); the
    # stragglers must be C-side and few
    assert full["majority_group_after_heal"] >= 1024 - 8, full
    if full["reconverge_ticks_after_full_heal"] < 0:
        # the stragglers are C-side observers stranded by the long
        # isolation (sticky marks / mutual isolation — see the runner's
        # comments: faithful reference behavior), and the operator
        # rescue (revive = restart + re-join) fully heals the cluster
        assert all(s == 2 for s in full["straggler_sides"]), full
        assert full["rescued_reconverge_ticks"] > 0, full
        assert full["residual_bad_marks_after_rescue"] == 0, full

    # scalable engine: the union truth marked both minority sides faulty,
    # per-side information survives in heard-sets (sides hold distinct
    # checksums during the split), and the union error is VISIBLE at the
    # partial heal (B subjects defamed by still-cut C refute late) but
    # fully resolves at the full heal
    assert full is not None and scal["faulty_per_side_at_split"][1] > 0
    assert scal["faulty_per_side_at_split"][2] > 0
    assert all(d >= 1 for d in scal["distinct_checksums_per_side_at_split"])
    assert scal["residual_bad_marks_after_full_heal"] == 0, scal

    # the envelope numbers for COVERAGE.md (run with -s to capture)
    print("3WAY full:", full)
    print("3WAY scalable:", scal)
