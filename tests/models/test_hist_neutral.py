"""Latency histograms are trajectory-neutral: enabling them changes NO
protocol state bit on either engine or the routed storm (the ISSUE 11
gate-equivalence acceptance), and the recorded distributions reconcile
with the trajectory that produced them."""

import numpy as np
import pytest

from ringpop_tpu.models.sim import engine
from ringpop_tpu.models.sim import engine_scalable as es
from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster
from ringpop_tpu.models.sim.storm import ScalableCluster, StormSchedule


def _assert_states_equal(sa, sb, skip=("hist",)):
    for f in type(sa)._fields:
        if f in skip:
            continue
        va, vb = getattr(sa, f), getattr(sb, f)
        if va is None and vb is None:
            continue
        assert va is not None and vb is not None, f
        assert np.array_equal(np.asarray(va), np.asarray(vb)), (
            "field %s diverged under histograms" % f
        )


def _full_pair(n, ticks, gate=True):
    out = []
    for histo in (False, True):
        c = SimCluster(
            n=n,
            params=engine.SimParams(
                n=n, histograms=histo, gate_phases=gate
            ),
            seed=11,
        )
        c.bootstrap()
        sched = EventSchedule.churn_window(ticks, n)
        ms = c.run(sched)
        out.append((c, ms))
    return out


def test_full_engine_hist_gate_equivalence_n64():
    (a, ma), (b, mb) = _full_pair(64, 24)
    _assert_states_equal(a.state, b.state)
    for f in engine.TickMetrics._fields:
        assert np.array_equal(
            np.asarray(getattr(ma, f)), np.asarray(getattr(mb, f))
        ), f
    assert b.state.hist is not None and a.state.hist is None


def test_full_engine_hist_identical_across_gate_phases_n64():
    # the recording masks must not depend on the cond-vs-straight-line
    # phase shape: same trajectory, same histogram counts
    def one(gate):
        c = SimCluster(
            n=64,
            params=engine.SimParams(n=64, histograms=True, gate_phases=gate),
            seed=11,
        )
        c.bootstrap()
        c.run(EventSchedule.churn_window(24, 64))
        return c

    g_on, g_off = one(True), one(False)
    _assert_states_equal(g_on.state, g_off.state, skip=())
    assert np.array_equal(
        np.asarray(g_on.state.hist), np.asarray(g_off.state.hist)
    )


@pytest.mark.slow
def test_full_engine_hist_gate_equivalence_n1k_farmhash():
    n = 1000
    out = []
    for histo in (False, True):
        c = SimCluster(
            n=n,
            params=engine.SimParams(
                n=n, checksum_mode="farmhash", histograms=histo
            ),
            seed=3,
        )
        c.bootstrap()
        c.run(EventSchedule.churn_window(16, n))
        out.append(c)
    _assert_states_equal(out[0].state, out[1].state)


def _scalable_pair(n, ticks, u=256, seed=9):
    out = []
    sched = StormSchedule.churn_storm(ticks, n, fraction=0.15, seed=seed)
    for histo in (False, True):
        c = ScalableCluster(
            n=n,
            params=es.ScalableParams(n=n, u=u, histograms=histo),
            seed=seed,
        )
        c.run(sched)
        out.append(c)
    return out


def test_scalable_engine_hist_gate_equivalence_n64():
    a, b = _scalable_pair(64, 40)
    _assert_states_equal(a.state, b.state)
    s = b.drain_histograms()
    # the wavefront twin reconciliation: every heard-bit turn-on is one
    # rumor_age observation — rerun WITH wavefront and count stamps
    c = ScalableCluster(
        n=64,
        params=es.ScalableParams(n=64, u=256, wavefront=True),
        seed=9,
    )
    c.run(StormSchedule.churn_storm(40, 64, fraction=0.15, seed=9))
    # publish-time stamps are first-heard but not exchange adoptions;
    # the histogram records EXCHANGE adoptions only, so it can never
    # exceed the wavefront's stamped count
    stamped = int((np.asarray(c.state.first_heard) >= 0).sum())
    assert 0 < s["rumor_age"]["count"] <= stamped


@pytest.mark.slow
def test_scalable_engine_hist_gate_equivalence_n1k():
    a, b = _scalable_pair(1000, 60, u=512, seed=4)
    _assert_states_equal(a.state, b.state)
    assert int(np.asarray(b.state.hist).sum()) > 0


def test_routed_storm_hist_gate_equivalence_n64():
    from ringpop_tpu.models.route.plane import RoutedStorm, RouteParams

    sched = StormSchedule.churn_storm(30, 64, fraction=0.15, seed=7)
    out = []
    for histo in (False, True):
        rs = RoutedStorm(
            64,
            params=es.ScalableParams(n=64, u=256, histograms=histo),
            route=RouteParams(
                n=64, queries_per_tick=128, histograms=histo
            ),
            seed=7,
        )
        _, rm = rs.run(sched)
        out.append((rs, rm))
    (ra, ma), (rb, mb) = out
    _assert_states_equal(ra.cluster.state, rb.cluster.state)
    assert ra.ring_checksum() == rb.ring_checksum()
    for f in ma._fields:
        assert np.array_equal(
            np.asarray(getattr(ma, f)), np.asarray(getattr(mb, f))
        ), f
    # drain reconciliation: retry_depth/reroute_hops record exactly the
    # sendable requests; dirty_buckets one observation per tick
    d = rb.drain_histograms()
    sendable = int(np.asarray(mb.route_queries).sum())
    assert d["route"]["retry_depth"]["count"] == sendable
    assert d["route"]["reroute_hops"]["count"] == sendable
    assert d["route"]["dirty_buckets"]["count"] == sched.ticks
    # the exact per-bucket reconciliation runs on the raw counters in
    # test_routed_storm_depth_counts_reconcile_exactly below


def test_routed_storm_depth_counts_reconcile_exactly():
    """Retry-depth bucket counts == the counter plane's own arithmetic:
    bucket(0) = sendable - retried, bucket(1) = retried, where retried =
    misroute | checksum-reject per request — read from the RAW counters
    before any drain reset."""
    from ringpop_tpu.models.route import plane as rp
    from ringpop_tpu.models.route.plane import RoutedStorm, RouteParams

    sched = StormSchedule.churn_storm(20, 64, fraction=0.2, seed=13)
    rs = RoutedStorm(
        64,
        params=es.ScalableParams(n=64, u=256),
        route=RouteParams(n=64, queries_per_tick=128, histograms=True),
        seed=13,
    )
    _, rm = rs.run(sched)
    hist = np.asarray(rs.rstate.hist, np.int64)
    depth_track = hist[rp.ROUTE_HIST_TRACKS.index("retry_depth")]
    sendable = int(np.asarray(rm.route_queries).sum())
    assert depth_track.sum() == sendable
    # depth-1 lanes: every request that retried.  retried = misroute |
    # reject; rejects == checksums_differ under enforce_consistency and
    # may overlap misroutes, so reconcile against the union bound
    misroutes = int(np.asarray(rm.route_misroutes).sum())
    rejects = int(np.asarray(rm.route_checksum_rejects).sum())
    assert misroutes <= depth_track[1] <= misroutes + rejects
    # hops: bucket(1)=direct+local, bucket(2)=remote reroutes exactly
    hops_track = hist[rp.ROUTE_HIST_TRACKS.index("reroute_hops")]
    remote = int(np.asarray(rm.route_reroute_remote).sum())
    assert hops_track[2] == remote
    assert hops_track[1] == sendable - remote


def test_drain_resets_and_requires_enabled():
    a, b = _scalable_pair(16, 10, u=128, seed=2)
    with pytest.raises(ValueError):
        a.drain_histograms()
    first = b.drain_histograms()
    assert any(v["count"] for v in first.values())
    again = b.drain_histograms()
    assert all(v["count"] == 0 for v in again.values())


def test_full_engine_suspicion_durations_bounded():
    """Suspicion-duration observations are bounded by the protocol: a
    timer stops within [1, suspicion_ticks] of its (re)start unless the
    observer was suspended — no churn of that kind here."""
    n = 48
    params = engine.SimParams(n=n, histograms=True, packet_loss=0.15)
    c = SimCluster(n=n, params=params, seed=21)
    c.bootstrap()
    c.run(EventSchedule(ticks=40, n=n))
    s = c.drain_histograms()
    st = s["suspicion_duration"]
    if st["count"]:
        assert st["max_hi"] <= 2 * params.suspicion_ticks  # bucket bound


def test_checkpoint_roundtrip_toggles_hist_plane(tmp_path):
    """A hist-enabled storm checkpoint restores onto a hist-off engine
    (plane dropped) and vice versa (fresh counters) — the histograms
    knob is trajectory-neutral in checkpoint params."""
    n = 32
    on = ScalableCluster(
        n=n, params=es.ScalableParams(n=n, u=128, histograms=True), seed=6
    )
    on.run(StormSchedule.churn_storm(10, n, fraction=0.1, seed=6))
    path = str(tmp_path / "ck")
    on.save(path)
    off = ScalableCluster(
        n=n, params=es.ScalableParams(n=n, u=128), seed=6
    )
    off.load(path)
    assert off.state.hist is None
    on2 = ScalableCluster(
        n=n, params=es.ScalableParams(n=n, u=128, histograms=True), seed=6
    )
    on2.load(path)
    assert on2.state.hist is not None
    _assert_states_equal(off.state, on2.state)
    # and the two resumes continue bitwise-identically
    cont = StormSchedule.churn_storm(8, n, fraction=0.1, seed=8)
    off.run(cont)
    on2.run(cont)
    _assert_states_equal(off.state, on2.state)
