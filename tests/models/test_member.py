"""Member precedence + damping tests mirroring
/root/reference/test/unit/member_test.js."""

from ringpop_tpu.models.membership import Member, Status, Update
from tests.lib.fixtures import RingpopFixture


def add_second_member(rp, address="127.0.0.1:3001"):
    rp.membership.update(
        [{"address": address, "status": Status.alive, "incarnationNumber": 1}]
    )
    return rp.membership.find_member_by_address(address)


def test_damp_score_initialized():
    rp = RingpopFixture()
    m2 = add_second_member(rp)
    assert m2.damp_score == rp.config.get("dampScoringInitial")


def test_penalized_for_update():
    rp = RingpopFixture()
    m2 = add_second_member(rp)
    m2.evaluate_update(
        {"status": Status.suspect, "incarnationNumber": rp.now() + 1}
    )
    assert m2.damp_score != rp.config.get("dampScoringInitial")


def test_flaps_until_exceeds_suppress_limit():
    rp = RingpopFixture()
    rp.config.set("dampScoringMax", 1000)
    rp.config.set("dampScoringSuppressLimit", 500)
    rp.config.set("dampScoringPenalty", 251)  # 2 updates is all it'll take
    m2 = add_second_member(rp)
    exceeded = []
    m2.on("suppressLimitExceeded", lambda: exceeded.append(True))
    m2.evaluate_update({"status": Status.suspect, "incarnationNumber": rp.now() + 1})
    m2.evaluate_update({"status": Status.faulty, "incarnationNumber": rp.now() + 2})
    assert m2.damp_score > rp.config.get("dampScoringSuppressLimit")
    assert exceeded


def test_damp_score_never_exceeds_max():
    rp = RingpopFixture()
    rp.config.set("dampScoringMax", 1000)
    rp.config.set("dampScoringPenalty", 5000)
    m2 = add_second_member(rp)
    m2.evaluate_update({"status": Status.suspect, "incarnationNumber": rp.now() + 1})
    assert m2.damp_score == rp.config.get("dampScoringMax")


def test_penalized_in_penalty_increments():
    rp = RingpopFixture()
    rp.config.set("dampScoringMax", 1000)
    rp.config.set("dampScoringPenalty", 100)
    m2 = add_second_member(rp)
    for i in range(1, 4):
        m2.evaluate_update(
            {"status": Status.suspect, "incarnationNumber": rp.now() + i}
        )
        assert m2.damp_score == rp.config.get("dampScoringPenalty") * i


def decay_by(rp, member, term_ms):
    member.now = lambda: rp.clock() + term_ms
    member.decay_damp_score()


def test_decays_by_arbitrary_amount():
    rp = RingpopFixture()
    m2 = add_second_member(rp)
    m2.evaluate_update({"status": Status.suspect, "incarnationNumber": rp.now() + 1})
    orig = m2.damp_score
    decay_by(rp, m2, 1000 + 1)
    assert m2.damp_score < orig


def test_decayed_by_half():
    rp = RingpopFixture()
    m2 = add_second_member(rp)
    m2.evaluate_update({"status": Status.suspect, "incarnationNumber": rp.now() + 1})
    orig = m2.damp_score
    decay_by(rp, m2, rp.config.get("dampScoringHalfLife") * 1000)
    assert m2.damp_score == round(orig / 2)


def test_never_decays_below_min():
    rp = RingpopFixture()
    rp.config.set("dampScoringInitial", 0)
    rp.config.set("dampScoringPenalty", 100)
    rp.config.set("dampScoringMin", 100)
    rp.config.set("dampScoringMax", 1000)
    m2 = add_second_member(rp)
    i = 1
    while m2.damp_score < rp.config.get("dampScoringMax"):
        m2.evaluate_update(
            {"status": Status.suspect, "incarnationNumber": rp.now() + i}
        )
        i += 1
    decay_by(rp, m2, rp.config.get("dampScoringHalfLife") * 1000 * 4)
    assert m2.damp_score == rp.config.get("dampScoringMin")


def test_member_id_is_address():
    rp = RingpopFixture()
    address = "127.0.0.1:3000"
    member = Member(rp, Update(address, 1, Status.alive))
    assert member.id == address


def test_update_happens_synchronously_or_not_at_all():
    rp = RingpopFixture()
    address = "127.0.0.1:3001"
    inc = rp.now()
    member = Member(rp, Update(address, inc, Status.alive))
    emitted = []
    member.on("updated", lambda u: emitted.append(u))

    member.evaluate_update(
        {"address": address, "status": Status.suspect, "incarnationNumber": inc + 1}
    )
    assert emitted

    emitted.clear()
    member.evaluate_update(
        {"address": address, "status": Status.suspect, "incarnationNumber": inc + 1}
    )
    assert not emitted


# -- the full precedence table (member.js:171-202), exhaustively -------------


def test_precedence_table_exhaustive():
    statuses = [Status.alive, Status.suspect, Status.faulty, Status.leave]

    def expected(cur_status, cur_inc, upd_status, upd_inc):
        if upd_status == Status.alive:
            return upd_inc > cur_inc
        if upd_status == Status.suspect:
            if cur_status in (Status.suspect, Status.faulty):
                return upd_inc > cur_inc
            if cur_status == Status.alive:
                return upd_inc >= cur_inc
            return False  # cur leave
        if upd_status == Status.faulty:
            if cur_status == Status.suspect:
                return upd_inc >= cur_inc
            if cur_status == Status.faulty:
                return upd_inc > cur_inc
            if cur_status == Status.alive:
                return upd_inc >= cur_inc
            return False  # cur leave
        if upd_status == Status.leave:
            return cur_status != Status.leave and upd_inc >= cur_inc
        return False

    rp = RingpopFixture()
    for cur_status in statuses:
        for upd_status in statuses:
            for delta in (-1, 0, 1):
                cur_inc = 1000
                upd_inc = cur_inc + delta
                member = Member(
                    rp, Update("127.0.0.1:3009", cur_inc, cur_status)
                )
                applied = member.evaluate_update(
                    {
                        "address": "127.0.0.1:3009",
                        "status": upd_status,
                        "incarnationNumber": upd_inc,
                    }
                )
                want = expected(cur_status, cur_inc, upd_status, upd_inc)
                assert applied == want, (cur_status, upd_status, delta)
                if want:
                    assert member.status == upd_status
                    assert member.incarnation_number == upd_inc
                else:
                    assert member.status == cur_status
                    assert member.incarnation_number == cur_inc


def test_local_refute_on_suspect_and_faulty():
    # member.js:76-81,155-169: local member re-asserts alive with fresh
    # incarnation on suspect/faulty claims about itself
    for claim in (Status.suspect, Status.faulty):
        rp = RingpopFixture()
        local = rp.membership.local_member
        orig_inc = local.incarnation_number
        rp.clock.advance(5000)
        rp.membership.update(
            [
                {
                    "address": rp.whoami(),
                    "status": claim,
                    "incarnationNumber": orig_inc,
                }
            ]
        )
        assert local.status == Status.alive
        assert local.incarnation_number == rp.now()
        assert local.incarnation_number > orig_inc


def test_local_leave_is_not_refuted():
    # leave about the local member is applied (higher inc), not refuted —
    # membership_test.js 'change with higher incarnation number results in
    # leave override'
    rp = RingpopFixture()
    local = rp.membership.local_member
    rp.membership.update(
        [
            {
                "address": rp.whoami(),
                "status": Status.leave,
                "incarnationNumber": local.incarnation_number + 1,
            }
        ]
    )
    assert local.status == Status.leave
