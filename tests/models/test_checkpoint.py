"""Checkpoint/resume: kill a run mid-storm, resume, bitwise-equal
trajectory (SURVEY §5.4)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.models.sim import engine, engine_scalable as es
from ringpop_tpu.models.sim.checkpoint import load_state, save_state
from ringpop_tpu.ops import checksum_encode as ce


def test_scalable_resume_bitwise_equal(tmp_path):
    n = 256
    params = es.ScalableParams(n=n, u=512, packet_loss=0.05, suspicion_ticks=5)
    state = es.init_state(params, seed=3)
    step = jax.jit(functools.partial(es.tick, params=params))
    rng = np.random.default_rng(0)

    def inputs_at(t):
        kill = np.zeros(n, bool)
        revive = np.zeros(n, bool)
        if t % 7 == 0:
            kill[rng.integers(0, n, 4)] = True  # deterministic per call order
        return es.ChurnInputs(kill=jnp.asarray(kill), revive=jnp.asarray(revive))

    # storm for 30 ticks, checkpoint, storm 30 more -> trajectory A
    sched = [inputs_at(t) for t in range(60)]
    for t in range(30):
        state, _ = step(state, sched[t])
    path = str(tmp_path / "storm.npz")
    save_state(path, state)
    cont = state
    for t in range(30, 60):
        cont, _ = step(cont, sched[t])

    # resume from the checkpoint -> trajectory B must equal A bitwise
    resumed = load_state(path, es.ScalableState)
    for t in range(30, 60):
        resumed, _ = step(resumed, sched[t])
    for f in es.ScalableState._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(cont, f)), np.asarray(getattr(resumed, f)), f
        )
    np.testing.assert_array_equal(
        np.asarray(es.compute_checksums(cont, params)),
        np.asarray(es.compute_checksums(resumed, params)),
    )


def test_full_engine_resume_bitwise_equal(tmp_path):
    n = 16
    params = engine.SimParams(n=n, checksum_mode="fast")
    universe = ce.Universe.from_addresses(
        ["127.0.0.1:%d" % (3000 + i) for i in range(n)]
    )
    tick = jax.jit(lambda s, i: engine.tick(s, i, params, universe))
    state = engine.init_state(params, seed=1)
    join = engine.TickInputs.quiet(n)._replace(join=jnp.ones(n, bool))
    state, _ = tick(state, join)
    for _ in range(10):
        state, _ = tick(state, engine.TickInputs.quiet(n))

    path = str(tmp_path / "sim.npz")
    save_state(path, state)
    a = state
    b = load_state(path, engine.SimState)
    for _ in range(20):
        a, _ = tick(a, engine.TickInputs.quiet(n))
        b, _ = tick(b, engine.TickInputs.quiet(n))
    for f in engine.SimState._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f)), np.asarray(getattr(b, f)), f
        )


def test_checkpoint_rejects_wrong_class_and_fields(tmp_path):
    params = es.ScalableParams(n=8, u=128)
    state = es.init_state(params)
    path = str(tmp_path / "s.npz")
    save_state(path, state)
    with pytest.raises(ValueError):
        load_state(path, engine.SimState)
    # non-checkpoint npz rejected
    other = str(tmp_path / "other.npz")
    np.savez(other, a=np.zeros(3))
    with pytest.raises(ValueError):
        load_state(other, es.ScalableState)
    # same class round-trips
    back = load_state(path, es.ScalableState)
    for f in es.ScalableState._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(state, f)), np.asarray(getattr(back, f)), f
        )
        assert np.asarray(getattr(back, f)).dtype == np.asarray(
            getattr(state, f)
        ).dtype


def test_pre_round4_checkpoint_missing_defame_by_loads(tmp_path):
    """A checkpoint written before defame_by existed must still load: the
    field defaults to the node's own id, which makes the refute
    reachability gate vacuously true (the old, laxer rule)."""
    import numpy as np

    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.models.sim.checkpoint import load_state, save_state

    params = es.ScalableParams(n=32, u=160)
    state = es.init_state(params, seed=4)
    path = str(tmp_path / "old.npz")
    save_state(path, state, params)
    # strip defame_by, simulating a round-3 artifact
    data = dict(np.load(path, allow_pickle=True))
    del data["defame_by"]
    np.savez(path, **data)

    loaded = load_state(path, es.ScalableState, params)
    db = np.asarray(loaded.defame_by)
    assert (db == np.arange(32)).all()
    for f in es.ScalableState._fields:
        if f == "defame_by":
            continue
        assert (
            np.asarray(getattr(loaded, f)) == np.asarray(getattr(state, f))
        ).all(), f


def test_hash_impl_is_trajectory_neutral(tmp_path):
    """A checkpoint saved under one FarmHash lowering resumes under
    another (the lowerings are bit-exact; hash_impl only picks the
    kernel), and a pre-hash_impl checkpoint with no such key loads."""
    import json as _json

    import numpy as np

    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.checkpoint import (
        _PARAMS_KEY,
        load_state,
        save_state,
    )

    params = engine.SimParams(n=8, checksum_mode="fast", hash_impl="scan")
    state = engine.init_state(params, seed=0)
    path = str(tmp_path / "st.npz")
    save_state(path, state, params)

    # cross-lowering resume
    load_state(
        path, engine.SimState, params._replace(hash_impl="pallas_nogrid")
    )

    # pre-hash_impl artifact: strip the key from the stored params JSON
    data = dict(np.load(path, allow_pickle=True))
    saved = _json.loads(str(data[_PARAMS_KEY][0]))
    del saved["hash_impl"]
    data[_PARAMS_KEY] = np.array([_json.dumps(saved)])
    np.savez(path, **data)
    load_state(path, engine.SimState, params)


def test_scalable_perm_and_exchange_knobs_are_trajectory_neutral(tmp_path):
    """A checkpoint saved under one (perm_impl, fused_exchange) pair
    resumes under another — both knobs are bit-identical by the
    gate-equivalence tests, and drivers pin backend-resolved values at
    construction (a TPU save carries "pallas", a CPU resume resolves
    "off") — and a pre-round-10 artifact with neither key loads."""
    import json as _json

    from ringpop_tpu.models.sim.checkpoint import _PARAMS_KEY

    params = es.ScalableParams(
        n=8, u=128, perm_impl="argsort", fused_exchange="off"
    )
    state = es.init_state(params, seed=0)
    path = str(tmp_path / "st.npz")
    save_state(path, state, params)

    # cross-mode resume (the TPU-save -> CPU-resume shape)
    load_state(
        path,
        es.ScalableState,
        params._replace(perm_impl="sortless", fused_exchange="xla"),
    )

    # pre-round-10 artifact: strip both keys from the stored params JSON
    data = dict(np.load(path, allow_pickle=True))
    saved = _json.loads(str(data[_PARAMS_KEY][0]))
    del saved["perm_impl"]
    del saved["fused_exchange"]
    data[_PARAMS_KEY] = np.array([_json.dumps(saved)])
    np.savez(path, **data)
    load_state(path, es.ScalableState, params)
