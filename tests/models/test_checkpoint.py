"""Checkpoint/resume: kill a run mid-storm, resume, bitwise-equal
trajectory (SURVEY §5.4); atomic-write + manifest-format integrity
(round 13: torn files, bit-rot, missing shards each fail with their
named error — never a silent resume)."""

import functools
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.models.sim import checkpoint as ckpt
from ringpop_tpu.models.sim import engine, engine_scalable as es
from ringpop_tpu.models.sim.checkpoint import load_state, save_state
from ringpop_tpu.ops import checksum_encode as ce


def _assert_states_equal(a, b):
    assert type(a) is type(b)
    for f in a._fields:
        x, y = getattr(a, f), getattr(b, f)
        if x is None:
            assert y is None, f
            continue
        x, y = np.asarray(x), np.asarray(y)
        assert x.dtype == y.dtype, f
        np.testing.assert_array_equal(x, y, f)


def test_scalable_resume_bitwise_equal(tmp_path):
    n = 256
    params = es.ScalableParams(n=n, u=512, packet_loss=0.05, suspicion_ticks=5)
    state = es.init_state(params, seed=3)
    step = jax.jit(functools.partial(es.tick, params=params))
    rng = np.random.default_rng(0)

    def inputs_at(t):
        kill = np.zeros(n, bool)
        revive = np.zeros(n, bool)
        if t % 7 == 0:
            kill[rng.integers(0, n, 4)] = True  # deterministic per call order
        return es.ChurnInputs(kill=jnp.asarray(kill), revive=jnp.asarray(revive))

    # storm for 30 ticks, checkpoint, storm 30 more -> trajectory A
    sched = [inputs_at(t) for t in range(60)]
    for t in range(30):
        state, _ = step(state, sched[t])
    path = str(tmp_path / "storm.npz")
    save_state(path, state)
    cont = state
    for t in range(30, 60):
        cont, _ = step(cont, sched[t])

    # resume from the checkpoint -> trajectory B must equal A bitwise
    resumed = load_state(path, es.ScalableState)
    for t in range(30, 60):
        resumed, _ = step(resumed, sched[t])
    for f in es.ScalableState._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(cont, f)), np.asarray(getattr(resumed, f)), f
        )
    np.testing.assert_array_equal(
        np.asarray(es.compute_checksums(cont, params)),
        np.asarray(es.compute_checksums(resumed, params)),
    )


def test_full_engine_resume_bitwise_equal(tmp_path):
    n = 16
    params = engine.SimParams(n=n, checksum_mode="fast")
    universe = ce.Universe.from_addresses(
        ["127.0.0.1:%d" % (3000 + i) for i in range(n)]
    )
    tick = jax.jit(lambda s, i: engine.tick(s, i, params, universe))
    state = engine.init_state(params, seed=1)
    join = engine.TickInputs.quiet(n)._replace(join=jnp.ones(n, bool))
    state, _ = tick(state, join)
    for _ in range(10):
        state, _ = tick(state, engine.TickInputs.quiet(n))

    path = str(tmp_path / "sim.npz")
    save_state(path, state)
    a = state
    b = load_state(path, engine.SimState)
    for _ in range(20):
        a, _ = tick(a, engine.TickInputs.quiet(n))
        b, _ = tick(b, engine.TickInputs.quiet(n))
    for f in engine.SimState._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f)), np.asarray(getattr(b, f)), f
        )


def test_checkpoint_rejects_wrong_class_and_fields(tmp_path):
    params = es.ScalableParams(n=8, u=128)
    state = es.init_state(params)
    path = str(tmp_path / "s.npz")
    save_state(path, state)
    with pytest.raises(ValueError):
        load_state(path, engine.SimState)
    # non-checkpoint npz rejected
    other = str(tmp_path / "other.npz")
    np.savez(other, a=np.zeros(3))
    with pytest.raises(ValueError):
        load_state(other, es.ScalableState)
    # same class round-trips
    back = load_state(path, es.ScalableState)
    for f in es.ScalableState._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(state, f)), np.asarray(getattr(back, f)), f
        )
        assert np.asarray(getattr(back, f)).dtype == np.asarray(
            getattr(state, f)
        ).dtype


def test_pre_round4_checkpoint_missing_defame_by_loads(tmp_path):
    """A checkpoint written before defame_by existed must still load: the
    field defaults to the node's own id, which makes the refute
    reachability gate vacuously true (the old, laxer rule)."""
    import numpy as np

    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.models.sim.checkpoint import load_state, save_state

    params = es.ScalableParams(n=32, u=160)
    state = es.init_state(params, seed=4)
    path = str(tmp_path / "old.npz")
    save_state(path, state, params)
    # strip defame_by, simulating a round-3 artifact
    data = dict(np.load(path, allow_pickle=True))
    del data["defame_by"]
    np.savez(path, **data)

    loaded = load_state(path, es.ScalableState, params)
    db = np.asarray(loaded.defame_by)
    assert (db == np.arange(32)).all()
    for f in es.ScalableState._fields:
        if f == "defame_by":
            continue
        assert (
            np.asarray(getattr(loaded, f)) == np.asarray(getattr(state, f))
        ).all(), f


def test_hash_impl_is_trajectory_neutral(tmp_path):
    """A checkpoint saved under one FarmHash lowering resumes under
    another (the lowerings are bit-exact; hash_impl only picks the
    kernel), and a pre-hash_impl checkpoint with no such key loads."""
    import json as _json

    import numpy as np

    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.checkpoint import (
        _PARAMS_KEY,
        load_state,
        save_state,
    )

    params = engine.SimParams(n=8, checksum_mode="fast", hash_impl="scan")
    state = engine.init_state(params, seed=0)
    path = str(tmp_path / "st.npz")
    save_state(path, state, params)

    # cross-lowering resume
    load_state(
        path, engine.SimState, params._replace(hash_impl="pallas_nogrid")
    )

    # pre-hash_impl artifact: strip the key from the stored params JSON
    data = dict(np.load(path, allow_pickle=True))
    saved = _json.loads(str(data[_PARAMS_KEY][0]))
    del saved["hash_impl"]
    data[_PARAMS_KEY] = np.array([_json.dumps(saved)])
    np.savez(path, **data)
    load_state(path, engine.SimState, params)


def test_scalable_perm_and_exchange_knobs_are_trajectory_neutral(tmp_path):
    """A checkpoint saved under one (perm_impl, fused_exchange) pair
    resumes under another — both knobs are bit-identical by the
    gate-equivalence tests, and drivers pin backend-resolved values at
    construction (a TPU save carries "pallas", a CPU resume resolves
    "off") — and a pre-round-10 artifact with neither key loads."""
    import json as _json

    from ringpop_tpu.models.sim.checkpoint import _PARAMS_KEY

    params = es.ScalableParams(
        n=8, u=128, perm_impl="argsort", fused_exchange="off"
    )
    state = es.init_state(params, seed=0)
    path = str(tmp_path / "st.npz")
    save_state(path, state, params)

    # cross-mode resume (the TPU-save -> CPU-resume shape)
    load_state(
        path,
        es.ScalableState,
        params._replace(perm_impl="sortless", fused_exchange="xla"),
    )

    # pre-round-10 artifact: strip both keys from the stored params JSON
    data = dict(np.load(path, allow_pickle=True))
    saved = _json.loads(str(data[_PARAMS_KEY][0]))
    del saved["perm_impl"]
    del saved["fused_exchange"]
    data[_PARAMS_KEY] = np.array([_json.dumps(saved)])
    np.savez(path, **data)
    load_state(path, es.ScalableState, params)


# -- round 13: atomic legacy writes ------------------------------------------


def test_save_state_interrupted_never_shadows_good_checkpoint(
    tmp_path, monkeypatch
):
    """The legacy single-file path goes through tmp + fsync + os.replace:
    a save killed before the rename leaves the PREVIOUS checkpoint
    intact at the final path (no torn npz shadowing it)."""
    params = es.ScalableParams(n=8, u=128)
    good = es.init_state(params, seed=1)
    path = str(tmp_path / "s.npz")
    save_state(path, good, params)

    # crash mid-write: the replace never happens
    def boom(src, dst):
        raise OSError("killed mid-rename")

    monkeypatch.setattr(ckpt.os, "replace", boom)
    other = es.init_state(params, seed=2)
    with pytest.raises(OSError):
        save_state(path, other, params)
    monkeypatch.undo()

    back = load_state(path, es.ScalableState, params)
    _assert_states_equal(good, back)
    # the leftover tmp file is suffix-tagged, never the final path
    stray = [f for f in os.listdir(tmp_path) if ".tmp." in f]
    assert stray, "tmp protocol not used"


def test_load_state_named_errors(tmp_path):
    """Legacy loads fail with the named taxonomy (all ValueError
    subclasses, so pre-round-13 callers keep working)."""
    params = es.ScalableParams(n=8, u=128)
    state = es.init_state(params)
    path = str(tmp_path / "s.npz")
    save_state(path, state, params)

    with pytest.raises(ckpt.CheckpointNotFoundError):
        load_state(str(tmp_path / "absent.npz"), es.ScalableState)
    with pytest.raises(ckpt.CheckpointFieldError):
        load_state(path, engine.SimState)
    with pytest.raises(ckpt.CheckpointParamsError):
        load_state(
            path, es.ScalableState, params._replace(suspicion_ticks=99)
        )
    # truncated npz -> torn, not a numpy/zlib traceback
    with open(path, "r+b") as fh:
        fh.truncate(os.path.getsize(path) // 2)
    with pytest.raises(ckpt.CheckpointTornError):
        load_state(path, es.ScalableState)


# -- round 13: manifest format ----------------------------------------------


def _make_state(n=24, u=160, seed=3, ticks=6):
    import jax as _jax

    params = es.ScalableParams(n=n, u=u, suspicion_ticks=4)
    state = es.init_state(params, seed=seed)
    step = _jax.jit(functools.partial(es.tick, params=params))
    rng = np.random.default_rng(0)
    for t in range(ticks):
        kill = np.zeros(n, bool)
        kill[rng.integers(0, n, 2)] = t % 2 == 0
        state, _ = step(
            state, es.ChurnInputs(kill=jnp.asarray(kill), revive=jnp.zeros(n, bool))
        )
    return params, state


def test_manifest_roundtrip_single_and_sharded(tmp_path):
    params, state = _make_state()
    p1, p3 = str(tmp_path / "ck1"), str(tmp_path / "ck3")
    m1 = ckpt.save_checkpoint(p1, state, params)
    m3 = ckpt.save_checkpoint(
        p3, state, params, shards=3, sharded_fields=es.NODE_SHARDED_FIELDS
    )
    assert m1["shards"] == 1 and m3["shards"] == 3
    s1 = ckpt.load_checkpoint(p1, es.ScalableState, params)
    s3 = ckpt.load_checkpoint(p3, es.ScalableState, params)
    _assert_states_equal(state, s1)
    # ACCEPTANCE: sharded save -> restore bitwise-identical to the
    # single-file path
    _assert_states_equal(s1, s3)
    # and a re-save at a DIFFERENT shard count still restores bitwise
    p5 = str(tmp_path / "ck5")
    ckpt.save_checkpoint(
        p5, s3, params, shards=5, sharded_fields=es.NODE_SHARDED_FIELDS
    )
    _assert_states_equal(s1, ckpt.load_checkpoint(p5, es.ScalableState, params))
    ckpt.verify_checkpoint(p5, deep=True)


def test_manifest_multi_state_roundtrip(tmp_path):
    """Named multi-state checkpoints (the RoutedStorm layout)."""
    from ringpop_tpu.models.route.plane import RouteCarry

    params, state = _make_state(n=16)
    carry = RouteCarry(
        mask=jnp.asarray(np.arange(16) % 3 != 0),
        rng=jnp.asarray(np.asarray([7, 9], np.uint32)),
    )
    path = str(tmp_path / "ck")
    ckpt.save_checkpoint(
        path,
        {"sim": state, "route": carry},
        {"sim": params, "route": None},
        shards=2,
        sharded_fields={
            "sim": es.NODE_SHARDED_FIELDS,
            "route": frozenset({"mask"}),
        },
    )
    out = ckpt.load_checkpoint(
        path,
        {"sim": es.ScalableState, "route": RouteCarry},
        {"sim": params, "route": None},
    )
    _assert_states_equal(state, out["sim"])
    _assert_states_equal(carry, out["route"])
    # requesting a state name the checkpoint does not hold is a named error
    with pytest.raises(ckpt.CheckpointFieldError):
        ckpt.load_checkpoint(path, {"nope": es.ScalableState})


def _saved(tmp_path, shards=2):
    params, state = _make_state(n=16)
    path = str(tmp_path / "ck")
    ckpt.save_checkpoint(
        path,
        state,
        params,
        shards=shards,
        sharded_fields=es.NODE_SHARDED_FIELDS if shards > 1 else None,
    )
    return params, state, path


def test_corruption_truncated_array_file_is_torn(tmp_path):
    params, _, path = _saved(tmp_path)
    target = os.path.join(path, "shard-00001-of-00002.npz")
    with open(target, "r+b") as fh:
        fh.truncate(os.path.getsize(target) // 3)
    with pytest.raises(ckpt.CheckpointTornError):
        ckpt.load_checkpoint(path, es.ScalableState, params)
    with pytest.raises(ckpt.CheckpointTornError):
        ckpt.verify_checkpoint(path, deep=False)  # size check alone catches it


def test_corruption_flipped_byte_is_digest_mismatch(tmp_path):
    params, _, path = _saved(tmp_path)
    target = os.path.join(path, "common.npz")
    size = os.path.getsize(target)
    with open(target, "r+b") as fh:
        fh.seek(size // 2)
        b = fh.read(1)
        fh.seek(size // 2)
        fh.write(bytes([b[0] ^ 0xFF]))
    assert os.path.getsize(target) == size  # same length: digest, not torn
    with pytest.raises(ckpt.CheckpointDigestError):
        ckpt.load_checkpoint(path, es.ScalableState, params)
    with pytest.raises(ckpt.CheckpointDigestError):
        ckpt.verify_checkpoint(path, deep=True)
    # the shallow probe (sizes only) cannot see bit-rot — documented
    ckpt.verify_checkpoint(path, deep=False)


def test_corruption_missing_shard_is_shard_error(tmp_path):
    params, _, path = _saved(tmp_path)
    os.remove(os.path.join(path, "shard-00000-of-00002.npz"))
    with pytest.raises(ckpt.CheckpointShardError):
        ckpt.load_checkpoint(path, es.ScalableState, params)


def test_corruption_torn_manifest_and_missing_manifest(tmp_path):
    params, _, path = _saved(tmp_path)
    mpath = os.path.join(path, ckpt.MANIFEST_NAME)
    with open(mpath, "r+b") as fh:
        fh.truncate(os.path.getsize(mpath) // 2)
    with pytest.raises(ckpt.CheckpointTornError):
        ckpt.load_checkpoint(path, es.ScalableState, params)
    os.remove(mpath)
    with pytest.raises(ckpt.CheckpointNotFoundError):
        ckpt.load_checkpoint(path, es.ScalableState, params)
    with pytest.raises(ckpt.CheckpointNotFoundError):
        ckpt.load_checkpoint(str(tmp_path / "never"), es.ScalableState)


def _edit_manifest(path, fn):
    mpath = os.path.join(path, ckpt.MANIFEST_NAME)
    with open(mpath, encoding="utf-8") as fh:
        doc = json.load(fh)
    fn(doc)
    with open(mpath, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)


def test_version_and_field_mismatch_matrix(tmp_path):
    """The version/field-mismatch matrix: every drift axis has a named
    error and none of them resumes silently."""
    params, _, path = _saved(tmp_path)

    # manifest format version drift
    _edit_manifest(path, lambda d: d.update(version=99))
    with pytest.raises(ckpt.CheckpointVersionError):
        ckpt.load_checkpoint(path, es.ScalableState, params)
    _edit_manifest(path, lambda d: d.update(version=ckpt.MANIFEST_VERSION))

    # engine state-format version drift (incarnation representation)
    _edit_manifest(path, lambda d: d.update(engine_version=1))
    with pytest.raises(ckpt.CheckpointVersionError):
        ckpt.load_checkpoint(path, es.ScalableState, params)
    _edit_manifest(
        path, lambda d: d.update(engine_version=ckpt._FORMAT_VERSION)
    )

    # wrong state class
    with pytest.raises(ckpt.CheckpointFieldError):
        ckpt.load_checkpoint(path, engine.SimState, None)

    # params drift (protocol constant changed between save and resume)
    with pytest.raises(ckpt.CheckpointParamsError):
        ckpt.load_checkpoint(
            path, es.ScalableState, params._replace(piggyback_factor=1)
        )
    # ... but trajectory-neutral knobs may differ freely
    ckpt.load_checkpoint(
        path,
        es.ScalableState,
        params._replace(gate_phases=False, perm_impl="argsort"),
    )

    # field-set drift: a field this build does not know
    def add_field(d):
        d["states"]["state"]["fields"]["not_a_field"] = {
            "dtype": "int32",
            "shape": [1],
            "where": "common",
            "crc32": 0,
        }

    _edit_manifest(path, add_field)
    with pytest.raises(ckpt.CheckpointFieldError):
        ckpt.load_checkpoint(path, es.ScalableState, params)


def test_shard_count_vs_file_list_drift(tmp_path):
    params, _, path = _saved(tmp_path)

    def drop_listed_shard(d):
        d["shard_files"] = d["shard_files"][:1]

    _edit_manifest(path, drop_listed_shard)
    with pytest.raises(ckpt.CheckpointShardError):
        ckpt.load_checkpoint(path, es.ScalableState, params)


def test_manifest_defame_by_default_like_legacy(tmp_path):
    """The manifest loader honors the same derived-default table as the
    legacy path (pre-round-4 artifacts lacking defame_by)."""
    params, state, path = _saved(tmp_path, shards=1)

    def strip(d):
        d["states"]["state"]["fields"]["defame_by"] = None

    _edit_manifest(path, strip)
    # also remove the array from the common file so available lacks it
    import numpy as _np

    common = os.path.join(path, "common.npz")
    data = dict(_np.load(common))
    data.pop("state.defame_by")
    bio_arrays = {k: v for k, v in data.items()}
    ckpt.atomic_write_bytes(common, ckpt._npz_bytes(bio_arrays))
    # size/crc changed -> patch the manifest file entry to keep integrity
    with open(common, "rb") as fh:
        buf = fh.read()

    def fix_files(d):
        d["files"]["common.npz"] = {
            "nbytes": len(buf),
            "crc32": ckpt._crc(buf),
        }

    _edit_manifest(path, fix_files)
    loaded = ckpt.load_checkpoint(path, es.ScalableState, params)
    np.testing.assert_array_equal(
        np.asarray(loaded.defame_by), np.arange(16)
    )
