"""Membership collection tests mirroring
/root/reference/test/unit/membership_test.js, membership-changeset-merge,
membership-iterator, and the checksum-string format."""

from ringpop_tpu.models.membership import (
    Status,
    Update,
    merge_membership_changesets,
)
from ringpop_tpu.ops import farmhash32 as fh
from tests.lib.fixtures import RingpopFixture, make_iterator


def test_checksum_changes_on_update():
    rp = RingpopFixture()
    rp.membership.make_alive("127.0.0.1:3001", rp.now())
    prev = rp.membership.checksum
    rp.membership.make_alive("127.0.0.1:3002", rp.now())
    assert rp.membership.checksum != prev


def test_checksum_string_format_and_hash():
    rp = RingpopFixture()
    rp.membership.make_alive("127.0.0.1:3001", 1414142122275)
    rp.membership.make_suspect("127.0.0.1:3001", 1414142122275)
    s = rp.membership.generate_checksum_string()
    local_inc = rp.membership.local_member.incarnation_number
    assert s == (
        "127.0.0.1:3000alive%d;127.0.0.1:3001suspect1414142122275" % local_inc
    )
    assert rp.membership.checksum == fh.hash32(s)


def test_suspect_faulty_update_refutes_local():
    for status in (Status.suspect, Status.faulty):
        rp = RingpopFixture()
        local = rp.membership.local_member
        prev_inc = local.incarnation_number
        rp.clock.advance(1)
        rp.membership.update(
            [
                {
                    "address": local.address,
                    "status": status,
                    "incarnationNumber": prev_inc,
                }
            ]
        )
        assert local.status == Status.alive
        assert local.incarnation_number > prev_inc


def test_alive_to_faulty_without_suspect():
    rp = RingpopFixture()
    rp.membership.make_alive("127.0.0.1:3001", rp.now())
    member = rp.membership.find_member_by_address("127.0.0.1:3001")

    # lower incarnation: no override
    rp.membership.update(
        [
            {
                "address": member.address,
                "status": Status.faulty,
                "incarnationNumber": member.incarnation_number - 1,
            }
        ]
    )
    assert member.status == Status.alive

    # same incarnation: faulty overrides alive
    rp.membership.update(
        [
            {
                "address": member.address,
                "status": Status.faulty,
                "incarnationNumber": member.incarnation_number,
            }
        ]
    )
    assert member.status == Status.faulty


def test_update_buffered_until_ready():
    rp = RingpopFixture(ready=False)
    rp.membership.make_alive(rp.whoami(), rp.now())  # local: applied directly

    # non-local updates stash until set()
    rp.membership.update(
        [{"address": "127.0.0.1:3001", "status": Status.alive, "incarnationNumber": 1}]
    )
    assert rp.membership.get_member_count() == 1
    assert len(rp.membership.stashed_updates) == 1

    rp.membership.set()
    assert rp.membership.get_member_count() == 2
    assert rp.membership.stashed_updates is None
    assert rp.membership.checksum is not None


def test_set_merges_stashed_changesets():
    rp = RingpopFixture(ready=False)
    rp.membership.make_alive(rp.whoami(), rp.now())
    rp.membership.update(
        [{"address": "127.0.0.1:3001", "status": Status.alive, "incarnationNumber": 1}]
    )
    rp.membership.update(
        [{"address": "127.0.0.1:3001", "status": Status.faulty, "incarnationNumber": 5}]
    )
    rp.membership.set()
    m = rp.membership.find_member_by_address("127.0.0.1:3001")
    # highest incarnation wins in the merge (merge.js:39-41)
    assert m.status == Status.faulty
    assert m.incarnation_number == 5


def test_changeset_merge_skips_local_and_keeps_highest():
    rp = RingpopFixture()
    cs1 = [
        Update("127.0.0.1:3001", 1, Status.alive),
        Update(rp.whoami(), 99, Status.faulty),
    ]
    cs2 = [Update("127.0.0.1:3001", 3, Status.suspect)]
    merged = merge_membership_changesets(rp, [cs1, cs2])
    assert len(merged) == 1
    assert merged[0].incarnation_number == 3
    assert merged[0].status == Status.suspect


def test_get_random_pingable_members_excludes():
    rp = RingpopFixture()
    for i in range(1, 6):
        rp.membership.make_alive("127.0.0.1:300%d" % i, rp.now())
    got = rp.membership.get_random_pingable_members(10, ["127.0.0.1:3001"])
    addrs = {m.address for m in got}
    assert "127.0.0.1:3001" not in addrs
    assert rp.whoami() not in addrs  # local never pingable
    assert len(got) == 4

    two = rp.membership.get_random_pingable_members(2, [])
    assert len(two) == 2


def test_iterator_round_robin_visits_all_pingable():
    rp = RingpopFixture()
    others = ["127.0.0.1:300%d" % i for i in range(1, 5)]
    for a in others:
        rp.membership.make_alive(a, rp.now())
    it = make_iterator(rp)
    seen = [it.next().address for _ in range(len(others))]
    assert sorted(seen) == sorted(others)  # one full round hits each once
    # second round revisits (reshuffled)
    seen2 = [it.next().address for _ in range(len(others))]
    assert sorted(seen2) == sorted(others)


def test_iterator_skips_faulty_and_local():
    rp = RingpopFixture()
    rp.membership.make_alive("127.0.0.1:3001", rp.now())
    rp.membership.make_alive("127.0.0.1:3002", rp.now())
    rp.membership.make_faulty("127.0.0.1:3002", rp.now())
    it = make_iterator(rp)
    for _ in range(6):
        m = it.next()
        assert m.address == "127.0.0.1:3001"


def test_iterator_returns_none_when_no_pingable():
    rp = RingpopFixture()
    it = make_iterator(rp)
    assert it.next() is None  # only the local member exists


def test_new_member_inserted_at_join_position():
    rp = RingpopFixture()
    for i in range(1, 10):
        rp.membership.make_alive("127.0.0.1:30%02d" % i, rp.now())
    # members list isn't (necessarily) in insertion order; address index works
    assert rp.membership.get_member_count() == 10
    for i in range(1, 10):
        assert rp.membership.find_member_by_address("127.0.0.1:30%02d" % i)
