"""BatchedSimClusters: vmap over a cluster axis is semantics-preserving.

The batched runner exists for TPU utilization at tick-cluster scale
(B clusters of n nodes in one compiled scan); these tests pin the claim
that batching changes NOTHING about any individual cluster's trajectory.
"""

import numpy as np

from ringpop_tpu.models.sim import engine
from ringpop_tpu.models.sim.batched import BatchedSimClusters
from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster


def test_batched_matches_solo_trajectories():
    b, n, T = 3, 48, 28
    bat = BatchedSimClusters(b=b, n=n, seed=11)
    bat.bootstrap()
    sched = EventSchedule(ticks=T, n=n)
    sched.kill[5, 7] = True
    sched.revive[18, 7] = True
    ms = bat.run(sched)
    assert ms.converged.shape == (T, b)
    for i in range(b):
        solo = SimCluster(
            n=n,
            params=engine.SimParams(
                n=n, checksum_mode="fast", gate_phases=False
            ),
            seed=11 + i,
        )
        solo.bootstrap()
        m1 = solo.run(sched)
        for f in ("converged", "distinct_checksums", "pings_delivered"):
            got = np.asarray(getattr(ms, f))[:, i]
            want = np.asarray(getattr(m1, f))
            assert (got == want).all(), (f, i)
        assert (bat.checksums()[i] == np.asarray(solo.state.checksum)).all()
    assert bool(np.asarray(ms.converged)[-1].all())


def test_batched_clusters_are_independent():
    """Different seeds => different mid-run trajectories (no cross-cluster
    state bleed through the vmap axis)."""
    b, n, T = 2, 48, 6
    bat = BatchedSimClusters(b=b, n=n, seed=3)
    bat.bootstrap()
    ms = bat.run(EventSchedule(ticks=T, n=n))
    # bootstrap dissemination order is seed-dependent (per-node iteration
    # permutations differ): the per-tick applied-changes traces should
    # differ somewhere mid-bootstrap
    assert (
        np.asarray(ms.changes_applied)[:, 0]
        != np.asarray(ms.changes_applied)[:, 1]
    ).any()


def test_batched_flight_recorder_drains_per_cluster():
    """The vmapped driver carries [B]-leading flight-recorder buffers;
    drain_events decodes one honest stream per cluster and the per-
    cluster counts reconcile with the per-cluster metric columns."""
    from ringpop_tpu.obs import events as obs_events

    b, n, T = 2, 8, 6
    bat = BatchedSimClusters(
        b=b,
        n=n,
        params=engine.SimParams(
            n=n,
            checksum_mode="fast",
            flight_recorder=True,
            event_capacity=4096,
        ),
        seed=5,
    )
    bat.bootstrap()
    bat.drain_events()  # align the event window with the run window
    ms = bat.run(EventSchedule(ticks=T, n=n))
    streams = bat.drain_events(reset=False)
    assert len(streams) == b
    for i, stream in enumerate(streams):
        per_cluster = {
            f: np.asarray(getattr(ms, f))[:, i]
            for f in engine.TickMetrics._fields
        }
        rec = obs_events.reconcile(stream, per_cluster)
        assert rec and all(v["match"] for v in rec.values()), (i, rec)
    # the two seeds' bootstrap orders differ, so the streams must too
    assert streams[0] != streams[1]
    # drain reset clears every cluster's head
    bat.drain_events()
    assert (np.asarray(bat.state.ev_head) == 0).all()
