"""BatchedSimClusters: vmap over a cluster axis is semantics-preserving.

The batched runner exists for TPU utilization at tick-cluster scale
(B clusters of n nodes in one compiled scan); these tests pin the claim
that batching changes NOTHING about any individual cluster's trajectory.
"""

import numpy as np

from ringpop_tpu.models.sim import engine
from ringpop_tpu.models.sim.batched import BatchedSimClusters
from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster


def test_batched_matches_solo_trajectories():
    b, n, T = 3, 48, 28
    bat = BatchedSimClusters(b=b, n=n, seed=11)
    bat.bootstrap()
    sched = EventSchedule(ticks=T, n=n)
    sched.kill[5, 7] = True
    sched.revive[18, 7] = True
    ms = bat.run(sched)
    assert ms.converged.shape == (T, b)
    for i in range(b):
        solo = SimCluster(
            n=n,
            params=engine.SimParams(
                n=n, checksum_mode="fast", gate_phases=False
            ),
            seed=11 + i,
        )
        solo.bootstrap()
        m1 = solo.run(sched)
        for f in ("converged", "distinct_checksums", "pings_delivered"):
            got = np.asarray(getattr(ms, f))[:, i]
            want = np.asarray(getattr(m1, f))
            assert (got == want).all(), (f, i)
        assert (bat.checksums()[i] == np.asarray(solo.state.checksum)).all()
    assert bool(np.asarray(ms.converged)[-1].all())


def test_batched_clusters_are_independent():
    """Different seeds => different mid-run trajectories (no cross-cluster
    state bleed through the vmap axis)."""
    b, n, T = 2, 48, 6
    bat = BatchedSimClusters(b=b, n=n, seed=3)
    bat.bootstrap()
    ms = bat.run(EventSchedule(ticks=T, n=n))
    # bootstrap dissemination order is seed-dependent (per-node iteration
    # permutations differ): the per-tick applied-changes traces should
    # differ somewhere mid-bootstrap
    assert (
        np.asarray(ms.changes_applied)[:, 0]
        != np.asarray(ms.changes_applied)[:, 1]
    ).any()
