"""Sampled per-request trace records are trajectory-neutral and honest.

Pins (ISSUE 19): enabling the request-trace plane changes NO protocol
state bit on the routed storm across BOTH ring impls (the gate-
equivalence acceptance, test_hist_neutral.py discipline); decoded
records reconcile exactly against the device-side sampled counters and
the counters against the window's RouteMetrics totals (equal at
sample_log2=0, a subset otherwise); capacity sized by
``req_capacity_for`` is drop-free and overflow keeps an honest prefix;
hash-of-key sampling is chi-square-unbiased across Zipf-skewed key
mixes; the checkpoint knob is trajectory-neutral on resume."""

import numpy as np
import pytest

from ringpop_tpu.models.route import reqtrace as rt
from ringpop_tpu.models.route import traffic
from ringpop_tpu.models.route.plane import RoutedStorm, RouteParams
from ringpop_tpu.models.sim import engine_scalable as es
from ringpop_tpu.models.sim.storm import StormSchedule
from ringpop_tpu.obs import requests as oreq


def _params(n, **kw):
    return es.ScalableParams(n=n, u=192, suspicion_ticks=4, **kw)


def _route(n, **kw):
    base = dict(queries_per_tick=256, key_space=1024)
    base.update(kw)
    return RouteParams(n=n, **base)


def _storm(n, ticks, seed=3):
    return StormSchedule.churn_storm(
        ticks=ticks, n=n, fraction=0.15, seed=seed
    )


def _run(n, ticks, seed=3, storm_seed=3, **route_kw):
    rs = RoutedStorm(
        n, params=_params(n), route=_route(n, **route_kw), seed=seed
    )
    em, rm = rs.run(_storm(n, ticks, seed=storm_seed))
    return rs, em, rm


def _assert_cluster_states_equal(sa, sb):
    for f in type(sa)._fields:
        va, vb = getattr(sa, f), getattr(sb, f)
        if va is None and vb is None:
            continue
        assert va is not None and vb is not None, f
        assert np.array_equal(np.asarray(va), np.asarray(vb)), (
            "field %s diverged under reqtrace" % f
        )


# -- gate equivalence --------------------------------------------------------


def test_routed_storm_reqtrace_gate_equivalence_n64():
    """Both ring impls, histograms on, sampling off/on: membership
    state, metrics, and the truth ring are bitwise-invisible to the
    trace plane — and the records themselves are impl-independent
    (the masks are)."""
    n = 64
    runs = {}
    for impl in ("incremental", "full"):
        for reqtrace in (False, True):
            rs, em, rm = _run(
                n,
                30,
                ring_impl=impl,
                histograms=True,
                reqtrace=reqtrace,
                req_capacity=rt.req_capacity_for(256, 30),
                req_sample_log2=2,
            )
            runs[impl, reqtrace] = (rs, em, rm)
    for impl in ("incremental", "full"):
        (ra, ea, ma), (rb, eb, mb) = runs[impl, False], runs[impl, True]
        _assert_cluster_states_equal(ra.cluster.state, rb.cluster.state)
        assert ra.ring_checksum() == rb.ring_checksum()
        for f in ma._fields:
            assert np.array_equal(
                np.asarray(getattr(ma, f)), np.asarray(getattr(mb, f))
            ), f
        for f in ea._fields:
            assert np.array_equal(
                np.asarray(getattr(ea, f)), np.asarray(getattr(eb, f))
            ), f
        assert ra.rstate.req_buf is None
        assert rb.rstate.req_buf is not None
    # impl-independence of the trace itself: same masks, same records
    ri, rf = runs["incremental", True][0], runs["full", True][0]
    np.testing.assert_array_equal(
        np.asarray(ri.rstate.req_buf), np.asarray(rf.rstate.req_buf)
    )
    assert int(ri.rstate.req_head) == int(rf.rstate.req_head)
    np.testing.assert_array_equal(
        np.asarray(ri.rstate.req_counts), np.asarray(rf.rstate.req_counts)
    )
    assert int(ri.rstate.req_head) > 0, "the storm must trace something"


@pytest.mark.slow
def test_routed_storm_reqtrace_gate_equivalence_n1k():
    n = 1000
    out = []
    for reqtrace in (False, True):
        rs = RoutedStorm(
            n,
            params=es.ScalableParams(n=n, u=512),
            route=RouteParams(
                n=n,
                queries_per_tick=256,
                key_space=1024,
                histograms=True,
                reqtrace=reqtrace,
                req_capacity=rt.req_capacity_for(256, 16),
                req_sample_log2=2,
            ),
            seed=4,
        )
        em, rm = rs.run(
            StormSchedule.churn_storm(16, n, fraction=0.1, seed=4)
        )
        out.append((rs, em, rm))
    (ra, ea, ma), (rb, eb, mb) = out
    _assert_cluster_states_equal(ra.cluster.state, rb.cluster.state)
    assert ra.ring_checksum() == rb.ring_checksum()
    for f in ma._fields:
        assert np.array_equal(
            np.asarray(getattr(ma, f)), np.asarray(getattr(mb, f))
        ), f
    assert int(rb.rstate.req_head) > 0


# -- reconciliation honesty --------------------------------------------------


def test_reconciliation_exact_at_sample_everything():
    """sample_log2=0 traces EVERY sendable request: decoded records ==
    device counters == the window's RouteMetrics totals, field for
    field — the honesty acceptance."""
    rs, _, rm = _run(
        64,
        20,
        reqtrace=True,
        req_capacity=rt.req_capacity_for(256, 20),
        req_sample_log2=0,
    )
    st = rs.rstate
    rec = oreq.reconcile_records(st.req_buf, st.req_head, st.req_counts)
    assert all(v["match"] for v in rec.values()), rec
    met = oreq.reconcile_metrics(st.req_counts, rm)
    assert set(met) == set(oreq.COUNT_FIELDS)
    for field, v in met.items():
        assert v["sampled"] == v["total"], (field, v)
    assert int(st.req_drops) == 0
    # and the record stream is the full request stream
    assert int(st.req_head) == int(np.asarray(rm.route_queries).sum())


def test_reconciliation_sampled_subset():
    """At a real sampling rate the counters are a subset of the totals
    (never more), records still match the counters exactly, and the
    drained row carries the same story."""
    rs, _, rm = _run(
        64,
        20,
        reqtrace=True,
        req_capacity=rt.req_capacity_for(256, 20),
        req_sample_log2=2,
    )
    st = rs.rstate
    rec = oreq.reconcile_records(st.req_buf, st.req_head, st.req_counts)
    assert all(v["match"] for v in rec.values()), rec
    met = oreq.reconcile_metrics(st.req_counts, rm)
    assert all(v["ok"] for v in met.values()), met
    total = int(np.asarray(rm.route_queries).sum())
    sampled = met["queries"]["sampled"]
    assert 0 < sampled < total  # ~1/4 of a 5120-query storm
    drained = rs.drain_requests(reset=True)
    assert drained["drops"] == 0
    assert len(drained["records"]) == sampled
    assert drained["counts"]["queries"] == sampled
    # reset starts a fresh window but keeps the monotone tick stamp
    assert int(rs.rstate.req_head) == 0
    assert int(rs.rstate.req_tick) == 20


# -- capacity sizing + overflow honesty --------------------------------------


def test_capacity_sizing_is_drop_free_at_worst_case():
    """``req_capacity_for`` is the flight.max_events_per_tick contract
    for the request plane: at sample_log2=0 (every request appends) a
    window sized by it never drops — and the bound is EXACT, reached
    by a quiet tick where every query is sendable."""
    q, ticks = 256, 12
    assert rt.max_requests_per_tick(q) == q
    assert rt.req_capacity_for(q, ticks) == ticks * q
    rs, _, rm = _run(
        32,
        ticks,
        reqtrace=True,
        req_capacity=rt.req_capacity_for(q, ticks),
        req_sample_log2=0,
    )
    assert int(rs.rstate.req_drops) == 0
    assert int(rs.rstate.req_head) == int(
        np.asarray(rm.route_queries).sum()
    )
    # a quiet cluster saturates the per-tick bound exactly
    quiet = RoutedStorm(
        32,
        params=_params(32),
        route=_route(
            32, reqtrace=True, req_capacity=2 * q, req_sample_log2=0
        ),
        seed=0,
    )
    quiet.run(StormSchedule(ticks=1, n=32))
    assert int(quiet.rstate.req_head) == rt.max_requests_per_tick(q)


def test_overflow_counts_never_overwrites():
    """An undersized buffer keeps an HONEST PREFIX: head pins at cap,
    every overflowing record bumps req_drops instead of clobbering, the
    stored rows still reconcile as a prefix (records <= counters), and
    the decoder annotates truncation."""
    cap = 100  # << the ~5120 sendable requests of the storm
    rs, _, rm = _run(
        64, 20, reqtrace=True, req_capacity=cap, req_sample_log2=0
    )
    st = rs.rstate
    total = int(np.asarray(rm.route_queries).sum())
    assert int(st.req_head) == cap
    assert int(st.req_drops) == total - cap
    rec = oreq.reconcile_records(st.req_buf, st.req_head, st.req_counts)
    for field, v in rec.items():
        assert v["records"] <= v["counts"], (field, v)
    # the prefix is the FIRST cap records: ticks are monotone from 1
    arrs = oreq.decode_arrays(st.req_buf, st.req_head)
    assert arrs["tick"][0] == 1
    assert (np.diff(arrs["tick"]) >= 0).all()
    reqs = oreq.decode_requests(st.req_buf, st.req_head, st.req_drops)
    assert len(reqs) == cap
    assert all(r["truncated_stream"] for r in reqs)
    drained = rs.drain_requests(reset=True)
    assert drained["drops"] == total - cap
    # the counters kept counting THROUGH the overflow
    assert drained["counts"]["queries"] == total


# -- sampler unbiasedness (chi-square, Zipf mixes) ---------------------------


def _chi2_binary(observed, trials, p):
    e1 = trials * p
    e0 = trials - e1
    o1 = observed
    o0 = trials - observed
    return (o1 - e1) ** 2 / e1 + (o0 - e0) ** 2 / e0


def test_sample_mask_chi_square_unbiased_over_key_space():
    """Per-key Bernoulli decisions are uniform over the key space: for
    each salt the sampled-key count over M distinct keys is a
    Binomial(M, 2^-s) draw; the summed chi-square across 8 salts must
    sit below the df=8 critical value at alpha=0.001 (26.12)."""
    m, s = 4096, 2
    kh = np.asarray(traffic.key_hashes(np.arange(m, dtype=np.int32)))
    stat = 0.0
    rates = []
    for salt in (0x7E57A8, 1, 2, 3, 0xDEADBEEF, 17, 257, 65537):
        mask = np.asarray(rt.sample_mask(kh, salt, s))
        assert mask.shape == (m,)
        stat += _chi2_binary(int(mask.sum()), m, 2.0**-s)
        rates.append(mask.mean())
    assert stat < 26.12, (stat, rates)


def test_sample_mask_unbiased_under_zipf_traffic():
    """The acceptance claim: sampling is per KEY, yet the sampled share
    of TRAFFIC stays ~2^-s even when the traffic is heavily Zipf-skewed
    (the top key draws ~14% of all queries) — averaged across salts the
    per-key decisions wash out of the skew."""
    m, s, q = 4096, 2, 1 << 16
    kh = np.asarray(traffic.key_hashes(np.arange(m, dtype=np.int32)))
    w = 1.0 / np.arange(1, m + 1) ** 1.1
    w /= w.sum()
    draws = np.random.default_rng(11).choice(m, size=q, p=w)
    shares = []
    for salt in (0x7E57A8, 1, 2, 3, 0xDEADBEEF, 17, 257, 65537):
        mask = np.asarray(rt.sample_mask(kh, salt, s))
        shares.append(float(mask[draws].mean()))
        # no single salt collapses or saturates under the skew
        assert 0.05 < shares[-1] < 0.6, (salt, shares[-1])
    assert abs(np.mean(shares) - 2.0**-s) < 0.05, shares


def test_sample_mask_rate_zero_and_consistency():
    kh = np.asarray(traffic.key_hashes(np.arange(512, dtype=np.int32)))
    assert np.asarray(rt.sample_mask(kh, 7, 0)).all()
    a = np.asarray(rt.sample_mask(kh, 7, 3))
    b = np.asarray(rt.sample_mask(kh, 7, 3))
    np.testing.assert_array_equal(a, b)  # per-key, deterministic
    c = np.asarray(rt.sample_mask(kh, 8, 3))
    assert (a != c).any()  # a different salt picks a different subset


# -- checkpoint neutrality ---------------------------------------------------


def test_checkpoint_roundtrip_toggles_reqtrace_plane(tmp_path):
    """A reqtrace-enabled storm checkpoint restores onto a reqtrace-off
    storm (plane dropped) and vice versa (fresh window) — the knob is
    trajectory-neutral in checkpoint params, and both resumes continue
    metrics-bitwise-identically."""
    n = 48
    sched = StormSchedule.churn_storm(10, n, fraction=0.2, seed=4)

    def mk(reqtrace):
        kw = {}
        if reqtrace:
            kw = dict(
                reqtrace=True,
                req_capacity=rt.req_capacity_for(256, 10),
                req_sample_log2=1,
            )
        return RoutedStorm(
            n=n, params=_params(n), route=_route(n, **kw), seed=6
        )

    on = mk(True)
    on.run(sched.window(0, 5))
    assert int(on.rstate.req_head) > 0
    path = str(tmp_path / "ck")
    on.save(path)

    off = mk(False)
    off.load(path)
    assert off.rstate.req_buf is None
    on2 = mk(True)
    on2.load(path)
    # telemetry, not trajectory: the resume starts a fresh window
    assert on2.rstate.req_buf is not None
    assert int(on2.rstate.req_head) == 0
    assert int(on2.rstate.req_tick) == 0

    _assert_cluster_states_equal(off.cluster.state, on2.cluster.state)
    em_a, rm_a = off.run(sched.window(5, 10))
    em_b, rm_b = on2.run(sched.window(5, 10))
    for f in rm_a._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(rm_a, f)), np.asarray(getattr(rm_b, f)), f
        )
    _assert_cluster_states_equal(off.cluster.state, on2.cluster.state)
    assert off.ring_checksum() == on2.ring_checksum()


def test_drain_requires_enabled():
    rs, _, _ = _run(16, 4)
    with pytest.raises(ValueError):
        rs.drain_requests()
