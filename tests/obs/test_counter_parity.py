"""Counter semantics across the two engines.

The full-fidelity [N, N] engine and the O(N·U) scalable engine model the
same protocol at different fidelities; on an identical trajectory
(same cluster, same fault schedule, no packet loss) the counters whose
semantics coincide must agree:

- ``pings_sent`` — both count gossip initiators per tick,
- exactly one faulty SUBJECT from a single kill (engine counters count
  per-observer marks, so the subject count is recovered from state),
- zero refutes and zero inconclusive ping-req verdicts in a loss-free
  run (nothing defames a live node; intermediaries always respond).
"""

from __future__ import annotations

import numpy as np

from ringpop_tpu.models.sim import engine, engine_scalable as es
from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster
from ringpop_tpu.models.sim.storm import ScalableCluster, StormSchedule

N = 24
KILL_TICK = 3
# 46 ticks: past the scalable engine's max_rumor_age at n=24
# (15*2 + 8 = 38), so the kill-era suspect rumor ages out in-window and
# rumors_retired is exercised on the SAME compiled scan
TICKS = 46


def _run_engine():
    sim = SimCluster(
        n=N,
        params=engine.SimParams(
            n=N, checksum_mode="fast", suspicion_ticks=6
        ),
        seed=1,
    )
    sim.bootstrap()
    sched = EventSchedule(ticks=TICKS, n=N)
    sched.kill[KILL_TICK, 5] = True
    return sim, sim.run(sched)


def _run_scalable():
    sc = ScalableCluster(
        n=N,
        params=es.ScalableParams(n=N, u=128, suspicion_ticks=6),
        seed=1,
    )
    sched = StormSchedule(ticks=TICKS, n=N)
    sched.kill[KILL_TICK, 5] = True
    return sc, sc.run(sched)


def test_counter_parity_on_identical_trajectory():
    sim, m_full = _run_engine()
    sc, m_scale = _run_scalable()

    # pings_sent: every live gossiping node initiates one exchange per
    # tick in BOTH engines (the engine's bootstrap happened pre-window,
    # the scalable cluster starts converged-alive)
    full_sent = np.asarray(m_full.pings_sent)
    scale_sent = np.asarray(m_scale.pings_sent)
    assert (full_sent == scale_sent).all(), (
        full_sent.tolist(),
        scale_sent.tolist(),
    )
    # the kill drops exactly one initiator in both
    assert full_sent[KILL_TICK - 1] == N
    assert full_sent[KILL_TICK] == N - 1

    # exactly one faulty SUBJECT either way
    scale_faulty = int(np.asarray(m_scale.faulties_published).sum())
    assert scale_faulty == 1
    st = sim.state
    full_faulty_subjects = int(
        np.asarray(
            (np.asarray(st.status) == engine.FAULTY).any(axis=0)
        ).sum()
    )
    assert full_faulty_subjects == 1
    # the engine counts suspicion-EXPIRY marks (observers whose own
    # clock fired; the rest learn the faulty via dissemination, counted
    # under changes_applied) — at least one observer expired
    assert int(np.asarray(m_full.faulties_marked).sum()) >= 1

    # suspicion fired for that subject in both engines
    assert int(np.asarray(m_full.suspects_marked).sum()) >= 1
    assert int(np.asarray(m_scale.suspects_published).sum()) == 1

    # loss-free run: no false defamations -> no refutes; intermediaries
    # always respond -> no inconclusive verdicts
    assert int(np.asarray(m_full.refutes).sum()) == 0
    assert int(np.asarray(m_scale.refutes_published).sum()) == 0
    assert int(np.asarray(m_full.ping_req_inconclusive).sum()) == 0
    assert int(np.asarray(m_scale.ping_req_inconclusive).sum()) == 0

    # both converge back to one view
    assert int(np.asarray(m_full.distinct_checksums)[-1]) == 1
    assert int(np.asarray(m_scale.distinct_checksums)[-1]) == 1


def test_lossy_run_fires_refutes_and_drops():
    """Packet loss produces false suspects -> refutes, and the window
    retires changes at the piggyback bound.  Engine-only: the scalable
    refute machinery has its own suite (tests/models/
    test_engine_scalable.py) and its aging/delivery counters are
    asserted on the shared loss-free trajectory below — one compile
    fewer in a tier-1 suite that runs close to its timeout."""
    p_full = engine.SimParams(
        n=N, checksum_mode="fast", packet_loss=0.25, suspicion_ticks=6
    )
    sim = SimCluster(n=N, params=p_full, seed=7)
    sim.bootstrap()
    m_full = sim.run(EventSchedule(ticks=44, n=N))
    assert int(np.asarray(m_full.refutes).sum()) > 0
    assert int(np.asarray(m_full.piggyback_drops).sum()) > 0
    # full syncs carry at least one record each
    fs = np.asarray(m_full.full_syncs)
    fsr = np.asarray(m_full.full_sync_records)
    assert (fsr >= fs).all()
    assert (fsr[fs == 0] == 0).all()


def test_scalable_aging_and_delivery_counters():
    """rumors_retired fires once the kill-era rumors age past
    15*ceil(log10(n+1)) + slack = 38 ticks, and pings_delivered ==
    pings_sent without loss.  Same params/schedule shape as the parity
    test above — the compiled scan is reused."""
    sc, m = _run_scalable()
    sent = np.asarray(m.pings_sent)
    deliv = np.asarray(m.pings_delivered)
    # loss-free: the only undelivered pings are those aimed at the dead
    # node (and a left/dead initiator sends none)
    assert (deliv <= sent).all()
    assert (sent - deliv).max() <= 1
    assert int(np.asarray(m.rumors_retired).sum()) > 0


def test_quiet_converged_ticks_have_silent_counters():
    """After convergence with no faults and no loss, every event counter
    sits at zero — the telemetry baseline for regression diffing."""
    sim = SimCluster(
        n=16, params=engine.SimParams(n=16, checksum_mode="fast"), seed=0
    )
    sim.bootstrap()
    assert sim.run_until_converged(max_ticks=40, quiet_after=1) > 0
    # convergence != empty change tables: bootstrap-era changes keep
    # burning piggyback budget until the 15*ceil(log10(17)) = 30 bound
    # retires them (as drops).  Settle past the bound first so the
    # measured window is the true steady state.
    for _ in range(34):
        sim.step()
    m = sim.run(EventSchedule(ticks=12, n=16))
    for field in (
        "refutes",
        "piggyback_drops",
        "full_syncs",
        "full_sync_records",
        "ping_req_inconclusive",
        "join_merges",
        "suspects_marked",
        "faulties_marked",
        "changes_applied",
        "dirty_rows",
        "parity_overflow",
    ):
        assert int(np.asarray(getattr(m, field)).sum()) == 0, field
