"""Host half of the mesh exchange telemetry (obs.exchange_stats):
exact wire-byte pricing, the (S-1)/S interconnect fraction, reconcile
identities, and schema-valid runlog/statsd emission from drain()."""

from __future__ import annotations

import importlib.util
import os

import numpy as np
import pytest

from ringpop_tpu.obs import exchange_stats as oxs
from ringpop_tpu.obs.recorder import RunRecorder
from ringpop_tpu.obs.statsd_bridge import StatsdBridge
from ringpop_tpu.ops import exchange as exch
from ringpop_tpu.utils.stats import CapturingStatsd

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _counters_2shard():
    """A hand-built 2-shard window: 2 ticks, one push trip fell back."""
    c = np.zeros((2, len(exch.EXCH_COUNTERS)), np.uint32)
    idx = {f: i for i, f in enumerate(exch.EXCH_COUNTERS)}
    for s in range(2):
        c[s, idx["ticks"]] = 2
        c[s, idx["a2a_pull"]] = 2
        c[s, idx["a2a_push"]] = 1
        c[s, idx["fallback_push"]] = 1
        c[s, idx["pull_rows"]] = 5 + s
        c[s, idx["push_rows"]] = 6 - s
        c[s, idx["dest_shards_pull"]] = 4
        c[s, idx["dest_shards_push"]] = 3
    return c


def test_drain_counters_price_wire_bytes_exactly():
    w, local = 4, 4
    rows = exch.drain_exchange_counters(
        _counters_2shard(), w=w, cap=None, local_rows=local
    )
    assert [r.shard for r in rows] == [0, 1]
    cap = exch.exchange_cap(local, 2)
    a2a = exch.a2a_trip_bytes(w, 2, cap)
    fb = exch.fallback_trip_bytes(local, w, 2)
    for r in rows:
        assert r.wire_bytes_pull == 2 * a2a
        assert r.wire_bytes_push == 1 * a2a + 1 * fb
    assert rows[0].pull_rows == 5 and rows[1].pull_rows == 6


def test_totals_and_interconnect_fraction():
    rows = exch.drain_exchange_counters(
        _counters_2shard(), w=4, cap=None, local_rows=4
    )
    tot = oxs.totals(rows)
    assert tot["shards"] == 2
    assert tot["pull_rows"] == 11 and tot["push_rows"] == 11
    full = tot["wire_bytes_pull"] + tot["wire_bytes_push"]
    # exactly the (S-1)/S fraction of the full buffers crosses shards
    assert oxs.measured_interconnect_bytes(tot) == full * 1 // 2
    # degenerate single shard: nothing crosses
    assert oxs.measured_interconnect_bytes({"shards": 1}) == 0


def test_reconcile_is_exact_without_fallbacks():
    """Construct totals straight from the model: ratio must be 1.0."""
    n, w, s, ticks = 64, 4, 4, 8
    cap = exch.exchange_cap(n // s, s)
    per_tick = s * exch.a2a_trip_bytes(w, s, cap)
    tot = {
        "shards": s,
        "ticks": s * ticks,
        "fallback_pull": 0,
        "fallback_push": 0,
        "wire_bytes_pull": per_tick * ticks,
        "wire_bytes_push": per_tick * ticks,
    }
    rec = oxs.reconcile(tot, n=n, w=w)
    assert rec["ticks"] == ticks
    assert rec["measured_interconnect"] == rec["model_interconnect"]
    assert rec["ratio"] == 1.0
    assert rec["fallback_trips"] == 0


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_metrics_schema",
        os.path.join(REPO_ROOT, "scripts", "check_metrics_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_drain_emits_schema_valid_rows_and_statsd_keys(tmp_path):
    path = str(tmp_path / "drain.runlog.jsonl")
    cap = CapturingStatsd()
    bridge = StatsdBridge(statsd=cap, host_port="127.0.0.1:4080")
    hist = np.asarray(exch.init_exchange_hist(2))
    with RunRecorder(path, config={}) as rec:
        summary = oxs.drain(
            _counters_2shard(),
            hist,
            w=4,
            local_rows=4,
            source="test",
            recorder=rec,
            statsd=bridge,
        )
    assert summary["totals"]["shards"] == 2
    assert summary["reconcile"]["shards"] == 2
    assert set(summary["cap_util"]) == set(exch.EXCH_HIST_TRACKS)
    # one drain row per shard + one reconcile row, all schema-valid
    problems = _load_checker().check([path], verbose=False)
    assert problems == [], "\n".join(problems)
    import json

    with open(path) as fh:
        rows = [json.loads(line) for line in fh]
    names = [r.get("name") for r in rows if r.get("kind") == "event"]
    assert names.count(oxs.EXCHANGE_DRAIN_EVENT) == 2
    assert names.count(oxs.TRAFFIC_RECONCILE_EVENT) == 1
    # statsd saw the summed counters
    keys = {r[1] for r in cap.records}
    assert "ringpop.127_0_0_1_4080.sharded.exchange.ticks" in keys


def test_sinks_run_before_any_reset_can_happen():
    """A raising sink propagates — the caller must not have reset the
    device window yet (the drain contract both drivers rely on)."""

    class Boom:
        def record_event(self, *a, **k):
            raise RuntimeError("sink down")

    with pytest.raises(RuntimeError, match="sink down"):
        oxs.drain(
            _counters_2shard(),
            w=4,
            local_rows=4,
            source="test",
            recorder=Boom(),
        )
