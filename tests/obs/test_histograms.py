"""Host half of the latency histograms: exact percentile extraction vs
a raw-value numpy oracle, batched drains, and the adaptive-period
consumer (obs/histograms.py)."""

import numpy as np
import pytest

from ringpop_tpu.obs import histograms as oh
from ringpop_tpu.ops import histogram as hg


def _counts_of(values) -> np.ndarray:
    counts = np.zeros(hg.NBUCKETS, np.int64)
    for b in hg.bucket_index_np(values):
        counts[b] += 1
    return counts


def _nearest_rank(values, q) -> int:
    s = np.sort(np.asarray(values))
    rank = max(1, int(np.ceil(q / 100.0 * s.size)))
    return int(s[rank - 1])


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
@pytest.mark.parametrize("q", [50, 95, 99])
def test_percentile_bucket_contains_true_order_statistic(seed, q):
    """The exactness claim: bucketization is monotone, so the bucket
    found by walking cumulative counts to the nearest-rank position is
    EXACTLY the bucket holding the true order statistic of the raw
    values — lo <= v* <= hi, and the bucket indices agree."""
    rng = np.random.default_rng(seed)
    values = (2.0 ** (rng.random(997) * 30)).astype(np.int64) - 1
    counts = _counts_of(values)
    p = oh.percentile(counts, q)
    vstar = _nearest_rank(values, q)
    assert p["bucket"] == int(hg.bucket_index_np(vstar))
    assert p["lo"] <= vstar <= p["hi"]
    assert p["value"] == p["hi"]


def test_percentile_empty_histogram_is_none():
    counts = np.zeros(hg.NBUCKETS, np.int64)
    assert oh.percentile(counts, 50) is None
    s = oh.summarize_track(counts)
    assert s["count"] == 0 and s["p50"] is None and s["p99"] is None


def test_percentile_single_bucket_and_top_bucket():
    counts = np.zeros(hg.NBUCKETS, np.int64)
    counts[0] = 10
    assert oh.percentile(counts, 99)["value"] == 0
    # overflow-range values (>= 2^30) land in the top bucket and come
    # back with its bounds, never clipped away
    top = np.zeros(hg.NBUCKETS, np.int64)
    top[hg.NBUCKETS - 1] = 3
    p = oh.percentile(top, 50)
    assert p["bucket"] == hg.NBUCKETS - 1 and p["hi"] == 2**31 - 1


def test_percentile_rank_boundaries_exact():
    # 100 observations of value 1, one of value 1000: p99 must stay in
    # bucket(1); only p>99.0099.. crosses — nearest-rank arithmetic, no
    # interpolation
    values = [1] * 100 + [1000]
    counts = _counts_of(values)
    assert oh.percentile(counts, 99)["value"] == 1
    assert oh.percentile(counts, 100)["bucket"] == int(
        hg.bucket_index_np(1000)
    )


def test_percentile_rejects_bad_q():
    counts = _counts_of([1, 2, 3])
    with pytest.raises(ValueError):
        oh.percentile(counts, 0)
    with pytest.raises(ValueError):
        oh.percentile(counts, 101)


def test_summarize_names_tracks_and_checks_shape():
    h = np.zeros((2, hg.NBUCKETS), np.int64)
    h[0][1] = 4
    s = oh.summarize(h, ("a", "b"))
    assert s["a"]["count"] == 4 and s["b"]["count"] == 0
    with pytest.raises(ValueError):
        oh.summarize(h, ("a",))
    with pytest.raises(ValueError):
        oh.summarize(np.zeros((2, 2, hg.NBUCKETS)), ("a", "b"))


def test_summarize_batched_aggregate_pools_observations():
    """A vmapped [B, H, NB] drain: aggregate percentiles == percentiles
    of the pooled raw observations (bucket counts are additive)."""
    rng = np.random.default_rng(7)
    per_instance = [rng.integers(0, 1000, size=50) for _ in range(4)]
    h = np.stack([[_counts_of(v)] for v in per_instance])  # [4, 1, NB]
    agg = oh.summarize_batched(h, ("t",), aggregate=True)
    pooled = np.concatenate(per_instance)
    want = oh.summarize_track(_counts_of(pooled))
    assert agg["t"] == want
    per = oh.summarize_batched(h, ("t",), aggregate=False)
    assert len(per) == 4
    for inst, vals in zip(per, per_instance):
        assert inst["t"]["count"] == len(vals)


def test_drain_row_shape_passes_schema_gate(tmp_path):
    """A hist.drain event row written through a RunRecorder validates
    against scripts/check_metrics_schema.py (the CI gate)."""
    import importlib.util as ilu
    import os

    from ringpop_tpu.obs.recorder import RunRecorder

    summary = oh.summarize(np.zeros((1, hg.NBUCKETS)), ("rumor_age",))
    path = str(tmp_path / "x.runlog.jsonl")
    with RunRecorder(path) as rec:
        rec.record_event("hist.drain", **oh.drain_row("sim.engine", summary))
    spec = ilu.spec_from_file_location(
        "check_metrics_schema",
        os.path.join(
            os.path.dirname(__file__), "..", "..", "scripts",
            "check_metrics_schema.py",
        ),
    )
    mod = ilu.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.check([path], verbose=False) == []
    # and a BROKEN drain row (track summary missing p-keys) is caught
    with open(path, "a", encoding="utf-8") as fh:
        fh.write(
            '{"kind": "event", "name": "hist.drain", "source": "x", '
            '"tracks": {"t": {"count": 1}}}\n'
        )
    assert mod.check([path], verbose=False) != []


def test_host_histogram_shares_bucket_scheme():
    h = oh.HostHistogram(unit=0.5)
    for v in (0.0, 1.0, 1.0, 4.0):
        h.observe(v)
    h.observe(-1.0)  # ignored
    s = h.summary()
    assert s["count"] == 4
    # values scale back to caller units (bucketized at 0.5/unit)
    assert s["p50"] == hg.bucket_hi(int(hg.bucket_index_np(2))) * 0.5


def test_compute_protocol_delay_reference_formula():
    """computeProtocolDelay (lib/gossip/index.js:42-50): p50 x 2 floored
    at the minimum protocol period; no samples -> the floor."""
    assert oh.compute_protocol_delay(None) == 200.0
    assert oh.compute_protocol_delay(50.0) == 200.0  # 100 < floor
    assert oh.compute_protocol_delay(150.0) == 300.0
    assert oh.compute_protocol_delay(150.0, min_protocol_period=400) == 400.0


def test_adaptive_protocol_period_consumer():
    app = oh.AdaptiveProtocolPeriod(min_period_ms=200.0)
    assert app.period_ms() == 200.0  # pre-samples: the floor
    for _ in range(100):
        app.observe(400.0)
    # p50 upper bound of bucket(400) x 2
    p50 = hg.bucket_hi(int(hg.bucket_index_np(400)))
    assert app.period_ms() == max(2.0 * p50, 200.0)
    assert app.period_ms() > 200.0  # the histogram is load-bearing
