"""Repo-wide run-log schema gate (the tier-1 twin of
scripts/check_metrics_schema.py): every committed *.runlog.jsonl must
validate against the recorder schema."""

from __future__ import annotations

import importlib.util
import os

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_metrics_schema",
        os.path.join(REPO_ROOT, "scripts", "check_metrics_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_every_repo_runlog_validates():
    checker = _load_checker()
    logs = checker.find_run_logs()
    # the sample artifact is committed, so the gate is never vacuous
    assert any(
        os.path.basename(p).startswith("sample_") for p in logs
    ), "committed sample runlog missing (runlogs/sample_*.runlog.jsonl)"
    problems = checker.check(logs, verbose=False)
    assert problems == [], "\n".join(problems)


def test_checker_catches_a_bad_log(tmp_path):
    checker = _load_checker()
    bad = tmp_path / "broken.runlog.jsonl"
    bad.write_text('{"kind": "tick", "metrics": {}}\nnot json\n')
    problems = checker.check([str(bad)], verbose=False)
    assert problems, "checker accepted a log with no header + bad JSON"


def _header_line():
    import json

    return json.dumps(
        {
            "kind": "header",
            "schema": 1,
            "run_id": "r",
            "config": {},
            "provenance": {},
        }
    )


def test_route_fields_stay_in_lockstep_with_route_metrics():
    # the validator's required set IS RouteMetrics — drift either way
    # (a renamed counter, a forgotten validator update) fails here
    from ringpop_tpu.models.route.plane import RouteMetrics

    checker = _load_checker()
    assert checker.ROUTE_TICK_FIELDS == frozenset(RouteMetrics._fields)


def test_partial_route_tick_row_rejected(tmp_path):
    import json

    checker = _load_checker()
    log = tmp_path / "route.runlog.jsonl"
    full = {f: 1 for f in checker.ROUTE_TICK_FIELDS}
    partial = {"route_queries": 7}  # route_* present but incomplete
    log.write_text(
        "\n".join(
            [
                _header_line(),
                json.dumps({"kind": "tick", "tick": 0, "metrics": full}),
                json.dumps({"kind": "tick", "tick": 1, "metrics": partial}),
            ]
        )
        + "\n"
    )
    problems = checker.check([str(log)], verbose=False)
    assert any("route tick row missing" in p for p in problems)
    # and the complete row alone passes
    log.write_text(
        _header_line()
        + "\n"
        + json.dumps({"kind": "tick", "tick": 0, "metrics": full})
        + "\n"
    )
    assert checker.check([str(log)], verbose=False) == []


def test_route_event_rows_validated(tmp_path):
    import json

    checker = _load_checker()
    log = tmp_path / "routeev.runlog.jsonl"
    log.write_text(
        "\n".join(
            [
                _header_line(),
                json.dumps(
                    {
                        "kind": "event",
                        "name": "route_window",
                        "ring_impl": "incremental",
                        "n": 64,
                        "q": 256,
                    }
                ),
                json.dumps({"kind": "event", "name": "route_window"}),
                json.dumps({"kind": "event", "name": "route_rebuild_ab"}),
            ]
        )
        + "\n"
    )
    problems = checker.check([str(log)], verbose=False)
    assert any(
        "route_window event missing 'ring_impl'" in p for p in problems
    )
    assert any(
        "route_rebuild_ab event missing 'incremental_ms'" in p
        for p in problems
    )
    # non-route events stay unconstrained
    log.write_text(
        _header_line()
        + "\n"
        + json.dumps({"kind": "event", "name": "window"})
        + "\n"
    )
    assert checker.check([str(log)], verbose=False) == []


def test_exchange_drain_fields_stay_in_lockstep_with_exchange_metrics():
    # the round-17 drain-row required set IS ExchangeMetrics (+ the
    # window-identity extras) — a renamed counter or a forgotten
    # validator update fails here, same pin as the RouteMetrics gate
    from ringpop_tpu.obs import exchange_stats as oxs
    from ringpop_tpu.obs import xprof
    from ringpop_tpu.ops.exchange import ExchangeMetrics

    checker = _load_checker()
    assert set(checker.ROUTE_EVENT_FIELDS["mesh.exchange.drain"]) == set(
        oxs.EXCHANGE_DRAIN_EXTRAS
    ) | set(ExchangeMetrics._fields)
    assert set(checker.ROUTE_EVENT_FIELDS["traffic_reconcile"]) == {
        "source"
    } | set(
        oxs.reconcile(
            {
                "shards": 2,
                "ticks": 2,
                "fallback_pull": 0,
                "fallback_push": 0,
                "wire_bytes_pull": 0,
                "wire_bytes_push": 0,
            },
            n=8,
            w=4,
        )
    )
    assert (
        checker.ROUTE_EVENT_FIELDS["xprof.capture"] == xprof.XPROF_FIELDS
    )


def test_observatory_event_rows_validated(tmp_path):
    """Round-17 observatory events: a drain row missing a counter, a
    reconcile row missing its model bytes, or an xprof row missing its
    trace pointer is a drifted recorder, not a valid artifact."""
    import json

    checker = _load_checker()
    log = tmp_path / "obsrv.runlog.jsonl"
    good_drain = {"kind": "event", "name": "mesh.exchange.drain"}
    good_drain.update(
        {f: 1 for f in checker.ROUTE_EVENT_FIELDS["mesh.exchange.drain"]}
    )
    bad_drain = dict(good_drain)
    del bad_drain["wire_bytes_pull"]
    log.write_text(
        "\n".join(
            [
                _header_line(),
                json.dumps(good_drain),
                json.dumps(bad_drain),
                json.dumps({"kind": "event", "name": "traffic_reconcile"}),
                json.dumps({"kind": "event", "name": "xprof.capture"}),
            ]
        )
        + "\n"
    )
    problems = checker.check([str(log)], verbose=False)
    assert any(
        "mesh.exchange.drain event missing 'wire_bytes_pull'" in p
        for p in problems
    )
    assert any(
        "traffic_reconcile event missing 'model_interconnect'" in p
        for p in problems
    )
    assert any(
        "xprof.capture event missing 'trace_dir'" in p for p in problems
    )
    # a complete drain row alone passes
    log.write_text(_header_line() + "\n" + json.dumps(good_drain) + "\n")
    assert checker.check([str(log)], verbose=False) == []


def test_mesh_event_rows_validated(tmp_path):
    """Round-14 mesh-plane events: a weak_scaling row without its gate
    verdict (or a mesh_window without its shard count) is a drifted
    recorder, not a valid artifact."""
    import json

    checker = _load_checker()
    log = tmp_path / "meshev.runlog.jsonl"
    good_window = {
        "kind": "event",
        "name": "mesh_window",
        "n": 2048,
        "shards": 8,
        "ticks": 4,
        "exchange_mode": "shard_map",
        "node_ticks_per_sec": 1.0,
    }
    log.write_text(
        "\n".join(
            [
                _header_line(),
                json.dumps(good_window),
                json.dumps({"kind": "event", "name": "mesh_window"}),
                json.dumps({"kind": "event", "name": "weak_scaling"}),
                json.dumps(
                    {
                        "kind": "event",
                        "name": "mesh_exchange_resolution",
                        "requested": "auto",
                    }
                ),
            ]
        )
        + "\n"
    )
    problems = checker.check([str(log)], verbose=False)
    assert any("mesh_window event missing 'shards'" in p for p in problems)
    assert any(
        "weak_scaling event missing 'bitwise_equal'" in p for p in problems
    )
    assert any(
        "mesh_exchange_resolution event missing 'mode'" in p
        for p in problems
    )
    # a complete row alone passes
    log.write_text(_header_line() + "\n" + json.dumps(good_window) + "\n")
    assert checker.check([str(log)], verbose=False) == []


def test_reqtrace_and_slo_fields_stay_in_lockstep_with_obs():
    """Round-19 observatory rows: the checker's static registries ARE
    the obs-package registries — a renamed sampled counter, a changed
    percentile set, or a drifted row builder fails here (the checker
    must not import the package, so the copies are pinned)."""
    import numpy as np

    from ringpop_tpu.obs import requests as oreq
    from ringpop_tpu.obs import slo as oslo
    from ringpop_tpu.ops import histogram as hg

    checker = _load_checker()
    assert checker.REQTRACE_COUNT_FIELDS == oreq.COUNT_FIELDS
    assert checker.SLO_WINDOW_QS == oslo.WINDOW_QS
    # the drain-row builder produces exactly the required field set
    row = oreq.drain_row(
        "route", 0, 0, 8, 2, {f: 0 for f in oreq.COUNT_FIELDS}
    )
    assert set(checker.ROUTE_EVENT_FIELDS["reqtrace.drain"]) == set(row)
    # the window row carries the required set plus the percentile keys
    plane = oslo.SLOWindowPlane()
    plane.observe(1, np.zeros(hg.NBUCKETS), queries=1, errors=0)
    wrow = plane.window_row(1)
    want = set(checker.ROUTE_EVENT_FIELDS["slo.window"]) | {
        "p%d" % q for q in checker.SLO_WINDOW_QS
    }
    assert set(wrow) == want
    # the breach row names exactly the required fields (+ its p99)
    assert set(checker.ROUTE_EVENT_FIELDS["slo.breach"]) | {"p99"} == {
        "target",
        "tick",
        "window_ticks",
        "reason",
        "burn_rate",
        "success_rate",
        "p99",
    }


def test_observatory_request_rows_validated(tmp_path):
    """Round-19 rows: a reqtrace.drain whose counts object lost a
    counter, or an slo.window missing a percentile key, is a drifted
    recorder, not a valid artifact."""
    import json

    checker = _load_checker()
    log = tmp_path / "req.runlog.jsonl"
    good_drain = {
        "kind": "event",
        "name": "reqtrace.drain",
        "source": "route",
        "records": 4,
        "drops": 0,
        "cap": 64,
        "sample_log2": 2,
        "counts": {f: 0 for f in checker.REQTRACE_COUNT_FIELDS},
    }
    bad_drain = dict(good_drain)
    bad_drain["counts"] = {"queries": 4}  # lost its counters
    good_window = {
        "kind": "event",
        "name": "slo.window",
        "target": "route",
        "tick": 5,
        "window_ticks": 20,
        "windows": 4,
        "queries": 100,
        "errors": 0,
        "p50": None,  # empty window: None is VALID, absence is not
        "p95": None,
        "p99": None,
        "success_rate": 1.0,
        "burn_rate": 0.0,
        "breach": False,
        "breach_reason": "",
    }
    bad_window = {
        k: v for k, v in good_window.items() if k != "p99"
    }
    log.write_text(
        "\n".join(
            [
                _header_line(),
                json.dumps(good_drain),
                json.dumps(bad_drain),
                json.dumps(good_window),
                json.dumps(bad_window),
                json.dumps({"kind": "event", "name": "slo.breach"}),
            ]
        )
        + "\n"
    )
    problems = checker.check([str(log)], verbose=False)
    assert any(
        "reqtrace.drain counts missing 'misroutes'" in p
        for p in problems
    )
    assert any("slo.window row missing 'p99'" in p for p in problems)
    assert any(
        "slo.breach event missing 'reason'" in p for p in problems
    )
    # the complete rows alone pass
    log.write_text(
        "\n".join(
            [_header_line(), json.dumps(good_drain), json.dumps(good_window)]
        )
        + "\n"
    )
    assert checker.check([str(log)], verbose=False) == []
