"""Repo-wide run-log schema gate (the tier-1 twin of
scripts/check_metrics_schema.py): every committed *.runlog.jsonl must
validate against the recorder schema."""

from __future__ import annotations

import importlib.util
import os

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_metrics_schema",
        os.path.join(REPO_ROOT, "scripts", "check_metrics_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_every_repo_runlog_validates():
    checker = _load_checker()
    logs = checker.find_run_logs()
    # the sample artifact is committed, so the gate is never vacuous
    assert any(
        os.path.basename(p).startswith("sample_") for p in logs
    ), "committed sample runlog missing (runlogs/sample_*.runlog.jsonl)"
    problems = checker.check(logs, verbose=False)
    assert problems == [], "\n".join(problems)


def test_checker_catches_a_bad_log(tmp_path):
    checker = _load_checker()
    bad = tmp_path / "broken.runlog.jsonl"
    bad.write_text('{"kind": "tick", "metrics": {}}\nnot json\n')
    problems = checker.check([str(bad)], verbose=False)
    assert problems, "checker accepted a log with no header + bad JSON"
