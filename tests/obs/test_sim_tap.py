"""Trace taps beyond membership.checksum.update: the ring checksum event
and the sim-tick metrics tap (TracerStore against simulation engines)."""

from __future__ import annotations

import numpy as np

from ringpop_tpu.models.sim import engine
from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster
from ringpop_tpu.obs.sim_tap import SimTracerHost
from ringpop_tpu.utils.trace import TRACE_EVENTS, Tracer


class ListLogger:
    def __init__(self):
        self.records = []

    def info(self, msg, extra=None, **kw):
        self.records.append((msg, extra or kw))

    def debug(self, *a, **k):
        pass

    warning = warn = error = debug


def test_trace_events_table_has_new_entries():
    assert "ring.checksum.computed" in TRACE_EVENTS
    assert TRACE_EVENTS["ring.checksum.computed"]["emitter"] == "ring"
    assert "sim.tick.metrics" in TRACE_EVENTS
    assert TRACE_EVENTS["sim.tick.metrics"]["emitter"] == "sim_events"


def test_ring_checksum_computed_tap_fires():
    """A log-sink tracer on ring.checksum.computed sees every ring
    rebuild, blob included."""
    from ringpop_tpu.api.ringpop import Ringpop
    from ringpop_tpu.net.timers import FakeTimers

    rp = Ringpop("tap-app", "127.0.0.1:3000", timers=FakeTimers())
    logger = ListLogger()
    rp.logger = logger
    tracer = Tracer(rp, "ring.checksum.computed", {"type": "log"})
    rp.tracers.add(tracer)
    rp.ring.add_server("127.0.0.1:3001")
    assert logger.records, "ring tap never fired"
    _, extra = logger.records[-1]
    blob = extra["blob"]
    assert blob["serverCount"] == 1
    assert blob["checksum"] == rp.ring.checksum
    rp.destroy()


def test_sim_tick_metrics_tap_through_tracer_store():
    """The simulation engines have no facade; SimTracerHost adapts a
    SimCluster so TracerStore/Tracer attach, and per-tick metric rows
    flow to the sink."""
    # n=16/T=12 matches the other tests/obs files: one shared compile
    sim = SimCluster(
        n=16, params=engine.SimParams(n=16, checksum_mode="fast")
    )
    host = SimTracerHost(sim, logger=ListLogger())
    tracer = Tracer(host, "sim.tick.metrics", {"type": "log"})
    host.tracers.add(tracer)

    sim.bootstrap()
    m = sim.run(EventSchedule(ticks=12, n=16))
    published = host.publish_tick_metrics(m, start_tick=1)
    assert published == 12

    records = host.logger.records
    assert len(records) == 12
    _, extra = records[0]
    blob = extra["blob"]
    assert blob["tick"] == 1
    assert blob["metrics"]["pings_sent"] == int(np.asarray(m.pings_sent)[0])
    assert "refutes" in blob["metrics"]

    # removal detaches the listener: further publishes stay silent
    host.tracers.remove("sim.tick.metrics", {"type": "log"})
    host.publish_tick_metrics(m)
    assert len(records) == 12
    host.destroy()


def test_sim_event_on_live_node_rejected_cleanly():
    """Regression: a known-but-unavailable event (sim.tick.metrics on a
    live facade, which has no sim_events emitter) must raise TraceError
    — so /trace/add answers ringpop.trace.invalid — not AttributeError."""
    import pytest

    from ringpop_tpu.api.ringpop import Ringpop
    from ringpop_tpu.net.timers import FakeTimers
    from ringpop_tpu.utils.trace import TraceError

    rp = Ringpop("tap-app", "127.0.0.1:3000", timers=FakeTimers())
    with pytest.raises(TraceError):
        Tracer(rp, "sim.tick.metrics", {"type": "log"})
    rp.destroy()


def test_single_tick_publish():
    host = SimTracerHost(logger=ListLogger())
    seen = []
    host.sim_events.on("tickMetrics", lambda blob: seen.append(blob))
    host.publish_tick_metrics(
        {"pings_sent": np.int32(7)}, start_tick=42
    )
    assert seen == [{"tick": 42, "metrics": {"pings_sent": 7}}]
