"""Vmapped [T, B] metric rows end-to-end through the telemetry layer.

PR 1 only exercised [T]-shaped series; the batched driver returns
[T, B]-shaped leaves (per-cluster vectors per tick).  This suite drives
REAL BatchedSimClusters metrics through ``iter_tick_rows`` ->
``StatsdBridge.emit_series`` and -> RunRecorder tick rows, plus the
ragged-pytree validation satellite."""

from __future__ import annotations

import numpy as np
import pytest

from ringpop_tpu.models.sim.batched import BatchedSimClusters
from ringpop_tpu.models.sim.cluster import EventSchedule
from ringpop_tpu.obs import RunRecorder, StatsdBridge
from ringpop_tpu.obs.recorder import iter_tick_rows


class _FakeStatsd:
    def __init__(self):
        self.incs = []
        self.gauges = []

    def increment(self, key, value=1):
        self.incs.append((key, value))

    def gauge(self, key, value):
        self.gauges.append((key, value))

    def timing(self, key, value):
        pass


@pytest.fixture(scope="module")
def batched_metrics():
    # same (params, universe) as tests/models/test_batched.py — the
    # compiled vmapped scan is shared via the module-level lru_cache
    b, n, T = 2, 48, 6
    bat = BatchedSimClusters(b=b, n=n, seed=3)
    bat.bootstrap()
    ms = bat.run(EventSchedule(ticks=T, n=n))
    return b, T, ms


def test_iter_tick_rows_unstacks_tb(batched_metrics):
    b, T, ms = batched_metrics
    rows = list(iter_tick_rows(ms))
    assert len(rows) == T
    for t, row in enumerate(rows):
        assert row["pings_sent"].shape == (b,)
        assert (
            row["pings_sent"] == np.asarray(ms.pings_sent)[t]
        ).all()


def test_statsd_bridge_sums_counters_across_the_batch(batched_metrics):
    b, T, ms = batched_metrics
    sink = _FakeStatsd()
    bridge = StatsdBridge(statsd=sink, host_port="127.0.0.1:3000")
    emitted = bridge.emit_series(ms)
    assert emitted > 0
    sent = [v for k, v in sink.incs if k.endswith(".ping.send")]
    # counters aggregate across the [B] axis per tick
    assert sum(sent) == int(np.asarray(ms.pings_sent).sum())
    # vector-valued gauges have no single-key meaning: skipped
    assert not any(
        k.endswith("checksums.distinct") for k, _ in sink.gauges
    )


def test_recorder_rows_carry_per_cluster_vectors(
    batched_metrics, tmp_path
):
    b, T, ms = batched_metrics
    rec = RunRecorder(str(tmp_path / "tb.runlog.jsonl"), config={})
    rec.record_ticks(ms)
    summary = rec.finish()
    from ringpop_tpu.obs import read_run_log, validate_run_log

    assert validate_run_log(rec.path) == []
    log = read_run_log(rec.path)
    # stride 1: every tick row landed, metrics are [B]-lists
    assert len(log["ticks"]) == T
    row0 = log["ticks"][0]["metrics"]
    assert isinstance(row0["pings_sent"], list)
    assert len(row0["pings_sent"]) == b
    # converged only counts when EVERY cluster converged
    conv = np.asarray(ms.converged)
    expect = None
    for t in range(T):
        if conv[t].all():
            expect = t
            break
    assert summary["convergence_tick"] == expect


def test_ragged_pytree_raises_before_misslicing():
    ragged = {
        "a": np.arange(4, dtype=np.int32),
        "b": np.arange(3, dtype=np.int32),
    }
    with pytest.raises(ValueError, match="ragged"):
        list(iter_tick_rows(ragged))
    mixed = {"a": np.arange(4, dtype=np.int32), "b": np.int32(7)}
    with pytest.raises(ValueError, match="ragged"):
        list(iter_tick_rows(mixed))
    # all-scalar and all-[T] stay valid
    assert len(list(iter_tick_rows({"a": np.int32(1), "b": np.int32(2)}))) == 1
    assert (
        len(
            list(
                iter_tick_rows(
                    {
                        "a": np.arange(4, dtype=np.int32),
                        "b": np.arange(4, dtype=np.int32),
                    }
                )
            )
        )
        == 4
    )
