"""RunRecorder: JSONL round-trip, striding, folding, and the CPU smoke
run of the batched epidemic (the PR's acceptance scenario) — including
the no-host-callback assertion on the scanned tick."""

from __future__ import annotations

import json

import jax
import numpy as np

from ringpop_tpu.models.sim import engine
from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster
from ringpop_tpu.obs.recorder import (
    SCHEMA_VERSION,
    RunRecorder,
    read_run_log,
    validate_run_log,
)


def test_jsonl_round_trip(tmp_path):
    rec = RunRecorder(
        str(tmp_path) + "/", run_id="rt1", config={"scenario": "unit"}
    )
    with rec.phase("warm"):
        pass
    rec.record_tick({"pings_sent": 5, "refutes": 1, "converged": False})
    rec.record_tick({"pings_sent": 5, "refutes": 0, "converged": True})
    rec.record_event("note", detail="hello")
    summary = rec.finish(extra_field=7)

    log = read_run_log(rec.path)
    assert log["header"]["schema"] == SCHEMA_VERSION
    assert log["header"]["run_id"] == "rt1"
    assert log["header"]["config"]["scenario"] == "unit"
    assert "provenance" in log["header"]
    assert [t["metrics"]["pings_sent"] for t in log["ticks"]] == [5, 5]
    assert log["phases"][0]["name"] == "warm"
    assert log["events"][0]["detail"] == "hello"
    assert log["summary"]["totals"]["pings_sent"] == 10
    assert log["summary"]["totals"]["refutes"] == 1
    assert log["summary"]["convergence_tick"] == 1
    assert log["summary"]["extra_field"] == 7
    assert summary["ticks_recorded"] == 2
    assert validate_run_log(rec.path) == []


def test_stride_keeps_every_kth_row_and_batch_tail(tmp_path):
    rec = RunRecorder(str(tmp_path) + "/", run_id="st1", stride=4)
    series = {"pings_sent": np.arange(10, dtype=np.int32)}
    rec.record_ticks(series)
    rec.finish()
    log = read_run_log(rec.path)
    # rows at tick 0, 4, 8 (stride) plus 9 (batch tail)
    assert [t["tick"] for t in log["ticks"]] == [0, 4, 8, 9]
    # totals fold EVERY tick regardless of stride
    assert log["summary"]["totals"]["pings_sent"] == sum(range(10))
    assert log["summary"]["ticks_recorded"] == 10


def test_histograms_and_meters_fold(tmp_path):
    rec = RunRecorder(str(tmp_path) + "/", run_id="h1")
    for v in (1, 2, 3, 4):
        rec.record_tick({"changes_applied": v})
    assert rec.histograms["changes_applied"].mean() == 2.5
    assert rec.meters["changes_applied"].to_dict()["count"] == 10
    s = rec.finish()
    assert s["histograms"]["changes_applied"]["max"] == 4


def test_validate_flags_corruption(tmp_path):
    rec = RunRecorder(str(tmp_path) + "/", run_id="bad1")
    rec.record_tick({"pings_sent": 1})
    rec.finish()
    with open(rec.path, "a") as fh:
        fh.write("this is not json\n")
        fh.write(json.dumps({"kind": "tick", "metrics": {}}) + "\n")
        fh.write(json.dumps({"kind": "mystery"}) + "\n")
    problems = validate_run_log(rec.path)
    # the tick-less row trips both the missing-field and the index check
    assert len(problems) == 4
    assert any("not JSON" in p for p in problems)
    assert any("missing 'tick'" in p for p in problems)
    assert any("unknown kind" in p for p in problems)


def test_vector_converged_rows_do_not_fake_convergence(tmp_path):
    """Regression: a batched [B] converged row is a LIST after json
    conversion — truthiness must not declare convergence until every
    cluster converged."""
    rec = RunRecorder(str(tmp_path) + "/", run_id="vc1")
    rec.record_tick({"converged": [False, False]})
    assert rec.convergence_tick is None
    rec.record_tick({"converged": [True, False]})
    assert rec.convergence_tick is None
    rec.record_tick({"converged": [True, True]})
    assert rec.convergence_tick == 2
    rec.finish()
    assert read_run_log(rec.path)["summary"]["convergence_tick"] == 2


def test_default_run_ids_are_unique_within_a_second(tmp_path):
    """Regression: bench retry loops construct recorders back-to-back;
    same-second defaults must not append to one another's log."""
    clock = lambda: 1234.5  # frozen second
    a = RunRecorder(str(tmp_path) + "/", clock=clock)
    b = RunRecorder(str(tmp_path) + "/", clock=clock)
    assert a.run_id != b.run_id
    assert a.path != b.path
    a.record_tick({"pings_sent": 1})
    b.record_tick({"pings_sent": 2})
    a.finish()
    b.finish()
    assert validate_run_log(a.path) == []
    assert validate_run_log(b.path) == []


def test_aborted_run_leaves_valid_prefix(tmp_path):
    rec = RunRecorder(str(tmp_path) + "/", run_id="ab1")
    rec.record_tick({"pings_sent": 1})
    rec.close()  # no finish(): crashed mid-run
    assert validate_run_log(rec.path) == []
    log = read_run_log(rec.path)
    assert log["summary"] is None and len(log["ticks"]) == 1


# -- acceptance: CPU smoke of the batched epidemic -------------------------


def _iter_primitives(jaxpr):
    for eqn in jaxpr.eqns:
        yield eqn.primitive.name
        for v in eqn.params.values():
            vs = v if isinstance(v, (tuple, list)) else (v,)
            for x in vs:
                name = type(x).__name__
                if name == "ClosedJaxpr":
                    yield from _iter_primitives(x.jaxpr)
                elif name == "Jaxpr":
                    yield from _iter_primitives(x)


def test_batched_epidemic_smoke_writes_runlog_with_new_counters(tmp_path):
    """The acceptance scenario: a CPU batched-epidemic run records a
    JSONL log whose per-tick rows carry the new protocol counters, and
    the scanned tick contains NO host callbacks (one jit trace of the
    driver proves it — per-tick metrics stacking is pure lax.scan).

    b=3/n=48/T=28 deliberately matches tests/models/test_batched.py so
    the tier-1 session reuses its lru-cached executables (the suite runs
    close to its timeout; see ROADMAP tier-1)."""
    from ringpop_tpu.models.sim.batched import BatchedSimClusters

    rec = RunRecorder(
        str(tmp_path) + "/", run_id="epidemic", config={"scenario": "epidemic"}
    )
    b, n, T = 3, 48, 28
    bat = BatchedSimClusters(b=b, n=n, seed=3)
    bat.attach_recorder(rec)
    with rec.phase("bootstrap"):
        bat.bootstrap()
    sched = EventSchedule(ticks=T, n=n)
    sched.kill[2, 5] = True
    with rec.phase("run"):
        bat.run(sched)
    rec.finish()

    assert validate_run_log(rec.path) == []
    log = read_run_log(rec.path)
    assert log["header"]["config"]["engine"] == "sim.engine[batched]"
    assert log["header"]["config"]["b"] == b
    # 1 bootstrap row + T scanned ticks
    assert len(log["ticks"]) == T + 1
    row = log["ticks"][1]["metrics"]
    # per-tick TickMetrics rows include the NEW counters ([B]-vectors
    # under the vmapped driver)
    for field in (
        "refutes",
        "piggyback_drops",
        "full_sync_records",
        "ping_req_inconclusive",
        "join_merges",
        "dirty_rows",
    ):
        assert field in row, field
    # the epidemic exercises the new counters: every node's bootstrap
    # join merged, and the kill dirties membership views cluster-wide
    # (piggyback-drop/refute nonzero coverage lives in
    # tests/obs/test_counter_parity.py's lossy window)
    assert np.asarray(log["ticks"][0]["metrics"]["join_merges"]).sum() == b * n
    dirty = np.asarray(
        [t["metrics"]["dirty_rows"] for t in log["ticks"]]
    )
    assert dirty.sum() > 0
    suspects = np.asarray(
        [t["metrics"]["suspects_marked"] for t in log["ticks"]]
    )
    assert suspects.sum() > 0  # the killed node was detected

    # no host callback inside the scanned tick: jit-trace the driver once
    params = bat.params
    universe = bat.universe

    def scanned(state, inputs):
        return jax.lax.scan(
            lambda st, inp: engine.tick(st, inp, params, universe),
            state,
            inputs,
        )

    single = jax.tree.map(lambda a: a[0], bat.state)
    jaxpr = jax.make_jaxpr(scanned)(single, sched.as_inputs())
    prims = set(_iter_primitives(jaxpr.jaxpr))
    offenders = {p for p in prims if "callback" in p or "host" in p}
    assert not offenders, offenders


def test_sim_cluster_recorder_hook(tmp_path):
    """SimCluster.attach_recorder folds step() and run() metrics and
    stamps the engine config (incl. the static checksum-recompute path)
    into the header."""
    rec = RunRecorder(str(tmp_path) + "/", run_id="sc1")
    # n=16/T=12 matches the other tests/obs files: one shared compile
    sim = SimCluster(
        n=16, params=engine.SimParams(n=16, checksum_mode="fast")
    )
    sim.attach_recorder(rec)
    sim.bootstrap()
    sim.run(EventSchedule(ticks=12, n=16))
    rec.finish()
    log = read_run_log(rec.path)
    assert len(log["ticks"]) == 13
    cfg = log["header"]["config"]["params"]
    assert cfg["checksum_mode"] == "fast"
    assert cfg["parity_recompute"] in ("gated", "bounded", "full", "auto")
    assert log["summary"]["convergence_tick"] is not None
