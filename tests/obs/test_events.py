"""Flight-recorder event registry + decoder unit tests (host side only —
no engine, no jax; the device half is covered by
tests/models/test_flight_recorder.py)."""

from __future__ import annotations

import numpy as np
import pytest

from ringpop_tpu.obs import events as ev


def _buf(rows):
    buf = np.zeros((max(len(rows), 4), ev.RECORD_WIDTH), np.int32)
    for i, r in enumerate(rows):
        buf[i] = r
    return buf


def test_registry_is_bijective_and_stable():
    assert len(ev.EVENT_KINDS) == len(ev.KIND_CODES)
    for code, name in ev.EVENT_KINDS.items():
        assert ev.KIND_CODES[name] == code
    # layout constants must match the record width (device+host contract)
    assert len(ev.FIELDS) == ev.RECORD_WIDTH
    assert ev.FIELDS[ev.F_TICK] == "tick"
    assert ev.FIELDS[ev.F_AUX] == "aux"


def test_decode_respects_head_and_flags_truncation():
    rows = [
        [1, ev.EV_PING, 0, 3, -1, -1, 0, 1],
        [1, ev.EV_STATUS, 3, 0, -1, 0, 2, ev.PHASE_PING_RECV],
        [2, ev.EV_JOIN, 5, -1, -1, -1, 0, 7],
    ]
    buf = _buf(rows)
    assert ev.decode_events(buf, 0) == []
    two = ev.decode_events(buf, 2)
    assert len(two) == 2
    assert two[0]["kind_name"] == "ping"
    assert two[1]["observer"] == 3 and two[1]["new_status"] == 0
    assert "truncated_stream" not in two[0]
    truncated = ev.decode_events(buf, 3, drops=5)
    assert all(e["truncated_stream"] for e in truncated)
    # a head beyond capacity clamps instead of exploding
    assert len(ev.decode_events(buf, 10 ** 6)) == buf.shape[0]


def test_decode_rejects_wrong_width():
    with pytest.raises(ValueError):
        ev.decode_arrays(np.zeros((4, 3), np.int32), 2)


def test_validate_event_stream():
    good = ev.decode_events(
        _buf([[1, ev.EV_PING, 0, 1, -1, -1, 0, 1]]), 1
    )
    assert ev.validate_event_stream(good) == []
    bad = [dict(good[0])]
    bad[0]["kind"] = 99
    assert any("unknown kind" in p for p in ev.validate_event_stream(bad))
    decreasing = [dict(good[0], tick=5), dict(good[0], tick=4)]
    assert any(
        "decreases" in p for p in ev.validate_event_stream(decreasing)
    )
    missing = [{"tick": 1}]
    assert any(
        "missing field" in p for p in ev.validate_event_stream(missing)
    )


def test_reconcile_counts_by_kind():
    rows = [
        [1, ev.EV_PING, 0, 1, -1, -1, 0, 1],
        [1, ev.EV_PING, 1, 2, -1, -1, 0, 0],
        [2, ev.EV_SUSPECT, 0, 2, 0, 1, 3, 0],
        [2, ev.EV_FULL_SYNC, 1, 0, -1, -1, 0, 4],
    ]
    metrics = {
        "pings_sent": np.asarray([2, 0]),
        "pings_delivered": np.asarray([1, 0]),
        "suspects_marked": np.asarray([0, 1]),
        "full_syncs": np.asarray([0, 1]),
        "full_sync_records": np.asarray([0, 4]),
        "faulties_marked": np.asarray([0, 0]),
        "refutes": np.asarray([0, 0]),
        "join_merges": np.asarray([0, 0]),
    }
    rec = ev.reconcile(ev.decode_events(_buf(rows), 4), metrics)
    assert all(v["match"] for v in rec.values()), rec
    bad = dict(metrics, pings_sent=np.asarray([3, 0]))
    rec2 = ev.reconcile(ev.decode_events(_buf(rows), 4), bad)
    assert not rec2["pings_sent"]["match"]


def test_rumor_wavefronts_and_summary():
    # rumor (subject=2, status=1, inc=9): born at node 0 on tick 3,
    # adopted by nodes 1 and 4 on tick 4, node 3 on tick 6
    rows = [
        [3, ev.EV_STATUS, 0, 2, 0, 1, 9, 1],
        [4, ev.EV_STATUS, 1, 2, 0, 1, 9, 1],
        [4, ev.EV_STATUS, 4, 2, 0, 1, 9, 2],
        [6, ev.EV_STATUS, 3, 2, 0, 1, 9, 1],
        # a repeat adoption must not move the first-heard tick
        [7, ev.EV_STATUS, 1, 2, 0, 1, 9, 4],
        # an unrelated single-observer rumor
        [5, ev.EV_STATUS, 0, 7, -1, 0, 11, 1],
    ]
    wf = ev.rumor_wavefronts(ev.decode_events(_buf(rows), len(rows)))
    assert set(wf) == {(2, 1, 9), (7, 0, 11)}
    big = wf[(2, 1, 9)]
    assert big["birth"] == 3
    assert big["first_heard"] == {0: 3, 1: 4, 4: 4, 3: 6}
    assert big["convergence_curve"] == [(3, 1), (4, 3), (6, 4)]
    assert big["latency"] == {0: 0, 1: 1, 4: 1, 3: 3}
    assert big["hops"] == {0: 0, 1: 1, 4: 1, 3: 2}
    summary = ev.dissemination_summary(wf)
    assert len(summary["rumors"]) == 1  # min_observers filters the lone one
    assert summary["latency_histogram_ticks"] == {"0": 1, "1": 2, "3": 1}
    assert summary["hop_histogram"] == {"0": 1, "1": 2, "2": 1}


def test_scalable_wavefront_summary_shape():
    fh = np.asarray(
        [
            [2, -1],
            [3, -1],
            [5, -1],
        ],
        np.int32,
    )
    out = ev.scalable_wavefront_summary(
        fh,
        np.asarray([2, 0], np.int32),
        np.asarray([True, False]),
    )
    (r,) = out["rumors"]
    assert r["slot"] == 0 and r["birth"] == 2
    assert r["convergence_curve"] == [[2, 1], [3, 2], [5, 3]]
    assert out["latency_histogram_ticks"] == {"0": 1, "1": 1, "3": 1}
    # dead nodes are excluded via the live mask
    out2 = ev.scalable_wavefront_summary(
        fh,
        np.asarray([2, 0], np.int32),
        np.asarray([True, False]),
        live=np.asarray([True, True, False]),
    )
    assert out2["rumors"][0]["observers"] == 2


# -- degenerate-buffer hardening (ISSUE 7 satellite) -------------------------


def test_decode_full_ring_head_equals_capacity():
    """head == capacity is the 'buffer exactly full' honest state: every
    row decodes, nothing is clamped away."""
    rows = [[t, ev.EV_PING, 0, 1, -1, -1, 0, 1] for t in range(1, 5)]
    buf = _buf(rows)  # capacity 4
    assert buf.shape[0] == 4
    assert len(ev.decode_events(buf, 4)) == 4
    arrs = ev.decode_arrays(buf, 4)
    assert arrs["tick"].tolist() == [1, 2, 3, 4]
    # full ring + drops: decoded prefix is annotated, derivations work
    truncated = ev.decode_events(buf, 4, drops=3)
    assert all(e["truncated_stream"] for e in truncated)
    assert ev.rumor_wavefronts(truncated) == {}


def test_decode_degenerate_heads_and_buffers():
    buf = _buf([[1, ev.EV_PING, 0, 1, -1, -1, 0, 1]])
    # head=0 with drops>0: an empty honest prefix — no crash, no rows
    assert ev.decode_events(buf, 0, drops=9) == []
    assert ev.decode_arrays(buf, 0)["tick"].shape == (0,)
    # negative head clamps to empty rather than wrapping from the tail
    assert ev.decode_events(buf, -2) == []
    # zero-capacity buffer round-trips through decode + derivations
    z = np.zeros((0, ev.RECORD_WIDTH), np.int32)
    assert ev.decode_events(z, 0) == []
    assert ev.decode_arrays(z, 7)["tick"].shape == (0,)
    assert ev.rumor_wavefronts(ev.decode_arrays(z, 0)) == {}


def test_reconcile_accepts_raw_pair_and_empty_stream():
    import collections

    MT = collections.namedtuple("MT", ["pings_sent", "refutes"])
    buf = _buf([[1, ev.EV_PING, 0, 1, -1, -1, 0, 1]])
    out = ev.reconcile((buf, 1), MT(pings_sent=np.ones(1, np.int32),
                                    refutes=np.zeros(1, np.int32)))
    assert out["pings_sent"]["match"] and out["refutes"]["match"]
    # empty stream vs zero counters reconciles
    out0 = ev.reconcile([], MT(pings_sent=np.zeros(2, np.int32),
                               refutes=np.zeros(2, np.int32)))
    assert all(row["match"] for row in out0.values())


def test_field_incomplete_inputs_raise_value_error_not_key_error():
    """A half-built columnar dict or event list must fail loudly at the
    boundary (these used to surface as bare KeyErrors deep inside the
    reconciliation lambdas)."""
    with pytest.raises(ValueError, match="missing fields"):
        ev._as_arrays({"tick": np.zeros(1)})
    with pytest.raises(ValueError, match="missing fields"):
        ev._as_arrays([{"tick": 1}, {"tick": 2}])
    import collections

    MT = collections.namedtuple("MT", ["pings_sent"])
    with pytest.raises(ValueError, match="missing fields"):
        ev.reconcile({}, MT(pings_sent=np.zeros(1)))
