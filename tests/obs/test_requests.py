"""Host half of the request observatory: decoder round-trip,
reconciliation, span trees, Perfetto export, and the ``reqtrace.drain``
row riding a schema-valid runlog (pure host side — the device half is
pinned in tests/models/test_reqtrace.py)."""

from __future__ import annotations

import importlib.util
import json
import os

import numpy as np
import pytest

from ringpop_tpu.obs import chrome_trace as ct
from ringpop_tpu.obs import requests as oreq

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _key(u):
    """uint32 key hash -> the int32 slot value the device stores."""
    return int(np.array([u], np.uint32).view(np.int32)[0])


# four requests telling the full lifecycle story; key 0xBEEF repeats
# (sampling is per key, so its trace is complete across ticks)
_ROWS = [
    # tick key              snd dst own mis rr                depth multi outcome
    [1, _key(0x80000001), 3, 5, 5, 0, oreq.RR_NONE, 0, 0, 0],
    [1, _key(0xBEEF), 0, 2, 4, 1, oreq.RR_REMOTE, 1, 0, 0],
    [
        2,
        _key(7),
        1,
        6,
        6,
        0,
        oreq.RR_LOCAL,
        1,
        0,
        oreq.OUT_CHECKSUMS_DIFFER | oreq.OUT_CHECKSUM_REJECT,
    ],
    [2, _key(0xBEEF), 0, 2, 4, 1, oreq.RR_REMOTE, 1, 1, oreq.OUT_KEYS_DIVERGED],
]

_COUNTS = [4, 2, 1, 2, 1, 1, 1]  # matches COUNT_FIELDS order


def _buf(cap=8):
    buf = np.zeros((cap, oreq.RECORD_WIDTH), np.int32)
    buf[: len(_ROWS)] = np.asarray(_ROWS, np.int32)
    return buf, len(_ROWS)


def test_decode_arrays_recovers_uint32_keys():
    buf, head = _buf()
    arrs = oreq.decode_arrays(buf, head)
    assert set(arrs) == set(oreq.FIELDS)
    assert arrs["key"].dtype == np.uint32
    assert arrs["key"][0] == 0x80000001  # sign-bit key survives bitcast
    assert list(arrs["tick"]) == [1, 1, 2, 2]
    with pytest.raises(ValueError):
        oreq.decode_arrays(np.zeros((4, 3), np.int32), 4)


def test_decode_requests_annotates_truncation():
    buf, head = _buf()
    clean = oreq.decode_requests(buf, head, drops=0)
    assert len(clean) == head
    assert "truncated_stream" not in clean[0]
    cut = oreq.decode_requests(buf, head, drops=5)
    assert all(r["truncated_stream"] for r in cut)


def test_counts_dict_validates_shape():
    assert oreq.counts_dict(_COUNTS)["queries"] == 4
    with pytest.raises(ValueError):
        oreq.counts_dict([1, 2, 3])


def test_reconcile_records_exact_and_prefix():
    buf, head = _buf()
    rec = oreq.reconcile_records(buf, head, _COUNTS)
    assert set(rec) == set(oreq.COUNT_FIELDS)
    assert all(v["match"] for v in rec.values()), rec
    # a dropped tail shows as records < counts, never records > counts
    short = oreq.reconcile_records(buf, head - 1, _COUNTS)
    assert not short["queries"]["match"]
    assert all(
        v["records"] <= v["counts"] for v in short.values()
    ), short


def test_reconcile_metrics_subset_vs_totals():
    metrics = {
        "route_queries": np.array([8, 8]),
        "route_misroutes": np.array([2, 1]),
        "route_reroute_local": np.array([1, 0]),
        "route_reroute_remote": np.array([1, 1]),
        "route_keys_diverged": np.array([1, 0]),
        "route_checksums_differ": np.array([1, 1]),
        "route_checksum_rejects": np.array([1, 0]),
    }
    out = oreq.reconcile_metrics(_COUNTS, metrics)
    assert all(v["ok"] for v in out.values()), out
    assert out["queries"] == {"sampled": 4, "total": 16, "ok": True}
    # an impossible sampled > total is flagged, not silently accepted
    bad = list(_COUNTS)
    bad[1] = 99
    assert not oreq.reconcile_metrics(bad, metrics)["misroutes"]["ok"]


def test_outcome_label_precedence():
    buf, head = _buf()
    reqs = oreq.decode_requests(buf, head)
    assert [oreq.outcome_label(r) for r in reqs] == [
        "ok",
        "reroute.remote",
        "reject.checksum",  # reject outranks the local reroute
        "abort.keys-diverged",  # abort outranks everything
    ]


def test_span_trees_group_per_key_complete_lifecycle():
    buf, head = _buf()
    reqs = oreq.decode_requests(buf, head)
    trees = oreq.span_trees(reqs)
    assert set(trees) == {0x80000001, 0xBEEF, 7}
    # the sampled key's two requests arrive tick-ordered
    beef = trees[0xBEEF]
    assert [s["tick"] for s in beef] == [1, 2]
    # first: retry with a remote reroute child to the truth owner
    retry = beef[0]["children"][0]
    assert retry["name"] == "retry"
    assert retry["children"][0] == {"name": "reroute.remote", "dest": 4}
    # second: the multi-key pair diverged inside the retry
    names = [c["name"] for c in beef[1]["children"][0]["children"]]
    assert "abort.keys-diverged" in names
    # the checksum story carries its reject verdict
    ck = trees[7][0]["children"][0]
    assert ck == {"name": "checksums-differ", "rejected": True}
    with pytest.raises(TypeError):
        oreq.span_trees([(1, 2), (3, 4)])


def test_export_request_trace_validates_and_flows():
    buf, head = _buf()
    reqs = oreq.decode_requests(buf, head)
    trace = oreq.export_request_trace(reqs, n=8, period_ms=200)
    assert ct.validate_chrome_trace(json.dumps(trace)) == []
    evs = trace["traceEvents"]
    # one process meta + one thread meta per distinct sender
    assert sum(1 for e in evs if e["ph"] == "M") == 1 + 3
    spans = [e for e in evs if e["ph"] == "X"]
    assert len(spans) == len(reqs)
    # a retried request spans two protocol periods
    durs = {e["name"]: e["dur"] for e in spans}
    assert durs["ok"] == 200_000
    assert durs["reroute.remote"] == 400_000
    # both remote reroutes draw a flow arrow to the truth owner's track
    starts = [e for e in evs if e["ph"] == "s"]
    ends = [e for e in evs if e["ph"] == "t"]
    assert len(starts) == len(ends) == 2
    assert {e["tid"] for e in ends} == {4}
    assert {e["id"] for e in starts} == {e["id"] for e in ends}


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_metrics_schema",
        os.path.join(REPO_ROOT, "scripts", "check_metrics_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_drain_rides_a_schema_valid_runlog(tmp_path):
    """obs.requests.drain logs ONE reqtrace.drain event row that the
    repo's schema gate accepts, and the Perfetto sidecar written next
    to it validates — the committed-artifact path end to end."""
    from ringpop_tpu.obs.recorder import RunRecorder, read_run_log

    buf, head = _buf()
    path = str(tmp_path / "req.runlog.jsonl")
    rec = RunRecorder(path, run_id="t", config={})
    out = oreq.drain(
        buf, head, 0, _COUNTS, sample_log2=2, recorder=rec
    )
    assert out["records"] == oreq.decode_requests(buf, head)
    assert out["cap"] == buf.shape[0]
    assert out["counts"] == oreq.counts_dict(_COUNTS)
    rec.record_trace_sidecar(
        oreq.export_request_trace(out["records"], n=8), name="requests"
    )
    rec.finish()
    rows = read_run_log(path)["events"]
    drains = [r for r in rows if r["name"] == "reqtrace.drain"]
    assert len(drains) == 1
    assert drains[0]["records"] == head
    assert drains[0]["counts"]["queries"] == 4
    checker = _load_checker()
    assert checker.check([path], verbose=False) == []


def test_drain_row_missing_count_field_fails_the_gate(tmp_path):
    """The schema gate is not vacuous: a drain row whose counts object
    lost a counter (recorder drift) is rejected."""
    checker = _load_checker()
    good = oreq.drain_row("route", 4, 0, 8, 2, oreq.counts_dict(_COUNTS))
    bad = dict(good, counts={"queries": 4})
    log = tmp_path / "bad.runlog.jsonl"
    header = json.dumps(
        {
            "kind": "header",
            "schema": 1,
            "run_id": "r",
            "config": {},
            "provenance": {},
        }
    )
    log.write_text(
        header
        + "\n"
        + json.dumps(dict(bad, kind="event", name="reqtrace.drain"))
        + "\n"
    )
    problems = checker.check([str(log)], verbose=False)
    assert problems, "checker accepted a counts object missing fields"
    log.write_text(
        header
        + "\n"
        + json.dumps(dict(good, kind="event", name="reqtrace.drain"))
        + "\n"
    )
    assert checker.check([str(log)], verbose=False) == []
