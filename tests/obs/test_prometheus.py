"""Prometheus text exposition: live-node rendering (the /admin/metrics
body) and recorded-series rendering."""

from __future__ import annotations

import numpy as np

from ringpop_tpu.api.ringpop import Ringpop
from ringpop_tpu.models.sim import engine
from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster
from ringpop_tpu.net.timers import FakeTimers
from ringpop_tpu.obs.prometheus import (
    PromWriter,
    render_device_histograms,
    render_ringpop_metrics,
    render_slo_plane,
    render_tick_series,
)


def make_ringpop():
    timers = FakeTimers()
    rp = Ringpop("prom-app", "127.0.0.1:3000", timers=timers)
    rp.is_ready = True
    rp.membership.make_alive(rp.whoami(), timers.now_ms())
    rp.membership.make_alive("127.0.0.1:3001", timers.now_ms())
    return rp


def test_prom_writer_format():
    w = PromWriter()
    w.sample("x_total", 3, "a counter", "counter", {"app": 'a"b\n'})
    w.sample("x_total", 4, "a counter", "counter", {"app": "c"})
    text = w.render()
    lines = text.splitlines()
    assert lines[0] == "# HELP x_total a counter"
    assert lines[1] == "# TYPE x_total counter"
    # HELP/TYPE emitted once per metric name, labels escaped
    assert lines[2] == 'x_total{app="a\\"b\\n"} 3'
    assert lines[3] == 'x_total{app="c"} 4'
    assert text.endswith("\n")


def test_prom_writer_groups_interleaved_families():
    """Regression: the text format requires all samples of one metric in
    a single group — interleaved emission (per-plane loops) must come
    out grouped per family, in first-seen order."""
    w = PromWriter()
    for plane in ("client", "server"):
        w.sample("a_total", 1, "a", "counter", {"plane": plane})
        w.sample("b_rate", 2.0, "b", "gauge", {"plane": plane})
    lines = w.render().splitlines()
    assert lines == [
        "# HELP a_total a",
        "# TYPE a_total counter",
        'a_total{plane="client"} 1',
        'a_total{plane="server"} 1',
        "# HELP b_rate b",
        "# TYPE b_rate gauge",
        'b_rate{plane="client"} 2.0',
        'b_rate{plane="server"} 2.0',
    ]


def test_live_exposition_families_are_contiguous():
    """No metric family appears in two separate groups in the real
    /admin/metrics body."""
    text = render_ringpop_metrics(make_ringpop())
    seen, last = set(), None
    for line in text.splitlines():
        name = line.split("{")[0].split(" ")[0]
        if line.startswith("#"):
            name = line.split(" ")[2]
        if name != last:
            assert name not in seen, "family %s split into two groups" % name
            seen.add(name)
            last = name


def test_render_ringpop_metrics_exposes_core_families():
    rp = make_ringpop()
    text = render_ringpop_metrics(rp)
    assert "# TYPE ringpop_members gauge" in text
    assert "# TYPE ringpop_requests_total counter" in text
    assert 'plane="server"' in text
    assert "ringpop_membership_checksum" in text
    assert "ringpop_ring_servers" in text
    assert 'ringpop_members_by_status{' in text
    assert 'status="alive"' in text
    # instance label carries the host_port identity
    assert 'instance="127.0.0.1:3000"' in text


def test_render_tick_series_totals_and_gauges():
    # n=16/T=12 matches the other tests/obs files: one shared compile
    sim = SimCluster(
        n=16, params=engine.SimParams(n=16, checksum_mode="fast")
    )
    sim.bootstrap()
    m = sim.run(EventSchedule(ticks=12, n=16))
    text = render_tick_series(m, labels={"run": "t1"})
    assert "# TYPE ringpop_sim_pings_sent_total counter" in text
    want = int(np.asarray(m.pings_sent).sum())
    assert 'ringpop_sim_pings_sent_total{run="t1"} %d' % want in text
    # non-counter fields render as last-value gauges
    last_distinct = int(np.asarray(m.distinct_checksums)[-1])
    assert (
        'ringpop_sim_distinct_checksums{run="t1"} %d' % last_distinct
        in text
    )
    # the new counters are all present
    for f in ("refutes", "piggyback_drops", "ping_req_inconclusive"):
        assert "ringpop_sim_%s_total" % f in text


def test_help_text_is_escaped_per_exposition_format():
    """The 0.0.4 text format requires ``\\`` -> ``\\\\`` and newline ->
    ``\\n`` in HELP lines; unescaped, a newline splits the line and
    corrupts every sample after it (satellite fix, ISSUE 4)."""
    from ringpop_tpu.obs.prometheus import PromWriter

    w = PromWriter()
    w.sample(
        "x_total",
        1,
        help_="line one\nline two \\ backslash",
        type_="counter",
    )
    w.sample("y", 2, help_="plain", labels={"k": 'v"\n\\'})
    text = w.render()
    lines = text.splitlines()
    help_line = next(l for l in lines if l.startswith("# HELP x_total"))
    assert help_line == "# HELP x_total line one\\nline two \\\\ backslash"
    # exactly one physical line per logical row: nothing got split
    assert len([l for l in lines if l.startswith("#")]) == 4
    assert "x_total 1" in lines
    # label values keep their own (stricter) escaping, including quotes
    assert 'y{k="v\\"\\n\\\\"} 2' in lines


def _parse_histogram(text, name):
    """Parse one rendered histogram family back out of the exposition
    text: ({le: cumulative}, sum, count, type)."""
    buckets, hsum, hcount, type_ = {}, None, None, None
    for line in text.splitlines():
        if line == "# TYPE %s histogram" % name:
            type_ = "histogram"
        elif line.startswith(name + "_bucket"):
            le = line.split('le="')[1].split('"')[0]
            buckets[le] = int(line.rsplit(" ", 1)[1])
        elif line.startswith(name + "_sum"):
            hsum = float(line.rsplit(" ", 1)[1])
        elif line.startswith(name + "_count"):
            hcount = int(line.rsplit(" ", 1)[1])
    return buckets, hsum, hcount, type_


def test_histogram_family_round_trips_log2_buckets():
    """ISSUE 19 satellite acceptance: render log2 bucket counts as a
    native histogram, parse the text back, and recover the per-bucket
    counts exactly — cumulative ordering, upper-edge le bounds, the
    mandatory +Inf line, and _sum/_count intact."""
    from ringpop_tpu.ops import histogram as hg

    counts = [5, 3, 0, 0, 8, 0, 2] + [0] * (hg.NBUCKETS - 7)
    w = PromWriter()
    w.histogram("rt_depth", counts, "retry depth", {"run": "t1"})
    text = w.render()
    buckets, hsum, hcount, type_ = _parse_histogram(text, "rt_depth")
    assert type_ == "histogram"
    # one line per bucket up to the LAST occupied one, plus +Inf
    assert set(buckets) == {
        str(hg.bucket_hi(b)) for b in range(7)
    } | {"+Inf"}
    # cumulative series is nondecreasing and ends at the total
    les = sorted(
        (k for k in buckets if k != "+Inf"), key=lambda s: int(s)
    )
    cum = [buckets[k] for k in les]
    assert cum == sorted(cum)
    assert buckets["+Inf"] == cum[-1] == sum(counts)
    # per-bucket counts recover exactly from the cumulative deltas
    recovered = np.diff([0] + cum).tolist()
    assert recovered == counts[:7]
    # _count matches, _sum is the conservative upper-bound estimate
    assert hcount == sum(counts)
    assert hsum == float(
        sum(c * hg.bucket_hi(b) for b, c in enumerate(counts))
    )
    # labels ride every line of the family
    assert 'rt_depth_bucket{le="0",run="t1"} 5' in text
    assert (
        'rt_depth_bucket{le="+Inf",run="t1"} %d' % sum(counts) in text
    )


def test_histogram_sum_override_and_empty():
    from ringpop_tpu.ops import histogram as hg

    w = PromWriter()
    w.histogram("empty", [0] * hg.NBUCKETS)
    w.histogram("known", [2, 1] + [0] * (hg.NBUCKETS - 2), sum_value=1.5)
    text = w.render()
    eb, es, ec, _ = _parse_histogram(text, "empty")
    assert eb == {"0": 0, "+Inf": 0} and es == 0.0 and ec == 0
    kb, ks, kc, _ = _parse_histogram(text, "known")
    assert ks == 1.5 and kc == 3


def test_render_device_histograms_one_family_per_track():
    from ringpop_tpu.ops import histogram as hg

    hist = np.zeros((2, hg.NBUCKETS), np.int64)
    hist[0, 1] = 7
    hist[1, 3] = 2
    text = render_device_histograms(
        hist, ("retry_depth", "reroute_hops"), labels={"run": "x"}
    )
    a, _, ac, at = _parse_histogram(text, "ringpop_sim_retry_depth")
    b, _, bc, bt = _parse_histogram(text, "ringpop_sim_reroute_hops")
    assert at == bt == "histogram"
    assert ac == 7 and bc == 2
    assert a["+Inf"] == 7 and b["+Inf"] == 2


def test_render_slo_plane_exposes_window_and_health():
    from ringpop_tpu.obs import slo as oslo
    from ringpop_tpu.ops import histogram as hg

    plane = oslo.SLOWindowPlane(
        oslo.SLOTarget(name="route", success_objective=0.999),
        window_len=2,
    )
    counts = np.zeros(hg.NBUCKETS, np.int64)
    counts[1] = 100
    plane.observe(1, counts, queries=100, errors=50)  # a breach
    text = render_slo_plane(plane, tick=1)
    buckets, _, hcount, _ = _parse_histogram(text, "ringpop_slo_window")
    assert hcount == 100 and buckets["+Inf"] == 100
    assert 'target="route"' in text
    assert 'ringpop_slo_window_queries{target="route"} 100' in text
    assert 'ringpop_slo_window_errors{target="route"} 50' in text
    assert 'ringpop_slo_breach{target="route"} 1' in text
    assert "# TYPE ringpop_slo_burn_rate gauge" in text
