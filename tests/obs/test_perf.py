"""Host-side phase timing (obs/perf.py): dispatch-timer wrapping,
compile/execute split via the jit-cache probe, perf.phase runlog rows,
and the host-timeline Perfetto track."""

import importlib.util as ilu
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.obs import perf as obs_perf
from ringpop_tpu.obs.chrome_trace import (
    add_host_timeline,
    validate_chrome_trace,
)
from ringpop_tpu.obs.recorder import RunRecorder, read_run_log


def _schema_module():
    spec = ilu.spec_from_file_location(
        "check_metrics_schema",
        os.path.join(
            os.path.dirname(__file__), "..", "..", "scripts",
            "check_metrics_schema.py",
        ),
    )
    mod = ilu.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_wrap_detects_compile_then_cache_hits():
    @jax.jit
    def f(x):
        return x * 2

    timer = obs_perf.DispatchTimer()
    g = timer.wrap("f", f)
    g(jnp.ones(8))  # fresh jit: compile-carrying call
    g(jnp.ones(8))  # same shape: warm
    g(jnp.ones(8))
    st = timer.phases["f"]
    assert st.calls == 3
    assert st.compile_calls == 1
    assert st.cache_hits == 2
    g(jnp.ones(16))  # new shape: a second (budgeted) compile
    assert timer.phases["f"].compile_calls == 2


def test_wrap_fences_outputs_and_preserves_results():
    @jax.jit
    def f(x):
        return x + 1

    timer = obs_perf.DispatchTimer()
    g = timer.wrap("f", f)
    out = g(jnp.arange(4))
    assert (np.asarray(out) == np.arange(4) + 1).all()
    assert timer.phases["f"].total_s > 0


def test_wrap_plain_callable_has_no_cache_probe():
    timer = obs_perf.DispatchTimer()
    g = timer.wrap("host", lambda x: x)
    g(3)
    st = timer.phases["host"]
    # compiled is unknowable: neither a compile call nor a cache hit
    assert st.calls == 1 and st.compile_calls == 0 and st.cache_hits == 0


def test_summary_and_emit_rows_validate_against_schema(tmp_path):
    @jax.jit
    def f(x):
        return jnp.sum(x * x)

    timer = obs_perf.DispatchTimer()
    g = timer.wrap("tick", f)
    for _ in range(5):
        g(jnp.ones(32))
    rows = timer.summary()
    (row,) = rows
    assert row["phase"] == "tick" and row["calls"] == 5
    assert row["warm_calls"] == row["calls"] - row["compile_calls"]
    assert row["p50_ms"] is not None and row["p99_ms"] >= row["p50_ms"]

    path = str(tmp_path / "perf.runlog.jsonl")
    with RunRecorder(path) as rec:
        assert timer.emit(rec) == 1
    assert _schema_module().check([path], verbose=False) == []
    log = read_run_log(path)
    (ev,) = [e for e in log["events"] if e["name"] == "perf.phase"]
    assert ev["phase"] == "tick" and ev["calls"] == 5 and "wall_s" in ev


def test_perf_phase_row_missing_fields_fails_schema(tmp_path):
    path = str(tmp_path / "bad.runlog.jsonl")
    with RunRecorder(path) as rec:
        rec.record_event("perf.phase", phase="tick")  # no wall_s/calls
    assert _schema_module().check([path], verbose=False) != []


def test_host_timeline_merges_into_flight_trace():
    timer = obs_perf.DispatchTimer()
    with timer.phase("scan"):
        pass
    trace = {"traceEvents": []}
    add_host_timeline(trace, timer)
    assert validate_chrome_trace(trace) == []
    names = [e.get("name") for e in trace["traceEvents"]]
    assert "process_name" in names and "scan" in names
    span = [e for e in trace["traceEvents"] if e.get("ph") == "X"][0]
    assert span["dur"] >= 1.0  # schema floor


def test_wrap_cluster_times_without_changing_trajectory():
    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster

    n = 8

    def run(wrapped):
        c = SimCluster(n=n, params=engine.SimParams(n=n), seed=5)
        timer = obs_perf.wrap_cluster(c) if wrapped else None
        c.bootstrap()
        c.run(EventSchedule(ticks=6, n=n))
        return c, timer

    a, _ = run(False)
    b, timer = run(True)
    for f in engine.SimState._fields:
        va, vb = getattr(a.state, f), getattr(b.state, f)
        if va is None and vb is None:
            continue
        assert np.array_equal(np.asarray(va), np.asarray(vb)), f
    assert timer.phases["tick"].calls >= 1  # bootstrap step
    assert timer.phases["scan"].calls == 1
    # idempotent: re-wrapping must not double-wrap, and re-instrumenting
    # WITHOUT an explicit timer returns the ORIGINAL bound timer (the
    # one the dispatches flow into), never a fresh disconnected one
    timer2 = obs_perf.wrap_cluster(b)
    assert timer2 is timer
    obs_perf.wrap_cluster(b, timer)
    assert b._tick.__name__ == "timed_tick"
    assert not getattr(b._tick.__wrapped__, "__perf_timed__", False)


def test_wrap_cluster_sharded_storm_fallback():
    """ShardedStorm dispatches through structure-keyed module caches,
    not instance handles — wrap_cluster falls back to timing its public
    step/run under the same phase names."""
    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.models.sim.storm import StormSchedule
    from ringpop_tpu.parallel import mesh as pmesh

    storm = pmesh.ShardedStorm(
        n=16,
        mesh=pmesh.make_mesh(1),
        params=es.ScalableParams(n=16, u=128),
        seed=0,
    )
    timer = obs_perf.wrap_cluster(storm)
    storm.step()
    storm.run(StormSchedule(ticks=3, n=16))
    assert timer.phases["tick"].calls == 1
    assert timer.phases["scan"].calls == 1


def test_timed_window_warms_measures_and_stamps_row(tmp_path):
    calls = []

    @jax.jit
    def f(x):
        return x * 3

    def run():
        calls.append(1)
        return f(jnp.ones(4))

    path = str(tmp_path / "w.runlog.jsonl")
    with RunRecorder(path) as rec:
        out, wall = obs_perf.timed_window(
            run, warmup=2, repeats=3, recorder=rec, phase="bench", n=4
        )
    assert len(calls) == 5  # 2 warm + 3 measured
    assert wall > 0 and (np.asarray(out) == 3).all()
    log = read_run_log(path)
    (ev,) = [e for e in log["events"] if e["name"] == "perf.phase"]
    assert ev["calls"] == 3 and ev["n"] == 4
    assert _schema_module().check([path], verbose=False) == []


def test_protocol_delay_consumer_reads_phase_histogram():
    timer = obs_perf.DispatchTimer()
    # no samples: the reference floor
    assert timer.protocol_delay_ms() == 200.0
    st = timer._stats("tick")
    for _ in range(32):
        st.observe(0.4, compiled=False)  # 400 ms warm dispatches
    assert timer.protocol_delay_ms() > 200.0


def test_percentiles_exact_nearest_rank():
    walls = [0.001 * k for k in range(1, 101)]
    out = obs_perf.percentiles_exact(walls)
    assert out["p50_ms"] == pytest.approx(50.0)
    assert out["p99_ms"] == pytest.approx(99.0)
    assert obs_perf.percentiles_exact([])["p50_ms"] is None
