"""Sliding-window SLO plane: windowed percentiles pinned against a
host-numpy nearest-rank oracle (the ISSUE 19 acceptance), burn-rate
math, breach fire/clear/eviction, schema-valid rows, and the
backpressure consumer hook."""

from __future__ import annotations

import importlib.util
import os

import numpy as np
import pytest

from ringpop_tpu.obs import slo as oslo
from ringpop_tpu.ops import histogram as hg

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _counts_of(samples):
    counts = np.zeros(hg.NBUCKETS, np.int64)
    np.add.at(counts, hg.bucket_index_np(samples), 1)
    return counts


def _oracle(pooled_samples, q):
    """Nearest-rank percentile of the RAW samples, reported as its log2
    bucket's upper bound — what a bucketed histogram must answer."""
    arr = np.sort(np.asarray(pooled_samples))
    rank = max(1, int(np.ceil(q / 100.0 * arr.size)))
    return hg.bucket_hi(int(hg.bucket_index_np(arr[rank - 1 : rank])[0]))


def test_windowed_percentiles_match_numpy_oracle():
    """The acceptance pin: after every observe(), each sliding-window
    percentile equals the nearest-rank percentile of the pooled RAW
    observations of the held windows (bucketing is monotone, so the
    bucket of the nearest-rank raw sample IS the nearest-rank bucket)."""
    rng = np.random.default_rng(5)
    plane = oslo.SLOWindowPlane(window_len=3)
    held = []
    for w in range(7):
        # heavy-tailed raw latencies, a different scale each window
        samples = rng.integers(0, 1 << (3 + 2 * (w % 4)), size=500)
        held.append(samples)
        held = held[-3:]
        row = plane.observe(w, _counts_of(samples), queries=500, errors=0)
        pooled = np.concatenate(held)
        for q in oslo.WINDOW_QS:
            assert row["p%d" % q] == _oracle(pooled, q), (w, q)
        assert row["windows"] == len(held)


def test_ring_eviction_and_pooling():
    plane = oslo.SLOWindowPlane(window_len=2)
    a, b, c = (np.zeros(hg.NBUCKETS, np.int64) for _ in range(3))
    a[1], b[2], c[3] = 10, 20, 30
    plane.observe(1, a, queries=10, errors=1, ticks=4)
    plane.observe(2, b, queries=20, errors=2, ticks=4)
    row = plane.observe(3, c, queries=30, errors=3, ticks=4)
    # window a evicted: only b+c pooled
    want = b + c
    np.testing.assert_array_equal(plane.window_counts(), want)
    assert row["windows"] == 2
    assert row["window_ticks"] == 8
    assert row["queries"] == 50 and row["errors"] == 5


def test_empty_window_percentiles_are_none():
    plane = oslo.SLOWindowPlane()
    row = plane.observe(0, np.zeros(hg.NBUCKETS), queries=0, errors=0)
    assert row["p50"] is None and row["p99"] is None
    assert row["success_rate"] == 1.0 and row["burn_rate"] == 0.0
    assert not row["breach"]


def test_burn_rate_math():
    assert oslo.burn_rate(0, 0, 0.999) == 0.0
    assert oslo.burn_rate(0, 1000, 0.999) == 0.0
    assert oslo.burn_rate(5, 0, 0.999) == 0.0  # no queries, no burn
    # 1 error / 1000 queries against a 0.1% budget burns at exactly 1x
    assert oslo.burn_rate(1, 1000, 0.999) == pytest.approx(1.0)
    assert oslo.burn_rate(2, 1000, 0.999) == pytest.approx(2.0)
    # a 100% objective has zero budget: any error burns at +inf
    assert oslo.burn_rate(1, 10, 1.0) == float("inf")


def test_breach_fires_and_clears():
    plane = oslo.SLOWindowPlane(
        oslo.SLOTarget(
            name="route", success_objective=0.999, burn_alert=2.0
        ),
        window_len=2,
    )
    zero = np.zeros(hg.NBUCKETS)
    clean = plane.observe(1, zero, queries=1000, errors=0)
    assert not clean["breach"] and plane.breaches == 0
    burst = plane.observe(2, zero, queries=1000, errors=50)
    assert burst["breach"]
    # the burst violates both the objective and the fast-burn alert
    assert burst["breach_reason"] == "success-rate,burn-rate"
    assert burst["burn_rate"] == pytest.approx((50 / 2000) / 0.001)
    assert plane.breaches == 1
    # one clean window still holds the burst (sliding!), two evict it
    assert plane.observe(3, zero, queries=1000, errors=0)["breach"]
    cleared = plane.observe(4, zero, queries=1000, errors=0)
    assert not cleared["breach"] and cleared["breach_reason"] == ""
    assert plane.breaches == 2


def test_p99_ceiling_breach():
    counts = np.zeros(hg.NBUCKETS, np.int64)
    counts[6] = 100  # every observation in [32, 63]
    plane = oslo.SLOWindowPlane(
        oslo.SLOTarget(p99_max=31, burn_alert=2.0), window_len=1
    )
    row = plane.observe(1, counts, queries=100, errors=0)
    assert row["p99"] == 63
    assert row["breach"] and row["breach_reason"] == "p99"
    # a roomier ceiling clears it
    ok = oslo.SLOWindowPlane(
        oslo.SLOTarget(p99_max=63), window_len=1
    ).observe(1, counts, queries=100, errors=0)
    assert not ok["breach"]


def test_validation():
    with pytest.raises(ValueError):
        oslo.SLOWindowPlane(window_len=0)
    plane = oslo.SLOWindowPlane()
    with pytest.raises(ValueError):
        plane.observe(0, np.zeros(3), queries=1, errors=0)
    with pytest.raises(ValueError):
        oslo.SLOBackpressure(max_factor=0.5)


def test_backpressure_consumer_hook():
    bp = oslo.SLOBackpressure(base_period_ms=200.0, max_factor=8.0)
    plane = oslo.SLOWindowPlane(
        oslo.SLOTarget(success_objective=0.999, burn_alert=2.0),
        window_len=1,
        consumer=bp,
    )
    zero = np.zeros(hg.NBUCKETS)
    plane.observe(1, zero, queries=1000, errors=0)
    assert bp.factor() == 1.0 and bp.period_ms() == 200.0
    # burn 5x -> period stretches 5x
    plane.observe(2, zero, queries=1000, errors=5)
    assert bp.factor() == pytest.approx(5.0)
    assert bp.period_ms() == pytest.approx(1000.0)
    # a catastrophic burn clamps at max_factor
    plane.observe(3, zero, queries=1000, errors=500)
    assert bp.factor() == 8.0
    # the window clearing snaps back to base
    plane.observe(4, zero, queries=1000, errors=0)
    assert bp.factor() == 1.0 and bp.period_ms() == 200.0


def test_observe_route_window_feeds_from_drained_telemetry():
    """The routing-plane feeder: one drained histogram window + the
    window's RouteMetrics stack become (counts, queries, errors) with
    the requestProxy failure surface as errors."""
    from ringpop_tpu.models.route.plane import (
        ROUTE_HIST_TRACKS,
        RoutedStorm,
        RouteParams,
    )
    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.models.sim.storm import StormSchedule

    n = 32
    rs = RoutedStorm(
        n,
        params=es.ScalableParams(n=n, u=192, suspicion_ticks=4),
        route=RouteParams(
            n=n, queries_per_tick=256, key_space=1024, histograms=True
        ),
        seed=2,
    )
    _, rm = rs.run(
        StormSchedule.churn_storm(8, n, fraction=0.2, seed=2)
    )
    hist = np.asarray(rs.rstate.hist)
    plane = oslo.SLOWindowPlane(window_len=4)
    row = plane.observe_route_window(8, hist, rm)
    assert row["window_ticks"] == 8
    assert row["queries"] == int(np.asarray(rm.route_queries).sum())
    want_errors = int(
        np.asarray(rm.route_misroutes).sum()
        + np.asarray(rm.route_checksum_rejects).sum()
        + np.asarray(rm.route_keys_diverged).sum()
    )
    assert row["errors"] == want_errors
    # the pooled window IS the drained retry_depth track
    np.testing.assert_array_equal(
        plane.window_counts(),
        hist[ROUTE_HIST_TRACKS.index("retry_depth")].astype(np.int64),
    )
    # retry_depth p-values come from buckets {0,1}: hi in {0,1}
    assert row["p50"] in (0, 1)


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_metrics_schema",
        os.path.join(REPO_ROOT, "scripts", "check_metrics_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_slo_rows_ride_a_schema_valid_runlog(tmp_path):
    """Every observe() emits one slo.window row — and a breach one
    slo.breach row — that the repo's schema gate accepts."""
    from ringpop_tpu.obs.recorder import RunRecorder, read_run_log

    path = str(tmp_path / "slo.runlog.jsonl")
    rec = RunRecorder(path, run_id="t", config={})
    plane = oslo.SLOWindowPlane(
        oslo.SLOTarget(success_objective=0.999, burn_alert=2.0),
        window_len=2,
        recorder=rec,
    )
    counts = np.zeros(hg.NBUCKETS, np.int64)
    counts[2] = 100
    plane.observe(1, counts, queries=1000, errors=0)
    plane.observe(2, counts, queries=1000, errors=100)
    rec.finish()
    events = read_run_log(path)["events"]
    assert [e["name"] for e in events] == [
        "slo.window",
        "slo.window",
        "slo.breach",
    ]
    breach = events[-1]
    assert breach["reason"] == "success-rate,burn-rate"
    assert breach["p99"] == 3  # bucket 2 hi
    checker = _load_checker()
    assert checker.check([path], verbose=False) == []
