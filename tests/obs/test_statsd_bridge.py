"""StatsdBridge: device counters land on the reference's statsd key
scheme — ``ringpop.<host_port with . and : -> _>.<key>`` (index.js:162-164,
527-541) — whether routed through a live facade's ``stat()`` or the
standalone prefix replica."""

from __future__ import annotations

import numpy as np
import pytest

from ringpop_tpu.api.ringpop import Ringpop
from ringpop_tpu.models.sim import engine
from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster
from ringpop_tpu.net.timers import FakeTimers
from ringpop_tpu.obs.statsd_bridge import TICK_KEY_MAP, StatsdBridge, stat_prefix
from ringpop_tpu.utils.stats import CapturingStatsd


def test_prefix_matches_facade_scheme():
    """The standalone prefix must be byte-identical to what
    Ringpop.__init__ computes for the same host_port."""
    statsd = CapturingStatsd()
    rp = Ringpop(
        "bridge-app",
        "10.0.0.7:3001",
        statsd=statsd,
        timers=FakeTimers(),
    )
    assert stat_prefix("10.0.0.7:3001") == rp.stat_prefix
    assert rp.stat_prefix == "ringpop.10_0_0_7_3001"


def test_emit_through_ringpop_stat_uses_fq_cache():
    statsd = CapturingStatsd()
    rp = Ringpop(
        "bridge-app",
        "127.0.0.1:3000",
        statsd=statsd,
        timers=FakeTimers(),
    )
    bridge = StatsdBridge(ringpop=rp)
    statsd.records.clear()  # drop constructor-era emissions
    bridge.emit_tick(
        {
            "pings_sent": 12,
            "ping_reqs": 3,
            "refutes": 1,
            "distinct_checksums": 4,
            "converged": False,  # unmapped: ignored
        }
    )
    keys = {r[1] for r in statsd.records}
    assert keys == {
        "ringpop.127_0_0_1_3000.ping.send",
        "ringpop.127_0_0_1_3000.ping-req.send",
        "ringpop.127_0_0_1_3000.refuted-update",
        "ringpop.127_0_0_1_3000.checksums.distinct",
    }
    # the facade's fq-key cache saw the bridge's keys (index.js:527-541)
    assert "ping.send" in rp.stat_keys


def test_emit_series_from_engine_run_matches_reference_scheme():
    """A real engine window through the standalone bridge: every
    emission carries the ringpop.<host_port>. prefix, increments are
    emitted only when nonzero, and window sums agree with the metrics."""
    sim = SimCluster(
        n=16, params=engine.SimParams(n=16, checksum_mode="fast")
    )
    sim.bootstrap()
    m = sim.run(EventSchedule(ticks=12, n=16))

    cap = CapturingStatsd()
    bridge = StatsdBridge(statsd=cap, host_port="127.0.0.1:4040")
    assert bridge.emit_series(m) > 0
    prefix = "ringpop.127_0_0_1_4040."
    assert cap.records  # something was emitted
    assert all(r[1].startswith(prefix) for r in cap.records)
    sent = sum(
        r[2]
        for r in cap.records
        if r[0] == "increment" and r[1] == prefix + "ping.send"
    )
    assert sent == int(np.asarray(m.pings_sent).sum())
    # gauges re-emit every tick
    gauges = [r for r in cap.records if r[0] == "gauge"]
    assert len([g for g in gauges if g[1] == prefix + "checksums.distinct"]) == 12


def test_emit_series_handles_vmapped_batch_axis():
    """Regression: [T, B] metrics from the batched driver must not
    crash — counter vectors aggregate (sum across clusters), gauge
    vectors are skipped (no single-key meaning)."""
    cap = CapturingStatsd()
    bridge = StatsdBridge(statsd=cap, host_port="127.0.0.1:4050")
    series = {
        "pings_sent": np.asarray([[3, 4], [5, 6]]),  # [T=2, B=2]
        "distinct_checksums": np.asarray([[2, 2], [1, 1]]),  # gauge
    }
    assert bridge.emit_series(series) == 2
    prefix = "ringpop.127_0_0_1_4050."
    sends = [r for r in cap.records if r[1] == prefix + "ping.send"]
    assert [r[2] for r in sends] == [7, 11]  # per-tick cross-cluster sums
    assert not any("checksums.distinct" in r[1] for r in cap.records)


def test_bridge_requires_a_sink():
    with pytest.raises(ValueError):
        StatsdBridge()
    with pytest.raises(ValueError):
        StatsdBridge(statsd=CapturingStatsd())  # host_port missing


def test_exchange_key_map_stays_in_lockstep_with_exchange_metrics():
    """Round-17 mesh observatory keys: every ExchangeMetrics counter
    (minus the shard id) maps to an increment, the shard count to a
    gauge, and every cap-utilization track to a timer key — drift in
    either direction (a renamed counter, a forgotten key) fails here."""
    from ringpop_tpu.obs.statsd_bridge import (
        EXCHANGE_HIST_KEYS,
        EXCHANGE_KEY_MAP,
        XPROF_KEY_MAP,
    )
    from ringpop_tpu.ops.exchange import EXCH_HIST_TRACKS, ExchangeMetrics

    counters = set(ExchangeMetrics._fields) - {"shard"}
    assert set(EXCHANGE_KEY_MAP) == counters | {"shards"}
    for f in counters:
        assert EXCHANGE_KEY_MAP[f][0] == "increment", f
    assert EXCHANGE_KEY_MAP["shards"][0] == "gauge"
    assert set(EXCHANGE_HIST_KEYS) == set(EXCH_HIST_TRACKS)
    # xprof: capture wall time is a TIMER (|ms), op count a gauge
    assert XPROF_KEY_MAP["wall_s"][0] == "timing"
    assert XPROF_KEY_MAP["ops"][0] == "gauge"


def test_emit_exchange_drain_wire_types():
    """Counters emit as nonzero-only increments, the shard count always
    as a gauge, all under the fq-key scheme."""
    cap = CapturingStatsd()
    bridge = StatsdBridge(statsd=cap, host_port="127.0.0.1:4060")
    tot = {
        "shards": 4,
        "ticks": 8,
        "a2a_pull": 8,
        "a2a_push": 8,
        "fallback_pull": 0,  # zero counter: suppressed
        "fallback_push": 0,
        "pull_rows": 100,
        "push_rows": 100,
        "dest_shards_pull": 30,
        "dest_shards_push": 31,
        "wire_bytes_pull": 1024,
        "wire_bytes_push": 1024,
        "not_a_counter": 7,  # unmapped: ignored
    }
    emitted = bridge.emit_exchange_drain(tot)
    prefix = "ringpop.127_0_0_1_4060."
    incs = {r[1]: r[2] for r in cap.records if r[0] == "increment"}
    assert incs[prefix + "sharded.exchange.wire-bytes.pull"] == 1024
    assert incs[prefix + "sharded.exchange.spread.push"] == 31
    assert not any("fallback" in r[1] for r in cap.records)
    assert not any("not_a_counter" in r[1] for r in cap.records)
    gauges = [r for r in cap.records if r[0] == "gauge"]
    assert gauges == [("gauge", prefix + "sharded.exchange.shards", 4)]
    assert emitted == len(cap.records)


def test_exchange_hist_summary_emits_timer_quantiles():
    from ringpop_tpu.obs.statsd_bridge import EXCHANGE_HIST_KEYS

    cap = CapturingStatsd()
    bridge = StatsdBridge(statsd=cap, host_port="127.0.0.1:4061")
    summary = {
        "cap_util_pull": {"count": 3, "p50": 2.0, "p95": 4.0, "p99": None},
        "cap_util_push": {"count": 0, "p50": None, "p95": None, "p99": None},
    }
    assert bridge.emit_hist_summary(summary, key_map=EXCHANGE_HIST_KEYS) == 2
    prefix = "ringpop.127_0_0_1_4061."
    assert cap.records == [
        ("timing", prefix + "sharded.exchange.cap-util.pull.p50", 2.0),
        ("timing", prefix + "sharded.exchange.cap-util.pull.p95", 4.0),
    ]


def test_xprof_emit_wire_types():
    """obs.xprof stamps capture wall time as a |ms timer and the
    attributed-op count as a gauge through the bridge's public seams."""
    from ringpop_tpu.obs import xprof

    cap = CapturingStatsd()
    bridge = StatsdBridge(statsd=cap, host_port="127.0.0.1:4070")
    row = {"phase": "p", "ok": True, "wall_s": 0.25, "ops": [{"name": "x"}]}
    xprof._emit(row, None, bridge)
    prefix = "ringpop.127_0_0_1_4070."
    assert ("timing", prefix + "xprof.capture", 250.0) in cap.records
    assert ("gauge", prefix + "xprof.ops", 1) in cap.records
    # a failed capture (no wall clock) still reports the zero op count
    cap.records.clear()
    xprof._emit({"phase": "p", "ok": False, "wall_s": None}, None, bridge)
    assert cap.records == [("gauge", prefix + "xprof.ops", 0)]


def test_key_map_covers_both_engines():
    from ringpop_tpu.models.sim.engine import TickMetrics
    from ringpop_tpu.models.sim.engine_scalable import ScalableMetrics

    unmapped_ok = {"converged", "full_coverage"}  # booleans, no stat
    for fields in (TickMetrics._fields, ScalableMetrics._fields):
        for f in fields:
            assert f in TICK_KEY_MAP or f in unmapped_ok, f


def test_reqtrace_key_map_stays_in_lockstep_with_count_fields():
    """ISSUE 19 keys: every sampled-subset counter (obs.requests
    .COUNT_FIELDS) maps to an increment under sim.reqtrace.sampled.*,
    record/drop volume to increments, the sampling rate to a gauge —
    drift in either direction fails here."""
    from ringpop_tpu.obs import requests as oreq
    from ringpop_tpu.obs.statsd_bridge import REQTRACE_KEY_MAP

    assert set(REQTRACE_KEY_MAP) == set(oreq.COUNT_FIELDS) | {
        "records",
        "drops",
        "sample_log2",
    }
    for f in oreq.COUNT_FIELDS:
        stat_type, key = REQTRACE_KEY_MAP[f]
        assert stat_type == "increment", f
        assert key.startswith("sim.reqtrace.sampled."), f
    assert REQTRACE_KEY_MAP["records"][0] == "increment"
    assert REQTRACE_KEY_MAP["drops"][0] == "increment"
    assert REQTRACE_KEY_MAP["sample_log2"][0] == "gauge"


def test_emit_reqtrace_drain_wire_types():
    """Zero counters are suppressed (statsd increments are deltas), the
    sampling-rate gauge always emits, and the nested counts object is
    flattened onto the key map."""
    from ringpop_tpu.obs import requests as oreq

    cap = CapturingStatsd()
    bridge = StatsdBridge(statsd=cap, host_port="127.0.0.1:4080")
    row = oreq.drain_row(
        "route",
        records=42,
        drops=0,  # zero counter: suppressed
        cap=1280,  # unmapped: ignored
        sample_log2=2,
        counts={
            "queries": 42,
            "misroutes": 5,
            "reroute_local": 0,
            "reroute_remote": 5,
            "keys_diverged": 0,
            "checksums_differ": 1,
            "checksum_rejects": 1,
        },
    )
    emitted = bridge.emit_reqtrace_drain(row)
    prefix = "ringpop.127_0_0_1_4080."
    incs = {r[1]: r[2] for r in cap.records if r[0] == "increment"}
    assert incs[prefix + "sim.reqtrace.records"] == 42
    assert incs[prefix + "sim.reqtrace.sampled.queries"] == 42
    assert incs[prefix + "sim.reqtrace.sampled.reroute.remote"] == 5
    assert not any("drops" in r[1] for r in cap.records)
    assert not any("reroute.local" in r[1] for r in cap.records)
    assert not any(".cap" in r[1] for r in cap.records)
    gauges = [r for r in cap.records if r[0] == "gauge"]
    assert gauges == [
        ("gauge", prefix + "sim.reqtrace.sample-log2", 2)
    ]
    assert emitted == len(cap.records)


def test_slo_key_map_and_emit_wire_types():
    """slo.window rows emit under slo.<target>.*: windowed percentiles
    as |ms TIMER samples (None = empty window = skipped), health ratios
    as gauges, window volume as nonzero-only increments; a breach ticks
    slo.<target>.breach."""
    from ringpop_tpu.obs import slo as oslo
    from ringpop_tpu.obs.statsd_bridge import SLO_KEY_MAP

    for q in oslo.WINDOW_QS:
        assert SLO_KEY_MAP["p%d" % q][0] == "timing"
    assert SLO_KEY_MAP["success_rate"] == ("gauge", "success-rate")
    assert SLO_KEY_MAP["burn_rate"] == ("gauge", "burn-rate")
    assert SLO_KEY_MAP["queries"][0] == "increment"
    assert SLO_KEY_MAP["errors"][0] == "increment"

    cap = CapturingStatsd()
    bridge = StatsdBridge(statsd=cap, host_port="127.0.0.1:4081")
    row = {
        "target": "route",
        "p50": 0,
        "p95": 1,
        "p99": None,  # empty-window percentile: skipped
        "success_rate": 0.99,
        "burn_rate": 10.0,
        "queries": 1000,
        "errors": 0,  # zero counter: suppressed
        "breach": True,  # unmapped: rides emit_slo_breach
    }
    bridge.emit_slo_window(row)
    bridge.emit_slo_breach("route")
    prefix = "ringpop.127_0_0_1_4081.slo.route."
    assert ("timing", prefix + "p50", 0) in cap.records
    assert ("timing", prefix + "p95", 1) in cap.records
    assert not any(r[1].endswith(".p99") for r in cap.records)
    assert ("gauge", prefix + "burn-rate", 10.0) in cap.records
    incs = {r[1]: r[2] for r in cap.records if r[0] == "increment"}
    assert incs == {
        prefix + "window.queries": 1000,
        prefix + "breach": 1,
    }
