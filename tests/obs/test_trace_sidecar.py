"""RunRecorder trace sidecars + the extended schema gate
(scripts/check_metrics_schema.py must validate runlogs AND sidecars AND
the links between them)."""

from __future__ import annotations

import importlib.util
import json
import os

import numpy as np

from ringpop_tpu.obs import chrome_trace as ct
from ringpop_tpu.obs import events as ev
from ringpop_tpu.obs.recorder import RunRecorder, validate_run_log

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_metrics_schema",
        os.path.join(REPO_ROOT, "scripts", "check_metrics_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _trace():
    rows = [
        [1, ev.EV_PING, 0, 1, -1, -1, 0, 1],
        [2, ev.EV_STATUS, 1, 2, 0, 1, 3, 1],
        [3, ev.EV_STATUS, 0, 2, 0, 1, 3, 1],
    ]
    events = ev.decode_events(np.asarray(rows, np.int32), len(rows))
    return ct.export_chrome_trace(events, n=3, period_ms=200)


def test_sidecar_written_linked_and_validated(tmp_path):
    log = str(tmp_path / "run.runlog.jsonl")
    rec = RunRecorder(log, config={"n": 3})
    rec.record_tick({"pings_sent": 3})
    sidecar = rec.record_trace_sidecar(_trace(), name="flight")
    rec.finish()
    assert os.path.basename(sidecar) == "run.flight.trace.json"
    assert validate_run_log(log) == []
    with open(sidecar, encoding="utf-8") as fh:
        assert ct.validate_chrome_trace(json.load(fh)) == []
    # the runlog's trace_sidecar event row points at the file
    with open(log, encoding="utf-8") as fh:
        rows = [json.loads(line) for line in fh if line.strip()]
    links = [
        r
        for r in rows
        if r.get("kind") == "event" and r.get("name") == "trace_sidecar"
    ]
    assert len(links) == 1
    assert links[0]["path"] == os.path.basename(sidecar)

    checker = _load_checker()
    assert checker.check([log, sidecar], verbose=False) == []


def test_checker_catches_broken_sidecar_and_missing_link(tmp_path):
    checker = _load_checker()
    bad = tmp_path / "broken.trace.json"
    bad.write_text('{"traceEvents": [{"ph": "Z"}]}')
    assert checker.check([str(bad)], verbose=False) != []
    not_json = tmp_path / "nope.trace.json"
    not_json.write_text("{")
    assert any(
        "not JSON" in p
        for p in checker.check([str(not_json)], verbose=False)
    )
    # a runlog whose sidecar link points at a missing file fails the gate
    log = str(tmp_path / "orphan.runlog.jsonl")
    rec = RunRecorder(log, config={})
    rec.record_event("trace_sidecar", sidecar="flight", path="gone.trace.json")
    rec.finish()
    assert any(
        "missing file" in p for p in checker.check([log], verbose=False)
    )


def test_repo_committed_sidecars_validate():
    """The tier-1 twin of the standalone gate: every committed sidecar
    under the repo validates, and the committed flight sample exists so
    the gate is never vacuous."""
    checker = _load_checker()
    sidecars = checker.find_trace_sidecars()
    assert any(
        os.path.basename(p).startswith("sample_") for p in sidecars
    ), "committed sample trace sidecar missing (runlogs/sample_*.trace.json)"
    problems = checker.check(sidecars, verbose=False)
    assert problems == [], "\n".join(problems)
