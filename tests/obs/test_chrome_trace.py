"""Chrome-trace exporter + validator unit tests (pure host side)."""

from __future__ import annotations

import json

import numpy as np
import pytest

from ringpop_tpu.obs import chrome_trace as ct
from ringpop_tpu.obs import events as ev


def _events():
    rows = [
        # tick 1: node 0 pings 1 (delivered)
        [1, ev.EV_PING, 0, 1, -1, -1, 0, 1],
        # tick 2: node 1 adopts suspicion about node 2 (rumor birth),
        # node 0 marks the verdict
        [2, ev.EV_SUSPECT, 1, 2, 0, 1, 3, 0],
        [2, ev.EV_STATUS, 1, 2, 0, 1, 3, 4],
        # node 2's own story: it sees itself suspect on tick 3, refutes
        # on tick 4
        [3, ev.EV_STATUS, 2, 2, 0, 1, 3, 1],
        [4, ev.EV_REFUTE, 2, 2, 1, 0, 5, 1],
        # the rumor spreads to node 0 on tick 4
        [4, ev.EV_STATUS, 0, 2, 0, 1, 3, 1],
        # a join and a full sync for instant coverage
        [5, ev.EV_JOIN, 3, -1, -1, -1, 0, 2],
        [5, ev.EV_FULL_SYNC, 0, 3, -1, -1, 0, 4],
    ]
    buf = np.asarray(rows, np.int32)
    return ev.decode_events(buf, len(rows))


def test_export_parses_and_validates():
    trace = ct.export_chrome_trace(_events(), n=4, period_ms=200)
    # round-trips through JSON (the artifact form)
    blob = json.dumps(trace)
    assert ct.validate_chrome_trace(blob) == []
    assert ct.validate_chrome_trace(trace) == []
    evs = trace["traceEvents"]
    # one process_name + one thread per node
    assert sum(1 for e in evs if e["ph"] == "M") == 5
    # node 2's self story renders alive -> suspect -> alive spans
    spans = [
        e["name"] for e in evs if e["ph"] == "X" and e["tid"] == 2
    ]
    assert spans == ["alive", "suspect", "alive"]
    # the suspect rumor about node 2 flows from its origin to observers
    flows = [e for e in evs if e["ph"] in ("s", "t")]
    assert any(e["ph"] == "s" for e in flows)
    assert any(e["ph"] == "t" for e in flows)
    # instants carry the protocol plane
    names = {e["name"] for e in evs if e["ph"] == "i"}
    assert any(x.startswith("suspect") for x in names)
    assert any(x.startswith("join") for x in names)
    # pings are opt-in
    assert not any(x.startswith("ping") for x in names)
    with_pings = ct.export_chrome_trace(
        _events(), n=4, period_ms=200, include_pings=True
    )
    names2 = {
        e["name"] for e in with_pings["traceEvents"] if e["ph"] == "i"
    }
    assert any(x.startswith("ping") for x in names2)


def test_timestamps_scale_with_period():
    t200 = ct.export_chrome_trace(_events(), n=4, period_ms=200)
    t500 = ct.export_chrome_trace(_events(), n=4, period_ms=500)
    x200 = [e for e in t200["traceEvents"] if e["ph"] == "i"][0]
    x500 = [e for e in t500["traceEvents"] if e["ph"] == "i"][0]
    assert x500["ts"] * 200 == x200["ts"] * 500


def test_addresses_label_tracks():
    addrs = ["10.0.0.%d:3000" % i for i in range(4)]
    trace = ct.export_chrome_trace(
        _events(), n=4, period_ms=200, addresses=addrs
    )
    labels = {
        e["args"]["name"]
        for e in trace["traceEvents"]
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert labels == set(addrs)


def test_validator_catches_broken_traces():
    assert ct.validate_chrome_trace("{not json") != []
    assert ct.validate_chrome_trace(42) != []
    assert ct.validate_chrome_trace({"nope": []}) != []
    bad_phase = {"traceEvents": [{"ph": "Z", "pid": 1, "tid": 0, "ts": 0}]}
    assert any("unknown phase" in p for p in ct.validate_chrome_trace(bad_phase))
    bad_span = {
        "traceEvents": [
            {"ph": "X", "pid": 1, "tid": 0, "ts": 0, "name": "x"}
        ]
    }
    assert any("dur" in p for p in ct.validate_chrome_trace(bad_span))
    orphan_flow = {
        "traceEvents": [
            {"ph": "t", "pid": 1, "tid": 0, "ts": 0, "id": 9, "name": "r"}
        ]
    }
    assert any(
        "no start" in p for p in ct.validate_chrome_trace(orphan_flow)
    )


def test_write_refuses_invalid(tmp_path):
    with pytest.raises(ValueError):
        ct.write_chrome_trace(
            {"traceEvents": [{"ph": "Z", "pid": 0, "tid": 0, "ts": 0}]},
            str(tmp_path / "bad.trace.json"),
        )
    good = ct.export_chrome_trace(_events(), n=4)
    path = ct.write_chrome_trace(good, str(tmp_path / "ok.trace.json"))
    with open(path, encoding="utf-8") as fh:
        assert ct.validate_chrome_trace(json.load(fh)) == []
