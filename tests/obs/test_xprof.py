"""Profiler trace harness (obs.xprof): Chrome-trace digestion, the
fuzzy COST_BUDGET keying, and a live capture round trip whose runlog
row validates against the metrics schema."""

from __future__ import annotations

import gzip
import importlib.util
import json
import os

from ringpop_tpu.obs import xprof

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _write_trace(path, events, bare=False):
    doc = events if bare else {"traceEvents": events}
    raw = json.dumps(doc).encode()
    if str(path).endswith(".gz"):
        with gzip.open(path, "wb") as fh:
            fh.write(raw)
    else:
        with open(path, "wb") as fh:
            fh.write(raw)


EVENTS = [
    {"ph": "X", "name": "fusion.exchange", "dur": 40.0, "ts": 0},
    {"ph": "X", "name": "fusion.exchange", "dur": 10.0, "ts": 1},
    {"ph": "X", "name": "all-to-all", "dur": 30.0, "ts": 2},
    {"ph": "X", "name": "copy", "dur": 5.0, "ts": 3},
    {"ph": "X", "name": "zero-dur-marker", "dur": 0, "ts": 4},  # dropped
    {"ph": "M", "name": "process_name", "args": {}},  # metadata: dropped
]


def test_load_trace_events_gzip_and_bare_list(tmp_path):
    gz = tmp_path / "plugins" / "profile" / "run1" / "t.trace.json.gz"
    gz.parent.mkdir(parents=True)
    _write_trace(gz, EVENTS)
    assert xprof.load_trace_events(str(gz)) == EVENTS
    plain = tmp_path / "bare.trace.json"
    _write_trace(plain, EVENTS, bare=True)
    assert xprof.load_trace_events(str(plain)) == EVENTS
    # discovery finds the gz under the profiler's nested layout
    assert xprof.find_trace_files(str(tmp_path)) == [str(gz)]


def test_op_table_aggregates_and_ranks():
    ops, total = xprof.op_table(EVENTS, top_k=2)
    assert total == 85.0
    assert [o["name"] for o in ops] == ["fusion.exchange", "all-to-all"]
    assert ops[0]["self_us"] == 50.0 and ops[0]["count"] == 2


def test_match_budget_entry_token_overlap():
    entries = ["exchange-plane", "engine-scalable-tick"]
    assert (
        xprof.match_budget_entry("fusion.exchange_plane.1", entries)
        == "exchange-plane"
    )
    assert (
        xprof.match_budget_entry("scalable_tick_scan", entries)
        == "engine-scalable-tick"
    )
    assert xprof.match_budget_entry("copy.42", entries) is None
    assert xprof.match_budget_entry("anything", None) is None


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_metrics_schema",
        os.path.join(REPO_ROOT, "scripts", "check_metrics_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_capture_round_trip_stamps_schema_valid_row(tmp_path):
    import jax
    import jax.numpy as jnp

    from ringpop_tpu.obs.recorder import RunRecorder

    x = jnp.arange(1024, dtype=jnp.float32)
    run = jax.jit(lambda: jnp.sum(x * x))
    path = str(tmp_path / "xprof.runlog.jsonl")
    with RunRecorder(path, config={}) as rec:
        row = xprof.capture(
            run,
            str(tmp_path / "trace"),
            phase="unit",
            warmup=1,
            repeats=1,
            recorder=rec,
        )
    assert row["ok"], row.get("error")
    assert row["num_trace_files"] >= 1
    assert row["wall_s"] is not None
    assert row["total_self_us"] > 0
    assert row["ops"], "no ops attributed"
    problems = _load_checker().check([path], verbose=False)
    assert problems == [], "\n".join(problems)
    # the console rendering carries the headline + every op line
    text = xprof.render_table(row)
    assert "xprof[unit]" in text and row["ops"][0]["name"][:40] in text


def test_capture_failure_is_a_row_not_an_exception(tmp_path, monkeypatch):
    import jax
    import jax.numpy as jnp

    run = jax.jit(lambda: jnp.zeros(8).sum())
    monkeypatch.setattr(xprof, "find_trace_files", lambda d: [])
    row = xprof.capture(
        run, str(tmp_path / "trace"), phase="unit", warmup=0
    )
    assert row["ok"] is False
    assert "no trace files" in row["error"]
    assert "error" in xprof.render_table(row)
