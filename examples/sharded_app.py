"""Runnable sharded-app example: the sharding handler in front of an app
endpoint (the analog of /root/reference/examples/tchannel-forwarding.js).

Three nodes form a ring; each registers an app endpoint ``hello`` behind a
``RingpopHandler``.  A request carrying a shard key (``sk`` header) sent to
ANY node is answered by the key's ring owner — relayed transparently when
that owner is another node.

Run it:

    JAX_PLATFORMS=cpu PYTHONPATH=. python examples/sharded_app.py
"""

import threading

from ringpop_tpu.api.handler import RingpopHandler
from ringpop_tpu.api.ringpop import Ringpop
from ringpop_tpu.net.channel import Channel


class App:
    def __init__(self, name: str):
        self.name = name
        self.channel = Channel("127.0.0.1:0")
        host_port = self.channel.listen()
        self.ringpop = Ringpop(
            "example-app",
            host_port,
            channel=self.channel,
            options={"autoGossip": False},
        )

        def hello(head, body):
            # (headers, body) -> answered by the sk owner, wherever the
            # request entered the cluster
            return None, "hello from %s for %s" % (self.name, head.get("sk"))

        RingpopHandler(self.ringpop, hello, "hello").register()

    def bootstrap(self, hosts):
        self.ringpop.bootstrap(hosts)

    def whoami(self):
        return self.ringpop.whoami()


def main():
    apps = [App("app%d" % i) for i in range(3)]
    hosts = [a.whoami() for a in apps]

    threads = [
        threading.Thread(target=a.bootstrap, args=(hosts,)) for a in apps
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    for _ in range(40):  # gossip until every node shares one checksum
        for a in apps:
            a.ringpop.gossip.tick()
        if len({a.ringpop.membership.checksum for a in apps}) == 1:
            break
    print("cluster converged:", ", ".join(hosts))

    entry = apps[0]
    for sk in ("alpha", "bravo", "charlie", "delta"):
        owner = entry.ringpop.lookup(sk)
        _, body = entry.channel.request(
            entry.whoami(), "hello", head={"sk": sk}, body=None
        )
        print("sk=%-8s owner=%s -> %r" % (sk, owner, body))

    for a in apps:
        a.ringpop.destroy()


if __name__ == "__main__":
    main()
