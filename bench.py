"""Headline benchmark: batched SWIM gossip throughput at 1k nodes.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: simulated node-protocol-periods per second for a 1k-node cluster
running the full SWIM tick (target selection, piggyback dissemination,
ping/ping-req delivery, suspicion, per-node membership checksums) as a
single compiled lax.scan.  Checksums use the fast commutative record-hash
mode (checksum_mode="fast"), which has the same equality semantics as the
reference's FarmHash32 string checksum but not its bit pattern; bit-exact
FarmHash32 checksums are the parity mode (checksum_mode="farmhash"),
exercised by the parity tests, at roughly 15x the per-tick cost.

Baseline: the reference (ringpop-node) runs clusters in real time with a
200 ms minimum protocol period (lib/gossip/index.js:194-196), i.e. a 1k-node
cluster advances at most 1000 x 5 = 5000 node-protocol-periods per second of
wall clock, using 1k OS processes.  ``vs_baseline`` is our rate divided by
that real-time rate on a single TPU chip.
"""

import json
import os
import sys
import time

import numpy as np


def main() -> None:
    n = int(os.environ.get("BENCH_N", "1024"))
    ticks = int(os.environ.get("BENCH_TICKS", "32"))

    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster

    sim = SimCluster(n=n, params=engine.SimParams(n=n, checksum_mode="fast"))
    sim.bootstrap()

    sched = EventSchedule(ticks=ticks, n=n)
    sim.run(sched)  # compile + warm
    import jax

    jax.block_until_ready(sim.state)

    t0 = time.perf_counter()
    metrics = sim.run(sched)
    jax.block_until_ready(sim.state)
    elapsed = time.perf_counter() - t0

    node_ticks_per_sec = n * ticks / elapsed
    baseline = n * 5.0  # real-time reference: 5 protocol periods/s/node
    result = {
        "metric": "swim_node_protocol_periods_per_sec_1k",
        "value": round(node_ticks_per_sec, 1),
        "unit": "node-ticks/s",
        "vs_baseline": round(node_ticks_per_sec / baseline, 2),
        "n_nodes": n,
        "ticks": ticks,
        "elapsed_s": round(elapsed, 3),
        "converged": bool(np.asarray(metrics.converged)[-1]),
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
