"""Headline benchmark: batched SWIM gossip throughput at 1k nodes.

Prints ONE JSON line with the primary metric {"metric", "value", "unit",
"vs_baseline"} plus secondary fields, including the parity-mode rate
(parity_mode_node_ticks_per_sec / parity_mode_vs_baseline).

Metric: simulated node-protocol-periods per second for a 1k-node cluster
running the full SWIM tick (target selection, piggyback dissemination,
ping/ping-req delivery, suspicion, per-node membership checksums) as a
single compiled lax.scan.  Two configurations are measured: the fast
commutative record-hash checksum mode (primary; same equality semantics
as the reference's FarmHash32 string checksum but not its bit pattern)
and the farmhash parity mode (bit-exact reference checksum strings with
dirty-row caching).  On TPU the bench measures up to four configurations
(gated fast, straight-line fast, an 8-cluster vmapped batch, farmhash
parity), roughly quadrupling single-config wall time; on CPU it runs
gated fast + parity only.  A scalable phase (BENCH_SCALABLE=0 opts out)
additionally measures the O(N·U) storm engine at n=100k: sortless-PRP
node-ticks/s vs the argsort twin (bitwise-gated A/B) and the fused
exchange op's achieved GB/s (scalable_* fields).  A routing phase
(BENCH_ROUTE=0 opts out; BENCH_ROUTE_N/_TICKS/_Q/_CHURN knobs) measures
the round-11 routing plane at n=100k under sparse churn: batched Zipf
queries/s + lookups/s, misroute / keys-diverged / checksum-reject rates,
and the incremental-vs-full-sort ring rebuild A/B with bitwise ring +
counter gates (route_* fields).

Baseline: the reference (ringpop-node) runs clusters in real time with a
200 ms minimum protocol period (lib/gossip/index.js:194-196), i.e. a 1k-node
cluster advances at most 1000 x 5 = 5000 node-protocol-periods per second of
wall clock, using 1k OS processes.  ``vs_baseline`` is our rate divided by
that real-time rate on a single TPU chip.

Robustness: the TPU tunnel in this image is occasionally held by another
client at backend-init time (round-1 failure: rc=1, "Unable to initialize
backend 'axon'").  The bench retries backend init / first compile with
backoff before giving up, and always emits a structured JSON line — with an
"error" field on terminal failure — so the round artifact is parseable
either way.
"""

import json
import os
import sys
import time
import traceback

import numpy as np

RETRIES = int(os.environ.get("BENCH_RETRIES", "10"))
RETRY_SLEEP_S = float(os.environ.get("BENCH_RETRY_SLEEP_S", "30"))
# fresh-process retries for a parity-phase compile-helper failure (the
# round-3 artifact regression: one HTTP 500 recorded as parity_error with
# no second attempt, where n=64 parity had compiled fine minutes before)
PARITY_RETRIES = int(os.environ.get("BENCH_PARITY_RETRIES", "4"))

# Transient TPU-tunnel / backend failures worth retrying vs compile-
# helper 500s — shared classification lives in utils.util so this file
# and the measurement sweep can't drift.
def _is_transient(exc: BaseException) -> bool:
    from ringpop_tpu.utils.util import is_transient_backend_error

    return is_transient_backend_error(exc)


def _is_compile_helper_500(exc: BaseException) -> bool:
    from ringpop_tpu.utils.util import is_compile_helper_500

    return is_compile_helper_500(exc)


def _runlog_recorder(config: dict):
    """Optional telemetry trail: BENCH_RUNLOG_DIR=<dir> makes every
    measured window write a JSONL run log (obs.RunRecorder) so the
    BENCH_* artifacts can be generated from recorded data instead of
    hand-curated.  Unset (the default): no recording, no overhead."""
    d = os.environ.get("BENCH_RUNLOG_DIR")
    if not d:
        return None
    from ringpop_tpu.obs import RunRecorder

    return RunRecorder(d + os.sep, config=dict(config, tool="bench.py"))


def _profile_ctx(phase: str, recorder=None):
    """Flag-gated jax.profiler capture (BENCH_PROFILE=1) around a bench
    phase; traces land next to the run logs so a tick-cost regression
    (e.g. the 23% between-session tunnel swing in RESULTS.md) can be
    diagnosed from the artifact instead of by re-running with prints.
    On exit the device memory profile is dumped alongside, and the
    artifact paths are stamped into the run log (phase + event rows) so
    every runlog points at its profiler captures."""
    import contextlib

    if os.environ.get("BENCH_PROFILE") != "1":
        return contextlib.nullcontext()
    import jax

    d = os.path.join(
        os.environ.get("BENCH_RUNLOG_DIR") or ".",
        "profile-%s" % phase,
    )

    @contextlib.contextmanager
    def _ctx():
        t0 = time.perf_counter()
        with jax.profiler.trace(d):
            yield
        mem_path = None
        try:
            mem_path = os.path.join(d, "device_memory.prof")
            with open(mem_path, "wb") as fh:
                fh.write(jax.profiler.device_memory_profile())
        except Exception as exc:  # profile capture must not sink the run
            print(
                "bench: device_memory_profile failed: %s" % exc,
                file=sys.stderr,
            )
            mem_path = None
        if recorder is not None:
            recorder.record_phase(
                "profile[%s]" % phase, time.perf_counter() - t0
            )
            recorder.record_event(
                "profiler_artifacts",
                profile_phase=phase,
                trace_dir=d,
                memory_profile=mem_path,
            )

    return _ctx()


def _xprof_capture(phase: str, run, recorder=None):
    """Flag-gated (BENCH_XPROF=1) per-op time attribution for a bench
    phase: one extra profiled window through obs.xprof.capture — the
    top-K ops by self-time land as an ``xprof.capture`` runlog row and
    on stdout, next to (not inside) the measured wall clocks.  The
    mesh-observatory companion to BENCH_PROFILE's raw trace capture
    (round 17)."""
    if os.environ.get("BENCH_XPROF") != "1":
        return None
    from ringpop_tpu.obs import xprof

    d = os.path.join(
        os.environ.get("BENCH_RUNLOG_DIR") or ".", "xprof-%s" % phase
    )
    row = xprof.capture(
        run, d, phase=phase, warmup=0, repeats=1, recorder=recorder
    )
    print(xprof.render_table(row))
    return row


def _mode_rate(
    n: int,
    ticks: int,
    mode: str,
    gate: bool = True,
    recorder=None,
    make_schedule=None,
    fused: "str | None" = None,
    window: str = "quiet",
) -> tuple:
    """One measured window: construct, bootstrap, converge (the round-5
    kernel-fault guard), warm, measure.  ``make_schedule(ticks, n)``
    overrides the quiet window — the churn capture rides this same
    protocol (same guard, same replay accounting) with
    EventSchedule.churn_window."""
    import jax

    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster

    sim = SimCluster(
        n=n,
        params=engine.SimParams(
            n=n,
            checksum_mode=mode,
            gate_phases=gate,
            # None keeps the per-backend auto resolution; an explicit
            # "on"/"off" pins the fused encode+hash pipeline regardless
            # of backend (the churn window passes "on": the round-7 CPU
            # artifact's 0.66x regression was the auto "off" pick routing
            # churn re-encodes through the ~3 MB/s XLA byte assembly)
            fused_checksum=fused if fused is not None else "auto",
        ),
    )
    sim.bootstrap()
    # converge via SINGLE steps before any long scan: a 256-tick scan
    # over the post-bootstrap dissemination wave is a long scan of heavy
    # ticks — the TPU worker's kernel-fault trigger (round-5 bisect:
    # the same scan on a converged quiet state is stable; mid-wave it
    # crashed the worker every run).  Steps are separate executions, so
    # no long heavy program ever runs; the step programs are already
    # compiled (bootstrap uses one).  The measured window is therefore
    # the converged steady state in BOTH modes — the same window every
    # prior round measured.
    converged_in = sim.run_until_converged(max_ticks=96, quiet_after=1)
    if converged_in < 0:
        # the guard's guarantee would be void: refuse to run the long
        # scan mid-wave (the kernel-fault shape) — fail loudly instead
        raise RuntimeError(
            "cluster failed to converge within 96 ticks before the "
            "measurement window (n=%d, mode=%s)" % (n, mode)
        )

    from ringpop_tpu.obs import perf as obs_perf

    sched = (
        make_schedule(ticks, n)
        if make_schedule is not None
        else EventSchedule(ticks=ticks, n=n)
    )
    obs_perf.fence(sim.run(sched))  # compile + warm (ends reconverged)
    jax.block_until_ready(sim.state)

    warm_replays = sim.parity_replays
    with _profile_ctx(mode, recorder=recorder):
        # the shared warm-then-measure helper (obs.perf): fenced wall +
        # a perf.phase runlog row stamped after the clock stops
        metrics, elapsed = obs_perf.timed_window(
            lambda: sim.run(sched),
            warmup=0,
            recorder=recorder,
            phase="measure[%s]" % mode,
            window=window,
            n=n,
        )
        jax.block_until_ready(sim.state)
    if recorder is not None:
        # record AFTER the clock stops: the JSONL fold is host-side
        # Python and must not ride inside the measured window (the rate
        # with recording on must be comparable to hand-measured rounds).
        # One run log carries every measured window, delimited by the
        # "window" events.
        recorder.describe("sim.engine", sim.params.n, sim.params)
        recorder.record_event(
            "window",
            mode=mode,
            gate_phases=gate,
            converged_in=converged_in,
            window=window,
            # pin the RESOLVED fused mode per window: the churn number is
            # only interpretable against the encode pipeline that ran
            fused_checksum=sim.params.fused_checksum,
        )
        recorder.record_ticks(metrics)
        recorder.record_phase("measure[%s]" % mode, elapsed)
    # bounded-parity replays INSIDE the measured window (quiet windows
    # have none; any nonzero count means the rate includes exact-shape
    # replay cost and must be read accordingly)
    extras = None
    if mode == "farmhash":
        # rows the recompute actually HASHED over the window, for the
        # encode-throughput floor the BENCH artifacts now track (the
        # round-5 bound was ~100 MB/s of XLA byte assembly; the fused
        # kernel exists to move it).  Under the fused bounded shape on
        # TPU the chunk runs straight-line — k == n rows x 2 recomputes
        # EVERY tick regardless of dirtiness; under cond-gated shapes
        # only dirty rows are re-encoded, so a quiet converged window
        # honestly reports ~0 (no encode work ran at all)
        fused_straightline = (
            sim.params.fused_checksum == "on"
            and jax.default_backend() == "tpu"
        )
        dirty_rows = int(np.asarray(metrics.dirty_rows).sum())
        extras = {
            "row_string_bytes": len(sim.checksum_string_of(0)),
            "dirty_rows": dirty_rows,
            "rows_hashed": (
                2 * n * ticks if fused_straightline else dirty_rows
            ),
            "fused": sim.params.fused_checksum,
        }
    return (
        n * ticks / elapsed,
        elapsed,
        metrics,
        sim.parity_replays - warm_replays,
        extras,
    )


def _churn_rate(n: int, ticks: int, recorder=None) -> tuple:
    """Parity-mode throughput for a window with churn INSIDE it (the
    shared EventSchedule.churn_window shape: kill wave early, revive at
    mid-window).  Same measurement protocol as every other window —
    _mode_rate with a schedule override.  Returns (rate, elapsed,
    replays_in_window, extras); the round-5 catastrophic case was
    overflow replays collapsing this to ~731 node-ticks/s — the fused
    bounded recompute must hold >= 1x real-time with zero replays.

    fused="on" on EVERY backend (round 10): the auto resolution keeps
    fused off on CPU — right for quiet windows, where the gated
    recompute skips encode work entirely, but the round-7 CPU artifact
    showed the churn window re-encoding dirty rows through the XLA byte
    assembly at 3.2 MB/s (0.66x real-time) while the fused pipeline's
    pure-XLA twin encodes at ~522 MB/s on the same image
    (PROF_PARITY_ROOFLINE.json).  With fused on the committed round-10
    artifact (BENCH_r10_cpu.json) measures the CPU churn window at
    8,252 node-ticks/s (1.61x real-time) vs the round-7 3,378 (0.66x),
    zero replays either way.  The pinned mode lands in the artifact
    (churn_parity_fused) and the runlog's window event."""
    from ringpop_tpu.models.sim.cluster import EventSchedule

    rate, elapsed, _, replays, extras = _mode_rate(
        n,
        ticks,
        "farmhash",
        recorder=recorder,
        make_schedule=EventSchedule.churn_window,
        fused="on",
        window="churn",
    )
    return rate, elapsed, replays, extras


def _scalable_rate(
    n: int, ticks: int, perm_impl: str, recorder=None
) -> tuple:
    """Storm node-ticks/s for the O(N·U) scalable engine (round 10's
    hot-path rewrite): one churn-storm window (10% kill + rejoin —
    StormSchedule.churn_storm, the north-star 1M shape) through the
    scanned ScalableCluster driver.  ``perm_impl`` selects the partner
    permutation ("auto" resolves sortless; "argsort" is the A/B twin —
    same PRP values, inverse by argsort, bit-identical trajectories), so
    calling this twice gives the sortless-vs-argsort headline.  Returns
    (rate, elapsed, cluster) — the cluster so the caller can A/B final
    states bitwise and reuse the heard mask for the exchange GB/s
    probe."""
    import jax

    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.models.sim.storm import ScalableCluster, StormSchedule

    from ringpop_tpu.obs import perf as obs_perf

    params = es.ScalableParams(n=n, perm_impl=perm_impl)
    sc = ScalableCluster(n=n, params=params, seed=0)
    sched = StormSchedule.churn_storm(
        ticks, n, fraction=0.10, fail_tick=1, seed=0
    )
    obs_perf.fence(sc.run(sched))  # compile + warm (donated state)
    jax.block_until_ready(sc.state)
    with _profile_ctx("scalable-%s" % perm_impl, recorder=recorder):
        ms, elapsed = obs_perf.timed_window(
            lambda: sc.run(sched),
            warmup=0,
            recorder=recorder,
            phase="measure[scalable:%s]" % sc.params.perm_impl,
            n=n,
        )
        jax.block_until_ready(sc.state)
    if recorder is not None:
        # after the clock stops, like every other window
        recorder.record_event(
            "window",
            mode="scalable_storm",
            window="churn_storm",
            perm_impl=sc.params.perm_impl,
            fused_exchange=sc.params.fused_exchange,
        )
        recorder.record_ticks(ms)
        recorder.record_phase(
            "measure[scalable:%s]" % sc.params.perm_impl, elapsed
        )
    _xprof_capture(
        "scalable-%s" % sc.params.perm_impl,
        lambda: sc.run(sched),
        recorder=recorder,
    )
    return n * ticks / elapsed, elapsed, sc


def _exchange_gbps(heard, r_delta) -> tuple:
    """Achieved bandwidth of the fused exchange op on the storm's own
    [N, U/32] mask shape — the shared in-scan probe + one-pass traffic
    model (ops.exchange.measure_bandwidth / step_traffic_bytes; same
    numbers convention as PROF_EXCHANGE_ROOFLINE.json and the
    tpu_measure fused_exchange phase).  Returns (gbps, impl)."""
    import jax
    import jax.numpy as jnp

    from ringpop_tpu.ops import exchange as exch

    impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    pulled = jnp.roll(heard, 1, axis=0)
    pushed = jnp.roll(heard, -1, axis=0)
    gbps, _sec = exch.measure_bandwidth(
        heard, pulled, pushed, r_delta, impl=impl
    )
    return gbps, impl


def _mesh_rate(
    n_per_shard: int, ticks: int, gate_n: int, recorder=None
) -> dict:
    """Round-14 mesh phase: weak-scaling of the shard_map'd exchange
    plane over the available devices (forced host CPUs now, chips on
    the next tunnel session), plus THE bitwise invariance gate.

    Weak scaling: a shard ladder (1/2/4/.. up to the device count) runs
    the same churn-storm shape at ``n_per_shard`` nodes PER SHARD;
    ``mesh_weak_scaling_efficiency`` = rate(S) / (S * rate(1)) at the
    top rung.  Gate: a FIXED ``gate_n`` seeded storm must produce
    bitwise-identical final states across every shard count, the
    single-device engine, and the partitionable GSPMD XLA twin (the
    fallback gate) — asserted here, not just recorded.  Each rung lands
    a ``mesh_window`` runlog event and the summary a ``weak_scaling``
    event (scripts/check_metrics_schema.py validates both)."""
    import jax

    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.models.sim.storm import ScalableCluster, StormSchedule
    from ringpop_tpu.ops import exchange as exch
    from ringpop_tpu.parallel import mesh as pmesh

    devs = len(jax.devices())
    ladder = [s for s in (1, 2, 4, 8, 16, 32) if s <= devs]
    out: dict = {
        "mesh_devices": devs,
        "mesh_shards_ladder": ladder,
        "mesh_n_per_shard": n_per_shard,
        "mesh_ticks": ticks,
    }
    rates = {}
    res_note = None
    for s in ladder:
        n = n_per_shard * s
        storm = pmesh.ShardedStorm(
            n=n,
            mesh=pmesh.make_mesh(s),
            params=es.ScalableParams(n=n),
            seed=0,
        )
        res_note = storm.exchange_resolution()
        sched = StormSchedule.churn_storm(
            ticks, n, fraction=0.10, fail_tick=1, seed=0
        )
        storm.run(sched)  # compile + warm (donated state: overwritten)
        jax.block_until_ready(storm.state)
        t0 = time.perf_counter()
        with _profile_ctx("mesh-%d" % s, recorder=recorder):
            storm.run(sched)
            jax.block_until_ready(storm.state)
        elapsed = time.perf_counter() - t0
        rates[s] = n * ticks / elapsed
        if recorder is not None:
            recorder.record_event(
                "mesh_window",
                n=n,
                shards=s,
                ticks=ticks,
                exchange_mode=storm.exchange_mode,
                exchange_impl=storm.exchange_impl,
                exchange_cap=storm.exchange_cap,
                node_ticks_per_sec=round(rates[s], 1),
            )
    top = ladder[-1]
    # per-op attribution at the top rung (the storm/sched of the last
    # ladder iteration): the chips' interconnect ops show up by name
    _xprof_capture(
        "mesh-%d" % top, lambda: storm.run(sched), recorder=recorder
    )
    out["mesh_node_ticks_per_sec"] = {
        str(s): round(r, 1) for s, r in rates.items()
    }
    out["mesh_weak_scaling_efficiency"] = round(
        rates[top] / (top * rates[1]), 3
    )
    out["mesh_exchange_mode"] = res_note["mode"]
    out["mesh_exchange_impl"] = res_note["impl"]
    # the shared cross-shard traffic model at the top rung (modeled
    # interconnect vs shard-local bytes per tick — the roofline rows)
    w = es.ScalableParams(n=n_per_shard * top).u // 32
    out["mesh_traffic_model"] = exch.cross_shard_traffic_bytes(
        n_per_shard * top, w, top
    )

    # ---- the bitwise invariance gate at the overlap size -------------
    gate_sched = lambda: StormSchedule.churn_storm(  # noqa: E731
        ticks, gate_n, fraction=0.10, fail_tick=1, seed=3
    )
    single = ScalableCluster(
        n=gate_n, params=es.ScalableParams(n=gate_n), seed=3
    )
    single.run(gate_sched())
    ref = {
        f: np.asarray(getattr(single.state, f))
        for f in ("heard", "checksum", "truth_status", "base_sum")
    }

    def _gate_one(storm):
        storm.run(gate_sched())
        return all(
            (np.asarray(getattr(storm.state, f)) == ref[f]).all()
            for f in ref
        )

    gate_ok = True
    for s in ladder:
        gate_ok &= _gate_one(
            pmesh.ShardedStorm(
                n=gate_n,
                mesh=pmesh.make_mesh(s),
                params=es.ScalableParams(n=gate_n),
                seed=3,
            )
        )
    # the partitionable XLA twin under GSPMD — the fallback gate
    gate_ok &= _gate_one(
        pmesh.ShardedStorm(
            n=gate_n,
            mesh=pmesh.make_mesh(top),
            params=es.ScalableParams(n=gate_n, fused_exchange="xla"),
            seed=3,
        )
    )
    out["mesh_gate_n"] = gate_n
    out["mesh_bitwise_equal"] = bool(gate_ok)
    assert gate_ok, (
        "mesh phase: sharded trajectory diverged from the single-device "
        "engine at n=%d" % gate_n
    )
    if recorder is not None:
        recorder.record_event(
            "weak_scaling",
            n_per_shard=n_per_shard,
            shards=top,
            ticks=ticks,
            node_ticks_per_sec=round(rates[top], 1),
            efficiency=out["mesh_weak_scaling_efficiency"],
            bitwise_equal=bool(gate_ok),
        )
        recorder.record_event(
            "mesh_exchange_resolution", **res_note
        )
    return out


def _ckpt_rate(n: int, ticks: int, every: int, recorder=None) -> dict:
    """Round-13 recovery-plane numbers at the storm shape: (a) per-tick
    overhead of a ``checkpoint_every`` cadence vs the same storm
    un-checkpointed (scan split at cadence lines + atomic manifest
    writes), (b) save/restore throughput (MB/s) for the single-file vs
    sharded manifest paths, with the restored states gated bitwise.
    Checkpoints go under BENCH_CKPT_DIR (default: a temp dir, cleaned)."""
    import shutil
    import tempfile

    import jax

    from ringpop_tpu.models.sim import checkpoint as ckpt
    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.models.sim.storm import ScalableCluster, StormSchedule

    workdir = os.environ.get("BENCH_CKPT_DIR") or tempfile.mkdtemp(
        prefix="bench-ckpt-"
    )
    out: dict = {"ckpt_n": n, "ckpt_ticks": ticks, "ckpt_every": every}
    params = es.ScalableParams(n=n)

    def _storm(seed=0):
        sc = ScalableCluster(n=n, params=params, seed=seed)
        sched = StormSchedule.churn_storm(
            ticks, n, fraction=0.10, fail_tick=1, seed=0
        )
        return sc, sched

    # warm the compile (both the full-window and the cadence-split
    # shapes), then measure base vs cadenced windows
    sc, sched = _storm()
    sc.run(sched)
    jax.block_until_ready(sc.state)
    sc, sched = _storm()
    t0 = time.perf_counter()
    sc.run(sched)
    jax.block_until_ready(sc.state)
    base_s = time.perf_counter() - t0

    ck, sched2 = _storm()
    ck.enable_checkpoints(os.path.join(workdir, "warm"), every=every, keep=2)
    ck.run(sched2)  # warm the chunked window shapes
    jax.block_until_ready(ck.state)
    ck, sched2 = _storm()
    ck.enable_checkpoints(os.path.join(workdir, "fam"), every=every, keep=2)
    t0 = time.perf_counter()
    with _profile_ctx("ckpt-cadence", recorder=recorder):
        ck.run(sched2)
        jax.block_until_ready(ck.state)
    ckpt_s = time.perf_counter() - t0
    saves = len(ck.checkpoint_manager.list_checkpoints())
    out["ckpt_base_s"] = round(base_s, 3)
    out["ckpt_cadence_s"] = round(ckpt_s, 3)
    out["ckpt_saves_in_window"] = saves
    out["ckpt_overhead_frac"] = round(max(0.0, ckpt_s / base_s - 1.0), 4)
    # cadence must not change the trajectory (the resume-bitwise plane
    # already gates this at small n; this is the at-scale sanity)
    out["ckpt_bitwise_equal"] = bool(
        (np.asarray(sc.state.checksum) == np.asarray(ck.state.checksum)).all()
        and (np.asarray(sc.state.heard) == np.asarray(ck.state.heard)).all()
    )

    # save/restore throughput, single-file vs sharded A/B
    shards_ab = int(os.environ.get("BENCH_CKPT_SHARDS", "8"))
    for label, shards in (("single", 1), ("sharded%d" % shards_ab, shards_ab)):
        path = os.path.join(workdir, "ab-%s" % label)
        t0 = time.perf_counter()
        manifest = ckpt.save_checkpoint(
            path,
            ck.state,
            ck.params,
            shards=shards,
            sharded_fields=es.NODE_SHARDED_FIELDS if shards > 1 else None,
        )
        save_s = time.perf_counter() - t0
        mb = manifest["nbytes"] / 1e6
        t0 = time.perf_counter()
        loaded = ckpt.load_checkpoint(path, es.ScalableState, ck.params)
        restore_s = time.perf_counter() - t0
        equal = all(
            getattr(loaded, f) is None
            if getattr(ck.state, f) is None
            else (
                np.asarray(getattr(loaded, f))
                == np.asarray(getattr(ck.state, f))
            ).all()
            for f in es.ScalableState._fields
        )
        out["ckpt_mb"] = round(mb, 2)
        out["ckpt_save_mbps_%s" % label] = round(mb / save_s, 1)
        out["ckpt_restore_mbps_%s" % label] = round(mb / restore_s, 1)
        out["ckpt_roundtrip_equal_%s" % label] = bool(equal)
    if recorder is not None:
        recorder.record_event(
            "ckpt_window",
            n=n,
            every=every,
            saves=saves,
            overhead_frac=out["ckpt_overhead_frac"],
            mb=out["ckpt_mb"],
            save_mbps_single=out["ckpt_save_mbps_single"],
            save_mbps_sharded=out[
                "ckpt_save_mbps_sharded%d" % shards_ab
            ],
        )
        recorder.record_phase("measure[ckpt-cadence]", ckpt_s)
    if not os.environ.get("BENCH_CKPT_DIR"):
        shutil.rmtree(workdir, ignore_errors=True)
    return out


def _leave_churn_schedule(ticks: int, n: int, every: int = 3, seed: int = 0):
    """Dissemination-active window for the full-engine ladder (round
    16): one graceful leave + next-tick rejoin every ``every`` ticks
    keeps the change tables hot — sender select, receiver apply/bump
    and response assembly fire every tick — without the ping-req storms
    a kill window adds.  That isolates exactly the phases the fused
    tick (SimParams.fused_tick) rewired; kill-window behavior is
    covered by the existing churn_parity capture.  Boundary clamp: a
    leave drawn on the window's last tick gets its rejoin the SAME
    tick (min(t+1, ticks-1)) — a leave+join TickInputs row instead of
    the leave->rejoin pair, still dissemination-active and shared by
    both A/B legs; kept as-is so the committed code reproduces the
    banked BENCH_r15 schedule byte-for-byte."""
    from ringpop_tpu.models.sim.cluster import EventSchedule

    rng = np.random.default_rng(seed)
    sched = EventSchedule(ticks=ticks, n=n)
    sched.leave = np.zeros((ticks, n), bool)
    for t in range(1, ticks, every):
        v = int(rng.integers(0, n))
        sched.leave[t, v] = True
        sched.join[min(t + 1, ticks - 1), v] = True
    return sched


def _full_rate(n: int, ticks: int, fused_tick: str, recorder=None):
    """One measured full-engine window at SimParams.fused_tick=
    ``fused_tick`` — same protocol as every other window (construct,
    bootstrap, converge, warm, fenced measure).  Returns (rate,
    elapsed, sim) so the ladder can bitwise-gate the A/B final states
    in-phase."""
    import jax

    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.cluster import SimCluster
    from ringpop_tpu.obs import perf as obs_perf

    sim = SimCluster(
        n=n,
        params=engine.SimParams(
            n=n, checksum_mode="fast", fused_tick=fused_tick
        ),
    )
    sim.bootstrap()
    converged_in = sim.run_until_converged(max_ticks=96, quiet_after=1)
    if converged_in < 0:
        raise RuntimeError(
            "full phase: cluster failed to converge before the window "
            "(n=%d, fused_tick=%s)" % (n, fused_tick)
        )
    sched = _leave_churn_schedule(ticks, n)
    obs_perf.fence(sim.run(sched))  # compile + warm
    jax.block_until_ready(sim.state)
    with _profile_ctx(
        "full-%s" % sim.params.fused_tick, recorder=recorder
    ):
        _metrics, elapsed = obs_perf.timed_window(
            lambda: sim.run(sched),
            warmup=0,
            recorder=recorder,
            phase="measure[full:%s]" % sim.params.fused_tick,
            n=n,
        )
        jax.block_until_ready(sim.state)
    _xprof_capture(
        "full-%s" % sim.params.fused_tick,
        lambda: sim.run(sched),
        recorder=recorder,
    )
    return n * ticks / elapsed, elapsed, sim


def _full_ladder(ns, ticks: int, recorder=None) -> dict:
    """Round-16 full-engine scaling ladder: fused (auto-resolved
    SimParams.fused_tick) vs classic phase-by-phase node-ticks/s at
    each ``n``, with the bitwise final-state gate ASSERTED in-phase —
    every SimState field must match or the bench aborts (the ISSUE 14
    acceptance shape)."""
    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.cluster import clear_executable_cache

    import jax

    # the fused leg pins the backend's twin EXPLICITLY (pallas on TPU,
    # xla elsewhere): the ladder's job is the fused-vs-classic A/B at
    # every rung — the auto table's small-n "off" pick would reduce the
    # low rungs to off-vs-off (auto itself is pinned from this ladder's
    # measured crossover; see engine.resolve_fused_tick)
    fused_mode = "pallas" if jax.default_backend() == "tpu" else "xla"
    rungs = []
    for n in ns:
        r_off, _, s_off = _full_rate(n, ticks, "off", recorder=recorder)
        r_f, _, s_f = _full_rate(
            n, ticks, fused_mode, recorder=recorder
        )
        for f in engine.SimState._fields:
            v = getattr(s_off.state, f)
            if v is None:
                continue
            if not np.array_equal(
                np.asarray(getattr(s_f.state, f)), np.asarray(v)
            ):
                raise RuntimeError(
                    "full phase: fused trajectory diverged from the "
                    "classic path at n=%d (state field %r)" % (n, f)
                )
        rung = {
            "n": n,
            "fused_tick": s_f.params.fused_tick,
            "node_ticks_per_sec": round(r_f, 1),
            "off_node_ticks_per_sec": round(r_off, 1),
            "fused_vs_off": round(r_f / r_off, 3),
            "bitwise_equal": True,
        }
        if recorder is not None:
            for mode, rate in (
                ("off", r_off),
                (s_f.params.fused_tick, r_f),
            ):
                recorder.record_event(
                    "full_window",
                    n=n,
                    ticks=ticks,
                    fused_tick=mode,
                    node_ticks_per_sec=round(rate, 1),
                    bitwise_equal=True,
                )
        rungs.append(rung)
        # two [N, N]-state executable sets per rung: drop them before
        # the next size so the ladder's memory high-water stays bounded
        clear_executable_cache()
    return {"full_ticks": ticks, "full_ladder": rungs}


def _sparse_churn_schedule(n: int, ticks: int, churn: int, seed: int = 0):
    """Sparse per-tick churn: ``churn`` random kills each tick, revived
    two ticks later — the steady trickle the incremental ring kernel is
    built for (a handful of dirty buckets per tick, never the caps)."""
    from ringpop_tpu.models.sim.storm import StormSchedule

    rng = np.random.default_rng(seed)
    sched = StormSchedule(ticks=ticks, n=n)
    waves = {}
    for t in range(1, ticks):
        waves[t] = rng.choice(n, size=min(churn, n), replace=False)
        sched.kill[t, waves[t]] = True
        if t - 2 in waves:
            sched.revive[t, waves[t - 2]] = True
    return sched


def _route_rate(
    n: int, ticks: int, q: int, churn: int, ring_impl: str, recorder=None
) -> tuple:
    """Routing-plane throughput (round 11): the coupled membership +
    routing scan under sparse churn.  Each tick routes ``q`` Zipf
    requests — 2 keys per request, each looked up under the stale AND
    truth rings, so the program performs ``4*q`` ring lookups per tick.
    ``ring_impl`` A/Bs the incremental bucketed kernel against the
    full-``jnp.sort`` twin (bit-identical metrics + materialized ring —
    the gate the caller asserts).  Returns (queries/s, elapsed, driver,
    route metric stack)."""
    import jax

    from ringpop_tpu.models.route.plane import RoutedStorm, RouteParams
    from ringpop_tpu.models.sim import engine_scalable as es

    from ringpop_tpu.obs import perf as obs_perf

    params = es.ScalableParams(n=n)
    route = RouteParams(n=n, queries_per_tick=q, ring_impl=ring_impl)
    rs = RoutedStorm(n, params=params, route=route, seed=0)
    sched = _sparse_churn_schedule(n, ticks, churn)
    obs_perf.fence(rs.run(sched))  # compile + warm (donated state)
    jax.block_until_ready(rs.cluster.state)
    with _profile_ctx("route-%s" % ring_impl, recorder=recorder):
        (em, rm), elapsed = obs_perf.timed_window(
            lambda: rs.run(sched),
            warmup=0,
            recorder=recorder,
            phase="measure[route:%s]" % rs.route_params.ring_impl,
            n=n,
            q=q,
        )
        jax.block_until_ready(rs.cluster.state)
    if recorder is not None:
        recorder.record_event(
            "route_window",
            ring_impl=rs.route_params.ring_impl,
            n=n,
            q=q,
            ticks=ticks,
            churn_per_tick=churn,
            bucket_bits=rs.route_params.bucket_bits,
        )
        rows = dict(em._asdict())
        rows.update(rm._asdict())
        recorder.record_ticks(rows)
        recorder.record_phase("measure[route:%s]" % ring_impl, elapsed)
    return q * ticks / elapsed, elapsed, rs, rm


def _hist_capture(
    n: int, ticks: int, q: int, churn: int, recorder=None
) -> dict:
    """Round-15 performance-observatory capture: ONE histogram-enabled
    routed storm (RouteParams.histograms + ScalableParams.histograms)
    whose device-side log2-bucket counters are drained through
    obs.histograms with exact p50/p95/p99 extraction — routing retry
    depth / reroute hops / dirty-bucket sizes plus rumor propagation
    latency and suspicion durations, logged as ``hist.drain`` runlog
    events AND emitted as statsd TIMER keys (the emitted key list lands
    in the artifact as proof).  A separate window from the measured
    A/Bs: recording costs ride here, never inside a published rate."""
    from ringpop_tpu.models.route.plane import RoutedStorm, RouteParams
    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.obs.statsd_bridge import StatsdBridge

    rs = RoutedStorm(
        n,
        params=es.ScalableParams(n=n, histograms=True),
        route=RouteParams(n=n, queries_per_tick=q, histograms=True),
        seed=0,
    )
    rs.run(_sparse_churn_schedule(n, ticks, churn))
    # recorder attached AFTER the run: this window contributes ONLY its
    # hist.drain events to the shared bench runlog — its per-tick rows
    # (a different n than the measured A/B windows) must not mix into
    # the A/Bs' counter stream
    rs.recorder = recorder

    class _Capture:  # in-memory statsd sink: the emitted-key proof
        def __init__(self):
            self.timings = []

        def timing(self, key, value):
            self.timings.append((key, value))

        def increment(self, key, value=1):
            pass

        def gauge(self, key, value):
            pass

    cap = _Capture()
    bridge = StatsdBridge(statsd=cap, host_port="127.0.0.1:3000")
    summaries = rs.drain_histograms(statsd=bridge)
    out = {"hist_n": n, "hist_ticks": ticks}
    route_s = summaries.get("route", {})
    sim_s = summaries.get("sim", {})
    for track, prefix in (
        ("retry_depth", "route_retry_depth"),
        ("reroute_hops", "route_reroute_hops"),
    ):
        st = route_s.get(track, {})
        for qq in ("p50", "p95", "p99"):
            out["%s_%s" % (prefix, qq)] = st.get(qq)
    for track, prefix in (
        ("rumor_age", "scalable_rumor_age_ticks"),
        ("suspicion_duration", "scalable_suspicion_ticks"),
    ):
        st = sim_s.get(track, {})
        for qq in ("p50", "p95", "p99"):
            out["%s_%s" % (prefix, qq)] = st.get(qq)
    out["hist_statsd_timer_keys"] = sorted({k for k, _ in cap.timings})
    return out


def _reqtrace_capture(
    n: int, ticks: int, q: int, churn: int, recorder=None
) -> dict:
    """Round-19 request-observatory capture: ONE reqtrace-enabled routed
    storm drained in two windows through the sliding-window SLO plane —
    sampled per-request records reconciled against the window's
    RouteMetrics (the honesty gate rides the bench artifact as a bool),
    ``reqtrace.drain``/``slo.window`` rows on the shared runlog, statsd
    keys through an in-memory sink (the emitted-key proof).  Like the
    histogram capture, a separate window from the measured A/Bs:
    recording costs ride here, never inside a published rate."""
    import numpy as np

    from ringpop_tpu.models.route import reqtrace as rt
    from ringpop_tpu.models.route.plane import RoutedStorm, RouteParams
    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.obs import requests as oreq
    from ringpop_tpu.obs.slo import SLOTarget, SLOWindowPlane
    from ringpop_tpu.obs.statsd_bridge import StatsdBridge

    window = max(ticks // 2, 1)
    rs = RoutedStorm(
        n,
        params=es.ScalableParams(n=n),
        route=RouteParams(
            n=n,
            queries_per_tick=q,
            histograms=True,
            reqtrace=True,
            req_capacity=rt.req_capacity_for(q, window),
            req_sample_log2=2,
        ),
        seed=0,
    )

    class _Capture:  # in-memory statsd sink: the emitted-key proof
        def __init__(self):
            self.keys = set()

        def timing(self, key, value):
            self.keys.add(key)

        def increment(self, key, value=1):
            self.keys.add(key)

        def gauge(self, key, value):
            self.keys.add(key)

    cap = _Capture()
    bridge = StatsdBridge(statsd=cap, host_port="127.0.0.1:3000")
    slo = SLOWindowPlane(
        SLOTarget(name="route", success_objective=0.999),
        window_len=4,
        recorder=recorder,
        statsd=bridge,
    )
    out = {
        "reqtrace_n": n,
        "reqtrace_ticks": 2 * window,
        "reqtrace_sample_log2": 2,
    }
    records = drops = 0
    reconcile_ok = True
    sched = _sparse_churn_schedule(n, 2 * window, churn)
    for w in range(2):
        lo, hi = w * window, (w + 1) * window
        chunk = type(sched)(ticks=window, n=n)
        chunk.kill = sched.kill[lo:hi]
        chunk.revive = sched.revive[lo:hi]
        # recorder attached only for the drains: this window's per-tick
        # rows (a different n than the measured A/Bs) stay out of the
        # shared bench runlog, like the histogram capture's
        rs.recorder = None
        _, rm = rs.run(chunk)
        rs.recorder = recorder
        hist = np.asarray(rs.rstate.hist)
        rs.drain_histograms(reset=True)
        slo.observe_route_window(hi, hist, rm)
        drained = rs.drain_requests(reset=True, statsd=bridge)
        records += len(drained["records"])
        drops += drained["drops"]
        recon = oreq.reconcile_metrics(
            np.asarray(
                [drained["counts"][f] for f in oreq.COUNT_FIELDS]
            ),
            rm,
        )
        reconcile_ok = reconcile_ok and all(
            v["ok"] for v in recon.values()
        )
    out["reqtrace_records"] = records
    out["reqtrace_drops"] = drops
    out["reqtrace_reconcile_ok"] = reconcile_ok
    row = slo.window_row(2 * window)
    out["reqtrace_slo_p99"] = row["p99"]
    out["reqtrace_slo_success_rate"] = row["success_rate"]
    out["reqtrace_slo_burn_rate"] = row["burn_rate"]
    out["reqtrace_statsd_keys"] = sorted(cap.keys)
    return out


def _ring_rebuild_ab(n: int, r: int, ticks: int, churn: int) -> dict:
    """Isolated ring-maintenance A/B (the ISSUE 6 perf headline): one
    scanned program per impl over the SAME sparse-churn mask sequence —
    incremental dirty-bucket re-merge vs full ``jnp.sort`` rebuild —
    timed warm, with a bitwise gate on the final materialized ring and
    on per-tick (n_points, first_owner) probe sums."""
    import jax
    import jax.numpy as jnp

    from ringpop_tpu.models.ring import device as ringdev
    from ringpop_tpu.models.route import ring_kernel as rk

    reps_np = np.asarray(ringdev.device_replica_hashes(n, r))
    bits = rk.default_bucket_bits(n, r)
    buckets = rk.build_buckets(reps_np, bits)
    reps = jnp.asarray(reps_np)

    rng = np.random.default_rng(1)
    masks = np.ones((ticks, n), bool)
    mask = np.ones(n, bool)
    for t in range(ticks):
        flips = rng.choice(n, size=min(churn, n), replace=False)
        mask = mask.copy()
        mask[flips] = ~mask[flips]
        masks[t] = mask
    jmasks = jnp.asarray(masks)

    @jax.jit
    def run_incremental(state0, jmasks):
        def body(carry, m):
            st, acc = carry
            st, _nc, _nd, _ov = rk.update(
                buckets,
                st,
                m,
                # static caps ARE the incremental work size: size them to
                # the schedule's churn (flips x replica points), not to
                # the bucket count — oversizing re-merges clean buckets
                max_changed=4 * churn,
                max_dirty=min(1 << bits, 4 * churn * r),
            )
            # consume every tick's state so no rebuild is dead code
            acc = acc + st.n_points.astype(jnp.int64) + st.first_owner
            return (st, acc), None

        (st, acc), _ = jax.lax.scan(body, (state0, jnp.int64(0)), jmasks)
        return st, acc

    @jax.jit
    def run_full_sort(jmasks):
        # the ring rides the CARRY, not the scan output: stacking every
        # tick's ring would allocate [ticks, N*R] uint64 (4+ GB at the
        # 1M chip config) and charge a per-tick full-ring write only to
        # this side of the A/B
        def body(carry, m):
            _prev, acc = carry
            ring = ringdev.build_ring(reps, m)
            npts = ringdev.ring_size(m, r)
            owner0 = jnp.where(
                npts > 0,
                (ring[0] & jnp.uint64(0xFFFFFFFF)).astype(jnp.int32),
                jnp.int32(-1),
            )
            acc = acc + npts.astype(jnp.int64) + owner0
            return (ring, acc), None

        ring0 = jnp.zeros(n * r, jnp.uint64)
        (ring, acc), _ = jax.lax.scan(
            body, (ring0, jnp.int64(0)), jmasks
        )
        return ring, acc

    state0 = rk.full_rebuild(buckets, jnp.ones(n, bool))

    from ringpop_tpu.obs import perf as obs_perf

    # the shared warm-then-measure loop (obs.perf.timed_window replaces
    # this phase's hand-rolled warm/fence/measure sequence)
    (st_inc, acc_inc), inc_s = obs_perf.timed_window(
        lambda: run_incremental(state0, jmasks), warmup=1
    )
    (ring_full, acc_full), full_s = obs_perf.timed_window(
        lambda: run_full_sort(jmasks), warmup=1
    )
    flat_inc = np.asarray(rk.materialize(st_inc, n * r))
    return {
        "n": n,
        "replica_points": r,
        "ticks": ticks,
        "churn_per_tick": churn,
        "bucket_bits": bits,
        "incremental_ms": round(inc_s / ticks * 1e3, 3),
        "full_sort_ms": round(full_s / ticks * 1e3, 3),
        "speedup": round(full_s / inc_s, 2),
        "bitwise_equal": bool(
            (flat_inc == np.asarray(ring_full)).all()
            and int(acc_inc) == int(acc_full)
        ),
    }


def _fuzz_rate(b: int, n: int, ticks: int, recorder=None) -> dict:
    """Round-12 scenario-fuzzer phase: B seeded storms through one
    vmapped scan (warm-then-measure), then the invariant layer over the
    drained event streams.  Returns artifact fields."""
    import jax

    from ringpop_tpu.fuzz import executor as fex
    from ringpop_tpu.fuzz import invariants as finv
    from ringpop_tpu.fuzz import scenarios as fsc

    cfg = fsc.ScenarioConfig(
        engine="full", n=n, ticks=ticks, loss_levels=(0.0,)
    )
    ex = fex.FullFuzzExecutor(cfg)
    seeds = list(range(b))
    ex.run_seeds(seeds)  # warm (compile + first dispatch)
    t0 = time.perf_counter()
    run = ex.run_seeds(seeds)
    jax.block_until_ready(run.final_state)
    device_el = time.perf_counter() - t0
    t1 = time.perf_counter()
    violations = finv.check_run(run)
    check_el = time.perf_counter() - t1
    out = {
        "fuzz_b": b,
        "fuzz_n": n,
        "fuzz_ticks": ticks,
        "fuzz_scenarios_per_sec": round(b / device_el, 1),
        "fuzz_node_ticks_per_sec": round(b * n * ticks / device_el, 1),
        "fuzz_events_decoded": sum(len(e) for e in run.events),
        "fuzz_check_sec": round(check_el, 3),
        "fuzz_violations": sum(len(v) for v in violations.values()),
    }
    if recorder is not None:
        recorder.record_event("fuzz_window", **out)
    return out


def _batched_rate(b: int, n: int, ticks: int) -> tuple:
    """Aggregate node-ticks/s for B independent clusters in one program
    (the TPU-utilization configuration; models/sim/batched.py)."""
    import jax

    from ringpop_tpu.models.sim.batched import BatchedSimClusters
    from ringpop_tpu.models.sim.cluster import EventSchedule

    from ringpop_tpu.obs import perf as obs_perf

    bat = BatchedSimClusters(b=b, n=n, seed=0)
    bat.bootstrap()
    sched = EventSchedule(ticks=ticks, n=n)
    ms, elapsed = obs_perf.timed_window(lambda: bat.run(sched), warmup=1)
    jax.block_until_ready(bat.state)
    return b * n * ticks / elapsed, elapsed, bool(
        np.asarray(ms.converged)[-1].all()
    )


def _retry_helper_500(fn, *args, **kwargs):
    """Shared in-process backoff for compile-helper 500s (utils.util.
    retry_compile_helper): transient backend errors re-raise immediately
    — main()'s retry loop owns those — as do real graph/engine failures.
    ONE retry policy for every measured config (fast, straight-line,
    batched, parity)."""
    from ringpop_tpu.utils.util import retry_compile_helper

    return retry_compile_helper(fn, *args, backoffs=_HELPER_BACKOFFS, **kwargs)


_HELPER_BACKOFFS = (0.0, 10.0, 25.0)


def _mode_rate_retry(
    n: int, ticks: int, mode: str, gate: bool = True, recorder=None
) -> tuple:
    return _retry_helper_500(
        _mode_rate, n, ticks, mode, gate=gate, recorder=recorder
    )


def _measure(n: int, ticks: int) -> dict:
    import jax

    platform = jax.devices()[0].platform
    recorder = _runlog_recorder(
        {"n": n, "ticks": ticks, "platform": platform}
    )
    try:
        return _measure_recorded(n, ticks, platform, recorder)
    finally:
        # a failed window must not leave a ZERO-BYTE runlog behind (the
        # file is created at recorder construction; close() writes the
        # header, which is the minimum valid log — the schema gate would
        # otherwise fail on the orphan).  finish() on the success paths
        # already closed it; close() is then a no-op.
        if recorder is not None:
            recorder.close()


def _measure_recorded(n: int, ticks: int, platform: str, recorder) -> dict:
    gate = True
    straightline_error = None
    rate, elapsed, metrics, _, _ = _mode_rate_retry(
        n, ticks, "fast", recorder=recorder
    )
    if platform == "tpu" and os.environ.get("BENCH_STRAIGHTLINE") == "1":
        # OPT-IN since round 5: the straight-line program now carries the
        # always-on ping-req dissemination legs (a 22x tick-cost handicap
        # vs gated on CPU), so it cannot win the probe — and long scans
        # of heavy ticks are the known TPU-worker kernel-fault trigger
        # (DIAG_BOUNDED.json v2_full_scan32): a faulted worker poisons
        # every later phase of the bench with UNAVAILABLE
        try:
            rate_sl, elapsed_sl, metrics_sl, _, _ = _mode_rate_retry(
                n, ticks, "fast", gate=False, recorder=recorder
            )
            if rate_sl > rate:
                gate = False
                rate, elapsed, metrics = rate_sl, elapsed_sl, metrics_sl
        except Exception as exc:
            if _is_transient(exc):
                raise
            straightline_error = "%s: %s" % (
                type(exc).__name__,
                str(exc)[:300],
            )
    baseline = n * 5.0  # real-time reference: 5 protocol periods/s/node
    result = {
        "metric": "swim_node_protocol_periods_per_sec_1k",
        "value": round(rate, 1),
        "unit": "node-ticks/s",
        "vs_baseline": round(rate / baseline, 2),
        "n_nodes": n,
        "ticks": ticks,
        "elapsed_s": round(elapsed, 3),
        "converged": bool(np.asarray(metrics.converged)[-1]),
        "platform": platform,
        "gate_phases": gate,
    }
    if straightline_error is not None:
        # a bug that only manifests in the straight-line program (the
        # config batched mode relies on) must be visible in the artifact
        result["straightline_error"] = straightline_error
    # aggregate throughput: B independent clusters, one program (the chip
    # is op-overhead-bound at a single [1k,1k] cluster).  OPT-IN
    # (BENCH_BATCHED=1): the B=8 vmapped compile is the largest graph the
    # bench can submit and a wedged remote-compile would hang the whole
    # artifact — the batched number is captured by tpu_measure.py's sweep
    # instead, where a stuck phase costs a session, not the round bench.
    if platform == "tpu" and os.environ.get("BENCH_BATCHED", "0") == "1":
        b = int(os.environ.get("BENCH_BATCH_B", "8"))
        try:
            agg, agg_el, agg_conv = _retry_helper_500(
                _batched_rate, b, n, ticks
            )
            result["batched_clusters"] = b
            result["batched_aggregate_node_ticks_per_sec"] = round(agg, 1)
            result["batched_per_cluster_node_ticks_per_sec"] = round(
                agg / b, 1
            )
            result["batched_converged"] = agg_conv
        except Exception as exc:
            if _is_transient(exc):
                raise
            result["batched_error"] = "%s: %s" % (
                type(exc).__name__,
                str(exc)[:300],
            )
    # scalable phase (BENCH_SCALABLE=0 opts out): the O(N·U) storm
    # engine at n=100k — the round-10 sortless-PRP + fused-exchange hot
    # path A/B'd against the argsort twin (bit-identical trajectories:
    # the final states are compared bitwise right here), plus the fused
    # exchange op's achieved GB/s on the storm's own mask shape.
    # Acceptance (round 10): sortless no worse than argsort, exchange
    # GB/s in the artifact + runlog.
    if os.environ.get("BENCH_SCALABLE", "1") == "1":
        try:
            sn = int(os.environ.get("BENCH_SCALABLE_N", "100000"))
            sticks = int(os.environ.get("BENCH_SCALABLE_TICKS", "8"))
            s_rate, _s_el, sc = _retry_helper_500(
                _scalable_rate, sn, sticks, "auto", recorder=recorder
            )
            a_rate, _a_el, sa = _retry_helper_500(
                _scalable_rate, sn, sticks, "argsort", recorder=recorder
            )
            gbps, ex_impl = _exchange_gbps(sc.state.heard, sc.state.r_delta)
            result["scalable_n"] = sn
            result["scalable_ticks"] = sticks
            result["scalable_perm_impl"] = sc.params.perm_impl
            result["scalable_fused_exchange"] = sc.params.fused_exchange
            result["scalable_node_ticks_per_sec"] = round(s_rate, 1)
            result["scalable_argsort_node_ticks_per_sec"] = round(a_rate, 1)
            result["scalable_vs_argsort"] = round(s_rate / a_rate, 2)
            # device-level gate: same seed + schedule, so the A/B final
            # states must match bit-for-bit (perm_impl is trajectory-
            # neutral by construction — this catches a backend-specific
            # divergence the CPU test suite can't)
            result["scalable_bitwise_equal"] = bool(
                (np.asarray(sc.state.heard) == np.asarray(sa.state.heard))
                .all()
                and (
                    np.asarray(sc.state.checksum)
                    == np.asarray(sa.state.checksum)
                ).all()
                and (
                    np.asarray(sc.state.truth_status)
                    == np.asarray(sa.state.truth_status)
                ).all()
            )
            result["scalable_exchange_gbps"] = round(gbps, 2)
            result["scalable_exchange_impl"] = ex_impl
            if recorder is not None:
                recorder.record_event(
                    "exchange_roofline",
                    gbps=round(gbps, 2),
                    impl=ex_impl,
                    n=sn,
                    words=int(sc.state.heard.shape[1]),
                )
        except Exception as exc:
            if _is_transient(exc):
                raise
            result["scalable_error"] = "%s: %s" % (
                type(exc).__name__,
                str(exc)[:300],
            )

    # mesh phase (BENCH_MESH=0 opts out): the round-14 shard_map'd
    # exchange plane — weak-scaling ladder over the available devices
    # (BENCH_MESH_FORCE_HOST=<k> pins k virtual CPU devices BEFORE
    # backend init, through utils.util.pin_cpu_platform) with the
    # shard-count bitwise invariance gate ASSERTED, mesh_window /
    # weak_scaling runlog events, and the shared cross-shard traffic
    # model (ops.exchange.cross_shard_traffic_bytes).
    if os.environ.get("BENCH_MESH", "1") == "1":
        try:
            mps = int(os.environ.get("BENCH_MESH_N_PER_SHARD", "8192"))
            mticks = int(os.environ.get("BENCH_MESH_TICKS", "8"))
            mgate = int(os.environ.get("BENCH_MESH_GATE_N", "1024"))
            result.update(
                _retry_helper_500(
                    _mesh_rate, mps, mticks, mgate, recorder=recorder
                )
            )
        except Exception as exc:
            if _is_transient(exc):
                raise
            result["mesh_error"] = "%s: %s" % (
                type(exc).__name__,
                str(exc)[:300],
            )

    # full-engine phase (BENCH_FULL=0 opts out): the round-16 fused
    # full-fidelity tick — fused vs phase-by-phase node-ticks/s ladder
    # over BENCH_FULL_N sizes on a dissemination-active window, with
    # the bitwise final-state gate asserted IN-PHASE (a divergence
    # aborts the bench) and full_window runlog events per measured
    # window.
    if os.environ.get("BENCH_FULL", "1") == "1":
        try:
            fns = [
                int(x)
                for x in os.environ.get(
                    "BENCH_FULL_N", "1024,4096"
                ).split(",")
                if x.strip()
            ]
            fticks = int(os.environ.get("BENCH_FULL_TICKS", "8"))
            result.update(
                _retry_helper_500(
                    _full_ladder, fns, fticks, recorder=recorder
                )
            )
        except Exception as exc:
            if _is_transient(exc):
                raise
            result["full_error"] = "%s: %s" % (
                type(exc).__name__,
                str(exc)[:300],
            )

    # checkpoint phase (BENCH_CKPT=0 opts out): the round-13 recovery
    # plane at the storm shape — checkpoint-cadence per-tick overhead vs
    # the un-checkpointed storm (bitwise-gated), and save/restore MB/s
    # single-file vs sharded (BENCH_CKPT_N/_TICKS/_EVERY/_SHARDS knobs;
    # ckpt_window runlog event stamps the headline numbers).
    if os.environ.get("BENCH_CKPT", "1") == "1":
        try:
            kn = int(os.environ.get("BENCH_CKPT_N", "100000"))
            kticks = int(os.environ.get("BENCH_CKPT_TICKS", "8"))
            kevery = int(os.environ.get("BENCH_CKPT_EVERY", "4"))
            result.update(
                _retry_helper_500(
                    _ckpt_rate, kn, kticks, kevery, recorder=recorder
                )
            )
        except Exception as exc:
            if _is_transient(exc):
                raise
            result["ckpt_error"] = "%s: %s" % (
                type(exc).__name__,
                str(exc)[:300],
            )

    # routing phase (BENCH_ROUTE=0 opts out): the round-11 device-
    # resident request-routing plane at n=100k under sparse churn —
    # batched Zipf lookups/s through the coupled membership+routing
    # scan, the incremental-vs-full-sort ring rebuild A/B with a
    # bitwise ring gate, and the RouteMetrics counter rates through the
    # runlog (schema-validated by scripts/check_metrics_schema.py).
    if os.environ.get("BENCH_ROUTE", "1") == "1":
        try:
            rn = int(os.environ.get("BENCH_ROUTE_N", "100000"))
            rticks = int(os.environ.get("BENCH_ROUTE_TICKS", "8"))
            rq = int(os.environ.get("BENCH_ROUTE_Q", "262144"))
            rchurn = int(os.environ.get("BENCH_ROUTE_CHURN", "8"))
            i_rate, _i_el, ri, rm_i = _retry_helper_500(
                _route_rate, rn, rticks, rq, rchurn, "incremental",
                recorder=recorder,
            )
            f_rate, _f_el, rf, rm_f = _retry_helper_500(
                _route_rate, rn, rticks, rq, rchurn, "full",
                recorder=recorder,
            )
            result["route_n"] = rn
            result["route_ticks"] = rticks
            result["route_q"] = rq
            result["route_churn_per_tick"] = rchurn
            result["route_bucket_bits"] = ri.route_params.bucket_bits
            result["route_queries_per_sec"] = round(i_rate, 1)
            # 2 keys/request x 2 rings (stale + truth) per tick
            result["route_lookups_per_sec"] = round(4 * i_rate, 1)
            result["route_queries_per_sec_full_sort"] = round(f_rate, 1)
            result["route_vs_full_sort"] = round(i_rate / f_rate, 2)
            # the bitwise gates: same seeds + schedule, so the two ring
            # impls must produce identical materialized rings AND
            # identical counter streams
            result["route_ring_bitwise_equal"] = bool(
                (
                    np.asarray(ri.truth_ring())
                    == np.asarray(rf.truth_ring())
                ).all()
            )
            result["route_metrics_equal"] = all(
                bool(
                    (
                        np.asarray(getattr(rm_i, f))
                        == np.asarray(getattr(rm_f, f))
                    ).all()
                )
                for f in rm_i._fields
            )
            # counter rates over the measured window
            rqs = float(np.asarray(rm_i.route_queries).sum())
            for fld in (
                "route_misroutes",
                "route_reroute_local",
                "route_reroute_remote",
                "route_keys_diverged",
                "route_checksums_differ",
                "route_checksum_rejects",
            ):
                tot = float(np.asarray(getattr(rm_i, fld)).sum())
                result[fld + "_per_1k"] = round(
                    1000.0 * tot / max(rqs, 1.0), 3
                )
            # isolated rebuild A/B — the perf headline's clean number
            ab = _retry_helper_500(
                _ring_rebuild_ab, rn, 16, max(2 * rticks, 16), rchurn
            )
            result["route_rebuild_incremental_ms"] = ab["incremental_ms"]
            result["route_rebuild_full_sort_ms"] = ab["full_sort_ms"]
            result["route_rebuild_speedup"] = ab["speedup"]
            result["route_rebuild_bitwise_equal"] = ab["bitwise_equal"]
            if recorder is not None:
                recorder.record_event(
                    "route_rebuild_ab",
                    n=ab["n"],
                    incremental_ms=ab["incremental_ms"],
                    full_sort_ms=ab["full_sort_ms"],
                    speedup=ab["speedup"],
                    bitwise_equal=ab["bitwise_equal"],
                    churn_per_tick=ab["churn_per_tick"],
                    bucket_bits=ab["bucket_bits"],
                )
            # round-15 histogram capture (BENCH_HIST=0 opts out): its
            # own window, so the recording cost never rides inside a
            # published rate; p50/p95/p99 for routing retry depth and
            # rumor propagation latency land in the artifact, the
            # runlog (hist.drain) and the statsd timer-key list
            if os.environ.get("BENCH_HIST", "1") == "1":
                hn = int(
                    os.environ.get("BENCH_HIST_N", str(min(rn, 20000)))
                )
                result.update(
                    _retry_helper_500(
                        _hist_capture,
                        hn,
                        rticks,
                        rq,
                        rchurn,
                        recorder=recorder,
                    )
                )
            # round-19 request-observatory capture (BENCH_REQTRACE=0
            # opts out): sampled per-request records + the sliding-
            # window SLO verdict, with the RouteMetrics reconciliation
            # bool riding the artifact as a correctness gate
            if os.environ.get("BENCH_REQTRACE", "1") == "1":
                qn = int(
                    os.environ.get("BENCH_REQTRACE_N", str(min(rn, 4096)))
                )
                # capacity is sized for the worst case (every query
                # sampled), so the trace window uses a bounded query
                # rate rather than the measured A/Bs' full storm
                qq = int(
                    os.environ.get(
                        "BENCH_REQTRACE_Q", str(min(rq, 16384))
                    )
                )
                result.update(
                    _retry_helper_500(
                        _reqtrace_capture,
                        qn,
                        rticks,
                        qq,
                        rchurn,
                        recorder=recorder,
                    )
                )
        except Exception as exc:
            if _is_transient(exc):
                raise
            result["route_error"] = "%s: %s" % (
                type(exc).__name__,
                str(exc)[:300],
            )

    # fuzz phase (BENCH_FUZZ=0 opts out): the round-12 scenario fuzzer's
    # aggregate throughput — B full-fidelity storm instances per device
    # pass (per-instance schedules, flight recorder on) plus the
    # host-side invariant check, reported as scenarios/s and
    # node-ticks/s.  The invariant gate doubles as a bench-time
    # correctness assert: a nonzero violation count fails the artifact
    # field rather than silently shipping a number from a broken engine.
    if os.environ.get("BENCH_FUZZ", "1") == "1":
        try:
            fb = int(os.environ.get("BENCH_FUZZ_B", "64"))
            fn_ = int(os.environ.get("BENCH_FUZZ_N", "8"))
            fticks = int(os.environ.get("BENCH_FUZZ_TICKS", "24"))
            fuzz = _retry_helper_500(
                _fuzz_rate, fb, fn_, fticks, recorder=recorder
            )
            result.update(fuzz)
        except Exception as exc:
            if _is_transient(exc):
                raise
            result["fuzz_error"] = "%s: %s" % (
                type(exc).__name__,
                str(exc)[:300],
            )

    # parity mode: bit-exact reference FarmHash32 string checksums in the
    # same compiled tick — the north-star config.  Not allowed to sink
    # the whole artifact: the tunneled chip's remote compile helper
    # occasionally 500s on large graphs, and a fast-mode number with a
    # parity_error beats an error-only artifact.  On TPU the parity tick
    # runs the "bounded" recompute with the auto-resolved K=4 dirty
    # chunk (the round-5 ladder optimum — engine.resolve_auto_parity;
    # one straight-line K-row chunk per recompute, overflowed windows
    # replayed under an exact shape — engine.SimParams.parity_recompute),
    # whose 256-tick scans are stable on the chip (DIAG_BOUNDED.json
    # round 5: no worker fault) — the round-4 32-tick cap is gone,
    # though BENCH_PARITY_TICKS still overrides.  Parity is pinned to
    # gate_phases=True regardless of the fast-mode winner: the gated
    # program is the shape the compile ladder validated.
    parity_ticks = int(os.environ.get("BENCH_PARITY_TICKS", str(ticks)))
    try:
        parity_rate, parity_el, _, parity_replays, pex = _retry_helper_500(
            _mode_rate, n, parity_ticks, "farmhash", gate=True,
            recorder=recorder,
        )
        result["parity_mode_node_ticks_per_sec"] = round(parity_rate, 1)
        result["parity_mode_vs_baseline"] = round(parity_rate / baseline, 2)
        result["parity_ticks"] = parity_ticks  # its own window, not `ticks`
        result["parity_replays_in_window"] = parity_replays
        if pex is not None:
            # string-encode throughput over the window: assembled
            # checksum-string bytes of every row the recompute hashed,
            # per wall second — the floor the fused kernel exists to
            # raise (round-5 XLA byte assembly: ~100 MB/s).  Quiet
            # windows under cond-gated shapes honestly report ~0 (no
            # encode ran); the churn capture below is the loaded number
            result["parity_fused"] = pex["fused"]
            result["parity_encode_mbps"] = round(
                pex["rows_hashed"] * pex["row_string_bytes"]
                / parity_el
                / 1e6,
                1,
            )
        # churn-window capture (BENCH_CHURN=0 opts out): kill+revive
        # INSIDE the measured parity window — the round-5 catastrophic
        # case (overflow replays at ~731 node-ticks/s).  Acceptance:
        # >= 5,120 node-ticks/s (1x real-time) with zero in-window
        # replays under the fused bounded recompute.
        if os.environ.get("BENCH_CHURN", "1") == "1":
            try:
                (
                    churn_rate,
                    churn_el,
                    churn_replays,
                    churn_ex,
                ) = _retry_helper_500(
                    _churn_rate, n, parity_ticks, recorder=recorder
                )
                result["churn_parity_node_ticks_per_sec"] = round(
                    churn_rate, 1
                )
                result["churn_parity_vs_baseline"] = round(
                    churn_rate / baseline, 2
                )
                result["churn_parity_replays_in_window"] = churn_replays
                result["churn_parity_fused"] = churn_ex["fused"]
                result["churn_parity_encode_mbps"] = round(
                    churn_ex["rows_hashed"] * churn_ex["row_string_bytes"]
                    / churn_el
                    / 1e6,
                    1,
                )
            except Exception as cexc:
                if _is_transient(cexc):
                    raise
                result["churn_parity_error"] = "%s: %s" % (
                    type(cexc).__name__,
                    str(cexc)[:300],
                )
        if recorder is not None:
            result["runlog"] = recorder.path
            recorder.finish(result=result)
        return result
    except Exception as e:
        exc = e
        if _is_transient(exc):
            raise  # retryable backend failures keep the retry semantics
        tries = getattr(exc, "_retry_attempts", 1)
    # in-process budget exhausted on a compile-helper 500: a FRESH
    # interpreter re-submits the compile through a clean tunnel session
    # (the fast-mode number is re-measured there — itself protected by
    # _mode_rate_retry — and the artifact prints once, at the end of
    # whichever process finally succeeds)
    if _is_compile_helper_500(exc):
        from ringpop_tpu.utils.util import reexec_retry

        if recorder is not None:
            # execve replaces the process: the finally-close in
            # _measure never runs, so seal the log (header + whatever
            # windows landed) here to keep it schema-valid
            recorder.record_event("reexec", reason="parity compile 500")
            recorder.close()
        if (
            reexec_retry(
                "BENCH_PARITY_ATTEMPT", PARITY_RETRIES, 20.0, __file__
            )
            is not False
        ):  # pragma: no cover — execve does not return
            raise AssertionError("unreachable")
    result["parity_error"] = "%s: %s" % (
        type(exc).__name__,
        str(exc)[:300],
    )
    # actual parity attempts across every process of this run: each
    # re-exec'd predecessor exhausted its full in-process budget (only
    # compile-helper 500s re-exec; other errors break out above)
    result["parity_attempts"] = tries + len(_HELPER_BACKOFFS) * int(
        os.environ.get("BENCH_PARITY_ATTEMPT", "0")
    )
    if recorder is not None:
        result["runlog"] = recorder.path
        recorder.finish(result=result)
    return result


def _reexec_if_cpu_fallback() -> bool:
    """Detect the SILENT tunnel-held mode and retry in a fresh process.

    Two distinct failure modes exist when another client holds the axon
    tunnel: backend init RAISES (handled by the retry loop in main), or
    discovery silently falls back to CPU.  The silent mode is only
    recoverable from a new interpreter (utils.util.reexec_retry).
    Returns True when this process should proceed with a CPU measurement
    (budget exhausted -> marked fallback).
    """
    import jax

    try:
        if jax.devices()[0].platform == "tpu":
            return False
    except Exception:
        return False  # raising mode: main()'s retry loop owns it
    from ringpop_tpu.utils.util import reexec_retry

    reexec_retry("BENCH_REEXEC_ATTEMPT", RETRIES, RETRY_SLEEP_S, __file__)
    return True  # budget exhausted: measure CPU, marked via "fallback"


def main() -> int:
    # repo-pointing PYTHONPATH entries break the axon discovery helper
    # (silent CPU fallback); our own imports ride the script-dir sys.path
    from ringpop_tpu.utils.util import scrub_repo_pythonpath

    scrub_repo_pythonpath(os.path.dirname(os.path.abspath(__file__)))

    # BENCH_MESH_FORCE_HOST=<k>: pin k virtual CPU devices for the mesh
    # phase's weak-scaling ladder BEFORE any backend init (the one
    # routed place — utils.util.pin_cpu_platform; XLA reads the count at
    # first client creation, so this cannot move later).  Implies an
    # intentional CPU run: the forced-host artifact must not be mistaken
    # for a tunnel fallback nor burn the TPU re-exec budget.
    mesh_force = os.environ.get("BENCH_MESH_FORCE_HOST")
    if mesh_force:
        from ringpop_tpu.utils.util import pin_cpu_platform

        pin_cpu_platform(int(mesh_force))
        os.environ.setdefault("BENCH_ALLOW_CPU", "1")

    n = int(os.environ.get("BENCH_N", "1024"))
    # 256-tick measurement window (was 32): the tunneled chip pays a
    # flat ~0.9 s PER EXECUTION in transport/launch overhead regardless
    # of scan length (DIAG_1K.json: 32 ticks -> 0.82 s, 256 ticks ->
    # 0.95 s), so a short window measures the tunnel, not the engine.
    # Both platforms are measured at the same window; the metric is a
    # sustained rate either way (the reference's tick-cluster gossips
    # continuously).
    ticks = int(os.environ.get("BENCH_TICKS", "256"))

    # snapshot BEFORE anything mutates the env: pin_cpu_platform() on the
    # last-resort path writes JAX_PLATFORMS=cpu, which must not be
    # mistaken for a user's intentional CPU pin by the fallback marker —
    # including by RE-EXEC'D children, which inherit the pinned env (the
    # BENCH_PINNED_FALLBACK flag marks bench-made pins across re-execs)
    intentional_cpu = bool(os.environ.get("BENCH_ALLOW_CPU")) or (
        "cpu" in os.environ.get("JAX_PLATFORMS", "")
        and not os.environ.get("BENCH_PINNED_FALLBACK")
    )
    # a bench-made CPU pin (this process's last resort, or inherited by a
    # re-exec'd child) is TERMINAL: the tunnel already exhausted its
    # budget when the pin was made, so children must not burn the re-exec
    # budget re-probing it — they measure CPU and mark the artifact
    pinned_fallback = bool(os.environ.get("BENCH_PINNED_FALLBACK"))
    if not intentional_cpu and not pinned_fallback:
        _reexec_if_cpu_fallback()

    last_err = None
    attempts_made = 0
    total = max(1, RETRIES)
    for attempt in range(total):
        attempts_made = attempt + 1
        try:
            if attempt == total - 1 and total > 1:
                # the TPU tunnel stayed unavailable through every retry —
                # a CPU measurement beats an error artifact.  A failed pin
                # degrades to one more plain attempt (keep the original
                # tunnel error as last_err, not the pin's).
                try:
                    from ringpop_tpu.utils.util import pin_cpu_platform

                    os.environ["BENCH_PINNED_FALLBACK"] = "1"
                    pin_cpu_platform()
                except Exception:
                    pass
            result = _measure(n, ticks)
            result["attempts"] = attempts_made + int(
                os.environ.get("BENCH_REEXEC_ATTEMPT", "0")
            )
            if result.get("platform") != "tpu" and not intentional_cpu:
                # re-read the pin: THIS process may have pinned CPU on
                # its last-resort attempt after the snapshot above
                pinned_fallback = pinned_fallback or bool(
                    os.environ.get("BENCH_PINNED_FALLBACK")
                )
                if not pinned_fallback:
                    # a SILENT mid-loop CPU fallback (an in-process
                    # backend re-init after a transient error can memoize
                    # a failed axon init and quietly hand back CPU) must
                    # not be accepted while fresh-interpreter budget
                    # remains — only a new process can re-attempt the
                    # plugin init.  A PINNED fallback skips this: the pin
                    # itself was the end of the budget, and a re-exec'd
                    # child inherits the pinned env anyway.
                    from ringpop_tpu.utils.util import reexec_retry

                    if (
                        reexec_retry(
                            "BENCH_REEXEC_ATTEMPT", RETRIES, RETRY_SLEEP_S,
                            __file__,
                        )
                        is not False
                    ):  # pragma: no cover — execve does not return
                        raise AssertionError("unreachable")
                # explicit marker: this number is a CPU measurement taken
                # because the TPU tunnel was unavailable (any path: pinned
                # last-resort, exhausted re-exec budget, or a silent
                # mid-loop fallback) — artifact consumers must not mistake
                # it for the TPU headline
                result["fallback"] = "cpu"
            print(json.dumps(result))
            return 0
        except Exception as exc:  # backend init / transient compile errors
            last_err = exc
            if not _is_transient(exc):
                break
            # a FRESH interpreter is the only reliable recovery: JAX
            # memoizes a failed plugin init, and a kernel-faulted TPU
            # worker stays UNAVAILABLE to this process even after
            # clearing backends (RESULTS.md round 4/5) — in-process
            # retries just burn the budget 30 s at a time.  Re-exec
            # while budget remains; fall back to the in-process loop
            # only once it's gone (the pin-CPU last resort still runs).
            from ringpop_tpu.utils.util import clear_jax_backends, reexec_retry

            # the error would otherwise vanish into the execve: record it
            print(
                "bench: transient failure, re-exec (attempt %s): %s: %s"
                % (
                    os.environ.get("BENCH_REEXEC_ATTEMPT", "0"),
                    type(exc).__name__,
                    str(exc)[:300],
                ),
                file=sys.stderr,
                flush=True,
            )
            if (
                reexec_retry(
                    "BENCH_REEXEC_ATTEMPT", RETRIES, RETRY_SLEEP_S, __file__
                )
                is not False
            ):  # pragma: no cover — execve does not return
                raise AssertionError("unreachable")
            clear_jax_backends()
            if attempt + 1 < total:
                time.sleep(RETRY_SLEEP_S)

    print(
        json.dumps(
            {
                "metric": "swim_node_protocol_periods_per_sec_1k",
                "value": 0.0,
                "unit": "node-ticks/s",
                "vs_baseline": 0.0,
                "error": "%s: %s"
                % (type(last_err).__name__, str(last_err)[:400]),
                "attempts": attempts_made
                + int(os.environ.get("BENCH_REEXEC_ATTEMPT", "0")),
            }
        )
    )
    traceback.print_exception(last_err, file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
