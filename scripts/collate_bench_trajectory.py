#!/usr/bin/env python
"""Fold every committed ``BENCH_*.json`` into ``BENCH_TRAJECTORY.json``
— the unified bench trajectory (ISSUE 19 satellite).

Each growth round commits one flat ``BENCH_r<round>[_tag][_backend]``
snapshot; until now nothing read them together, so the repo's headline
numbers had no visible history.  The collator parses round and backend
out of each filename, keeps every numeric metric (bool gates fold to
0/1), groups metrics into their bench phase by name prefix, and writes
one deterministic artifact: per-backend, per-phase metric series keyed
by round.  Metadata strings (cmd, tail, note, runlog paths) and list
payloads stay out — the trajectory tracks numbers.

Usage::

    python scripts/collate_bench_trajectory.py            # gate: committed
                                                          # artifact must match
                                                          # a regeneration
    python scripts/collate_bench_trajectory.py --write    # regenerate
    python scripts/collate_bench_trajectory.py --check    # flag >10%
                                                          # regressions between
                                                          # consecutive rounds
                                                          # (same backend)

The no-argument mode is the eighth ``check_all_budgets.py`` gate: it
exits 1 when the committed trajectory is stale (a new BENCH file landed
without re-running ``--write``) and prints — without failing on — the
``--check`` regression report, so drift is visible on every gate run.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[1]
ARTIFACT = REPO_ROOT / "BENCH_TRAJECTORY.json"

_NAME_RE = re.compile(r"^BENCH_r(\d+)((?:_[a-z0-9]+)*)\.json$")
_BACKENDS = ("cpu", "tpu", "gpu")

# metric-name prefix -> bench phase (first match wins; order matters:
# churn_parity_ before parity_).  Unmatched metrics ride "core" — the
# headline swim numbers and run metadata scalars.
PHASE_PREFIXES = (
    ("churn_parity_", "churn_parity"),
    ("parity_", "parity"),
    ("scalable_", "scalable"),
    ("route_", "route"),
    ("reqtrace_", "reqtrace"),
    ("slo_", "slo"),
    ("ckpt_", "ckpt"),
    ("mesh_", "mesh"),
    ("fuzz_", "fuzz"),
    ("hist_", "hist"),
    ("full_", "full"),
    ("exchange_", "exchange"),
    ("xprof_", "xprof"),
)

# fractional drop (improvement-direction-aware) between consecutive
# rounds of one backend that --check flags
REGRESSION_THRESHOLD = 0.10

# metric-name suffix heuristics for improvement direction: rates and
# throughputs regress DOWN, latencies and overheads regress UP.
# Higher-better is matched FIRST ("..._per_sec" must not fall into the
# "_sec" bucket).  Unmatched metrics — including the round-dependent
# "value"/"elapsed_s" headline scalars, whose meaning shifts with the
# round's bench configuration — are informational and never flagged.
_HIGHER_BETTER = (
    "_per_sec",
    "_mbps",
    "_gbps",
    "_vs_baseline",
    "_efficiency",
    "_equal",
    "_converged",
)
_LOWER_BETTER = ("_ms", "_overhead_frac", "_drops")


def parse_name(name: str):
    """``BENCH_r<round>[_tag...][_backend].json`` -> (round, backend)
    or None for non-matching names.  The backend is the trailing token
    when it names a known platform; earlier rounds committed none, and
    those fold under "unknown"."""
    m = _NAME_RE.match(name)
    if not m:
        return None
    rnd = int(m.group(1))
    tokens = [t for t in m.group(2).split("_") if t]
    backend = tokens[-1] if tokens and tokens[-1] in _BACKENDS else "unknown"
    return rnd, backend


def phase_of(metric: str) -> str:
    for prefix, phase in PHASE_PREFIXES:
        if metric.startswith(prefix):
            return phase
    return "core"


def numeric_metrics(payload: dict) -> dict:
    """The flat numeric view of one BENCH snapshot: ints/floats kept,
    bools folded to 0/1 (the bitwise gate verdicts ARE trajectory
    signal), everything else — strings, lists, nested objects, null —
    dropped."""
    out = {}
    for key, value in payload.items():
        if isinstance(value, bool):
            out[key] = int(value)
        elif isinstance(value, (int, float)):
            out[key] = value
    return out


def collate(root: Path = REPO_ROOT) -> dict:
    """Fold the committed BENCH files into the trajectory structure:
    ``backends.<backend>.rounds`` (sorted, as strings in ``series``
    keys for JSON stability) and ``backends.<backend>.phases.<phase>.
    <metric> = {round: value}``."""
    sources = []
    backends: dict = {}
    for path in sorted(root.glob("BENCH_*.json")):
        if path.name == ARTIFACT.name:
            continue
        parsed = parse_name(path.name)
        if parsed is None:
            continue
        rnd, backend = parsed
        try:
            payload = json.loads(path.read_text(encoding="utf-8"))
        except ValueError:
            continue
        if not isinstance(payload, dict):
            continue
        sources.append(path.name)
        b = backends.setdefault(backend, {"rounds": [], "phases": {}})
        if rnd not in b["rounds"]:
            b["rounds"].append(rnd)
        for metric, value in numeric_metrics(payload).items():
            series = b["phases"].setdefault(phase_of(metric), {})
            series.setdefault(metric, {})[str(rnd)] = value
    for b in backends.values():
        b["rounds"].sort()
    return {
        "generated_by": "scripts/collate_bench_trajectory.py",
        "sources": sources,
        "backends": {k: backends[k] for k in sorted(backends)},
    }


def direction(metric: str):
    """+1 higher-is-better, -1 lower-is-better, None informational."""
    for suffix in _HIGHER_BETTER:
        if metric.endswith(suffix):
            return +1
    for suffix in _LOWER_BETTER:
        if metric.endswith(suffix):
            return -1
    return None


def regressions(trajectory: dict, threshold: float = REGRESSION_THRESHOLD):
    """>threshold moves AGAINST a metric's improvement direction
    between consecutive recorded rounds of the same backend."""
    out = []
    for backend, b in trajectory.get("backends", {}).items():
        for phase, series in b.get("phases", {}).items():
            for metric, points in series.items():
                sign = direction(metric)
                if sign is None:
                    continue
                rounds = sorted(points, key=int)
                for prev, cur in zip(rounds, rounds[1:]):
                    a, z = points[prev], points[cur]
                    if not a:
                        continue
                    delta = sign * (z - a) / abs(a)
                    if delta < -threshold:
                        out.append(
                            {
                                "backend": backend,
                                "phase": phase,
                                "metric": metric,
                                "from_round": int(prev),
                                "to_round": int(cur),
                                "from": a,
                                "to": z,
                                "drop_frac": -delta,
                            }
                        )
    return out


def render(trajectory: dict) -> str:
    return json.dumps(trajectory, indent=2, sort_keys=True) + "\n"


def report_regressions(trajectory: dict, threshold: float) -> int:
    found = regressions(trajectory, threshold)
    for r in found:
        print(
            "REGRESSION %(backend)s %(phase)s.%(metric)s "
            "r%(from_round)d -> r%(to_round)d: %(from)g -> %(to)g "
            "(-%(pct).0f%%)"
            % dict(r, pct=100 * r["drop_frac"])
        )
    return len(found)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--write",
        action="store_true",
        help="(re)write BENCH_TRAJECTORY.json from the committed files",
    )
    parser.add_argument(
        "--check",
        action="store_true",
        help="exit 1 if any metric regressed >threshold between "
        "consecutive rounds of one backend",
    )
    parser.add_argument(
        "--threshold", type=float, default=REGRESSION_THRESHOLD
    )
    args = parser.parse_args(argv)

    trajectory = collate()
    if args.write:
        ARTIFACT.write_text(render(trajectory), encoding="utf-8")
        n = sum(
            len(b["rounds"]) for b in trajectory["backends"].values()
        )
        print(
            "wrote %s (%d snapshots, backends: %s)"
            % (
                ARTIFACT.name,
                n,
                ", ".join(trajectory["backends"]) or "none",
            )
        )
        report_regressions(trajectory, args.threshold)
        return 0
    if args.check:
        found = report_regressions(trajectory, args.threshold)
        print(
            "%d regression(s) above %.0f%%"
            % (found, 100 * args.threshold)
        )
        return 1 if found else 0

    # gate mode: the committed artifact must match a regeneration
    if not ARTIFACT.exists():
        print(
            "%s missing — run scripts/collate_bench_trajectory.py --write"
            % ARTIFACT.name,
            file=sys.stderr,
        )
        return 1
    committed = ARTIFACT.read_text(encoding="utf-8")
    fresh = render(trajectory)
    if committed != fresh:
        print(
            "%s is stale — run scripts/collate_bench_trajectory.py --write"
            % ARTIFACT.name,
            file=sys.stderr,
        )
        return 1
    print(
        "%s: OK (%d source snapshots)"
        % (ARTIFACT.name, len(trajectory["sources"]))
    )
    report_regressions(trajectory, args.threshold)
    return 0


if __name__ == "__main__":
    sys.exit(main())
