#!/usr/bin/env python
"""Diff jit compile counts against the committed retrace manifest.

Runs the jaxgate retrace-budget probes (fresh jitted entry points driven
through a fixed same-shape / different-value / different-shape call
sequence — see ringpop_tpu/analysis/retrace.py) and compares the
observed ``_cache_size()`` sequences to ANALYSIS_BUDGET.json.

Usage::

    python scripts/check_retrace_budget.py          # diff, exit 1 on drift
    python scripts/check_retrace_budget.py --write  # regenerate manifest

The manifest is backend-portable: it records compile COUNTS, not
artifacts, so the next chip session can run this unchanged on the TPU
tunnel and see whether the device build retraces where the CPU build did
not (and vice versa).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from ringpop_tpu.analysis import retrace  # noqa: E402
from ringpop_tpu.analysis.findings import render_text  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--write",
        action="store_true",
        help="run the probes and (re)write ANALYSIS_BUDGET.json",
    )
    parser.add_argument(
        "--budget",
        default=None,
        help="manifest path (default: ANALYSIS_BUDGET.json at repo root)",
    )
    args = parser.parse_args(argv)
    path = Path(args.budget) if args.budget else None

    if args.write:
        actual = retrace.run_probes()
        out = retrace.write_manifest(actual, path)
        total = sum(steps[-1]["cache_size"] for steps in actual.values())
        print(
            f"wrote {out} ({len(actual)} probes, "
            f"{total} budgeted compiles)"
        )
        return 0

    findings = retrace.check_against_manifest(path=path)
    print(render_text(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
