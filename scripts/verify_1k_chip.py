#!/usr/bin/env python3
"""Cross-backend trajectory equality for the 1k full-fidelity engine.

The TPU path differs from CPU in two deliberate ways (one-hot MXU row
selection with Precision.HIGHEST, f32-exact reshuffle mod) — both proven
exact op-level; this drives the whole bench config end-to-end on ONE
backend and dumps the final state's integer digests so a run on the
OTHER backend can be compared bit-for-bit.

Usage:
  env -u JAX_PLATFORMS python scripts/verify_1k_chip.py tpu out_tpu.npz
  python scripts/verify_1k_chip.py cpu out_cpu.npz   (forces CPU)
  python scripts/verify_1k_chip.py compare out_tpu.npz out_cpu.npz
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run(backend: str, out: str) -> int:
    from ringpop_tpu.utils.util import scrub_repo_pythonpath

    scrub_repo_pythonpath(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import jax

    if backend == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import ringpop_tpu  # noqa: F401
    from ringpop_tpu.utils.util import wait_for_tpu

    if backend == "tpu":
        wait_for_tpu(__file__, "VERIFY_1K_ATTEMPT", 90, 20.0)
    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster

    n = 1024
    sim = SimCluster(
        n=n, params=engine.SimParams(n=n, checksum_mode="fast")
    )
    sim.bootstrap()
    sched = EventSchedule(ticks=32, n=n)
    sched.kill[5, 7] = True
    sched.revive[20, 7] = True
    m = sim.run(sched)
    st = sim.state
    np.savez(
        out,
        platform=np.array(jax.devices()[0].platform),
        checksum=np.asarray(st.checksum),
        status=np.asarray(st.status),
        inc=np.asarray(st.inc),
        known=np.asarray(st.known),
        ch_active=np.asarray(st.ch_active),
        perm_inv=np.asarray(st.perm_inv),
        converged=np.asarray(m.converged),
        changes_applied=np.asarray(m.changes_applied),
    )
    print("wrote", out, "platform", jax.devices()[0].platform)
    return 0


def compare(a_path: str, b_path: str) -> int:
    import numpy as np

    a, b = np.load(a_path), np.load(b_path)
    bad = 0
    for k in a.files:
        if k == "platform":
            continue
        ok = (a[k] == b[k]).all()
        print(k, "OK" if ok else "MISMATCH %d" % int((a[k] != b[k]).sum()))
        bad += not ok
    print("platforms:", a["platform"], b["platform"])
    return 1 if bad else 0


if __name__ == "__main__":
    mode = sys.argv[1]
    if mode == "compare":
        sys.exit(compare(sys.argv[2], sys.argv[3]))
    sys.exit(run(mode, sys.argv[2]))
