#!/usr/bin/env python
"""Diff the donation/aliasing surface of the donating jitted drivers
against DONATION_BUDGET.json — the static half of the PR-7/PR-8
donation-hazard defenses.

Compiles every donating driver (storm tick/scan, routed tick/scan, the
sharded storm tick) at toy shapes and compares the executables'
``input_output_alias`` maps to the committed manifest (see
ringpop_tpu/analysis/donation.py).  A donated leaf no output aliases is
a silently dropped donation and ALWAYS a finding; the CPU manifest pins
the PR-8 donation-off backend gate as expected-empty alias maps.

Usage::

    python scripts/check_donation_budget.py            # diff, exit 1 on drift
    python scripts/check_donation_budget.py --write    # regenerate manifest
    python scripts/check_donation_budget.py --entries scalable-tick,routed-tick

``--write`` REFUSES to commit a manifest containing entries that failed
to compile or that drop donations — a broken or lossy donation surface
is a finding, not a budget.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from ringpop_tpu.analysis import donation  # noqa: E402
from ringpop_tpu.analysis.findings import render_text  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--write",
        action="store_true",
        help="compile the donating drivers and (re)write DONATION_BUDGET.json",
    )
    parser.add_argument(
        "--budget",
        default=None,
        help="manifest path (default: DONATION_BUDGET.json at repo root)",
    )
    parser.add_argument(
        "--entries",
        default=None,
        help="comma-separated entry-name subset (diff mode only)",
    )
    args = parser.parse_args(argv)
    path = Path(args.budget) if args.budget else None
    names = (
        [n.strip() for n in args.entries.split(",") if n.strip()]
        if args.entries
        else None
    )

    if args.write:
        if names is not None:
            parser.error("--write regenerates the FULL manifest; drop --entries")
        actual = donation.collect()
        out = donation.write_manifest(actual, path)
        donated = sum(e.get("donated_params", 0) for e in actual.values())
        aliased = sum(e.get("aliased_params", 0) for e in actual.values())
        print(
            "wrote %s (%d entries, %d donated / %d aliased params)"
            % (out, len(actual), donated, aliased)
        )
        return 0

    findings = donation.check_against_manifest(entry_names=names, path=path)
    print(render_text(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
