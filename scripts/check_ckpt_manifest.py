#!/usr/bin/env python
"""Validate checkpoint manifests: schema + full digest re-verify.

The recovery plane's CI gate (the checkpoint twin of
``check_metrics_schema.py``): every manifest-format checkpoint directory
found under the given paths (default: the repo root, which covers the
committed ``runlogs/sample_ckpt_*`` artifact so the gate is never
vacuous) must

- parse as a current-version manifest (``ringpop-tpu-ckpt`` v1, engine
  state format v2),
- list every array file it digests, with each file present at its exact
  recorded size and whole-file CRC32,
- hold per-array content digests that re-verify against the stored
  bytes (sharded fields per shard piece).

Runs standalone::

    python scripts/check_ckpt_manifest.py [paths...]
    python scripts/check_ckpt_manifest.py --repair-scan <family-dir>

``--repair-scan`` is the operator's recovery preview: scan a checkpoint
FAMILY directory (``ckpt-<tick>`` children, as the drivers'
``enable_checkpoints`` lays out) newest-first and report which
checkpoints are salvageable and which are corrupt (with the named
error) — exactly the fallback order ``restore_latest()`` would take.
Inside the tier-1 suite via tests/models/test_ckpt_validator.py, which
calls the same entry points.
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# directories never worth descending into (virtualenv-ish, caches)
_SKIP_DIRS = {".git", "__pycache__", ".jax_cache", ".pytest_cache", "node_modules"}


def find_checkpoints(paths=None) -> list:
    """Every directory holding a ``manifest.json`` under ``paths``
    (default: repo root).  A path that IS a checkpoint dir is returned
    as itself."""
    from ringpop_tpu.models.sim.checkpoint import MANIFEST_NAME

    out = []
    for root in paths or [REPO_ROOT]:
        root = os.path.abspath(root)
        if os.path.isfile(os.path.join(root, MANIFEST_NAME)):
            out.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            if MANIFEST_NAME in filenames:
                out.append(dirpath)
    return sorted(out)


def check(paths, verbose: bool = True) -> list:
    """Deep-verify each checkpoint dir; returns human-readable problems
    (empty == all valid)."""
    from ringpop_tpu.models.sim import checkpoint as ckpt

    problems = []
    for path in paths:
        try:
            manifest = ckpt.verify_checkpoint(path, deep=True)
        except ckpt.CheckpointError as e:
            problems.append("%s: %s: %s" % (path, type(e).__name__, e))
            continue
        if verbose:
            states = ",".join(
                "%s=%s" % (k, v["class"])
                for k, v in sorted(manifest["states"].items())
            )
            print(
                "ok   %s (%s; shards=%d, %d bytes)"
                % (path, states, manifest["shards"], manifest["nbytes"])
            )
    return problems


def repair_scan(family_dir: str, verbose: bool = True) -> dict:
    """Newest-first salvageability report over a checkpoint family.

    Returns ``{"valid": [(tick, path)...], "corrupt": [(tick, path,
    error)...], "resume_from": (tick, path) | None}`` — ``resume_from``
    is what ``CheckpointManager.restore_latest`` would pick."""
    from ringpop_tpu.models.sim import checkpoint as ckpt
    from ringpop_tpu.models.sim import recovery

    entries = []
    for entry in sorted(os.listdir(family_dir)):
        m = recovery._CKPT_RE.match(entry)
        if m is not None:
            entries.append((int(m.group(1)), os.path.join(family_dir, entry)))
    valid, corrupt = [], []
    for tick, path in reversed(entries):
        try:
            ckpt.verify_checkpoint(path, deep=True)
        except ckpt.CheckpointError as e:
            corrupt.append((tick, path, "%s: %s" % (type(e).__name__, e)))
            if verbose:
                print("corrupt tick=%d %s (%s)" % (tick, path, type(e).__name__))
            continue
        valid.append((tick, path))
        if verbose:
            print("valid   tick=%d %s" % (tick, path))
    resume_from = valid[0] if valid else None
    if verbose:
        if resume_from:
            print(
                "resume_from tick=%d %s (%d valid, %d corrupt)"
                % (resume_from[0], resume_from[1], len(valid), len(corrupt))
            )
        else:
            print(
                "resume_from NONE — clean restart (%d corrupt)" % len(corrupt)
            )
    return {"valid": valid, "corrupt": corrupt, "resume_from": resume_from}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("paths", nargs="*", help="checkpoint dirs or roots to scan")
    p.add_argument(
        "--repair-scan",
        metavar="FAMILY_DIR",
        default=None,
        help="salvageability report over a ckpt-<tick> family directory",
    )
    p.add_argument("-q", "--quiet", action="store_true")
    args = p.parse_args(argv)

    if args.repair_scan:
        report = repair_scan(args.repair_scan, verbose=not args.quiet)
        # a family with corrupt entries still exits 0 when something is
        # salvageable — that IS the recovery contract; exit 1 only when
        # checkpoints exist but none survive
        if report["corrupt"] and not report["valid"]:
            return 1
        return 0

    ckpts = find_checkpoints(args.paths or None)
    if not args.quiet:
        print("checking %d checkpoint dir(s)" % len(ckpts))
    problems = check(ckpts, verbose=not args.quiet)
    for prob in problems:
        print("PROBLEM %s" % prob)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
