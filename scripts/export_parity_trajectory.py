#!/usr/bin/env python3
"""Export a FULL-TRAJECTORY Node-parity artifact (PARITY_TRAJECTORY.json).

Where PARITY_REPLAY.json validates static membership views at a handful
of checkpoints, this artifact carries a scripted 1k tick-cluster
schedule and, for EVERY tick, the checksum-group view the reference's
tick-cluster harness prints (scripts/tick-cluster.js:87-114 groups live
nodes by membership checksum) — plus, per group, one representative
observer's complete membership view.  A single `node
validate_trajectory.js PARITY_TRAJECTORY.json` run on any Node machine
(scripts/replay_node.md) then proves, per tick, that every represented
group's checksum is exactly `farmhash.hash32` of ringpop-node's
`generateChecksumString` over a real view — the per-tick checksum
SEQUENCE of the trajectory, not just isolated snapshots.

Groups beyond --max-groups per tick (early-dissemination ticks can have
hundreds of one-node groups) carry counts but no representative view;
the artifact records how many member-bytes went unrepresented so the
coverage is explicit.  Converged ticks (one group) are always fully
covered.

Usage: python scripts/export_parity_trajectory.py [-n 1024] [--ticks 36]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STATUS_STR = {0: "alive", 1: "suspect", 2: "faulty", 3: "leave"}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="export-parity-trajectory")
    p.add_argument("-n", type=int, default=1024)
    p.add_argument("--ticks", type=int, default=42)  # reconverges at 39
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max-groups", type=int, default=2)
    p.add_argument("--output", "-o", default="PARITY_TRAJECTORY.json")
    args = p.parse_args(argv)

    from ringpop_tpu.utils.util import pin_cpu_platform

    pin_cpu_platform()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.cluster import default_addresses
    from ringpop_tpu.ops import checksum_encode as ce

    n = args.n
    params = engine.SimParams(
        n=n, checksum_mode="farmhash", suspicion_ticks=6
    )
    addresses = default_addresses(n)
    universe = ce.Universe.from_addresses(addresses)
    state = engine.init_state(params, seed=args.seed, universe=universe)
    tick = jax.jit(lambda s, i: engine.tick(s, i, params, universe))

    rng = np.random.default_rng(args.seed)
    victims = [int(v) for v in rng.choice(n, size=4, replace=False)]
    # the scripted schedule (recorded in the artifact): bootstrap ->
    # kill wave -> suspects -> faulties -> revive -> reconverge
    schedule = {0: {"join": "all"}, 8: {"kill": victims[:2]},
                20: {"revive": victims[:2]}, 24: {"kill": victims[2:]}}

    ticks_out = []
    total_unrepresented = 0
    for t in range(args.ticks):
        inputs = engine.TickInputs.quiet(n)
        ev = schedule.get(t, {})
        if ev.get("join") == "all":
            inputs = inputs._replace(join=jnp.ones(n, bool))
        if "kill" in ev:
            kill = np.zeros(n, bool)
            kill[ev["kill"]] = True
            inputs = inputs._replace(kill=jnp.asarray(kill))
        if "revive" in ev:
            rv = np.zeros(n, bool)
            rv[ev["revive"]] = True
            inputs = inputs._replace(revive=jnp.asarray(rv))
        state, m = tick(state, inputs)

        checksums = np.asarray(state.checksum)
        part = np.asarray(state.proc_alive) & np.asarray(state.ready)
        groups: dict = {}
        for i in np.flatnonzero(part):
            groups.setdefault(int(checksums[i]), []).append(int(i))
        ordered = sorted(
            groups.items(), key=lambda kv: (-len(kv[1]), kv[0])
        )
        known = status = inc_ms = None
        entry_groups = []
        for gi, (cs, members_idx) in enumerate(ordered):
            g = {"checksum": cs, "count": len(members_idx)}
            if gi < args.max_groups:
                if known is None:
                    known = np.asarray(state.known)
                    status = np.asarray(state.status)
                    inc_ms = np.asarray(engine.stamp_to_ms(state.inc, params))
                o = members_idx[0]
                g["representative"] = {
                    "observer": addresses[o],
                    # compact member triples: [address, status, incMs]
                    "members": [
                        [
                            addresses[j],
                            STATUS_STR[int(status[o, j])],
                            int(inc_ms[o, j]),
                        ]
                        for j in range(n)
                        if known[o, j]
                    ],
                }
            else:
                total_unrepresented += len(members_idx)
            entry_groups.append(g)
        ticks_out.append(
            {
                "tick": t,
                "distinct_checksums": len(ordered),
                "groups": entry_groups,
            }
        )

    converged = ticks_out[-1]["distinct_checksums"] == 1
    assert converged, "trajectory must reconverge by its last tick"
    out = {
        "description": (
            "Full-trajectory membership-checksum parity vs ringpop-node: "
            "per tick, live nodes grouped by checksum (the tick-cluster "
            "convergence view, scripts/tick-cluster.js:87-114); each "
            "represented group's checksum must equal farmhash.hash32 of "
            "generateChecksumString (lib/membership/index.js:101-123 — "
            "sort members by address, concat "
            "address+status+incarnationNumber, join ';') over the "
            "representative view.  Member triples are "
            "[address, status, incarnationNumber]."
        ),
        "generator": "scripts/export_parity_trajectory.py",
        "validator": "scripts/replay_node.md (validate_trajectory.js)",
        "n": n,
        "ticks": args.ticks,
        "seed": args.seed,
        "schedule": {str(k): v for k, v in schedule.items()},
        "max_groups_represented_per_tick": args.max_groups,
        "unrepresented_group_nodes_total": total_unrepresented,
        "ticks_data": ticks_out,
    }
    with open(args.output, "w") as f:
        json.dump(out, f, separators=(",", ":"))
    print(
        json.dumps(
            {
                "ticks": len(ticks_out),
                "final_distinct": ticks_out[-1]["distinct_checksums"],
                "unrepresented_total": total_unrepresented,
                "bytes": os.path.getsize(args.output),
                "output": args.output,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
