#!/usr/bin/env python
"""Run every committed-manifest gate in one shot with a summary table.

ISSUE 18 satellite: the repo now has eight chip-free gates, each a
standalone ``scripts/check_*.py`` diffing live analysis against a
committed artifact (or validating committed artifacts in place).  This
driver runs them all (subprocesses: each gate owns its JAX state, same
isolation CI gives them), prints one PASS/FAIL table with wall time,
and exits non-zero if ANY gate failed — the single pre-push command::

    python scripts/check_all_budgets.py            # all gates
    python scripts/check_all_budgets.py --only cost,scale
    python scripts/check_all_budgets.py --list
    python scripts/check_all_budgets.py --verbose  # stream gate output

Gate output is captured and only replayed for FAILING gates (or with
``--verbose``), so a clean run is one table.
"""

from __future__ import annotations

import argparse
import subprocess
import sys
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[1]

# name -> script; every entry supports a no-argument invocation that
# exits 0 iff its committed artifact matches the tree
GATES = (
    ("retrace", "check_retrace_budget.py"),
    ("cost", "check_cost_budget.py"),
    ("donation", "check_donation_budget.py"),
    ("scale", "check_scale_budget.py"),
    ("metrics-schema", "check_metrics_schema.py"),
    ("ckpt-manifest", "check_ckpt_manifest.py"),
    ("traffic-model", "check_traffic_model.py"),
    ("bench-trajectory", "collate_bench_trajectory.py"),
)


def run_gate(script: str, verbose: bool) -> tuple:
    cmd = [sys.executable, str(REPO_ROOT / "scripts" / script)]
    t0 = time.monotonic()
    proc = subprocess.run(
        cmd,
        cwd=REPO_ROOT,
        capture_output=not verbose,
        text=True,
    )
    dt = time.monotonic() - t0
    out = "" if verbose else (proc.stdout or "") + (proc.stderr or "")
    return proc.returncode, dt, out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--only",
        default=None,
        help="comma list of gate names (see --list)",
    )
    parser.add_argument(
        "--list", action="store_true", help="print gate names and exit"
    )
    parser.add_argument(
        "--verbose",
        action="store_true",
        help="stream every gate's output instead of capturing",
    )
    args = parser.parse_args(argv)

    by_name = dict(GATES)
    if args.list:
        for name, script in GATES:
            print(f"{name:16s} scripts/{script}")
        return 0
    names = [n for n, _ in GATES]
    if args.only:
        names = [n.strip() for n in args.only.split(",") if n.strip()]
        unknown = [n for n in names if n not in by_name]
        if unknown:
            parser.error(f"unknown gate(s): {unknown}")

    results = []
    for name in names:
        if args.verbose:
            print(f"=== {name} (scripts/{by_name[name]})", flush=True)
        rc, dt, out = run_gate(by_name[name], args.verbose)
        results.append((name, rc, dt, out))
        if rc != 0 and not args.verbose:
            print(f"=== {name} FAILED (scripts/{by_name[name]})")
            print(out.rstrip())

    width = max(len(n) for n in names)
    print()
    print(f"{'gate':<{width}}  result  seconds")
    for name, rc, dt, _ in results:
        print(f"{name:<{width}}  {'PASS' if rc == 0 else 'FAIL':6s}  {dt:7.1f}")
    failed = [name for name, rc, _, _ in results if rc != 0]
    if failed:
        print(f"\n{len(failed)} gate(s) failed: {', '.join(failed)}")
        return 1
    print(f"\nall {len(results)} gates pass")
    return 0


if __name__ == "__main__":
    sys.exit(main())
