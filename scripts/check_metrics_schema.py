#!/usr/bin/env python
"""Validate every JSONL run log in the repo against the recorder schema.

The telemetry layer's CI gate: any ``*.runlog.jsonl`` under the repo
root (committed artifacts in runlogs/, stray logs from local runs) must
parse against ``obs.recorder``'s schema — one JSON object per line, a
leading header row with the current schema version, monotonically
increasing tick indices.  Runs standalone::

    python scripts/check_metrics_schema.py [paths...]

and inside the tier-1 suite via tests/obs/test_runlog_schema.py, which
calls the same entry point.
"""

from __future__ import annotations

import glob
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def find_run_logs(root: str = REPO_ROOT) -> list:
    return sorted(
        glob.glob(os.path.join(root, "**", "*.runlog.jsonl"), recursive=True)
    )


def check(paths=None, verbose: bool = True) -> list:
    """Returns the list of problems across all logs (empty == all valid)."""
    from ringpop_tpu.obs.recorder import validate_run_log

    paths = list(paths) if paths else find_run_logs()
    problems = []
    for path in paths:
        found = validate_run_log(path)
        problems.extend(found)
        if verbose:
            status = "OK" if not found else "%d problem(s)" % len(found)
            print("%s: %s" % (os.path.relpath(path, REPO_ROOT), status))
    return problems


def main(argv) -> int:
    sys.path.insert(0, REPO_ROOT)
    paths = argv[1:] or None
    if paths is None and not find_run_logs():
        print("no *.runlog.jsonl files found under %s" % REPO_ROOT)
        return 0
    problems = check(paths)
    for p in problems:
        print(p, file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
