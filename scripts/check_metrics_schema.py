#!/usr/bin/env python
"""Validate every JSONL run log AND trace sidecar in the repo.

The telemetry layer's CI gate: any ``*.runlog.jsonl`` under the repo
root (committed artifacts in runlogs/, stray logs from local runs) must
parse against ``obs.recorder``'s schema — one JSON object per line, a
leading header row with the current schema version, monotonically
increasing tick indices.  Any ``*.trace.json`` flight-recorder sidecar
(obs.chrome_trace) must parse against the Trace Event Format schema,
and every ``trace_sidecar`` event row inside a runlog must point at a
file that exists next to it.  Runs standalone::

    python scripts/check_metrics_schema.py [paths...]

and inside the tier-1 suite via tests/obs/test_runlog_schema.py, which
calls the same entry point.
"""

from __future__ import annotations

import glob
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Routing-plane schema (round 11): a tick row that carries ANY route_*
# field must carry the full RouteMetrics counter set — a partial row
# means the recorder and the engine's RouteMetrics drifted.  Kept in
# lockstep with models/route/plane.RouteMetrics._fields by
# tests/obs/test_runlog_schema.py.
ROUTE_TICK_FIELDS = frozenset(
    {
        "route_queries",
        "route_misroutes",
        "route_reroute_local",
        "route_reroute_remote",
        "route_keys_diverged",
        "route_checksums_differ",
        "route_checksum_rejects",
        "route_ring_changed",
        "route_ring_dirty_buckets",
        "route_ring_full_rebuilds",
        "route_ring_points",
    }
)
# event rows announcing a measured routing window must identify the ring
# implementation and the workload shape
ROUTE_EVENT_FIELDS = {
    "route_window": ("ring_impl", "n", "q"),
    "route_rebuild_ab": ("n", "incremental_ms", "full_sort_ms"),
    # recovery-plane lifecycle rows (models/sim/recovery.py, round 13):
    # every save/corrupt/resume must be attributable to a tick + artifact
    "ckpt.saved": ("tick", "path", "nbytes", "shards", "wall_s"),
    "ckpt.corrupt": ("tick", "path", "error"),
    "ckpt.resumed": ("tick", "path", "skipped_corrupt"),
    "ckpt_window": ("n", "every", "overhead_frac", "save_mbps_single"),
    # round-14 mesh plane events: every weak-scaling rung names its
    # shard count + resolved exchange mode, the summary row carries the
    # efficiency AND the bitwise gate verdict, and the resolution note
    # (the observable replacement for the PR-5 silent drop-to-XLA) is
    # attributable to a requested mode + shard count
    "mesh_window": (
        "n",
        "shards",
        "ticks",
        "exchange_mode",
        "node_ticks_per_sec",
    ),
    "weak_scaling": (
        "n_per_shard",
        "shards",
        "node_ticks_per_sec",
        "efficiency",
        "bitwise_equal",
    ),
    "mesh_exchange_resolution": (
        "requested",
        "mode",
        "impl",
        "shards",
        "single_device_resolution",
        "differs_from_single_device",
    ),
    # round-15 performance observatory: every host phase-timing row
    # names its phase, wall and call count (obs/perf.py DispatchTimer /
    # timed_window), and every device-histogram drain names its source
    # plane and carries the per-track summaries (obs/histograms.py
    # drain_row — tracks is a dict of {count, p50, p95, p99, ...})
    "perf.phase": ("phase", "wall_s", "calls"),
    "hist.drain": ("source", "tracks"),
    # round-16 kernel toolkit: every backend-resolved fused-op knob is
    # an observable event row (ops.toolkit.resolution_note — the
    # single-device generalization of mesh_exchange_resolution)
    "op_resolution": (
        "knob",
        "requested",
        "impl",
        "backend",
        "single_device_resolution",
        "differs_from_single_device",
    ),
    # round-16 fused full-fidelity tick: every measured A/B window of
    # the full-engine ladder names its size, tick mode, and the bitwise
    # gate verdict
    "full_window": (
        "n",
        "ticks",
        "fused_tick",
        "node_ticks_per_sec",
        "bitwise_equal",
    ),
    # round-17 mesh observatory: every per-shard exchange drain row
    # carries the full ExchangeMetrics counter set plus the window's
    # identity — kept in lockstep with ops.exchange.ExchangeMetrics and
    # obs.exchange_stats.EXCHANGE_DRAIN_EXTRAS by
    # tests/obs/test_runlog_schema.py
    "mesh.exchange.drain": (
        "source",
        "shards",
        "w",
        "cap",
        "local_rows",
        "shard",
        "ticks",
        "a2a_pull",
        "a2a_push",
        "fallback_pull",
        "fallback_push",
        "pull_rows",
        "push_rows",
        "dest_shards_pull",
        "dest_shards_push",
        "wire_bytes_pull",
        "wire_bytes_push",
    ),
    # measured-vs-model reconciliation rows (obs.exchange_stats.reconcile
    # + a source tag): both byte totals must ship so a logged window is
    # auditable without rerunning the storm
    "traffic_reconcile": (
        "source",
        "shards",
        "n",
        "w",
        "cap",
        "ticks",
        "measured_interconnect",
        "model_interconnect",
        "ratio",
        "fallback_trips",
    ),
    # round-19 request observatory: every drained request-trace window
    # names its sampling configuration and carries the sampled-subset
    # counter object (obs.requests.drain_row — counts is a dict holding
    # every obs.requests.COUNT_FIELDS key, checked below); every SLO
    # window row carries the windowed health verdict, and every breach
    # names its violated clauses.  Field sets are kept in lockstep with
    # obs/requests.py and obs/slo.py by tests/obs/test_runlog_schema.py.
    "reqtrace.drain": (
        "source",
        "records",
        "drops",
        "cap",
        "sample_log2",
        "counts",
    ),
    "slo.window": (
        "target",
        "tick",
        "window_ticks",
        "windows",
        "queries",
        "errors",
        "success_rate",
        "burn_rate",
        "breach",
        "breach_reason",
    ),
    "slo.breach": (
        "target",
        "tick",
        "window_ticks",
        "reason",
        "burn_rate",
        "success_rate",
    ),
    # profiler capture rows (obs.xprof.XPROF_FIELDS — pinned by
    # tests/obs/test_runlog_schema.py): every capture names its phase
    # and trace artifact even when the capture itself failed (ok=False)
    "xprof.capture": (
        "phase",
        "ok",
        "wall_s",
        "trace_dir",
        "num_trace_files",
        "total_self_us",
        "ops",
    ),
}


# static copies of the decoder's registries (the checker must not import
# the package — it validates artifacts standalone); lockstep pinned to
# obs.requests.COUNT_FIELDS / obs.slo.WINDOW_QS by
# tests/obs/test_runlog_schema.py
REQTRACE_COUNT_FIELDS = (
    "queries",
    "misroutes",
    "reroute_local",
    "reroute_remote",
    "keys_diverged",
    "checksums_differ",
    "checksum_rejects",
)
SLO_WINDOW_QS = (50, 95, 99)


def _check_reqtrace_drain(row: dict, path: str, ln: int) -> list:
    """reqtrace.drain rows: the counts object must carry every
    sampled-subset counter the decoder reconciles against."""
    problems = []
    counts = row.get("counts")
    if not isinstance(counts, dict):
        if "counts" in row:
            problems.append(
                "%s:%d: reqtrace.drain counts must be an object"
                % (path, ln)
            )
        return problems
    for field in REQTRACE_COUNT_FIELDS:
        if field not in counts:
            problems.append(
                "%s:%d: reqtrace.drain counts missing %r"
                % (path, ln, field)
            )
    return problems


def _check_slo_window(row: dict, path: str, ln: int) -> list:
    """slo.window rows: every windowed percentile key must be present
    (None for an empty window is valid)."""
    problems = []
    for q in SLO_WINDOW_QS:
        if "p%d" % q not in row:
            problems.append(
                "%s:%d: slo.window row missing %r" % (path, ln, "p%d" % q)
            )
    return problems


def _check_hist_drain(row: dict, path: str, ln: int) -> list:
    """hist.drain rows: per-track summaries must carry count + the
    p50/p95/p99 keys (None for empty tracks is valid)."""
    problems = []
    tracks = row.get("tracks")
    if not isinstance(tracks, dict):
        if "tracks" in row:
            problems.append(
                "%s:%d: hist.drain tracks must be an object" % (path, ln)
            )
        return problems
    for name, stats in tracks.items():
        if not isinstance(stats, dict):
            problems.append(
                "%s:%d: hist.drain track %r must be an object"
                % (path, ln, name)
            )
            continue
        for field in ("count", "p50", "p95", "p99"):
            if field not in stats:
                problems.append(
                    "%s:%d: hist.drain track %r missing %r"
                    % (path, ln, name, field)
                )
    return problems


def _check_route_rows(path: str) -> list:
    """Routing-plane runlog validation: complete route_* tick rows and
    well-formed route event rows."""
    problems = []
    with open(path, encoding="utf-8") as fh:
        for ln, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue  # validate_run_log already reports this
            if not isinstance(row, dict):
                continue
            if row.get("kind") == "tick" and isinstance(
                row.get("metrics"), dict
            ):
                keys = set(row["metrics"])
                if any(k.startswith("route_") for k in keys):
                    missing = ROUTE_TICK_FIELDS - keys
                    if missing:
                        problems.append(
                            "%s:%d: route tick row missing %s"
                            % (path, ln, ", ".join(sorted(missing)))
                        )
            elif row.get("kind") == "event":
                need = ROUTE_EVENT_FIELDS.get(row.get("name"))
                if need:
                    for field in need:
                        if field not in row:
                            problems.append(
                                "%s:%d: %s event missing %r"
                                % (path, ln, row["name"], field)
                            )
                if row.get("name") == "hist.drain":
                    problems.extend(_check_hist_drain(row, path, ln))
                elif row.get("name") == "reqtrace.drain":
                    problems.extend(_check_reqtrace_drain(row, path, ln))
                elif row.get("name") == "slo.window":
                    problems.extend(_check_slo_window(row, path, ln))
    return problems


def find_run_logs(root: str = REPO_ROOT) -> list:
    return sorted(
        glob.glob(os.path.join(root, "**", "*.runlog.jsonl"), recursive=True)
    )


def find_trace_sidecars(root: str = REPO_ROOT) -> list:
    return sorted(
        glob.glob(os.path.join(root, "**", "*.trace.json"), recursive=True)
    )


def _check_sidecar_links(path: str) -> list:
    """Every trace_sidecar event row in a runlog must reference a file
    that exists next to the log (the pair ships together)."""
    problems = []
    logdir = os.path.dirname(os.path.abspath(path))
    with open(path, encoding="utf-8") as fh:
        for ln, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue  # validate_run_log already reports this
            if (
                isinstance(row, dict)
                and row.get("kind") == "event"
                and row.get("name") == "trace_sidecar"
            ):
                ref = row.get("path")
                if not isinstance(ref, str):
                    problems.append(
                        "%s:%d: trace_sidecar row missing path" % (path, ln)
                    )
                elif not os.path.exists(os.path.join(logdir, ref)):
                    problems.append(
                        "%s:%d: trace_sidecar points at missing file %r"
                        % (path, ln, ref)
                    )
    return problems


def check(paths=None, verbose: bool = True) -> list:
    """Returns the list of problems across all logs and sidecars (empty
    == all valid)."""
    from ringpop_tpu.obs.chrome_trace import validate_chrome_trace
    from ringpop_tpu.obs.recorder import validate_run_log

    if paths:
        paths = list(paths)
    else:
        paths = find_run_logs() + find_trace_sidecars()
    problems = []
    for path in paths:
        if path.endswith(".trace.json"):
            try:
                with open(path, encoding="utf-8") as fh:
                    trace = json.load(fh)
            except ValueError as e:
                found = ["%s: not JSON (%s)" % (path, e)]
            else:
                found = ["%s: %s" % (path, p) for p in validate_chrome_trace(trace)]
        else:
            found = validate_run_log(path)
            found.extend(_check_sidecar_links(path))
            found.extend(_check_route_rows(path))
        problems.extend(found)
        if verbose:
            status = "OK" if not found else "%d problem(s)" % len(found)
            print("%s: %s" % (os.path.relpath(path, REPO_ROOT), status))
    return problems


def main(argv) -> int:
    sys.path.insert(0, REPO_ROOT)
    paths = argv[1:] or None
    if paths is None and not (find_run_logs() or find_trace_sidecars()):
        print(
            "no *.runlog.jsonl or *.trace.json files found under %s"
            % REPO_ROOT
        )
        return 0
    problems = check(paths)
    for p in problems:
        print(p, file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
