#!/usr/bin/env python3
"""Roofline the fused push-pull exchange op: bytes moved vs bandwidth.

The round-10 companion to scripts/prof_parity_roofline.py, applied to
ops/exchange.py — the megakernel that fuses the scalable engine's
push-pull OR, new-bit diff, popcount, and checksum delta-sum into one
pass over the [N, U/32] heard mask.  For each measured shape the
artifact records:

1. ms per exchange step (in-scan window — no per-call dispatch in the
   number) for the Pallas kernel (interpret mode off-TPU, marked) and
   the pure-XLA twin;
2. a MODELED bytes-moved lower bound, itemized: the op's contract is
   3 mask reads (heard + the two partner-row planes the engine gathers)
   + 1 mask write + the [N] delta/count outputs; the delta table is
   negligible.  A lower bound because fusion can only reduce traffic
   below it — achieved GB/s is conservative;
3. the derived GB/s, and — the comparison the megakernel exists to win —
   the UNFUSED bytes model: separate OR / diff / popcount / delta
   passes, each materializing its [N, U/32] temporary (and the delta
   reduction's 32x bit expansion) through HBM.  ``fusion_traffic_ratio``
   = unfused bytes / fused bytes: the per-tick traffic multiple the
   fused op removes at identical arithmetic.

Round 14 adds the CROSS-SHARD traffic model (item 4 per shape): for the
shard_map'd exchange plane (parallel/mesh.py), the modeled bytes per
tick that cross the interconnect — ICI within a slice, DCN across hosts
— versus the bytes that stay shard-local in the fused kernel pass, from
the ONE shared model (ops.exchange.cross_shard_traffic_bytes: two
all_to_all directions at the static cap, the (S-1)/S cross fraction,
plus the position planes).  ``cross_to_local_ratio`` < 1 means the plane
is local-bandwidth-bound (the kernel still dominates); >> 1 means
interconnect-bound and the cap/slack sizing is the lever.

Round 17 (the mesh observatory) adds MEASURED columns next to the model:
``cross_shard_measured`` runs the sharded storm on small forced-host-
device meshes with the exchange telemetry plane on
(ScalableParams.exchange_metrics), drains the per-shard wire counters,
and reports measured interconnect bytes / ratio-to-model from the SAME
reconciliation path the traffic gate checks
(obs.exchange_stats.reconcile; scripts/check_traffic_model.py) — the
(S-1)/S cross-fraction claim as a number observed on the wire, not just
derived from it.

Writes PROF_EXCHANGE_ROOFLINE.json; CPU runs are explicitly marked
(platform + peak_gbps null, interpret flag on the pallas rows) so nobody
mistakes them for chip numbers.  PROF_ROOFLINE_FORCE_CPU=1 skips the TPU
wait on tunnel-less images.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the measured cross-shard rows need a multi-device mesh: force the
# host-platform split before jax initializes (no-op for a TPU backend;
# same lever as tests/conftest.py and scripts/check_traffic_model.py).
# The flag spelling lives in utils/util.force_host_device_count alone
# (round 14); loaded by FILE PATH because the package import pulls jax.
if "jax" not in sys.modules:
    import importlib.util as _ilu

    _spec = _ilu.spec_from_file_location(
        "_ringpop_util_boot",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "ringpop_tpu",
            "utils",
            "util.py",
        ),
    )
    _util_boot = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(_util_boot)
    if (
        "xla_force_host_platform_device_count"
        not in os.environ.get("XLA_FLAGS", "")
        and "JAX_NUM_CPU_DEVICES" not in os.environ
    ):
        _util_boot.force_host_device_count(8)

OUT = os.environ.get("PROF_EXCHANGE_OUT", "PROF_EXCHANGE_ROOFLINE.json")
# v5e-class chip HBM peak; only attached to TPU measurements
TPU_PEAK_GBPS = 819.0
ITERS = int(os.environ.get("PROF_EXCHANGE_ITERS", "16"))


def _bytes_models(n: int, w: int) -> dict:
    """Itemized per-step traffic models, fused vs the XLA phase-by-phase
    lowering the op replaced (engine_scalable's round-4 notes).  The
    fused total is the SHARED model (ops.exchange.step_traffic_bytes —
    the itemization here must sum to it; asserted) so this artifact
    stays comparable with bench.py and tpu_measure.py."""
    from ringpop_tpu.ops import exchange as exch

    mask = n * w * 4
    fused = {
        "mask_reads_3x": 3 * mask,  # heard + pulled + pushed planes
        "mask_write_1x": mask,  # new_heard
        "row_outputs": 2 * n * 4,  # [N] delta + [N] count
    }
    assert sum(fused.values()) == exch.step_traffic_bytes(n, w)
    unfused = {
        # new = heard | pulled | pushed: 3 reads + 1 write
        "or_pass": 4 * mask,
        # diff = new ^ heard: 2 reads + 1 write
        "diff_pass": 3 * mask,
        # popcount(diff) -> [N]: 1 read + output
        "popcount_pass": mask + n * 4,
        # bits @ limbs delta reduction: the diff's 32x bit expansion
        # materializes [N, U] through HBM (write + read) + the diff read
        "delta_bit_expansion": mask + 2 * n * w * 32,
    }
    return {
        "fused": fused,
        "fused_total": sum(fused.values()),
        "unfused": unfused,
        "unfused_total": sum(unfused.values()),
        "fusion_traffic_ratio": round(
            sum(unfused.values()) / sum(fused.values()), 2
        ),
    }


def measure_shape(res: dict, n: int, u: int) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.ops import exchange as exch

    w = u // 32
    rng = np.random.default_rng(11)
    heard = jnp.asarray(rng.integers(0, 2**32, (n, w), dtype=np.uint32))
    pulled = jnp.roll(heard, 1, axis=0)
    pushed = jnp.roll(heard, -1, axis=0)
    r_delta = jnp.asarray(rng.integers(0, 2**32, (u,), dtype=np.uint32))
    models = _bytes_models(n, w)

    shape_res: dict = {"n": n, "u": u, "bytes_model": models}
    # cross-shard model rows (round 14): per-tick interconnect vs
    # shard-local bytes for the shard_map'd plane at the storm's mesh
    # shapes — from the ONE shared model so bench.py's mesh phase and
    # tpu_measure.py's weak_scaling phase report the same bytes
    shape_res["cross_shard_model"] = {}
    for shards in (2, 4, 8):
        if n % shards:
            continue
        m = exch.cross_shard_traffic_bytes(n, w, shards)
        m["cross_to_local_ratio"] = round(
            m["interconnect_total"] / m["local_fused_total"], 3
        )
        shape_res["cross_shard_model"]["shards_%d" % shards] = m
    on_tpu = jax.default_backend() == "tpu"
    for impl in ("pallas", "xla"):
        try:
            # the SHARED in-scan probe (ops.exchange.measure_bandwidth):
            # h ^ pulled re-dirties bits every step, warm-then-distinct-
            # input timing — one protocol across every bandwidth artifact
            gbps, sec = exch.measure_bandwidth(
                heard, pulled, pushed, r_delta, impl=impl, iters=ITERS
            )
            row = {
                "ms_per_step": round(sec * 1e3, 3),
                "achieved_gbps": round(gbps, 3),
                "protocol": "in-scan x%d" % ITERS,
            }
            if impl == "pallas" and not on_tpu:
                row["interpret"] = True  # NOT a kernel number
            shape_res[impl] = row
        except Exception as e:
            shape_res[impl] = {"error": str(e)[:300]}
    res["shape_%dx%d" % (n, u)] = shape_res


def measure_cross_shard(res: dict, n: int = 4096, u: int = 512) -> None:
    """Measured interconnect bytes per mesh size (round 17): a short
    telemetry-instrumented storm per shard count, drained and reconciled
    against the analytic model.  Sized down from the bandwidth shapes —
    the exchange cap scales with N/S, so the RATIO (the claim under
    test) is shape-independent while the run stays seconds on CPU."""
    import jax

    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.obs import exchange_stats as oxs
    from ringpop_tpu.parallel import mesh as pmesh

    ticks = 4
    out: dict = {"n": n, "u": u, "ticks": ticks}
    for shards in (2, 4, 8):
        key = "shards_%d" % shards
        if n % shards or jax.local_device_count() < shards:
            out[key] = {
                "error": "need %d devices, have %d"
                % (shards, jax.local_device_count())
            }
            continue
        try:
            params = es.ScalableParams(
                n=n, u=u, exchange_metrics=shards
            )
            storm = pmesh.ShardedStorm(
                n, mesh=pmesh.make_mesh(shards), params=params
            )
            if storm.exchange_mode != "shard_map":
                out[key] = {
                    "error": "exchange mode %r" % (storm.exchange_mode,)
                }
                continue
            for _ in range(ticks):
                storm.step()
            drained = storm.drain_exchange_metrics(reset=False)
            out[key] = oxs.reconcile(drained["totals"], n=n, w=u // 32)
        except Exception as e:
            out[key] = {"error": str(e)[:300]}
    res["cross_shard_measured"] = out


def main() -> int:
    from ringpop_tpu.utils.util import scrub_repo_pythonpath

    scrub_repo_pythonpath(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import ringpop_tpu  # noqa: F401

    if os.environ.get("PROF_ROOFLINE_FORCE_CPU") != "1":
        try:
            from ringpop_tpu.utils.util import wait_for_tpu

            wait_for_tpu(__file__, "PROF_EXCHANGE_ATTEMPT", 3, 10.0)
        except Exception:
            pass
    import jax

    plat = jax.default_backend()
    res = {
        "platform": plat,
        "device": str(jax.devices()[0]),
        "peak_gbps": TPU_PEAK_GBPS if plat == "tpu" else None,
        "note": (
            "modeled bytes are a LOWER bound (3 mask reads + 1 write + "
            "row outputs); achieved GB/s is conservative.  CPU runs "
            "exist so the artifact regenerates on tunnel-less images — "
            "interpret-mode pallas rows are flagged and are NOT kernel "
            "numbers."
        ),
    }
    # the storm's own shapes: 100k everywhere, 1M only where the mask
    # fits comfortably (a [1M, 16]-word in-scan window on a CPU image is
    # minutes of interpret-mode pallas — chip-gated)
    shapes = [(100_000, 512)]
    if plat == "tpu":
        shapes.append((1_000_000, 512))
    for n, u in shapes:
        measure_shape(res, n, u)
    measure_cross_shard(res)
    for key, sr in res.items():
        if not key.startswith("shape_") or not res.get("peak_gbps"):
            continue
        g = sr.get("pallas", {}).get("achieved_gbps")
        if g:
            sr["pct_of_peak"] = round(100.0 * g / res["peak_gbps"], 2)
    with open(OUT, "w") as f:
        json.dump(res, f, indent=1)
    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    sys.exit(main())
