#!/usr/bin/env python3
"""Operator entry point: interactive N-node cluster harness
(reference: scripts/tick-cluster.js).  Thin wrapper over
ringpop_tpu.api.tick_cluster — `--backend live` spawns real node
processes; `--backend jax-sim` drives the batched device simulator."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ringpop_tpu.api.tick_cluster import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
