#!/usr/bin/env python
"""Scenario-fuzzer operator CLI: sweep seed ranges, shrink failures,
replay fixtures.

Examples::

    # 512 full-engine storms, n=8, default loss menu
    python scripts/fuzz_sweep.py sweep --engine full --seeds 0:512

    # wide scalable sweep
    python scripts/fuzz_sweep.py sweep --engine scalable --n 32 --seeds 0:256

    # shrink one failing seed to a minimal schedule and save the fixture
    python scripts/fuzz_sweep.py shrink --engine full --seed 45 \
        --out tests/fuzz/fixtures/my_bug.json

    # replay a committed fixture on the current engines
    python scripts/fuzz_sweep.py replay tests/fuzz/fixtures/*.json

    # crash-and-recover gate: preempt checkpointing drivers at seed-drawn
    # ticks (incl. mid-checkpoint-write torn files), auto-recover, and
    # require the final state bitwise-equal to the uninterrupted run
    python scripts/fuzz_sweep.py crash --driver routed --n 64 --seeds 0:16

A sweep exits nonzero when any scenario violates an invariant, printing
per-seed violation names — feed the failing seed to ``shrink``.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def _seed_range(spec: str):
    if ":" in spec:
        lo, hi = spec.split(":", 1)
        return list(range(int(lo), int(hi)))
    return [int(s) for s in spec.split(",")]


def _config(args):
    from ringpop_tpu.fuzz import scenarios as sc

    return sc.ScenarioConfig(
        engine=args.engine,
        n=args.n,
        ticks=args.ticks,
        loss_levels=tuple(float(x) for x in args.loss.split(",")),
    )


def cmd_sweep(args) -> int:
    from ringpop_tpu.fuzz import executor as fex
    from ringpop_tpu.fuzz import invariants as inv

    cfg = _config(args)
    seeds = _seed_range(args.seeds)
    runs = fex.sweep(seeds, cfg)
    n_bad = 0
    for run in runs:
        for b, vs in sorted(inv.check_run(run).items()):
            n_bad += 1
            print(
                "FAIL seed=%d loss=%g invariants=%s"
                % (
                    run.seeds[b],
                    run.params.packet_loss,
                    ",".join(inv.violation_names(vs)),
                )
            )
            for v in vs[: args.verbose_violations]:
                print("  %s: %s" % (v.invariant, v.message))
    total = sum(len(r.seeds) for r in runs)
    print(
        "%d/%d scenarios clean (%s engine, n=%d, T=%d, %d loss buckets)"
        % (total - n_bad, total, cfg.engine, cfg.n, cfg.ticks, len(runs))
    )
    return 1 if n_bad else 0


def cmd_shrink(args) -> int:
    from ringpop_tpu.fuzz import executor as fex
    from ringpop_tpu.fuzz import scenarios as sc
    from ringpop_tpu.fuzz import shrinker

    cfg = _config(args)
    ex = fex.executor_for(
        cfg, packet_loss=sc.packet_loss_of(args.seed, cfg)
    )
    res = shrinker.shrink_seed(ex, args.seed)
    print(
        "seed %d -> %d fault cells (%d evaluations): %s"
        % (
            args.seed,
            len(res.faults),
            res.evaluations,
            res.invariant_names,
        )
    )
    for f in res.faults:
        print("  %s t=%d node=%d value=%d" % f)
    if args.out:
        shrinker.save_fixture(res, args.out, note=args.note)
        print("fixture written: %s" % args.out)
    return 0


def cmd_replay(args) -> int:
    from ringpop_tpu.fuzz import shrinker

    bad = 0
    for path in args.fixtures:
        doc = shrinker.load_fixture(path)
        vs = shrinker.replay_fixture(doc)
        if vs:
            bad += 1
            print(
                "FAIL %s: %s"
                % (path, sorted({v.invariant for v in vs}))
            )
            for v in vs[:4]:
                print("  %s: %s" % (v.invariant, v.message))
        else:
            print(
                "ok   %s (guards: %s)"
                % (path, ",".join(doc["invariants"]))
            )
    return 1 if bad else 0


def cmd_crash(args) -> int:
    import tempfile

    from ringpop_tpu.fuzz import crash
    from ringpop_tpu.fuzz import scenarios as sc

    cfg = sc.ScenarioConfig(n=args.n, ticks=args.ticks)
    seeds = _seed_range(args.seeds)
    workdir = args.workdir or tempfile.mkdtemp(prefix="ringpop-crash-")
    reports = crash.sweep_crash(
        seeds,
        workdir,
        driver=args.driver,
        config=cfg,
        every=args.every,
        keep=args.keep,
        shards=args.shards,
    )
    n_bad = 0
    for seed, rep in sorted(reports.items()):
        status = "ok  " if not rep.violations else "FAIL"
        n_bad += bool(rep.violations)
        print(
            "%s seed=%d kill=%d corrupt=%s resumed=%s skipped=%s"
            % (
                status,
                seed,
                rep.kill_tick,
                rep.corrupt,
                rep.resumed_tick,
                ",".join(rep.skipped_errors) or "-",
            )
        )
        for v in rep.violations[: args.verbose_violations]:
            print("  %s: %s" % (v.invariant, v.message))
    print(
        "%d/%d crash-resume exercises bit-exact (%s driver, n=%d, T=%d, "
        "every=%d, shards=%d)"
        % (
            len(reports) - n_bad,
            len(reports),
            args.driver,
            cfg.n,
            cfg.ticks,
            args.every,
            args.shards,
        )
    )
    return 1 if n_bad else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp):
        sp.add_argument("--engine", choices=("full", "scalable"), default="full")
        sp.add_argument("--n", type=int, default=None)
        sp.add_argument("--ticks", type=int, default=24)
        sp.add_argument("--loss", default="0.0,0.05,0.2")

    sp = sub.add_parser("sweep", help="run a seed range, check invariants")
    common(sp)
    sp.add_argument("--seeds", default="0:64", help="lo:hi or comma list")
    sp.add_argument("--verbose-violations", type=int, default=2)
    sp.set_defaults(fn=cmd_sweep)

    sp = sub.add_parser("shrink", help="minimize one failing seed")
    common(sp)
    sp.add_argument("--seed", type=int, required=True)
    sp.add_argument("--out", default=None, help="fixture JSON path")
    sp.add_argument("--note", default="", help="fixture provenance note")
    sp.set_defaults(fn=cmd_shrink)

    sp = sub.add_parser("replay", help="replay committed fixtures")
    sp.add_argument("fixtures", nargs="+")
    sp.set_defaults(fn=cmd_replay)

    sp = sub.add_parser(
        "crash", help="crash-and-recover gate (resume-bitwise invariant)"
    )
    sp.add_argument(
        "--driver", choices=("full", "scalable", "routed"), default="scalable"
    )
    sp.add_argument("--n", type=int, default=64)
    sp.add_argument("--ticks", type=int, default=12)
    sp.add_argument("--seeds", default="0:8", help="lo:hi or comma list")
    sp.add_argument("--every", type=int, default=3, help="checkpoint cadence")
    sp.add_argument("--keep", type=int, default=3, help="keep-last-K rotation")
    sp.add_argument("--shards", type=int, default=1)
    sp.add_argument("--workdir", default=None, help="checkpoint family root")
    sp.add_argument("--verbose-violations", type=int, default=2)
    sp.set_defaults(fn=cmd_crash)

    args = p.parse_args(argv)
    if getattr(args, "n", None) is None and hasattr(args, "engine"):
        args.n = 8 if args.engine == "full" else 32
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
