#!/usr/bin/env node
// Validates PARITY_REPLAY.json against real ringpop-node code: for every
// snapshot, rebuild the reference's generateChecksumString
// (lib/membership/index.js:101-123 — members sorted by address,
// address + status + incarnationNumber concatenated, joined with ';')
// and compare farmhash.hash32(str) >>> 0 with the engine's checksum.
//
// Two validation modes, strongest available wins:
//  1. RINGPOP_NODE_DIR set (or /root/reference present): require() the
//     actual checksum-string builder from a ringpop-node checkout and
//     feed it the snapshot's member records verbatim.
//  2. Otherwise: rebuild the string by hand per the documented contract
//     (still hashes with the REAL farmhash native addon ringpop loads).
//
// Usage: npm install && node validate_replay.js ../../PARITY_REPLAY.json

'use strict';

var fs = require('fs');
var path = require('path');
var farmhash = require('farmhash');

var artifactPath = process.argv[2] || '../../PARITY_REPLAY.json';
var refDir = process.env.RINGPOP_NODE_DIR || '/root/reference';

function manualChecksumString(members) {
    // lib/membership/index.js:101-123, bytewise ASCII sort by address
    var sorted = members.slice().sort(function (a, b) {
        return a.address < b.address ? -1 : a.address > b.address ? 1 : 0;
    });
    return sorted
        .map(function (m) {
            return m.address + m.status + m.incarnationNumber;
        })
        .join(';');
}

function referenceChecksumString(members) {
    // Drive the real module: a Membership instance populated with the
    // snapshot's member records, asked for its own checksum string.
    var Membership = require(path.join(refDir, 'lib', 'membership', 'index.js'));
    var Member = require(path.join(refDir, 'lib', 'membership', 'member.js'));
    var stub = {
        logger: { debug: noop, info: noop, warn: noop, error: noop, trace: noop },
        stat: noop,
        whoami: function () { return members[0] && members[0].address; },
        config: { get: function () { return undefined; } },
        loggerFactory: { getLogger: function () { return stub.logger; } },
        timers: { setTimeout: noop, clearTimeout: noop },
    };
    function noop() {}
    var membership = new Membership({ ringpop: stub });
    members.forEach(function (m) {
        var member = new Member(stub, {
            address: m.address,
            status: m.status,
            incarnationNumber: m.incarnationNumber,
        });
        membership.members.push(member);
        membership.membersByAddress[m.address] = member;
    });
    return membership.generateChecksumString();
}

var useReference = false;
try {
    fs.accessSync(path.join(refDir, 'lib', 'membership', 'index.js'));
    referenceChecksumString([
        { address: '127.0.0.1:3000', status: 'alive', incarnationNumber: 1 },
    ]);
    useReference = true;
    console.log('mode: ringpop-node Membership module (' + refDir + ')');
} catch (e) {
    console.log('mode: manual string rebuild (' + e.message + ')');
}

var artifact = JSON.parse(fs.readFileSync(artifactPath, 'utf8'));
var bad = 0;
artifact.snapshots.forEach(function (snap) {
    var str = useReference
        ? referenceChecksumString(snap.members)
        : manualChecksumString(snap.members);
    var got = farmhash.hash32(str) >>> 0;
    if (got !== snap.expected_checksum) {
        bad++;
        console.error(
            'MISMATCH tick=' + snap.tick + ' observer=' + snap.observer +
            ' got=' + got + ' want=' + snap.expected_checksum
        );
    }
});
console.log(artifact.snapshots.length + ' snapshots, ' + bad + ' mismatches');
process.exit(bad ? 1 : 0);
