#!/usr/bin/env node
// Validates PARITY_TRAJECTORY.json: a complete scripted tick-cluster run
// (bootstrap -> kill wave -> suspect -> faulty -> revive -> reconverge)
// where every tick carries the checksum-group view the reference's
// tick-cluster harness prints (scripts/tick-cluster.js:87-114), each
// represented group with one observer's full membership view.  Rebuilds
// the reference checksum string for every representative and compares
// farmhash.hash32 (the real native addon) with the engine's checksum.
//
// Usage: npm install && node validate_trajectory.js ../../PARITY_TRAJECTORY.json

'use strict';

var fs = require('fs');
var farmhash = require('farmhash');

var art = JSON.parse(
    fs.readFileSync(process.argv[2] || '../../PARITY_TRAJECTORY.json', 'utf8')
);
var checked = 0;
var bad = 0;
art.ticks_data.forEach(function (t) {
    t.groups.forEach(function (g) {
        if (!g.representative) return; // counts-only group (capped)
        var sorted = g.representative.members.slice().sort(function (a, b) {
            return a[0] < b[0] ? -1 : a[0] > b[0] ? 1 : 0;
        });
        var str = sorted
            .map(function (m) {
                return m[0] + m[1] + m[2]; // address + status + incarnation
            })
            .join(';');
        var got = farmhash.hash32(str) >>> 0;
        checked++;
        if (got !== g.checksum) {
            bad++;
            console.error(
                'MISMATCH tick=' + t.tick +
                ' observer=' + g.representative.observer +
                ' got=' + got + ' want=' + g.checksum
            );
        }
    });
});
console.log(
    checked + ' group checksums checked across ' + art.ticks_data.length +
    ' ticks, ' + bad + ' mismatches; final tick has ' +
    art.ticks_data[art.ticks_data.length - 1].distinct_checksums +
    ' distinct checksum(s)'
);
process.exit(bad ? 1 : 0);
