#!/usr/bin/env python3
"""Round-4 per-op cost decomposition of the 1M scalable-engine tick on TPU.

The round-3 storm numbers (RESULTS_TPU_r03.json) say 1.28 s/tick of
non-checksum work and ~0.75 s/tick attributed to compute_checksums at
N=1M, U=512 — but a traffic estimate puts the checksum limb-matmul at
~10 ms.  Before optimizing, measure where the tick actually goes:
argsorts (4 partner perms + 4 argsort-inverses per tick), the [1M,16]
row gathers, the distinct-checksum sort, the publish record_mix chains,
and compute_checksums itself.

Prints one JSON dict; also writes PROF_R4.json.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = os.environ.get("PROF_R4_OUT", "PROF_R4.json")


def wait_for_tpu():
    from ringpop_tpu.utils.util import wait_for_tpu as _wait

    return _wait(__file__, "PROF_R4_ATTEMPT", 90, 20.0)


def timeit(fn, *args, reps=5):
    import jax

    out = jax.block_until_ready(fn(*args))  # compile
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps * 1e3  # ms


def main() -> int:
    from ringpop_tpu.utils.util import scrub_repo_pythonpath

    scrub_repo_pythonpath(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import ringpop_tpu  # noqa: F401

    plat = wait_for_tpu()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.models.sim import engine_scalable as es

    n, u = 1_000_000, 512
    w = u // 32
    res = {"platform": plat, "device": str(jax.devices()[0]), "n": n, "u": u}

    key = jnp.asarray([0x12345678, 0x9ABCDEF0], jnp.uint32)
    r = es._rand_u32(key, (n,), 7)
    ids = jnp.arange(n, dtype=jnp.int32)
    heard = es._rand_u32(key, (n, w), 11)
    perm_host = np.random.default_rng(0).permutation(n).astype(np.int32)
    perm = jnp.asarray(perm_host)

    # 1. one partner permutation: argsort of [N] uint32
    f_perm = jax.jit(lambda k: es._perm(k, n, 0xA11CE))
    res["perm_argsort_ms"] = timeit(f_perm, key)

    # 2. batched: all 4 perms in one [4, N] argsort
    def four_perms(k):
        rr = es._rand_u32(k, (4, n), 3)
        return jnp.argsort(
            rr ^ jnp.arange(n, dtype=jnp.uint32)[None, :], axis=-1
        )

    res["perm_argsort_x4_batched_ms"] = timeit(jax.jit(four_perms), key)

    # 3. inverse: argsort vs scatter
    res["inv_argsort_ms"] = timeit(jax.jit(jnp.argsort), perm)
    f_scat = jax.jit(
        lambda p: jnp.zeros(n, jnp.int32).at[p].set(ids, unique_indices=True)
    )
    res["inv_scatter_ms"] = timeit(f_scat, perm)

    # 4. row gather [1M, 16] by permutation
    f_gather = jax.jit(lambda h, p: h[p])
    res["gather_rows_ms"] = timeit(f_gather, heard, perm)

    # 5. distinct sort: jnp.sort of [N] uint32
    res["sort_u32_ms"] = timeit(jax.jit(jnp.sort), r)

    # 6. popcount metrics block
    f_pop = jax.jit(lambda h: jnp.sum(es._popcount(h), axis=1))
    res["popcount_rows_ms"] = timeit(f_pop, heard)

    # 7. compute_checksums at 1M (full recompute, the in-tick cost)
    params = es.ScalableParams(n=n, u=u, checksum_in_tick=True)
    state = es.init_state(params, seed=0)
    f_cs = jax.jit(functools.partial(es.compute_checksums, params=params))
    res["compute_checksums_ms"] = timeit(f_cs, state)

    # 8. record_mix over [N] (x2 per publish, 3 publishes per tick)
    from ringpop_tpu.ops.record_mix import record_mix

    f_mix = jax.jit(
        lambda s, i: record_mix(ids, s, i)
    )
    res["record_mix_ms"] = timeit(
        f_mix, jnp.zeros(n, jnp.int32), jnp.ones(n, jnp.int32)
    )

    # 9. full quiet tick, both checksum modes
    for in_tick in (True, False):
        p2 = es.ScalableParams(n=n, u=u, checksum_in_tick=in_tick)
        st = es.init_state(p2, seed=0)
        step = jax.jit(functools.partial(es.tick, params=p2))
        quiet = es.ChurnInputs.quiet(n)
        st, _ = step(st, quiet)  # compile + settle
        jax.block_until_ready(st)
        t0 = time.perf_counter()
        reps = 5
        for _ in range(reps):
            st, _ = step(st, quiet)
        jax.block_until_ready(st)
        res["tick_quiet_ms_%s" % ("intick" if in_tick else "deferred")] = (
            (time.perf_counter() - t0) / reps * 1e3
        )

    # 10. tick with 10% dead (storm steady state: direct fails every tick)
    st = es.init_state(params, seed=0)
    step = jax.jit(functools.partial(es.tick, params=params))
    kill = jnp.asarray(np.arange(n) % 10 == 3)
    st, _ = step(st, es.ChurnInputs(kill=kill, revive=jnp.zeros(n, bool)))
    jax.block_until_ready(st)
    quiet = es.ChurnInputs.quiet(n)
    st, _ = step(st, quiet)
    jax.block_until_ready(st)
    t0 = time.perf_counter()
    for _ in range(5):
        st, _ = step(st, quiet)
    jax.block_until_ready(st)
    res["tick_10pct_dead_ms"] = (time.perf_counter() - t0) / 5 * 1e3

    for k, v in sorted(res.items()):
        if isinstance(v, float):
            res[k] = round(v, 2)
    with open(OUT, "w") as f:
        json.dump(res, f, indent=1)
    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    sys.exit(main())
