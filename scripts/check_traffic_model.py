#!/usr/bin/env python
"""Measured-vs-model mesh exchange traffic gate (TRAFFIC_BUDGET.json).

Runs the sharded storm on small forced-host-device meshes (2/4/8
shards) with the exchange telemetry plane on, drains the per-shard wire
counters (ops.exchange.ExchangeMetrics), and reconciles the MEASURED
interconnect bytes against the analytic traffic model
(``cross_shard_traffic_bytes`` — the (S-1)/S cross-fraction claim the
roofline math stands on).  Two checks per entry:

1. measured vs model within ``--rtol`` (exact equality whenever every
   trip took the a2a path at the default cap);
2. both numbers vs the committed TRAFFIC_BUDGET.json manifest — a
   silent change to the wire format, the cap sizing, or the byte
   pricing fails the diff.

Usage::

    python scripts/check_traffic_model.py                 # diff, exit 1 on drift
    python scripts/check_traffic_model.py --write         # regenerate manifest
    python scripts/check_traffic_model.py --entries a,b   # subset (diff only)
    python scripts/check_traffic_model.py --rtol 0.02

``--write`` REFUSES to commit a manifest containing entries that failed
to run — a broken mesh config is a finding, not a budget.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path
from typing import Dict, Iterable, List, Optional

REPO_ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO_ROOT))

# the 8-shard mesh needs 8 (virtual) devices — force the host-platform
# split BEFORE jax initializes, exactly like tests/conftest.py.  A
# too-late call (jax already imported by the embedding process, e.g. the
# tier-1 test run) is a no-op; the test env forces 8 devices itself.
# The flag spelling lives in utils/util.force_host_device_count alone
# (round 14); loaded by FILE PATH because the package import pulls jax.
if "jax" not in sys.modules:
    import importlib.util as _ilu

    _spec = _ilu.spec_from_file_location(
        "_ringpop_util_boot",
        str(REPO_ROOT / "ringpop_tpu" / "utils" / "util.py"),
    )
    _util_boot = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(_util_boot)
    if (
        "xla_force_host_platform_device_count"
        not in os.environ.get("XLA_FLAGS", "")
        and "JAX_NUM_CPU_DEVICES" not in os.environ
    ):
        _util_boot.force_host_device_count(8)

from ringpop_tpu.analysis.findings import Finding, render_text  # noqa: E402

DEFAULT_BUDGET = REPO_ROOT / "TRAFFIC_BUDGET.json"
DEFAULT_RTOL = 0.01

# small CPU-friendly configs; counters are deterministic (seed 0), so
# the manifest diff is exact modulo --rtol slack for forward-compat
MESH_CONFIGS = (
    {"shards": 2, "n": 64, "u": 128, "ticks": 8},
    {"shards": 4, "n": 64, "u": 128, "ticks": 8},
    {"shards": 8, "n": 64, "u": 128, "ticks": 8},
)

# config-identity fields (exact match required — a mismatch is a stale
# manifest, not drift) and rtol-banded measurement fields
EXACT_FIELDS = ("shards", "n", "w", "cap", "ticks", "fallback_trips")
BANDED_FIELDS = ("measured_interconnect", "model_interconnect")


def entry_name(cfg: Dict) -> str:
    return "mesh-s%d-n%d" % (cfg["shards"], cfg["n"])


def measure_entry(cfg: Dict) -> Dict[str, object]:
    """One config's reconciliation record: run ``ticks`` quiet storm
    ticks on a ``shards``-device mesh with the telemetry plane on,
    drain, reconcile.  Errors come back as ``{"error": ...}`` rows (the
    cost gate's convention) so one broken config doesn't hide the
    rest."""
    import jax

    try:
        from ringpop_tpu.models.sim import engine_scalable as es
        from ringpop_tpu.obs import exchange_stats as oxs
        from ringpop_tpu.parallel import mesh as pmesh

        shards, n, u = cfg["shards"], cfg["n"], cfg["u"]
        if jax.local_device_count() < shards:
            return {
                "error": "need %d devices, have %d"
                % (shards, jax.local_device_count())
            }
        params = es.ScalableParams(n=n, u=u, exchange_metrics=shards)
        storm = pmesh.ShardedStorm(
            n, mesh=pmesh.make_mesh(shards), params=params
        )
        if storm.exchange_mode != "shard_map":
            return {
                "error": "exchange mode %r (the gate measures the "
                "shard_map plane)" % (storm.exchange_mode,)
            }
        for _ in range(cfg["ticks"]):
            storm.step()
        drained = storm.drain_exchange_metrics(reset=False)
        return oxs.reconcile(drained["totals"], n=n, w=u // 32)
    except Exception as e:  # pragma: no cover - defensive
        return {"error": "%s: %s" % (type(e).__name__, e)}


def collect_measurements(
    entry_names: Optional[Iterable[str]] = None,
) -> Dict[str, Dict]:
    names = None if entry_names is None else set(entry_names)
    out: Dict[str, Dict] = {}
    for cfg in MESH_CONFIGS:
        name = entry_name(cfg)
        if names is not None and name not in names:
            continue
        out[name] = measure_entry(cfg)
    return out


def load_manifest(path: Optional[Path] = None) -> Optional[Dict]:
    path = DEFAULT_BUDGET if path is None else Path(path)
    if not path.exists():
        return None
    return json.loads(path.read_text())


def write_manifest(
    actual: Dict[str, Dict], path: Optional[Path] = None
) -> Path:
    import jax

    broken = sorted(k for k, v in actual.items() if "error" in v)
    if broken:
        raise ValueError(
            "refusing to write a manifest with failed entries: %s"
            % ", ".join(broken)
        )
    path = DEFAULT_BUDGET if path is None else Path(path)
    doc = {
        "backend": jax.default_backend(),
        "rtol": DEFAULT_RTOL,
        "entries": actual,
    }
    path.write_text(json.dumps(doc, indent=2, sort_keys=True) + "\n")
    return path


def _finding(name: str, message: str) -> Finding:
    return Finding(
        rule="traffic-budget",
        path="<entry:%s>" % name,
        line=0,
        message=message,
        prong="traffic",
    )


def reconcile_findings(
    actual: Dict[str, Dict], rtol: float = DEFAULT_RTOL
) -> List[Finding]:
    """The model-vs-measurement check itself, manifest-free: measured
    interconnect bytes within ``rtol`` of the analytic model's."""
    out: List[Finding] = []
    for name, rec in sorted(actual.items()):
        if "error" in rec:
            out.append(_finding(name, "measurement failed: %s" % rec["error"]))
            continue
        model = int(rec["model_interconnect"])
        measured = int(rec["measured_interconnect"])
        if abs(measured - model) > rtol * max(model, 1):
            out.append(
                _finding(
                    name,
                    "measured interconnect %d vs model %d (ratio %s, "
                    "%d fallback trips) exceeds rtol %g"
                    % (
                        measured,
                        model,
                        rec.get("ratio"),
                        int(rec.get("fallback_trips", 0)),
                        rtol,
                    ),
                )
            )
    return out


def compare_to_manifest(
    actual: Dict[str, Dict],
    manifest: Dict,
    rtol: float = DEFAULT_RTOL,
) -> List[Finding]:
    out: List[Finding] = []
    entries = manifest.get("entries", {})
    for name, exp in sorted(entries.items()):
        if name not in actual:
            out.append(
                _finding(name, "manifest entry not measured (stale manifest?)")
            )
    for name, rec in sorted(actual.items()):
        if "error" in rec:
            continue  # already a reconcile finding
        exp = entries.get(name)
        if exp is None:
            out.append(
                _finding(
                    name,
                    "no manifest entry — run scripts/check_traffic_model.py "
                    "--write",
                )
            )
            continue
        for f in EXACT_FIELDS:
            if int(rec[f]) != int(exp[f]):
                out.append(
                    _finding(
                        name,
                        "%s changed: measured %d, manifest %d"
                        % (f, int(rec[f]), int(exp[f])),
                    )
                )
        for f in BANDED_FIELDS:
            a, e = int(rec[f]), int(exp[f])
            if abs(a - e) > rtol * max(e, 1):
                out.append(
                    _finding(
                        name,
                        "%s drifted: measured %d, manifest %d (rtol %g)"
                        % (f, a, e, rtol),
                    )
                )
    return out


def check_against_manifest(
    entry_names: Optional[Iterable[str]] = None,
    path: Optional[Path] = None,
    rtol: float = DEFAULT_RTOL,
) -> List[Finding]:
    import jax

    manifest = load_manifest(path)
    if manifest is None:
        return [
            _finding(
                "*",
                "missing manifest %s — run scripts/check_traffic_model.py "
                "--write" % (DEFAULT_BUDGET if path is None else path),
            )
        ]
    if manifest.get("backend") != jax.default_backend():
        # wire-byte counters are backend-independent in principle, but
        # the committed numbers were banked on one backend — mirror the
        # cost gate's clean skip rather than risk a false alarm
        return []
    actual = collect_measurements(entry_names)
    findings = reconcile_findings(actual, rtol)
    if entry_names is not None:
        manifest = dict(manifest)
        manifest["entries"] = {
            k: v
            for k, v in manifest.get("entries", {}).items()
            if k in set(entry_names)
        }
    return findings + compare_to_manifest(actual, manifest, rtol)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--write",
        action="store_true",
        help="measure the mesh configs and (re)write TRAFFIC_BUDGET.json",
    )
    parser.add_argument(
        "--budget",
        default=None,
        help="manifest path (default: TRAFFIC_BUDGET.json at repo root)",
    )
    parser.add_argument(
        "--entries",
        default=None,
        help="comma-separated entry-name subset (diff mode only)",
    )
    parser.add_argument(
        "--rtol",
        type=float,
        default=DEFAULT_RTOL,
        help="relative drift tolerance (default %g)" % DEFAULT_RTOL,
    )
    args = parser.parse_args(argv)
    path = Path(args.budget) if args.budget else None
    names = (
        [n.strip() for n in args.entries.split(",") if n.strip()]
        if args.entries
        else None
    )

    if args.write:
        if names is not None:
            parser.error("--write regenerates the FULL manifest; drop --entries")
        actual = collect_measurements()
        findings = reconcile_findings(actual)
        if findings:
            print(render_text(findings))
            return 1
        out = write_manifest(actual, path)
        total = sum(
            int(e["measured_interconnect"]) for e in actual.values()
        )
        print(
            "wrote %s (%d entries, %d measured interconnect bytes)"
            % (out, len(actual), total)
        )
        return 0

    findings = check_against_manifest(
        entry_names=names, path=path, rtol=args.rtol
    )
    print(render_text(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
