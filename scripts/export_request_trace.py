#!/usr/bin/env python
"""Generate the committed request-observatory sample artifacts (runlogs/).

Runs an n=64 RoutedStorm with the sampled per-request trace buffer AND
the device histograms enabled, drained in fixed windows that feed the
sliding-window SLO plane.  The middle windows inject a churn burst
(kill a quarter of the cluster, rejoin later), so the committed runlog
demonstrates the full story the request observatory tells:

- ``runlogs/sample_requests_n64.runlog.jsonl`` — per-tick sim+route
  metric rows, one ``reqtrace.drain`` + ``hist.drain`` + ``slo.window``
  row per drained window, and the ``slo.breach`` rows the churn burst
  fires (schema-gated by scripts/check_metrics_schema.py),
- ``runlogs/sample_requests_n64.requests.trace.json`` — the Perfetto
  request-lifecycle sidecar (one track per sender, flow arrows for
  remote reroutes; load at https://ui.perfetto.dev).

Deterministic (fixed seed, CPU-pinnable via JAX_PLATFORMS=cpu), so the
artifacts regenerate reproducibly::

    JAX_PLATFORMS=cpu python scripts/export_request_trace.py
"""

from __future__ import annotations

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

N = 64
WINDOW_TICKS = 5
WINDOWS = 8
BURST_WINDOWS = (2, 3)  # churn burst: kill in window 2, rejoin in 3
RUN_ID = "sample_requests_n%d" % N


def main() -> int:
    import numpy as np

    from ringpop_tpu.models.route import reqtrace as rt
    from ringpop_tpu.models.route.plane import RoutedStorm, RouteParams
    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.models.sim.storm import StormSchedule
    from ringpop_tpu.obs import RunRecorder
    from ringpop_tpu.obs import requests as oreq
    from ringpop_tpu.obs.slo import SLOBackpressure, SLOTarget, SLOWindowPlane

    out_dir = os.path.join(REPO_ROOT, "runlogs")
    os.makedirs(out_dir, exist_ok=True)

    qpt = 256
    route = RouteParams(
        n=N,
        queries_per_tick=qpt,
        key_space=1024,
        histograms=True,
        reqtrace=True,
        # drop-free at worst case for one drain window (sized the
        # flight-recorder way: capacity >= ticks * max-per-tick)
        req_capacity=rt.req_capacity_for(qpt, WINDOW_TICKS),
        req_sample_log2=2,  # trace 1/4 of the key space
    )
    rs = RoutedStorm(
        N,
        params=es.ScalableParams(n=N, u=192, suspicion_ticks=4),
        route=route,
        seed=1,
    )
    rec = RunRecorder(
        os.path.join(out_dir, "%s.runlog.jsonl" % RUN_ID),
        run_id=RUN_ID,
        config={"tool": "scripts/export_request_trace.py", "seed": 1},
    )
    # regenerate in place: the recorder appends, so stale rows must go
    open(rec.path, "w").close()
    rs.attach_recorder(rec)

    backpressure = SLOBackpressure(base_period_ms=200.0)
    slo = SLOWindowPlane(
        SLOTarget(name="route", success_objective=0.999, burn_alert=2.0),
        window_len=3,
        recorder=rec,
        consumer=backpressure,
    )

    # the burst: a quarter of the cluster dies in window 2, rejoins in 3
    burst = np.random.default_rng(7).choice(N, N // 4, replace=False)
    all_requests = []
    tick = 0
    for w in range(WINDOWS):
        sched = StormSchedule(ticks=WINDOW_TICKS, n=N)
        if w == BURST_WINDOWS[0]:
            sched.kill[1, burst] = True
        elif w == BURST_WINDOWS[1]:
            sched.revive[1, burst] = True
        _, rm = rs.run(sched)
        tick += WINDOW_TICKS

        hist = np.asarray(rs.rstate.hist)  # window delta: reset follows
        rs.drain_histograms(reset=True)
        slo.observe_route_window(tick, hist, rm)
        drained = rs.drain_requests(reset=True)
        assert drained["drops"] == 0, "sized capacity must be drop-free"
        recon = oreq.reconcile_metrics(
            np.asarray(
                [drained["counts"][f] for f in oreq.COUNT_FIELDS]
            ),
            rm,
        )
        assert all(v["ok"] for v in recon.values()), recon
        all_requests.extend(drained["records"])

    assert slo.breaches > 0, "the churn burst must fire a breach"
    assert backpressure.factor() == 1.0, (
        "the quiet tail windows must clear the breach"
    )

    trace = oreq.export_request_trace(all_requests, N)
    sidecar = rec.record_trace_sidecar(trace, name="requests")

    rec.finish(
        requests_traced=len(all_requests),
        slo_breaches=slo.breaches,
        windows=WINDOWS,
        window_ticks=WINDOW_TICKS,
    )
    print("wrote %s" % os.path.relpath(rec.path, REPO_ROOT))
    print("wrote %s" % os.path.relpath(sidecar, REPO_ROOT))
    print(
        "requests=%d breaches=%d"
        % (len(all_requests), slo.breaches)
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
