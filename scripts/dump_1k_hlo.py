#!/usr/bin/env python3
"""Compile the 1k fast-mode scan for TPU and print the bodies of the hot
fusions/conditionals from the round-4 trace (PROF_1K_OPS.json) with their
jax source metadata, so the 10 ms fusions can be attributed to engine
lines.  Compile-only; writes /tmp/hlo_1k.txt and prints a filtered view.
"""

from __future__ import annotations

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from ringpop_tpu.utils.util import scrub_repo_pythonpath, wait_for_tpu

    scrub_repo_pythonpath(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import ringpop_tpu  # noqa: F401

    wait_for_tpu(__file__, "HLO_1K_ATTEMPT", 90, 20.0)
    import jax

    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster

    n = 1024
    sim = SimCluster(
        n=n, params=engine.SimParams(n=n, checksum_mode="fast")
    )
    sched = EventSchedule(ticks=32, n=n)
    lowered = sim._scanned.lower(sim.state, sched.as_inputs())
    txt = lowered.compile().as_text()
    with open("/tmp/hlo_1k.txt", "w") as f:
        f.write(txt)
    print("HLO bytes:", len(txt))

    # print each hot computation's instruction lines w/ metadata op names
    lines = txt.splitlines()
    for line in lines:
        s = line.strip()
        m = re.match(r"%?(fusion\.[4-8]|conditional\.7[4-9]) =", s)
        if m:
            print("==== DEF:", s[:400])
    # fusions are defined as computations named %fused_computation.N —
    # map fusion.N instruction to its called computation and dump ops
    for name in ["fusion.4", "fusion.5", "fusion.6", "fusion.7", "fusion.8"]:
        m = re.search(r"%s = [^\n]*calls=([%%\w.\-_]+)" % re.escape(name), txt)
        if not m:
            continue
        comp = m.group(1).lstrip("%")
        print("\n######## %s -> %s" % (name, comp))
        cm = re.search(
            r"^%%?%s[^\n]*\{(.*?)^\}" % re.escape(comp),
            txt,
            re.M | re.S,
        )
        if cm:
            body = cm.group(1)
            # keep op lines with metadata source info, compressed
            for ln in body.splitlines():
                ln = ln.strip()
                if not ln:
                    continue
                meta = re.search(r'op_name="([^"]+)"', ln)
                op = ln.split(" = ")[0]
                kind = ln.split(" = ")[-1].split("(")[0][:60]
                if meta:
                    print("  ", op[:28], "|", kind, "|", meta.group(1)[-120:])
    return 0


if __name__ == "__main__":
    sys.exit(main())
