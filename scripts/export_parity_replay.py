#!/usr/bin/env python3
"""Export an offline Node-parity replay artifact (PARITY_REPLAY.json).

In this image the bit-exact checksum-parity chain is engine == host
oracle == (transitively) ringpop-node, because no Node.js runtime is
available (COVERAGE.md).  This exporter closes the residual gap by
producing a self-contained artifact a Node-equipped machine can check
against REAL ringpop-node code with no knowledge of this repo:

- a churny full-engine run (farmhash mode) at small n,
- at checkpoint ticks, the complete membership view of several observer
  nodes — (address, status string, incarnationNumber ms) triples exactly
  as the reference's member records hold them,
- the engine's per-view FarmHash32 checksum.

The validator (scripts/replay_node.md) rebuilds the reference's
generateChecksumString for each snapshot (lib/membership/index.js:101-123
— sort by address, concat address+status+incarnationNumber, join ';')
and compares farmhash.hash32(str) to expected_checksum.

Usage: python scripts/export_parity_replay.py [-n 64] [-o PARITY_REPLAY.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STATUS_STR = {0: "alive", 1: "suspect", 2: "faulty", 3: "leave"}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="export-parity-replay")
    p.add_argument("-n", type=int, default=64)
    p.add_argument("--ticks", type=int, default=48)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--output", "-o", default="PARITY_REPLAY.json")
    # default ON: the artifact attests the production parity pipeline —
    # the fused record-cache encode + streaming hash (trajectory is
    # bitwise-identical either way; --no-fused re-derives it through the
    # classic membership_rows + hash32_rows composition as a cross-check)
    p.add_argument(
        "--fused", action="store_true", default=True, dest="fused"
    )
    p.add_argument("--no-fused", action="store_false", dest="fused")
    args = p.parse_args(argv)
    if args.ticks < 32:
        p.error(
            "--ticks must be >= 32 (kill at 10, revive at 26, checkpoint "
            "at 30 are fixed; fewer ticks drops the faulty/revive "
            "coverage the artifact exists to exercise)"
        )

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.cluster import default_addresses
    from ringpop_tpu.ops import checksum_encode as ce

    n = args.n
    params = engine.SimParams(
        n=n,
        checksum_mode="farmhash",
        suspicion_ticks=6,
        # fused: direct engine use (no driver replay plumbing), so the
        # exact full-recompute shape — parity_recompute stays "auto",
        # which the fused path resolves to "full"
        fused_checksum="on" if args.fused else "off",
    )
    addresses = default_addresses(n)
    universe = ce.Universe.from_addresses(addresses)
    state = engine.init_state(params, seed=args.seed, universe=universe)
    tick = jax.jit(lambda s, i: engine.tick(s, i, params, universe))

    rng = np.random.default_rng(args.seed)
    victims = rng.choice(n, size=3, replace=False)
    # churny schedule: bootstrap, kill wave (-> suspects -> faulties),
    # revive (-> fresh-incarnation alives), reconvergence
    snapshots = []
    checkpoint_ticks = {
        6,  # post-bootstrap dissemination
        12,  # suspects in flight (kill at 10, suspicion 6 ticks)
        20,  # faulties escalated
        30,  # revived with fresh incarnations
        args.ticks - 1,  # reconverged
    }
    observers = [0, int(n // 3), int(victims[0])]

    for t in range(args.ticks):
        inputs = engine.TickInputs.quiet(n)
        if t == 0:
            inputs = inputs._replace(join=jnp.ones(n, bool))
        if t == 10:
            kill = np.zeros(n, bool)
            kill[victims] = True
            inputs = inputs._replace(kill=jnp.asarray(kill))
        if t == 26:
            rv = np.zeros(n, bool)
            rv[victims] = True
            inputs = inputs._replace(revive=jnp.asarray(rv))
        state, m = tick(state, inputs)
        if t in checkpoint_ticks:
            known = np.asarray(state.known)
            status = np.asarray(state.status)
            inc_ms = np.asarray(
                engine.stamp_to_ms(state.inc, params)
            )
            checksums = np.asarray(state.checksum)
            alive = np.asarray(state.proc_alive)
            for o in observers:
                if not alive[o]:
                    continue
                members = [
                    {
                        "address": addresses[j],
                        "status": STATUS_STR[int(status[o, j])],
                        "incarnationNumber": int(inc_ms[o, j]),
                    }
                    for j in range(n)
                    if known[o, j]
                ]
                snapshots.append(
                    {
                        "tick": t,
                        "observer": addresses[o],
                        "members": members,
                        "expected_checksum": int(checksums[o]),
                    }
                )

    statuses = {
        m["status"] for s in snapshots for m in s["members"]
    }
    assert {"alive", "suspect", "faulty"} <= statuses, (
        "snapshots must exercise alive+suspect+faulty strings: %r"
        % statuses
    )
    out = {
        "description": (
            "Membership-checksum parity replay against ringpop-node: for "
            "each snapshot, rebuild the reference checksum string "
            "(lib/membership/index.js:101-123 — members sorted by "
            "address, address+status+incarnationNumber joined with ';') "
            "and compare farmhash.hash32(str) >>> 0 to expected_checksum."
        ),
        "generator": "scripts/export_parity_replay.py",
        "engine": (
            "ringpop_tpu full-fidelity engine, farmhash mode"
            + (
                " (fused record-cache encode + streaming hash)"
                if args.fused
                else ""
            )
        ),
        "n": n,
        "ticks": args.ticks,
        "seed": args.seed,
        "validator": "scripts/replay_node.md",
        "status_values_present": sorted(statuses),
        "snapshots": snapshots,
    }
    with open(args.output, "w") as f:
        json.dump(out, f, indent=1)
    print(
        json.dumps(
            {
                "snapshots": len(snapshots),
                "statuses": sorted(statuses),
                "output": args.output,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
