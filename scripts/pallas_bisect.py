#!/usr/bin/env python3
"""Bisect the axon remote-compile failure on Pallas TPU kernels.

The tunnel's compile helper has 500'd on ops/pallas_farmhash.py for two
rounds (RESULTS_TPU_r03/r02).  This ladder compiles+runs progressively
richer Pallas kernels on the chip to find the first failing feature:

  1. copy        — single-program elementwise copy, no grid
  2. grid1d      — 1-D grid, blocked row tiles
  3. scratch     — + VMEM scratch carried across a 1-D grid axis
  4. grid2d_when — + 2-D grid with pl.when init/flush (the real shape)
  5. farmhash_tiny / 6. farmhash_bench — the real kernel
  7. fused_* — the fused encode+hash streaming kernel's compile
     constraints: gridless shape at tiny/bench scale, the VMEM
     member-chunk shrink, and the row-tiled path for row counts whose
     slab would overflow the budget

Writes PALLAS_BISECT.json with pass/fail + error heads per rung.

PALLAS_BISECT_INTERPRET=1 runs every rung through the Pallas
interpreter instead of the chip (no TPU needed): that validates kernel
construction/lowering shapes and refreshes the artifact honestly on a
CPU-only image — the artifact records which mode produced it, and chip
results from a previous round are preserved under "previous_chip".
"""

from __future__ import annotations

import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = os.environ.get("PALLAS_BISECT_OUT", "PALLAS_BISECT.json")


def main() -> int:
    from ringpop_tpu.utils.util import scrub_repo_pythonpath, wait_for_tpu

    scrub_repo_pythonpath(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import ringpop_tpu  # noqa: F401

    interp = os.environ.get("PALLAS_BISECT_INTERPRET") == "1"
    if not interp:
        wait_for_tpu(__file__, "PALLAS_BISECT_ATTEMPT", 90, 20.0)
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl

    res = {
        "device": str(jax.devices()[0]),
        "mode": "interpret" if interp else "chip",
    }
    if interp:
        # keep the r05 chip truth visible next to the interpret refresh
        try:
            with open(OUT) as f:
                prev = json.load(f)
            res["previous_chip"] = prev.get("previous_chip", prev)
        except Exception:
            pass
        _real_call = pl.pallas_call

        def pallas_call(*a, **kw):
            kw.setdefault("interpret", True)
            return _real_call(*a, **kw)

        pl.pallas_call = pallas_call

    def attempt(name, fn):
        try:
            out = fn()
            jax.block_until_ready(out)
            res[name] = {"ok": True}
        except Exception as e:
            res[name] = {"ok": False, "error": str(e)[:400]}
        print(json.dumps({name: res[name]["ok"]}), flush=True)

    x = jnp.arange(8 * 128, dtype=jnp.uint32).reshape(8, 128)

    # 1. single-program copy
    def copy_kernel(x_ref, o_ref):
        o_ref[:] = x_ref[:] * jnp.uint32(3)

    attempt(
        "copy",
        lambda: pl.pallas_call(
            copy_kernel, out_shape=jax.ShapeDtypeStruct((8, 128), jnp.uint32)
        )(x),
    )

    # 2. 1-D grid over row tiles
    big = jnp.arange(64 * 128, dtype=jnp.uint32).reshape(64, 128)

    def grid_kernel(x_ref, o_ref):
        o_ref[:] = x_ref[:] + jnp.uint32(1)

    attempt(
        "grid1d",
        lambda: pl.pallas_call(
            grid_kernel,
            grid=(8,),
            in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((64, 128), jnp.uint32),
        )(big),
    )

    # 3. scratch accumulator across a serial grid axis
    def scratch_kernel(x_ref, o_ref, acc):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            acc[:] = jnp.zeros_like(acc)

        acc[:] += x_ref[:]

        @pl.when(i == 7)
        def _():
            o_ref[:] = acc[:]

    import jax.experimental.pallas.tpu as pltpu

    attempt(
        "scratch_when",
        lambda: pl.pallas_call(
            scratch_kernel,
            grid=(8,),
            in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.uint32),
            scratch_shapes=[pltpu.VMEM((8, 128), jnp.uint32)],
        )(big),
    )

    # 4. 2-D grid with carries across the SECOND axis + pl.when — the
    # real kernel's control shape, with a trivial body
    def grid2d_kernel(x_ref, o_ref, acc):
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _():
            acc[:] = jnp.zeros_like(acc)

        acc[:] += x_ref[0, 0]

        @pl.when(j == 3)
        def _():
            o_ref[0] = acc[:]

    big2 = jnp.arange(2 * 4 * 8 * 128, dtype=jnp.uint32).reshape(
        2, 4, 8, 128
    )
    attempt(
        "grid2d_when",
        lambda: pl.pallas_call(
            grid2d_kernel,
            grid=(2, 4),
            in_specs=[
                pl.BlockSpec((1, 1, 8, 128), lambda i, j: (i, j, 0, 0))
            ],
            out_specs=pl.BlockSpec((1, 8, 128), lambda i, j: (i, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((2, 8, 128), jnp.uint32),
            scratch_shapes=[pltpu.VMEM((8, 128), jnp.uint32)],
        )(big2),
    )

    # 4b-4d. GRIDLESS workaround rungs: the round-4 run showed `copy`
    # (no grid) compiles on the tunnel while every grid'd kernel 500s —
    # so probe the features a gridless farmhash block loop needs.
    def round_kernel(h_ref, g_ref, f_ref, a_ref, b_ref, o_ref):
        h = h_ref[:] + a_ref[:]
        g = g_ref[:] + b_ref[:]
        f = f_ref[:] + h * jnp.uint32(0xCC9E2D51)
        o_ref[:] = h ^ (g + f)

    def nogrid_round():
        t = jnp.arange(8 * 128, dtype=jnp.uint32).reshape(8, 128)
        return pl.pallas_call(
            round_kernel,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.uint32),
        )(t, t + 1, t + 2, t + 3, t + 4)

    attempt("nogrid_round", nogrid_round)

    def fori_kernel(x_ref, o_ref):
        def body(k, acc):
            return acc + x_ref[k]

        o_ref[:] = jax.lax.fori_loop(
            0, x_ref.shape[0], body, jnp.zeros((8, 128), jnp.uint32)
        )

    def nogrid_fori():
        t = jnp.arange(16 * 8 * 128, dtype=jnp.uint32).reshape(16, 8, 128)
        return pl.pallas_call(
            fori_kernel,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.uint32),
        )(t)

    attempt("nogrid_fori", nogrid_fori)

    def scan_of_pallas():
        t = jnp.arange(8 * 128, dtype=jnp.uint32).reshape(8, 128)
        xs = jnp.arange(32 * 8 * 128, dtype=jnp.uint32).reshape(32, 8, 128)
        call = pl.pallas_call(
            round_kernel,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.uint32),
        )

        @jax.jit
        def run(t, xs):
            def body(carry, x):
                return call(carry, x, x, x, x), None

            out, _ = jax.lax.scan(body, t, xs)
            return out

        return run(t, xs)

    attempt("scan_of_pallas", scan_of_pallas)

    # 5/6. the real farmhash block loop, tiny then bench shape
    from ringpop_tpu.ops import jax_farmhash as jfh

    def hash_rows(n_rows, row_bytes):
        rng = np.random.default_rng(0)
        bufs = jnp.asarray(
            rng.integers(32, 127, size=(n_rows, row_bytes), dtype=np.uint8)
        )
        lens = jnp.full((n_rows,), row_bytes, jnp.int32)
        fn = jax.jit(functools.partial(jfh.hash32_rows, impl="pallas"))
        return fn(bufs, lens)

    attempt("farmhash_tiny", lambda: hash_rows(1024, 128))
    attempt("farmhash_bench", lambda: hash_rows(1024, 36868))

    # 7. fused encode+hash streaming kernel (gridless; the round-6
    # production parity shape).  Rungs walk its compile constraints:
    # tiny, the 1k bench shape, a forced member-chunk shrink, and the
    # row-tiled fallback for row counts past the VMEM slab budget.
    from ringpop_tpu.models.sim.cluster import default_addresses
    from ringpop_tpu.ops import checksum_encode as ce
    from ringpop_tpu.ops import fused_checksum as fc

    def fused_rows(n_rows, n_members, **kw):
        uni = ce.Universe.from_addresses(default_addresses(n_members))
        rng = np.random.default_rng(0)
        pres = jnp.asarray(rng.random((n_rows, n_members)) > 0.2)
        stat = jnp.asarray(rng.integers(0, 4, (n_rows, n_members)))
        inc = jnp.asarray(rng.integers(1, 10**14, (n_rows, n_members)))
        rec_b, rec_l = fc.member_records(uni, pres, stat, inc, 14)
        rw = fc.pack_record_words(rec_b)
        tb = jnp.maximum(jnp.sum(rec_l, axis=1) - 1, 0)
        tb = jnp.where(tb > 24, (tb - 1) // 20, 0)
        h = jnp.zeros(n_rows, jnp.uint32)
        from ringpop_tpu.ops import pallas_farmhash as pfh

        fn = jax.jit(
            functools.partial(
                pfh.fused_stream_nogrid, interpret=interp, **kw
            )
        )
        return fn(h, h, h, rw, rec_l.astype(jnp.int32), tb)

    attempt("fused_tiny", lambda: fused_rows(1024, 32))
    attempt("fused_bench_1k", lambda: fused_rows(1024, 1024))
    # member chunk forced down to 8 by a 1 MiB budget
    attempt(
        "fused_chunk_shrink",
        lambda: fused_rows(1024, 256, vmem_budget=1 << 20),
    )
    # row tiling: 4096 rows, slab past the budget even at chunk=1
    attempt(
        "fused_row_tiled",
        lambda: fused_rows(4096, 128, vmem_budget=1 << 19),
    )

    with open(OUT, "w") as f:
        json.dump(res, f, indent=1)
    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    sys.exit(main())
