#!/usr/bin/env python3
"""Roofline the parity tick (and a storm tick): bytes moved vs bandwidth.

The round-5 verdict's complaint: "fast" was unfalsifiable without a
roofline — nothing stated what fraction of the chip's HBM bandwidth the
hot ticks achieve (VERDICT.md "What's weak" #5).  This applies the
scripts/prof_r4.py method to the two ticks this round touches:

1. one fused-parity quiet tick and one churn tick at n=1024 (the
   headline parity shape — SimCluster, fused record cache + streaming
   kernel), and
2. one scalable-engine storm tick (1M on chip; scaled to 100k on a
   CPU-only image so the artifact still regenerates everywhere).

For each, the artifact records the measured ms/tick, a MODELED
bytes-moved lower bound (each array the tick must read/write once,
itemized in the artifact — a lower bound because reuse/fusion can only
reduce traffic below it, so achieved GB/s is conservative), the derived
GB/s, and — the comparable headline — the parity tick's *string-encode
throughput*: assembled checksum-string bytes hashed per second, the
metric whose ~100 MB/s XLA floor motivated the fused kernel.

Writes PROF_PARITY_ROOFLINE.json; CPU runs are explicitly marked
(platform + peak_gbps null) so nobody mistakes them for chip numbers.
PROF_ROOFLINE_FORCE_CPU=1 skips the TPU wait on tunnel-less images.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = os.environ.get("PROF_ROOFLINE_OUT", "PROF_PARITY_ROOFLINE.json")
# v5e-class chip HBM peak; only attached to TPU measurements
TPU_PEAK_GBPS = 819.0


def timeit(step, reps=5):
    import jax

    out = step()  # compile/settle
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = step()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps * 1e3  # ms


def parity_phase(res: dict, n: int) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster
    from ringpop_tpu.ops import fused_checksum as fc

    params = engine.SimParams(
        n=n,
        checksum_mode="farmhash",
        fused_checksum="on",
        parity_recompute="bounded",
        dirty_batch=n,
        suspicion_ticks=6,
    )
    sim = SimCluster(n=n, params=params)
    sim.bootstrap()
    assert sim.run_until_converged(max_ticks=96) > 0

    r = fc.record_width(sim.universe, params.max_digits)
    rw = fc.record_word_width(sim.universe, params.max_digits)
    row_bytes = int(np.asarray(sim.state.rec_len).sum(axis=1).max())
    # modeled bytes per tick, itemized (fused bounded shape, k == n):
    # 2 recomputes/tick, each streaming every row's record words through
    # VMEM once + the cell-chunk encode; plus one read+write pass over
    # the [N, N] protocol state the tick phases touch (7 int32 + 3 bool
    # arrays) and the record cache write-back
    stream = 2 * n * n * rw * 4
    cells = 2 * min(params.cell_batch, n * n) * (r + 4)
    state_pass = (7 * 4 + 3) * n * n * 2
    model = {
        "stream_record_words_2x": stream,
        "cell_chunk_encode_2x": cells,
        "nn_state_read_write": state_pass,
    }
    total_bytes = sum(model.values())

    quiet = engine.TickInputs.quiet(n)
    ms_quiet = timeit(lambda: sim._tick(sim.state, quiet))
    # churn tick: measured at the kill tick's shape (suspect marks + the
    # wave's first dissemination) — representative of in-window cost
    kill = np.zeros(n, bool)
    kill[3] = True
    churn_in = quiet._replace(kill=jnp.asarray(kill))
    ms_churn = timeit(lambda: sim._tick(sim.state, churn_in))

    # encode throughput: string bytes hashed per second (2 recomputes x
    # n rows x assembled row bytes) — the old XLA floor was ~100 MB/s
    enc_q = 2 * n * row_bytes / (ms_quiet / 1e3)
    res["parity"] = {
        "n": n,
        "record_width_bytes": r,
        "row_string_bytes": row_bytes,
        "tick_quiet_ms": round(ms_quiet, 2),
        "tick_churn_ms": round(ms_churn, 2),
        "modeled_bytes_per_tick": model,
        "modeled_total_bytes": total_bytes,
        "achieved_gbps_quiet": round(total_bytes / (ms_quiet / 1e3) / 1e9, 3),
        "encode_mbps_quiet": round(enc_q / 1e6, 1),
        "node_ticks_per_sec_quiet": round(n / (ms_quiet / 1e3), 1),
        "node_ticks_per_sec_churn": round(n / (ms_churn / 1e3), 1),
    }

    # a scanned churn window — the SAME shape bench.py's churn_parity_*
    # capture measures, so the two artifacts stay comparable
    sched = EventSchedule.churn_window(32, n)
    sim.run(sched)
    pre = sim.parity_replays
    t0 = time.perf_counter()
    sim.run(sched)
    import jax as _jax

    _jax.block_until_ready(sim.state)
    el = time.perf_counter() - t0
    res["parity"]["churn_window_node_ticks_per_sec"] = round(
        n * sched.ticks / el, 1
    )
    res["parity"]["churn_window_replays"] = sim.parity_replays - pre


def storm_phase(res: dict, n: int, u: int = 512) -> None:
    import jax

    from ringpop_tpu.models.sim import engine_scalable as es

    params = es.ScalableParams(n=n, u=u, checksum_in_tick=True)
    st = es.init_state(params, seed=0)
    step = jax.jit(functools.partial(es.tick, params=params))
    quiet = es.ChurnInputs.quiet(n)

    holder = {"st": st}

    def one():
        holder["st"], m = step(holder["st"], quiet)
        return holder["st"]

    ms = timeit(one)
    w = u // 32
    # modeled bytes: heard [N, W] read+write x (exchange diff, checksum
    # fold, coverage popcount) + partner perms/gathers [N] int32 x ~8
    model = {
        "heard_bitmask_3x_rw": 3 * 2 * n * w * 4,
        "per_node_vectors_8x": 8 * n * 4,
    }
    total = sum(model.values())
    res["storm"] = {
        "n": n,
        "u": u,
        "tick_quiet_ms": round(ms, 2),
        "modeled_bytes_per_tick": model,
        "modeled_total_bytes": total,
        "achieved_gbps": round(total / (ms / 1e3) / 1e9, 3),
        "node_ticks_per_sec": round(n / (ms / 1e3), 1),
    }


def main() -> int:
    from ringpop_tpu.utils.util import scrub_repo_pythonpath

    scrub_repo_pythonpath(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import ringpop_tpu  # noqa: F401

    if os.environ.get("PROF_ROOFLINE_FORCE_CPU") != "1":
        try:
            from ringpop_tpu.utils.util import wait_for_tpu

            wait_for_tpu(__file__, "PROF_ROOFLINE_ATTEMPT", 3, 10.0)
        except Exception:
            pass
    import jax

    plat = jax.default_backend()
    res = {
        "platform": plat,
        "device": str(jax.devices()[0]),
        "peak_gbps": TPU_PEAK_GBPS if plat == "tpu" else None,
        "note": (
            "modeled bytes are a LOWER bound (each array counted at one "
            "read+write); achieved GB/s is therefore conservative.  CPU "
            "runs exist so the artifact regenerates on tunnel-less "
            "images — they are NOT chip numbers."
        ),
    }
    parity_phase(res, n=int(os.environ.get("PROF_ROOFLINE_N", "1024")))
    storm_n = 1_000_000 if plat == "tpu" else 100_000
    storm_phase(res, n=int(os.environ.get("PROF_ROOFLINE_STORM_N", storm_n)))
    if res.get("peak_gbps"):
        for k in ("parity", "storm"):
            g = res[k].get("achieved_gbps") or res[k].get(
                "achieved_gbps_quiet"
            )
            res[k]["pct_of_peak"] = round(100.0 * g / res["peak_gbps"], 2)
    with open(OUT, "w") as f:
        json.dump(res, f, indent=1)
    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    sys.exit(main())
