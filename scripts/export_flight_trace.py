#!/usr/bin/env python
"""Generate the committed flight-recorder sample artifacts (runlogs/).

Runs an n=64 full-fidelity cluster with the device-side flight recorder
enabled through a churn window (kill -> suspect -> faulty escalation,
revive -> rejoin wave), then writes:

- ``runlogs/sample_flight_n64.runlog.jsonl`` — the RunRecorder log with
  per-tick metrics, the flight_drain event and the sidecar link,
- ``runlogs/sample_flight_n64.flight.trace.json`` — the Chrome-trace/
  Perfetto sidecar (load at https://ui.perfetto.dev),
- ``runlogs/sample_dissemination_n64.json`` — per-rumor convergence
  ticks + dissemination-latency histogram (ISSUE 4 acceptance
  artifact), with the event/metric reconciliation table inline.

Deterministic (fixed seed, CPU-pinnable via JAX_PLATFORMS=cpu), so the
artifacts regenerate reproducibly::

    JAX_PLATFORMS=cpu python scripts/export_flight_trace.py
"""

from __future__ import annotations

import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

N = 64
TICKS = 40
RUN_ID = "sample_flight_n%d" % N


def main() -> int:
    import numpy as np

    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster
    from ringpop_tpu.obs import RunRecorder
    from ringpop_tpu.obs import events as obs_events

    out_dir = os.path.join(REPO_ROOT, "runlogs")
    os.makedirs(out_dir, exist_ok=True)

    params = engine.SimParams(
        n=N,
        checksum_mode="fast",
        suspicion_ticks=6,
        flight_recorder=True,
    )
    sim = SimCluster(n=N, params=params, seed=1)
    rec = RunRecorder(
        os.path.join(out_dir, "%s.runlog.jsonl" % RUN_ID),
        run_id=RUN_ID,
        config={"tool": "scripts/export_flight_trace.py", "seed": 1},
    )
    # regenerate in place: the recorder appends, so stale rows must go
    open(rec.path, "w").close()
    sim.attach_recorder(rec)

    sim.bootstrap()
    sim.drain_events()  # the sample window starts post-bootstrap
    sched = EventSchedule(ticks=TICKS, n=N)
    sched.kill[3, 5] = True
    sched.revive[TICKS // 2, 5] = True
    metrics = sim.run(sched)

    events = sim.drain_events(reset=False)
    reconciliation = obs_events.reconcile(events, metrics)
    assert all(v["match"] for v in reconciliation.values()), reconciliation
    assert sim.event_drops() == 0

    trace = sim.export_flight_trace(events=events)
    sidecar = rec.record_trace_sidecar(trace, name="flight")

    wavefronts = obs_events.rumor_wavefronts(events)
    summary = obs_events.dissemination_summary(wavefronts)
    summary["run"] = {
        "n": N,
        "ticks": TICKS,
        "seed": 1,
        "events_decoded": len(events),
        "event_drops": 0,
        "schedule": "kill node 5 @ tick 3, revive @ tick %d" % (TICKS // 2),
    }
    summary["reconciliation"] = reconciliation
    dissem_path = os.path.join(
        out_dir, "sample_dissemination_n%d.json" % N
    )
    with open(dissem_path, "w", encoding="utf-8") as fh:
        json.dump(summary, fh, indent=1, sort_keys=True)
        fh.write("\n")

    rec.finish(
        events_decoded=len(events),
        rumors=len(wavefronts),
        converged=bool(np.asarray(metrics.converged)[-1]),
    )
    print("wrote %s" % os.path.relpath(rec.path, REPO_ROOT))
    print("wrote %s" % os.path.relpath(sidecar, REPO_ROOT))
    print("wrote %s" % os.path.relpath(dissem_path, REPO_ROOT))
    return 0


if __name__ == "__main__":
    sys.exit(main())
