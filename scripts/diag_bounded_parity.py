"""Bounded-parity compile + throughput ladder on the axon tunnel (v2).

Round-5 findings so far (DIAG_BOUNDED.json, first run, pre-phase-7-split
engine): the tunnel's compile helper 500s on a lax.cond whose body holds
even a K=256-row encode — AND on the all-straight-line bounded tick.  The
engine has since been restructured: the bounded chunk always runs
STRAIGHT-LINE on TPU while the other phases stay cond-gated
(engine._checksums_where chunk_gate), and phase 7 (which now carries the
ping-req piggyback exchange) was split so its checksum refresh sits at
the top level of the tick, outside every cond.  This script validates the
new shapes on the real chip:

  stage 0  full-recompute control (parity_recompute="full") — also
           revalidates that the ENLARGED tick (piggybacked ping-req,
           three recomputes) still compiles at all
  stage 1  bounded, gate_phases=True, straight-line chunks — the
           shipping TPU config — at dirty_batch in {256, 64, 32}
  stage 2  longer windows (64/256 ticks) on the best config

Protocol (RESULTS.md round 4): rates timed around forced outputs of full
scans; state mutates between runs (defeats the tunnel's result cache);
>= 3 repetitions with min/med/max recorded.
"""

import json
import os
import sys
import time
import traceback

OUT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "DIAG_BOUNDED.json",
)
out = {}
if os.path.exists(OUT):
    try:
        out = json.load(open(OUT))
    except Exception:
        out = {}


def rec(k, v):
    out[k] = v
    with open(OUT, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: v}), flush=True)


def main():
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import jax

    import ringpop_tpu  # noqa: F401
    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.cluster import EventSchedule, SimCluster

    platform = jax.devices()[0].platform
    rec("platform_v2", platform)
    assert platform == "tpu", "this diagnostic needs the real chip"

    n = 1024
    base = engine.SimParams(
        n=n,
        checksum_mode="farmhash",
        hash_impl="pallas_nogrid",
        gate_phases=True,
    )

    def timed(f):
        t0 = time.perf_counter()
        r = f()
        jax.block_until_ready(r)
        return time.perf_counter() - t0, r

    # -- stage 0: bootstrap + convergence via single STEPS (the v2 run's
    # full-mode 32-tick scan kernel-faulted the TPU worker; steps avoid
    # the long-scan trigger and later stages only need the state) ---------
    full = SimCluster(n=n, params=base._replace(parity_recompute="full"))
    try:
        dt, _ = timed(lambda: full.bootstrap())
        rec("v3_full_bootstrap_s", round(dt, 2))
        for _ in range(40):
            m = full.step()
            if bool(m.converged) and int(m.changes_applied) == 0:
                break
        rec("v3_converged_after_steps", int(full.state.tick_index))
    except Exception as e:
        rec(
            "v3_stage0",
            {"ok": False, "error": "%s: %s" % (type(e).__name__, str(e)[:300])},
        )
        return 1
    sched32 = EventSchedule(ticks=32, n=n)
    conv_state = full.state

    # -- stage 1: the shipping bounded config, K sweep --------------------
    best_key_rate = (None, 0.0)
    for K in (256, 64, 32):
        tag = "v2_bounded_k%d" % K
        if tag in out and not (
            isinstance(out[tag], dict) and out[tag].get("ok") is False
        ):
            if isinstance(out[tag], dict) and out[tag].get("med", 0) > best_key_rate[1]:
                best_key_rate = (K, out[tag]["med"])
            continue
        b = SimCluster(
            n=n,
            params=base._replace(parity_recompute="bounded", dirty_batch=K),
        )
        b.state = conv_state
        try:
            dt, _ = timed(lambda: b.run(sched32))  # compile + warm
            runs = []
            for _ in range(5):
                dt2, _ = timed(lambda: b.run(sched32))
                runs.append(n * 32 / dt2)
            runs.sort()
            med = round(runs[len(runs) // 2], 1)
            rec(
                tag,
                {
                    "ok": True,
                    "compile_s": round(dt, 2),
                    "min": round(runs[0], 1),
                    "med": med,
                    "max": round(runs[-1], 1),
                    "replays": b.parity_replays,
                },
            )
            if med > best_key_rate[1]:
                best_key_rate = (K, med)
        except Exception as e:
            rec(
                tag,
                {"ok": False, "error": "%s: %s" % (type(e).__name__, str(e)[:300])},
            )

    # -- stage 1b: churn inside the window (dirty ticks, no overflow) -----
    K = best_key_rate[0]
    if K is not None and "v2_bounded_churn" not in out:
        b = SimCluster(
            n=n,
            params=base._replace(parity_recompute="bounded", dirty_batch=K),
        )
        b.state = conv_state
        runs = []
        try:
            for r in range(3):
                sched = EventSchedule(ticks=32, n=n)
                sched.kill[5, 100 + r] = True
                sched.revive[20, 100 + r] = True
                dt, _ = timed(lambda: b.run(sched))
                runs.append(n * 32 / dt)
            runs.sort()
            rec(
                "v2_bounded_churn",
                {
                    "ok": True,
                    "K": K,
                    "min_med_max": [round(x, 1) for x in runs],
                    "replays": b.parity_replays,
                },
            )
        except Exception as e:
            rec(
                "v2_bounded_churn",
                {"ok": False, "error": "%s: %s" % (type(e).__name__, str(e)[:300])},
            )

    # -- stage 2: longer windows on the best K ----------------------------
    if K is not None:
        for ticks in (64, 256):
            tag = "v2_bounded_k%d_scan%d" % (K, ticks)
            if tag in out:
                continue
            b = SimCluster(
                n=n,
                params=base._replace(
                    parity_recompute="bounded", dirty_batch=K
                ),
            )
            b.state = conv_state
            try:
                sched = EventSchedule(ticks=ticks, n=n)
                dt, _ = timed(lambda: b.run(sched))
                dt2, _ = timed(lambda: b.run(sched))
                rec(
                    tag,
                    {
                        "ok": True,
                        "compile_plus_run_s": round(dt, 2),
                        "warm_rate": round(n * ticks / dt2, 1),
                    },
                )
            except Exception as e:
                rec(
                    tag,
                    {
                        "ok": False,
                        "error": "%s: %s" % (type(e).__name__, str(e)[:300]),
                    },
                )
                break  # worker faults poison the process

    # -- stage 3 (LAST: a worker fault here must not block the bounded
    # answers): does the full-mode 32-tick scan still run, as in round 4?
    if "v3_full_scan32" not in out:
        try:
            dt, _ = timed(lambda: full.run(sched32))
            dt2, _ = timed(lambda: full.run(sched32))
            rec(
                "v3_full_scan32",
                {
                    "ok": True,
                    "compile_plus_run_s": round(dt, 2),
                    "warm_rate": round(n * 32 / dt2, 1),
                },
            )
        except Exception as e:
            rec(
                "v3_full_scan32",
                {"ok": False, "error": "%s: %s" % (type(e).__name__, str(e)[:300])},
            )

    rec("v2_done", True)
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception:
        traceback.print_exc()
        rec("v2_fatal", traceback.format_exc()[-400:])
        sys.exit(1)
