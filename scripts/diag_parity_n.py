#!/usr/bin/env python3
"""Parity-compile bisect #2: which axis breaks the tunnel's compile
helper — cluster size, or a specific parity component at 1k?

Rungs: parity single tick at n=128/256/512/768/1024, then at the first
failing n, the isolated pieces (full farmhash compute_checksums,
membership_rows encode, hash32_rows) to finger the component.
Writes DIAG_PARITY_N.json.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = os.environ.get("DIAG_PARITY_N_OUT", "DIAG_PARITY_N.json")


def main() -> int:
    from ringpop_tpu.utils.util import scrub_repo_pythonpath, wait_for_tpu

    scrub_repo_pythonpath(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import ringpop_tpu  # noqa: F401

    wait_for_tpu(__file__, "DIAG_PARITY_N_ATTEMPT", 90, 20.0)
    import jax

    from ringpop_tpu.models.sim import engine
    from ringpop_tpu.models.sim.cluster import SimCluster

    res = {"device": str(jax.devices()[0])}

    def attempt(name, fn):
        try:
            t0 = time.perf_counter()
            out = fn()
            jax.block_until_ready(out)
            res[name] = {"ok": True, "s": round(time.perf_counter() - t0, 2)}
        except Exception as e:
            res[name] = {"ok": False, "error": str(e)[:200]}
        print(json.dumps({name: res[name]}), flush=True)

    def one_parity_tick(n):
        sim = SimCluster(
            n=n, params=engine.SimParams(n=n, checksum_mode="farmhash")
        )
        sim.bootstrap()
        return sim.state.checksum

    first_fail = None
    for n in (128, 256, 512, 768, 1024):
        attempt("parity_tick_n%d" % n, functools.partial(one_parity_tick, n))
        if first_fail is None and not res["parity_tick_n%d" % n]["ok"]:
            first_fail = n

    # isolate components at 1k (or the first failing n)
    n = first_fail or 1024
    from ringpop_tpu.models.sim.cluster import default_addresses
    from ringpop_tpu.ops import checksum_encode as ce
    from ringpop_tpu.ops import jax_farmhash as jfh

    params = engine.SimParams(n=n, checksum_mode="farmhash")
    universe = ce.Universe.from_addresses(default_addresses(n))
    state = engine.init_state(params, seed=0, universe=universe)

    attempt(
        "compute_checksums_full_n%d" % n,
        lambda: jax.jit(
            lambda s: engine.compute_checksums(s, universe, params)
        )(state),
    )

    # the dirty-batch bounded recompute path in isolation
    import jax.numpy as jnp

    dirty = jnp.zeros(n, bool).at[3].set(True)

    attempt(
        "checksums_where_n%d" % n,
        lambda: jax.jit(
            lambda s, d: engine._checksums_where(
                s, universe, params, d, s.checksum
            )
        )(state, dirty),
    )

    # fast tick at same n (control: should compile)
    attempt(
        "fast_tick_n%d" % n,
        functools.partial(
            lambda n: (
                lambda sim: (sim.bootstrap(), sim.state.checksum)[1]
            )(
                SimCluster(
                    n=n,
                    params=engine.SimParams(n=n, checksum_mode="fast"),
                )
            ),
            n,
        ),
    )

    with open(OUT, "w") as f:
        json.dump(res, f, indent=1)
    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    sys.exit(main())
